"""Profiler hardening tests (ISSUE 14 satellite): the module predates
this suite — pause/resume/dump/dumps had no dedicated coverage.

Covers: chrome-trace JSON shape and atomic dump, dump-while-running
snapshot-and-continue semantics, event ordering, pause/resume gating,
dumps aggregation (+ reset), Counter/Marker emission, and thread
safety of concurrent record_span vs dump.
"""
import json
import os
import threading

import pytest

from mxtpu import profiler as prof


@pytest.fixture(autouse=True)
def _clean_profiler():
    prof.reset()
    prof.set_state("stop")
    yield
    prof.reset()
    prof.set_state("stop")


def test_dump_chrome_trace_shape(tmp_path):
    fname = str(tmp_path / "p.json")
    prof.set_config(filename=fname)
    prof.set_state("run")
    with prof.Domain("d").new_task("work"):
        pass
    prof.record_span("explicit", "cat", 10.0, 20.0, {"k": "v"})
    out = prof.dump()
    assert out == fname and os.path.exists(fname)
    doc = json.load(open(fname))
    assert doc["displayTimeUnit"] == "ms"
    evs = doc["traceEvents"]
    assert len(evs) == 2
    for e in evs:
        assert e["ph"] == "X" and e["dur"] > 0
        assert {"name", "cat", "ts", "pid", "tid"} <= set(e)
    byname = {e["name"]: e for e in evs}
    assert byname["explicit"]["args"] == {"k": "v"}
    assert byname["explicit"]["dur"] == 10.0
    # no .tmp leftovers: the dump is atomic (tmp + rename)
    assert not [f for f in os.listdir(tmp_path) if ".tmp" in f]


def test_dump_snapshots_and_continues(tmp_path):
    """A dump mid-run must neither stop collection nor clear events —
    and a later dump sees both old and new events."""
    fname = str(tmp_path / "p.json")
    prof.set_config(filename=fname)
    prof.set_state("run")
    prof.record_span("before", "c", 0.0, 1.0)
    prof.dump()
    assert len(json.load(open(fname))["traceEvents"]) == 1
    prof.record_span("after", "c", 2.0, 3.0)   # still collecting
    prof.dump()
    names = [e["name"] for e in json.load(open(fname))["traceEvents"]]
    assert names == ["before", "after"]        # insertion order kept


def test_pause_resume_gate_collection():
    prof.set_state("run")
    with prof.Domain("d").new_task("kept"):
        pass
    prof.pause()
    assert not prof.is_active()
    with prof.Domain("d").new_task("dropped"):
        pass
    prof.resume()
    with prof.Domain("d").new_task("kept2"):
        pass
    names = [e["name"] for e in prof.snapshot_events()]
    assert names == ["kept", "kept2"]


def test_stopped_profiler_records_nothing():
    with prof.Domain("d").new_task("t"):
        pass
    c = prof.Domain("d").new_counter("c")
    c.increment(5)
    prof.Domain("d").new_marker("m").mark()
    assert prof.snapshot_events() == []


def test_dumps_aggregates_and_resets():
    prof.set_state("run")
    for i in range(3):
        prof.record_span("op_a", "c", 0.0, 10.0)
    prof.record_span("op_b", "c", 0.0, 50.0)
    text = prof.dumps()
    lines = [ln for ln in text.splitlines()[1:] if ln.strip()]
    # sorted by total time descending: op_b (50) over op_a (30)
    assert lines[0].startswith("op_b") and lines[1].startswith("op_a")
    assert "3" in lines[1]                     # op_a call count
    prof.dumps(reset=True)
    assert prof.snapshot_events() == []


def test_counter_and_marker_events():
    prof.set_state("run")
    c = prof.Domain("d").new_counter("queue", value=2)
    c += 3
    c -= 1
    prof.Domain("d").new_marker("mark").mark(scope="thread")
    evs = prof.snapshot_events()
    counts = [e for e in evs if e["ph"] == "C"]
    assert [e["args"]["value"] for e in counts] == [2, 5, 4]
    marks = [e for e in evs if e["ph"] == "i"]
    assert marks and marks[0]["s"] == "t"


def test_concurrent_record_and_dump_race_free(tmp_path):
    """The satellite's original complaint: dump() racing the event
    list. N writer threads record while a reader dumps repeatedly —
    every dump must parse as complete JSON and the final event count
    must be exact."""
    fname = str(tmp_path / "race.json")
    prof.set_config(filename=fname)
    prof.set_state("run")
    n_threads, per = 8, 200
    start = threading.Event()

    def writer(k):
        start.wait()
        for i in range(per):
            prof.record_span("t%d" % k, "c", float(i), float(i + 1))

    def dumper():
        start.wait()
        for _ in range(30):
            prof.dump()
            json.load(open(fname))             # always complete JSON

    threads = [threading.Thread(target=writer, args=(k,))
               for k in range(n_threads)] + \
        [threading.Thread(target=dumper)]
    for t in threads:
        t.start()
    start.set()
    for t in threads:
        t.join(timeout=30)
    assert len(prof.snapshot_events()) == n_threads * per
    prof.dump()
    assert len(json.load(open(fname))["traceEvents"]) == \
        n_threads * per


def test_counter_thread_safe_increments():
    prof.set_state("stop")                     # no event emission cost
    c = prof.Domain("d").new_counter("n")
    per = 2000

    def bump():
        for _ in range(per):
            c.increment()

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert c._value == 8 * per
