"""Fault matrix for the dist_async stack (mxtpu/fault.py +
kvstore_async's retry/dedupe/health/auto-resume layers; see the module
docstring's "Fault tolerance" section and docs/fault_tolerance.md).

Every scenario is deterministic: faults come from the injection harness
on exact event schedules (never from timing), servers are loopback
threads in this process, and the only sleeps are sub-second backoffs the
retry layer itself performs. The matrix each test row covers:

fault kind x injection point        -> recovery path proven
---------------------------------------------------------------------
sever  @ worker.send (pre-apply)    -> plain retry, applied once
sever  @ server.send (post-apply)   -> retry + seq dedupe (at-most-once)
truncate @ worker.send              -> garbage frame isolated, retried
drop   @ worker.send                -> per-call timeout fires, retried
delay  @ worker.send                -> transparent (just slower)
kill   @ server.recv                -> snapshot-backed restart, buffered
                                       pushes flushed, workers reconverge
server gone (no injection)          -> pull degrades to cached value,
                                       health() reports the dead shard
kill_worker mid-push-window         -> SIGKILL between pipelined part
                                       pushes: the applied prefix is
                                       consistent (each part <= once),
                                       the dead worker's membership +
                                       dedupe seqs are GC'd, the fleet
                                       continues (worker-liveness rows)
stall  @ worker.send                -> straggler surfaces in the
                                       per-worker push counters /
                                       kv.stats()["stragglers"]
worker dead (no bye)                -> server-side lease expiry GCs its
                                       buffered state; barrier degrades
                                       on its deadline instead of
                                       hanging the survivors
drop   @ stream.append              -> record shed before any byte hits
                                       the segment file: no torn record
                                       is ever tailer-visible
sever  @ stream.tail                -> consumer dies holding a segment
                                       lease; bye requeues it and the
                                       successor resumes exactly-once
                                       from the committed offset
trainer killed post-apply           -> the respawn's bit-identical
                                       stream_push frame (grads +
                                       offset commit) is refused by the
                                       (origin, seq) watermark
partition @ client->primary         -> probe-through-peer, promotion,
                                       fencing epoch minted; the healed
                                       incumbent fences + rejoins
partition @ primary->backup (sync)  -> stream detaches, primary acks
                                       solo + buffers for heal-time
                                       reconciliation; reattach catches
                                       back up
partition @ client->primary ONLY    -> peer_alive says the primary is
  (asymmetric, within grace)           healthy: marked unreachable, NO
                                       promotion — pushes buffer, pulls
                                       degrade, heal flushes
partition full split-brain + heal   -> divergence window reconciled
                                       exactly-once at the new primary,
                                       tables bit-equal, journal clean
stale-epoch cursor_done             -> fenced refusal: a re-granted
                                       shard/lease cannot be retired
                                       under its pre-partition grant
"""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault
from mxtpu import kvstore_async as ka
from mxtpu.devtools import consistency
from mxtpu.kvstore_async import ParameterServer


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    """Small retry/backoff windows so every fault path resolves in
    well under a second, heartbeat thread off (tests sweep health
    synchronously via kv._check_health()), and a clean injector."""
    monkeypatch.setattr(ka, "_RETRIES", 2)
    monkeypatch.setattr(ka, "_BACKOFF", 0.01)
    monkeypatch.setattr(ka, "_BACKOFF_MAX", 0.05)
    monkeypatch.setattr(ka, "_RECONNECT_TIMEOUT", 0.2)
    monkeypatch.setattr(ka, "_DEAD_AFTER", 2)
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    # the matrix is about the WIRE: pin the same-process shortcut off so
    # every row exercises real framing (the local-transport rows below
    # flip it back on explicitly)
    monkeypatch.setattr(ka, "_LOCAL_ON", False)
    fault.uninstall()
    yield
    fault.uninstall()


def _store(monkeypatch, addrs, rank=0, nproc=1):
    monkeypatch.setenv("MXTPU_PS_ADDRS", addrs)
    monkeypatch.setenv("MXTPU_PROC_ID", str(rank))
    monkeypatch.setenv("MXTPU_NUM_PROCS", str(nproc))
    return mx.kv.create("dist_async")


# ---------------------------------------------------------------------------
# the injection harness itself
# ---------------------------------------------------------------------------

def test_fault_spec_parsing_and_validation():
    rules = fault.parse_spec(
        "kind=sever,point=server.send,op=push,nth=3,count=2;"
        "kind=delay,point=any,delay=0.25,count=inf")
    assert len(rules) == 2
    assert (rules[0].kind, rules[0].point, rules[0].op,
            rules[0].nth, rules[0].count) == \
        ("sever", "server.send", "push", 3, 2)
    assert rules[1].delay == 0.25 and rules[1].count == float("inf")
    with pytest.raises(ValueError, match="unknown fault kind"):
        fault.parse_spec("kind=explode")
    with pytest.raises(ValueError, match="unknown fault point"):
        fault.parse_spec("kind=sever,point=everywhere")
    with pytest.raises(ValueError, match="kill only applies to server"):
        fault.parse_spec("kind=kill,point=worker.send")
    with pytest.raises(ValueError, match="no kind="):
        fault.parse_spec("point=worker.send")


def test_injector_schedule_is_deterministic():
    inj = fault.FaultInjector("kind=sever,point=worker.send,op=push,"
                              "nth=2,count=2")
    outcomes = []
    for _ in range(5):
        try:
            inj.fire("worker.send", op="push")
            outcomes.append("ok")
        except fault.FaultSever:
            outcomes.append("sever")
    # exactly events 2 and 3 fault, nothing else — replayable schedule
    assert outcomes == ["ok", "sever", "sever", "ok", "ok"]
    inj2 = fault.FaultInjector("kind=sever,point=server.recv,op=pull,"
                               "key=big")
    inj2.fire("server.recv", op="pull", key="other")      # key mismatch
    inj2.fire("worker.send", op="pull", key="big0")       # point mismatch
    with pytest.raises(fault.FaultSever):
        inj2.fire("server.recv", op="pull", key="big0")
    assert inj2.stats()[0][3:] == (1, 1)                  # seen, fired


def test_env_spec_bootstrap(monkeypatch):
    monkeypatch.setenv("MXTPU_FAULT_SPEC",
                       "kind=delay,point=worker.recv,delay=0.01")
    monkeypatch.setattr(fault, "_env_loaded", False)
    monkeypatch.setattr(fault, "_injector", None)
    inj = fault.active()
    assert inj is not None and inj.rules[0].kind == "delay"


# ---------------------------------------------------------------------------
# retry / at-most-once replay
# ---------------------------------------------------------------------------

def test_pre_apply_sever_is_retried(monkeypatch):
    """Connection dies BEFORE the frame reaches the server: the retry
    needs no dedupe help — the replay is the first copy to arrive."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        with fault.inject("kind=sever,point=worker.send,op=push,nth=1") \
                as inj:
            kv.push("w", mx.nd.ones((4,)))
        assert inj.stats()[0][4] == 1          # the fault really fired
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        assert srv._clock["w"] == 1 and srv._dup_n == 0
        assert kv.health()["num_dead"] == 0    # one blip != dead
    finally:
        kv.close()
        srv.stop()


def test_lost_ack_push_replay_applied_exactly_once(monkeypatch):
    """Connection dies AFTER the server applied the push but before the
    ack: the blind replay MUST be deduped by the (origin, seq) pair —
    clock-checked, the acceptance-criteria scenario."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        with fault.inject("kind=sever,point=server.send,op=push,nth=1") \
                as inj:
            kv.push("w", mx.nd.ones((4,)))     # applied; ack lost; replay
        assert inj.stats()[0][4] == 1
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))  # not 2.0!
        assert srv._clock["w"] == 1            # applied exactly once
        assert srv._dup_n == 1                 # the replay was refused
    finally:
        kv.close()
        srv.stop()


def test_truncate_fault_recovers(monkeypatch):
    """A torn frame (bogus length then close) must be contained by the
    server's framing guards and recovered by the worker's retry."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((2,)))
        with fault.inject(
                "kind=truncate,point=worker.send,op=push,nth=1"):
            kv.push("w", mx.nd.ones((2,)))
        out = mx.nd.zeros((2,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(2))
        assert srv._clock["w"] == 1 and srv._dup_n == 0
    finally:
        kv.close()
        srv.stop()


def test_dropped_frame_hits_timeout_then_retries(monkeypatch):
    """kind=drop silently loses the request frame, so ONLY the per-call
    timeout can notice — proves the timeout path, not just the
    connection-error path."""
    monkeypatch.setattr(ka, "_REQUEST_TIMEOUT", 0.3)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.array(np.arange(3, dtype="f")))
        with fault.inject("kind=drop,point=worker.send,op=pull,nth=1") \
                as inj:
            out = mx.nd.zeros((3,))
            kv.pull("w", out=out)
        assert inj.stats()[0][4] == 1
        np.testing.assert_allclose(out.asnumpy(),
                                   np.arange(3, dtype="f"))
    finally:
        kv.close()
        srv.stop()


def test_delay_fault_is_transparent(monkeypatch):
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((2,)))
        with fault.inject("kind=delay,point=worker.send,op=push,"
                          "delay=0.05,count=2") as inj:
            kv.push("w", mx.nd.ones((2,)))
            kv.push("w", mx.nd.ones((2,)))
        assert inj.stats()[0][4] == 2
        out = mx.nd.zeros((2,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(2))
    finally:
        kv.close()
        srv.stop()


def test_barrier_is_never_replayed(monkeypatch):
    """barrier is NOT idempotent (a replayed arrival would double-count
    this worker in the generation), so a barrier fault must surface
    instead of retrying."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((2,)))
        with fault.inject("kind=sever,point=worker.send,op=barrier,"
                          "nth=1"):
            with pytest.raises(ConnectionError):
                kv.barrier()
        assert srv._barrier_arrived == 0       # no half-arrived worker
    finally:
        kv.close()
        srv.stop()


# ---------------------------------------------------------------------------
# liveness: dead-shard degradation + recovery
# ---------------------------------------------------------------------------

def test_dead_shard_pull_degrades_to_last_known(monkeypatch):
    """Acceptance scenario: a pull whose shard is dead returns the
    worker's last-known value (staleness-marked) instead of raising,
    health() reports the dead server, and a recovered server clears
    both on the next health sweep + pull."""
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    kv = _store(monkeypatch, s1.address + "," + s2.address)
    try:
        keys = ["k%d" % i for i in range(6)]
        for k in keys:
            kv.init(k, mx.nd.ones((3,)) * float(k[1]))
        out = mx.nd.zeros((3,))
        for k in keys:
            kv.pull(k, out=out)                # warm the last-known cache
        # kill whichever server owns k0; remember its port for revival
        dead = s1 if "k0" in s1._clock else s2
        live = s2 if dead is s1 else s1
        dead_port = int(dead.address.split(":")[1])
        dead_keys = sorted(dead._clock)
        dead.stop()

        kv.pull("k0", out=out)                 # degraded, NOT an error
        np.testing.assert_allclose(out.asnumpy(), np.zeros(3))
        h = kv.health()
        assert h["num_dead"] == 1
        assert "k0" in h["degraded_keys"]
        states = {s["addr"]: s["state"] for s in h["servers"]}
        assert states[dead.address] == "dead"
        assert states[live.address] == "ok"
        assert kv.get_num_dead_node() == 1     # the NumDeadNodes analogue
        # keys on the live shard are untouched by the dead one
        live_key = sorted(live._clock)[0]
        kv.pull(live_key, out=out)
        assert live_key not in kv.health()["degraded_keys"]

        # shard comes back on the same port: the background probe path
        # (run synchronously here) re-marks it ok, and a live pull
        # clears the staleness mark
        revived = ParameterServer(port=dead_port).start()
        try:
            kv._check_health()
            assert kv.health()["num_dead"] == 0
            # revived empty table: the key is gone (no snapshot); a pull
            # still degrades to cache rather than raising mid-training
            kv.pull("k0", out=out)
            assert "k0" in kv.health()["degraded_keys"], \
                "no live value yet -> still staleness-marked"
            for k in dead_keys:                # re-init repopulates
                kv.init(k, mx.nd.ones((3,)) * 7)
            kv.pull("k0", out=out)
            np.testing.assert_allclose(out.asnumpy(), 7 * np.ones(3))
            assert "k0" not in kv.health()["degraded_keys"]
        finally:
            revived.stop()
    finally:
        kv.close()
        s1.stop()
        s2.stop()


def test_pull_without_cache_still_raises(monkeypatch):
    """Degradation never invents data: a key this worker NEVER pulled
    has no last-known value, so a dead shard must still raise."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((2,)))        # init warms no pull cache
        srv.stop()
        with pytest.raises(ConnectionError):
            kv.pull("w", out=mx.nd.zeros((2,)))
    finally:
        kv.close()


# ---------------------------------------------------------------------------
# auto-resume: snapshots, buffered pushes, restart
# ---------------------------------------------------------------------------

def test_killed_server_restores_snapshot_and_reconverges(monkeypatch,
                                                         tmp_path):
    """Acceptance scenario: the injector kills the server on schedule
    mid-training; a restart on the same port restores table, clocks,
    optimizer AND the push-dedupe seqs from the snapshot; the worker's
    buffered push flushes with its original seq (at-most-once across
    the crash) and training reconverges with no operator action."""
    snap = str(tmp_path / "snaps")
    srv = ParameterServer(snapshot_dir=snap, snapshot_every=1).start()
    port = int(srv.address.split(":")[1])
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        kv.push("w", mx.nd.ones((4,)))         # applied + snapshotted
        # 2nd push: the server is killed on receipt, BEFORE applying
        # (the injector counts from installation, so nth=1 here)
        with fault.inject("kind=kill,point=server.recv,op=push,nth=1"):
            kv.push("w", mx.nd.ones((4,)))     # buffered, not lost
        h = kv.health()
        assert h["num_dead"] == 1 and h["pending_pushes"] == 1

        srv2 = ParameterServer(port=port, snapshot_dir=snap).start()
        try:
            assert srv2._restored_step is not None
            assert srv2._updater is not None, \
                "optimizer must ride the snapshot"
            np.testing.assert_allclose(srv2._table["w"],  # numpy table
                                       -0.5 * np.ones(4))
            assert srv2._clock["w"] == 1

            kv._check_health()                 # probe + flush buffered
            h = kv.health()
            assert h["num_dead"] == 0 and h["pending_pushes"] == 0
            out = mx.nd.zeros((4,))
            kv.pull("w", out=out)              # -0.5 - 0.5 = -1.0
            np.testing.assert_allclose(out.asnumpy(), -np.ones(4))
            assert srv2._clock["w"] == 2 and srv2._dup_n == 0

            # reconvergence: the fleet keeps training as if nothing
            # happened — each further push is applied exactly once
            for _ in range(3):
                kv.push("w", mx.nd.ones((4,)))
            kv.pull("w", out=out)
            np.testing.assert_allclose(out.asnumpy(), -2.5 * np.ones(4))
            assert srv2._clock["w"] == 5
        finally:
            srv2.stop()
    finally:
        kv.close()
        srv.stop()


def test_buffered_push_flush_is_deduped_against_retry(monkeypatch,
                                                      tmp_path):
    """The nastiest interleaving: the push's ack is lost (server DID
    apply it), the server then dies before the worker's replay lands, so
    the replay gets buffered — and after restart the flush replays a seq
    the SNAPSHOT already recorded as applied. The restored dedupe table
    must refuse it."""
    snap = str(tmp_path / "snaps")
    srv = ParameterServer(snapshot_dir=snap, snapshot_every=1).start()
    port = int(srv.address.split(":")[1])
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        # push 1 applied + snapshotted (seq recorded), then the ack is
        # severed AND the server dies, so every replay attempt fails
        with fault.inject(
                "kind=sever,point=server.send,op=push,nth=1;"
                "kind=kill,point=server.recv,op=push,nth=2"):
            kv.push("w", mx.nd.ones((4,)))
        assert kv.health()["pending_pushes"] == 1
        srv2 = ParameterServer(port=port, snapshot_dir=snap).start()
        try:
            kv._check_health()                 # flush replays seq 1
            assert kv.health()["pending_pushes"] == 0
            out = mx.nd.zeros((4,))
            kv.pull("w", out=out)
            np.testing.assert_allclose(out.asnumpy(), np.ones(4))
            assert srv2._clock["w"] == 1       # exactly once, ever
            assert srv2._dup_n == 1            # the flush was refused
        finally:
            srv2.stop()
    finally:
        kv.close()
        srv.stop()


def test_snapshot_roundtrip_preserves_key_types(tmp_path):
    """Table keys are ints, plain strings, and NUL-separated part
    subkeys — the snapshot's tagged-key encoding must round-trip all
    three exactly."""
    snap = str(tmp_path / "s")
    srv = ParameterServer(snapshot_dir=snap, snapshot_every=0)
    conn = ka._ServerConn(srv.start().address)
    try:
        conn.request("init", 7, np.arange(3, dtype="f"))
        conn.request("init", "name", np.ones((2, 2), "f"))
        conn.request("init", "big\x001", np.zeros(2, "f"))
        conn.request("push", "big\x001", np.ones(2, "f"), 0, "o1", 5)
        assert srv.snapshot()
    finally:
        conn.close()
        srv.stop()
    srv2 = ParameterServer(snapshot_dir=snap)
    try:
        assert set(srv2._table) == {7, "name", "big\x001"}
        assert srv2._clock == {7: 0, "name": 0, "big\x001": 1}
        assert srv2._applied == {("o1", "big\x001"): 5}
        np.testing.assert_allclose(srv2._table[7],        # numpy table
                                   np.arange(3, dtype="f"))
    finally:
        srv2.stop()


def test_local_store_health_is_trivially_ok():
    kv = mx.kv.create("local")
    h = kv.health()
    assert h["num_dead"] == 0 and h["servers"] == []
    assert kv.get_num_dead_node() == 0


# ---------------------------------------------------------------------------
# pipelined-window rows (ISSUE 2): the fast path must keep every fault
# semantic above while many requests ride one socket unacknowledged
# ---------------------------------------------------------------------------

def _eight_part_push(monkeypatch):
    """Shrink the bigarray bound so an (8, 4) array splits into 8
    one-row parts — all of which stream back-to-back inside one
    MXTPU_PS_WINDOW=8 window on the single socket. Coalescing is
    pinned off so each part is its own pipelined frame (op=push on the
    wire), which is what these rows are about."""
    monkeypatch.setattr(ka, "_BIGARRAY_BOUND", 4)
    monkeypatch.setattr(ka, "_COALESCE_BYTES", 0)


def test_window_sever_mid_window_at_most_once(monkeypatch):
    """Sever the connection after the server applied part 3 of an
    8-part pipelined push but before its ack: the whole unacked window
    fails onto the retry layer; the replay of the applied part is
    deduped, the never-dispatched tail applies first-time — the table
    holds each part EXACTLY once and stats() shows the evidence
    (retransmits worker-side, dup_pushes server-side)."""
    _eight_part_push(monkeypatch)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((8, 4)))
        with fault.inject("kind=sever,point=server.send,op=push,nth=3") \
                as inj:
            kv.push("w", mx.nd.ones((8, 4)))
        assert inj.stats()[0][4] == 1
        out = mx.nd.zeros((8, 4))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones((8, 4)))
        assert all(srv._clock["w\x00%d" % i] == 1 for i in range(8))
        assert srv._dup_n >= 1                 # the applied part replayed
        s = kv.stats()
        assert s["retransmits"] >= 1           # window replay happened
        assert s["dup_pushes"] >= 1            # ...and was deduped
        assert s["inflight_hwm"] >= 2          # requests really pipelined
    finally:
        kv.close()
        srv.stop()


def test_window_truncate_mid_window(monkeypatch):
    """A torn frame in the middle of a streaming window: the channel
    dies, every in-flight part replays, framing guards keep the server
    sane — in-order flush still lands the whole array exactly once."""
    _eight_part_push(monkeypatch)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((8, 4)))
        with fault.inject(
                "kind=truncate,point=worker.send,op=push,nth=4") as inj:
            kv.push("w", mx.nd.ones((8, 4)))
        assert inj.stats()[0][4] == 1
        out = mx.nd.zeros((8, 4))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones((8, 4)))
        assert all(srv._clock["w\x00%d" % i] == 1 for i in range(8))
    finally:
        kv.close()
        srv.stop()


def test_window_drop_mid_window(monkeypatch):
    """A silently dropped frame mid-window: only the waiter's deadline
    can notice; the channel fails, the unacked window replays, dedupe
    keeps the already-applied prefix at-most-once."""
    _eight_part_push(monkeypatch)
    monkeypatch.setattr(ka, "_REQUEST_TIMEOUT", 0.3)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((8, 4)))
        with fault.inject("kind=drop,point=worker.send,op=push,nth=5") \
                as inj:
            kv.push("w", mx.nd.ones((8, 4)))
        assert inj.stats()[0][4] == 1
        out = mx.nd.zeros((8, 4))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones((8, 4)))
        assert all(srv._clock["w\x00%d" % i] == 1 for i in range(8))
    finally:
        kv.close()
        srv.stop()


def test_window_inorder_flush_same_key(monkeypatch):
    """Two sequential pushes of ONE key with a sever between their acks:
    replays must neither reorder nor double-apply — the final value is
    the exact two-push sum."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        with fault.inject("kind=sever,point=server.send,op=push,nth=1"):
            kv.push("w", mx.nd.ones((4,)))
            kv.push("w", 2 * mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 3 * np.ones(4))
        assert srv._clock["w"] == 2 and srv._dup_n == 1
    finally:
        kv.close()
        srv.stop()


def test_coalesced_multi_sever_mid_batch(monkeypatch):
    """Sever inside a coalesced multi-key frame after a prefix of its
    sub-pushes applied: the client replays the WHOLE batch; the seq
    dedupe refuses the prefix and applies only the tail — every key
    lands exactly once."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        keys = ["k%d" % i for i in range(8)]
        vals = [mx.nd.ones((3,)) * (i + 1) for i in range(8)]
        kv.init(keys, [mx.nd.zeros((3,)) for _ in keys])
        # 5th push EVENT at server.recv = sub-push 5 of the multi frame
        # (subs fire their own server.recv), so 4 subs applied first
        with fault.inject("kind=sever,point=server.recv,op=push,nth=5") \
                as inj:
            kv.push(keys, vals)
        assert inj.stats()[0][4] == 1
        for i, k in enumerate(keys):
            out = mx.nd.zeros((3,))
            kv.pull(k, out=out)
            np.testing.assert_allclose(out.asnumpy(),
                                       (i + 1) * np.ones(3))
            assert srv._clock[k] == 1, (k, srv._clock)
        assert srv._dup_n == 4                 # the applied prefix
        s = kv.stats()
        assert s["coalesced_subs"] >= 8        # they really coalesced
    finally:
        kv.close()
        srv.stop()


def test_worker_membership_hello_bye_gc(monkeypatch):
    """Worker-liveness row: a store registers at creation (hello), its
    pushes feed per-worker counters, and a clean close (bye) drops the
    membership AND reclaims the worker's dedupe seqs — the per-origin
    at-most-once table cannot grow one entry per worker incarnation
    forever."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    origin = kv._origin
    try:
        assert origin in srv._workers          # hello at creation
        epoch0 = srv._membership_epoch
        kv.init("w", mx.nd.zeros((4,)))
        kv.push("w", mx.nd.ones((4,)))
        kv.push("w", mx.nd.ones((4,)))
        rec = srv._workers[origin]
        assert rec["rank"] == 0 and rec["pushes"] == 2
        assert (origin, "w") in srv._applied
        s = kv.stats()
        assert s["workers"][origin]["pushes"] == 2
        # per-server epochs (the counters are independent per server —
        # an aggregate max would be meaningless); churn is the only
        # cross-server verdict kept
        assert s["membership_epochs"][srv.address] == epoch0
        assert s["membership_churn"] is True   # our own hello counts
        assert s["elastic"]["joins"] == 1
        h = kv.health()
        assert origin in h["workers"] and h["stragglers"] == []
    finally:
        kv.close()                             # sends bye
        assert origin not in srv._workers
        assert (origin, "w") not in srv._applied
        assert srv._membership_epoch == epoch0 + 1
        srv.stop()


def test_dead_worker_lease_expiry_gc(monkeypatch):
    """A worker that vanishes WITHOUT a bye (kill -9): once its lease is
    silent past MXTPU_PS_WORKER_DEAD_AFTER, the next sweep garbage-
    collects its membership and buffered dedupe state."""
    import time
    monkeypatch.setattr(ka, "_WORKER_DEAD_AFTER", 0.05)
    srv = ParameterServer().start()
    conn = ka._ServerConn(srv.address)
    try:
        conn.request("init", "w", np.zeros(4, "f"))
        conn.request("hello", "gone-worker", 3)
        conn.request("push", "w", np.ones(4, "f"), 0, "gone-worker", 1)
        assert "gone-worker" in srv._workers
        assert ("gone-worker", "w") in srv._applied
        time.sleep(0.08)                       # lease expires
        assert srv._gc_workers() == 1          # the lazy sweep reaps it
        assert "gone-worker" not in srv._workers
        assert ("gone-worker", "w") not in srv._applied
        # the table itself is untouched — only the worker's bookkeeping
        np.testing.assert_allclose(srv._table["w"], np.ones(4))
    finally:
        conn.close()
        srv.stop()


def test_barrier_deadline_degrades_instead_of_hanging(monkeypatch):
    """A barrier a dead member can never complete: the server force-
    releases the generation at the deadline, the waiter returns (logged
    + counted), and the NEXT barrier round starts clean."""
    import time
    monkeypatch.setattr(ka, "_BARRIER_TIMEOUT", 0.3)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address, rank=0, nproc=2)
    try:
        kv.init("w", mx.nd.zeros((2,)))        # init barriers: 2 workers
        # ...which itself would hang forever without the deadline — the
        # second worker never existed. Measure the bound:
        t0 = time.time()
        kv.barrier()
        assert time.time() - t0 < 5
        assert srv._barrier_timeouts >= 1
        assert srv._barrier_arrived == 0       # generation fully reset
        assert kv.stats()["barrier_timeouts"] >= 1
    finally:
        kv.close()
        srv.stop()


def test_stall_fault_surfaces_straggler_counters(monkeypatch):
    """stall row: a stalled worker's push rate falls behind the fleet;
    the per-worker push counters make the straggler observable in
    kv.stats() — push-count based, so the verdict is deterministic."""
    monkeypatch.setattr(ka, "_STRAGGLER_MIN", 10)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        # the stalled worker: 3 pushes, each through an injected stall
        # (tiny delay — the *counter* is the evidence, not wall time)
        with fault.inject("kind=stall,point=worker.send,op=push,"
                          "delay=0.01,count=3") as inj:
            for _ in range(3):
                kv.push("w", mx.nd.ones((4,)))
        assert inj.stats()[0][4] == 3
        # a healthy peer outruns it 4:1
        conn = ka._ServerConn(srv.address)
        conn.request("hello", "fast-worker", 1)
        for i in range(12):
            conn.request("push", "w", np.ones(4, "f"), 0,
                         "fast-worker", i + 1)
        s = kv.stats()
        assert s["workers"][kv._origin]["pushes"] == 3
        assert s["workers"]["fast-worker"]["pushes"] == 12
        assert kv._origin in s["stragglers"]
        assert "fast-worker" not in s["stragglers"]
        conn.close()
    finally:
        kv.close()
        srv.stop()


# ---------------------------------------------------------------------------
# replication rows (ISSUE 4): primary/backup pairs, hot failover, zero
# acknowledged-update loss. Every row drives promotion/rejoin/catch-up
# through the same injection points as the rest of the matrix.
# ---------------------------------------------------------------------------

def _wait_for(cond, timeout=10.0, what="condition"):
    """Poll an eventual condition with a hard deadline (the condition
    itself is deterministic — only its arrival time is not)."""
    import time
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError("timed out waiting for %s" % what)


def _pair(monkeypatch, repl_mode="sync", **srv_kw):
    """A joined (primary, backup) shard pair plus a replicated store
    pointed at the primary. The store learns the backup from hello."""
    pri = ParameterServer(role="primary", repl_mode=repl_mode,
                          **srv_kw).start()
    bak = ParameterServer(role="backup", peer_addr=pri.address,
                          repl_mode=repl_mode).start()
    pri._peer_addr = bak.address
    bak.join_cluster(probe_interval=0)
    _wait_for(lambda: bak._catchup_complete, what="initial catch-up")
    monkeypatch.setenv("MXTPU_PS_REPLICAS", "2")
    kv = _store(monkeypatch, pri.address)
    assert isinstance(kv._conns[0], ka._ReplicatedConn)
    assert kv._conns[0]._addrs[1] == bak.address, \
        "hello must teach the client the shard map"
    return pri, bak, kv


def test_sync_replication_mirrors_every_push(monkeypatch):
    """The baseline invariant everything below builds on: in sync mode
    a push RETURNING means the backup already applied it — no waits,
    no eventually."""
    pri, bak, kv = _pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        for i in range(3):
            kv.push("w", mx.nd.ones((4,)))
            assert bak._clock.get("w") == i + 1, \
                "sync ack returned before the backup applied"
        np.testing.assert_allclose(bak._table["w"], 3 * np.ones(4))
        assert pri._clock["w"] == 3
        srv = kv.stats()
        assert srv["replication"][0]["repl"]["lag"] == 0
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_failover_pull_is_fresh_dead_shard_pull_is_stale(monkeypatch):
    """Satellite: a pull served by a just-promoted backup is a LIVE
    pull — no stale marker — while a genuinely dead shard (both
    replicas gone) still degrades to the staleness-marked cache."""
    pri, bak, kv = _pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        kv.push("w", mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)                  # warm the cache
        pri.kill()
        _wait_for(lambda: not pri._thread.is_alive(),
                  what="primary teardown")
        kv.pull("w", out=out)                  # failover, not degrade
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        h = kv.health()
        assert h["degraded_keys"] == [], \
            "a failover pull must not carry the stale marker"
        assert h["num_dead"] == 0
        assert h["failovers"] == 1 and h["servers"][0]["failed_over"]
        assert bak._role == "primary" and bak._promotions == 1
        # now the shard dies for REAL: both replicas gone — the pull
        # degrades to the last-known value and marks staleness
        bak.stop()
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        h = kv.health()
        assert "w" in h["degraded_keys"]
        assert h["num_dead"] == 1
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_kill_primary_mid_window_zero_acked_loss(monkeypatch):
    """Kill the primary between the pipelined part-pushes of one big
    array (sync mode): the whole unacked window replays against the
    promoted backup; parts the primary forwarded pre-kill are refused
    by the transferred dedupe seqs — every part lands EXACTLY once and
    nothing acked is lost."""
    _eight_part_push(monkeypatch)
    pri, bak, kv = _pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((8, 4)))
        with fault.inject(
                "kind=kill,point=server.recv,op=push,nth=3") as inj:
            kv.push("w", mx.nd.ones((8, 4)))
        assert inj.stats()[0][4] == 1
        assert bak._role == "primary"
        # the promoted table holds each part exactly once, values whole
        for i in range(8):
            sk = "w\x00%d" % i
            assert bak._clock[sk] == 1, (sk, bak._clock)
            assert np.allclose(bak._table[sk], 1.0), bak._table[sk]
        out = mx.nd.zeros((8, 4))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones((8, 4)))
        assert kv.stats()["failovers"] == 1
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_kill_primary_mid_coalesced_batch(monkeypatch):
    """Kill the primary inside a coalesced multi-key frame after a
    prefix of its sub-pushes applied (and sync-replicated): the client
    replays the WHOLE batch on the promoted backup, whose transferred
    seqs refuse the prefix — every key exactly once."""
    pri, bak, kv = _pair(monkeypatch)
    try:
        keys = ["k%d" % i for i in range(8)]
        vals = [mx.nd.ones((3,)) * (i + 1) for i in range(8)]
        kv.init(keys, [mx.nd.zeros((3,)) for _ in keys])
        # sub-pushes fire their own server.recv inside the multi frame
        with fault.inject(
                "kind=kill,point=server.recv,op=push,nth=5") as inj:
            kv.push(keys, vals)
        assert inj.stats()[0][4] == 1
        assert bak._role == "primary"
        for i, k in enumerate(keys):
            out = mx.nd.zeros((3,))
            kv.pull(k, out=out)
            np.testing.assert_allclose(out.asnumpy(),
                                       (i + 1) * np.ones(3))
            assert bak._clock[k] == 1, (k, bak._clock)
        assert bak._dup_n >= 1         # the replayed prefix was refused
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_sever_repl_stream_sync_mode_acks_after_recovery(monkeypatch):
    """Sever the replication stream itself (sync mode): the push's ack
    is withheld until the stream's retry lands the record — when
    push() returns, the backup must hold the update, sever or no
    sever, applied exactly once."""
    pri, bak, kv = _pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        with fault.inject(
                "kind=sever,point=worker.send,op=repl,nth=1") as inj:
            kv.push("w", mx.nd.ones((4,)))
        assert inj.stats()[0][4] == 1          # the stream really tore
        assert bak._clock.get("w") == 1, \
            "sync ack returned before the re-sent record landed"
        np.testing.assert_allclose(bak._table["w"], np.ones(4))
        assert pri._repl is not None and not pri._repl.dead
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_async_repl_mode_bounds_lag_then_drains(monkeypatch):
    """async replication: pushes ack immediately, the stream lags at
    most MXTPU_PS_REPL_LAG_MAX records, and drains to equality."""
    monkeypatch.setattr(ka, "_REPL_LAG_MAX", 2)
    pri, bak, kv = _pair(monkeypatch, repl_mode="async")
    try:
        kv.init("w", mx.nd.zeros((4,)))
        with fault.inject("kind=delay,point=worker.send,op=repl,"
                          "delay=0.02,count=inf"):
            for _ in range(6):
                kv.push("w", mx.nd.ones((4,)))
                assert pri._repl.lag() <= 2, "lag bound violated"
        _wait_for(lambda: bak._clock.get("w") == 6, what="drain")
        np.testing.assert_allclose(bak._table["w"], 6 * np.ones(4))
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_kill_backup_during_catchup_primary_detaches(monkeypatch):
    """Kill the backup mid-state-transfer: the stream dies terminally,
    the primary detaches it (redundancy lost, loudly) and keeps
    serving — the fleet never wedges on a dead backup."""
    monkeypatch.setattr(ka, "_REPL_TIMEOUT", 5.0)
    pri = ParameterServer(role="primary").start()
    kv = _store(monkeypatch, pri.address)
    try:
        for i in range(6):
            kv.init("k%d" % i, mx.nd.ones((3,)) * i)
        bak = ParameterServer(role="backup",
                              peer_addr=pri.address).start()
        pri._peer_addr = bak.address
        # the 3rd repl record (an xfer mid-transfer) kills the backup
        with fault.inject(
                "kind=kill,point=server.recv,op=repl,nth=3") as inj:
            bak.join_cluster(probe_interval=0)
            _wait_for(lambda: pri._repl is None,
                      what="primary to detach the dead backup")
        assert inj.stats()[0][4] == 1
        assert not bak._catchup_complete
        # the primary serves on, unreplicated
        kv.push("k0", mx.nd.ones((3,)))
        out = mx.nd.zeros((3,))
        kv.pull("k0", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(3))
        assert kv.health()["num_dead"] == 0
        bak.stop()
    finally:
        kv.close()
        pri.stop()


def test_respawned_primary_rejoins_and_catches_up(monkeypatch):
    """The full repair loop in-process: primary dies mid-training, the
    backup promotes and serves, a fresh server on the old port demotes
    itself against the promoted peer and catches up (table + clocks +
    dedupe seqs + optimizer + ACCUMULATED updater state) — after which
    new pushes replicate to it and the pair is redundant again.
    Momentum SGD on purpose: a catch-up that transferred the table but
    not the momentum buffers would diverge on the very next forwarded
    push (the bug the public-API verify drive caught)."""
    pri, bak, kv = _pair(monkeypatch)
    port = int(pri.address.split(":")[1])
    # momentum-SGD ground truth for grad=1 pushes: m += 0.9m+1,
    # w -= 0.5m  ->  w1=-0.5, w2=-1.45, w3=-2.805
    try:
        kv.init("w", mx.nd.zeros((4,)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5,
                                          momentum=0.9))
        kv.push("w", mx.nd.ones((4,)))
        pri.kill()
        _wait_for(lambda: not pri._thread.is_alive(),
                  what="primary teardown")
        kv.push("w", mx.nd.ones((4,)))         # fails over mid-stream
        assert bak._role == "primary"
        np.testing.assert_allclose(bak._table["w"], -1.45 * np.ones(4),
                                   rtol=1e-6)
        pri2 = ParameterServer(port=port, role="primary",
                               peer_addr=bak.address).start()
        try:
            pri2.join_cluster(probe_interval=0)
            assert pri2._role == "backup", \
                "a respawn facing a promoted peer must demote"
            _wait_for(lambda: pri2._catchup_complete, what="catch-up")
            np.testing.assert_allclose(pri2._table["w"],
                                       -1.45 * np.ones(4), rtol=1e-6)
            assert pri2._clock["w"] == 2
            assert pri2._updater is not None, \
                "the optimizer must ride the state transfer"
            assert any(k == "w" for (_, k) in pri2._applied), \
                "push-dedupe seqs must ride the state transfer"
            kv.push("w", mx.nd.ones((4,)))     # replicates to pri2 now
            assert pri2._clock["w"] == 3
            np.testing.assert_allclose(
                pri2._table["w"], -2.805 * np.ones(4), rtol=1e-6,
                err_msg="rejoined backup diverged — the accumulated "
                        "momentum state did not ride the catch-up")
            row = kv.health()["replication"][0]
            assert row["role"] == "primary"
            assert row["promotions"] == 1
            assert row["repl"]["catchup"]["done"]
            assert row["repl"]["lag"] == 0
        finally:
            pri2.stop()
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_backup_refuses_client_ops_until_promoted(monkeypatch):
    """Routing safety: a store (mis)pointed at a live backup gets the
    not_serving verdict and swaps to the real primary instead of
    reading a possibly-stale table."""
    pri, bak, kv0 = _pair(monkeypatch)
    kv0.init("w", mx.nd.zeros((4,)))
    kv0.push("w", mx.nd.ones((4,)))
    try:
        # a second store whose 'primary' entry is actually the backup
        monkeypatch.setenv("MXTPU_PS_BACKUP_ADDRS", pri.address)
        kv = _store(monkeypatch, bak.address)
        try:
            out = mx.nd.zeros((4,))
            kv.pull("w", out=out)              # refused, re-routed
            np.testing.assert_allclose(out.asnumpy(), np.ones(4))
            assert kv._conns[0].failovers == 1
            assert pri._role == "primary"      # promote was a no-op
        finally:
            kv.close()
    finally:
        kv0.close()
        pri.stop()
        bak.stop()


@pytest.mark.slow
def test_kill_worker_mid_push_window(monkeypatch, tmp_path):
    """kill_worker row: a child worker is SIGKILLed by the fault
    harness between the pipelined part-pushes of one big array. The
    server must be left consistent — every part applied at most once,
    no torn values — and a successor worker (fresh origin, the
    launcher-respawn situation) completes the same push cleanly."""
    import json
    import subprocess
    import sys
    srv = ParameterServer().start()
    child = r"""
import os, numpy as np
import jax; jax.config.update("jax_platforms", "cpu")
import mxtpu as mx
from mxtpu import kvstore_async as ka
ka._BIGARRAY_BOUND = 4            # (8, 4) splits into 8 one-row parts
ka._COALESCE_BYTES = 0
kv = mx.kv.create("dist_async")
kv.init("w", mx.nd.zeros((8, 4)))
print("READY", flush=True)
# SIGKILL fires on the 5th wire event after init's frames drain —
# mid-window, with a prefix of the 8 part-pushes applied
import mxtpu.fault as fault
fault.install("kind=kill_worker,point=any,op=push,nth=5")
kv.push("w", mx.nd.ones((8, 4)))
print("UNREACHABLE", flush=True)
"""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.update({"JAX_PLATFORMS": "cpu", "MXTPU_PS_ADDRS": srv.address,
                "MXTPU_PS_HEARTBEAT": "0", "MXTPU_PS_LOCAL": "0",
                "MXTPU_PROC_ID": "0", "MXTPU_NUM_PROCS": "1"})
    try:
        proc = subprocess.run([sys.executable, "-c", child], env=env,
                              capture_output=True, text=True, timeout=120)
        assert "READY" in proc.stdout, proc.stdout + proc.stderr
        assert "UNREACHABLE" not in proc.stdout
        assert proc.returncode == -9           # really SIGKILLed
        # applied prefix is consistent: each part 0 or 1 times, values
        # whole (the zero-copy receive can never tear a row)
        for i in range(8):
            sk = "w\x00%d" % i
            assert srv._clock[sk] in (0, 1)
            row = srv._table[sk]
            assert np.allclose(row, 0.0) or np.allclose(row, 1.0)
        applied = sum(srv._clock["w\x00%d" % i] for i in range(8))
        assert applied < 8                     # it really died mid-push
        # the successor (fresh origin = respawned worker) finishes the
        # job: its push is NOT deduped against the dead origin's seqs
        monkeypatch.setattr(ka, "_BIGARRAY_BOUND", 4)
        monkeypatch.setattr(ka, "_COALESCE_BYTES", 0)
        kv = _store(monkeypatch, srv.address)
        try:
            kv.push("w", mx.nd.ones((8, 4)))
            out = mx.nd.zeros((8, 4))
            kv.pull("w", out=out)
            got = out.asnumpy()
            # every row = prefix (0/1) + successor's 1
            for i in range(8):
                expect = 1.0 + (1.0 if srv._clock["w\x00%d" % i] == 2
                                else 0.0)
                assert np.allclose(got[i], expect), (i, got[i])
        finally:
            kv.close()
    finally:
        srv.stop()
    """The same-process shortcut must keep the matrix semantics: a
    post-apply sever replays through the same retry layer and the
    replay is seq-deduped — at-most-once holds with zero wire."""
    monkeypatch.setattr(ka, "_LOCAL_ON", True)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        with fault.inject("kind=sever,point=server.send,op=push,nth=1") \
                as inj:
            kv.push("w", mx.nd.ones((4,)))
        assert inj.stats()[0][4] == 1
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        assert srv._clock["w"] == 1 and srv._dup_n == 1
        s = kv.stats()
        assert s["local_reqs"] > 0             # it really went local
        assert s["retransmits"] >= 1
    finally:
        kv.close()
        srv.stop()


# ---------------------------------------------------------------------------
# serving rows: the model-serving request path through the same harness
# (mxtpu/serving; the full behavior matrix lives in tests/test_serving.py,
# these are the two wire-level rows of the fault matrix —
# sever @ server.send (op=predict)  -> lost ack AFTER compute: replay
#                                      with the ORIGINAL request id,
#                                      answered exactly once client-side
# kill  @ serve.batch               -> replica dies mid-batch: clients
#                                      fail over, replays answered by
#                                      the surviving replica)
# ---------------------------------------------------------------------------

def _serving_pair(batch_deadline_ms=10):
    from mxtpu.serving import InferenceEngine, ModelServer
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, 6))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Uniform(0.1))
    ap, xp = mod.get_params()

    def mkeng():
        return InferenceEngine(net, ap, xp, {"data": (6,)},
                               buckets=(4,), warm=False)

    s1 = ModelServer(mkeng(), model_name="fm",
                     batch_deadline_ms_=batch_deadline_ms).start()
    s2 = ModelServer(mkeng(), model_name="fm",
                     batch_deadline_ms_=batch_deadline_ms,
                     replicas=[s1.address]).start()
    s1._replicas.append(s2.address)
    return s1, s2, mkeng


def test_serving_spec_points_validate():
    rules = fault.parse_spec(
        "kind=drop,point=serve.request,op=predict,nth=2;"
        "kind=kill,point=serve.batch;"
        "kind=kill,point=serve.swap;"
        "kind=sever,point=publish.snapshot")
    assert rules[0].point == "serve.request"
    assert rules[1].point == "serve.batch"
    assert rules[2].point == "serve.swap"
    assert rules[3].point == "publish.snapshot"
    # signal kinds stay training-loop-only; transport kinds are free
    with pytest.raises(ValueError, match="worker.step"):
        fault.parse_spec("kind=nan_grad,point=serve.request")
    with pytest.raises(ValueError, match="worker.step"):
        fault.parse_spec("kind=join_worker,point=serve.batch")
    with pytest.raises(ValueError, match="worker.step"):
        fault.parse_spec("kind=split_shard,point=serve.swap")


def test_serving_sever_mid_predict_window(monkeypatch):
    """Lost predict ack (sever @ server.send, post-compute): the
    client's window fails, the health probe finds the replica alive,
    and the replay carries the ORIGINAL request id — the server sees
    the duplicate, the client delivers exactly one answer."""
    from mxtpu.serving import ServingClient
    s1, s2, mkeng = _serving_pair()
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        cli.hello()
        x = np.ones((1, 6), "f")
        warm = cli.predict(x)[0]                    # fault-free baseline
        with fault.inject(
                "kind=sever,point=server.send,op=predict,nth=1") as inj:
            out = cli.predict(x)[0]
        assert inj.stats()[0][4] == 1, "the sever never fired"
        np.testing.assert_array_equal(out, warm)    # same bits, once
        assert cli.stats()["replays"] >= 1
        dups = (s1.stats()["counters"]["dup_requests"]
                + s2.stats()["counters"]["dup_requests"])
        assert dups == 1, "replay did not carry the original rid"
    finally:
        s2.stop()
        s1.stop()


def test_serving_kill_replica_mid_batch(monkeypatch):
    """kind=kill @ serve.batch: the active replica crashes between
    coalescing and compute. Every in-flight client fails over and
    replays on the survivor; each request is answered exactly once,
    bit-identical to the fault-free engine."""
    import threading as _threading
    from mxtpu.serving import ServingClient
    s1, s2, mkeng = _serving_pair(batch_deadline_ms=20)
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        cli.hello()
        oracle = mkeng()
        rng = np.random.RandomState(5)
        xs = [rng.rand(1, 6).astype("f") for _ in range(4)]
        want = [oracle.predict([x])[0] for x in xs]
        outs, errs = {}, {}
        lock = _threading.Lock()

        def one(i):
            try:
                r = cli.predict(xs[i])[0]
                with lock:
                    outs[i] = r
            except Exception as e:
                with lock:
                    errs[i] = e

        with fault.inject("kind=kill,point=serve.batch,nth=1") as inj:
            ts = [_threading.Thread(target=one, args=(i,))
                  for i in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
        assert inj.stats()[0][4] == 1, "the kill never fired"
        assert not errs, errs
        assert len(outs) == 4
        for i, out in outs.items():
            np.testing.assert_array_equal(out, want[i][:1])
        assert cli.stats()["failovers"] >= 1
        alive = [s for s in (s1, s2) if not s._tcp.dying]
        assert len(alive) == 1
        assert alive[0].stats()["counters"]["responses"] >= 1
    finally:
        s2.stop()
        s1.stop()


# ---------------------------------------------------------------------------
# weight-rollout rows (ISSUE 11): the train→serve stream through the same
# harness (full behavior matrix in tests/test_rollout.py) —
# drop  @ serve.swap        -> version record lost; the replica keeps
#                              answering from the last COMPLETE version
#                              and the stream's watermark re-delivers
# sever @ serve.swap        -> weight stream severed mid-record: the
#                              sync round fails, serving is unaffected,
#                              the retry is an exact catch-up
# kill  @ serve.swap        -> replica dies mid-swap: clients fail over,
#                              the peer swaps the same version and
#                              answers the replays exactly once
# drop/sever @ publish.snapshot -> the trainer's publish is lost BEFORE
#                              any byte lands; subscribers never see a
#                              torn version
# kill  @ publish.snapshot  -> the parameter server crashes mid-publish;
#                              subscribers keep the last complete
#                              version
# ---------------------------------------------------------------------------

def test_weight_swap_drop_keeps_last_complete_version():
    """kind=drop @ serve.swap: the version record is lost at the swap
    choke point — never a half-swapped table, the replica answers from
    the last complete version; the next delivery of the SAME version
    (the watermark was not advanced) applies cleanly."""
    from mxtpu.serving import ServingClient
    s1, s2, mkeng = _serving_pair()
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        x = np.ones((1, 6), "f")
        _, ri = cli.predict2(x)
        assert ri["version"] == 0
        p1 = {n: v * 1.25
              for n, v in s1._engine.current_params().items()}
        with fault.inject(
                "kind=drop,point=serve.swap,nth=1,count=1") as inj:
            assert s1.swap_weights(p1, version=1) is None
        assert inj.stats()[0][4] == 1, "the drop never fired"
        assert s1.stats()["counters"]["swaps_dropped"] == 1
        _, ri = cli.predict2(x)
        assert ri["version"] == 0          # last complete version
        # re-delivery (stream catch-up) lands the same version
        assert s1.swap_weights(p1, version=1) == 1
        _, ri = cli.predict2(x)
        assert ri["version"] == 1
    finally:
        s2.stop()
        s1.stop()


def test_weight_stream_sever_mid_record_catches_up(tmp_path):
    """kind=sever @ serve.swap: the weight stream dies mid-record. The
    sync round surfaces the ConnectionError (counted), serving keeps
    the old version, and the NEXT round re-delivers from the watermark
    — the _ReplStream catch-up discipline on weights."""
    from mxtpu.serving import ServingClient, WeightPublisher, WeightSync
    s1, s2, mkeng = _serving_pair()
    sync = None
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        pub = WeightPublisher(str(tmp_path / "w"))
        pub.publish({n: v * 2.0
                     for n, v in s1._engine.current_params().items()})
        sync = WeightSync(s1, weight_dir=str(tmp_path / "w"), poll=0.05)
        with fault.inject(
                "kind=sever,point=serve.swap,nth=1,count=1") as inj:
            with pytest.raises(ConnectionError):
                sync.poll_once()
        assert inj.stats()[0][4] == 1, "the sever never fired"
        x = np.ones((1, 6), "f")
        _, ri = cli.predict2(x)
        assert ri["version"] == 0          # unaffected mid-sever
        assert sync.poll_once() == 1       # exact catch-up, fault gone
        _, ri = cli.predict2(x)
        assert ri["version"] == 1
    finally:
        if sync is not None:
            sync.stop()
        s2.stop()
        s1.stop()


def test_weight_swap_kill_mid_swap_fails_over_exactly_once():
    """kind=kill @ serve.swap: the active replica dies mid-swap. Its
    clients fail over with their ORIGINAL request ids; the peer (which
    received the same version record) answers every replay exactly
    once from the NEW version — zero acknowledged loss across the
    kill."""
    import threading as _threading
    from mxtpu.serving import ServingClient
    s1, s2, mkeng = _serving_pair(batch_deadline_ms=20)
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        cli.hello()
        p1 = {n: v * 1.5
              for n, v in s1._engine.current_params().items()}
        oracle = mkeng()
        oracle.swap_weights(p1, version=1)
        rng = np.random.RandomState(6)
        xs = [rng.rand(1, 6).astype("f") for _ in range(4)]
        want = [oracle.predict([x])[0] for x in xs]
        with fault.inject("kind=kill,point=serve.swap,nth=1") as inj:
            with pytest.raises((ConnectionError, RuntimeError)):
                s1.swap_weights(p1, version=1)   # dies mid-swap
            assert s2.swap_weights(p1, version=1) == 1
        assert inj.stats()[0][4] == 1, "the kill never fired"
        assert s1._tcp.dying and not s2._tcp.dying
        outs, errs = {}, {}
        lock = _threading.Lock()

        def one(i):
            try:
                r, ri = cli.predict2(xs[i])
                with lock:
                    outs[i] = (r[0], ri["version"])
            except Exception as e:
                with lock:
                    errs[i] = e

        ts = [_threading.Thread(target=one, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs
        assert len(outs) == 4              # exactly one answer each
        for i, (out, v) in outs.items():
            assert v == 1
            np.testing.assert_array_equal(out, want[i][:1])
        assert cli.stats()["failovers"] >= 1
    finally:
        s2.stop()
        s1.stop()


def test_publish_snapshot_drop_loses_publish_cleanly(tmp_path):
    """kind=drop @ publish.snapshot: the publish is lost BEFORE any
    byte is written — no torn snapshot, no version bump; the next
    publish lands normally with the next version number."""
    from mxtpu.serving import WeightPublisher
    pub = WeightPublisher(str(tmp_path / "w"))
    params = {"w": np.arange(4, dtype="f")}
    with fault.inject(
            "kind=drop,point=publish.snapshot,nth=1,count=1") as inj:
        assert pub.publish(params) is None
    assert inj.stats()[0][4] == 1, "the drop never fired"
    assert pub.versions() == [] and pub.version == 0
    assert pub.stats()["dropped"] == 1
    out = pub.publish(params)
    assert out["version"] == 1 and pub.versions() == [1]


def test_publish_snapshot_sever_crashes_trainer_mid_publish(tmp_path):
    """kind=sever @ publish.snapshot: the trainer-side publish dies
    mid-flight. The fault fires BEFORE the snapshot write, so
    subscribers can never observe a half-published version — the dir
    still holds only complete, digest-verified versions."""
    from mxtpu.serving import WeightPublisher
    pub = WeightPublisher(str(tmp_path / "w"))
    pub.publish({"w": np.zeros(4, "f")})
    with fault.inject(
            "kind=sever,point=publish.snapshot,nth=1,count=1") as inj:
        with pytest.raises(ConnectionError):
            pub.publish({"w": np.ones(4, "f")})
    assert inj.stats()[0][4] == 1, "the sever never fired"
    assert pub.versions() == [1]           # v2 never became visible
    out = pub.publish({"w": np.ones(4, "f")})
    assert out["version"] == 2 and pub.versions() == [1, 2]


def test_publish_snapshot_kill_takes_down_ps_mid_publish():
    """kind=kill @ publish.snapshot on the parameter server: the shard
    crashes mid-publish. The publishing client sees the connection
    die; the weight stream's published version never advances, so
    subscribers keep the last complete version."""
    srv = ka.ParameterServer()
    srv.start()
    conn = ka._ServerConn(srv.address, n_socks=1)
    try:
        conn.request("init", "w", np.ones(4, "f"))
        reply = conn.request("publish", None, None, False)
        assert reply[1]["version"] == 1
        with fault.inject(
                "kind=kill,point=publish.snapshot,nth=1") as inj:
            with pytest.raises((ConnectionError, RuntimeError)):
                conn.request("publish", None, None, False,
                             retries=0, timeout=5.0)
        assert inj.stats()[0][4] == 1, "the kill never fired"
        assert srv._tcp.dying
        assert srv._pub_version == 1       # v2 never became visible
    finally:
        conn.close()
        srv.stop()


# ---------------------------------------------------------------------------
# fused Module dist path (ISSUE 10): faults mid-grad-push-window
# ---------------------------------------------------------------------------

def _fused_dist_module(monkeypatch, kv, batches=4):
    """A Module on the fused dist fast path (async window) driven for
    ``batches`` fit-loop steps against ``kv``. Returns (module, number
    of trainable params)."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_MODULE_FUSED_DIST", "1")
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", "async")
    rng = np.random.RandomState(3)
    x = rng.rand(64, 8).astype("f")
    y = (rng.rand(64) * 4).astype("f")
    it = mx.io.NDArrayIter(x, y, batch_size=16,
                           label_name="softmax_label")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4,
                              name="ffd"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None and mod._fused.mode == "dist"
    pool = list(it)
    for i in range(batches):
        b = pool[i % len(pool)]
        mod.forward_backward(b)
        mod.update()
    mod._fused.flush()
    return mod, 2


def test_fused_dist_sever_mid_grad_push_window(monkeypatch):
    """Sever the connection after the server applied a fused-step
    pushpull but before its ack (the grad-push window is in flight):
    the window fails onto the retry layer, the replay of the applied
    sub-pushes is REFUSED by seq dedupe while still answering with the
    current value — each step's gradient lands exactly once and
    training completes."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        with fault.inject(
                "kind=sever,point=server.send,op=multi,nth=2") as inj:
            mod, n_params = _fused_dist_module(monkeypatch, kv,
                                               batches=4)
        assert inj.stats()[0][4] == 1, "the sever never fired"
        # exactly-once: every key's clock counts each step's push once
        for k, c in srv._clock.items():
            assert c == 4, (k, c)
        assert srv._dup_n >= 1          # the applied batch replayed
        s = kv.stats()
        assert s["retransmits"] >= 1    # window replay happened
        assert s["dup_pushes"] >= 1     # ...and was deduped
        args, _ = mod.get_params()
        for v in args.values():
            assert np.isfinite(v.asnumpy()).all()
    finally:
        kv.close()
        srv.stop()


def test_fused_dist_kill_primary_mid_grad_push_window(monkeypatch):
    """SIGKILL the primary inside a fused-step pushpull frame after a
    prefix of the step's sub-pushes applied (and sync-replicated): the
    client fails over IN PLACE, replays the whole window on the
    promoted backup, whose transferred dedupe seqs refuse the prefix —
    every gradient exactly once, zero acknowledged loss, and the fused
    path keeps training through the failover."""
    pri, bak, kv = _pair(monkeypatch)
    try:
        # 2 sub-pushes per step frame: nth=6 lands on the SECOND sub of
        # the third step, so the frame dies with a one-sub applied (and
        # sync-replicated) prefix for the replay to be refused on
        with fault.inject(
                "kind=kill,point=server.recv,op=pushpull,nth=6") as inj:
            mod, n_params = _fused_dist_module(monkeypatch, kv,
                                               batches=4)
        assert inj.stats()[0][4] == 1, "the kill never fired"
        assert bak._role == "primary"
        for k, c in bak._clock.items():
            assert c == 4, (k, c)
        assert bak._dup_n >= 1, "the replayed prefix must be refused"
        assert kv.stats()["failovers"] == 1
        assert mod._fused is not None and mod._fused.mode == "dist"
        args, _ = mod.get_params()
        for v in args.values():
            assert np.isfinite(v.asnumpy()).all()
    finally:
        kv.close()
        pri.stop()
        bak.stop()


# ---------------------------------------------------------------------------
# AMP half-width wire rows (ISSUE 12): the push payload's dtype IS the
# wire tag — replay/dedupe must be dtype-stable, the server table stays
# the fp32 master, and pushpull replies ride bf16 in kind.
# ---------------------------------------------------------------------------

def test_pushpull_bf16_wire_dtype_tag_replay_dedupe(monkeypatch):
    """A bf16 pushpull severed at server.send (applied; ack lost): the
    blind replay carries the SAME bf16 payload, the (origin, seq)
    dedupe refuses the re-apply, the retry still answers with the
    current value — and both the reply dtype (bf16, in kind) and the
    server table dtype (fp32 master) survive the replay."""
    import ml_dtypes
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        g = np.ones(4, ml_dtypes.bfloat16)
        out = mx.nd.zeros((4,))
        with fault.inject(
                "kind=sever,point=server.send,op=pushpull,nth=1") as inj:
            kv.push_pull("w", g, out=out)
        assert inj.stats()[0][4] == 1
        # applied exactly once into the fp32 master, replay refused
        assert srv._clock["w"] == 1
        assert srv._dup_n == 1
        assert srv._table["w"].dtype == np.float32
        np.testing.assert_allclose(srv._table["w"], np.ones(4))
        # the pull target got the post-update value, upcast to fp32
        assert out.dtype == np.float32
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        # the raw wire reply is bf16 — the in-kind half of the tag
        reply = kv._conn("w").request(
            "pushpull", "w", np.ones(4, ml_dtypes.bfloat16), 0,
            kv._origin, next(kv._seq))
        assert reply[0] == "ok"
        assert reply[1].dtype == ml_dtypes.bfloat16
        assert srv._table["w"].dtype == np.float32
    finally:
        kv.close()
        srv.stop()


def test_push_bf16_payload_upcasts_into_fp32_table(monkeypatch):
    """A plain bf16 push (the ShardedTrainer attach_kvstore wire, or a
    buffered replay): _wire_decode upcasts before the in-place apply,
    so the accumulate math never runs half-precision."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        import ml_dtypes
        kv.init("w", mx.nd.zeros((4,)))
        for _ in range(3):
            kv.push("w", np.full(4, 0.5, ml_dtypes.bfloat16))
        assert srv._table["w"].dtype == np.float32
        np.testing.assert_allclose(srv._table["w"], np.full(4, 1.5))
        assert srv._clock["w"] == 3
    finally:
        kv.close()
        srv.stop()


def test_module_step_fault_point_validates():
    """The module.step grammar row: nan_grad is valid there (the AMP
    loss-scale overflow drill), the elastic signal kinds are not (the
    guard owns the fleet callbacks)."""
    rules = fault.parse_spec("kind=nan_grad,point=module.step,nth=2")
    assert rules[0].point == "module.step"
    with pytest.raises(ValueError, match="join_worker"):
        fault.parse_spec("kind=join_worker,point=module.step")


# ---------------------------------------------------------------------------
# row-sparse pushpull (ISSUE 13): faults mid-sparse-wire. The matrix rows:
#   sever @ server.send op=spushpull -> replay refused by seq dedupe,
#       reply still carries the CURRENT row values (exactly-once apply)
#   kill primary mid-sparse-push     -> promoted backup holds the
#       forwarded prefix and REFUSES its replay; rows land exactly once
#   online split of an embedding shard -> row-range value + clock +
#       dedupe seqs + row-wise optimizer state move exactly-once
# ---------------------------------------------------------------------------

def test_sparse_pushpull_sever_replays_exactly_once(monkeypatch):
    """Sever after the server applied a sparse pushpull but before its
    ack: the blind replay carries the same (row_ids, rows) payload,
    the (origin, seq) watermark refuses the re-apply, and the retry's
    reply still gathers the current row values — rows land exactly
    once, the pull half stays fresh."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("emb", mx.nd.zeros((6, 3)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0,
                                          momentum=0.9,
                                          rescale_grad=1.0))
        ids = np.array([1, 4], "int64")
        out = mx.nd.zeros((6, 3))
        with fault.inject(
                "kind=sever,point=server.send,op=spushpull,nth=1") as inj:
            kv.sparse_push_pull("emb", ids, np.ones((2, 3), "f"),
                                out=out)
        assert inj.stats()[0][4] == 1, "the sever never fired"
        assert srv._clock["emb"] == 1          # applied exactly once
        assert srv._dup_n == 1                 # the replay was refused
        assert kv.stats()["retransmits"] >= 1
        got = out.asnumpy()
        np.testing.assert_allclose(got[ids], -np.ones((2, 3)))
        assert np.all(got[[0, 2, 3, 5]] == 0)  # untouched rows intact
        # momentum applied once, not twice: second push continues it
        kv.sparse_push_pull("emb", ids, np.ones((2, 3), "f"), out=out)
        np.testing.assert_allclose(out.asnumpy()[ids],
                                   np.full((2, 3), -2.9))
    finally:
        kv.close()
        srv.stop()


def test_sparse_push_kill_primary_refuses_replayed_prefix(monkeypatch):
    """SIGKILL the primary AFTER a sparse pushpull applied and
    sync-replicated but before its ack: the client fails over in
    place and replays the frame at the promoted backup, whose
    forwarded watermark REFUSES the re-apply — every row update
    exactly once, zero acknowledged loss, row values bit-intact."""
    pri, bak, kv = _pair(monkeypatch)
    try:
        kv.init("emb", mx.nd.zeros((6, 3)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0,
                                          rescale_grad=1.0))
        ids = np.array([2, 5], "int64")
        out = mx.nd.zeros((6, 3))
        kv.sparse_push_pull("emb", ids, np.ones((2, 3), "f"), out=out)
        with fault.inject(
                "kind=kill,point=server.send,op=spushpull,nth=1") as inj:
            kv.sparse_push_pull("emb", ids, np.ones((2, 3), "f"),
                                out=out)
        assert inj.stats()[0][4] == 1, "the kill never fired"
        assert bak._role == "primary"
        assert kv.stats()["failovers"] == 1
        # first frame refused (forwarded prefix), second applied fresh
        assert bak._clock["emb"] == 2
        assert bak._dup_n >= 1
        got = out.asnumpy()
        np.testing.assert_allclose(got[ids], -2 * np.ones((2, 3)))
        np.testing.assert_allclose(
            np.asarray(bak._table["emb"])[np.asarray(ids)],
            -2 * np.ones((2, 3)))
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_split_moves_sparse_embedding_state_exactly_once(monkeypatch):
    """Online split of a hot embedding shard: the sparse key moves with
    its value, clock, push-dedupe seqs and ROW-WISE optimizer state
    (numpy momentum table) — a replayed pre-split seq is refused at the
    new home, and the next fresh push continues the momentum sequence
    bit-for-bit with an unsplit control run."""
    src = ParameterServer().start()
    dst = ParameterServer().start()
    ctl = ParameterServer().start()
    kv = _store(monkeypatch, src.address)
    monkeypatch.setenv("MXTPU_PS_ADDRS", ctl.address)
    kv_ctl = mx.kv.create("dist_async")
    try:
        opt = dict(learning_rate=0.5, momentum=0.9, rescale_grad=1.0)
        ids = np.array([1, 4], "int64")
        for store in (kv, kv_ctl):
            store.init("emb", mx.nd.zeros((6, 3)))
            store.set_optimizer(mx.optimizer.SGD(**opt))
        out, out_ctl = mx.nd.zeros((6, 3)), mx.nd.zeros((6, 3))
        kv.sparse_push_pull("emb", ids, np.ones((2, 3), "f"), out=out)
        kv_ctl.sparse_push_pull("emb", ids, np.ones((2, 3), "f"),
                                out=out_ctl)
        reply = kv._conn("emb").request("split", dst.address, ["emb"])
        assert reply[0] == "ok" and reply[1]["moved"] == ["emb"]
        assert "emb" not in src._table
        # replay a PRE-SPLIT seq at the new home: the transferred
        # dedupe seqs refuse it (nothing double-applies)
        dst_conn = kv._conn_for_addr(dst.address)
        r = dst_conn.request("spush", "emb", ids, np.ones((2, 3), "f"),
                             0, kv._origin, 1)
        assert r == ("ok", "dup")
        assert dst._clock["emb"] == 1
        # fresh push routes via map_stale to dst and CONTINUES the
        # moved momentum state exactly like the unsplit control
        kv.sparse_push_pull("emb", ids, np.ones((2, 3), "f"), out=out)
        kv_ctl.sparse_push_pull("emb", ids, np.ones((2, 3), "f"),
                                out=out_ctl)
        assert dst._clock["emb"] == 2
        np.testing.assert_array_equal(out.asnumpy(), out_ctl.asnumpy())
        assert kv.stats()["map_reroutes"] >= 1
    finally:
        kv.close()
        kv_ctl.close()
        src.stop()
        dst.stop()
        ctl.stop()


# ---------------------------------------------------------------------------
# streaming data plane rows (ISSUE 18; full drills in test_streaming.py
# and the serve->train loop in test_dist_launch.py)
# ---------------------------------------------------------------------------

def test_stream_append_drop_no_torn_record(tmp_path):
    """drop @ stream.append: the injected loss sheds the record BEFORE
    any byte reaches the segment file — a concurrent tailer can never
    observe a torn record, only a clean gap the producer re-sends."""
    from mxtpu.streaming import StreamReader, StreamWriter
    w = StreamWriter(str(tmp_path), shard=0)
    w.append(b"first")
    with fault.inject("kind=drop,point=stream.append,nth=1") as inj:
        assert w.append(b"lost") is None
        assert inj.stats()[0][4] == 1
    seg, _ = w.append(b"second")
    records, _end, _sealed = StreamReader(str(tmp_path), 0).read(seg)
    assert [p for p, _ in records] == [b"first", b"second"]
    w.close()


def test_stream_sever_mid_tail_requeues_lease(monkeypatch, tmp_path):
    """sever @ stream.tail: the consumer dies mid-tail holding the
    segment lease; its bye requeues the lease and a successor replays
    the segment from the committed offset — exactly once (the clock
    totals in test_streaming.py's twin prove the arithmetic)."""
    from mxtpu.kvstore_async import stream_origin
    from mxtpu.streaming import StreamingIter, StreamWriter
    w = StreamWriter(str(tmp_path), shard=0)
    w.append(b"rec")
    w.close()
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, str(tmp_path), group="m", batch_size=1,
                           decode=None, idle_timeout=0.3, poll=0.01)
        with fault.inject("kind=sever,point=stream.tail,nth=1"):
            with pytest.raises(ConnectionError):
                it.iter_next()
        assert srv._cursors[stream_origin("m", 0, 0)]["outstanding"]
        kv.close()                          # bye -> lease requeues
        kv2 = _store(monkeypatch, srv.address)
        it2 = StreamingIter(kv2, str(tmp_path), group="m",
                            batch_size=1, decode=None,
                            idle_timeout=0.3, poll=0.01)
        assert it2.iter_next() is True      # successor owns the lease
        assert it2.getdata() == [b"rec"]
        kv2.stream_push([], it2.pending_commit())
        it2.commit_done()
        assert kv2.stream_offsets("m")[(0, 0)][1] is True
        kv2.close()
    finally:
        srv.stop()


def test_stream_killed_trainer_replay_refused(monkeypatch, tmp_path):
    """Trainer killed between the server durably applying a frame
    (grads + offset commit) and recording its success locally: the
    respawn re-derives the SAME (origin, seq) frame from the log and
    the server refuses the double — grads AND commit."""
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("w", mx.nd.zeros((2,)))
        frame_parts = [("w", np.ones((2,), "f"))]
        commit = ("m", 0, 0, 64, False)
        assert kv.stream_push(frame_parts, commit) is False  # applied
        # the respawn's bit-identical replay
        assert kv.stream_push(frame_parts, commit) is True   # refused
        out = mx.nd.zeros((2,))
        kv.pull("w", out=out)
        np.testing.assert_allclose(out.asnumpy(), 1.0)       # once
        assert srv._stream_dup == 1 and srv._clock["w"] == 1
        assert kv.stream_offsets("m")[(0, 0)] == (64, False)
    finally:
        kv.close()
        srv.stop()


# ---------------------------------------------------------------------------
# partition rows (ISSUE 19): epoch-fenced replication, split-brain
# prevention, probe-through-peer unreachable verdicts, heal-time
# reconciliation. The 10k-op acceptance drill with a control run and
# the full journal checker lives in ci/check_partition.py; these rows
# pin each mechanism in isolation.
# ---------------------------------------------------------------------------

# the whole client command surface toward one address — what a real
# network partition cuts (peer_info/join_backup/promote/repl ride other
# links or other addrs and are scoped by their own rules)
_CLIENT_OPS = "push|pull|pushpull|spushpull|multi|init|hello|ping" \
              "|barrier|shard_map"


def _split_pair(monkeypatch, repl_mode="sync"):
    """_pair, but with addresses guaranteed substring-free of each
    other (partition rules match addr by substring)."""
    pri = ParameterServer(role="primary", repl_mode=repl_mode).start()
    bak = None
    for _ in range(4):
        bak = ParameterServer(role="backup", peer_addr=pri.address,
                              repl_mode=repl_mode).start()
        if pri.address not in bak.address \
                and bak.address not in pri.address:
            break
        bak.stop()
    pri._peer_addr = bak.address
    bak.join_cluster(probe_interval=0)
    _wait_for(lambda: bak._catchup_complete, what="initial catch-up")
    monkeypatch.setenv("MXTPU_PS_REPLICAS", "2")
    kv = _store(monkeypatch, pri.address)
    return pri, bak, kv


def test_partition_primary_from_clients_promotes_and_fences(monkeypatch):
    """partition @ client->primary mid-push-window: the failover probe
    finds the standby CAN still reach the primary, but the grace window
    is spent (grace=0) so availability wins — the backup is promoted
    and mints fencing epoch 2 while the cut-off incumbent still thinks
    it is primary at epoch 1. On heal the incumbent's own peer probe is
    the fencing trigger: it demotes, rejoins as backup and catches up;
    no acked push is lost and the pair reconverges bit-for-bit."""
    monkeypatch.setattr(ka, "_PARTITION_GRACE", 0.0)
    pri, bak, kv = _split_pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        for _ in range(3):
            kv.push("w", mx.nd.ones((4,)))
        with fault.inject("kind=partition,point=worker.send,"
                          "addr=%s,op=%s"
                          % (pri.address, _CLIENT_OPS)) as inj:
            for _ in range(3):
                kv.push("w", mx.nd.ones((4,)))
            assert inj.stats()[0][4] >= 1
            assert bak._role == "primary" and bak._epoch == 2
            assert bak._promotions == 1
            # the cut-off incumbent never heard the promotion: still
            # primary at epoch 1 — but no client can reach it, so no
            # two servers ack the same key in the same epoch
            assert pri._role == "primary" and pri._epoch == 1
            out = mx.nd.zeros((4,))
            kv.pull("w", out=out)     # served LIVE by the new primary
            np.testing.assert_allclose(out.asnumpy(), 6.0)
            h = kv.health()
            assert h["failovers"] == 1 and h["fence_epoch"] == 2
        # heal: one incumbent monitor tick fences + rejoins
        assert pri._probe_peer()
        assert pri._role == "backup" and pri._epoch == 2
        assert not pri._fenced        # rejoin completed
        _wait_for(lambda: pri._catchup_complete,
                  what="post-heal catch-up")
        for _ in range(2):            # sync acks mirror on pri again
            kv.push("w", mx.nd.ones((4,)))
        _wait_for(lambda: pri._clock.get("w") == 8,
                  what="replication to the rejoined backup")
        assert np.asarray(pri._table["w"]).tobytes() \
            == np.asarray(bak._table["w"]).tobytes()
        np.testing.assert_allclose(np.asarray(bak._table["w"]), 8.0)
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_partition_repl_link_sync_acks_solo_and_buffers(monkeypatch):
    """partition @ primary->backup in sync mode: an ack blocks only
    until the send failure kills the stream, then the primary acks
    solo — loudly unreplicated — and keeps the cut records for
    heal-time reconciliation. Reattach streams the whole table back
    (reconciliation window included) and redundancy returns."""
    pri, bak, kv = _split_pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        kv.push("w", mx.nd.ones((4,)))
        assert bak._clock.get("w") == 1     # sync ack == mirrored
        with fault.inject("kind=partition,point=worker.send,"
                          "addr=%s,op=repl" % bak.address) as inj:
            # the push STILL acks (liveness): the dead stream is
            # detected within the sync budget and the record kept
            kv.push("w", mx.nd.ones((4,)))
            assert inj.stats()[0][4] >= 1
            _wait_for(lambda: pri._repl_lost, what="stream detach")
            kv.push("w", mx.nd.ones((4,)))  # solo from the start
            assert pri._clock["w"] == 3
            assert bak._clock.get("w") == 1  # frozen mid-cut
            with pri._ctr_lock:
                kept = len(pri._unreplicated)
            assert kept == 2
            assert kv.stats()["replication"][0]["repl"] is None
        # heal: the backup's own monitor tick reattaches it
        assert bak._probe_peer()
        assert not pri._repl_lost and pri._unreplicated == []
        _wait_for(lambda: bak._clock.get("w") == 3,
                  what="post-heal catch-up")
        assert np.asarray(bak._table["w"]).tobytes() \
            == np.asarray(pri._table["w"]).tobytes()
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_asymmetric_cut_unreachable_not_dead_no_promotion(monkeypatch):
    """Only the CLIENT's link to the primary is cut; the standby can
    still reach it (peer_alive). Within MXTPU_PS_PARTITION_GRACE the
    verdict is 'unreachable', NOT 'dead': no promotion, pushes buffer
    with their original seqs, pulls degrade to the cached value, and
    the heal-time health sweep flushes everything — zero loss, zero
    failovers (satellite: health() tells the two states apart)."""
    monkeypatch.setattr(ka, "_PARTITION_GRACE", 60.0)
    pri, bak, kv = _split_pair(monkeypatch)
    try:
        kv.init("w", mx.nd.zeros((4,)))
        kv.push("w", mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("w", out=out)               # warm the pull cache
        with fault.inject("kind=partition,point=worker.send,"
                          "addr=%s,op=%s" % (pri.address, _CLIENT_OPS)):
            for _ in range(2):
                kv.push("w", mx.nd.ones((4,)))   # buffered, not lost
            h = kv.health()
            assert h["num_unreachable"] == 1 and h["num_dead"] == 0
            assert h["failovers"] == 0
            assert h["pending_pushes"] == 2
            assert h["servers"][0]["state"] == "unreachable"
            assert bak._role == "backup" and bak._epoch == 1, \
                "a healthy-but-unreachable primary must not be deposed"
            kv.pull("w", out=out)           # degraded cached value
            np.testing.assert_allclose(out.asnumpy(), 1.0)
            assert "w" in kv.health()["degraded_keys"]
        # heal: one health sweep re-registers and flushes the buffer
        kv._check_health()
        assert kv.health()["pending_pushes"] == 0
        _wait_for(lambda: bak._clock.get("w") == 3,
                  what="flushed pushes to replicate")
        np.testing.assert_allclose(np.asarray(pri._table["w"]), 3.0)
        kv.pull("w", out=out)               # live again: marker clears
        np.testing.assert_allclose(out.asnumpy(), 3.0)
        h = kv.health()
        assert h["failovers"] == 0 and h["num_unreachable"] == 0
        assert h["degraded_keys"] == []
    finally:
        kv.close()
        pri.stop()
        bak.stop()


def test_split_brain_heal_reconciles_bit_equal(monkeypatch, tmp_path):
    """The full lifecycle in miniature (ci/check_partition.py is the
    10k-op version): async-mode divergence window buffered at the
    cut-off primary, backup promoted under epoch 2, heal-time
    reconciliation replays the window at the new primary EXACTLY once
    — the client's post-failover seqs sit ABOVE the window's, so the
    (origin, key) watermarks alone could not dedupe the replay (the
    regression this row pins) — and the journal checker proves no
    acked write was lost."""
    monkeypatch.setattr(ka, "_PARTITION_GRACE", 0.0)
    monkeypatch.setenv("MXTPU_HISTORY_DIR", str(tmp_path))
    consistency.reset()
    try:
        pri, bak, kv = _split_pair(monkeypatch, repl_mode="async")
        try:
            kv.init("w", mx.nd.zeros((4,)))
            for _ in range(2):
                kv.push("w", mx.nd.ones((4,)))
            _wait_for(lambda: bak._clock.get("w") == 2,
                      what="warm-up replication")
            # divergence: repl link cut, the primary acks + buffers
            with fault.inject("kind=partition,point=worker.send,"
                              "addr=%s,op=repl" % bak.address):
                for _ in range(3):
                    kv.push("w", mx.nd.ones((4,)))
                _wait_for(lambda: pri._repl_lost, what="stream detach")
                _wait_for(lambda: pri._clock.get("w") == 5,
                          what="solo acks")
            with pri._ctr_lock:
                assert len(pri._unreplicated) == 3
            # split: clients lose the primary, the backup is promoted
            with fault.inject("kind=partition,point=worker.send,"
                              "addr=%s,op=%s"
                              % (pri.address, _CLIENT_OPS)):
                for _ in range(3):
                    kv.push("w", mx.nd.ones((4,)))
                assert bak._role == "primary" and bak._epoch == 2
            # heal: fence via the peer probe, reconcile, demote
            assert pri._probe_peer()
            assert pri._role == "backup" and pri._epoch == 2
            _wait_for(lambda: bak._clock.get("w") == 8,
                      what="reconciled divergence window")
            _wait_for(lambda: pri._catchup_complete,
                      what="post-heal catch-up")
            for _ in range(2):
                kv.push("w", mx.nd.ones((4,)))
            _wait_for(lambda: bak._clock.get("w") == 10
                      and pri._clock.get("w") == 10,
                      what="post-heal convergence")
            np.testing.assert_allclose(
                np.asarray(bak._table["w"]), 10.0)
            assert np.asarray(pri._table["w"]).tobytes() \
                == np.asarray(bak._table["w"]).tobytes()
            assert kv.health()["failovers"] == 1
        finally:
            kv.close()
            pri.stop()
            bak.stop()
        consistency.reset()       # close the writer before reading
        report = consistency.check(str(tmp_path))
        assert report["ok"], report["violations"]
        assert sorted(report["epochs"]) == [1, 2]
        assert report["acked"] >= 10
    finally:
        consistency.reset()


def test_stale_epoch_cursor_done_is_fenced():
    """Epoch discipline on the server-owned cursor (tentpole b): a
    segment lease granted before a partition cannot be retired under
    its stale grant epoch once the shard was re-granted after the heal
    — the late completion gets the ``fenced`` verdict, so two tailers
    can never both retire one segment."""
    srv = ParameterServer(role="primary").start()
    conn = ka._ServerConn(srv.address)
    try:
        conn.request("hello", "tailer-a", 0)
        r = conn.request("cursor_next", "tailer-a", "seg", 1, "r1")
        assert r[1] == 0 and r[3] == 1     # granted under epoch 1
        # the fleet moves on: a promotion elsewhere minted epoch 2 and
        # this server adopted it at the rejoin handshake (white-box
        # stand-in — the full adoption path runs in the rows above)
        with srv._repl_guard:
            srv._epoch = 2
        conn.request("bye", "tailer-a")    # death requeues the lease
        conn.request("hello", "tailer-b", 0)
        r2 = conn.request("cursor_next", "tailer-b", "seg", 1, "r2")
        assert r2[1] == 0 and r2[3] == 2   # re-granted under epoch 2
        # the partitioned ex-holder's late completion: refused
        with pytest.raises(RuntimeError, match="fenced"):
            conn.request("cursor_done", "tailer-a", "seg", 0, 1,
                         retries=0)
        assert 0 not in srv._cursors["seg"]["done"]
        # the current holder retires it fine
        conn.request("cursor_done", "tailer-b", "seg", 0, 2)
        assert 0 in srv._cursors["seg"]["done"]
    finally:
        conn.close()
        srv.stop()


def test_stream_lease_lost_across_heal_is_yielded(monkeypatch):
    """Client half of the cursor fencing: stream_lease_done meeting a
    ``fenced`` refusal treats the lease as LOST — the new holder owns
    the segment — instead of raising into the consumer loop, and the
    witnessed epoch is adopted."""
    from mxtpu.kvstore_async import stream_origin
    srv = ParameterServer(role="primary").start()
    kv = _store(monkeypatch, srv.address)
    kv2 = None
    try:
        lease = stream_origin("g", 0, 0)
        assert kv.stream_lease(lease) == "owned"
        with srv._repl_guard:
            srv._epoch = 2
        srv._drop_worker(kv._origin)   # requeue, as a GC'd death would
        kv2 = _store(monkeypatch, srv.address)
        assert kv2.stream_lease(lease) == "owned"
        kv.stream_lease_done(lease)        # fenced -> lease yielded
        assert kv._fleet_epoch == 2
        assert srv._cursors[lease]["outstanding"] == {0: kv2._origin}
        assert 0 not in srv._cursors[lease]["done"]
        kv2.stream_lease_done(lease)       # the real holder retires it
        assert 0 in srv._cursors[lease]["done"]
    finally:
        kv.close()
        if kv2 is not None:
            kv2.close()
        srv.stop()
