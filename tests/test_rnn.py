"""mx.rnn tests, modeled on the reference tests/python/unittest/test_rnn.py:
cell unroll shapes, stacked/bidirectional composition, fused<->unfused
weight conversion, bucketing iterator, and an end-to-end bucketing LM
training run (the PTB-style config, BASELINE configs item 4).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def _check_unroll(cell, num_hidden, t=3, b=2, d=4):
    inputs = [mx.sym.var("t%d_data" % i) for i in range(t)]
    outputs, states = cell.unroll(t, inputs)
    out = mx.sym.Group(outputs) if isinstance(outputs, list) else outputs
    shape_kwargs = {"t%d_data" % i: (b, d) for i in range(t)}
    arg_shapes, out_shapes, _ = out.infer_shape_partial(**shape_kwargs)
    return out, out_shapes


def test_rnn_cell_unroll():
    cell = mx.rnn.RNNCell(10, prefix="rnn_")
    out, shapes = _check_unroll(cell, 10)
    assert sorted(cell.params._params.keys()) == [
        "rnn_h2h_bias", "rnn_h2h_weight", "rnn_i2h_bias", "rnn_i2h_weight"]
    assert all(s == (2, 10) for s in shapes)


def test_lstm_cell_unroll():
    cell = mx.rnn.LSTMCell(16, prefix="lstm_")
    out, shapes = _check_unroll(cell, 16)
    assert all(s == (2, 16) for s in shapes)


def test_gru_cell_unroll():
    cell = mx.rnn.GRUCell(16, prefix="gru_")
    out, shapes = _check_unroll(cell, 16)
    assert all(s == (2, 16) for s in shapes)


def test_stacked_and_residual():
    stack = mx.rnn.SequentialRNNCell()
    stack.add(mx.rnn.LSTMCell(8, prefix="l0_"))
    stack.add(mx.rnn.ResidualCell(mx.rnn.LSTMCell(8, prefix="l1_")))
    stack.add(mx.rnn.DropoutCell(0.2))
    inputs = [mx.sym.var("t%d_data" % i) for i in range(3)]
    outputs, states = stack.unroll(3, inputs)
    assert len(states) == 4  # 2 lstm cells x (h, c)
    out = mx.sym.Group(outputs)
    _, out_shapes, _ = out.infer_shape_partial(
        **{"t%d_data" % i: (2, 8) for i in range(3)})
    assert all(s == (2, 8) for s in out_shapes)


def test_bidirectional():
    cell = mx.rnn.BidirectionalCell(mx.rnn.LSTMCell(6, prefix="l_"),
                                    mx.rnn.LSTMCell(6, prefix="r_"))
    inputs = [mx.sym.var("t%d_data" % i) for i in range(3)]
    outputs, states = cell.unroll(3, inputs)
    out = mx.sym.Group(outputs)
    _, out_shapes, _ = out.infer_shape_partial(
        **{"t%d_data" % i: (2, 5) for i in range(3)})
    assert all(s == (2, 12) for s in out_shapes)  # concat of fwd+bwd


def test_unpack_pack_weights_round_trip():
    cell = mx.rnn.LSTMCell(4, prefix="lstm_")
    rng = np.random.RandomState(0)
    args = {
        "lstm_i2h_weight": nd.array(rng.randn(16, 3).astype(np.float32)),
        "lstm_i2h_bias": nd.array(rng.randn(16).astype(np.float32)),
        "lstm_h2h_weight": nd.array(rng.randn(16, 4).astype(np.float32)),
        "lstm_h2h_bias": nd.array(rng.randn(16).astype(np.float32)),
    }
    orig = {k: v.asnumpy().copy() for k, v in args.items()}
    unpacked = cell.unpack_weights(dict(args))
    assert "lstm_i2h_i_weight" in unpacked
    assert "lstm_i2h_weight" not in unpacked
    packed = cell.pack_weights(unpacked)
    for k in orig:
        np.testing.assert_allclose(packed[k].asnumpy(), orig[k], rtol=1e-6)


def test_bucket_sentence_iter():
    rng = np.random.RandomState(1)
    sentences = [list(rng.randint(1, 20, size=l))
                 for l in rng.randint(2, 12, size=200)]
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8,
                                   buckets=[4, 8, 12], invalid_label=0)
    seen = 0
    for batch in it:
        assert batch.bucket_key in (4, 8, 12)
        assert batch.data[0].shape == (8, batch.bucket_key)
        # label is data shifted left
        d = batch.data[0].asnumpy()
        l = batch.label[0].asnumpy()
        np.testing.assert_array_equal(l[:, :-1], d[:, 1:])
        seen += 1
    assert seen > 3


def test_bucketing_module_lm_end_to_end():
    """Tiny PTB-style LM with BucketingModule over unrolled LSTM cells:
    perplexity must drop (reference example/rnn/lstm_bucketing.py)."""
    rng = np.random.RandomState(2)
    vocab = 16
    # learnable data: next token = (token + 1) % vocab
    sentences = []
    for _ in range(120):
        ln = rng.choice([4, 8])
        start = rng.randint(1, vocab)
        sentences.append([(start + i) % (vocab - 1) + 1 for i in range(ln)])
    it = mx.rnn.BucketSentenceIter(sentences, batch_size=8, buckets=[4, 8],
                                   invalid_label=0)

    num_hidden = 32

    def sym_gen(seq_len):
        data = mx.sym.var("data")
        label = mx.sym.var("softmax_label")
        embed = mx.sym.Embedding(data, input_dim=vocab, output_dim=16,
                                 name="embed")
        stack = mx.rnn.SequentialRNNCell()
        stack.add(mx.rnn.LSTMCell(num_hidden, prefix="lstm_l0_"))
        outputs, _ = stack.unroll(seq_len, inputs=embed, layout="NTC",
                                  merge_outputs=True)
        pred = mx.sym.Reshape(outputs, shape=(-1, num_hidden))
        pred = mx.sym.FullyConnected(pred, num_hidden=vocab, name="pred")
        label_r = mx.sym.Reshape(label, shape=(-1,))
        pred = mx.sym.SoftmaxOutput(pred, label_r, name="softmax")
        return pred, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=8,
                                 context=mx.cpu())
    metric = mx.metric.Perplexity(ignore_label=0)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod.init_params(mx.init.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    ppl = []
    for epoch in range(3):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward(batch)
            mod.update_metric(metric, batch.label)
            mod.backward()
            mod.update()
        ppl.append(metric.get()[1])
    assert ppl[-1] < ppl[0] * 0.9, ppl


def test_fused_rnn_cell_unroll_and_init():
    """FusedRNNCell unrolls via the scan RNN op; FusedRNN initializer
    fills the flat blob (weights nonzero, lstm forget bias set)."""
    cell = mx.rnn.FusedRNNCell(8, num_layers=2, mode="lstm",
                               get_next_state=True, prefix="flstm_")
    inputs = [mx.sym.var("t%d_data" % i) for i in range(4)]
    outputs, states = cell.unroll(4, inputs, merge_outputs=False)
    assert len(outputs) == 4 and len(states) == 2
    out = mx.sym.Group(outputs)
    _, out_shapes, _ = out.infer_shape_partial(
        **{"t%d_data" % i: (2, 6) for i in range(4)})
    assert all(s == (2, 8) for s in out_shapes)
    # initializer on the blob
    from mxtpu.ops.rnn import rnn_param_size
    sz = rnn_param_size("lstm", 6, 8, 2, False)
    blob = nd.zeros((sz,))
    mx.init.FusedRNN(None, 8, 2, "lstm")("flstm_parameters", blob)
    assert (blob.asnumpy() != 0).mean() > 0.4


def test_fused_unfuse_shapes_match():
    fused = mx.rnn.FusedRNNCell(8, num_layers=2, mode="gru",
                                prefix="g_")
    stack = fused.unfuse()
    inputs = [mx.sym.var("t%d_data" % i) for i in range(3)]
    outputs, _ = stack.unroll(3, inputs)
    out = mx.sym.Group(outputs)
    _, out_shapes, _ = out.infer_shape_partial(
        **{"t%d_data" % i: (2, 4) for i in range(3)})
    assert all(s == (2, 8) for s in out_shapes)


def test_fused_rnn_tnc_layout_batch_resolution():
    """begin_state batch must come from the RNN data's TNC batch dim,
    not the first bound shape's dim 0 (T != N here)."""
    t, n, c, h = 6, 2, 4, 8
    data = mx.sym.var("data")  # fed time-major [T, N, C]
    cell = mx.rnn.FusedRNNCell(h, num_layers=1, mode="lstm",
                               get_next_state=True, prefix="tnc_")
    outputs, states = cell.unroll(t, inputs=mx.sym.split(
        data, axis=0, num_outputs=t, squeeze_axis=True),
        merge_outputs=True, layout="TNC")
    out = mx.sym.Group([outputs] + states)
    arg_shapes, out_shapes, _ = out.infer_shape(data=(t, n, c))
    assert out_shapes[0] == (t, n, h)
    assert out_shapes[1] == (1, n, h)  # state batch == n, not t
    # executes too
    exe = out.simple_bind(mx.cpu(), data=(t, n, c))
    res = exe.forward(data=nd.ones((t, n, c)))
    assert res[0].shape == (t, n, h)


def test_rnn_symbol_json_round_trip():
    """RNN with state_outputs keeps 3 outputs across save/load."""
    data = mx.sym.var("data")
    p = mx.sym.var("p")
    s = mx.sym.var("s")
    sc = mx.sym.var("sc")
    r = mx.sym.RNN(data, p, s, sc, state_size=4, num_layers=1, mode="lstm",
                   state_outputs=True, name="r")
    assert len(r.list_outputs()) == 3
    r2 = mx.sym.load_json(r.tojson())
    assert len(r2.list_outputs()) == 3
    assert r2.list_outputs() == r.list_outputs()


def test_fused_unpack_weights_matches_unfused_numerics():
    """FusedRNNCell.unpack_weights slices the flat blob into the unfuse()
    stack's per-gate weights such that both graphs compute IDENTICAL
    outputs (the reference's fused-vs-unfused consistency check,
    tests/python/unittest/test_rnn.py), across modes x directions x
    depth."""
    from mxtpu.ops.rnn import rnn_param_size

    T, N, C, H = 5, 3, 4, 6
    rng = np.random.RandomState(7)
    x_np = rng.uniform(-1, 1, (N, T, C)).astype(np.float32)

    for mode in ("lstm", "gru", "rnn_tanh", "rnn_relu"):
        for bidir in (False, True):
            L = 2
            fused = mx.rnn.FusedRNNCell(H, num_layers=L, mode=mode,
                                        bidirectional=bidir,
                                        prefix="f_%s%d_" % (mode, bidir))
            size = rnn_param_size(mode, C, H, L, bidir)
            blob = nd.array(rng.uniform(-0.4, 0.4, (size,))
                            .astype(np.float32))

            inputs = [mx.sym.var("t%d" % i) for i in range(T)]
            fout, _ = fused.unroll(T, inputs, merge_outputs=True)
            shapes = {"t%d" % i: (N, C) for i in range(T)}
            fex = fout.simple_bind(mx.cpu(), grad_req="null", **shapes)
            for i in range(T):
                fex.arg_dict["t%d" % i][:] = x_np[:, i]
            fex.arg_dict[fused._parameter.name][:] = blob
            f_res = fex.forward(is_train=False)[0].asnumpy()

            stack = fused.unfuse()
            uout, _ = stack.unroll(T, [mx.sym.var("t%d" % i)
                                       for i in range(T)],
                                   merge_outputs=True)
            unpacked = fused.unpack_weights(
                {fused._parameter.name: blob})
            feed = stack.pack_weights(unpacked)
            uex = uout.simple_bind(mx.cpu(), grad_req="null", **shapes)
            for i in range(T):
                uex.arg_dict["t%d" % i][:] = x_np[:, i]
            for name, val in feed.items():
                uex.arg_dict[name][:] = val
            u_res = uex.forward(is_train=False)[0].asnumpy()
            np.testing.assert_allclose(
                f_res, u_res, rtol=1e-4, atol=1e-5,
                err_msg="%s bidir=%s" % (mode, bidir))

            # pack round-trips back to the exact blob
            repacked = fused.pack_weights(
                fused.unpack_weights({fused._parameter.name: blob}))
            np.testing.assert_allclose(
                repacked[fused._parameter.name].asnumpy(),
                blob.asnumpy(), rtol=1e-6)
