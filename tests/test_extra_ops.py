"""Coverage-sweep ops: bbox utilities, deformable conv/PSROI, legacy and
image ops (reference contrib/bounding_box.cc, deformable_*.cc, crop.cc,
image_random-inl.h, optimizer_op.cc)."""
import numpy as np
import jax.numpy as jnp
import pytest

import mxtpu as mx
from mxtpu import nd


def test_box_iou():
    a = nd.array(np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32))
    b = nd.array(np.array([[0, 0, 2, 2], [10, 10, 11, 11]], np.float32))
    iou = mx.nd.contrib.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou[0, 0], 1.0)
    np.testing.assert_allclose(iou[1, 0], 1.0 / 7.0, rtol=1e-5)
    np.testing.assert_allclose(iou[:, 1], 0.0)


def test_box_nms():
    # rows: [cls_id, score, x1, y1, x2, y2]
    rows = np.array([
        [0, 0.9, 0, 0, 2, 2],
        [0, 0.8, 0.1, 0.1, 2, 2],     # overlaps the first -> suppressed
        [0, 0.7, 5, 5, 6, 6],         # far away -> kept
        [1, 0.6, 0, 0, 2, 2],         # other class -> kept
        [0, -1.0, 0, 0, 1, 1],        # invalid
    ], np.float32)
    out = mx.nd.contrib.box_nms(nd.array(rows), overlap_thresh=0.5,
                                coord_start=2, score_index=1,
                                id_index=0).asnumpy()
    kept = out[out[:, 1] > 0]
    assert len(kept) == 3
    np.testing.assert_allclose(sorted(kept[:, 1])[::-1], [0.9, 0.7, 0.6])
    # force_suppress ignores class ids
    out2 = mx.nd.contrib.box_nms(nd.array(rows), overlap_thresh=0.5,
                                 coord_start=2, score_index=1, id_index=0,
                                 force_suppress=True).asnumpy()
    assert (out2[:, 1] > 0).sum() == 2


def test_bipartite_matching():
    score = np.array([[0.9, 0.1], [0.8, 0.7], [0.2, 0.2]], np.float32)
    rows, cols = mx.nd.contrib.bipartite_matching(nd.array(score),
                                                  threshold=0.5)
    rows, cols = rows.asnumpy(), cols.asnumpy()
    # greedy: (0,0)=0.9 first, then (1,1)=0.7; row 2 below threshold
    np.testing.assert_allclose(rows, [0, 1, -1])
    np.testing.assert_allclose(cols, [0, 1])


def test_deformable_conv_zero_offset_matches_conv():
    rng = np.random.RandomState(0)
    x = rng.standard_normal((1, 4, 7, 7)).astype(np.float32)
    w = rng.standard_normal((6, 4, 3, 3)).astype(np.float32)
    off = np.zeros((1, 2 * 9, 5, 5), np.float32)
    out = mx.nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(3, 3),
        num_filter=6, no_bias=True).asnumpy()
    ref = mx.nd.Convolution(nd.array(x), nd.array(w), kernel=(3, 3),
                            num_filter=6, no_bias=True).asnumpy()
    np.testing.assert_allclose(out, ref, atol=1e-4, rtol=1e-4)


def test_deformable_conv_integer_shift():
    # offset of exactly (0, +1) everywhere == shifting the input left
    rng = np.random.RandomState(1)
    x = rng.standard_normal((1, 2, 6, 6)).astype(np.float32)
    w = rng.standard_normal((3, 2, 1, 1)).astype(np.float32)
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[:, 1] = 1.0                      # x offset +1 for the single tap
    out = mx.nd.contrib.DeformableConvolution(
        nd.array(x), nd.array(off), nd.array(w), kernel=(1, 1),
        num_filter=3, no_bias=True).asnumpy()
    ref = mx.nd.Convolution(nd.array(np.roll(x, -1, axis=3)), nd.array(w),
                            kernel=(1, 1), num_filter=3,
                            no_bias=True).asnumpy()
    np.testing.assert_allclose(out[..., :-1], ref[..., :-1], atol=1e-4)


def test_deformable_psroi_pooling_uniform():
    # constant per-ps-channel data: each bin must return its own channel's
    # constant regardless of offsets
    out_dim, gs, P = 2, 2, 2
    C = out_dim * gs * gs
    data = np.zeros((1, C, 8, 8), np.float32)
    for c in range(C):
        data[0, c] = c
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = mx.nd.contrib.DeformablePSROIPooling(
        nd.array(data), nd.array(rois), spatial_scale=1.0,
        output_dim=out_dim, group_size=gs, pooled_size=P,
        no_trans=True).asnumpy()
    assert out.shape == (1, out_dim, P, P)
    # reference ctop-major layout: c = (ctop*gs + gh)*gs + gw
    for iy in range(P):
        for ix in range(P):
            chan = (iy * gs + ix)
            np.testing.assert_allclose(
                out[0, :, iy, ix],
                [d * gs * gs + chan for d in range(out_dim)])


def test_small_ops():
    x = nd.array(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = nd.array(np.zeros((3, 2), np.float32))
    assert mx.nd.reshape_like(x, y).shape == (3, 2)
    lab = nd.array(np.array([2.0, 0.0], np.float32))
    ce = mx.nd.softmax_cross_entropy(x, lab).asnumpy()
    logp = np.log(np.exp(x.asnumpy()) /
                  np.exp(x.asnumpy()).sum(1, keepdims=True))
    np.testing.assert_allclose(ce, -(logp[0, 2] + logp[1, 0]), rtol=1e-5)
    q = mx.nd.contrib.quadratic(nd.array(np.array([2.0], np.float32)),
                                a=1.0, b=2.0, c=3.0).asnumpy()
    np.testing.assert_allclose(q, [11.0])


def test_adagrad_update():
    w = nd.array(np.array([1.0, 2.0], np.float32))
    g = nd.array(np.array([0.5, -0.5], np.float32))
    h = nd.array(np.zeros(2, np.float32))
    new_w, new_h = mx.nd.adagrad_update(w, g, h, lr=0.1)
    np.testing.assert_allclose(new_h.asnumpy(), [0.25, 0.25])
    np.testing.assert_allclose(
        new_w.asnumpy(), [1.0 - 0.1 * 0.5 / 0.5, 2.0 + 0.1 * 0.5 / 0.5],
        rtol=1e-4)


def test_kl_sparse_reg_identity_forward():
    import jax
    from mxtpu.ops.extra_ops import identity_attach_kl_sparse_reg as klreg
    x = nd.array(np.array([[0.3, -0.2]], np.float32))
    out = mx.nd.IdentityAttachKLSparseReg(x)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy())
    # gradient = identity + penalty term (non-1 everywhere)
    g = jax.grad(lambda v: jnp.sum(klreg(v, penalty=0.1)))(
        jnp.array([[0.3, -0.2]], jnp.float32))
    assert np.all(np.abs(np.asarray(g) - 1.0) > 1e-6)
    # penalty=0 degenerates to pure identity
    g0 = jax.grad(lambda v: jnp.sum(klreg(v, penalty=0.0)))(
        jnp.array([[0.3, -0.2]], jnp.float32))
    np.testing.assert_allclose(np.asarray(g0), 1.0)


def test_crop_and_image_ops():
    x = nd.array(np.arange(2 * 3 * 6 * 6, dtype=np.float32)
                 .reshape(2, 3, 6, 6))
    like = nd.zeros((2, 3, 4, 4))
    out = mx.nd.Crop(x, like, center_crop=True)
    assert out.shape == (2, 3, 4, 4)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy()[:, :, 1:5, 1:5])

    img = nd.array(np.full((4, 5, 3), 255, np.uint8))
    t = mx.nd.image_to_tensor(img)
    assert t.shape == (3, 4, 5)
    np.testing.assert_allclose(t.asnumpy(), 1.0)
    norm = mx.nd.image_normalize(t, mean=(1.0, 1.0, 1.0),
                                 std=(0.5, 0.5, 0.5))
    np.testing.assert_allclose(norm.asnumpy(), 0.0)


def test_legacy_aliases():
    from mxtpu.ops import get_op
    assert get_op("Convolution_v1") is get_op("Convolution")
    assert get_op("Pooling_v1") is get_op("Pooling")
    assert get_op("_contrib_SparseEmbedding") is get_op("Embedding")


def test_box_nms_center_output():
    rows = np.array([[0, 0.9, 1.0, 1.0, 3.0, 5.0]], np.float32)
    out = mx.nd.contrib.box_nms(nd.array(rows), coord_start=2,
                                score_index=1, id_index=0,
                                in_format="corner",
                                out_format="center").asnumpy()
    np.testing.assert_allclose(out[0, 2:6], [2.0, 3.0, 2.0, 4.0])


def test_symbolic_crop_and_trans_inputs():
    # optional array inputs must flow through the SYMBOLIC frontend too
    d = mx.sym.var("d")
    like = mx.sym.var("like")
    s = mx.sym.Crop(d, like, center_crop=True)
    assert set(s.list_arguments()) == {"d", "like"}
    exe = s.simple_bind(mx.cpu(), grad_req="null", d=(1, 2, 6, 6),
                        like=(1, 2, 4, 4))
    out = exe.forward(is_train=False,
                      d=np.arange(72, dtype=np.float32).reshape(1, 2, 6, 6),
                      like=np.zeros((1, 2, 4, 4), np.float32))[0]
    assert out.shape == (1, 2, 4, 4)
    # without crop_like: no phantom variable is created
    s2 = mx.sym.Crop(d, h_w=(3, 3))
    assert s2.list_arguments() == ["d"]

    # DeformablePSROIPooling keeps its trans input symbolically
    data = mx.sym.var("data")
    rois = mx.sym.var("rois")
    trans = mx.sym.var("trans")
    ps = mx.sym.contrib.DeformablePSROIPooling(
        data, rois, trans, spatial_scale=1.0, output_dim=2, group_size=1,
        pooled_size=2, part_size=2, trans_std=0.1)
    assert set(ps.list_arguments()) == {"data", "rois", "trans"}


def test_symbolic_extra_positional_raises():
    d = mx.sym.var("d")
    e = mx.sym.var("e")
    import pytest
    with pytest.raises(TypeError):
        mx.sym.relu(d, e)        # relu takes one input: loud, not silent


def test_symbolic_adagrad_update():
    w = mx.sym.var("w")
    g = mx.sym.var("g")
    h = mx.sym.var("h")
    s = mx.sym.adagrad_update(w, g, h, lr=0.1)
    assert set(s.list_arguments()) == {"w", "g", "h"}
    exe = s.simple_bind(mx.cpu(), grad_req="null", w=(2,), g=(2,), h=(2,))
    outs = exe.forward(is_train=False,
                       w=np.array([1.0, 2.0], np.float32),
                       g=np.array([0.5, -0.5], np.float32),
                       h=np.zeros(2, np.float32))
    assert len(outs) == 2
    np.testing.assert_allclose(outs[1].asnumpy(), [0.25, 0.25])


def test_symbolic_none_positional_keeps_alignment():
    # a None in the middle must consume its slot, not shift later inputs
    x = mx.sym.var("x")
    b = mx.sym.var("b")
    s = mx.sym.FullyConnected(x, None, b, num_hidden=3, name="fc")
    args = s.list_arguments()
    assert "b" in args and "fc_weight" in args    # b bound as BIAS
    assert "fc_bias" not in args
