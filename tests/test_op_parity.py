"""Registry parity audit against the reference's operator registrations.

The op-name lists below are vendored verbatim from the reference source
(mechanically extracted; extraction commands in the comments). Every name
must resolve to one of our surfaces — the op registry (canonical name or
alias, with MXNet's leading-underscore "internal" prefix stripped), the
``nd.*`` / ``nd.sparse`` eager namespaces, or an NDArray method — or match
an explicitly justified subsumption rule. The test fails on any
unaccounted-for reference op AND on any subsumption entry that has become
stale (i.e. the op now resolves), so the audit can't rot in either
direction.

Reference extraction (regexes over src/operator --include=*.cc):
  NNVM_REGISTER_OP, MXNET_OPERATOR_REGISTER_<FAMILY>,
  MXNET_REGISTER_OP_PROPERTY
(macro-parameter artifacts ``name``/``__name``/``distr``/``sample_``
dropped).
"""
import re

import pytest

from mxtpu.ops.registry import _REGISTRY
import mxtpu.ndarray as nd
from mxtpu.ndarray import sparse as nd_sparse
import mxtpu.operator as legacy_operator

# -- src/operator NNVM_REGISTER_OP sites (reference, 166 names) -------------
REF_NNVM_OPS = [
    "BatchNorm", "BatchNorm_v1", "Cast", "Concat", "Convolution",
    "CuDNNBatchNorm", "Custom", "Deconvolution", "Dropout", "Embedding",
    "Flatten", "FullyConnected", "IdentityAttachKLSparseReg", "LRN",
    "LayerNorm", "LeakyReLU", "Pad", "Pooling", "Reshape", "SliceChannel",
    "SwapAxis", "UpSampling", "_arange", "_backward_Activation",
    "_backward_BatchNorm", "_backward_Concat", "_backward_Convolution",
    "_backward_CuDNNBatchNorm", "_backward_Custom", "_backward_Deconvolution",
    "_backward_Dropout", "_backward_Embedding", "_backward_FullyConnected",
    "_backward_LRN", "_backward_LayerNorm", "_backward_Pooling",
    "_backward_SoftmaxActivation", "_backward_SparseEmbedding",
    "_backward_UpSampling", "_backward_add", "_backward_batch_dot",
    "_backward_broadcast_add", "_backward_broadcast_div",
    "_backward_broadcast_hypot", "_backward_broadcast_maximum",
    "_backward_broadcast_minimum", "_backward_broadcast_mod",
    "_backward_broadcast_mul", "_backward_broadcast_power",
    "_backward_broadcast_sub", "_backward_cast", "_backward_clip",
    "_backward_contrib_bipartite_matching", "_backward_contrib_box_iou",
    "_backward_contrib_box_nms", "_backward_copy", "_backward_div",
    "_backward_dot", "_backward_gather_nd", "_backward_hypot",
    "_backward_linalg_gelqf", "_backward_linalg_gemm",
    "_backward_linalg_gemm2", "_backward_linalg_potrf",
    "_backward_linalg_potri", "_backward_linalg_sumlogdiag",
    "_backward_linalg_syevd", "_backward_linalg_syrk",
    "_backward_linalg_trmm", "_backward_linalg_trsm", "_backward_maximum",
    "_backward_minimum", "_backward_mod", "_backward_mul", "_backward_pick",
    "_backward_power", "_backward_repeat", "_backward_reverse",
    "_backward_sample_multinomial", "_backward_slice", "_backward_slice_axis",
    "_backward_softmax_cross_entropy", "_backward_sparse_retain",
    "_backward_squeeze", "_backward_stack", "_backward_sub", "_backward_take",
    "_backward_tile", "_backward_topk", "_backward_where",
    "_broadcast_backward", "_contrib_CTCLoss", "_contrib_SparseEmbedding",
    "_contrib_backward_quadratic", "_contrib_bipartite_matching",
    "_contrib_box_iou", "_contrib_box_nms", "_contrib_dequantize",
    "_contrib_quadratic", "_contrib_quantize", "_eye", "_full",
    "_identity_with_attr_like_rhs", "_image_normalize", "_image_to_tensor",
    "_linalg_gelqf", "_linalg_gemm", "_linalg_gemm2", "_linalg_potrf",
    "_linalg_potri", "_linalg_sumlogdiag", "_linalg_syevd", "_linalg_syrk",
    "_linalg_trmm", "_linalg_trsm", "_ones", "_sample_multinomial",
    "_scatter_set_nd", "_shuffle", "_slice_assign", "_slice_assign_scalar",
    "_sparse_adagrad_update", "_sparse_retain", "_zeros", "adam_update",
    "add_n", "argmax_channel", "argsort", "batch_dot", "batch_take",
    "cast_storage", "clip", "dot", "expand_dims", "ftml_update",
    "ftrl_update", "gather_nd", "khatri_rao", "mp_sgd_mom_update",
    "mp_sgd_update", "norm", "one_hot", "ones_like", "pick", "repeat",
    "reshape_like", "reverse", "rmsprop_update", "rmspropalex_update",
    "scatter_nd", "sgd_mom_update", "sgd_update", "signsgd_update",
    "signum_update", "slice", "slice_axis", "softmax_cross_entropy", "sort",
    "squeeze", "stack", "take", "tile", "topk", "transpose", "where",
    "zeros_like",
]

# -- MXNET_OPERATOR_REGISTER_* macro families (unary/binary/broadcast/
#    scalar/sample/reduce; 184 names after dropping macro-param artifacts) --
REF_MACRO_OPS = [
    "Activation", "SoftmaxActivation", "_div_scalar", "_equal_scalar",
    "_grad_add", "_greater_equal_scalar", "_greater_scalar", "_hypot_scalar",
    "_lesser_equal_scalar", "_lesser_scalar", "_maximum_scalar",
    "_minimum_scalar", "_minus_scalar", "_mod_scalar", "_mul_scalar",
    "_not_equal_scalar", "_plus_scalar", "_power_scalar", "_rdiv_scalar",
    "_rminus_scalar", "_rmod_scalar", "_rpower_scalar",
    "_scatter_elemwise_div", "_scatter_minus_scalar", "_scatter_plus_scalar",
    "_square_sum", "abs", "arccos", "arccosh", "arcsin", "arcsinh", "arctan",
    "arctanh", "broadcast_add", "broadcast_div", "broadcast_equal",
    "broadcast_greater", "broadcast_greater_equal", "broadcast_hypot",
    "broadcast_lesser", "broadcast_lesser_equal", "broadcast_maximum",
    "broadcast_minimum", "broadcast_mod", "broadcast_mul",
    "broadcast_not_equal", "broadcast_power", "broadcast_sub", "cbrt", "ceil",
    "cos", "cosh", "degrees", "elemwise_add", "elemwise_div", "elemwise_mul",
    "elemwise_sub", "exp", "expm1", "exponential", "fix", "floor", "gamma",
    "gammaln", "generalized_negative_binomial", "log", "log10", "log1p",
    "log2", "make_loss", "negative", "negative_binomial", "normal", "poisson",
    "radians", "reciprocal", "relu", "rint", "round", "rsqrt", "sigmoid",
    "sign", "sin", "sinh", "softsign", "sqrt", "square", "tan", "tanh",
    "trunc", "uniform",
]

# -- legacy MXNET_REGISTER_OP_PROPERTY sites (39 names) ---------------------
REF_LEGACY_OPS = [
    "BatchNorm_v1", "BilinearSampler", "Convolution_v1", "Correlation",
    "Crop", "GridGenerator", "IdentityAttachKLSparseReg", "InstanceNorm",
    "L2Normalization", "LeakyReLU", "MakeLoss", "Pad", "Pooling_v1", "RNN",
    "ROIPooling", "SVMOutput", "SequenceLast", "SequenceMask",
    "SequenceReverse", "SliceChannel", "Softmax", "SoftmaxOutput",
    "SpatialTransformer", "SwapAxis", "_CrossDeviceCopy", "_NDArray",
    "_Native", "_contrib_CTCLoss", "_contrib_DeformableConvolution",
    "_contrib_DeformablePSROIPooling", "_contrib_MultiBoxDetection",
    "_contrib_MultiBoxPrior", "_contrib_MultiBoxTarget",
    "_contrib_MultiProposal", "_contrib_PSROIPooling", "_contrib_Proposal",
    "_contrib_count_sketch", "_contrib_fft", "_contrib_ifft",
]

# ---------------------------------------------------------------------------
# Subsumption rules: reference registry entries that intentionally have no
# same-named op here because the capability lives elsewhere. Each rule is a
# (predicate, reason); a name matched by a rule must NOT also resolve
# directly (that would mean the rule is stale).
# ---------------------------------------------------------------------------
SUBSUMED_PREFIX = {
    "_backward_": "gradients come from jax.vjp of the forward op "
                  "(ops/registry.py); no per-op backward registrations",
}

SUBSUMED_EXACT = {
    "_broadcast_backward": "jax.vjp handles broadcast reduction in grads",
    "_contrib_backward_quadratic": "jax.vjp",
    "_grad_add": "gradient accumulation is jnp.add inside the vjp trace "
                 "(the inplace-addto pass is XLA fusion, VERDICT 2.2)",
    "_identity_with_attr_like_rhs": "Gradient-pass internal for zero grads; "
                                    "jax.vjp materializes zeros directly",
    "_scatter_set_nd": "NDArray.__setitem__ lowers to jax .at[].set",
    "_slice_assign": "NDArray.__setitem__ (ndarray/__init__.py)",
    "_slice_assign_scalar": "NDArray.__setitem__",
    "_crop_assign": "NDArray.__setitem__",
    "_crop_assign_scalar": "NDArray.__setitem__",
    "_scatter_elemwise_div": "sparse-gradient internal; eager sparse "
                             "arithmetic (ndarray/sparse.py) covers stypes",
    "_scatter_minus_scalar": "sparse-gradient internal",
    "_scatter_plus_scalar": "sparse-gradient internal",
    "_CrossDeviceCopy": "NDArray.as_in_context / jax.device_put; sharded "
                        "placement via ShardingRules (parallel/mesh.py)",
    "_NDArray": "legacy python-op bridge = operator.NDArrayOp",
    "_Native": "legacy native-op bridge = operator.NativeOp",
    "_sparse_retain": "eager sparse API nd.sparse.retain "
                      "(ndarray/sparse.py)",
}

# v0.x CamelCase aliases of the scalar/binary family and the scalar-op
# registrations: the public surface for these is operator overloading on
# NDArray/Symbol (__add__ with a python scalar, etc.), which both
# frontends implement; there is no string-keyed scalar-op dispatch to keep.
SCALAR_OP_RE = re.compile(r"^_(r?)(plus|minus|mul|div|mod|power|maximum|"
                          r"minimum|hypot|equal|not_equal|greater|lesser|"
                          r"greater_equal|lesser_equal)(_scalar)?$")


def _resolves(name):
    """True if the name maps onto a public surface of this framework."""
    cands = [name, name.lstrip("_")]
    # reference sampling ops: bare distribution name registered, exposed as
    # random_*/sample_* (python/mxnet/ndarray/random.py does the same remap)
    cands += ["random_" + name, "sample_" + name]
    for c in cands:
        if c in _REGISTRY:
            return True
        if hasattr(nd, c) or hasattr(nd_sparse, c):
            return True
        if hasattr(legacy_operator, c):
            return True
        if hasattr(nd.NDArray, c):
            return True
    return False


def _subsumed(name):
    for prefix, reason in SUBSUMED_PREFIX.items():
        if name.startswith(prefix) and name not in SUBSUMED_EXACT:
            return reason
    if name in SUBSUMED_EXACT:
        return SUBSUMED_EXACT[name]
    if SCALAR_OP_RE.match(name):
        return "scalar ops via NDArray/Symbol operator overloads"
    return None


ALL_REF_OPS = sorted(set(REF_NNVM_OPS + REF_MACRO_OPS + REF_LEGACY_OPS))


def test_every_reference_op_accounted_for():
    unaccounted = [n for n in ALL_REF_OPS
                   if not _subsumed(n) and not _resolves(n)]
    assert not unaccounted, (
        "reference ops with no implementation or subsumption rule: %r"
        % unaccounted)


def test_no_stale_subsumption_rules():
    # a SUBSUMED_EXACT key that resolves directly means the rule is stale
    # (or the op was added later) — keep the audit honest both ways.
    stale = [n for n in SUBSUMED_EXACT
             if n in _REGISTRY or n.lstrip("_") in _REGISTRY]
    assert not stale, "subsumption rules for ops that now exist: %r" % stale


def test_reference_list_sizes():
    # guard against accidental truncation of the vendored lists
    assert len(REF_NNVM_OPS) == 166
    assert len(REF_LEGACY_OPS) == 39
    assert len(set(ALL_REF_OPS)) >= 270


@pytest.mark.parametrize("name", [
    "eye", "sample_exponential", "sample_poisson",
    "sample_negative_binomial", "sample_generalized_negative_binomial",
    "broadcast_plus", "broadcast_minus", "make_loss",
])
def test_new_parity_surfaces_exist(name):
    assert name in _REGISTRY or hasattr(nd, name) or \
        hasattr(nd_sparse, name)


def test_eye_matches_numpy():
    import numpy as np
    out = nd.eye(4, 3, k=-1).asnumpy()
    assert np.array_equal(out, np.eye(4, 3, k=-1, dtype=np.float32))


def test_square_sum_row_sparse():
    import numpy as np
    dense = np.zeros((5, 3), np.float32)
    dense[1] = [1, 2, 3]
    dense[4] = [2, 0, 1]
    rsp = nd_sparse.array(dense).tostype("row_sparse")
    out = nd_sparse.square_sum(rsp, axis=1)
    assert np.allclose(out.asnumpy(), (dense ** 2).sum(axis=1))


def test_sample_family_shapes():
    import numpy as np
    lam = nd.array(np.array([1.0, 5.0], np.float32))
    s = getattr(nd, "sample_exponential")(lam, shape=(3,))
    assert s.shape == (2, 3)
    p = getattr(nd, "sample_poisson")(lam, shape=(4,))
    assert p.shape == (2, 4)
    k = nd.array(np.array([2.0, 3.0], np.float32))
    pr = nd.array(np.array([0.4, 0.6], np.float32))
    nb = getattr(nd, "sample_negative_binomial")(k, pr, shape=(3,))
    assert nb.shape == (2, 3)
    mu = nd.array(np.array([2.0, 3.0], np.float32))
    al = nd.array(np.array([0.0, 0.5], np.float32))
    gnb = getattr(nd, "sample_generalized_negative_binomial")(
        mu, al, shape=(3,))
    assert gnb.shape == (2, 3)
    assert np.all(gnb.asnumpy() >= 0)
