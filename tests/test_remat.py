"""Rematerialization (the reference's MXNET_BACKWARD_DO_MIRROR,
docs/faq/env_var.md: trade extra forward compute for backward memory).

mxtpu renders the mirror pass as jax.checkpoint over the differentiated
region (base.maybe_remat), reachable three ways: the env knob on a bound
Executor, ``hybridize(remat=True)`` per block, and
``ShardedTrainer(remat=True)``. These tests assert (a) the checkpoint
actually engages (the ``remat`` primitive appears in the jaxpr and the
backward recomputes forward ops), and (b) results are unchanged.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import gluon
from mxtpu.base import maybe_remat, backward_mirror_enabled
from mxtpu.gluon import nn
from mxtpu.parallel import MeshContext, ShardedTrainer


def test_maybe_remat_engages_and_preserves_grads():
    def deep(x, ws):
        for w in ws:
            x = jnp.tanh(x @ w)
        return x.sum()

    ws = [jnp.ones((16, 16)) * 0.1 for _ in range(6)]
    x = jnp.ones((4, 16))
    g_plain = jax.grad(deep, argnums=1)
    g_remat = jax.grad(maybe_remat(deep, enabled=True), argnums=1)
    jx_plain = str(jax.make_jaxpr(g_plain)(x, ws))
    jx_remat = str(jax.make_jaxpr(g_remat)(x, ws))
    assert "remat" not in jx_plain
    assert "remat" in jx_remat
    for a, b in zip(g_plain(x, ws), g_remat(x, ws)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6)
    # disabled -> identity
    assert maybe_remat(deep, enabled=False) is deep


def test_env_knob(monkeypatch):
    monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)
    assert not backward_mirror_enabled()
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    assert backward_mirror_enabled()
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "0")
    assert not backward_mirror_enabled()


def _mlp_sym():
    net = mx.sym.var("data")
    for i in range(4):
        net = mx.sym.FullyConnected(net, name="fc%d" % i, num_hidden=16)
        net = mx.sym.Activation(net, act_type="tanh")
    net = mx.sym.FullyConnected(net, name="out", num_hidden=4)
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _run_executor_grads(monkeypatch, mirror):
    if mirror:
        monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    else:
        monkeypatch.delenv("MXNET_BACKWARD_DO_MIRROR", raising=False)
    r = np.random.RandomState(0)
    sym = _mlp_sym()
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write",
                         data=(8, 12), softmax_label=(8,))
    assert ex._mirror == mirror
    for name, arr in ex.arg_dict.items():
        if name == "data":
            arr[:] = r.uniform(-1, 1, arr.shape).astype(np.float32)
        elif name == "softmax_label":
            arr[:] = r.randint(0, 4, arr.shape).astype(np.float32)
        else:
            arr[:] = r.uniform(-0.3, 0.3, arr.shape).astype(np.float32)
    ex.forward(is_train=True)
    ex.backward()
    return {n: g.asnumpy() for n, g in ex.grad_dict.items()
            if g is not None}


def test_executor_mirror_env_same_grads(monkeypatch):
    plain = _run_executor_grads(monkeypatch, False)
    mirrored = _run_executor_grads(monkeypatch, True)
    assert plain.keys() == mirrored.keys() and len(plain) > 3
    for n in plain:
        np.testing.assert_allclose(plain[n], mirrored[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def _gluon_loss_and_grads(remat):
    mx.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        for _ in range(3):
            net.add(nn.Dense(16, activation="tanh"))
        net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier(), force_reinit=True)
    net.hybridize(remat=remat)
    x = mx.nd.array(np.random.RandomState(1)
                    .uniform(-1, 1, (8, 12)).astype(np.float32))
    with mx.autograd.record():
        out = net(x)
        loss = (out * out).mean()
    loss.backward()
    grads = [p.grad().asnumpy() for p in net.collect_params().values()]
    return float(loss.asnumpy()), grads


def test_hybridize_remat_flag_same_results():
    l0, g0 = _gluon_loss_and_grads(remat=False)
    l1, g1 = _gluon_loss_and_grads(remat=True)
    np.testing.assert_allclose(l0, l1, rtol=1e-6)
    for a, b in zip(g0, g1):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-7)


@pytest.mark.parametrize("policy", [None, "dots"])
def test_sharded_trainer_remat(policy):
    kw = {}
    if policy == "dots":
        kw["remat_policy"] = \
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    losses = {}
    for remat in (False, True):
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(32, activation="tanh"))
            net.add(nn.Dense(32, activation="tanh"))
            net.add(nn.Dense(4))
        net.initialize(mx.init.Xavier(), force_reinit=True)
        r = np.random.RandomState(2)
        x = r.uniform(-1, 1, (16, 8)).astype(np.float32)
        y = r.randint(0, 4, (16,)).astype(np.float32)
        net(mx.nd.array(x[:2]))
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.1},
                            mesh=MeshContext(jax.devices()[:1], data=1),
                            remat=remat, **(kw if remat else {}))
        assert st._remat == remat
        losses[remat] = [st.step(x, y) for _ in range(4)]
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
    assert losses[True][-1] < losses[True][0]


def test_remat_policy_implies_remat_and_false_conflicts():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    pol = jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                        {"learning_rate": 0.1},
                        mesh=MeshContext(jax.devices()[:1], data=1),
                        remat_policy=pol)
    assert st._remat
    with pytest.raises(ValueError):
        ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                       {"learning_rate": 0.1},
                       mesh=MeshContext(jax.devices()[:1], data=1),
                       remat=False, remat_policy=pol)
