"""Worker-side resilience (mxtpu/resilience.py TrainGuard + the
trainer/iterator/scheduler state plumbing behind elastic resume).

Deterministic like the rest of the fault matrix: NaN/spike/stall events
come from the injection harness (or explicit calls) on exact step
schedules, and every assertion is on counters/values, never timing. The
rows this file covers:

fault / scenario                      -> defense proven
---------------------------------------------------------------------
nan_grad @ worker.step (skip policy)  -> in-jit finite check: params,
                                         opt state, aux and step count
                                         held; kvstore push dropped;
                                         server table stays finite
nan_grad (rollback policy)            -> M consecutive bad steps restore
                                         the last-good checkpoint
consecutive bad steps                 -> LR halved every K, scale rides
                                         checkpoints
finite loss spike                     -> EMA z-score soft anomaly: push
                                         withheld, streak counted
kill -9 / elastic resume (in-process  -> full worker state round-trips
half; the real SIGKILL e2e lives in      through CheckpointManager
test_dist_launch.py)                     (step, RNG, optimizer, LR
                                         schedule, iterator cursor)
"""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault, gluon
from mxtpu.gluon import nn
from mxtpu.checkpoint import CheckpointManager
from mxtpu.parallel import MeshContext, ShardedTrainer
from mxtpu.resilience import TrainGuard


@pytest.fixture(autouse=True)
def _clean_injector():
    fault.uninstall()
    yield
    fault.uninstall()


def _xy(seed=0):
    rs = np.random.RandomState(seed)
    return (rs.randn(8, 4).astype(np.float32),
            rs.randint(0, 10, (8,)).astype(np.float32))


def _trainer(seed=3, **kw):
    import mxtpu.gluon.block as _blk
    _blk._NAME_COUNTERS.clear()
    mx.random.seed(seed)
    np.random.seed(seed)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(16), nn.Activation("relu"), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x, _ = _xy()
    net(mx.nd.array(x))
    kw.setdefault("mesh", MeshContext(data=8))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", kw.pop("optimizer_params",
                                      {"learning_rate": 0.1,
                                       "momentum": 0.9}), **kw)
    return net, st


# ---------------------------------------------------------------------------
# the new fault kinds
# ---------------------------------------------------------------------------

def test_new_fault_kinds_parse_and_validate():
    rules = fault.parse_spec(
        "kind=nan_grad,point=worker.step,nth=3,count=2;"
        "kind=stall,point=worker.send,op=push,delay=0.01;"
        "kind=kill_worker,point=worker.step,nth=9")
    assert [r.kind for r in rules] == ["nan_grad", "stall", "kill_worker"]
    with pytest.raises(ValueError, match="worker.step"):
        fault.parse_spec("kind=nan_grad,point=server.recv")
    # kill_worker is valid at ANY point since ISSUE 4: at a server
    # point (scoped by role=server) it SIGKILLs a parameter-server
    # process — the replication failover drill. role= scopes a
    # launcher-wide spec to one process kind.
    (rule,) = fault.parse_spec(
        "kind=kill_worker,point=server.recv,op=push,role=server")
    assert rule.role == "server"
    assert not rule.matches("server.recv", "push", None), \
        "a role=server rule must not fire in a worker process"


def test_nan_grad_schedule_is_deterministic():
    inj = fault.FaultInjector(
        "kind=nan_grad,point=worker.step,nth=2,count=2")
    acts = [inj.fire("worker.step", op="step") for _ in range(5)]
    assert acts == [None, "nan_grad", "nan_grad", None, None]


# ---------------------------------------------------------------------------
# the guarded step: skip policy
# ---------------------------------------------------------------------------

def test_nan_grad_skipped_in_jit():
    """The acceptance row: injected NaN gradients with TrainGuard active
    leave params/opt-state/step-count untouched — selected in the SAME
    jitted program, not patched up afterwards — and the skip counters
    match the injection schedule exactly."""
    _, st = _trainer()
    x, y = _xy()
    guard = TrainGuard(st, spike_z=0)
    losses = [guard.step(x, y) for _ in range(2)]
    assert all(np.isfinite(l) for l in losses)
    w_good = np.asarray(st._param_vals[0]).copy()
    opt_good = np.asarray(st._opt_states[0][0]).copy()   # sgd momentum
    t_good = int(st._num_update)
    with fault.inject(
            "kind=nan_grad,point=worker.step,nth=1,count=3") as inj:
        bad = [guard.step(x, y) for _ in range(3)]
    assert inj.stats()[0][4] == 3
    assert all(np.isnan(l) for l in bad)      # caller sees the truth
    np.testing.assert_array_equal(np.asarray(st._param_vals[0]), w_good)
    np.testing.assert_array_equal(np.asarray(st._opt_states[0][0]),
                                  opt_good)
    assert int(st._num_update) == t_good      # LR schedule unmoved
    assert int(np.asarray(st._t_dev)) == t_good
    s = guard.stats()
    assert s["steps"] == 5 and s["good_steps"] == 2
    assert s["skipped"] == 3 and s["skipped_nonfinite"] == 3
    assert s["rollbacks"] == 0
    # and training continues cleanly once the injection window closes
    assert np.isfinite(guard.step(x, y))
    assert int(st._num_update) == t_good + 1


def test_guard_keeps_server_table_finite():
    """nan_grad + attached dist_async store: the poisoned step's push is
    dropped before it ever reaches the wire — the server table stays
    finite and the guard counters surface in kv.stats()['guard'] with
    exactly the injected schedule."""
    from mxtpu.kvstore_async import ParameterServer
    os.environ["MXTPU_PS_HEARTBEAT"] = "0"
    _, st = _trainer()
    x, y = _xy()
    srv = ParameterServer().start()
    os.environ["MXTPU_PS_ADDRS"] = srv.address
    kv = mx.kv.create("dist_async")
    try:
        guard = TrainGuard(st, spike_z=0)
        guard.attach_kvstore(kv)
        guard.step(x, y)
        with fault.inject(
                "kind=nan_grad,point=worker.step,nth=1,count=2") as inj:
            guard.step(x, y)
            guard.step(x, y)
        guard.step(x, y)
        st.flush_grad_pushes()
        assert inj.stats()[0][4] == 2
        # every table entry finite: no NaN was ever applied
        for k, v in srv._table.items():
            assert np.isfinite(v).all(), k
        # clocks prove the two bad steps' pushes never arrived
        names = [p.name for p in st._params if p.grad_req != "null"]
        for n in names:
            assert srv._clock[n] == 2, (n, srv._clock)
        s = kv.stats()
        assert s["guard"]["skipped_nonfinite"] == 2
        assert s["guard"]["good_steps"] == 2
        assert s["guard"]["rollbacks"] == 0
    finally:
        kv.close()
        srv.stop()
        del os.environ["MXTPU_PS_ADDRS"]


def test_lr_halving_on_consecutive_bad_steps():
    _, st = _trainer()
    x, y = _xy()
    guard = TrainGuard(st, spike_z=0, lr_halve_after=2)
    guard.step(x, y)
    lr0 = st.learning_rate
    with fault.inject("kind=nan_grad,point=worker.step,nth=1,count=4"):
        for _ in range(4):
            guard.step(x, y)
    assert st.learning_rate == pytest.approx(lr0 * 0.25)
    assert guard.stats()["lr_halvings"] == 2
    # a good step resets the streak, not the scale (the model earned
    # that caution) — scale persists until a rollback/restore says so
    guard.step(x, y)
    assert guard.stats()["bad_streak"] == 0
    assert st.learning_rate == pytest.approx(lr0 * 0.25)


def test_spike_detector_soft_anomaly():
    """A finite loss far outside the EMA distribution: the update
    already happened (finiteness was fine) but the gradients are
    withheld and the streak counts — a soft anomaly, not a skip."""
    _, st = _trainer()
    x, y = _xy()
    guard = TrainGuard(st, spike_z=3.0, spike_warmup=3, spike_window=10)
    seen = []

    def fake_push(grads):
        seen.append(len(grads))

    st.set_grad_push(fake_push)
    guard._trainer.set_guard(True)        # set_grad_push dropped caches
    for _ in range(4):
        guard.step(x, y)
    n_good = len(seen)
    assert n_good == 4
    # forge a spike through the real pipeline: poison the EMA baseline
    # comparison by feeding a loss 1000x the baseline — easiest done by
    # scaling the labels into nonsense for one step is NOT finite-safe,
    # so drive the detector directly with the real update path instead
    assert guard._spike_check(guard._ema_mean * 1000 + 1000.0)
    guard._c["spikes"] += 0               # (sanity: callable state)
    s = guard.stats()
    assert s["spikes"] == 0               # _spike_check alone is pure
    # and through step(): monkey-level injection via a huge-loss batch
    big = x * 1e18                        # finite loss, absurd scale
    loss = guard.step(big, y)
    if np.isfinite(loss):                 # spike path (not inf overflow)
        assert guard.stats()["spikes"] == 1
        assert len(seen) == n_good        # push withheld
    else:                                 # overflowed to inf -> hard skip
        assert guard.stats()["skipped_nonfinite"] == 1
        assert len(seen) == n_good


def test_rollback_policy_restores_last_good(tmp_path):
    _, st = _trainer()
    x, y = _xy()
    ck = CheckpointManager(str(tmp_path / "g"), async_save=False,
                           use_orbax=False)
    guard = TrainGuard(st, ckpt=ck, policy="rollback", rollback_after=3,
                       lr_halve_after=0, spike_z=0, ckpt_every=0)
    guard.step(x, y)
    guard.step(x, y)
    assert guard.save() == 2
    w_good = np.asarray(st._param_vals[0]).copy()
    with fault.inject("kind=nan_grad,point=worker.step,nth=1,count=3"):
        for _ in range(3):
            guard.step(x, y)
    s = guard.stats()
    assert s["rollbacks"] == 1 and s["restores"] == 1
    assert s["bad_streak"] == 0
    np.testing.assert_allclose(np.asarray(st._param_vals[0]), w_good,
                               rtol=1e-6)
    assert int(st._num_update) == 2
    assert np.isfinite(guard.step(x, y))


# ---------------------------------------------------------------------------
# elastic resume: full worker state round trip
# ---------------------------------------------------------------------------

def test_worker_state_roundtrip_matches_uninterrupted(tmp_path):
    """Save at step 3, keep training to 6; a FRESH process-alike
    (new net/trainer/iterator from the same seeds) restores the
    checkpoint, fast-forwards its iterator, trains the same 3 remaining
    steps — and lands on identical parameters and LR-schedule position.
    This is the in-process half of the e2e kill -9 parity test."""
    rs = np.random.RandomState(11)
    X = rs.randn(32, 4).astype(np.float32)
    Y = rs.randint(0, 10, (32,)).astype(np.float32)

    def build():
        import mxtpu.gluon.block as _blk
        _blk._NAME_COUNTERS.clear()
        mx.random.seed(5)
        np.random.seed(5)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16), nn.Activation("relu"), nn.Dense(10))
        net.initialize(mx.init.Xavier())
        net(mx.nd.array(X[:8]))
        sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
        sched.base_lr = 0.1
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.1,
                                    "momentum": 0.9,
                                    "lr_scheduler": sched},
                            mesh=MeshContext(data=8))
        it = mx.io.NDArrayIter(X, Y, batch_size=8)
        return net, st, it

    def advance(guard, it, st, n):
        for _ in range(n):
            try:
                b = it.next()
            except StopIteration:
                it.reset()
                b = it.next()
            guard.step(b.data[0], b.label[0])

    ckdir = str(tmp_path / "w")
    net, st, it = build()
    guard = TrainGuard(st, data_iter=it,
                       ckpt=CheckpointManager(ckdir, async_save=False,
                                              use_orbax=False),
                       ckpt_every=0, spike_z=0)
    advance(guard, it, st, 3)
    guard.save()
    advance(guard, it, st, 3)
    st.sync_params()
    want = {p.name: p.data().asnumpy().copy()
            for p in net._ordered_params()}
    want_lr = st.learning_rate

    net2, st2, it2 = build()
    guard2 = TrainGuard(st2, data_iter=it2,
                        ckpt=CheckpointManager(ckdir, async_save=False,
                                               use_orbax=False),
                        ckpt_every=0, spike_z=0)
    assert guard2.restore() == 3
    assert int(st2._num_update) == 3
    advance(guard2, it2, st2, 3)
    st2.sync_params()
    assert st2.learning_rate == pytest.approx(want_lr)
    for p in net2._ordered_params():
        np.testing.assert_allclose(
            p.data().asnumpy(), want[p.name], rtol=1e-6, atol=1e-7,
            err_msg="resume diverged at %s" % p.name)


def test_scheduler_state_rides_trainer_checkpoint(tmp_path):
    """Satellite: LR-scheduler progress (FactorScheduler's applied-decay
    counter) round-trips through CheckpointManager.save/restore with the
    trainer — a resume mid-schedule continues the decay ladder instead
    of replaying it from scratch."""
    net, st = _trainer(optimizer_params={
        "learning_rate": 1.0,
        "lr_scheduler": mx.lr_scheduler.FactorScheduler(step=2,
                                                        factor=0.5)})
    x, y = _xy()
    for _ in range(5):                    # two decays applied
        st.step(x, y)
    lr_mid = st.learning_rate
    assert lr_mid < 1.0
    ck = CheckpointManager(str(tmp_path / "s"), async_save=False,
                           use_orbax=False)
    st.sync_params()
    ck.save(5, net.collect_params(), trainer=st)

    net2, st2 = _trainer(optimizer_params={
        "learning_rate": 1.0,
        "lr_scheduler": mx.lr_scheduler.FactorScheduler(step=2,
                                                        factor=0.5)})
    ck.restore(5, net2.collect_params(), trainer=st2)
    assert int(st2._num_update) == 5
    assert st2.learning_rate == pytest.approx(lr_mid)
    sched = st2._optimizer.lr_scheduler
    assert sched.count == st._optimizer.lr_scheduler.count
    assert sched.base_lr == pytest.approx(
        st._optimizer.lr_scheduler.base_lr)


def test_scheduler_state_dicts():
    s = mx.lr_scheduler.FactorScheduler(step=3, factor=0.5)
    s.base_lr = 1.0
    s(7)                                   # decays applied
    st = s.state_dict()
    s2 = mx.lr_scheduler.FactorScheduler(step=3, factor=0.5)
    s2.load_state_dict(st)
    assert (s2.base_lr, s2.count) == (s.base_lr, s.count)
    m = mx.lr_scheduler.MultiFactorScheduler(step=[2, 4], factor=0.1)
    m.base_lr = 1.0
    m(3)
    m2 = mx.lr_scheduler.MultiFactorScheduler(step=[2, 4], factor=0.1)
    m2.load_state_dict(m.state_dict())
    assert (m2.base_lr, m2.count, m2.cur_step_ind) == \
        (m.base_lr, m.count, m.cur_step_ind)
