"""Mixed-precision training tier (reference tests/python/train/test_dtype.py
trains resnet at float16; the TPU-native dtype is bfloat16).

Covers the bench's exact bf16 configuration (ShardedTrainer
dtype='bfloat16') on the CPU mesh so the mixed-precision step is
validated without hardware: convergence, f32 master weights/optimizer
state/BN statistics, and agreement with the f32 step at loose tolerance.
Also the optimizer-level multi-precision contract (reference
mp_sgd_update: fp16 weights pair with an f32 master copy).
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn
from mxtpu.parallel import MeshContext, ShardedTrainer


def _toy_net():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Conv2D(8, 3, padding=1, activation="relu"))
        net.add(nn.BatchNorm())
        net.add(nn.GlobalAvgPool2D())
        net.add(nn.Dense(10))
    return net


def _toy_data(n=64, seed=0):
    r = np.random.RandomState(seed)
    y = r.randint(0, 10, n)
    protos = r.uniform(0, 1, (10, 3, 8, 8)).astype(np.float32)
    x = protos[y] + 0.1 * r.randn(n, 3, 8, 8).astype(np.float32)
    return x.astype(np.float32), y.astype(np.float32)


def test_bf16_trainer_converges_and_keeps_f32_state():
    mx.random.seed(0)
    net = _toy_net()
    net.initialize(mx.init.Xavier())
    x, y = _toy_data()
    net(mx.nd.array(x[:2]))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                        mesh=MeshContext(jax.devices()[:1], data=1),
                        dtype="bfloat16")
    first = st.step(x, y)
    losses = [st.step(x, y) for _ in range(60)]
    assert losses[-1] < first * 0.5, (first, losses[-1])
    # master weights, momentum and BN statistics all stay f32
    for v in st._param_vals:
        assert v.dtype == jnp.float32, v.dtype
    for v in st._aux_vals:
        assert v.dtype == jnp.float32, v.dtype
    for state in st._opt_states:
        for leaf in jax.tree_util.tree_leaves(state):
            assert leaf.dtype == jnp.float32, leaf.dtype


def test_bf16_step_tracks_f32_step():
    """One bf16 step from identical init lands near the f32 step (bf16
    has f32's exponent range; only mantissa precision differs)."""
    losses = {}
    for dtype in (None, "bfloat16"):
        mx.random.seed(0)
        net = _toy_net()
        net.initialize(mx.init.Xavier(), force_reinit=True)
        x, y = _toy_data()
        net(mx.nd.array(x[:2]))
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.05},
                            mesh=MeshContext(jax.devices()[:1], data=1),
                            dtype=dtype)
        losses[dtype] = [st.step(x, y) for _ in range(3)]
    f32, bf16 = losses[None], losses["bfloat16"]
    np.testing.assert_allclose(bf16, f32, rtol=0.05)


@pytest.mark.parametrize("opt_name", ["sgd", "adam"])
def test_optimizer_multi_precision_fp16_master_copy(opt_name):
    """update_multi_precision on float16 weights keeps an f32 master copy
    (reference optimizer_op-inl.h mp_sgd; optimizer.py multi_precision)."""
    opt = mx.optimizer.create(opt_name, learning_rate=0.1,
                              multi_precision=True)
    w16 = mx.nd.array(np.linspace(-1, 1, 8).astype(np.float16),
                      dtype="float16")
    g16 = mx.nd.array(np.full(8, 1e-3, np.float16), dtype="float16")
    state = opt.create_state_multi_precision(0, w16)

    def find_f32_master(st):
        if isinstance(st, mx.nd.NDArray):
            return st if (st.dtype == np.float32 and
                          st.shape == w16.shape) else None
        if isinstance(st, (tuple, list)):
            for s in st:
                m = find_f32_master(s)
                if m is not None:
                    return m
        return None

    master = find_f32_master(state)
    assert master is not None, "no f32 master copy in mp state"
    np.testing.assert_allclose(master.asnumpy(),
                               w16.asnumpy().astype(np.float32))
    master0 = master.asnumpy().copy()
    for _ in range(5):
        opt.update_multi_precision(0, w16, g16, state)
    # fp16 weight tracks the master (cast down)...
    master = find_f32_master(state)
    np.testing.assert_allclose(w16.asnumpy(),
                               master.asnumpy().astype(np.float16))
    # ...and the master actually moved from its fp16-initialized value by
    # roughly 5 steps worth of lr*g (sub-fp16-resolution updates are
    # exactly what the master copy exists to accumulate)
    delta = master0 - master.asnumpy()
    assert np.all(np.abs(delta) > 1e-4), delta
    if opt_name == "sgd":
        np.testing.assert_allclose(delta, np.full(8, 5 * 0.1 * 1e-3),
                                   rtol=0.05)
