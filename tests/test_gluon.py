"""Gluon tests (modeled on reference tests/python/unittest/test_gluon.py,
test_gluon_rnn.py, test_gluon_data.py, test_loss.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn


def test_parameter():
    p = gluon.Parameter("weight", shape=(10, 10))
    p.initialize(init="xavier")
    assert p.data().shape == (10, 10)
    assert p.grad().shape == (10, 10)
    assert p.list_data()[0] is p.data()


def test_parameter_invalid_access():
    p = gluon.Parameter("weight", shape=(10, 10))
    with pytest.raises(RuntimeError):
        p.data()


def test_paramdict():
    params = gluon.ParameterDict("net_")
    params.get("weight", shape=(10, 10))
    assert list(params.keys()) == ["net_weight"]
    params.initialize(ctx=mx.cpu())
    params.save("/tmp/test_paramdict.params")
    params.load("/tmp/test_paramdict.params", mx.cpu())


def test_dense():
    model = nn.Dense(128, activation="tanh", in_units=10, flatten=False,
                     prefix="test_dense_")
    inputs = mx.nd.zeros((2, 3, 10))
    model.initialize()
    out = model(inputs)
    assert out.shape == (2, 3, 128)
    assert list(model.collect_params().keys()) == \
        ["test_dense_weight", "test_dense_bias"]

    model2 = nn.Dense(64, activation="relu", in_units=30, prefix="fc_")
    inputs2 = mx.nd.zeros((17, 2, 15))
    model2.initialize()
    assert model2(inputs2).shape == (17, 64)


def test_hybrid_eager_consistency():
    np.random.seed(0)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(8))
    net.initialize()
    x = mx.nd.array(np.random.rand(4, 16))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, y_hybrid, rtol=1e-5, atol=1e-6)


def test_hybrid_backward_matches_eager():
    np.random.seed(0)

    def build():
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"))
            net.add(nn.Dense(4))
        return net

    net = build()
    net.initialize()
    x = mx.nd.array(np.random.rand(8, 10))
    label = mx.nd.array(np.random.randint(0, 4, (8,)))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    with mx.autograd.record():
        loss = loss_fn(net(x), label)
    loss.backward()
    eager_grads = {k: v.grad().asnumpy().copy()
                   for k, v in net.collect_params().items()}

    net.hybridize()
    with mx.autograd.record():
        loss = loss_fn(net(x), label)
    loss.backward()
    for k, v in net.collect_params().items():
        np.testing.assert_allclose(eager_grads[k], v.grad().asnumpy(),
                                   rtol=1e-4, atol=1e-5, err_msg=k)


def test_batchnorm_running_stats():
    layer = nn.BatchNorm(in_channels=4)
    layer.initialize()
    x = mx.nd.random_normal(loc=2.0, scale=3.0, shape=(16, 4, 5, 5))
    with mx.autograd.record():
        y = layer(x)
    # running mean moved toward batch mean
    rm = layer.running_mean.data().asnumpy()
    assert np.abs(rm).sum() > 0
    # inference mode uses running stats (no crash, deterministic)
    y1 = layer(x).asnumpy()
    y2 = layer(x).asnumpy()
    np.testing.assert_allclose(y1, y2)


def test_dropout_modes():
    layer = nn.Dropout(0.5)
    layer.initialize()
    x = mx.nd.ones((100, 100))
    # predict mode: identity
    np.testing.assert_allclose(layer(x).asnumpy(), x.asnumpy())
    with mx.autograd.record():
        y = layer(x)
    frac_zero = (y.asnumpy() == 0).mean()
    assert 0.3 < frac_zero < 0.7


def test_trainer_convergence():
    np.random.seed(0)
    net = nn.Dense(1, in_units=4, use_bias=False)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    w_true = np.array([[1.0, -2.0, 3.0, 0.5]], dtype=np.float32)
    loss_fn = gluon.loss.L2Loss()
    for _ in range(200):
        x = mx.nd.array(np.random.rand(16, 4))
        y = mx.nd.array(x.asnumpy() @ w_true.T)
        with mx.autograd.record():
            loss = loss_fn(net(x), y)
        loss.backward()
        trainer.step(16)
    np.testing.assert_allclose(net.weight.data().asnumpy(), w_true,
                               atol=1e-2)


def test_save_load_params(tmp_path):
    net = nn.HybridSequential(prefix="model_")
    with net.name_scope():
        net.add(nn.Dense(8, in_units=4))
        net.add(nn.Dense(2, in_units=8))
    net.initialize()
    x = mx.nd.ones((1, 4))
    y0 = net(x).asnumpy()
    fname = str(tmp_path / "net.params")
    net.save_params(fname)

    net2 = nn.HybridSequential(prefix="model_")
    with net2.name_scope():
        net2.add(nn.Dense(8, in_units=4))
        net2.add(nn.Dense(2, in_units=8))
    net2.load_params(fname)
    np.testing.assert_allclose(net2(x).asnumpy(), y0, rtol=1e-6)


def test_losses():
    pred = mx.nd.array([[1.0, 2.0, 3.0], [0.0, 0.0, 0.0]])
    label = mx.nd.array([2, 1])
    l = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label).asnumpy()
    # manual
    p = pred.asnumpy()
    lse = np.log(np.exp(p).sum(1))
    expected = np.array([lse[0] - p[0, 2], lse[1] - p[1, 1]])
    np.testing.assert_allclose(l, expected, rtol=1e-5)

    l2 = gluon.loss.L2Loss()(pred, mx.nd.zeros((2, 3))).asnumpy()
    np.testing.assert_allclose(l2, 0.5 * (p ** 2).mean(1), rtol=1e-5)

    l1 = gluon.loss.L1Loss()(pred, mx.nd.zeros((2, 3))).asnumpy()
    np.testing.assert_allclose(l1, np.abs(p).mean(1), rtol=1e-5)

    bce = gluon.loss.SigmoidBinaryCrossEntropyLoss()
    out = bce(mx.nd.array([[10.0]]), mx.nd.array([[1.0]])).asnumpy()
    assert out[0] < 1e-3

    hl = gluon.loss.HuberLoss()(pred, mx.nd.zeros((2, 3))).asnumpy()
    assert hl.shape == (2,)


def test_ctc_loss():
    loss = gluon.loss.CTCLoss(layout="TNC")
    T, N, C = 20, 2, 6
    acts = mx.nd.random_uniform(shape=(T, N, C))
    label = mx.nd.array([[2, 3], [4, 0]])
    l = loss(acts, label).asnumpy()
    assert l.shape == (N,)
    assert (l > 0).all()


def test_rnn_cells_unroll():
    for cell_cls, n_states in [(gluon.rnn.RNNCell, 1),
                               (gluon.rnn.LSTMCell, 2),
                               (gluon.rnn.GRUCell, 1)]:
        cell = cell_cls(16, input_size=8)
        cell.initialize()
        x = mx.nd.random_uniform(shape=(4, 5, 8))
        outs, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
        assert outs.shape == (4, 5, 16)
        assert len(states) == n_states


def test_fused_lstm_matches_cell():
    """Fused scan LSTM must agree with the unfused cell stepping."""
    np.random.seed(0)
    H, I, T, N = 8, 4, 6, 3
    layer = gluon.rnn.LSTM(H, input_size=I)
    layer.initialize()
    x = mx.nd.array(np.random.rand(T, N, I).astype(np.float32))
    out = layer(x)

    cell = gluon.rnn.LSTMCell(H, input_size=I)
    cell.initialize()
    # copy fused weights into the cell
    cell.i2h_weight.set_data(layer.l0_i2h_weight.data())
    cell.h2h_weight.set_data(layer.l0_h2h_weight.data())
    cell.i2h_bias.set_data(layer.l0_i2h_bias.data())
    cell.h2h_bias.set_data(layer.l0_h2h_bias.data())
    outs, _ = cell.unroll(T, x.swapaxes(0, 1), layout="NTC",
                          merge_outputs=True)
    np.testing.assert_allclose(out.asnumpy(),
                               outs.swapaxes(0, 1).asnumpy(),
                               rtol=1e-4, atol=1e-5)


def test_bidirectional_gru_shape():
    layer = gluon.rnn.GRU(12, num_layers=2, bidirectional=True,
                          input_size=6)
    layer.initialize()
    x = mx.nd.random_uniform(shape=(7, 2, 6))
    out, states = layer(x, layer.begin_state(2))
    assert out.shape == (7, 2, 24)
    assert states[0].shape == (4, 2, 12)


def test_sequential_rnn_cell():
    stack = gluon.rnn.SequentialRNNCell()
    stack.add(gluon.rnn.LSTMCell(16, input_size=8))
    stack.add(gluon.rnn.LSTMCell(16, input_size=16))
    stack.initialize()
    x = mx.nd.random_uniform(shape=(2, 5, 8))
    outs, _ = stack.unroll(5, x, layout="NTC", merge_outputs=True)
    assert outs.shape == (2, 5, 16)


def test_conv_layers():
    x = mx.nd.random_uniform(shape=(2, 3, 16, 16))
    layer = nn.Conv2D(8, 3, padding=1)
    layer.initialize()
    assert layer(x).shape == (2, 8, 16, 16)

    layer = nn.Conv2DTranspose(4, 2, strides=2, in_channels=3)
    layer.initialize()
    assert layer(x).shape == (2, 4, 32, 32)

    assert nn.MaxPool2D(2)(x).shape == (2, 3, 8, 8)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)

    x1 = mx.nd.random_uniform(shape=(2, 3, 16))
    layer = nn.Conv1D(8, 3)
    layer.initialize()
    assert layer(x1).shape == (2, 8, 14)


def test_model_zoo_smoke():
    """Construct every family; forward the small ones."""
    from mxtpu.gluon.model_zoo import vision as models
    net = models.get_model("resnet18_v1", classes=10, thumbnail=True)
    net.initialize()
    assert net(mx.nd.zeros((1, 3, 32, 32))).shape == (1, 10)
    net = models.get_model("mobilenet0.25", classes=7)
    net.initialize()
    assert net(mx.nd.zeros((1, 3, 224, 224))).shape == (1, 7)
    # constructors for the big variants (squeezenet1.0 has a distinct
    # first-conv config from 1.1, so keep it constructed here)
    for name in ["resnet50_v1", "resnet50_v2", "vgg16", "densenet201",
                 "mobilenet1.0", "squeezenet1.0", "vgg11"]:
        models.get_model(name)


def test_model_zoo_every_family_forwards():
    """One variant per family runs a real forward at its native input
    size (reference model zoo gluon/model_zoo/vision: resnet, vgg,
    alexnet, densenet, squeezenet, inception, mobilenet)."""
    from mxtpu.gluon.model_zoo import vision as models
    specs = [("resnet34_v2", 224), ("vgg11_bn", 224), ("alexnet", 224),
             ("densenet121", 224), ("squeezenet1.1", 224),
             ("inceptionv3", 299), ("mobilenet0.5", 224)]
    for name, hw in specs:
        net = models.get_model(name, classes=13)
        net.initialize()
        out = net(mx.nd.zeros((1, 3, hw, hw)))
        assert out.shape == (1, 13), name


def test_dataloader():
    X = np.random.rand(37, 5).astype(np.float32)
    y = np.arange(37).astype(np.float32)
    dataset = gluon.data.ArrayDataset(X, y)
    loader = gluon.data.DataLoader(dataset, batch_size=8, shuffle=False)
    batches = list(loader)
    assert len(batches) == 5
    assert batches[0][0].shape == (8, 5)
    assert batches[-1][0].shape == (5, 5)
    np.testing.assert_allclose(batches[0][1].asnumpy(), y[:8])

    # threaded workers produce the same batches in order
    loader2 = gluon.data.DataLoader(dataset, batch_size=8, shuffle=False,
                                    num_workers=2)
    for (a, _), (b, _) in zip(loader, loader2):
        np.testing.assert_allclose(a.asnumpy(), b.asnumpy())

    # last_batch=discard
    loader3 = gluon.data.DataLoader(dataset, batch_size=8,
                                    last_batch="discard")
    assert len(list(loader3)) == 4


def test_split_and_load():
    data = mx.nd.arange(0, 80).reshape((8, 10))
    splits = gluon.utils.split_data(data, 4)
    assert len(splits) == 4
    assert splits[0].shape == (2, 10)


def test_clip_global_norm():
    x1 = mx.nd.ones((3,)) * 3.0
    x2 = mx.nd.ones((4,)) * 4.0
    norm = gluon.utils.clip_global_norm([x1, x2], 1.0)
    total = np.sqrt((x1.asnumpy() ** 2).sum() + (x2.asnumpy() ** 2).sum())
    np.testing.assert_allclose(total, 1.0, rtol=1e-3)


def test_symbol_block():
    data = mx.sym.var("data")
    out = mx.sym.FullyConnected(data, name="fc1", num_hidden=6)
    out = mx.sym.Activation(out, act_type="relu")
    block = gluon.SymbolBlock(out, data)
    block.initialize()
    y = block(mx.nd.ones((2, 3)))
    assert y.shape == (2, 6)


def test_embedding_block():
    layer = nn.Embedding(10, 4)
    layer.initialize()
    idx = mx.nd.array([1, 2, 3])
    assert layer(idx).shape == (3, 4)
    # grads flow to weight
    with mx.autograd.record():
        out = layer(idx).sum()
    out.backward()
    g = layer.weight.grad().asnumpy()
    assert np.abs(g[1:4]).sum() > 0 and np.abs(g[5:]).sum() == 0


def test_hybridize_shape_change():
    """jit cache re-specializes per input shape like CachedOp rebind."""
    net = nn.Dense(4, in_units=3)
    net.initialize()
    net.hybridize()
    assert net(mx.nd.ones((2, 3))).shape == (2, 4)
    assert net(mx.nd.ones((5, 3))).shape == (5, 4)
