"""Compact O(nnz_max) row-sparse storage (reference row_sparse's memory
contract, include/mxnet/ndarray.h:61-66: a table bigger than device
memory, accessed row-wise — SparseEmbedding fwd/bwd, lazy optimizer
updates on stored rows, kvstore row_sparse_pull without densifying)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import gluon
from mxtpu.ndarray import sparse


VOCAB, DIM = 300_000, 16  # dense would be ~19 MB; compact is ~KBs


def _nbytes(arr):
    total = arr._data.size * arr._data.dtype.itemsize
    for v in arr._aux.values():
        total += v._data.size * v._data.dtype.itemsize
    return total


def test_device_memory_proportional_to_nnz_max():
    a = sparse.zeros("row_sparse", (VOCAB, DIM), nnz_max=32)
    assert a.shape == (VOCAB, DIM)
    assert a._data.shape == (32, DIM)
    dense_bytes = VOCAB * DIM * 4
    assert _nbytes(a) < dense_bytes / 1000
    # value round-trip through the host
    a._set_rows(np.array([7, 100_000]),
                a._data[:2] + 1.0)
    host = a.asnumpy()
    assert host.shape == (VOCAB, DIM)
    assert host[7, 0] == 1.0 and host[100_000, 0] == 1.0
    assert host.sum() == 2 * DIM
    # dense materialization on device is refused
    with pytest.raises(Exception, match="nnz_max rows"):
        a.todense()


def test_compact_constructors_merge_retain():
    a = sparse.compact_row_sparse_array(
        (np.array([[1.0] * DIM, [2.0] * DIM], "f"), np.array([10, 3])),
        shape=(VOCAB, DIM), nnz_max=8)
    np.testing.assert_array_equal(a.indices.asnumpy(), [3, 10])
    b = sparse.compact_row_sparse_array(
        (np.array([[5.0] * DIM], "f"), np.array([10])),
        shape=(VOCAB, DIM), nnz_max=4)
    m = sparse.compact_merge([a, b])
    np.testing.assert_array_equal(m.indices.asnumpy(), [3, 10])
    np.testing.assert_allclose(m.data.asnumpy()[1], [6.0] * DIM)
    r = m.retain([3, 77])
    np.testing.assert_array_equal(r.indices.asnumpy(), [3])
    np.testing.assert_allclose(r.data.asnumpy()[0], [2.0] * DIM)


def test_sparse_embedding_grad_matches_dense_gradcheck():
    """The compact sparse-embedding backward must equal the dense
    Embedding autograd gradient on the touched rows (and be zero-free
    elsewhere by construction)."""
    np.random.seed(0)
    vocab, dim, batch = 50, 4, 6
    ids = np.array([3, 7, 3, 49, 0, 7], "f")
    w0 = np.random.randn(vocab, dim).astype("f")
    head = np.random.randn(batch, dim).astype("f")

    # dense reference: plain take under autograd
    wd = mx.nd.array(w0)
    gd = mx.nd.zeros((vocab, dim))
    mx.autograd.mark_variables([wd], [gd])
    with mx.autograd.record():
        out = mx.nd.take(wd, mx.nd.array(ids).astype("int32"), axis=0)
        loss = mx.nd.sum(out * mx.nd.array(head))
    loss.backward()
    dense_grad = gd.asnumpy()

    # compact path through the gluon block
    emb = gluon.contrib.nn.SparseEmbedding(vocab, dim, nnz_max=8)
    emb.initialize()
    emb.weight.set_data(mx.nd.array(w0))
    with mx.autograd.record():
        out2 = emb(mx.nd.array(ids))
        loss2 = mx.nd.sum(out2 * mx.nd.array(head))
    loss2.backward()
    g = emb.weight._grad
    assert isinstance(g, sparse.CompactRowSparseNDArray)
    np.testing.assert_array_equal(g.indices.asnumpy(), [0, 3, 7, 49])
    np.testing.assert_allclose(g.asnumpy(), dense_grad, rtol=1e-5,
                               atol=1e-6)
    # forward values match the dense take
    np.testing.assert_allclose(out2.asnumpy(), out.asnumpy())


def test_sparse_embedding_trains_with_lazy_sgd():
    """End to end: SparseEmbedding + Trainer(sgd) converges on a toy
    classification task; the optimizer touches stored rows only."""
    np.random.seed(1)
    vocab, dim, classes = 120, 8, 4
    net = gluon.nn.Sequential()
    emb = gluon.contrib.nn.SparseEmbedding(vocab, dim, nnz_max=32)
    net.add(emb)
    net.add(gluon.nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    ids = np.random.randint(0, 40, (128,)).astype("f")  # rows 40+ untouched
    labels = (ids % classes).astype("f")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    w_before = emb.weight.data().asnumpy().copy()
    losses = []
    for _ in range(60):
        with mx.autograd.record():
            out = net(mx.nd.array(ids))
            loss = loss_fn(out, mx.nd.array(labels))
        loss.backward()
        trainer.step(len(ids))
        losses.append(float(mx.nd.mean(loss).asscalar()))
    assert losses[-1] < losses[0] * 0.2, (losses[0], losses[-1])
    w_after = emb.weight.data().asnumpy()
    # untouched rows were never updated (lazy semantics)
    np.testing.assert_array_equal(w_before[60:], w_after[60:])
    assert np.abs(w_before[:40] - w_after[:40]).sum() > 0


def test_kvstore_compact_pull_push_no_densify():
    """row_sparse_pull from a compact store moves rows compactly; pushes
    of compact grads union-merge without a dense buffer."""
    kv = mx.kv.create("local")
    table = sparse.compact_row_sparse_array(
        (np.arange(3 * DIM, dtype="f").reshape(3, DIM),
         np.array([5, 900, 200_000])),
        shape=(VOCAB, DIM), nnz_max=16)
    kv.init(0, table)
    dst = sparse.zeros("row_sparse", (VOCAB, DIM), nnz_max=8)
    kv.row_sparse_pull(0, out=dst, row_ids=mx.nd.array([900, 5]))
    np.testing.assert_array_equal(dst.indices.asnumpy(), [5, 900])
    np.testing.assert_allclose(dst.data.asnumpy()[0], np.arange(DIM))
    # a dense pull of the compact table is refused
    with pytest.raises(TypeError, match="row_sparse_pull"):
        kv.pull(0, out=mx.nd.zeros((VOCAB, DIM)))
    # compact push merge
    g1 = sparse.compact_row_sparse_array(
        (np.ones((1, DIM), "f"), np.array([900])),
        shape=(VOCAB, DIM), nnz_max=4)
    g2 = sparse.compact_row_sparse_array(
        (np.ones((2, DIM), "f"), np.array([900, 7])),
        shape=(VOCAB, DIM), nnz_max=4)
    seen = {}

    def updater(key, recv, local):
        seen["recv"] = recv

    kv._set_updater(updater)
    kv.push(0, [g1, g2])
    recv = seen["recv"]
    assert isinstance(recv, sparse.CompactRowSparseNDArray)
    np.testing.assert_array_equal(recv.indices.asnumpy(), [7, 900])
    np.testing.assert_allclose(recv.data.asnumpy()[1], [2.0] * DIM)


def test_lazy_update_on_compact_weight():
    """SGD on a compact weight updates resident rows in place; rows not
    in the gradient keep their value; non-resident gradient rows raise."""
    w = sparse.compact_row_sparse_array(
        (np.ones((3, DIM), "f"), np.array([2, 50, 9000])),
        shape=(VOCAB, DIM), nnz_max=8)
    g = sparse.compact_row_sparse_array(
        (np.full((2, DIM), 0.5, "f"), np.array([50, 9000])),
        shape=(VOCAB, DIM), nnz_max=4)
    opt = mx.optimizer.SGD(learning_rate=1.0, rescale_grad=1.0, wd=0.0)
    opt.update(0, w, g, opt.create_state(0, w))
    out = w.asnumpy()
    np.testing.assert_allclose(out[2], np.ones(DIM))          # untouched
    np.testing.assert_allclose(out[50], np.full(DIM, 0.5))    # 1 - 0.5
    np.testing.assert_allclose(out[9000], np.full(DIM, 0.5))
    bad = sparse.compact_row_sparse_array(
        (np.ones((1, DIM), "f"), np.array([77])),
        shape=(VOCAB, DIM), nnz_max=2)
    with pytest.raises(KeyError, match="not resident"):
        opt.update(0, w, bad, None)


def test_sparse_embedding_shared_weight_sums_in_one_pass():
    """A SparseEmbedding applied twice inside one recorded graph must sum
    both contributions (grad_req='write' replaces only across passes)."""
    vocab, dim = 30, 4
    emb = gluon.contrib.nn.SparseEmbedding(vocab, dim, nnz_max=8)
    emb.initialize(mx.init.One())
    ids_a = mx.nd.array(np.array([1, 2], "f"))
    ids_b = mx.nd.array(np.array([2, 5], "f"))
    with mx.autograd.record():
        loss = mx.nd.sum(emb(ids_a)) + mx.nd.sum(emb(ids_b))
    loss.backward()
    g = emb.weight._grad
    np.testing.assert_array_equal(g.indices.asnumpy(), [1, 2, 5])
    dense = g.asnumpy()
    np.testing.assert_allclose(dense[2], np.full(dim, 2.0))  # both calls
    np.testing.assert_allclose(dense[1], np.ones(dim))
    # second backward pass with grad_req='write' replaces, not accumulates
    with mx.autograd.record():
        loss = mx.nd.sum(emb(ids_a))
    loss.backward()
    g2 = emb.weight._grad
    np.testing.assert_array_equal(g2.indices.asnumpy(), [1, 2])
    np.testing.assert_allclose(g2.asnumpy()[2], np.ones(dim))


def test_sparse_embedding_batch_exceeding_nnz_max_grows():
    """More unique ids in a batch than nnz_max must lose NO gradient —
    the grad buffer grows instead of truncating."""
    vocab, dim = 100, 4
    emb = gluon.contrib.nn.SparseEmbedding(vocab, dim, nnz_max=2)
    emb.initialize(mx.init.One())
    ids = mx.nd.array(np.arange(10, dtype="f"))
    with mx.autograd.record():
        loss = mx.nd.sum(emb(ids))
    loss.backward()
    g = emb.weight._grad
    assert g.nnz == 10
    np.testing.assert_array_equal(g.indices.asnumpy(), np.arange(10))
    np.testing.assert_allclose(g.data.asnumpy(), np.ones((10, dim)))


def test_stateful_optimizer_on_compact_weight_refused():
    w = sparse.compact_row_sparse_array(
        (np.ones((1, DIM), "f"), np.array([3])), shape=(VOCAB, DIM),
        nnz_max=2)
    g = sparse.compact_row_sparse_array(
        (np.ones((1, DIM), "f"), np.array([3])), shape=(VOCAB, DIM),
        nnz_max=2)
    opt = mx.optimizer.SGD(learning_rate=0.1, momentum=0.9,
                           rescale_grad=1.0)
    with pytest.raises(NotImplementedError, match="full table lives"):
        opt.update(0, w, g, opt.create_state(0, w))


def test_kvstore_compact_push_into_dense_store_refused():
    """A compact gradient pushed at a dense-initialised key without an
    updater must raise instead of installing the (nnz_max, row) buffer
    as the store's full value (pull already guards the mirror case)."""
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.zeros((50, 4)))
    g = sparse.compact_row_sparse_array(
        (np.ones((2, 4), "f"), np.array([3, 7])), shape=(50, 4),
        nnz_max=8)
    with pytest.raises(TypeError):
        kv.push("w", g)
