"""Row-sparse embeddings on the dist_async fast path (ISSUE 13):
the ``sparse_push_pull`` wire op (frames carry (row_ids, rows), the
server applies with row-wise optimizers, replies gather in kind),
row-range sharding of one table across servers
(``PartitionRules.mark_row_sharded``), seq-dedupe replay semantics,
bf16 row payloads, and the wire-bytes-scale-with-rows-touched
contract the whole feature exists for."""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import kvstore_async as ka
from mxtpu.kvstore_async import ParameterServer
from mxtpu.partition import PartitionRules


@pytest.fixture(autouse=True)
def _quiet(monkeypatch):
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")


def _store(monkeypatch, addrs):
    monkeypatch.setenv("MXTPU_PS_ADDRS", addrs)
    monkeypatch.setenv("MXTPU_PROC_ID", "0")
    monkeypatch.setenv("MXTPU_NUM_PROCS", "1")
    return mx.kv.create("dist_async")


def _table(rows=10, dim=4, seed=0):
    return np.random.RandomState(seed).rand(rows, dim).astype("f")


# ---------------------------------------------------------------------------
# row-wise server optimizers (Optimizer.update_host_rows)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("opt_name,kw", [
    ("sgd", {"learning_rate": 0.5, "momentum": 0.9}),
    ("adagrad", {"learning_rate": 0.5}),
    ("adam", {"learning_rate": 0.1}),
])
def test_row_wise_server_optimizer_math(opt_name, kw):
    """sparse_push_pull applies the optimizer to ONLY the touched rows
    and its math equals the dense host mirror restricted to those rows
    (same operation order), accumulating state across pushes."""
    w = _table()
    kv = mx.kv.create("dist_async")
    try:
        kv.init("emb", mx.nd.array(w))
        kv.set_optimizer(mx.optimizer.create(opt_name, rescale_grad=1.0,
                                             **kw))
        ids = np.array([1, 3, 7], "int64")
        out = mx.nd.array(w)
        # independent host mirror of the same sequence
        ref = mx.optimizer.create(opt_name, rescale_grad=1.0, **kw)
        upd = mx.optimizer.get_updater(ref)
        mirror = w.copy()
        for step in range(3):
            g = np.full((3, 4), 0.25 * (step + 1), "f")
            kv.sparse_push_pull("emb", ids, g, out=out)
            dense_g = np.zeros_like(mirror)
            dense_g[ids] = g
            new_w = upd.update_host(0, mirror, dense_g)
            assert new_w is not None
            mirror = np.asarray(new_w)
        got = out.asnumpy()
        untouched = np.setdiff1d(np.arange(10), ids)
        np.testing.assert_array_equal(got[untouched], w[untouched])
        np.testing.assert_allclose(got[ids], mirror[ids], rtol=2e-6)
        stats = kv.stats()
        assert stats["sparse_pushes"] == 3
        assert stats["sparse_rows"] == 9
    finally:
        kv.close()


def test_row_wise_touched_rows_bit_parity_with_dense_pushpull():
    """Acceptance: in sync mode the sparse wire is BIT-FOR-BIT with the
    dense pushpull path on the touched rows (sgd momentum — every
    operation order identical, only the untouched-row momentum decay
    differs by the documented lazy-update semantics, so the comparison
    touches every row each push)."""
    w = _table(rows=6)
    ids = np.arange(6, dtype="int64")
    kv_s = mx.kv.create("dist_async")
    kv_d = mx.kv.create("dist_async")
    try:
        for kv in (kv_s, kv_d):
            kv.init("emb", mx.nd.array(w))
            kv.set_optimizer(mx.optimizer.SGD(
                learning_rate=0.3, momentum=0.9, rescale_grad=1.0))
        out_s, out_d = mx.nd.array(w), mx.nd.array(w)
        for step in range(4):
            g = np.random.RandomState(step).rand(6, 4).astype("f")
            kv_s.sparse_push_pull("emb", ids, g, out=out_s)
            kv_d.push_pull("emb", g.copy(), out=out_d)
            np.testing.assert_array_equal(out_s.asnumpy(),
                                          out_d.asnumpy())
    finally:
        kv_s.close()
        kv_d.close()


def test_densify_fallback_keeps_any_optimizer_correct():
    """An optimizer WITHOUT a row-wise host mirror (rmsprop) still
    applies sparse pushes correctly: the server densifies the rows and
    takes the dense path."""
    w = _table()
    kv = mx.kv.create("dist_async")
    try:
        kv.init("emb", mx.nd.array(w))
        kv.set_optimizer(mx.optimizer.RMSProp(learning_rate=0.5,
                                              rescale_grad=1.0))
        ids = np.array([2, 8], "int64")
        out = mx.nd.array(w)
        kv.sparse_push_pull("emb", ids, np.ones((2, 4), "f"), out=out)
        got = out.asnumpy()
        assert not np.array_equal(got[ids], w[ids])
        untouched = np.setdiff1d(np.arange(10), ids)
        np.testing.assert_array_equal(got[untouched], w[untouched])
    finally:
        kv.close()


def test_sparse_then_pull_no_aliasing_tear():
    """A sparse-flagged key's table mutates rows in place — full pulls
    must ship a COPY (not the zero-copy alias the dense updater path
    uses), so a later in-place row write never tears a value a client
    already holds."""
    kv = mx.kv.create("dist_async")
    try:
        kv.init("emb", mx.nd.zeros((4, 2)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=1.0,
                                          rescale_grad=1.0))
        out = mx.nd.zeros((4, 2))
        kv.sparse_push_pull("emb", np.array([0], "int64"),
                            np.ones((1, 2), "f"), out=out)
        pulled = mx.nd.zeros((4, 2))
        kv.pull("emb", out=pulled)
        before = pulled.asnumpy().copy()
        kv.sparse_push_pull("emb", np.array([0], "int64"),
                            np.ones((1, 2), "f"), out=out)
        np.testing.assert_array_equal(pulled.asnumpy(), before)
    finally:
        kv.close()


# ---------------------------------------------------------------------------
# validation + replay semantics
# ---------------------------------------------------------------------------

def test_sparse_push_pull_validation():
    kv = mx.kv.create("dist_async")
    try:
        with pytest.raises(KeyError, match="uninitialized"):
            kv.sparse_push_pull("absent", np.array([0], "int64"),
                                np.ones((1, 2), "f"),
                                out=mx.nd.zeros((4, 2)))
        kv.init("emb", mx.nd.zeros((4, 2)))
        with pytest.raises(IndexError, match="out of range"):
            kv.sparse_push_pull("emb", np.array([4], "int64"),
                                np.ones((1, 2), "f"),
                                out=mx.nd.zeros((4, 2)))
        with pytest.raises(ValueError, match="unique"):
            kv.sparse_push_pull("emb", np.array([1, 1], "int64"),
                                np.ones((2, 2), "f"),
                                out=mx.nd.zeros((4, 2)))
        # drop_padding compacts the fused step's static-shape sentinel
        out = mx.nd.zeros((4, 2))
        kv.sparse_push_pull("emb", np.array([1, 4, 4], "int64"),
                            np.ones((3, 2), "f"), out=out,
                            drop_padding=True)
        got = out.asnumpy()
        np.testing.assert_array_equal(got[1], np.ones(2))
        assert np.all(got[[0, 2, 3]] == 0)
        # empty after compaction: a valid no-op, no wire traffic
        kv.sparse_push_pull("emb", np.array([4], "int64"),
                            np.ones((1, 2), "f"), out=out,
                            drop_padding=True)
        assert kv.staleness_stats()["clocks"]["emb"] == 1
    finally:
        kv.close()


def test_spushpull_dedupe_replay_answers_current_rows(monkeypatch):
    """A replayed spushpull (same origin+seq) is REFUSED by the
    watermark but still answers with the CURRENT row values — the
    at-most-once apply / always-fresh read contract of dense pushpull,
    row-sparse."""
    monkeypatch.setattr(ka, "_LOCAL_ON", False)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("emb", mx.nd.zeros((4, 2)))
        ids = np.array([1, 2], "int64")
        seq = next(kv._seq)
        conn = kv._conn("emb")
        r1 = conn.request("spushpull", "emb", ids, np.ones((2, 2), "f"),
                          0, kv._origin, seq)
        assert r1[0] == "ok" and srv._clock["emb"] == 1
        # replay with the SAME seq: not re-applied, fresh rows back
        r2 = conn.request("spushpull", "emb", ids, np.ones((2, 2), "f"),
                          0, kv._origin, seq)
        assert r2[0] == "ok"
        assert srv._clock["emb"] == 1
        assert srv._dup_n == 1
        np.testing.assert_array_equal(r2[1], r1[1])
        np.testing.assert_array_equal(r2[1], np.ones((2, 2), "f"))
    finally:
        kv.close()
        srv.stop()


def test_spushpull_bf16_rows_upcast_into_fp32_master(monkeypatch):
    """bf16 row payloads (MXTPU_AMP composition): the server upcasts
    into the fp32 master table and replies bf16 in kind."""
    import ml_dtypes
    monkeypatch.setattr(ka, "_LOCAL_ON", False)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        kv.init("emb", mx.nd.zeros((4, 2)))
        ids = np.array([0, 3], "int64")
        rows = np.ones((2, 2), ml_dtypes.bfloat16)
        reply = kv._conn("emb").request("spushpull", "emb", ids, rows,
                                        0, kv._origin, next(kv._seq))
        assert reply[0] == "ok"
        assert reply[1].dtype == ml_dtypes.bfloat16   # in kind
        assert srv._table["emb"].dtype == np.float32  # master stays
        np.testing.assert_allclose(srv._table["emb"][np.asarray(ids)],
                                   np.ones((2, 2)))
        # the high-level call restores the target's master dtype
        out = mx.nd.zeros((4, 2))
        kv.sparse_push_pull("emb", ids,
                            np.ones((2, 2), ml_dtypes.bfloat16),
                            out=out)
        assert out.dtype == np.float32
    finally:
        kv.close()
        srv.stop()


# ---------------------------------------------------------------------------
# row-range sharding: one table across many servers
# ---------------------------------------------------------------------------

def test_row_sharded_table_across_two_servers(monkeypatch):
    """A table bigger than one server wants: row-range parts SPREAD
    across shards (PartitionRules.mark_row_sharded), sparse frames fan
    to the row-range owners, replies reassemble in one device_put —
    and training math is identical to the single-server run."""
    monkeypatch.setattr(ka, "_BIGARRAY_BOUND", 16)   # (10,4): 4-row parts
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    kv = _store(monkeypatch, "%s,%s" % (s1.address, s2.address))
    kv_ref = None
    try:
        rules = PartitionRules([("emb.*", None)]).mark_row_sharded(
            "emb.*")
        kv.set_partition_rules(rules)
        w = _table()
        kv.init("emb", mx.nd.array(w))
        assert len(kv._parts["emb"]) == 3
        # parts really spread: both servers own part subkeys
        assert s1._table and s2._table
        owners = {len(s1._table), len(s2._table)}
        assert owners == {1, 2}
        opt = dict(learning_rate=0.5, momentum=0.9, rescale_grad=1.0)
        kv.set_optimizer(mx.optimizer.SGD(**opt))
        # reference: same sequence on a single-server store
        monkeypatch.setenv("MXTPU_PS_ADDRS", "")
        kv_ref = mx.kv.create("dist_async")
        kv_ref.init("emb", mx.nd.array(w))
        kv_ref.set_optimizer(mx.optimizer.SGD(**opt))
        out, out_ref = mx.nd.array(w), mx.nd.array(w)
        for step in range(3):
            ids = np.array([0, 4, 5, 9], "int64")   # spans all 3 parts
            g = np.random.RandomState(step).rand(4, 4).astype("f")
            kv.sparse_push_pull("emb", ids, g, out=out)
            kv_ref.sparse_push_pull("emb", ids, g, out=out_ref)
            np.testing.assert_array_equal(out.asnumpy(),
                                          out_ref.asnumpy())
        # per-part clocks count every step exactly once
        clocks = kv.staleness_stats()["clocks"]
        assert all(c == 3 for c in clocks.values()), clocks
    finally:
        kv.close()
        if kv_ref is not None:
            kv_ref.close()
        s1.stop()
        s2.stop()


def test_wire_bytes_scale_with_rows_touched(monkeypatch):
    """THE point of the feature: sparse pushpull wire bytes scale with
    rows touched, dense pushpull with table size — at 1% touch the
    sparse step ships <= 0.05x the dense step's bytes (measured over
    real framing, the ci/check_embedding_perf.py contract)."""
    monkeypatch.setattr(ka, "_LOCAL_ON", False)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        rows, dim, touched = 1000, 16, 10
        w = np.zeros((rows, dim), "f")
        kv.init("emb", mx.nd.array(w))
        out = mx.nd.array(w)
        ids = np.arange(0, rows, rows // touched, dtype="int64")[:touched]
        g_rows = np.ones((touched, dim), "f")
        g_dense = np.zeros_like(w)
        g_dense[ids] = 1.0

        def step_bytes(fn):
            before = kv.stats()
            fn()
            after = kv.stats()
            return ((after["bytes_sent"] - before["bytes_sent"])
                    + (after["bytes_recv"] - before["bytes_recv"]))

        dense_b = step_bytes(
            lambda: kv.push_pull("emb", g_dense, out=out))
        sparse_b = step_bytes(
            lambda: kv.sparse_push_pull("emb", ids, g_rows, out=out))
        assert sparse_b <= 0.05 * dense_b, (sparse_b, dense_b)
        assert kv.stats()["sparse_rows"] == touched
    finally:
        kv.close()
        srv.stop()
