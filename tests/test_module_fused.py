"""Fused Module train-step tests (ISSUE 5): fused-vs-eager parity,
BucketingModule bucket-switch cache reuse over the shared device store,
and the eager fallback paths (Monitor / custom updater — warn once)."""
import warnings

import numpy as np
import pytest

import mxtpu as mx


def _toy_problem(n=128, dim=20, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype("float32")
    w = rng.randn(dim, classes).astype("float32")
    y = (x @ w).argmax(axis=1).astype("float32")
    return x, y


def _mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit(fused, monkeypatch, optimizer="sgd", opt_params=None, epochs=2):
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1" if fused else "0")
    np.random.seed(7)
    mx.random.seed(7)
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer=optimizer,
            optimizer_params=opt_params or {"learning_rate": 0.05,
                                            "momentum": 0.9, "wd": 1e-4},
            initializer=mx.initializer.Xavier(), num_epoch=epochs,
            eval_metric="acc")
    assert (mod._fused is not None) == fused
    args, auxs = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01}),
])
def test_fused_vs_eager_parity(monkeypatch, optimizer, opt_params):
    """Params after K epochs of fit() must match between the fused
    one-program path and the eager forward/backward/update loop."""
    _, fused_params = _fit(True, monkeypatch, optimizer, opt_params)
    _, eager_params = _fit(False, monkeypatch, optimizer, opt_params)
    assert fused_params.keys() == eager_params.keys()
    for k in fused_params:
        np.testing.assert_allclose(fused_params[k], eager_params[k],
                                   rtol=5e-4, atol=1e-5, err_msg=k)


def test_fused_optimizer_state_roundtrip(monkeypatch, tmp_path):
    """Optimizer states written by the fused multi-tensor apply must
    save/load through the standard Updater serialization."""
    mod, _ = _fit(True, monkeypatch, "sgd",
                  {"learning_rate": 0.05, "momentum": 0.9})
    fname = str(tmp_path / "opt.states")
    mod.save_optimizer_states(fname)
    states = mod._updater.states
    assert states and all(s is not None for s in states.values())
    mod.load_optimizer_states(fname)
    # training continues on the fused path after a state reload
    x, y = _toy_problem()
    batch = mx.io.DataBatch([mx.nd.array(x[:32])], [mx.nd.array(y[:32])])
    mod.forward_backward(batch)
    mod.update()
    assert mod._fused is not None


def test_bucketing_switch_is_cache_hit(monkeypatch):
    """After each bucket's first batch, alternating buckets must re-use
    compiled programs (no new compiles) and share ONE device parameter
    store (no host-side param propagation on switch)."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    np.random.seed(3)
    mx.random.seed(3)

    def sym_gen(bucket_key):
        data = mx.sym.var("data")
        net = mx.sym.sum(data, axis=1)          # (B, L, D) -> (B, D)
        net = mx.sym.FullyConnected(net, num_hidden=16, name="fc1")
        net = mx.sym.Activation(net, act_type="relu", name="relu1")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=10,
                                 context=mx.cpu())
    mod.bind([("data", (8, 10, 6))], [("softmax_label", (8,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    rng = np.random.RandomState(0)

    def batch_for(key):
        x = rng.randn(8, key, 6).astype("float32")
        y = rng.randint(0, 4, 8).astype("float32")
        return mx.io.DataBatch(
            [mx.nd.array(x)], [mx.nd.array(y)], bucket_key=key,
            provide_data=[("data", (8, key, 6))],
            provide_label=[("softmax_label", (8,))])

    metric = mx.metric.create("acc")
    # warmup: each bucket compiles its own program(s) on first visit
    for key in (10, 20, 10, 20):
        b = batch_for(key)
        mod.forward_backward(b)
        mod.update()
        mod.update_metric(metric, b.label)
    metric.get()

    m10, m20 = mod._buckets[10], mod._buckets[20]
    assert m10._fused is not None and m20._fused is not None
    fs = m10._fused._group
    assert m20._fused._group is fs, "buckets must share one fused group"
    # one shared device store: the SAME NDArray objects back every bucket
    e10 = m10._exec_group.execs[0]
    e20 = m20._exec_group.execs[0]
    for name in ("fc1_weight", "fc1_bias", "fc2_weight", "fc2_bias"):
        assert e10.arg_dict[name] is e20.arg_dict[name], name

    compiles = fs.stats["compiles"]
    syncs_before = fs.stats["metric_drains"]
    before = e10.arg_dict["fc1_weight"].asnumpy()
    for key in (20, 10, 20, 10, 20, 10):
        b = batch_for(key)
        mod.forward_backward(b)
        mod.update()
        mod.update_metric(metric, b.label)
    assert fs.stats["compiles"] == compiles, \
        "bucket switches after warmup must be program-cache hits"
    assert fs.stats["metric_drains"] == syncs_before, \
        "no per-batch metric drains during steady-state switching"
    after = e10.arg_dict["fc1_weight"].asnumpy()
    assert np.abs(after - before).max() > 0, "training must still learn"
    assert np.isfinite(after).all()


def test_monitor_forces_eager_and_warns_once(monkeypatch):
    """Installing a Monitor is incompatible with the one-program step:
    the module must fall back to the eager path with ONE warning."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    assert mod._fused is not None
    mod.install_monitor(mx.monitor.Monitor(1))
    batch = next(iter(train))
    with pytest.warns(UserWarning, match="fused train step disabled"):
        mod.forward_backward(batch)
    mod.update()
    assert mod._fused is None, "monitor install must disable fusion"
    with warnings.catch_warnings():
        warnings.simplefilter("error")   # a second warning would raise
        mod.forward_backward(batch)
        mod.update()


def test_custom_updater_forces_eager_and_warns_once(monkeypatch):
    """A custom Python updater can't be traced into the fused program:
    fall back (warning once) and keep applying it eagerly."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    assert mod._fused is not None

    applied = []

    def updater(index, grad, weight):
        applied.append(index)
        weight._data = weight._data - 0.01 * grad._data

    mod._updater = updater
    batch = next(iter(train))
    before = mod._exec_group.execs[0].arg_dict["fc1_weight"].asnumpy()
    with pytest.warns(UserWarning, match="custom updater"):
        mod.forward_backward(batch)
    mod.update()
    assert mod._fused is None
    assert applied, "custom updater must run on the eager path"
    after = mod._exec_group.execs[0].arg_dict["fc1_weight"].asnumpy()
    assert np.abs(after - before).max() > 0
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        mod.forward_backward(batch)
        mod.update()


def test_fused_env_kill_switch(monkeypatch):
    """MXTPU_MODULE_FUSED=0 keeps the whole Module stack eager."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "0")
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer()
    assert mod._fused is None


def test_fused_donation_rebinds_wrappers(monkeypatch):
    """Donation invalidates old device buffers but every NDArray WRAPPER
    (arg_dict entries, param_arrays) must stay live across steps."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    w = mod._exec_group.execs[0].arg_dict["fc1_weight"]
    for batch in list(train)[:3]:
        mod.forward_backward(batch)
        mod.update()
    vals = w.asnumpy()                  # wrapper rebound, still readable
    assert np.isfinite(vals).all()
    outs = mod.get_outputs()            # fused step published outputs
    assert outs[0].shape == (32, 4)
