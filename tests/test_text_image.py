"""contrib.text + ImageRecordIter tests (reference
tests/python/unittest/test_contrib_text.py and the iterator checks in
test_io.py)."""
import collections
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, recordio
from mxtpu.contrib import text


def test_vocabulary():
    c = text.utils.count_tokens_from_str("a b b c c c\nd d d d")
    v = text.vocab.Vocabulary(c, min_freq=2, reserved_tokens=["<pad>"])
    assert v.idx_to_token[0] == "<unk>"
    assert v.idx_to_token[1] == "<pad>"
    # frequency order: d(4), c(3), b(2); a dropped (freq 1 < min_freq 2)
    assert v.idx_to_token[2:] == ["d", "c", "b"]
    assert v.to_indices(["d", "nope"]) == [2, 0]
    assert v.to_tokens([0, 2]) == ["<unk>", "d"]
    assert len(v) == 5


def test_vocabulary_most_freq_count():
    c = collections.Counter({"a": 5, "b": 4, "c": 3, "d": 2})
    v = text.vocab.Vocabulary(c, most_freq_count=2)
    assert len(v) == 3  # unk + 2


def test_custom_embedding_and_composite(tmp_path):
    path = str(tmp_path / "emb.txt")
    with open(path, "w") as f:
        for t, vec in [("hello", [1, 2]), ("world", [3, 4])]:
            f.write("%s %s\n" % (t, " ".join(map(str, vec))))
    emb = text.embedding.create("customembedding",
                                pretrained_file_path=path)
    assert emb.vec_len == 2
    np.testing.assert_array_equal(
        emb.get_vecs_by_tokens("world").asnumpy(), [3, 4])
    np.testing.assert_array_equal(
        emb.get_vecs_by_tokens("unknown-token").asnumpy(), [0, 0])
    emb.update_token_vectors("hello", nd.array(np.array([[9., 9.]])))
    np.testing.assert_array_equal(
        emb.get_vecs_by_tokens("hello").asnumpy(), [9, 9])
    v = text.vocab.Vocabulary(collections.Counter(["hello", "world"]))
    comp = text.embedding.CompositeEmbedding(v, [emb, emb])
    assert comp.idx_to_vec.shape == (3, 4)


def _write_rec(tmp_path, n=6, size=20):
    pytest.importorskip("PIL")
    from PIL import Image
    import io as _io
    prefix = str(tmp_path / "imgs")
    w = recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec", "w")
    for i in range(n):
        arr = np.full((size, size, 3), i * 40, np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        w.write_idx(i, recordio.pack(
            recordio.IRHeader(0, float(i % 2), i, 0), buf.getvalue()))
    w.close()
    return prefix + ".rec"


def test_image_record_iter(tmp_path):
    rec = _write_rec(tmp_path)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 16, 16),
                               batch_size=2, shuffle=True,
                               rand_mirror=True, mean_r=10.0)
    batches = list(it)
    assert len(batches) == 3
    for b in batches:
        assert b.data[0].shape == (2, 3, 16, 16)
        assert b.label[0].shape == (2,)


def test_image_record_iter_sharded(tmp_path):
    rec = _write_rec(tmp_path)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 16, 16),
                               batch_size=1, part_index=1, num_parts=3)
    assert len(list(it)) == 2  # 6 records / 3 parts


def test_image_iter_from_list(tmp_path):
    pytest.importorskip("PIL")
    from PIL import Image
    root = tmp_path / "imgs"
    root.mkdir()
    entries = []
    for i in range(4):
        p = root / ("img%d.png" % i)
        Image.fromarray(np.full((18, 18, 3), i * 30, np.uint8)).save(p)
        entries.append((float(i), "img%d.png" % i))
    it = mx.image.ImageIter(2, (3, 12, 12), imglist=entries,
                            path_root=str(root))
    b = next(iter(it))
    assert b.data[0].shape == (2, 3, 12, 12)


def test_vocab_most_freq_count_zero():
    c = collections.Counter({"a": 5, "b": 4})
    v = text.vocab.Vocabulary(c, most_freq_count=0)
    assert len(v) == 1  # only <unk>


def test_embedding_vocab_alignment(tmp_path):
    path = str(tmp_path / "emb2.txt")
    with open(path, "w") as f:
        f.write("x 1 1\ny 2 2\nz 3 3\n")
    v = text.vocab.Vocabulary(collections.Counter({"z": 3, "x": 1}))
    emb = text.embedding.CustomEmbedding(path, vocabulary=v)
    assert emb.idx_to_token == v.idx_to_token
    np.testing.assert_array_equal(
        emb.idx_to_vec.asnumpy()[v.to_indices("z")], [3, 3])
    np.testing.assert_array_equal(
        emb.idx_to_vec.asnumpy()[v.to_indices("x")], [1, 1])


def test_update_token_vectors_validates_length(tmp_path):
    path = str(tmp_path / "emb3.txt")
    with open(path, "w") as f:
        f.write("a 1 1\nb 2 2\n")
    emb = text.embedding.CustomEmbedding(path)
    with pytest.raises(ValueError):
        emb.update_token_vectors(["a", "b"], nd.array(np.ones((1, 2))))


def test_count_tokens_regex_delim():
    c = text.utils.count_tokens_from_str("a]b]c", token_delim="]")
    assert c == collections.Counter({"a": 1, "b": 1, "c": 1})


def test_image_record_iter_mean_img(tmp_path):
    rec = _write_rec(tmp_path)
    mean_path = str(tmp_path / "mean.bin")
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 16, 16),
                               batch_size=2, mean_img=mean_path)
    b = next(iter(it))
    assert os.path.exists(mean_path)
    # mean-subtracted data is centered around 0 over the dataset
    all_vals = []
    all_vals.append(b.data[0].asnumpy())
    for b2 in it:
        all_vals.append(b2.data[0].asnumpy())
    m = np.concatenate(all_vals).mean()
    assert abs(m) < 2.0
