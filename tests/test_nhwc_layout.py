"""MXTPU_CONV_LAYOUT=NHWC — the channels-last experiment knob must be
bit-compatible with the default NCHW path (tools/run_tpu_checks.py
measures its perf effect on hardware)."""
import numpy as np
import pytest

import mxtpu.ndarray as nd


def _both(fn, monkeypatch):
    monkeypatch.delenv("MXTPU_CONV_LAYOUT", raising=False)
    base = fn()
    monkeypatch.setenv("MXTPU_CONV_LAYOUT", "NHWC")
    alt = fn()
    monkeypatch.delenv("MXTPU_CONV_LAYOUT", raising=False)
    return base, alt


def test_conv_nhwc_matches(monkeypatch):
    r = np.random.RandomState(0)
    x = nd.array(r.randn(2, 3, 8, 8).astype("f"))
    w = nd.array(r.randn(4, 3, 3, 3).astype("f"))
    b = nd.array(r.randn(4).astype("f"))

    def run():
        return nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4,
                              stride=(2, 2), pad=(1, 1)).asnumpy()
    base, alt = _both(run, monkeypatch)
    np.testing.assert_allclose(base, alt, rtol=1e-5, atol=1e-5)


def test_grouped_conv_nhwc_matches(monkeypatch):
    r = np.random.RandomState(1)
    x = nd.array(r.randn(1, 4, 6, 6).astype("f"))
    w = nd.array(r.randn(8, 2, 3, 3).astype("f"))

    def run():
        return nd.Convolution(x, w, kernel=(3, 3), num_filter=8,
                              num_group=2, no_bias=True).asnumpy()
    base, alt = _both(run, monkeypatch)
    np.testing.assert_allclose(base, alt, rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("pool_type", ["max", "avg", "sum"])
def test_pooling_nhwc_matches(monkeypatch, pool_type):
    r = np.random.RandomState(2)
    x = nd.array(r.randn(2, 3, 7, 7).astype("f"))

    def run():
        return nd.Pooling(x, kernel=(3, 3), pool_type=pool_type,
                          stride=(2, 2), pad=(1, 1),
                          count_include_pad=False).asnumpy()
    base, alt = _both(run, monkeypatch)
    np.testing.assert_allclose(base, alt, rtol=1e-5, atol=1e-5)


def test_pooling_full_convention_and_global(monkeypatch):
    r = np.random.RandomState(3)
    x = nd.array(r.randn(1, 2, 9, 9).astype("f"))

    def run_full():
        return nd.Pooling(x, kernel=(3, 3), pool_type="max", stride=(2, 2),
                          pooling_convention="full").asnumpy()

    def run_global():
        return nd.Pooling(x, pool_type="avg", global_pool=True,
                          kernel=(1, 1)).asnumpy()
    for fn in (run_full, run_global):
        base, alt = _both(fn, monkeypatch)
        np.testing.assert_allclose(base, alt, rtol=1e-5, atol=1e-5)


def test_resnet_block_nhwc_matches(monkeypatch):
    """A conv->pool->conv chain end to end through gluon."""
    import mxtpu as mx
    from mxtpu.gluon.model_zoo import vision
    r = np.random.RandomState(4)
    x = r.randn(1, 3, 32, 32).astype("f")

    def run():
        mx.random.seed(0)
        net = vision.get_resnet(1, 18)
        net.initialize(mx.init.Xavier(), force_reinit=True)
        return net(mx.nd.array(x)).asnumpy()
    base, alt = _both(run, monkeypatch)
    np.testing.assert_allclose(base, alt, rtol=1e-4, atol=1e-4)
