"""Caffe prototxt -> symbol conversion (reference tools/caffe_converter)."""
import sys, os
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
import caffe_converter as cc  # noqa: E402
import mxtpu as mx  # noqa: E402

LENET = """
name: "LeNet"
input: "data"
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 8 kernel_size: 5 stride: 1 } }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 2 stride: 2 } }
layer { name: "relu1" type: "ReLU" bottom: "pool1" top: "pool1" }
layer { name: "ip1" type: "InnerProduct" bottom: "pool1" top: "ip1"
  inner_product_param { num_output: 32 } }
layer { name: "relu2" type: "ReLU" bottom: "ip1" top: "ip1" }
layer { name: "ip2" type: "InnerProduct" bottom: "ip1" top: "ip2"
  inner_product_param { num_output: 10 } }
layer { name: "loss" type: "SoftmaxWithLoss" bottom: "ip2" top: "loss" }
"""


def test_parse_prototxt():
    msg = cc.parse_prototxt(LENET)
    assert msg["name"] == "LeNet"
    layers = msg["layer"]
    assert len(layers) == 7
    assert layers[0]["convolution_param"]["num_output"] == 8
    assert str(layers[1]["pooling_param"]["pool"]) == "MAX"


def test_convert_lenet_runs():
    sym, inp = cc.convert_symbol(LENET)
    assert inp == "data"
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(2, 1, 28, 28))
    out = exe.forward(is_train=False,
                      data=np.random.RandomState(0).rand(2, 1, 28, 28)
                      .astype(np.float32))[0]
    assert out.shape == (2, 10)
    np.testing.assert_allclose(out.asnumpy().sum(1), 1.0, rtol=1e-5)


def test_convert_residual_block():
    proto = """
input: "data"
layer { name: "c1" type: "Convolution" bottom: "data" top: "c1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layer { name: "bn1" type: "BatchNorm" bottom: "c1" top: "c1" }
layer { name: "sc1" type: "Scale" bottom: "c1" top: "c1" }
layer { name: "r1" type: "ReLU" bottom: "c1" top: "c1" }
layer { name: "c2" type: "Convolution" bottom: "c1" top: "c2"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 } }
layer { name: "add" type: "Eltwise" bottom: "c2" bottom: "c1" top: "add"
  eltwise_param { operation: SUM } }
layer { name: "sm" type: "Softmax" bottom: "add" top: "sm" }
"""
    sym, _ = cc.convert_symbol(proto)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(1, 3, 8, 8))
    out = exe.forward(is_train=False,
                      data=np.zeros((1, 3, 8, 8), np.float32))[0]
    assert out.shape[0] == 1


def test_cli(tmp_path):
    p = tmp_path / "net.prototxt"
    p.write_text(LENET)
    rc = cc.main([str(p), str(tmp_path / "conv")])
    assert rc == 0
    assert (tmp_path / "conv-symbol.json").exists()
    loaded = mx.sym.load(str(tmp_path / "conv-symbol.json"))
    assert "loss" in loaded.list_outputs()[0]


def test_unsupported_layer():
    import pytest
    with pytest.raises(NotImplementedError):
        cc.convert_symbol('input: "data"\n'
                          'layer { name: "x" type: "SPP" bottom: "data" '
                          'top: "x" }')


def test_non_square_kernel():
    proto = """
input: "data"
layer { name: "c" type: "Convolution" bottom: "data" top: "c"
  convolution_param { num_output: 2 kernel_h: 3 kernel_w: 5
                      stride_h: 1 stride_w: 2 pad_h: 1 pad_w: 2 } }
layer { name: "sm" type: "Softmax" bottom: "c" top: "sm" }
"""
    sym, _ = cc.convert_symbol(proto)
    exe = sym.simple_bind(mx.cpu(), grad_req="null", data=(1, 1, 9, 12))
    out = exe.forward(is_train=False,
                      data=np.zeros((1, 1, 9, 12), np.float32))[0]
    # H: (9+2*1-3)/1+1 = 9 ; W: (12+2*2-5)/2+1 = 6
    assert out.shape == (1, 2, 9, 6), out.shape


def test_compute_gradient_contrib():
    # reference contract (contrib/autograd.py:158): deprecated alias of
    # backward — gradients land in the marked buffers, returns None
    from mxtpu.contrib import autograd as cag
    from mxtpu import nd
    x = nd.array(np.array([1.0, 2.0], np.float32))
    g = nd.zeros((2,))
    cag.mark_variables([x], [g])
    with cag.train_section():
        y = x * x
    assert cag.compute_gradient([y]) is None
    np.testing.assert_allclose(g.asnumpy(), 2 * x.asnumpy())


def test_empty_net_raises():
    import pytest
    with pytest.raises(ValueError):
        cc.convert_symbol('input: "data"')


def test_batchnorm_gamma_learnable():
    proto = """
input: "data"
layer { name: "bn" type: "BatchNorm" bottom: "data" top: "bn" }
layer { name: "sc" type: "Scale" bottom: "bn" top: "bn" }
layer { name: "sm" type: "Softmax" bottom: "bn" top: "sm" }
"""
    sym, _ = cc.convert_symbol(proto)
    js = sym.tojson()
    assert '"fix_gamma": "False"' in js or "'fix_gamma': 'False'" in js or \
        '"fix_gamma": false' in js.lower()
