"""Rollout & weight streaming fast tier (ISSUE 11): versioned engine
stores, the publisher→sync stream over both sources (snapshot dir and
the parameter-server weight stream), canary/A-B routing, promote/abort
verdicts, bit-exact rollback, multi-model serving — all loopback in
this process (the E2E trainer-into-fleet drill lives in
tests/test_dist_launch.py; the CI drill in ci/check_rollout.py).
"""
import threading

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault
from mxtpu import kvstore_async as ka
from mxtpu.checkpoint import weight_digest
from mxtpu.serving import (InferenceEngine, ModelServer,
                           RolloutController, ServingClient,
                           WeightPublisher, WeightSync)

IN_DIM = 6


@pytest.fixture(autouse=True)
def _serving_knobs(monkeypatch):
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    monkeypatch.setattr(ka, "_RETRIES", 1)
    monkeypatch.setattr(ka, "_BACKOFF", 0.01)
    monkeypatch.setattr(ka, "_BACKOFF_MAX", 0.05)
    monkeypatch.setattr(ka, "_RECONNECT_TIMEOUT", 0.2)
    fault.uninstall()
    yield
    fault.uninstall()


@pytest.fixture(scope="module")
def model():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, IN_DIM))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Uniform(0.1))
    arg_params, aux_params = mod.get_params()
    return net, arg_params, aux_params


def _engine(model, buckets=(4,), warm=False):
    net, arg_params, aux_params = model
    return InferenceEngine(net, arg_params, aux_params,
                           {"data": (IN_DIM,)}, buckets=buckets,
                           warm=warm)


def _params_v(model, scale):
    _net, arg_params, _aux = model
    return {n: v.asnumpy() * scale for n, v in arg_params.items()}


# ---------------------------------------------------------------------------
# engine: versioned stores
# ---------------------------------------------------------------------------

def test_swap_is_a_program_cache_hit_never_a_retrace(model):
    eng = _engine(model, buckets=(1, 4), warm=True)
    base = eng.cache.compiles
    x = np.ones((1, IN_DIM), "f")
    before = eng.predict([x])[0]
    assert eng.swap_weights(_params_v(model, 2.0)) == 1
    after, v = eng.predict_versioned([x])
    assert v == 1
    assert eng.cache.compiles == base        # zero recompiles
    assert not np.array_equal(after[0], before)
    assert eng.stats()["swaps"] == 1


def test_swap_refuses_shape_mismatch_and_half_tables(model):
    eng = _engine(model, warm=False)
    good = _params_v(model, 1.0)
    bad = dict(good)
    bad["fc1_weight"] = np.zeros((2, 2), "f")
    with pytest.raises(ValueError, match="never retrace"):
        eng.swap_weights(bad, version=5)
    half = dict(good)
    del half["fc2_bias"]
    assert eng.swap_weights(half, version=5) is None   # half table
    assert eng.version_state()["latest"] == 0
    assert eng.stats()["swaps_refused"] >= 1


def test_swap_verifies_digest_and_dedupes_stale_versions(model):
    eng = _engine(model, warm=False)
    p1 = _params_v(model, 1.5)
    with pytest.raises(ValueError, match="digest"):
        eng.swap_weights(p1, version=1, digest="0" * 64)
    assert eng.swap_weights(p1, version=1,
                            digest=weight_digest(p1)) == 1
    # stale/replayed version records are refused by the watermark
    assert eng.swap_weights(_params_v(model, 9.0), version=1) is None
    assert eng.version_state()["version"] == 1


def test_store_retention_keeps_live_set_and_last_k(model, monkeypatch):
    monkeypatch.setenv("MXTPU_SERVE_VERSION_KEEP", "2")
    eng = _engine(model, warm=False)
    for v in range(1, 6):
        eng.swap_weights(_params_v(model, 1.0 + v), version=v)
    state = eng.version_state()
    assert state["version"] == 5
    assert state["versions"] == [4, 5]       # keep-last-2
    # pinned stores never GC: pin 4, stream past it
    eng.pin(4)
    for v in range(6, 9):
        eng.swap_weights(_params_v(model, 10.0 + v), version=v)
    state = eng.version_state()
    assert 4 in state["versions"] and state["version"] == 4


def test_requests_resolve_one_coherent_version_mid_swap(model):
    """A version resolved at admission stays answerable after newer
    swaps land (retention keeps it) — the never-half-swapped
    contract's observable half."""
    eng = _engine(model, warm=True)
    v1 = eng.swap_weights(_params_v(model, 2.0))
    x = np.ones((2, IN_DIM), "f")
    want_v1 = eng.predict_versioned([x], version=v1)[0]
    v2 = eng.swap_weights(_params_v(model, 3.0))
    outs, v = eng.predict_versioned([x], version=v1)
    assert v == v1 and v2 == 2
    np.testing.assert_array_equal(outs[0], want_v1[0])


# ---------------------------------------------------------------------------
# publisher -> sync: the two stream sources
# ---------------------------------------------------------------------------

def test_publisher_snapshot_stream_end_to_end(model, tmp_path):
    srv = ModelServer(_engine(model), model_name="m",
                      batch_deadline_ms_=5).start()
    sync = None
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        pub = WeightPublisher(str(tmp_path / "w"))
        out = pub.publish(_params_v(model, 2.0), pin=True)
        assert out["version"] == 1 and len(out["digest"]) == 64
        pub.publish(_params_v(model, 3.0))
        sync = WeightSync(srv, weight_dir=str(tmp_path / "w"),
                          poll=0.05)
        assert sync.catch_up() == 2          # latest wins
        _, info = cli.predict2(np.ones((1, IN_DIM), "f"))
        assert info["version"] == 2
        assert sync.stats()["applied"] == 1
        assert pub.stats()["pinned"] == [1]
    finally:
        if sync is not None:
            sync.stop()
        srv.stop()


def test_sync_skips_corrupt_newest_snapshot(model, tmp_path):
    import os
    srv = ModelServer(_engine(model), model_name="m").start()
    sync = None
    try:
        pub = WeightPublisher(str(tmp_path / "w"))
        pub.publish(_params_v(model, 2.0))
        pub.publish(_params_v(model, 3.0))
        blob = os.path.join(str(tmp_path / "w"), "step_2",
                            "params.npz")
        with open(blob, "wb") as f:
            f.write(b"torn")
        sync = WeightSync(srv, weight_dir=str(tmp_path / "w"),
                          poll=0.05)
        assert sync.catch_up() == 1          # fell back to complete v1
        # every round re-probes the torn newest (it may be replaced by
        # a later complete publish), counting each skip
        assert sync.stats()["corrupt_skipped"] >= 1
        assert srv._engine.version_state()["version"] == 1
    finally:
        if sync is not None:
            sync.stop()
        srv.stop()


def test_ps_weight_stream_publish_subscribe(model, tmp_path):
    """The repl-stream discipline on the PS weights ops: publish bumps
    a total order, the subscriber's watermark dedupes, catch-up after
    reconnect is just asking again — and subscriber watermarks surface
    in stats()['weight_stream']."""
    net, arg_params, _aux = model
    ps = ka.ParameterServer().start()
    conn = ka._ServerConn(ps.address, n_socks=1)
    srv = ModelServer(_engine(model), model_name="m").start()
    sync = None
    try:
        for name, v in arg_params.items():
            conn.request("init", name, v.asnumpy())
        sync = WeightSync(srv, kv_addrs=[ps.address], poll=0.05)
        assert sync.poll_once() is None      # nothing published yet
        r = conn.request("publish", None, {"step": 10}, False)
        assert r[1]["version"] == 1
        assert sync.poll_once(wait_s=2.0) == 1
        assert srv._engine.version_state()["version"] == 1
        # dup publish: watermark refuses, reports the current version
        r = conn.request("publish", 1, None, False)
        assert r[1]["dup"] is True and r[1]["version"] == 1
        # replayed delivery (same watermark) is a no-op
        assert sync.poll_once() is None
        stream = conn.request("stats")[1]["weight_stream"]
        assert stream["published_version"] == 1
        assert stream["publishes"] == 1
        assert sync._origin in stream["subscribers"]
    finally:
        if sync is not None:
            sync.stop()
        srv.stop()
        conn.close()
        ps.stop()


def test_kv_publish_version_client_surface(model):
    import os
    net, arg_params, _aux = model
    ps = ka.ParameterServer().start()
    saved = os.environ.get("MXTPU_PS_ADDRS")
    os.environ["MXTPU_PS_ADDRS"] = ps.address
    try:
        kv = ka.AsyncDistKVStore()
        for name, v in arg_params.items():
            kv.init(name, mx.nd.array(v.asnumpy()))
        out = kv.publish_version(version=3, meta={"step": 3})
        assert out[0]["version"] == 3
        kv.close()
    finally:
        if saved is None:
            os.environ.pop("MXTPU_PS_ADDRS", None)
        else:
            os.environ["MXTPU_PS_ADDRS"] = saved
        ps.stop()


# ---------------------------------------------------------------------------
# rollout: canary, verdicts, rollback, hot swap, multi-model
# ---------------------------------------------------------------------------

def _fleet(model, tmp_path, n=2):
    servers = []
    for i in range(n):
        peers = [s.address for s in servers]
        srv = ModelServer(_engine(model), model_name="m",
                          batch_deadline_ms_=5,
                          replicas=peers or None,
                          weight_dir=str(tmp_path / "w")).start()
        for s in servers:
            s._replicas.append(srv.address)
        servers.append(srv)
    return servers


def test_canary_split_is_deterministic_and_promotes(model, tmp_path):
    srv = _fleet(model, tmp_path, n=1)[0]
    ctl = RolloutController([srv.address], model="m")
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        pub = WeightPublisher(str(tmp_path / "w"))
        pub.publish(_params_v(model, 2.0))
        sync = WeightSync(srv, weight_dir=str(tmp_path / "w"))
        sync.catch_up()
        ctl.canary(0, 0.5)                    # A/B: v0 vs v1
        rng = np.random.RandomState(0)
        seen, by_rid = set(), {}
        for i in range(40):
            outs, info = cli.predict2(rng.rand(1, IN_DIM).astype("f"))
            seen.add(info["version"])
        assert seen == {0, 1}
        # same rid hash -> same route: the split is deterministic, so
        # a failover replay is answered by the same version
        state = srv.stats()["models"]["m"]
        assert set(state["by_version"]) == {0, 1}
        verdict = ctl.verdict(0, stable_version=1)
        assert verdict["verdict"] == "promote"
        assert verdict["evidence"]["canary"]["responses"] >= 5
        ctl.promote(0)
        _, info = cli.predict2(np.ones((1, IN_DIM), "f"))
        assert info["version"] == 0
        ctl.abort()                           # idempotent, no canary
        sync.stop()
    finally:
        ctl.close()
        srv.stop()


def test_verdict_waits_then_aborts_on_errors(model, tmp_path):
    srv = _fleet(model, tmp_path, n=1)[0]
    ctl = RolloutController([srv.address], model="m")
    try:
        srv.swap_weights(_params_v(model, 2.0), version=1)
        assert ctl.verdict(1)["verdict"] == "wait"   # no canary traffic
        entry = srv._entry_for("m")
        for _ in range(10):
            entry.note(1, "errors")
            entry.note(0, "responses", lat_ms=1.0)
        entry.note(1, "responses", lat_ms=1.0)
        for _ in range(5):
            entry.note(1, "responses", lat_ms=1.0)
        out = ctl.verdict(1, stable_version=0)
        assert out["verdict"] == "abort"
        assert out["evidence"]["canary"]["err_ratio"] > 0.5
    finally:
        ctl.close()
        srv.stop()


def test_rollback_is_bit_exact_from_snapshot(model, tmp_path):
    """The pinned version aged out of memory; rollback restores it
    from the versioned snapshot, verifies the RECORDED digest, pins —
    and reproduces the version's bits exactly."""
    srv = _fleet(model, tmp_path, n=1)[0]
    ctl = RolloutController([srv.address], model="m")
    sync = None
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        pub = WeightPublisher(str(tmp_path / "w"))
        pub.publish(_params_v(model, 2.0), pin=True)      # v1
        sync = WeightSync(srv, weight_dir=str(tmp_path / "w"),
                          poll=0.05)
        sync.catch_up()
        x = np.ones((2, IN_DIM), "f")
        want, info = cli.predict2(x)
        assert info["version"] == 1
        for scale in (3.0, 4.0, 5.0, 6.0):                # v2..v5
            pub.publish(_params_v(model, scale))
            sync.catch_up()
        state = srv._engine.version_state()
        assert state["version"] == 5 and 1 not in state["versions"]
        base_compiles = srv._engine.cache.compiles
        out = ctl.rollback(1)[srv.address]
        assert out["weights"]["pinned"] == 1
        got, info = cli.predict2(x)
        assert info["version"] == 1
        np.testing.assert_array_equal(got[0], want[0])
        assert srv._engine.cache.compiles == base_compiles
        # pinned: the stream keeps landing but stops activating
        pub.publish(_params_v(model, 7.0))
        sync.catch_up()
        _, info = cli.predict2(x)
        assert info["version"] == 1
        ctl.unpin()
        pub.publish(_params_v(model, 8.0))
        sync.catch_up()
        _, info = cli.predict2(x)
        assert info["version"] == 7
    finally:
        if sync is not None:
            sync.stop()
        ctl.close()
        srv.stop()


def test_rollback_refuses_digest_mismatch(model, tmp_path):
    import json
    import os
    srv = _fleet(model, tmp_path, n=1)[0]
    ctl = RolloutController([srv.address], model="m")
    try:
        pub = WeightPublisher(str(tmp_path / "w"))
        pub.publish(_params_v(model, 2.0), pin=True)
        for scale in (3.0, 4.0, 5.0, 6.0):
            pub.publish(_params_v(model, scale))
            srv.swap_weights(_params_v(model, scale))
        # corrupt v1's params while keeping the recorded digest: the
        # CRC tags would catch a torn file; rewrite them consistently
        # so ONLY the digest check stands between us and wrong bits
        step = os.path.join(str(tmp_path / "w"), "step_1")
        wrong = _params_v(model, 99.0)
        with open(os.path.join(step, "params.npz"), "wb") as f:
            np.savez(f, **wrong)
        with open(os.path.join(step, "integrity.json")) as f:
            tags = json.load(f)
        import zlib as _z
        tags["params"] = {
            k: _z.crc32(np.ascontiguousarray(v).tobytes())
            for k, v in wrong.items()}
        with open(os.path.join(step, "integrity.json"), "w") as f:
            json.dump(tags, f)
        with pytest.raises(RuntimeError, match="digest"):
            ctl.rollback(1)
    finally:
        ctl.close()
        srv.stop()


def test_hot_swap_is_zero_downtime_under_load(model, tmp_path):
    """drain → swap → resume, one replica at a time, while concurrent
    clients stream requests: every request is answered exactly once
    (the draining verdict steers to the peer), zero retraces."""
    s1, s2 = _fleet(model, tmp_path, n=2)
    ctl = RolloutController([s1.address, s2.address], model="m")
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=8000)
        cli.hello()
        compiles0 = (s1._engine.cache.compiles,
                     s2._engine.cache.compiles)
        stop = threading.Event()
        outs, errs = [], []
        lock = threading.Lock()

        def pound(seed):
            rng = np.random.RandomState(seed)
            c = ServingClient(addrs=[s1.address, s2.address],
                              budget_ms=8000)
            while not stop.is_set():
                try:
                    _, info = c.predict2(
                        rng.rand(1, IN_DIM).astype("f"))
                    with lock:
                        outs.append(info["version"])
                except Exception as e:
                    with lock:
                        errs.append(e)
            c.close()

        ts = [threading.Thread(target=pound, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        ctl.hot_swap(_params_v(model, 2.0), 1)
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs[:3]
        assert len(outs) > 0
        # both replicas landed the version and resumed admissions
        for s in (s1, s2):
            assert s._engine.version_state()["version"] == 1
            assert not s._draining
        _, info = cli.predict2(np.ones((1, IN_DIM), "f"))
        assert info["version"] == 1
        assert (s1._engine.cache.compiles,
                s2._engine.cache.compiles) == compiles0
    finally:
        ctl.close()
        s2.stop()
        s1.stop()


def test_multi_model_menus_route_by_id(model, tmp_path):
    net, arg_params, aux_params = model
    srv = ModelServer(_engine(model), model_name="m").start()
    try:
        eng2 = InferenceEngine(net, {n: mx.nd.array(v.asnumpy() * -1.0)
                                     for n, v in arg_params.items()},
                               aux_params, {"data": (IN_DIM,)},
                               buckets=(4,), warm=False)
        srv.add_model("m2", eng2)
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        info = cli.hello()
        assert sorted(info["models"]) == ["m", "m2"]
        assert cli.models["m2"]["weights"]["version"] == 0
        x = np.ones((1, IN_DIM), "f")
        out_default = cli.predict(x)[0]
        out_m2 = cli.predict(x, model="m2")[0]
        assert not np.array_equal(out_default, out_m2)
        # per-menu weight versions move independently
        srv.swap_weights(_params_v(model, 2.0), model="m2")
        _, info2 = cli.predict2(x, model="m2")
        assert info2["version"] == 1
        _, info1 = cli.predict2(x)
        assert info1["version"] == 0
        with pytest.raises(RuntimeError, match="unknown model"):
            cli.predict(x, model="nope")
        s = srv.stats()["models"]
        assert set(s) == {"m", "m2"}
    finally:
        srv.stop()


def test_streaming_under_load_exactly_once_zero_retraces(model,
                                                         tmp_path):
    """The tentpole invariant, in-process: concurrent clients stream
    requests while versions swap continuously — every request answered
    exactly once by exactly one coherent version, zero recompiles."""
    srv = _fleet(model, tmp_path, n=1)[0]
    try:
        srv._engine.warm()
        base = srv._engine.cache.compiles
        stop = threading.Event()
        answered, errs = [], []
        lock = threading.Lock()

        def pound(seed):
            rng = np.random.RandomState(seed)
            c = ServingClient(addrs=[srv.address], budget_ms=8000)
            n = 0
            while not stop.is_set() and n < 200:
                _try_one(c, rng, answered, errs, lock)
                n += 1
            c.close()

        def _try_one(c, rng, answered, errs, lock):
            try:
                _, info = c.predict2(rng.rand(1, IN_DIM).astype("f"))
                with lock:
                    answered.append(info["version"])
            except Exception as e:
                with lock:
                    errs.append(e)

        ts = [threading.Thread(target=pound, args=(i,))
              for i in range(3)]
        for t in ts:
            t.start()
        for v in range(1, 8):
            srv.swap_weights(_params_v(model, 1.0 + 0.5 * v),
                             version=v)
        stop.set()
        for t in ts:
            t.join(timeout=60)
        assert not errs, errs[:3]
        assert len(answered) > 0
        assert set(answered) <= set(range(0, 8))
        assert srv._engine.cache.compiles == base
        assert srv.stats()["counters"]["swaps"] == 7
    finally:
        srv.stop()
