"""C predict API tests: drive the flat C ABI (libmxtpu_predict.so) via
ctypes and via a freshly compiled pure-C program, comparing against the
Python Module.predict path (reference tests exercise c_predict_api through
the amalgamation/cpp-package).
"""
import ctypes
import os
import subprocess

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd

_NATIVE = os.path.join(os.path.dirname(__file__), "..", "mxtpu", "_native")
_SO = os.path.join(_NATIVE, "libmxtpu_predict.so")


def _export_model(tmp_path):
    mx.random.seed(0)
    data = mx.sym.var("data")
    fc1 = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    act = mx.sym.Activation(fc1, act_type="relu")
    fc2 = mx.sym.FullyConnected(act, num_hidden=3, name="fc2")
    out = mx.sym.SoftmaxOutput(fc2, name="softmax")
    mod = mx.mod.Module(out, context=mx.cpu())
    rng = np.random.RandomState(0)
    x = rng.randn(32, 5).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)
    mod.fit(mx.io.NDArrayIter(x, y, batch_size=8), num_epoch=1,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            initializer=mx.init.Xavier())
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 1)
    probe = np.arange(10, dtype=np.float32).reshape(2, 5) / 10.0
    sym2, arg, aux = mx.model.load_checkpoint(prefix, 1)
    mod2 = mx.mod.Module(out, context=mx.cpu())
    mod2.bind(data_shapes=[("data", (2, 5))], for_training=False)
    mod2.set_params(arg, aux)
    expect = mod2.predict(
        mx.io.NDArrayIter(probe, None, batch_size=2)).asnumpy()
    return prefix, probe, expect


@pytest.mark.skipif(not os.path.exists(_SO),
                    reason="libmxtpu_predict.so not built")
def test_c_predict_ctypes(tmp_path):
    prefix, probe, expect = _export_model(tmp_path)
    lib = ctypes.CDLL(_SO)
    lib.MXGetLastError.restype = ctypes.c_char_p
    json_data = open(prefix + "-symbol.json", "rb").read()
    params = open(prefix + "-0001.params", "rb").read()
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint * 2)(0, 2)
    shape = (ctypes.c_uint * 2)(2, 5)
    handle = ctypes.c_void_p()
    rc = lib.MXPredCreate(json_data, params, len(params), 1, 0, 1, keys,
                          indptr, shape, ctypes.byref(handle))
    assert rc == 0, lib.MXGetLastError()
    flat = probe.ravel().astype(np.float32)
    buf = (ctypes.c_float * flat.size)(*flat)
    assert lib.MXPredSetInput(handle, b"data", buf, flat.size) == 0
    assert lib.MXPredForward(handle) == 0
    sdata = ctypes.POINTER(ctypes.c_uint)()
    ndim = ctypes.c_uint()
    assert lib.MXPredGetOutputShape(handle, 0, ctypes.byref(sdata),
                                    ctypes.byref(ndim)) == 0
    oshape = tuple(sdata[i] for i in range(ndim.value))
    assert oshape == (2, 3)
    out = (ctypes.c_float * 6)()
    assert lib.MXPredGetOutput(handle, 0, out, 6) == 0
    got = np.asarray(out[:6], np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
    # reshape path: new batch size
    indptr2 = (ctypes.c_uint * 2)(0, 2)
    shape2 = (ctypes.c_uint * 2)(4, 5)
    h2 = ctypes.c_void_p()
    assert lib.MXPredReshape(1, keys, indptr2, shape2, handle,
                             ctypes.byref(h2)) == 0, lib.MXGetLastError()
    probe4 = np.tile(probe, (2, 1)).astype(np.float32)
    buf4 = (ctypes.c_float * 20)(*probe4.ravel())
    assert lib.MXPredSetInput(h2, b"data", buf4, 20) == 0
    assert lib.MXPredForward(h2) == 0
    out4 = (ctypes.c_float * 12)()
    assert lib.MXPredGetOutput(h2, 0, out4, 12) == 0
    got4 = np.asarray(out4[:12], np.float32).reshape(4, 3)
    np.testing.assert_allclose(got4[:2], expect, rtol=1e-5, atol=1e-6)
    lib.MXPredFree(handle)
    lib.MXPredFree(h2)


_C_PROGRAM = r"""
#include <stdio.h>
#include <stdlib.h>
#include "mxtpu/c_predict_api.h"
static char *rf(const char *p, long *n) {
  FILE *f = fopen(p, "rb"); fseek(f, 0, SEEK_END); *n = ftell(f);
  fseek(f, 0, SEEK_SET); char *b = malloc(*n + 1);
  fread(b, 1, *n, f); b[*n] = 0; fclose(f); return b;
}
int main(int argc, char **argv) {
  long js, ps;
  char *j = rf(argv[1], &js), *p = rf(argv[2], &ps);
  const char *keys[] = {"data"};
  mx_uint ip[] = {0, 2}, sh[] = {2, 5};
  PredictorHandle h = NULL;
  if (MXPredCreate(j, p, (int)ps, 1, 0, 1, keys, ip, sh, &h)) {
    fprintf(stderr, "%s\n", MXGetLastError()); return 1; }
  mx_float in[10];
  for (int i = 0; i < 10; ++i) in[i] = i / 10.0f;
  if (MXPredSetInput(h, "data", in, 10) || MXPredForward(h)) return 1;
  mx_float out[6];
  if (MXPredGetOutput(h, 0, out, 6)) return 1;
  for (int i = 0; i < 6; ++i) printf("%.6f ", out[i]);
  MXPredFree(h);
  return 0;
}
"""


@pytest.mark.skipif(not os.path.exists(_SO),
                    reason="libmxtpu_predict.so not built")
def test_c_predict_from_pure_c_program(tmp_path):
    import shutil
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    prefix, probe, expect = _export_model(tmp_path)
    src = tmp_path / "t.c"
    src.write_text(_C_PROGRAM)
    exe = str(tmp_path / "t")
    inc = os.path.join(os.path.dirname(__file__), "..", "include")
    subprocess.run(["gcc", "-O1", str(src), "-I", inc, "-L", _NATIVE,
                    "-lmxtpu_predict", "-o", exe,
                    "-Wl,-rpath," + os.path.abspath(_NATIVE)], check=True)
    env = dict(os.environ,
               PYTHONPATH=os.path.abspath(
                   os.path.join(os.path.dirname(__file__), "..")),
               JAX_PLATFORMS="cpu")
    res = subprocess.run([exe, prefix + "-symbol.json",
                          prefix + "-0001.params"], env=env,
                         capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stderr
    got = np.asarray([float(v) for v in res.stdout.split()],
                     np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_predict_impl_output_shape_before_forward(tmp_path):
    from mxtpu import _c_predict_impl as impl
    prefix, probe, expect = _export_model(tmp_path)
    json_data = open(prefix + "-symbol.json").read()
    params = open(prefix + "-0001.params", "rb").read()
    pred = impl.create(json_data, params, 1, 0, ["data"], [(2, 5)])
    # reference MXPredCreate infers output shapes at bind time; clients
    # size their buffers from this before ever calling forward
    assert pred.output_shape(0) == [2, 3]
    pred.set_input("data", probe.ravel())
    pred.forward()
    np.testing.assert_allclose(
        pred.output(0).reshape(2, 3), expect, rtol=1e-5, atol=1e-5)


def test_predict_impl_reshape_does_not_alias_inputs(tmp_path):
    from mxtpu import _c_predict_impl as impl
    prefix, probe, expect = _export_model(tmp_path)
    json_data = open(prefix + "-symbol.json").read()
    params = open(prefix + "-0001.params", "rb").read()
    pred = impl.create(json_data, params, 1, 0, ["data"], [(2, 5)])
    pred.set_input("data", probe.ravel())

    # same-shape reshape: inputs must be independent copies
    pred2 = impl.reshape(pred, ["data"], [(2, 5)])
    assert pred2.output_shape(0) == [2, 3]
    assert pred2._exe.arg_dict["data"] is not pred._exe.arg_dict["data"]
    # executor-internal views (arg_arrays) must agree with arg_dict
    for i, n in enumerate(pred2._exe._arg_names):
        assert pred2._exe.arg_arrays[i] is pred2._exe.arg_dict[n]
    pred2.set_input("data", np.zeros(10, np.float32))
    pred.forward()
    np.testing.assert_allclose(
        pred.output(0).reshape(2, 3), expect, rtol=1e-5, atol=1e-5)

    # weights stay shared semantically: new predictor still computes the
    # trained function on its own input
    pred2.set_input("data", probe.ravel())
    pred2.forward()
    np.testing.assert_allclose(
        pred2.output(0).reshape(2, 3), expect, rtol=1e-5, atol=1e-5)


_CPP_PROGRAM = r"""
#include <cstdio>
#include <mxtpu/mxtpu_cpp.hpp>

int main(int argc, char **argv) {
  using mxtpu::cpp::Predictor;
  using mxtpu::cpp::Context;
  Predictor pred(mxtpu::cpp::LoadFile(argv[1]),
                 mxtpu::cpp::LoadFile(argv[2]), Context::cpu(),
                 {{"data", {2, 5}}});
  std::vector<mx_uint> shape = pred.GetOutputShape(0);  // pre-forward
  if (shape.size() != 2 || shape[0] != 2 || shape[1] != 3) return 2;
  std::vector<mx_float> probe(10);
  for (int i = 0; i < 10; ++i) probe[i] = i / 10.0f;
  pred.SetInput("data", probe);
  pred.Forward();
  mxtpu::cpp::NDArray out = pred.GetOutputArray(0);
  // reshape keeps weights; run the same input through the new predictor
  Predictor pred2 = pred.Reshape({{"data", {2, 5}}});
  pred2.SetInput("data", probe);
  pred2.Forward();
  std::vector<mx_float> out2 = pred2.GetOutput(0);
  for (size_t i = 0; i < out.Data().size(); ++i) {
    if (out.Data()[i] - out2[i] > 1e-6f || out2[i] - out.Data()[i] > 1e-6f)
      return 3;
    std::printf("%f\n", out.Data()[i]);
  }
  return 0;
}
"""


def test_cpp_package_header(tmp_path):
    import shutil
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    prefix, probe, expect = _export_model(tmp_path)
    src = tmp_path / "t.cc"
    src.write_text(_CPP_PROGRAM)
    exe = str(tmp_path / "tcc")
    inc = os.path.join(os.path.dirname(__file__), "..", "include")
    subprocess.run(["g++", "-std=c++14", "-O1", str(src), "-I", inc,
                    "-L", _NATIVE, "-lmxtpu_predict", "-o", exe,
                    "-Wl,-rpath," + os.path.abspath(_NATIVE)], check=True)
    env = dict(os.environ,
               PYTHONPATH=os.path.abspath(
                   os.path.join(os.path.dirname(__file__), "..")),
               JAX_PLATFORMS="cpu")
    res = subprocess.run([exe, prefix + "-symbol.json",
                          prefix + "-0001.params"], env=env,
                         capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, (res.returncode, res.stderr)
    got = np.asarray([float(v) for v in res.stdout.split()],
                     np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_amalgamation_builds_and_predicts(tmp_path):
    import shutil
    if shutil.which("g++") is None or shutil.which("python3-config") is None:
        pytest.skip("no g++/python3-config")
    sys_path = os.path.join(os.path.dirname(__file__), "..")
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "amalgamate", os.path.join(sys_path, "amalgamation",
                                   "amalgamate.py"))
    amal = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(amal)
    out = str(tmp_path / "dist")
    cc = amal.amalgamate(out)

    import subprocess as sp
    inc = sp.run(["python3-config", "--includes"], capture_output=True,
                 text=True).stdout.split()
    ld = sp.run(["python3-config", "--ldflags", "--embed"],
                capture_output=True, text=True).stdout.split()
    so = str(tmp_path / "libamal.so")
    sp.run(["g++", "-O2", "-std=c++17", "-fPIC", "-shared", cc] + inc +
           ld + ["-o", so], check=True)

    # drive the amalgamated .so from a FRESH process whose embedded
    # interpreter can only see the bundle -- proves the bundle is a
    # complete runtime, not just that the ABI compiled
    prefix, probe, expect = _export_model(tmp_path)
    driver = tmp_path / "drive.py"
    driver.write_text("""
import ctypes, sys
import numpy as np
lib = ctypes.CDLL(sys.argv[1])
lib.MXGetLastError.restype = ctypes.c_char_p
json_data = open(sys.argv[2], 'rb').read()
params = open(sys.argv[3], 'rb').read()
keys = (ctypes.c_char_p * 1)(b'data')
indptr = (ctypes.c_uint * 2)(0, 2)
shape = (ctypes.c_uint * 2)(2, 5)
h = ctypes.c_void_p()
rc = lib.MXPredCreate(json_data, params, len(params), 1, 0, 1, keys,
                      indptr, shape, ctypes.byref(h))
assert rc == 0, lib.MXGetLastError()
probe = (np.arange(10, dtype=np.float32) / 10.0)
assert lib.MXPredSetInput(h, b'data',
    probe.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 10) == 0
assert lib.MXPredForward(h) == 0, lib.MXGetLastError()
out = np.empty(6, np.float32)
assert lib.MXPredGetOutput(h, 0,
    out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), 6) == 0
print(' '.join('%r' % float(v) for v in out))
""")
    env = dict(os.environ,
               PYTHONPATH=os.path.join(out, "bundle"),
               JAX_PLATFORMS="cpu")
    res = subprocess.run(
        [os.sys.executable, str(driver), so, prefix + "-symbol.json",
         prefix + "-0001.params"],
        env=env, capture_output=True, text=True, timeout=240)
    assert res.returncode == 0, res.stderr
    got = np.asarray([float(v) for v in res.stdout.split()],
                     np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)
