"""attribute/log/registry/libinfo/executor_manager/misc parity modules."""
import logging

import numpy as np
import pytest

import mxtpu as mx


def test_attr_scope_stamps_symbols():
    with mx.AttrScope(ctx_group="dev1", lr_mult="2"):
        a = mx.sym.var("a")
        b = mx.sym.FullyConnected(a, num_hidden=4, name="fc")
        with mx.AttrScope(ctx_group="dev2"):
            c = mx.sym.relu(b, name="r")
    d = mx.sym.relu(b, name="d")
    assert a.list_attr().get("ctx_group") == "dev1"   # variables stamped
    assert b.list_attr().get("ctx_group") == "dev1"
    assert b.list_attr().get("lr_mult") == "2"
    assert c.list_attr().get("ctx_group") == "dev2"
    assert c.list_attr().get("lr_mult") == "2"     # nesting inherits
    assert "ctx_group" not in d.list_attr()
    with pytest.raises(ValueError):
        mx.AttrScope(ctx_group=1)


def test_explicit_attr_wins():
    with mx.AttrScope(ctx_group="scope"):
        s = mx.sym.var("x")
        y = mx.sym.relu(s, name="y", attr={"ctx_group": "explicit"})
    assert y.list_attr()["ctx_group"] == "explicit"


def test_log_get_logger(tmp_path):
    lg = mx.log.get_logger("mxtpu_test_log", level=logging.INFO)
    assert lg.level == logging.INFO
    assert lg.handlers
    lg2 = mx.log.get_logger("mxtpu_test_log")
    assert lg2 is lg and len(lg2.handlers) == 1   # no duplicate handlers
    lgf = mx.log.get_logger("mxtpu_test_log_f", str(tmp_path / "x.log"))
    lgf.warning("hello")
    for h in lgf.handlers:
        h.flush()
    assert "hello" in (tmp_path / "x.log").read_text()


def test_generic_registry():
    class Base:
        pass

    reg = mx.registry.get_register_func(Base, "thing")
    alias = mx.registry.get_alias_func(Base, "thing")
    create = mx.registry.get_create_func(Base, "thing")

    @alias("t2")
    @reg
    class Thing(Base):
        def __init__(self, v=1):
            self.v = v

    assert create("thing").v == 1
    assert create("T2", v=5).v == 5
    inst = Thing(9)
    assert create(inst) is inst
    assert "thing" in mx.registry.get_registry(Base)
    with pytest.raises(AssertionError):
        create("missing")


def test_libinfo_and_misc_and_manager():
    libs = mx.libinfo.find_lib_path()
    assert any(p.endswith(".so") for p in libs)
    assert mx.libinfo.__version__
    from mxtpu.executor_manager import (DataParallelExecutorManager,
                                        _split_input_slice)
    slices = _split_input_slice(10, [1, 1])
    assert len(slices) == 2
    assert mx.misc.FactorScheduler is mx.lr_scheduler.FactorScheduler


def test_attr_scope_reuse_no_leak():
    s = mx.AttrScope(lr_mult="2")
    with mx.AttrScope(ctx_group="dev1"):
        with s:
            pass
    with s:
        v = mx.sym.var("leakcheck")
    attrs = v.list_attr()
    assert attrs.get("lr_mult") == "2"
    assert "ctx_group" not in attrs      # dev1 must not leak out


def test_get_logger_retry_after_failure(tmp_path):
    with pytest.raises(OSError):
        mx.log.get_logger("mxtpu_retry_log", "/nonexistent_dir_xyz/a.log")
    lg = mx.log.get_logger("mxtpu_retry_log", str(tmp_path / "b.log"))
    assert lg.handlers                   # retry actually initialized


def test_attr_scope_reentrant():
    outer = mx.AttrScope(a="1")
    s = mx.AttrScope(lr_mult="2")
    with outer:
        with s:
            with s:
                pass
        v = mx.sym.var("reentrant_check")
    attrs = v.list_attr()
    assert attrs.get("a") == "1"            # outer still active + intact
    assert "lr_mult" not in attrs           # s fully exited
    from mxtpu.attribute import AttrScope as A
    assert A._stack() == []                 # stack balanced


def test_attr_scope_reentrant_sees_intervening_scope():
    s = mx.AttrScope(a="1")
    other = mx.AttrScope(b="2")
    with s:
        with other:
            with s:
                v = mx.sym.var("nested_reentrant")
    attrs = v.list_attr()
    assert attrs.get("a") == "1" and attrs.get("b") == "2"
