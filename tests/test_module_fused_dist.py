"""Fused DISTRIBUTED Module train step (ISSUE 10): the kvstore-managed
fast path — sync-mode bit-for-bit parity with the eager dist loop
(sgd + adam, optimizer-state round-trip through the server), async-mode
loss band + bounded push window, the dist_local (merged-gradient) mode,
and the narrowed fallback predicate with its one-shot debug log."""
import logging

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.module import fused as fused_mod


def _toy_problem(n=192, seed=5, classes=4):
    r = np.random.RandomState(seed)
    y = (r.rand(n) * classes).astype("f")
    x = r.rand(n, 16).astype("f") * 0.1
    for i in range(n):
        x[i, int(y[i]) * 4:int(y[i]) * 4 + 4] += 1.0
    return x, y


def _mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _dist_fit(monkeypatch, fused_dist, mode="sync", optimizer="sgd",
              opt_params=None, epochs=3, keep_module=False):
    """One Module.fit through an in-process dist_async store; returns
    (module-or-None, params, kv stats, engaged mode)."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_MODULE_FUSED_DIST",
                       "1" if fused_dist else "0")
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", mode)
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    np.random.seed(7)
    mx.random.seed(7)
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(it, optimizer=optimizer,
            optimizer_params=opt_params or {"learning_rate": 0.1,
                                            "momentum": 0.9},
            num_epoch=epochs, kvstore="dist_async", eval_metric="acc")
    engaged = mod._fused.mode if mod._fused is not None else None
    args, _ = mod.get_params()
    params = {k: v.asnumpy().copy() for k, v in args.items()}
    stats = mod._kvstore.stats()
    if keep_module:
        return mod, params, stats, engaged
    mod._kvstore.close()
    return None, params, stats, engaged


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01}),
])
def test_fused_dist_sync_parity_bitwise(monkeypatch, optimizer,
                                        opt_params):
    """Sync-mode fused dist fit must match the eager dist path
    BIT-FOR-BIT: same grads (one fused program vs speculative
    fwd+bwd), same server-side update sequence per key."""
    _, fused, _, m1 = _dist_fit(monkeypatch, True, "sync", optimizer,
                                opt_params)
    _, eager, _, m2 = _dist_fit(monkeypatch, False, "sync", optimizer,
                                opt_params)
    assert m1 == "dist", "fused dist path must engage"
    assert m2 is None, "eager run must not engage the fused path"
    assert fused.keys() == eager.keys()
    for k in fused:
        assert np.array_equal(fused[k], eager[k]), \
            "%s differs between fused and eager dist paths" % k


def test_fused_dist_optimizer_state_roundtrip_server(monkeypatch,
                                                     tmp_path):
    """save/load_optimizer_states ride the SERVER (update_on_kvstore):
    the fused dist path must round-trip them and keep training fused."""
    mod, _, _, engaged = _dist_fit(monkeypatch, True, "sync", "adam",
                                   {"learning_rate": 0.01},
                                   keep_module=True)
    try:
        assert engaged == "dist"
        fname = str(tmp_path / "dist_opt.states")
        mod.save_optimizer_states(fname)
        mod.load_optimizer_states(fname)
        x, y = _toy_problem()
        batch = mx.io.DataBatch([mx.nd.array(x[:32])],
                                [mx.nd.array(y[:32])])
        mod.forward_backward(batch)
        mod.update()
        assert mod._fused is not None and mod._fused.mode == "dist"
    finally:
        mod._kvstore.close()


def test_fused_dist_async_loss_band_and_window(monkeypatch):
    """Async mode: same model converges (loss band = final accuracy),
    pushes ride the bounded-inflight window whose counters surface in
    kv.stats()['module_fused_dist']."""
    _, params, stats, engaged = _dist_fit(
        monkeypatch, True, "async", "sgd", {"learning_rate": 0.5})
    assert engaged == "dist"
    for v in params.values():
        assert np.isfinite(v).all()
    win = stats["module_fused_dist"]
    assert 1 <= win["inflight_hwm"] <= win["window"]
    assert win["dispatched"] >= 6          # epochs * batches shipped
    assert win["inflight"] == 0            # flushed at get_params
    assert win["completed"] == win["dispatched"]
    # accuracy band vs the eager dist run
    _, eparams, _, _ = _dist_fit(monkeypatch, False, "sync", "sgd",
                                 {"learning_rate": 0.5})
    for k in params:
        # async staleness means not bitwise, but the same neighborhood
        assert np.allclose(params[k], eparams[k], rtol=0.3, atol=0.3), k


def test_fused_dist_local_mode_parity(monkeypatch):
    """MXTPU_UPDATE_ON_KVSTORE=0: the store only merges gradients and
    the worker applies the optimizer — the fused path renders this as
    grad program + donated local apply. Parity is the PR-5 fused-apply
    tolerance (one fusion boundary differs from the eager per-param
    op), not bitwise; the bit-for-bit contract is the server-side
    (update_on_kvstore) sync mode above."""
    monkeypatch.setenv("MXTPU_UPDATE_ON_KVSTORE", "0")
    _, fused, _, m1 = _dist_fit(monkeypatch, True, "sync", "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                epochs=2)
    _, eager, _, m2 = _dist_fit(monkeypatch, False, "sync", "sgd",
                                {"learning_rate": 0.1, "momentum": 0.9},
                                epochs=2)
    assert m1 == "dist_local" and m2 is None
    for k in fused:
        np.testing.assert_allclose(fused[k], eager[k], rtol=5e-4,
                                   atol=1e-6, err_msg=k)


def test_fused_dist_kill_switch_logs_reason(monkeypatch, caplog):
    """MXTPU_MODULE_FUSED_DIST=0 keeps kvstore modules eager, and the
    silent fallback names its reason ONCE at debug level."""
    with caplog.at_level(logging.DEBUG):
        _, _, _, engaged = _dist_fit(monkeypatch, False, "sync")
    assert engaged is None
    msgs = [r.message for r in caplog.records
            if "fused train step not engaged" in r.message]
    assert msgs, "fallback must be logged"
    assert "MXTPU_MODULE_FUSED_DIST=0" in msgs[0]
    assert len(msgs) == 1, "the fallback log is one-shot per module"


def test_fallback_reasons_are_named(monkeypatch, caplog):
    """The narrowed predicate: every silent fallback (inputs_need_grad
    here) is diagnosable through the debug log."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label, inputs_need_grad=True)
    mod.init_params(mx.initializer.Xavier())
    with caplog.at_level(logging.DEBUG):
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05})
    assert mod._fused is None
    assert any("inputs_need_grad" in r.message for r in caplog.records)


def test_fused_eligible_modes():
    """_fused_eligible's (mode, reason) contract on a plain local
    module."""
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    mode, reason = fused_mod._fused_eligible(mod)
    assert mode == "local" and reason is None


def test_fused_dist_monitor_falls_back_mid_run(monkeypatch):
    """A Monitor install mid-run disables the dist fast path with the
    usual one warning and drains the window first."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", "async")
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore="dist_async", optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    kv = mod._kvstore
    try:
        assert mod._fused is not None and mod._fused.mode == "dist"
        batch = next(iter(it))
        mod.forward_backward(batch)
        mod.update()
        mod.install_monitor(mx.monitor.Monitor(1))
        with pytest.warns(UserWarning, match="fused train step disabled"):
            mod.forward_backward(batch)
        mod.update()
        assert mod._fused is None
        win = kv.stats()["module_fused_dist"]
        assert win["inflight"] == 0, "disable must drain the window"
    finally:
        kv.close()
