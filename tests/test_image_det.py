"""Detection augmenters + ImageDetIter (reference tests for
python/mxnet/image/detection.py; geometry checked analytically)."""
import random

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.image_detection import (DetBorrowAug, DetHorizontalFlipAug,
                                   DetRandomCropAug, DetRandomPadAug,
                                   DetRandomSelectAug, CreateDetAugmenter,
                                   ImageDetIter, _box_iob)


def _img(h=60, w=80):
    rng = np.random.RandomState(0)
    return nd.array(rng.randint(0, 255, (h, w, 3)).astype(np.uint8))


def _label():
    # one object in the left half, one in the bottom-right corner
    return np.array([[0, 0.10, 0.20, 0.40, 0.60],
                     [1, 0.70, 0.70, 0.95, 0.95]], np.float32)


def test_box_iob():
    boxes = _label()[:, 1:5]
    full = np.array([0.0, 0.0, 1.0, 1.0])
    np.testing.assert_allclose(_box_iob(boxes, full), [1.0, 1.0])
    left = np.array([0.0, 0.0, 0.5, 1.0])
    cov = _box_iob(boxes, left)
    assert cov[0] == pytest.approx(1.0)
    assert cov[1] == pytest.approx(0.0)


def test_horizontal_flip_boxes():
    random.seed(0)
    aug = DetHorizontalFlipAug(p=1.0)
    img, lab = aug(_img(), _label())
    # x mirrored, y unchanged, still well-formed
    np.testing.assert_allclose(lab[0, [1, 3]], [1 - 0.40, 1 - 0.10],
                               atol=1e-6)
    np.testing.assert_allclose(lab[:, [2, 4]], _label()[:, [2, 4]])
    assert (lab[:, 1] <= lab[:, 3]).all()
    # image actually mirrored
    np.testing.assert_allclose(img.asnumpy(),
                               _img().asnumpy()[:, ::-1])


def test_random_crop_keeps_and_renormalizes():
    random.seed(3)
    aug = DetRandomCropAug(min_object_covered=0.5,
                           area_range=(0.3, 0.9), min_eject_coverage=0.3,
                           max_attempts=100)
    img, lab = aug(_img(), _label())
    assert lab.shape[0] >= 1
    assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()
    assert (lab[:, 1] < lab[:, 3]).all() and (lab[:, 2] < lab[:, 4]).all()
    assert img.shape[0] <= 60 and img.shape[1] <= 80


def test_random_pad_expands_and_rescales():
    random.seed(1)
    aug = DetRandomPadAug(area_range=(1.5, 2.5), pad_val=(9, 9, 9))
    img, lab = aug(_img(), _label())
    assert img.shape[0] >= 60 and img.shape[1] >= 80
    # boxes shrink into the canvas but stay ordered
    assert (lab[:, 1] < lab[:, 3]).all() and (lab[:, 2] < lab[:, 4]).all()
    w_before = _label()[:, 3] - _label()[:, 1]
    w_after = lab[:, 3] - lab[:, 1]
    assert (w_after <= w_before + 1e-6).all()


def test_random_select_skip():
    aug = DetRandomSelectAug([DetHorizontalFlipAug(p=1.0)], skip_prob=1.0)
    img, lab = aug(_img(), _label())
    np.testing.assert_allclose(lab, _label())


def test_create_det_augmenter_chain():
    random.seed(0)
    augs = CreateDetAugmenter((3, 32, 48), rand_crop=0.5, rand_pad=0.5,
                              rand_mirror=True, mean=True, std=True)
    img, lab = _img(), _label()
    for a in augs:
        img, lab = a(img, lab)
    assert img.shape == (32, 48, 3)          # forced to data_shape
    assert lab.shape[1] == 5
    assert img.dtype == np.float32


def test_image_det_iter_batching():
    random.seed(0)
    items = []
    rng = np.random.RandomState(0)
    for i in range(5):
        arr = rng.randint(0, 255, (40, 50, 3)).astype(np.uint8)
        import io as _io
        try:
            from PIL import Image
            buf = _io.BytesIO()
            Image.fromarray(arr).save(buf, format="PNG")
            items.append((buf.getvalue(),
                          _label()[:1 + i % 2]))
        except ImportError:
            pytest.skip("PIL not available")
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32), imglist=None,
                      aug_list=CreateDetAugmenter((3, 32, 32)),
                      path_imgrec=None)
    # inject pre-parsed items directly (record files covered elsewhere)
    it._items = [(src, it._parse_label(lbl)) for src, lbl in items]
    it.max_objects = max(l.shape[0] for _, l in it._items)
    it._order = list(range(len(it._items)))
    it.reset()
    batch = it.next()
    data, label = batch.data[0], batch.label[0]
    assert data.shape == (2, 3, 32, 32)
    assert label.shape == (2, it.max_objects, 5)
    lab = label.asnumpy()
    # padding rows are -1
    assert ((lab == -1).all(axis=2) | (lab[..., 3] > lab[..., 1])).all()
    assert it.provide_label[0].shape == (2, it.max_objects, 5)


def test_parse_label_flat_reference_format():
    it = ImageDetIter.__new__(ImageDetIter)
    flat = np.array([4, 5, 0, 0,
                     0, 0.1, 0.2, 0.4, 0.6,
                     1, 0.7, 0.7, 0.95, 0.95], np.float32)
    parsed = ImageDetIter._parse_label(it, flat)
    assert parsed.shape == (2, 5)
    np.testing.assert_allclose(parsed, _label())
    with pytest.raises(ValueError):
        ImageDetIter._parse_label(it, np.array([1.0, 2.0, 3.0]))


def test_sync_label_shape():
    a = ImageDetIter.__new__(ImageDetIter)
    b = ImageDetIter.__new__(ImageDetIter)
    a.max_objects, a.label_width = 3, 5
    b.max_objects, b.label_width = 7, 6
    a.sync_label_shape(b)
    assert a.max_objects == b.max_objects == 7
    assert a.label_width == b.label_width == 6
    assert a.label_shape == (7, 6)


def test_gray_hue_augmenters():
    random.seed(0)
    from mxtpu.image import RandomGrayAug, HueJitterAug
    img = _img()
    gray = RandomGrayAug(p=1.0)(img)
    g = gray.asnumpy()
    np.testing.assert_allclose(g[..., 0], g[..., 1], atol=1.0)
    np.testing.assert_allclose(g[..., 1], g[..., 2], atol=1.0)
    hue = HueJitterAug(hue=0.3)(img)
    assert hue.shape == img.shape
    # hue rotation preserves rough luminance
    lum = lambda a: (a.asnumpy().astype(np.float64)
                     @ [0.299, 0.587, 0.114]).mean()
    assert abs(lum(hue) - lum(img)) < 12.0
    augs = CreateDetAugmenter((3, 32, 32), rand_gray=0.5, hue=0.2)
    im2, lab = _img(), _label()
    for a in augs:
        im2, lab = a(im2, lab)
    assert im2.shape == (32, 32, 3)


def test_last_batch_discard():
    from mxtpu.image import ImageIter
    import io as _io
    from PIL import Image
    rng = np.random.RandomState(0)
    items = []
    for _ in range(5):
        buf = _io.BytesIO()
        Image.fromarray(rng.randint(0, 255, (8, 8, 3), dtype=np.uint8)
                        ).save(buf, format="PNG")
        items.append((buf.getvalue(), 0.0))
    it = ImageIter(2, (3, 8, 8), aug_list=[], last_batch_handle="discard")
    it._items = items
    it._order = list(range(5))
    it.reset()
    assert sum(1 for _ in it) == 2   # 5//2, last partial batch dropped
    with pytest.raises(ValueError):
        ImageIter(2, (3, 8, 8), aug_list=[], last_batch_handle="roll_over")


def test_det_iter_reshape_updates_aug_chain():
    it = ImageDetIter.__new__(ImageDetIter)
    it.det_auglist = CreateDetAugmenter((3, 32, 32))
    it.data_shape = (3, 32, 32)
    it.max_objects, it.label_width = 2, 5
    it.reshape(data_shape=(3, 64, 48))
    import mxtpu.image as mimg
    sizes = [a.augmenter.size for a in it.det_auglist
             if getattr(a, "augmenter", None) is not None
             and isinstance(a.augmenter, mimg.ForceResizeAug)]
    assert sizes == [(48, 64)]
    img, lab = it.det_auglist[0](_img(), _label())
    for a in it.det_auglist:
        img, lab = a(img, lab)
    assert img.shape[:2] == (64, 48)
