"""Mixed-precision bf16 Module training (ISSUE 12, ``MXTPU_AMP=bf16``):
bf16 compute + fp32 master weights as a MODE of the fused train step —
parity bands vs the fp32 fused path (sgd + adam, single-host and dist
sync), fp32 master-weight/optimizer-state invariants and their
save/load round-trips (CheckpointManager artifacts AND the server
``opt_states`` ops), the loss-scale overflow skip driven by a seeded
``nan_grad`` fault row at the new ``module.step`` point, BN running
statistics staying fp32 on device, GradientCompression composition
(2-bit beats bf16 — no double-compress), the AMP-ineligible one-shot
debug log, and the shared auto-layout wrapper on the fused path."""
import logging

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import fault
from mxtpu.module import fused as fused_mod


def _toy_problem(n=192, seed=5, classes=4):
    r = np.random.RandomState(seed)
    y = (r.rand(n) * classes).astype("f")
    x = r.rand(n, 16).astype("f") * 0.1
    for i in range(n):
        x[i, int(y[i]) * 4:int(y[i]) * 4 + 4] += 1.0
    return x, y


def _mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _bn_mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.BatchNorm(net, name="bn1", fix_gamma=False)
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit(monkeypatch, amp, kvstore=None, optimizer="sgd",
         opt_params=None, epochs=3, sym_fn=_mlp, keep_module=False,
         auto_layout=None):
    """One Module.fit with/without AMP; returns (module-or-None,
    params, engaged fused mode, group state-or-None)."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_AMP", amp)
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    if auto_layout is not None:
        monkeypatch.setenv("MXTPU_AUTO_LAYOUT", auto_layout)
    np.random.seed(7)
    mx.random.seed(7)
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    mod = mx.mod.Module(sym_fn(), context=mx.cpu())
    kw = {"kvstore": kvstore} if kvstore else {}
    mod.fit(it, optimizer=optimizer,
            optimizer_params=opt_params or {"learning_rate": 0.1,
                                            "momentum": 0.9},
            num_epoch=epochs, eval_metric="acc", **kw)
    engaged = mod._fused.mode if mod._fused is not None else None
    group = mod._fused._group if mod._fused is not None else None
    args, _ = mod.get_params()
    params = {k: v.asnumpy().copy() for k, v in args.items()}
    if keep_module:
        return mod, params, engaged, group
    if mod._kvstore is not None:
        mod._kvstore.close()
    return None, params, engaged, group


# adam normalizes step sizes, so a near-zero weight takes full-size
# steps whose bf16 rounding noise accumulates — its band is absolute
# (a few steps' worth), sgd's is the tight one
_BANDS = {"sgd": dict(rtol=0.1, atol=0.02),
          "adam": dict(rtol=0.25, atol=0.06)}


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01}),
])
def test_amp_local_parity_band(monkeypatch, optimizer, opt_params):
    """The bf16 fused fit lands in the fp32 fused fit's neighborhood
    (bf16 shares fp32's exponent range — only mantissa differs), with
    fp32 master weights in the donated store the whole way."""
    mod, bf16, m1, fs = _fit(monkeypatch, "bf16", optimizer=optimizer,
                             opt_params=dict(opt_params),
                             keep_module=True)
    assert m1 == "local" and fs.amp == "bf16"
    # fp32 masters: the device param store, the updater state slots
    for name, arr in fs.param_store.items():
        assert arr.dtype == np.float32, (name, arr.dtype)
    for slot, st in fs.updater.states.items():
        for leaf in jax.tree_util.tree_leaves(
                fused_mod.state_to_tree(st)):
            assert leaf.dtype == jnp.float32, (slot, leaf.dtype)
    _, f32, m2, _ = _fit(monkeypatch, "", optimizer=optimizer,
                         opt_params=dict(opt_params))
    assert m2 == "local"
    assert bf16.keys() == f32.keys()
    for k in bf16:
        assert np.isfinite(bf16[k]).all(), k
        np.testing.assert_allclose(bf16[k], f32[k], err_msg=k,
                                   **_BANDS[optimizer])


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
])
def test_amp_dist_sync_parity_band(monkeypatch, optimizer, opt_params):
    """dist sync (update_on_kvstore): bf16 gradients on the wire, fp32
    master tables on the server, final params in the fp32 run's band."""
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", "sync")
    mod, bf16, m1, fs = _fit(monkeypatch, "bf16", kvstore="dist_async",
                             optimizer=optimizer,
                             opt_params=dict(opt_params),
                             keep_module=True)
    try:
        assert m1 == "dist" and fs.amp == "bf16"
        assert fs.wire_dtype == jnp.bfloat16
        # the server-side master tables stay fp32
        srv = mod._kvstore._own_server
        for k, v in srv._table.items():
            assert v.dtype == np.float32, (k, v.dtype)
    finally:
        mod._kvstore.close()
    _, f32, m2, _ = _fit(monkeypatch, "", kvstore="dist_async",
                         optimizer=optimizer,
                         opt_params=dict(opt_params))
    assert m2 == "dist"
    for k in bf16:
        assert np.isfinite(bf16[k]).all(), k
        np.testing.assert_allclose(bf16[k], f32[k], err_msg=k,
                                   **_BANDS[optimizer])


def test_amp_master_weight_checkpoint_roundtrip(monkeypatch, tmp_path):
    """save_checkpoint artifacts carry fp32 masters (never a rounded
    bf16 copy), and a load + continued AMP training works."""
    mod, params, engaged, _ = _fit(monkeypatch, "bf16",
                                   optimizer="adam",
                                   opt_params={"learning_rate": 0.01},
                                   keep_module=True)
    assert engaged == "local"
    prefix = str(tmp_path / "amp")
    mod.save_checkpoint(prefix, 1, save_optimizer_states=True)
    loaded = mx.mod.Module.load(prefix, 1, load_optimizer_states=True)
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    loaded.bind(it.provide_data, it.provide_label)
    loaded.init_optimizer(optimizer="adam",
                          optimizer_params={"learning_rate": 0.01})
    args, _ = loaded.get_params()
    for k, v in args.items():
        assert v.dtype == np.float32, (k, v.dtype)
        np.testing.assert_array_equal(v.asnumpy(), params[k], err_msg=k)
    assert loaded._fused is not None and \
        loaded._fused._group.amp == "bf16"
    batch = mx.io.DataBatch([mx.nd.array(x[:32])], [mx.nd.array(y[:32])])
    loaded.forward_backward(batch)
    loaded.update()
    args2, _ = loaded.get_params()
    assert any(not np.array_equal(args2[k].asnumpy(), params[k])
               for k in params)


def test_amp_dist_server_opt_states_roundtrip(monkeypatch, tmp_path):
    """save/load_optimizer_states through the SERVER ``opt_states`` /
    ``set_opt_states`` wire ops while the wire runs bf16: the restored
    state is the fp32 master state and AMP training continues fused."""
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", "sync")
    mod, _, engaged, fs = _fit(monkeypatch, "bf16", kvstore="dist_async",
                               optimizer="adam",
                               opt_params={"learning_rate": 0.01},
                               keep_module=True)
    try:
        assert engaged == "dist" and fs.amp == "bf16"
        fname = str(tmp_path / "amp_dist.states")
        mod.save_optimizer_states(fname)
        mod.load_optimizer_states(fname)
        srv = mod._kvstore._own_server
        with srv._updater_lock:
            for slot, st in srv._updater.states.items():
                for leaf in jax.tree_util.tree_leaves(
                        fused_mod.state_to_tree(st)):
                    assert np.dtype(leaf.dtype) == np.float32, slot
        x, y = _toy_problem()
        batch = mx.io.DataBatch([mx.nd.array(x[:32])],
                                [mx.nd.array(y[:32])])
        mod.forward_backward(batch)
        mod.update()
        assert mod._fused is not None and mod._fused.mode == "dist"
    finally:
        mod._kvstore.close()


def test_amp_loss_scale_overflow_skip_nan_grad_fault_row(monkeypatch):
    """Fault-matrix row (kind=nan_grad, point=module.step): a poisoned
    batch under MXTPU_AMP_LOSS_SCALE makes every gradient non-finite;
    the fused program's TrainGuard-style verdict SKIPS the step
    in-program — params/opt-state/step-count bit-identical to before,
    the skip counted by amp_overflow_skips(), training resumes on the
    next good batch."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    monkeypatch.setenv("MXTPU_AMP_LOSS_SCALE", "1024")
    np.random.seed(7)
    mx.random.seed(7)
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1,
                                         "momentum": 0.9})
    fs = mod._fused._group
    assert fs.loss_scale == 1024.0
    batches = list(it)

    def snap():
        exec_ = mod._exec_group.execs[0]
        return {n: np.asarray(exec_.arg_dict[n].asnumpy()).copy()
                for n in ("fc1_weight", "fc2_weight", "fc1_bias")}

    with fault.inject("kind=nan_grad,point=module.step,nth=3") as inj:
        for b in batches[:2]:
            mod.forward_backward(b)
            mod.update()
        before = snap()
        mod.forward_backward(batches[0])   # step 3: poisoned
        mod.update()
        assert inj.stats()[0][4] == 1, "the nan_grad never fired"
    after_skip = snap()
    for k in before:
        np.testing.assert_array_equal(before[k], after_skip[k],
                                      err_msg=k)
    assert fs.amp_overflow_skips() == 1
    mod.forward_backward(batches[1])       # good batch: training resumes
    mod.update()
    resumed = snap()
    assert any(not np.array_equal(after_skip[k], resumed[k])
               for k in resumed)
    for k, v in resumed.items():
        assert np.isfinite(v).all(), k


def test_amp_bn_running_stats_stay_fp32_on_device(monkeypatch):
    """BN running mean/var live in the donated aux store as fp32 and
    update INSIDE the fused program — the AMP cast policy never touches
    aux, and the per-batch stat math runs f32."""
    mod, _, engaged, fs = _fit(monkeypatch, "bf16", sym_fn=_bn_mlp,
                               keep_module=True)
    assert engaged == "local" and fs.amp == "bf16"
    exec_ = mod._exec_group.execs[0]
    init_mean = np.zeros(16, np.float32)
    for name, arr in exec_.aux_dict.items():
        assert arr.dtype == np.float32, (name, arr.dtype)
        host = arr.asnumpy()
        assert np.isfinite(host).all(), name
        if name.endswith("moving_mean"):
            assert not np.array_equal(host, init_mean), \
                "running mean never updated in-program"


def test_amp_gradient_compression_composes(monkeypatch):
    """2-bit compression beats bf16: with a compressed store the fused
    dist step keeps fp32 emitted gradients (wire_dtype cleared — no
    double-compress) while compute stays bf16, and training stays
    finite."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", "sync")
    np.random.seed(7)
    mx.random.seed(7)
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    kv = mx.kv.create("dist_async")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    try:
        fs = mod._fused._group
        assert fs.amp == "bf16" and fs.compute_dtype == jnp.bfloat16
        assert fs.wire_dtype is None, "compressed parts must skip the cast"
        for b in list(it)[:3]:
            mod.forward_backward(b)
            mod.update()
        args, _ = mod.get_params()
        for k, v in args.items():
            assert np.isfinite(v.asnumpy()).all(), k
    finally:
        kv.close()


def test_amp_ineligible_params_log_once_keep_fp32_fused(monkeypatch,
                                                        caplog):
    """Non-fp32 parameters: AMP stays off with a ONE-shot named debug
    log, the fp32 fused path still engages — never a silent wrong-dtype
    step, never a needless eager fallback."""
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    monkeypatch.setenv("MXTPU_AMP", "bf16")
    x, y = _toy_problem()
    it = mx.io.NDArrayIter(x, y, batch_size=32,
                           label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.initializer.Xavier())
    exec_ = mod._exec_group.execs[0]
    exec_.arg_dict["fc1_weight"]._data = \
        exec_.arg_dict["fc1_weight"]._data.astype(jnp.float16)
    with caplog.at_level(logging.DEBUG):
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05})
    assert mod._fused is not None, "fp32 fused path must still engage"
    assert mod._fused._group.amp is None
    msgs = [r.message for r in caplog.records
            if "AMP mode not engaged" in r.message]
    assert len(msgs) == 1, msgs
    assert "fc1_weight" in msgs[0] and "float16" in msgs[0]


def test_amp_rejects_unknown_mode(monkeypatch):
    monkeypatch.setenv("MXTPU_AMP", "fp8")
    with pytest.raises(ValueError, match="MXTPU_AMP"):
        fused_mod.amp_mode()


@pytest.mark.parametrize("amp", ["", "bf16"])
def test_auto_layout_fused_local_parity_and_zero_retraces(monkeypatch,
                                                          amp):
    """MXTPU_AUTO_LAYOUT=1 on the fused Module path: the AutoLayoutStep
    wrapper compiles once per signature (zero retraces after warmup,
    same program-cache accounting) and the numbers agree with the
    default-layout run."""
    _, base, m0, _ = _fit(monkeypatch, amp, auto_layout="0")
    _, auto, m1, fs = _fit(monkeypatch, amp, auto_layout="1")
    assert m0 == m1 == "local" and fs.auto_layout
    assert fs.stats["compiles"] <= 2
    assert fs.stats["cache_hits"] >= fs.stats["steps"] - 2
    for k in base:
        np.testing.assert_allclose(auto[k], base[k], rtol=1e-4,
                                   atol=1e-5, err_msg=k)


def test_auto_layout_fused_dist_modes(monkeypatch):
    """Auto-layout composes with the dist modes (grad-emitting step:
    AUTO on the donated aux store only; dist_local: donated apply)."""
    monkeypatch.setenv("MXTPU_MODULE_DIST_MODE", "sync")
    _, params, mode, _ = _fit(monkeypatch, "bf16", kvstore="dist_async",
                              auto_layout="1")
    assert mode == "dist"
    for k, v in params.items():
        assert np.isfinite(v).all(), k
    monkeypatch.setenv("MXTPU_UPDATE_ON_KVSTORE", "0")
    _, params, mode, _ = _fit(monkeypatch, "bf16", kvstore="dist_async",
                              auto_layout="1")
    assert mode == "dist_local"
    for k, v in params.items():
        assert np.isfinite(v).all(), k
