"""Aux subsystem tests: recordio (python + native), profiler, engine,
monitor, visualization. Reference models: tests for recordio in
tests/python/unittest/test_recordio.py, profiler example in
example/profiler/, monitor in python/mxnet/monitor.py docstrings.
"""
import json
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, recordio
from mxtpu import _native


def test_recordio_round_trip(tmp_path):
    path = str(tmp_path / "a.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"x" * i for i in range(1, 6)]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        d = r.read()
        if d is None:
            break
        got.append(d)
    assert got == payloads


def test_indexed_recordio(tmp_path):
    idx = str(tmp_path / "a.idx")
    rec = str(tmp_path / "a.rec")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(10):
        w.write_idx(i, b"record-%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec, "r")
    assert r.keys == list(range(10))
    assert r.read_idx(7) == b"record-7"
    assert r.read_idx(0) == b"record-0"


def test_pack_unpack_header():
    h = recordio.IRHeader(0, 3.5, 42, 0)
    rec = recordio.pack(h, b"payload")
    h2, s = recordio.unpack(rec)
    assert h2.label == 3.5 and h2.id == 42 and s == b"payload"
    # array label
    h3 = recordio.IRHeader(3, np.array([1.0, 2.0, 3.0], np.float32), 1, 0)
    rec3 = recordio.pack(h3, b"z")
    h4, s4 = recordio.unpack(rec3)
    np.testing.assert_array_equal(h4.label, [1, 2, 3])
    assert s4 == b"z"


@pytest.mark.skipif(not _native.available(),
                    reason="native IO library not built")
def test_native_matches_python(tmp_path):
    path = str(tmp_path / "n.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [os.urandom(n) for n in (1, 7, 64, 0, 13)]
    for p in payloads:
        w.write(p)
    w.close()
    r = _native.NativeRecordReader(path)
    got = []
    while True:
        d = r.read()
        if d is None:
            break
        got.append(d)
    assert got == payloads
    # native writer -> python reader
    path2 = str(tmp_path / "n2.rec")
    nw = _native.NativeRecordWriter(path2)
    offsets = [nw.write(p) for p in payloads]
    nw.close()
    pr = recordio.MXRecordIO(path2, "r")
    got2 = []
    while True:
        d = pr.read()
        if d is None:
            break
        got2.append(d)
    assert got2 == payloads
    # random access by offset
    r2 = _native.NativeRecordReader(path2)
    assert r2.read_at(offsets[2]) == payloads[2]


@pytest.mark.skipif(not _native.available(),
                    reason="native IO library not built")
def test_native_prefetcher(tmp_path):
    path = str(tmp_path / "p.rec")
    w = recordio.MXRecordIO(path, "w")
    for i in range(100):
        w.write(b"%06d" % i)
    w.close()
    pf = _native.NativePrefetcher(path, capacity=8)
    recs = list(pf)
    assert recs == [b"%06d" % i for i in range(100)]


def test_profiler_chrome_trace(tmp_path):
    fname = str(tmp_path / "prof.json")
    mx.profiler.set_config(filename=fname)
    mx.profiler.set_state("run")
    a = nd.ones((8, 8))
    (a * 3).sum().wait_to_read()
    with mx.profiler.Task("mytask"):
        pass
    d = mx.profiler.Domain("custom")
    c = d.new_counter("ctr", 5)
    c += 2
    mx.profiler.set_state("stop")
    mx.profiler.dump()
    data = json.load(open(fname))
    names = [e["name"] for e in data["traceEvents"]]
    assert "broadcast_mul" in names
    assert "mytask" in names
    assert "ctr" in names
    txt = mx.profiler.dumps(reset=True)
    assert "broadcast_mul" in txt


def test_profiler_pause_resume(tmp_path):
    mx.profiler.set_config(filename=str(tmp_path / "p.json"))
    mx.profiler.set_state("run")
    mx.profiler.pause()
    nd.ones((2, 2)).wait_to_read()
    before = len(mx.profiler._state["events"])
    (nd.ones((2, 2)) + 1).wait_to_read()
    assert len(mx.profiler._state["events"]) == before
    mx.profiler.resume()
    (nd.ones((2, 2)) + 1).wait_to_read()
    assert len(mx.profiler._state["events"]) > before
    mx.profiler.set_state("stop")
    mx.profiler._state["events"] = []


def test_naive_engine_sync():
    mx.engine.set_engine_type("NaiveEngine")
    assert mx.engine.is_synchronous()
    out = nd.ones((4, 4)) * 2  # each op blocks; result must be correct
    np.testing.assert_array_equal(out.asnumpy(), np.full((4, 4), 2.0))
    mx.engine.set_engine_type("ThreadedEnginePerDevice")
    assert not mx.engine.is_synchronous()
    prev = mx.engine.set_bulk_size(30)
    with mx.engine.bulk(5):
        pass
    mx.engine.set_bulk_size(prev)
    mx.engine.waitall()


def test_monitor_collects_stats():
    s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4, name="fc")
    exe = s.simple_bind(mx.cpu(), data=(2, 3))
    mon = mx.monitor.Monitor(1, pattern=".*")
    mon.install(exe)
    mon.tic()
    exe.forward(data=nd.ones((2, 3)))
    res = mon.toc()
    assert len(res) > 0
    names = [k for _, k, _ in res]
    assert any("fc" in n for n in names)


def test_print_summary_counts_params(capsys):
    s = mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4, name="fc1")
    total = mx.viz.print_summary(s, shape={"data": (2, 8)})
    out = capsys.readouterr().out
    assert "fc1" in out
    assert total == 8 * 4 + 4


def test_launcher_cluster_modes_dry_run():
    """mpi/slurm/sge launcher modes construct the reference-shaped
    dispatch (tools/launch.py vs reference dmlc-tracker dispatchers);
    dry-run prints the exact command/script with the env contract."""
    import os
    import subprocess
    import sys
    root = os.path.join(os.path.dirname(__file__), "..")
    for mode, markers in (
            ("mpi", ["mpirun", "-np 4", "OMPI_COMM_WORLD_RANK"]),
            ("slurm", ["srun", "--ntasks=4", "SLURM_PROCID"]),
            ("sge", ["#$ -t 1-4", "SGE_TASK_ID"])):
        res = subprocess.run(
            [sys.executable, os.path.join(root, "tools", "launch.py"),
             "-n", "4", "--launcher", mode, "--dry-run",
             "--coordinator-host", "node0", "python worker.py"],
            capture_output=True, text=True, timeout=60)
        assert res.returncode == 0, (mode, res.stderr)
        for m in markers:
            assert m in res.stdout, (mode, m, res.stdout)
        assert "MXTPU_COORDINATOR=node0:9327" in res.stdout, mode
        assert "MXTPU_NUM_PROCS=4" in res.stdout, mode


def test_jit_step_attributes_blocks_via_named_scope():
    """Gluon blocks stamp jax.named_scope onto their traced ops, so a
    compiled step's HLO op_name metadata attributes time per block/phase
    (the reference's per-op profiler view, threaded_engine.h:339-350)."""
    import jax
    import jax.numpy as jnp
    from mxtpu import gluon

    net = gluon.nn.HybridSequential(prefix="prof_")
    with net.name_scope():
        net.add(gluon.nn.Dense(8, activation="relu"))
        net.add(gluon.nn.Dense(4))
    net.initialize()
    x = mx.nd.ones((2, 6))
    net(x)  # materialize params

    def f(xv):
        return net(mx.nd.NDArray(xv))._data

    hlo = jax.jit(f).lower(jnp.ones((2, 6))).compile().as_text()
    assert "prof_" in hlo, "block name_scope missing from compiled HLO"
