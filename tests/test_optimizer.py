"""Optimizer tests (reference: tests/python/unittest/test_optimizer.py —
each optimizer's update checked against a numpy reference implementation)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import optimizer as opt


def _quadratic_min(optimizer, steps=200):
    """Minimize ||w - target||^2; returns final distance."""
    target = np.arange(6, dtype="float32").reshape(2, 3) / 10.0
    w = mx.nd.zeros((2, 3))
    state = optimizer.create_state(0, w)
    for _ in range(steps):
        g = mx.nd.array(2.0 * (w.asnumpy() - target))
        optimizer.update(0, w, g, state)
    return float(np.abs(w.asnumpy() - target).max())


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.05}),
    ("adagrad", {"learning_rate": 0.2}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01, "centered": True}),
    ("adadelta", {"rho": 0.9}),
    ("ftrl", {"learning_rate": 0.5}),
    ("adamax", {"learning_rate": 0.05}),
    ("nadam", {"learning_rate": 0.05}),
    ("signum", {"learning_rate": 0.01}),
    ("ftml", {"learning_rate": 0.02}),
    ("dcasgd", {"learning_rate": 0.1}),
])
def test_optimizer_converges(name, kwargs):
    o = opt.create(name, **kwargs)
    err = _quadratic_min(o)
    assert err < 0.05, "%s end error %f" % (name, err)


def test_sgd_matches_numpy():
    """sgd_mom_update vs explicit numpy update rule."""
    lr, momentum, wd = 0.1, 0.9, 0.01
    w0 = np.random.RandomState(0).randn(4, 5).astype("float32")
    g0 = np.random.RandomState(1).randn(4, 5).astype("float32")
    w = mx.nd.array(w0)
    o = opt.create("sgd", learning_rate=lr, momentum=momentum, wd=wd,
                   rescale_grad=1.0)
    state = o.create_state(0, w)
    o.update(0, w, mx.nd.array(g0), state)
    mom_np = -(lr) * (g0 + wd * w0)
    w_np = w0 + mom_np
    np.testing.assert_allclose(w.asnumpy(), w_np, rtol=1e-5)
    o.update(0, w, mx.nd.array(g0), state)
    mom_np = momentum * mom_np - lr * (g0 + wd * w_np)
    w_np = w_np + mom_np
    np.testing.assert_allclose(w.asnumpy(), w_np, rtol=1e-5)


def test_adam_matches_numpy():
    lr, b1, b2, eps = 0.01, 0.9, 0.999, 1e-8
    w0 = np.random.RandomState(0).randn(10).astype("float32")
    g0 = np.random.RandomState(1).randn(10).astype("float32")
    w = mx.nd.array(w0)
    o = opt.create("adam", learning_rate=lr, beta1=b1, beta2=b2, epsilon=eps,
                   rescale_grad=1.0)
    state = o.create_state(0, w)
    o.update(0, w, mx.nd.array(g0), state)
    m = (1 - b1) * g0
    v = (1 - b2) * g0 ** 2
    lr_t = lr * np.sqrt(1 - b2) / (1 - b1)
    w_np = w0 - lr_t * m / (np.sqrt(v) + eps)
    np.testing.assert_allclose(w.asnumpy(), w_np, rtol=1e-5)


def test_lr_scheduler_factor():
    from mxtpu.lr_scheduler import FactorScheduler, MultiFactorScheduler, \
        PolyScheduler
    s = FactorScheduler(step=10, factor=0.5)
    s.base_lr = 1.0
    assert s(5) == 1.0
    assert s(11) == 0.5
    assert s(21) == 0.25

    m = MultiFactorScheduler(step=[5, 15], factor=0.1)
    m.base_lr = 1.0
    assert m(2) == 1.0
    assert abs(m(7) - 0.1) < 1e-12
    assert abs(m(16) - 0.01) < 1e-12

    p = PolyScheduler(max_update=100, base_lr=1.0, pwr=2)
    assert p(0) == 1.0
    assert abs(p(50) - 0.25) < 1e-12


def test_lr_wd_mult():
    """lr_mult/wd_mult routing by name (reference test_optimizer)."""
    o = opt.create("sgd", learning_rate=1.0,
                   param_idx2name={0: "w_weight", 1: "b_bias"})
    o.set_lr_mult({"w_weight": 0.0})
    w = mx.nd.ones((2, 2))
    g = mx.nd.ones((2, 2))
    st = o.create_state(0, w)
    o.update(0, w, g, st)
    np.testing.assert_allclose(w.asnumpy(), np.ones((2, 2)))  # lr 0 => frozen


def test_updater_states_roundtrip():
    o = opt.create("adam", learning_rate=0.01)
    u = opt.get_updater(o)
    w = mx.nd.ones((3,))
    u(0, mx.nd.ones((3,)), w)
    st = u.get_states()
    u2 = opt.get_updater(opt.create("adam", learning_rate=0.01))
    u2.set_states(st)
    assert 0 in u2.states
