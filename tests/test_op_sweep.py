"""Registry-wide operator correctness sweep.

Every canonical op in ``mxtpu.ops.registry`` is either:

* **SPEC'd** here — forward-checked against an independent numpy reference
  (or a structural ``check``), and, when differentiable, gradient-checked
  against central finite differences through ``mxtpu.autograd``; or
* **SKIP'd** with an explicit reason — usually a pointer to the dedicated
  test file that covers it in depth, or a statement of why a generic
  numeric check does not apply (custom_vjp training grads, stochastic
  ops, factorizations with sign conventions).

``test_registry_fully_covered`` asserts this partition is *total* over the
registry, so a newly registered op fails CI until it is added here.

Reference model: ``tests/python/unittest/test_operator.py`` (5.4k lines of
per-op checks) — this file is the breadth tier; the dedicated test files
(test_operator/test_vision_ops/test_rnn/...) keep the depth tier.
"""
import math
import zlib

import numpy as np
import pytest

import mxtpu.autograd as ag
import mxtpu.ndarray as nd
from mxtpu.ops import registry

# --------------------------------------------------------------------------
# machinery
# --------------------------------------------------------------------------


def _seed(name):
    return zlib.crc32(name.encode()) % (2 ** 31)


def _canonical_ops():
    seen = {}
    for n in registry.list_ops():
        op = registry.get_op(n)
        seen.setdefault(op.name, op)
    return seen


class Spec:
    """Inputs + reference for one op.

    args : callable(rng) -> list of inputs (np arrays or scalars)
    params : static keyword params for the op call
    ref : callable(*np_args, **params) -> array or tuple of arrays
          compared elementwise to the op's (user) outputs; None = smoke
    check : callable(outs, args) doing custom asserts (e.g. statistical
            checks for samplers, reconstruction checks for factorizations)
    grad : False to disable the FD gradient check (requires reason)
    grad_args : explicit arg indices to differentiate (default: every
                float-typed array argument)
    """

    def __init__(self, args, params=None, ref=None, check=None, grad=None,
                 grad_args=None, reason=None, rtol=1e-4, atol=1e-5,
                 g_rtol=0.05, g_atol=5e-3):
        self.args = args
        self.params = params or {}
        self.ref = ref
        self.check = check
        self.grad = grad
        self.grad_args = grad_args
        self.reason = reason
        self.rtol, self.atol = rtol, atol
        self.g_rtol, self.g_atol = g_rtol, g_atol


def _to_nd(a):
    return nd.array(a) if isinstance(a, np.ndarray) else a


def _run(name, args, params):
    out = getattr(nd, name)(*[_to_nd(a) for a in args], **params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    return [o.asnumpy() for o in outs]


GRAD_COORD_CAP = 10  # FD coords sampled per input (all if size <= cap)
FD_EPS = 1e-3


def _float_arg_indices(args):
    return [i for i, a in enumerate(args)
            if isinstance(a, np.ndarray) and a.dtype.kind == "f"]


# helper input factories ----------------------------------------------------

def u(r, *shape, lo=-1.0, hi=1.0):
    return r.uniform(lo, hi, shape).astype(np.float32)


def pos(r, *shape, lo=0.3, hi=2.0):
    return r.uniform(lo, hi, shape).astype(np.float32)


def away0(r, *shape, lo=0.2, hi=1.0):
    """Floats bounded away from 0 (kinks of relu/abs/sign/...)."""
    return (r.uniform(lo, hi, shape) *
            r.choice([-1.0, 1.0], shape)).astype(np.float32)


def distinct(r, *shape):
    """Distinct values (no ties for max/min/sort FD)."""
    n = int(np.prod(shape))
    vals = (np.arange(n) - n / 2.0) * 0.1 + r.uniform(-0.01, 0.01, n)
    return r.permutation(vals).reshape(shape).astype(np.float32)


def idx(r, *shape, high):
    return r.randint(0, high, shape).astype(np.int32)


def spd(r, n, batch=()):
    """Symmetric positive-definite matrix (cholesky-friendly)."""
    b = r.uniform(-1, 1, batch + (n, n))
    a = np.einsum("...ij,...kj->...ik", b, b) + n * np.eye(n)
    return a.astype(np.float32)


def lower_tri(r, n):
    m = np.tril(r.uniform(0.5, 1.5, (n, n))) + np.eye(n)
    return m.astype(np.float32)


# numpy reference helpers ---------------------------------------------------

def np_conv2d(x, w, b=None, stride=(1, 1), pad=(0, 0), dilate=(1, 1)):
    N, C, H, W = x.shape
    O, _, KH, KW = w.shape
    x = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])))
    dkh = (KH - 1) * dilate[0] + 1
    dkw = (KW - 1) * dilate[1] + 1
    OH = (x.shape[2] - dkh) // stride[0] + 1
    OW = (x.shape[3] - dkw) // stride[1] + 1
    out = np.zeros((N, O, OH, OW), np.float64)
    for n in range(N):
        for o in range(O):
            for i in range(OH):
                for j in range(OW):
                    patch = x[n, :,
                              i * stride[0]:i * stride[0] + dkh:dilate[0],
                              j * stride[1]:j * stride[1] + dkw:dilate[1]]
                    out[n, o, i, j] = (patch * w[o]).sum()
    if b is not None:
        out += b.reshape(1, -1, 1, 1)
    return out.astype(np.float32)


def np_deconv2d(x, w, stride=(1, 1), pad=(0, 0)):
    N, C, H, W = x.shape
    _, O, KH, KW = w.shape
    OH = (H - 1) * stride[0] + KH - 2 * pad[0]
    OW = (W - 1) * stride[1] + KW - 2 * pad[1]
    full = np.zeros((N, O, (H - 1) * stride[0] + KH,
                     (W - 1) * stride[1] + KW), np.float64)
    for n in range(N):
        for c in range(C):
            for i in range(H):
                for j in range(W):
                    full[n, :, i * stride[0]:i * stride[0] + KH,
                         j * stride[1]:j * stride[1] + KW] += x[n, c, i, j] * w[c]
    out = full[:, :, pad[0]:pad[0] + OH, pad[1]:pad[1] + OW]
    return out.astype(np.float32)


def np_pool2d(x, kernel, pool_type="max", stride=None, pad=(0, 0),
              count_include_pad=True):
    stride = stride or kernel
    N, C, H, W = x.shape
    fill = -np.inf if pool_type == "max" else 0.0
    xp = np.pad(x, ((0, 0), (0, 0), (pad[0], pad[0]), (pad[1], pad[1])),
                constant_values=fill)
    OH = (xp.shape[2] - kernel[0]) // stride[0] + 1
    OW = (xp.shape[3] - kernel[1]) // stride[1] + 1
    out = np.zeros((N, C, OH, OW), np.float64)
    for i in range(OH):
        for j in range(OW):
            patch = xp[:, :, i * stride[0]:i * stride[0] + kernel[0],
                       j * stride[1]:j * stride[1] + kernel[1]]
            if pool_type == "max":
                out[:, :, i, j] = patch.max(axis=(2, 3))
            else:
                out[:, :, i, j] = patch.mean(axis=(2, 3))
    return out.astype(np.float32)


def np_softmax(x, axis=-1):
    e = np.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def np_lrn(x, alpha, beta, knorm, nsize):
    N, C, H, W = x.shape
    out = np.zeros_like(x, np.float64)
    half = nsize // 2
    for c in range(C):
        lo, hi = max(0, c - half), min(C, c + half + 1)
        sq = (x[:, lo:hi] ** 2).sum(axis=1)
        out[:, c] = x[:, c] / (knorm + alpha / nsize * sq) ** beta
    return out.astype(np.float32)


def _vec(f):
    return np.vectorize(f, otypes=[np.float32])


# --------------------------------------------------------------------------
# SKIP list — ops not swept generically, with the reason / covering test
# --------------------------------------------------------------------------

SKIP = {
    "RNN": "fused multi-layer LSTM/GRU/vanilla kernel; depth-tested vs "
           "manual cell unrolls (fwd+grad) in tests/test_rnn.py",
    "Custom": "needs a user-registered python op; round-trip (fwd+bwd) "
              "covered in tests/test_custom_op.py",
    "_contrib_flash_attention": "Pallas kernel; fwd/bwd vs XLA attention in "
                                "tests/test_pallas_attention.py",
    "_contrib_gc_quantize_2bit": "2-bit gradient compression round-trip + "
                                 "error-feedback in tests/test_gradcomp.py",
    "_contrib_gc_dequantize_2bit": "see _contrib_gc_quantize_2bit",
}

# --------------------------------------------------------------------------
# SPECS
# --------------------------------------------------------------------------

SPECS = {}


def S(name, *a, **kw):
    SPECS[name] = Spec(*a, **kw)


NO_FD_CUSTOM_GRAD = ("custom_vjp training gradient by design (loss/output "
                     "head); analytic grad asserted in "
                     "test_output_head_gradients")

# ---- elemwise unary (numpy-backed and mxtpu.ops.elemwise) ----------------

S("abs", lambda r: [away0(r, 3, 4)], ref=np.abs)
S("arccos", lambda r: [u(r, 3, 4, lo=-0.8, hi=0.8)], ref=np.arccos)
S("arccosh", lambda r: [u(r, 3, 4, lo=1.5, hi=3.0)], ref=np.arccosh)
S("arcsin", lambda r: [u(r, 3, 4, lo=-0.8, hi=0.8)], ref=np.arcsin)
S("arcsinh", lambda r: [u(r, 3, 4)], ref=np.arcsinh)
S("arctan", lambda r: [u(r, 3, 4)], ref=np.arctan)
S("arctanh", lambda r: [u(r, 3, 4, lo=-0.8, hi=0.8)], ref=np.arctanh)
S("cbrt", lambda r: [pos(r, 3, 4)], ref=np.cbrt)
S("ceil", lambda r: [u(r, 3, 4, lo=-3, hi=3)], ref=np.ceil)
S("cos", lambda r: [u(r, 3, 4)], ref=np.cos)
S("cosh", lambda r: [u(r, 3, 4)], ref=np.cosh)
S("degrees", lambda r: [u(r, 3, 4)], ref=np.degrees)
S("erf", lambda r: [u(r, 3, 4)], ref=_vec(math.erf), rtol=1e-4, atol=1e-5)
S("exp", lambda r: [u(r, 3, 4)], ref=np.exp)
S("expm1", lambda r: [u(r, 3, 4)], ref=np.expm1)
S("fix", lambda r: [u(r, 3, 4, lo=-3, hi=3)], ref=np.fix)
S("floor", lambda r: [u(r, 3, 4, lo=-3, hi=3)], ref=np.floor)
S("gamma", lambda r: [pos(r, 3, 4, lo=0.5, hi=3.0)], ref=_vec(math.gamma),
  rtol=1e-3, atol=1e-4)
S("gammaln", lambda r: [pos(r, 3, 4, lo=0.5, hi=3.0)], ref=_vec(math.lgamma),
  rtol=1e-3, atol=1e-4)
S("identity", lambda r: [u(r, 3, 4)], ref=lambda x: x)
S("log", lambda r: [pos(r, 3, 4)], ref=np.log)
S("log10", lambda r: [pos(r, 3, 4)], ref=np.log10)
S("log1p", lambda r: [u(r, 3, 4, lo=-0.5, hi=2.0)], ref=np.log1p)
S("log2", lambda r: [pos(r, 3, 4)], ref=np.log2)
S("logical_not", lambda r: [r.choice([0.0, 1.0, 2.0], (3, 4)).astype("f")],
  ref=lambda x: np.logical_not(x).astype(np.float32))
S("negative", lambda r: [u(r, 3, 4)], ref=np.negative)
S("radians", lambda r: [u(r, 3, 4, lo=-180, hi=180)], ref=np.radians)
S("rcbrt", lambda r: [pos(r, 3, 4)], ref=lambda x: 1.0 / np.cbrt(x))
S("reciprocal", lambda r: [away0(r, 3, 4, lo=0.5)], ref=lambda x: 1.0 / x)
S("relu", lambda r: [away0(r, 3, 4)], ref=lambda x: np.maximum(x, 0))
S("rint", lambda r: [u(r, 3, 4, lo=-3, hi=3)], ref=np.rint)
S("round", lambda r: [u(r, 3, 4, lo=-3, hi=3)],
  ref=lambda x: np.floor(np.abs(x) + 0.5) * np.sign(x))  # MXNet rounds half away from zero
S("rsqrt", lambda r: [pos(r, 3, 4)], ref=lambda x: 1.0 / np.sqrt(x))
S("sigmoid", lambda r: [u(r, 3, 4)], ref=lambda x: 1 / (1 + np.exp(-x)))
S("sign", lambda r: [away0(r, 3, 4)], ref=np.sign)
S("sin", lambda r: [u(r, 3, 4)], ref=np.sin)
S("sinh", lambda r: [u(r, 3, 4)], ref=np.sinh)
S("smooth_l1", lambda r: [u(r, 3, 4, lo=-2, hi=2)], params={"scalar": 1.0},
  ref=lambda x, scalar: np.where(np.abs(x) < 1.0 / scalar ** 2,
                                 0.5 * (scalar * x) ** 2,
                                 np.abs(x) - 0.5 / scalar ** 2))
S("softrelu", lambda r: [u(r, 3, 4)], ref=lambda x: np.log1p(np.exp(x)))
S("softsign", lambda r: [u(r, 3, 4)], ref=lambda x: x / (1 + np.abs(x)))
S("sqrt", lambda r: [pos(r, 3, 4)], ref=np.sqrt)
S("square", lambda r: [u(r, 3, 4)], ref=np.square)
S("tan", lambda r: [u(r, 3, 4)], ref=np.tan)
S("tanh", lambda r: [u(r, 3, 4)], ref=np.tanh)
S("trunc", lambda r: [u(r, 3, 4, lo=-3, hi=3)], ref=np.trunc)
S("clip", lambda r: [np.array([[-0.9, -0.2, 0.3, 0.8],
                               [0.1, -0.7, 0.9, -0.3]], np.float32)],
  params={"a_min": -0.5, "a_max": 0.5},
  ref=lambda x, a_min, a_max: np.clip(x, a_min, a_max))

# ---- tensor-scalar family (elemwise_binary_scalar_op_*.cc) ---------------

S("_plus_scalar", lambda r: [u(r, 3, 4)], params={"scalar": 1.5},
  ref=lambda x, scalar: x + scalar)
S("_minus_scalar", lambda r: [u(r, 3, 4)], params={"scalar": 1.5},
  ref=lambda x, scalar: x - scalar)
S("_rminus_scalar", lambda r: [u(r, 3, 4)], params={"scalar": 1.5},
  ref=lambda x, scalar: scalar - x)
S("_mul_scalar", lambda r: [u(r, 3, 4)], params={"scalar": 3.0},
  ref=lambda x, scalar: x * scalar)
S("_div_scalar", lambda r: [u(r, 3, 4)], params={"scalar": 2.0},
  ref=lambda x, scalar: x / scalar)
S("_rdiv_scalar", lambda r: [away0(r, 3, 4, lo=0.5)],
  params={"scalar": 2.0}, ref=lambda x, scalar: scalar / x)
S("_mod_scalar", lambda r: [pos(r, 3, 4, lo=2.1, hi=2.9)],
  params={"scalar": 0.8}, ref=lambda x, scalar: np.mod(x, scalar))
S("_rmod_scalar", lambda r: [pos(r, 3, 4, lo=0.7, hi=0.95)],
  params={"scalar": 2.5}, ref=lambda x, scalar: np.mod(scalar, x))
S("_power_scalar", lambda r: [pos(r, 3, 4)], params={"scalar": 2.0},
  ref=lambda x, scalar: np.power(x, scalar))
S("_rpower_scalar", lambda r: [u(r, 3, 4, lo=-2, hi=2)],
  params={"scalar": 2.0}, ref=lambda x, scalar: np.power(scalar, x))
S("_maximum_scalar", lambda r: [distinct(r, 3, 4)], params={"scalar": 0.1},
  ref=lambda x, scalar: np.maximum(x, scalar))
S("_minimum_scalar", lambda r: [distinct(r, 3, 4)], params={"scalar": 0.1},
  ref=lambda x, scalar: np.minimum(x, scalar))
S("_hypot_scalar", lambda r: [away0(r, 3, 4)], params={"scalar": 1.5},
  ref=lambda x, scalar: np.hypot(x, scalar))
for _sn, _sref in [
        ("_equal_scalar", np.equal), ("_not_equal_scalar", np.not_equal),
        ("_greater_scalar", np.greater),
        ("_greater_equal_scalar", np.greater_equal),
        ("_lesser_scalar", np.less), ("_lesser_equal_scalar", np.less_equal),
        ("_logical_and_scalar", np.logical_and),
        ("_logical_or_scalar", np.logical_or),
        ("_logical_xor_scalar", np.logical_xor)]:
    def _mk_sref(f):
        return lambda x, scalar: f(x, scalar).astype(np.float32)
    S(_sn, lambda r: [r.choice([0.0, 0.5, 1.0], (3, 4)).astype("f")],
      params={"scalar": 0.5}, ref=_mk_sref(_sref))

# ---- elemwise binary ------------------------------------------------------

S("broadcast_add", lambda r: [u(r, 3, 4), u(r, 1, 4)], ref=np.add)
S("broadcast_sub", lambda r: [u(r, 3, 4), u(r, 1, 4)], ref=np.subtract)
S("broadcast_mul", lambda r: [u(r, 3, 4), u(r, 1, 4)], ref=np.multiply)
S("broadcast_div", lambda r: [u(r, 3, 4), pos(r, 1, 4)], ref=np.divide)
S("broadcast_mod", lambda r: [pos(r, 3, 4, lo=2.1, hi=2.9),
                              pos(r, 1, 4, lo=0.7, hi=0.95)],
  ref=np.mod)
S("broadcast_power", lambda r: [pos(r, 3, 4), u(r, 1, 4, lo=-2, hi=2)],
  ref=np.power)
S("broadcast_maximum", lambda r: [distinct(r, 3, 4), distinct(r, 3, 4)],
  ref=np.maximum)
S("broadcast_minimum", lambda r: [distinct(r, 3, 4), distinct(r, 3, 4)],
  ref=np.minimum)
S("broadcast_hypot", lambda r: [away0(r, 3, 4), away0(r, 1, 4)],
  ref=np.hypot)
S("arctan2", lambda r: [away0(r, 3, 4), away0(r, 3, 4)], ref=np.arctan2)
S("broadcast_equal", lambda r: [r.randint(0, 2, (3, 4)).astype("f"),
                                r.randint(0, 2, (3, 4)).astype("f")],
  ref=lambda a, b: (a == b).astype(np.float32))
S("broadcast_not_equal", lambda r: [r.randint(0, 2, (3, 4)).astype("f"),
                                    r.randint(0, 2, (3, 4)).astype("f")],
  ref=lambda a, b: (a != b).astype(np.float32))
S("broadcast_greater", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda a, b: (a > b).astype(np.float32))
S("broadcast_greater_equal", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda a, b: (a >= b).astype(np.float32))
S("broadcast_lesser", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda a, b: (a < b).astype(np.float32))
S("broadcast_lesser_equal", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda a, b: (a <= b).astype(np.float32))
S("broadcast_logical_and", lambda r: [r.randint(0, 2, (3, 4)).astype("f"),
                                      r.randint(0, 2, (3, 4)).astype("f")],
  ref=lambda a, b: np.logical_and(a, b).astype(np.float32))
S("broadcast_logical_or", lambda r: [r.randint(0, 2, (3, 4)).astype("f"),
                                     r.randint(0, 2, (3, 4)).astype("f")],
  ref=lambda a, b: np.logical_or(a, b).astype(np.float32))
S("broadcast_logical_xor", lambda r: [r.randint(0, 2, (3, 4)).astype("f"),
                                      r.randint(0, 2, (3, 4)).astype("f")],
  ref=lambda a, b: np.logical_xor(a, b).astype(np.float32))
S("where", lambda r: [r.randint(0, 2, (3, 4)).astype("f"),
                      u(r, 3, 4), u(r, 3, 4)],
  ref=lambda c, x, y: np.where(c != 0, x, y), grad_args=[1, 2])
S("add_n", lambda r: [u(r, 3, 4), u(r, 3, 4), u(r, 3, 4)],
  ref=lambda *xs: sum(xs))

# ---- reductions / ordering ------------------------------------------------

S("sum", lambda r: [u(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: x.sum(axis=axis))
S("mean", lambda r: [u(r, 3, 4)], params={"axis": 0, "keepdims": True},
  ref=lambda x, axis, keepdims: x.mean(axis=axis, keepdims=keepdims))
S("prod", lambda r: [pos(r, 3, 4, lo=0.5, hi=1.5)], params={"axis": 1},
  ref=lambda x, axis: x.prod(axis=axis))
S("max", lambda r: [distinct(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: x.max(axis=axis))
S("min", lambda r: [distinct(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: x.min(axis=axis))
S("nansum", lambda r: [u(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: np.nansum(x, axis=axis))  # finite inputs: FD needs them
S("nanprod", lambda r: [pos(r, 3, 4, lo=0.5, hi=1.5)], params={"axis": 1},
  ref=lambda x, axis: np.nanprod(x, axis=axis))
S("norm", lambda r: [u(r, 3, 4)], params={"ord": 2, "axis": 1},
  ref=lambda x, ord, axis: np.sqrt((x ** 2).sum(axis=axis)))
S("argmax", lambda r: [distinct(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: np.argmax(x, axis=axis).astype(np.float32))
S("argmin", lambda r: [distinct(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: np.argmin(x, axis=axis).astype(np.float32))
S("argmax_channel", lambda r: [distinct(r, 3, 4)],
  ref=lambda x: np.argmax(x, axis=1).astype(np.float32))
S("argsort", lambda r: [distinct(r, 2, 5)],
  ref=lambda x: np.argsort(x, axis=-1).astype(np.float32))
S("sort", lambda r: [distinct(r, 2, 5)], ref=lambda x: np.sort(x, axis=-1))
S("topk", lambda r: [distinct(r, 2, 5)], params={"k": 2, "ret_typ": "value"},
  ref=lambda x, k, ret_typ: np.sort(x, axis=-1)[..., ::-1][..., :k])

# ---- shape / index --------------------------------------------------------

S("cast", lambda r: [u(r, 3, 4)], params={"dtype": "float64"},
  ref=lambda x, dtype: x.astype(dtype))
S("concat", lambda r: [u(r, 2, 3), u(r, 2, 4)], params={"dim": 1},
  ref=lambda a, b, dim: np.concatenate([a, b], axis=dim))
S("flatten", lambda r: [u(r, 2, 3, 4)], ref=lambda x: x.reshape(2, 12))
S("reshape", lambda r: [u(r, 2, 6)], params={"shape": (3, 4)},
  ref=lambda x, shape: x.reshape(shape))
S("reshape_like", lambda r: [u(r, 2, 6), u(r, 3, 4)],
  ref=lambda x, y: x.reshape(y.shape), grad_args=[0])
S("expand_dims", lambda r: [u(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: np.expand_dims(x, axis))
S("squeeze", lambda r: [u(r, 3, 1, 4)], params={"axis": 1},
  ref=lambda x, axis: np.squeeze(x, axis))
S("transpose", lambda r: [u(r, 2, 3, 4)], params={"axes": (2, 0, 1)},
  ref=lambda x, axes: np.transpose(x, axes))
S("swapaxes", lambda r: [u(r, 2, 3, 4)], params={"dim1": 0, "dim2": 2},
  ref=lambda x, dim1, dim2: np.swapaxes(x, dim1, dim2))
S("tile", lambda r: [u(r, 2, 3)], params={"reps": (2, 2)},
  ref=lambda x, reps: np.tile(x, reps))
S("repeat", lambda r: [u(r, 2, 3)], params={"repeats": 2, "axis": 1},
  ref=lambda x, repeats, axis: np.repeat(x, repeats, axis))
S("reverse", lambda r: [u(r, 3, 4)], params={"axis": 1},
  ref=lambda x, axis: np.flip(x, axis))
S("slice", lambda r: [u(r, 4, 5)], params={"begin": (1, 0), "end": (3, 4)},
  ref=lambda x, begin, end: x[1:3, 0:4])
S("slice_axis", lambda r: [u(r, 4, 5)],
  params={"axis": 1, "begin": 1, "end": 4},
  ref=lambda x, axis, begin, end: x[:, 1:4])
S("slice_like", lambda r: [u(r, 4, 5), u(r, 2, 3)],
  ref=lambda x, y: x[:2, :3], grad_args=[0])
S("take", lambda r: [u(r, 4, 3), idx(r, 5, high=4)],
  ref=lambda a, i: a[i])
S("batch_take", lambda r: [u(r, 3, 4), idx(r, 3, high=4)],
  ref=lambda a, i: a[np.arange(3), i])
S("gather_nd", lambda r: [u(r, 4, 5), idx(r, 2, 3, high=4)],
  ref=lambda d, i: d[i[0], i[1]])
S("scatter_nd", lambda r: [u(r, 3), np.array([[0, 2, 0]], np.int32)],
  params={"shape": (4,)},
  ref=lambda d, i, shape: np.array(
      [d[0] + d[2], 0, d[1], 0], np.float32))
S("one_hot", lambda r: [idx(r, 5, high=4)],
  params={"depth": 4, "on_value": 2.0, "off_value": -1.0},
  ref=lambda i, depth, on_value, off_value:
      np.where(np.arange(depth)[None, :] == i[:, None],
               on_value, off_value).astype(np.float32))
S("pick", lambda r: [u(r, 3, 4), idx(r, 3, high=4).astype(np.float32)],
  params={"axis": 1},
  ref=lambda d, i, axis: d[np.arange(3), i.astype(np.int64)],
  grad_args=[0])
S("depth_to_space", lambda r: [u(r, 1, 8, 2, 2)], params={"block_size": 2},
  grad_args=[0],
  ref=lambda x, block_size: x.reshape(1, 2, 2, 2, 2, 2)
      .transpose(0, 3, 4, 1, 5, 2).reshape(1, 2, 4, 4))
S("space_to_depth", lambda r: [u(r, 1, 2, 4, 4)], params={"block_size": 2},
  grad_args=[0],
  ref=lambda x, block_size: x.reshape(1, 2, 2, 2, 2, 2)
      .transpose(0, 3, 5, 1, 2, 4).reshape(1, 8, 2, 2))
S("diag", lambda r: [u(r, 4, 4)], ref=lambda x: np.diag(x))
S("stack", lambda r: [u(r, 3, 4), u(r, 3, 4)], params={"axis": 1},
  ref=lambda a, b, axis: np.stack([a, b], axis=axis))
S("split", lambda r: [u(r, 2, 6)], params={"num_outputs": 3, "axis": 1},
  ref=lambda x, num_outputs, axis: tuple(np.split(x, num_outputs, axis)))
S("broadcast_axis", lambda r: [u(r, 3, 1)], params={"axis": 1, "size": 4},
  ref=lambda x, axis, size: np.broadcast_to(x, (3, 4)))
S("broadcast_like", lambda r: [u(r, 3, 1), u(r, 3, 4)],
  ref=lambda x, y: np.broadcast_to(x, y.shape), grad_args=[0])
S("broadcast_to", lambda r: [u(r, 3, 1)], params={"shape": (3, 4)},
  ref=lambda x, shape: np.broadcast_to(x, shape))
S("pad", lambda r: [u(r, 1, 2, 3, 3)],
  params={"mode": "constant",
          "pad_width": (0, 0, 0, 0, 1, 1, 1, 1), "constant_value": 0.5},
  ref=lambda x, mode, pad_width, constant_value:
      np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)),
             constant_values=constant_value))
S("ones_like", lambda r: [u(r, 3, 4)], ref=np.ones_like)
S("zeros_like", lambda r: [u(r, 3, 4)], ref=np.zeros_like)
S("_ones", lambda r: [], params={"shape": (3, 4)},
  ref=lambda shape: np.ones(shape, np.float32))
S("_zeros", lambda r: [], params={"shape": (3, 4)},
  ref=lambda shape: np.zeros(shape, np.float32))
S("shape_array", lambda r: [u(r, 3, 4)],
  ref=lambda x: np.array(x.shape, np.int64))
S("size_array", lambda r: [u(r, 3, 4)],
  ref=lambda x: np.array([x.size], np.int64))
S("_index", lambda r: [u(r, 4, 5)], params={"key": (slice(1, 3),)},
  ref=lambda x, key: x[key])
S("Crop", lambda r: [u(r, 1, 2, 6, 6)],
  params={"offset": (1, 1), "h_w": (4, 4)},
  ref=lambda x, offset, h_w: x[:, :, 1:5, 1:5])

# ---- linalg ---------------------------------------------------------------

S("dot", lambda r: [u(r, 3, 4), u(r, 4, 5)], ref=lambda a, b: a @ b)
S("batch_dot", lambda r: [u(r, 2, 3, 4), u(r, 2, 4, 5)],
  ref=lambda a, b: a @ b)
S("khatri_rao", lambda r: [u(r, 2, 4), u(r, 3, 4)],
  ref=lambda a, b: np.einsum("ik,jk->ijk", a, b).reshape(6, 4))
S("linalg_gemm", lambda r: [u(r, 3, 4), u(r, 4, 5), u(r, 3, 5)],
  params={"alpha": 2.0, "beta": 0.5},
  ref=lambda a, b, c, alpha, beta: alpha * (a @ b) + beta * c)
S("linalg_gemm2", lambda r: [u(r, 3, 4), u(r, 4, 5)], params={"alpha": 1.5},
  ref=lambda a, b, alpha: alpha * (a @ b))
S("linalg_syrk", lambda r: [u(r, 3, 4)], params={"alpha": 1.0},
  ref=lambda a, alpha: alpha * (a @ a.T))
S("linalg_trmm", lambda r: [lower_tri(r, 3), u(r, 3, 4)],
  ref=lambda a, b: np.tril(a) @ b)
S("linalg_trsm", lambda r: [lower_tri(r, 3), u(r, 3, 4)],
  ref=lambda a, b: np.linalg.solve(np.tril(a), b))
S("linalg_sumlogdiag", lambda r: [spd(r, 3)],
  ref=lambda a: np.log(np.diag(a)).sum().reshape(1,))
S("linalg_potrf", lambda r: [spd(r, 3)],
  ref=lambda a: np.linalg.cholesky(a),
  grad=False, reason="FD through a factorization is numerically unstable "
                     "(perturbation breaks SPD); forward vs np.linalg")
S("linalg_potri", lambda r: [np.linalg.cholesky(spd(r, 3))
                             .astype(np.float32)],
  ref=lambda l: np.linalg.inv(l @ l.T),  # potri: inv(A) from A's factor L
  grad=False, reason="see linalg_potrf", rtol=1e-3, atol=1e-4)
S("linalg_gelqf", lambda r: [u(r, 3, 5)],
  check=lambda outs, args: (
      np.testing.assert_allclose(outs[0] @ outs[1], args[0],
                                 rtol=1e-4, atol=1e-5),
      np.testing.assert_allclose(outs[1] @ outs[1].T, np.eye(3),
                                 rtol=1e-4, atol=1e-5)),
  grad=False, reason="LQ factors are sign/rotation-convention dependent; "
                     "checked by reconstruction (L@Q==A, Q orthonormal)")
S("linalg_syevd", lambda r: [spd(r, 3)],
  check=lambda outs, args: np.testing.assert_allclose(
      outs[0].T * outs[1] @ outs[0],
      args[0], rtol=1e-3, atol=1e-4),
  grad=False, reason="eigenvector sign conventions; checked by "
                     "reconstruction U^T diag(L) U == A")

# ---- NN core --------------------------------------------------------------

S("Activation", lambda r: [u(r, 3, 4)], params={"act_type": "tanh"},
  ref=lambda x, act_type: np.tanh(x))
S("FullyConnected", lambda r: [u(r, 2, 3), u(r, 4, 3), u(r, 4)],
  params={"num_hidden": 4},
  ref=lambda x, w, b, num_hidden: x @ w.T + b)
S("Convolution",
  lambda r: [u(r, 1, 2, 5, 5), u(r, 3, 2, 3, 3), u(r, 3)],
  params={"kernel": (3, 3), "num_filter": 3, "pad": (1, 1), "stride": (2, 2)},
  ref=lambda x, w, b, kernel, num_filter, pad, stride:
      np_conv2d(x, w, b, stride=stride, pad=pad),
  rtol=1e-3, atol=1e-4)
S("Deconvolution",
  lambda r: [u(r, 1, 2, 4, 4), u(r, 2, 3, 3, 3)],
  params={"kernel": (3, 3), "num_filter": 3, "stride": (2, 2), "pad": (1, 1)},
  ref=lambda x, w, kernel, num_filter, stride, pad:
      np_deconv2d(x, w, stride=stride, pad=pad),
  rtol=1e-3, atol=1e-4)
S("Pooling", lambda r: [distinct(r, 1, 2, 4, 4)],
  params={"kernel": (2, 2), "pool_type": "max", "stride": (2, 2)},
  ref=lambda x, kernel, pool_type, stride:
      np_pool2d(x, kernel, pool_type, stride))
S("BatchNorm",
  lambda r: [u(r, 2, 3, 4), pos(r, 3), u(r, 3), u(r, 3), pos(r, 3)],
  params={"fix_gamma": False, "use_global_stats": True, "eps": 1e-3},
  ref=lambda x, g, b, mm, mv, fix_gamma, use_global_stats, eps:
      (x - mm[None, :, None]) / np.sqrt(mv[None, :, None] + eps)
      * g[None, :, None] + b[None, :, None],
  grad_args=[0, 1, 2], rtol=1e-3, atol=1e-4)
S("LayerNorm", lambda r: [u(r, 3, 4), pos(r, 4), u(r, 4)],
  params={"eps": 1e-5},
  ref=lambda x, g, b, eps: (x - x.mean(-1, keepdims=True)) /
      np.sqrt(x.var(-1, keepdims=True) + eps) * g + b,
  rtol=1e-3, atol=1e-4)
S("InstanceNorm", lambda r: [u(r, 2, 3, 5), pos(r, 3), u(r, 3)],
  params={"eps": 1e-3},
  ref=lambda x, g, b, eps: (x - x.mean(-1, keepdims=True)) /
      np.sqrt(x.var(-1, keepdims=True) + eps) * g[None, :, None] +
      b[None, :, None],
  rtol=1e-3, atol=1e-4)
S("L2Normalization", lambda r: [u(r, 2, 3, 4)], params={"eps": 1e-10},
  ref=lambda x, eps: x / np.sqrt((x ** 2).sum(axis=(1, 2), keepdims=True)
                                 + eps),
  rtol=1e-3, atol=1e-4)
S("LRN", lambda r: [u(r, 1, 4, 3, 3)],
  params={"alpha": 1e-2, "beta": 0.75, "knorm": 2.0, "nsize": 3},
  ref=lambda x, alpha, beta, knorm, nsize: np_lrn(x, alpha, beta, knorm,
                                                  nsize),
  rtol=1e-3, atol=1e-4)
S("softmax", lambda r: [u(r, 3, 4)], params={"axis": -1},
  ref=lambda x, axis: np_softmax(x, axis))
S("log_softmax", lambda r: [u(r, 3, 4)], params={"axis": -1},
  ref=lambda x, axis: np.log(np_softmax(x, axis)))
S("SoftmaxActivation", lambda r: [u(r, 3, 4)],
  ref=lambda x: np_softmax(x, -1))
S("softmax_cross_entropy", lambda r: [u(r, 3, 4),
                                      idx(r, 3, high=4).astype(np.float32)],
  ref=lambda x, y: np.array(
      [-np.log(np_softmax(x, -1))[np.arange(3), y.astype(np.int64)].sum()],
      np.float32),
  grad_args=[0], rtol=1e-3, atol=1e-4)
S("Embedding", lambda r: [idx(r, 2, 3, high=5).astype(np.float32),
                          u(r, 5, 4)],
  params={"input_dim": 5, "output_dim": 4},
  ref=lambda i, w, input_dim, output_dim: w[i.astype(np.int64)],
  grad_args=[1])
S("Dropout", lambda r: [u(r, 3, 4)], params={"p": 0.5},
  ref=lambda x, p: x,  # eval mode = identity
  grad=False, reason="stochastic in train mode (per-call Bernoulli mask); "
                     "eval-mode identity is checked; masked-grad behavior "
                     "in tests/test_gluon dropout cases")
S("LeakyReLU", lambda r: [away0(r, 3, 4)],
  params={"act_type": "leaky", "slope": 0.25},
  ref=lambda x, act_type, slope: np.where(x > 0, x, slope * x))
S("BlockGrad", lambda r: [u(r, 3, 4)], ref=lambda x: x,
  grad=False, reason="gradient-blocking by design; zero-grad asserted in "
                     "test_blockgrad_blocks_gradient")
S("IdentityAttachKLSparseReg", lambda r: [u(r, 3, 4, lo=0.05, hi=0.95)],
  ref=lambda x: x,
  grad=False, reason="identity with attached KL regularizer gradient by "
                     "design; fwd identity checked")
S("UpSampling", lambda r: [u(r, 1, 2, 3, 3)],
  params={"scale": 2, "sample_type": "nearest"},
  ref=lambda x, scale, sample_type:
      x.repeat(scale, axis=2).repeat(scale, axis=3))
S("ctc_loss", lambda r: [u(r, 5, 2, 4), np.array([[1, 2], [3, 1]],
                                                 np.float32)],
  check=lambda outs, args: (
      # CTC loss is a positive scalar per batch element
      np.testing.assert_equal(outs[0].shape, (2,)),
      np.testing.assert_array_less(0.0, outs[0])),
  grad_args=[0], g_rtol=0.08, g_atol=1e-2)
S("MakeLoss", lambda r: [pos(r, 3)],
  ref=lambda x: x,
  grad=False, reason=NO_FD_CUSTOM_GRAD)
S("SoftmaxOutput", lambda r: [u(r, 3, 4), idx(r, 3, high=4).astype("f")],
  ref=lambda x, y: np_softmax(x, -1),
  grad=False, reason=NO_FD_CUSTOM_GRAD)
S("LinearRegressionOutput", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda x, y: x, grad=False, reason=NO_FD_CUSTOM_GRAD)
S("MAERegressionOutput", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda x, y: x, grad=False, reason=NO_FD_CUSTOM_GRAD)
S("LogisticRegressionOutput", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  ref=lambda x, y: 1 / (1 + np.exp(-x)), grad=False,
  reason=NO_FD_CUSTOM_GRAD)
S("SVMOutput", lambda r: [u(r, 3, 4), idx(r, 3, high=4).astype("f")],
  ref=lambda x, y: x, grad=False, reason=NO_FD_CUSTOM_GRAD)

# ---- vision / spatial -----------------------------------------------------

S("BilinearSampler", lambda r: [u(r, 1, 2, 5, 5), u(r, 1, 2, 4, 4, lo=-0.7,
                                                    hi=0.7)],
  g_rtol=0.08, g_atol=1e-2)
S("GridGenerator", lambda r: [np.array([[1.1, 0.1, 0.05,
                                         -0.1, 0.9, -0.05]], np.float32)],
  params={"transform_type": "affine", "target_shape": (4, 4)},
  g_rtol=0.08, g_atol=1e-2)
S("SpatialTransformer", lambda r: [u(r, 1, 2, 5, 5),
                                   np.array([[1.0, 0.1, 0.05,
                                              -0.1, 0.9, -0.05]],
                                            np.float32)],
  params={"target_shape": (4, 4)}, g_rtol=0.08, g_atol=1e-2)
S("ROIPooling", lambda r: [distinct(r, 1, 2, 6, 6),
                           np.array([[0, 0, 0, 3, 3],
                                     [0, 1, 1, 5, 5]], np.float32)],
  params={"pooled_size": (2, 2), "spatial_scale": 1.0},
  grad_args=[0], g_rtol=0.08, g_atol=1e-2)
S("Correlation", lambda r: [u(r, 1, 2, 5, 5), u(r, 1, 2, 5, 5)],
  params={"kernel_size": 1, "max_displacement": 1},
  g_rtol=0.08, g_atol=1e-2)
S("SequenceLast", lambda r: [u(r, 4, 3, 2),
                             np.array([2, 4, 3], np.float32)],
  params={"use_sequence_length": True},
  ref=lambda d, sl, use_sequence_length:
      d[sl.astype(np.int64) - 1, np.arange(3)],
  grad_args=[0])
S("SequenceMask", lambda r: [u(r, 4, 3, 2), np.array([2, 4, 3], np.float32)],
  params={"use_sequence_length": True, "value": -1.0},
  ref=lambda d, sl, use_sequence_length, value: np.where(
      np.arange(4)[:, None, None] < sl.astype(np.int64)[None, :, None],
      d, value),
  grad_args=[0])
S("SequenceReverse", lambda r: [u(r, 4, 3, 2),
                                np.array([2, 4, 3], np.float32)],
  params={"use_sequence_length": True},
  ref=lambda d, sl, use_sequence_length: _np_seq_reverse(d, sl),
  grad_args=[0])
S("_contrib_PSROIPooling",
  lambda r: [u(r, 1, 8, 6, 6), np.array([[0, 0, 0, 4, 4]], np.float32)],
  params={"output_dim": 2, "pooled_size": 2, "spatial_scale": 1.0},
  grad_args=[0], g_rtol=0.08, g_atol=1e-2)
S("_contrib_DeformableConvolution",
  lambda r: [u(r, 1, 2, 5, 5), u(r, 1, 18, 3, 3, lo=-0.1, hi=0.1),
             u(r, 3, 2, 3, 3)],
  params={"kernel": (3, 3), "num_filter": 3, "no_bias": True},
  grad_args=[0, 2], g_rtol=0.08, g_atol=1e-2)
S("_contrib_DeformablePSROIPooling",
  lambda r: [u(r, 1, 8, 6, 6), np.array([[0, 0, 0, 4, 4]], np.float32)],
  params={"output_dim": 2, "pooled_size": 2, "group_size": 2,
          "spatial_scale": 1.0, "no_trans": True},
  grad_args=[0], g_rtol=0.08, g_atol=1e-2)
S("_contrib_MultiBoxPrior", lambda r: [u(r, 1, 3, 4, 4)],
  params={"sizes": (0.5, 0.3), "ratios": (1.0, 2.0)},
  check=lambda outs, args: (
      np.testing.assert_equal(outs[0].shape[-1], 4),
      np.testing.assert_array_less(outs[0], 1.5)))
S("_contrib_MultiBoxTarget",
  lambda r: [nd.contrib.MultiBoxPrior(nd.array(u(r, 1, 3, 4, 4)),
                                      sizes=(0.5,)).asnumpy(),
             np.array([[[0, 0.1, 0.1, 0.6, 0.6]]], np.float32),
             u(r, 1, 2, 16)],
  check=lambda outs, args: np.testing.assert_equal(len(outs), 3))
S("_contrib_MultiBoxDetection",
  lambda r: [np_softmax(u(r, 1, 2, 16), 1),
             u(r, 1, 64, lo=-0.1, hi=0.1),
             np.clip(np.sort(u(r, 1, 16, 4, lo=0.1, hi=0.9), axis=-1), 0, 1)],
  check=lambda outs, args: np.testing.assert_equal(outs[0].shape[-1], 6))
S("_contrib_Proposal",
  lambda r: [np_softmax(u(r, 1, 24, 4, 4).reshape(1, 2, 12, 4, 4), 1)
             .reshape(1, 24, 4, 4),
             u(r, 1, 48, 4, 4, lo=-0.1, hi=0.1),
             np.array([[64, 64, 1]], np.float32)],
  params={"rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4,
          "rpn_min_size": 1},
  check=lambda outs, args: np.testing.assert_equal(outs[0].shape[-1], 5))
S("_contrib_MultiProposal",
  lambda r: [np_softmax(u(r, 2, 24, 4, 4).reshape(2, 2, 12, 4, 4), 1)
             .reshape(2, 24, 4, 4),
             u(r, 2, 48, 4, 4, lo=-0.1, hi=0.1),
             np.array([[64, 64, 1], [64, 64, 1]], np.float32)],
  params={"rpn_pre_nms_top_n": 12, "rpn_post_nms_top_n": 4,
          "rpn_min_size": 1},
  check=lambda outs, args: np.testing.assert_equal(outs[0].shape[-1], 5))
S("_contrib_box_iou",
  lambda r: [np.array([[0, 0, 2, 2], [1, 1, 3, 3]], np.float32),
             np.array([[0, 0, 2, 2]], np.float32)],
  ref=lambda a, b: np.array([[1.0], [1.0 / 7.0]], np.float32))
S("_contrib_box_nms",
  lambda r: [np.array([[[0, 0.9, 0, 0, 2, 2],
                        [0, 0.8, 0.1, 0.1, 2, 2],
                        [0, 0.7, 5, 5, 7, 7]]], np.float32)],
  params={"overlap_thresh": 0.5, "coord_start": 2, "score_index": 1,
          "id_index": 0},
  check=lambda outs, args: (
      # the heavily-overlapping second box is suppressed (score -> -1)
      np.testing.assert_equal(outs[0].shape, (1, 3, 6)),
      np.testing.assert_equal((outs[0][0, :, 1] < 0).sum(), 1)))
S("_contrib_bipartite_matching",
  lambda r: [np.array([[[0.9, 0.1], [0.2, 0.8]]], np.float32)],
  params={"threshold": 0.05},
  check=lambda outs, args: np.testing.assert_allclose(
      outs[0][0], np.array([0.0, 1.0], np.float32)))

# ---- random (statistical forward checks; no gradients) --------------------

_N = 4000


def _moments(outs, mean, std, tol=0.15):
    x = outs[0].astype(np.float64)
    assert abs(x.mean() - mean) < tol * max(1.0, abs(mean) + std), \
        (x.mean(), mean)
    assert abs(x.std() - std) < tol * max(1.0, std), (x.std(), std)


S("random_uniform", lambda r: [], params={"low": -1.0, "high": 3.0,
                                          "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 1.0, 4.0 / math.sqrt(12)))
S("random_normal", lambda r: [], params={"loc": 2.0, "scale": 3.0,
                                         "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 2.0, 3.0))
S("random_exponential", lambda r: [], params={"lam": 2.0, "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 0.5, 0.5))
S("random_gamma", lambda r: [], params={"alpha": 3.0, "beta": 2.0,
                                        "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 6.0, math.sqrt(12.0)))
S("random_poisson", lambda r: [], params={"lam": 4.0, "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 4.0, 2.0))
S("random_negative_binomial", lambda r: [],
  params={"k": 3, "p": 0.5, "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 3.0, math.sqrt(6.0), tol=0.2))
S("random_generalized_negative_binomial", lambda r: [],
  params={"mu": 2.0, "alpha": 0.5, "shape": (_N,)},
  check=lambda outs, args: _moments(outs, 2.0, math.sqrt(2 + 0.5 * 4),
                                    tol=0.2))
S("random_randint", lambda r: [], params={"low": 2, "high": 8,
                                          "shape": (_N,)},
  check=lambda outs, args: (
      np.testing.assert_array_less(outs[0], 8),
      np.testing.assert_array_less(1, outs[0] + 1e-6),
      _moments(outs, 4.5, math.sqrt(35 / 12.0), tol=0.2)))
S("sample_uniform", lambda r: [np.array([0.0, 10.0], np.float32),
                               np.array([1.0, 20.0], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: (
      _moments([outs[0][0]], 0.5, 1.0 / math.sqrt(12)),
      _moments([outs[0][1]], 15.0, 10.0 / math.sqrt(12))))
S("sample_normal", lambda r: [np.array([0.0, 5.0], np.float32),
                              np.array([1.0, 2.0], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: (
      _moments([outs[0][0]], 0.0, 1.0),
      _moments([outs[0][1]], 5.0, 2.0)))
S("sample_gamma", lambda r: [np.array([2.0], np.float32),
                             np.array([3.0], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: _moments([outs[0][0]], 6.0, math.sqrt(18.0)))
S("sample_exponential", lambda r: [np.array([2.0, 0.5], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: (
      _moments([outs[0][0]], 0.5, 0.5),
      _moments([outs[0][1]], 2.0, 2.0)))
S("sample_poisson", lambda r: [np.array([4.0, 9.0], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: (
      _moments([outs[0][0]], 4.0, 2.0),
      _moments([outs[0][1]], 9.0, 3.0)))
S("sample_negative_binomial", lambda r: [np.array([3.0], np.float32),
                                         np.array([0.5], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: _moments([outs[0][0]], 3.0, math.sqrt(6.0),
                                    tol=0.2))
S("sample_generalized_negative_binomial",
  lambda r: [np.array([2.0], np.float32), np.array([0.5], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: _moments([outs[0][0]], 2.0,
                                    math.sqrt(2 + 0.5 * 4), tol=0.2))
S("sample_multinomial", lambda r: [np.array([[0.7, 0.2, 0.1],
                                             [0.05, 0.05, 0.9]], np.float32)],
  params={"shape": (_N,)},
  check=lambda outs, args: (
      np.testing.assert_array_less(outs[0], 3),
      np.testing.assert_(abs((outs[0][0] == 0).mean() - 0.7) < 0.1),
      np.testing.assert_(abs((outs[0][1] == 2).mean() - 0.9) < 0.1)))
S("shuffle", lambda r: [np.arange(24, dtype=np.float32).reshape(24)],
  check=lambda outs, args: np.testing.assert_allclose(
      np.sort(outs[0]), np.sort(args[0])))

# ---- optimizer update ops -------------------------------------------------


def _clip(g, c):
    return np.clip(g, -c, c) if c >= 0 else g


def _ref_sgd(w, g, lr, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
             lazy_update=True):
    return w - lr * (_clip(g * rescale_grad, clip_gradient) + wd * w)


def _ref_sgd_mom(w, g, m, lr, momentum=0.0, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0, lazy_update=True):
    gg = _clip(g * rescale_grad, clip_gradient) + wd * w
    m2 = momentum * m - lr * gg
    return w + m2, m2


def _ref_adam(w, g, mean, var, lr, beta1=0.9, beta2=0.999, epsilon=1e-8,
              wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
              lazy_update=True):
    gg = _clip(g * rescale_grad, clip_gradient) + wd * w
    m2 = beta1 * mean + (1 - beta1) * gg
    v2 = beta2 * var + (1 - beta2) * gg ** 2
    return w - lr * m2 / (np.sqrt(v2) + epsilon), m2, v2


def _ref_rmsprop(w, g, n, lr, gamma1=0.95, epsilon=1e-8, wd=0.0,
                 rescale_grad=1.0, clip_gradient=-1.0, clip_weights=-1.0):
    gg = _clip(g * rescale_grad, clip_gradient) + wd * w
    n2 = (1 - gamma1) * gg ** 2 + gamma1 * n
    w2 = w - lr * gg / np.sqrt(n2 + epsilon)
    return (np.clip(w2, -clip_weights, clip_weights)
            if clip_weights > 0 else w2), n2


def _ref_adagrad(w, g, h, lr=None, epsilon=1e-7, wd=0.0, rescale_grad=1.0,
                 clip_gradient=-1.0):
    gg = _clip(g * rescale_grad, clip_gradient)
    h2 = h + gg ** 2
    return w - lr * (gg / np.sqrt(h2 + epsilon) + wd * w), h2


OPTIM_NO_GRAD = dict(grad=False,
                     reason="in-place optimizer update rule, not a "
                            "differentiable graph op (reference runs these "
                            "with kNullOp grads)")

S("sgd_update", lambda r: [u(r, 3, 4), u(r, 3, 4)],
  params={"lr": 0.1, "wd": 0.01}, ref=_ref_sgd, **OPTIM_NO_GRAD)
S("sgd_mom_update", lambda r: [u(r, 3, 4), u(r, 3, 4), u(r, 3, 4)],
  params={"lr": 0.1, "momentum": 0.9, "wd": 0.01}, ref=_ref_sgd_mom,
  **OPTIM_NO_GRAD)
S("mp_sgd_update",
  lambda r: [u(r, 3, 4).astype(np.float16), u(r, 3, 4).astype(np.float16),
             u(r, 3, 4)],
  params={"lr": 0.1, "wd": 0.01},
  ref=lambda w, g, w32, lr, wd: (
      _ref_sgd(w32, g.astype(np.float32), lr, wd).astype(np.float16),
      _ref_sgd(w32, g.astype(np.float32), lr, wd)),
  rtol=2e-3, atol=2e-3, **OPTIM_NO_GRAD)
S("mp_sgd_mom_update",
  lambda r: [u(r, 3, 4).astype(np.float16), u(r, 3, 4).astype(np.float16),
             u(r, 3, 4), u(r, 3, 4)],
  params={"lr": 0.1, "momentum": 0.9},
  ref=lambda w, g, m, w32, lr, momentum: (
      lambda wm: (wm[0].astype(np.float16), wm[1], wm[0]))(
      _ref_sgd_mom(w32, g.astype(np.float32), m, lr, momentum)),
  rtol=2e-3, atol=2e-3, **OPTIM_NO_GRAD)
S("adam_update",
  lambda r: [u(r, 3, 4), u(r, 3, 4), u(r, 3, 4), pos(r, 3, 4)],
  params={"lr": 0.01, "wd": 0.01}, ref=_ref_adam, **OPTIM_NO_GRAD)
S("rmsprop_update", lambda r: [u(r, 3, 4), u(r, 3, 4), pos(r, 3, 4)],
  params={"lr": 0.01}, ref=_ref_rmsprop, **OPTIM_NO_GRAD)
S("rmspropalex_update",
  lambda r: [u(r, 3, 4), u(r, 3, 4), pos(r, 3, 4, lo=1.0, hi=2.0),
             u(r, 3, 4, lo=-0.3, hi=0.3), u(r, 3, 4)],
  params={"lr": 0.01},
  ref=lambda w, g, n, gs, d, lr, gamma1=0.95, gamma2=0.9, epsilon=1e-8:
      (lambda n2, g2: (lambda d2: (w + d2, n2, g2, d2))(
          gamma2 * d - lr * g / np.sqrt(n2 - g2 ** 2 + epsilon)))(
      (1 - 0.95) * g ** 2 + 0.95 * n, (1 - 0.95) * g + 0.95 * gs),
  **OPTIM_NO_GRAD)
S("ftml_update",
  lambda r: [u(r, 3, 4), u(r, 3, 4), pos(r, 3, 4), pos(r, 3, 4),
             u(r, 3, 4)],
  params={"lr": 0.01, "t": 2},
  ref=lambda w, g, d, v, z, lr, t, beta1=0.6, beta2=0.999, epsilon=1e-8:
      (lambda v2: (lambda dt: (lambda z2: (-z2 / dt, dt, v2, z2))(
          beta1 * z + (1 - beta1) * g - (dt - beta1 * d) * w))(
          (1 - beta1 ** t) / lr * (np.sqrt(v2 / (1 - beta2 ** t)) + epsilon)))(
      beta2 * v + (1 - beta2) * g ** 2),
  **OPTIM_NO_GRAD)
S("signsgd_update", lambda r: [u(r, 3, 4), away0(r, 3, 4)],
  params={"lr": 0.1},
  ref=lambda w, g, lr: w - lr * np.sign(g), **OPTIM_NO_GRAD)
S("signum_update", lambda r: [u(r, 3, 4), away0(r, 3, 4), u(r, 3, 4)],
  params={"lr": 0.1, "momentum": 0.9},
  ref=lambda w, g, m, lr, momentum: (
      lambda m2: (w + lr * np.sign(m2), m2))(
      momentum * m - (1 - momentum) * g),
  **OPTIM_NO_GRAD)
S("ftrl_update",
  lambda r: [u(r, 3, 4), u(r, 3, 4), u(r, 3, 4), pos(r, 3, 4)],
  params={"lr": 0.1},
  ref=lambda w, g, z, n, lr, lamda1=0.01, beta=1.0, wd=0.0:
      (lambda n2: (lambda z2: (
          np.where(np.abs(z2) > lamda1,
                   -(z2 - np.sign(z2) * lamda1) /
                   ((beta + np.sqrt(n2)) / lr + wd),
                   np.zeros_like(w)), z2, n2))(
          z + g - (np.sqrt(n2) - np.sqrt(n)) / lr * w))(n + g ** 2),
  **OPTIM_NO_GRAD)
S("adagrad_update", lambda r: [u(r, 3, 4), u(r, 3, 4), pos(r, 3, 4)],
  params={"lr": 0.1}, ref=lambda w, g, h, lr: _ref_adagrad(w, g, h, lr),
  **OPTIM_NO_GRAD)

# ---- contrib / misc -------------------------------------------------------

S("_contrib_quadratic", lambda r: [u(r, 3, 4)],
  params={"a": 2.0, "b": -1.0, "c": 0.5},
  ref=lambda x, a, b, c: a * x ** 2 + b * x + c)
S("_contrib_quantize",
  lambda r: [u(r, 3, 4, lo=-0.9, hi=0.9), np.array([-1.0], np.float32),
             np.array([1.0], np.float32)],
  params={"out_type": "uint8"},
  ref=lambda d, lo, hi, out_type: (
      np.clip(np.round((d - lo[0]) * 255.0 / (hi[0] - lo[0])), 0,
              255).astype(np.uint8),
      lo, hi))
S("_contrib_dequantize",
  lambda r: [r.randint(0, 256, (3, 4)).astype(np.uint8),
             np.array([-1.0], np.float32), np.array([1.0], np.float32)],
  ref=lambda q, lo, hi: (q.astype(np.float32) * (hi[0] - lo[0]) / 255.0
                         + lo[0]),
  rtol=1e-3, atol=1e-3)
S("_contrib_fft", lambda r: [u(r, 2, 8)],
  ref=lambda x: np.stack([np.fft.fft(x).real, np.fft.fft(x).imag],
                         axis=-1).reshape(2, 16).astype(np.float32),
  rtol=1e-3, atol=1e-4)
S("_contrib_ifft", lambda r: [u(r, 2, 16)],
  ref=lambda x: (np.fft.ifft(
      x.reshape(2, 8, 2)[..., 0] + 1j * x.reshape(2, 8, 2)[..., 1]) *
      8).real.astype(np.float32),
  rtol=1e-3, atol=1e-4)
S("_contrib_count_sketch",
  lambda r: [u(r, 2, 5), np.array([0, 2, 1, 0, 3], np.float32),
             np.array([1, -1, 1, -1, 1], np.float32)],
  params={"out_dim": 4},
  ref=lambda d, h, s, out_dim: _np_count_sketch(d, h, s, out_dim),
  grad_args=[0])
S("_image_to_tensor", lambda r: [r.randint(0, 256, (5, 4, 3))
                                 .astype(np.uint8)],
  ref=lambda x: (x.astype(np.float32) / 255.0).transpose(2, 0, 1))
S("_image_normalize", lambda r: [u(r, 3, 4, 5, lo=0, hi=1)],
  params={"mean": (0.5, 0.4, 0.3), "std": (0.2, 0.25, 0.3)},
  ref=lambda x, mean, std: (x - np.array(mean).reshape(3, 1, 1)) /
      np.array(std).reshape(3, 1, 1))


def _np_count_sketch(d, h, s, out_dim):
    out = np.zeros((d.shape[0], out_dim), np.float32)
    for j in range(d.shape[1]):
        out[:, int(h[j])] += s[j] * d[:, j]
    return out


def _np_seq_reverse(d, sl):
    out = d.copy()
    for b in range(d.shape[1]):
        n = int(sl[b])
        out[:n, b] = d[:n, b][::-1]
    return out


# --------------------------------------------------------------------------
# the tests
# --------------------------------------------------------------------------


def test_registry_fully_covered():
    """The SPEC/SKIP partition is total over canonical registry ops."""
    names = set(_canonical_ops())
    covered = set(SPECS) | set(SKIP)
    missing = sorted(names - covered)
    stale = sorted(covered - names)
    assert not missing, "ops with neither spec nor skip reason: %s" % missing
    assert not stale, "specs for unregistered ops: %s" % stale
    overlap = sorted(set(SPECS) & set(SKIP))
    assert not overlap, "ops both specced and skipped: %s" % overlap


@pytest.mark.parametrize("name", sorted(SPECS))
def test_forward(name):
    spec = SPECS[name]
    r = np.random.RandomState(_seed(name))
    args = spec.args(r)
    outs = _run(name, args, spec.params)
    for o in outs:
        if np.asarray(o).dtype.kind == "f":
            assert np.all(np.isfinite(o)), "%s produced non-finite output" % name
    if spec.ref is not None:
        exp = spec.ref(*[a for a in args], **spec.params)
        exp = list(exp) if isinstance(exp, (tuple, list)) else [exp]
        assert len(outs) >= len(exp), \
            "%s: %d outputs < %d expected" % (name, len(outs), len(exp))
        for i, (o, e) in enumerate(zip(outs, exp)):
            np.testing.assert_allclose(
                np.asarray(o, np.float64), np.asarray(e, np.float64),
                rtol=spec.rtol, atol=spec.atol,
                err_msg="%s output %d" % (name, i))
    if spec.check is not None:
        spec.check(outs, args)


@pytest.mark.parametrize("name", sorted(SPECS))
def test_gradient(name):
    spec = SPECS[name]
    op = _canonical_ops()[name]
    if not op.differentiable:
        pytest.skip("op flagged non-differentiable")
    if spec.grad is False:
        assert spec.reason, "%s: grad disabled without a reason" % name
        pytest.skip(spec.reason)
    r = np.random.RandomState(_seed(name) + 1)
    args = spec.args(r)
    grad_idx = (spec.grad_args if spec.grad_args is not None
                else _float_arg_indices(args))
    if not grad_idx:
        pytest.skip("no float array inputs to differentiate")
    params = spec.params

    nd_args = [_to_nd(a) for a in args]
    for i in grad_idx:
        nd_args[i].attach_grad()
    with ag.record():
        out = getattr(nd, name)(*nd_args, **params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    fouts = [o for o in outs if o.asnumpy().dtype.kind == "f"]
    assert fouts, "%s has no float outputs to project" % name
    projs = [r.normal(0, 1, o.shape).astype(np.float32) for o in fouts]
    ag.backward(fouts, head_grads=[nd.array(p) for p in projs])
    analytic = {i: nd_args[i].grad.asnumpy().astype(np.float64)
                for i in grad_idx}

    def f(mod):
        nds = [_to_nd(a) for a in mod]
        with ag.record():  # train-mode semantics, matching the analytic pass
            o = getattr(nd, name)(*nds, **params)
        os_ = o if isinstance(o, (list, tuple)) else [o]
        fs = [x for x in os_ if x.asnumpy().dtype.kind == "f"]
        return sum(float((x.asnumpy().astype(np.float64) * p).sum())
                   for x, p in zip(fs, projs))

    for i in grad_idx:
        base = args[i].astype(np.float64)
        flat_n = base.size
        if flat_n <= GRAD_COORD_CAP:
            coords = range(flat_n)
        else:
            coords = r.choice(flat_n, GRAD_COORD_CAP, replace=False)
        ana_flat = analytic[i].reshape(-1)
        for j in coords:
            pert = base.reshape(-1).copy()
            pert[j] += FD_EPS
            args_p = list(args)
            args_p[i] = pert.reshape(base.shape).astype(np.float32)
            fp = f(args_p)
            pert[j] -= 2 * FD_EPS
            args_m = list(args)
            args_m[i] = pert.reshape(base.shape).astype(np.float32)
            fm = f(args_m)
            gnum = (fp - fm) / (2 * FD_EPS)
            gana = ana_flat[j]
            assert abs(gana - gnum) <= spec.g_atol + spec.g_rtol * max(
                abs(gnum), abs(gana)), (
                "%s: d/d(arg%d)[%d] analytic %g vs numeric %g"
                % (name, i, j, gana, gnum))


# --------------------------------------------------------------------------
# explicit semantics tests backing SKIP/no-FD reasons above
# --------------------------------------------------------------------------


def test_blockgrad_blocks_gradient():
    x = nd.array(np.ones((3,), np.float32))
    x.attach_grad()
    with ag.record():
        y = (nd.BlockGrad(x) * nd.array(np.full((3,), 2.0, np.float32))
             + x).sum()
    y.backward()
    # only the direct `+ x` path contributes
    np.testing.assert_allclose(x.grad.asnumpy(), np.ones(3))


def test_output_head_gradients():
    """The custom_vjp loss heads produce the reference's training grads
    (src/operator/softmax_output-inl.h, regression_output-inl.h)."""
    r = np.random.RandomState(0)
    x = r.uniform(-1, 1, (3, 4)).astype(np.float32)
    lab = np.array([1, 3, 0], np.float32)

    xd = nd.array(x)
    xd.attach_grad()
    with ag.record():
        out = nd.SoftmaxOutput(xd, nd.array(lab))
    out.backward()
    sm = np_softmax(x, -1)
    onehot = np.eye(4, dtype=np.float32)[lab.astype(np.int64)]
    np.testing.assert_allclose(xd.grad.asnumpy(), sm - onehot,
                               rtol=1e-4, atol=1e-5)

    y = r.uniform(-1, 1, (3, 4)).astype(np.float32)
    xd = nd.array(x)
    xd.attach_grad()
    with ag.record():
        out = nd.LinearRegressionOutput(xd, nd.array(y))
    out.backward()
    np.testing.assert_allclose(xd.grad.asnumpy(), (x - y) / 4.0,
                               rtol=1e-4, atol=1e-5)


# --------------------------------------------------------------------------
# symbolic tier: replay every spec through Symbol + the jitted Executor
# --------------------------------------------------------------------------

# ops whose generic symbolic replay cannot work, with reasons
SYM_SKIP = {
    "_index": "getitem key params contain slice objects, which the "
              "symbol json/param path treats as internal (covered via "
              "NDArray.__getitem__ under autograd in test_autograd)",
    "_ones": "no array inputs: creation ops are frontend functions "
             "symbolically (sym.zeros/ones build constant nodes)",
    "_zeros": "see _ones",
    "BlockGrad": "covered by test_blockgrad_blocks_gradient",
}


def _sym_differs(name):
    """Ops where eval-mode executor output legitimately differs from the
    eager call (training-mode stochasticity is off in the executor)."""
    op = _canonical_ops()[name]
    return op.stateful


@pytest.mark.parametrize("name", sorted(SPECS))
def test_symbolic_forward(name):
    """Each spec replayed through sym.<op> + simple_bind matches the eager
    result — covering the symbolic arg mapping and the jitted Executor
    for the whole registry (reference test_operator.py exercises ops
    through simple_bind the same way)."""
    import mxtpu as mx
    import mxtpu.symbol as sym

    if name in SYM_SKIP:
        pytest.skip(SYM_SKIP[name])
    if _sym_differs(name):
        pytest.skip("stateful op: executor draws its own PRNG key")
    spec = SPECS[name]
    r = np.random.RandomState(_seed(name))
    args = spec.args(r)
    if not any(isinstance(a, np.ndarray) for a in args):
        pytest.skip("no array inputs")
    eager = _run(name, args, spec.params)

    op = _canonical_ops()[name]
    aux_pos = set(op.aux_update.keys())
    var_names = ["in%d" % i for i in range(len(args))]
    sym_fn = getattr(sym, name)
    sym_args = [sym.var(n) for n in var_names]
    out = sym_fn(*sym_args, **spec.params)
    arg_feed, aux_feed = {}, {}
    for i, (vn, a) in enumerate(zip(var_names, args)):
        (aux_feed if i in aux_pos else arg_feed)[vn] = nd.array(a)
    # auto-created inputs (implicit bias/label vars): zeros of the
    # inferred shape, matching their eager absence
    missing = [n_ for n_ in out.list_arguments() if n_ not in arg_feed]
    if missing:
        shapes, _, _ = out.infer_shape_partial(
            **{k: v.shape for k, v in arg_feed.items()})
        for n_, s in zip(out.list_arguments(), shapes):
            if n_ in missing:
                assert s is not None, "cannot infer %s for %s" % (n_, name)
                arg_feed[n_] = nd.zeros(s)
    ex = out.bind(mx.cpu(), arg_feed, aux_states=aux_feed or None)
    outs = [o.asnumpy() for o in ex.forward(is_train=False)]
    for i, (e, s) in enumerate(zip(eager, outs)):
        if np.asarray(e).dtype.kind == "f":
            np.testing.assert_allclose(
                np.asarray(s, np.float64), np.asarray(e, np.float64),
                rtol=1e-4, atol=1e-5,
                err_msg="%s symbolic output %d" % (name, i))
        else:
            np.testing.assert_array_equal(s, e)


@pytest.mark.parametrize("name", sorted(SPECS))
def test_symbolic_gradient(name):
    """The executor's fused forward+vjp produces the same input gradients
    as the eager tape for every differentiable op — locking the two
    autograd paths (per-op jax.vjp on the tape vs whole-graph jax.vjp in
    Executor._fwd_bwd) together."""
    import mxtpu as mx
    import mxtpu.symbol as sym

    if name in SYM_SKIP:
        pytest.skip(SYM_SKIP[name])
    op = _canonical_ops()[name]
    if not op.differentiable or _sym_differs(name):
        pytest.skip("non-differentiable or stateful")
    spec = SPECS[name]
    if spec.grad is False and spec.reason != NO_FD_CUSTOM_GRAD:
        # custom_vjp heads still compare eager-vs-symbolic (same vjp);
        # everything else skipped for grad has structural reasons
        pytest.skip(spec.reason)
    r = np.random.RandomState(_seed(name) + 7)
    args = spec.args(r)
    grad_idx = (spec.grad_args if spec.grad_args is not None
                else _float_arg_indices(args))
    if not grad_idx:
        pytest.skip("no float array inputs")
    params = spec.params

    # eager tape gradients
    nd_args = [_to_nd(a) for a in args]
    for i in grad_idx:
        nd_args[i].attach_grad()
    with ag.record():
        out = getattr(nd, name)(*nd_args, **params)
    outs = out if isinstance(out, (list, tuple)) else [out]
    fmask = [o.asnumpy().dtype.kind == "f" for o in outs]
    projs = [r.normal(0, 1, o.shape).astype(np.float32) if f else None
             for o, f in zip(outs, fmask)]
    ag.backward([o for o, f in zip(outs, fmask) if f],
                head_grads=[nd.array(p) for p in projs if p is not None])
    eager_grads = {i: nd_args[i].grad.asnumpy() for i in grad_idx}

    # symbolic executor gradients
    op_def = _canonical_ops()[name]
    aux_pos = set(op_def.aux_update.keys())
    var_names = ["in%d" % i for i in range(len(args))]
    s_out = getattr(sym, name)(*[sym.var(n) for n in var_names], **params)
    arg_feed, aux_feed = {}, {}
    for i, (vn, a) in enumerate(zip(var_names, args)):
        (aux_feed if i in aux_pos else arg_feed)[vn] = nd.array(a)
    missing = [n_ for n_ in s_out.list_arguments() if n_ not in arg_feed]
    if missing:
        shapes, _, _ = s_out.infer_shape_partial(
            **{k: v.shape for k, v in arg_feed.items()})
        for n_, sh in zip(s_out.list_arguments(), shapes):
            if n_ in missing:
                arg_feed[n_] = nd.zeros(sh)
    grad_names = {"in%d" % i for i in grad_idx}
    req = {n_: ("write" if n_ in grad_names else "null")
           for n_ in s_out.list_arguments()}
    ex = s_out.simple_bind(ctx=mx.cpu(), grad_req=req,
                           **{k: v.shape for k, v in arg_feed.items()})
    for k, v in arg_feed.items():
        ex.arg_dict[k]._assign_value(v)
    for k, v in aux_feed.items():
        ex.aux_dict[k]._assign_value(v)
    ex.forward(is_train=True)
    ex.backward([nd.array(p) if p is not None else
                 nd.zeros(o.shape)
                 for p, o in zip(projs, ex.outputs)])
    for i in grad_idx:
        np.testing.assert_allclose(
            ex.grad_dict["in%d" % i].asnumpy(), eager_grads[i],
            rtol=1e-4, atol=1e-5,
            err_msg="%s d/d(arg%d): executor vs tape" % (name, i))
