"""One partition spec, three layouts (ISSUE 10, mxtpu/partition.py):
the SAME PartitionRules object must drive ShardedTrainer mesh
placement, dist_async KVStore key->server assignment, and the
CheckpointManager file layout — pinned by the layout-agreement test."""
import os

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.checkpoint import CheckpointManager
from mxtpu.kvstore_async import ParameterServer
from mxtpu.parallel import MeshContext, PartitionSpec as P
from mxtpu.partition import PartitionRules

RULES = [
    (r".*fc1_.*", P("data", None)),
    (r".*fc2_.*", P()),
]
NAMES = ["net_fc1_weight", "net_fc1_bias", "net_fc2_weight",
         "net_fc2_bias", "embedding_table"]


def test_group_and_shard_assignment():
    rules = PartitionRules(RULES)
    # first match wins; groups are the matched rule pattern
    assert rules.group_for("net_fc1_weight") == r".*fc1_.*"
    assert rules.group_for("net_fc1_weight") == \
        rules.group_for("net_fc1_bias")
    assert rules.group_for("embedding_table") is None
    # part subkeys route through their base key
    assert rules.group_for("net_fc1_weight\x000") == r".*fc1_.*"
    # one group -> one shard, deterministic in num_shards
    for n in (1, 2, 3, 7):
        s_w = rules.shard_for("net_fc1_weight", n)
        assert s_w == rules.shard_for("net_fc1_bias", n)
        assert s_w == rules.shard_for("net_fc1_weight\x003", n)
        assert 0 <= s_w < n
    assert rules.shard_for("embedding_table", 4) is None


def test_layout_groups():
    rules = PartitionRules(RULES)
    layout = rules.layout(NAMES)
    tag1 = rules.group_tag(r".*fc1_.*")
    tag2 = rules.group_tag(r".*fc2_.*")
    assert layout[tag1] == ["net_fc1_weight", "net_fc1_bias"]
    assert layout[tag2] == ["net_fc2_weight", "net_fc2_bias"]
    assert layout[""] == ["embedding_table"]     # unmatched remainder


def test_layout_agreement(monkeypatch, tmp_path):
    """THE contract: two names in one rule group agree on (a) the mesh
    PartitionSpec the trainer places them with, (b) the kvstore server
    their keys land on, and (c) the checkpoint blob they restore from
    — all read off the SAME PartitionRules object."""
    rules = PartitionRules(RULES)
    mc = MeshContext(data=2)

    # (a) mesh placement: the ShardingRules half (what ShardedTrainer's
    # _place consumes via rules.sharding_for)
    s_w = rules.sharding_for(mc, "net_fc1_weight", (32, 16))
    s_b = rules.sharding_for(mc, "net_fc1_bias", (32,))
    assert s_w.spec == P("data", None)
    assert s_b.spec == P("data")

    # (b) kvstore key shards: two servers, rules installed
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    monkeypatch.setenv("MXTPU_PS_ADDRS", s1.address + "," + s2.address)
    monkeypatch.setenv("MXTPU_PROC_ID", "0")
    monkeypatch.setenv("MXTPU_NUM_PROCS", "1")
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    kv = mx.kv.create("dist_async")
    try:
        kv.set_partition_rules(rules)
        for name in NAMES:
            kv.init(name, mx.nd.ones((4,)))
            kv.push(name, mx.nd.ones((4,)))
        servers = [s1, s2]
        placed = {name: next(i for i, srv in enumerate(servers)
                             if name in srv._clock)
                  for name in NAMES}
        # rule groups co-locate, exactly where shard_for says
        assert placed["net_fc1_weight"] == placed["net_fc1_bias"] \
            == rules.shard_for("net_fc1_weight", 2)
        assert placed["net_fc2_weight"] == placed["net_fc2_bias"] \
            == rules.shard_for("net_fc2_weight", 2)
        # unmatched keys keep the legacy per-key crc32 spread
        import zlib
        assert placed["embedding_table"] == \
            zlib.crc32(b"embedding_table") % 2
        # pulls still roundtrip through the rule-routed shards
        out = mx.nd.zeros((4,))
        kv.pull("net_fc1_weight", out=out)
        np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(4))
    finally:
        kv.close()
        s1.stop()
        s2.stop()

    # (c) checkpoint layout: one blob per rule group
    ckpt = CheckpointManager(str(tmp_path), async_save=False,
                             use_orbax=False)
    params = {n: mx.nd.ones((4,)) * (i + 1)
              for i, n in enumerate(NAMES)}
    ckpt.save(0, params, layout=rules)
    step_dir = os.path.join(str(tmp_path), "step_0")
    blobs = sorted(f for f in os.listdir(step_dir)
                   if f.startswith("params") and f.endswith(".npz"))
    tag1 = rules.group_tag(r".*fc1_.*")
    tag2 = rules.group_tag(r".*fc2_.*")
    assert set(blobs) == {"params.npz", "params-%s.npz" % tag1,
                          "params-%s.npz" % tag2}
    with np.load(os.path.join(step_dir, "params-%s.npz" % tag1)) as z:
        assert set(z.files) == {"net_fc1_weight", "net_fc1_bias"}
    # restore is layout-agnostic and verifies the merged CRC tags
    tree = ckpt.restore(0)
    assert set(tree["params"]) == set(NAMES)
    for i, n in enumerate(NAMES):
        np.testing.assert_allclose(tree["params"][n], (i + 1) * np.ones(4))


def test_sharded_trainer_accepts_partition_rules():
    """PartitionRules drops into ShardedTrainer's rules= unchanged:
    after placement every parameter carries the sharding the shared
    spec names (the trainer side of the layout agreement)."""
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import ShardedTrainer

    net = nn.HybridSequential(prefix="lay_")
    with net.name_scope():
        net.add(nn.Dense(16, activation="relu", prefix="fc1_"))
        net.add(nn.Dense(4, prefix="fc2_"))
    net.initialize(mx.initializer.Xavier())
    rules = PartitionRules(RULES)
    mesh = MeshContext(data=2)
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1}, mesh=mesh,
                        rules=rules)
    x = mx.nd.array(np.random.RandomState(0).rand(8, 12))
    yl = mx.nd.array(np.zeros(8))
    st.step(x, yl)
    for p, sh in zip(st._params, st._shardings):
        assert sh == rules.sharding_for(mesh, p.name, p.shape), p.name
