"""Symbolic storage-type inference (the reference's InferStorageType pass,
src/executor/infer_graph_attr_pass.cc + exec_pass.h:151-179): stype
declarations on variables propagate through per-op rules with dense
fallback, and simple_bind materializes sparse-typed args and grads."""
import numpy as np

import mxtpu as mx
import mxtpu.ndarray as nd
import mxtpu.symbol as sym
from mxtpu.ndarray.sparse import CSRNDArray, RowSparseNDArray


def test_var_stype_declared():
    x = sym.var("x", stype="csr")
    arg_st, out_st, _ = x.infer_storage_type()
    assert arg_st == ["csr"]
    assert out_st == ["csr"]


def test_dot_rules():
    data = sym.var("data", stype="csr")
    w = sym.var("w")
    # dot(csr, dense) -> dense
    out = sym.dot(data, w)
    _, out_st, _ = out.infer_storage_type()
    assert out_st == ["default"]
    # dot(csr.T, dense) -> row_sparse (reference dot-inl.h)
    outT = sym.dot(data, w, transpose_a=True)
    _, out_stT, _ = outT.infer_storage_type()
    assert out_stT == ["row_sparse"]


def test_elemwise_and_fallback():
    a = sym.var("a", stype="row_sparse")
    b = sym.var("b", stype="row_sparse")
    _, out_st, _ = (a + b).infer_storage_type()
    assert out_st == ["row_sparse"]
    # zero-preserving unary keeps stype
    _, out_st, _ = sym.negative(a).infer_storage_type()
    assert out_st == ["row_sparse"]
    # non-zero-preserving op falls back to dense
    _, out_st, _ = sym.exp(a).infer_storage_type()
    assert out_st == ["default"]
    # mixing with dense falls back for addition
    c = sym.var("c")
    _, out_st, _ = (a + c).infer_storage_type()
    assert out_st == ["default"]
    # but multiplication by rsp preserves the zero structure
    _, out_st, _ = sym.broadcast_mul(a, c).infer_storage_type()
    assert out_st == ["row_sparse"]


def test_infer_storage_type_overrides():
    x = sym.var("x")
    y = sym.var("y")
    out = sym.dot(x, y, transpose_a=True)
    # positional + keyword overrides, reference infer_storage_type API
    arg_st, out_st, _ = out.infer_storage_type("csr", None)
    assert arg_st == ["csr", "default"]
    assert out_st == ["row_sparse"]
    arg_st, out_st, _ = out.infer_storage_type(x="csr")
    assert out_st == ["row_sparse"]


def test_simple_bind_materializes_sparse():
    data = sym.var("data", stype="csr")
    w = sym.var("w", stype="row_sparse")
    out = sym.dot(data, w)
    ex = out.simple_bind(ctx=mx.cpu(), grad_req={"w": "write"},
                         data=(4, 6), w=(6, 3))
    assert isinstance(ex.arg_dict["data"], CSRNDArray)
    assert isinstance(ex.arg_dict["w"], RowSparseNDArray)
    assert isinstance(ex.grad_dict["w"], RowSparseNDArray)

    # feed a CSR batch; metadata travels into the bound slot
    dense = np.zeros((4, 6), np.float32)
    dense[0, 1] = 2.0
    dense[2, 4] = 3.0
    batch = nd.array(dense).tostype("csr")
    wv = np.random.RandomState(0).randn(6, 3).astype(np.float32)
    ex.arg_dict["w"][:] = wv
    outs = ex.forward(is_train=True, data=batch)
    np.testing.assert_allclose(outs[0].asnumpy(), dense @ wv, rtol=1e-5)
    assert ex.arg_dict["data"].indices.size == 2  # metadata propagated

    ex.backward(nd.array(np.ones((4, 3), np.float32)))
    g = ex.grad_dict["w"]
    assert isinstance(g, RowSparseNDArray)
    np.testing.assert_allclose(g.asnumpy(), dense.T @ np.ones((4, 3)),
                               rtol=1e-5)
    # lazily-recovered metadata exposes the TRUE stored rows: the weight
    # grad of dot(csr, w) is nonzero only on the batch's nonzero columns
    np.testing.assert_array_equal(np.sort(g.indices.asnumpy()), [1, 4])
    assert g.nnz == 2


def test_stype_dict_override_in_simple_bind():
    x = sym.var("x")
    out = sym.negative(x)
    ex = out.simple_bind(ctx=mx.cpu(), grad_req="null",
                         stype_dict={"x": "row_sparse"}, x=(3, 2))
    assert isinstance(ex.arg_dict["x"], RowSparseNDArray)


def test_stype_survives_json_roundtrip():
    x = sym.var("x", stype="csr")
    out = sym.dot(x, sym.var("w"), transpose_a=True)
    js = out.tojson()
    loaded = sym.load_json(js)
    _, out_st, _ = loaded.infer_storage_type()
    assert out_st == ["row_sparse"]
