"""Plugin parity tests (reference plugin/opencv, plugin/sframe)."""
import os

import sys

import numpy as np
import pytest

import mxtpu as mx


from mxtpu.plugin.dataframe import DataFrameIter  # noqa: E402


def _cv():
    """cv2 + the opencv plugin, or skip — kept per-test so the
    pandas-only DataFrameIter tests still run without cv2."""
    cv2 = pytest.importorskip("cv2")
    from mxtpu.plugin import opencv as cvplug
    return cv2, cvplug


def _jpeg_bytes(cv2, h=48, w=64, seed=0):
    img = np.random.RandomState(seed).randint(0, 255, (h, w, 3), np.uint8)
    ok, buf = cv2.imencode(".jpg", img)
    assert ok
    return buf.tobytes(), img


def test_imdecode_resize_border():
    cv2, cvplug = _cv()
    raw, img = _jpeg_bytes(cv2)
    out = cvplug.imdecode(raw, 1)
    assert out.shape == img.shape
    small = cvplug.resize(out, (32, 24))
    assert small.shape == (24, 32, 3)
    padded = cvplug.copyMakeBorder(out, 2, 3, 4, 5)
    assert padded.shape == (48 + 5, 64 + 9, 3)


def test_float_values_survive_resize():
    # normalized (negative/fractional) pixels must not wrap through uint8
    cv2, cvplug = _cv()
    raw, _ = _jpeg_bytes(cv2)
    src = cvplug.imdecode(raw, 1)
    n = cvplug.color_normalize(src, mx.nd.array(np.float32([120] * 3)),
                               mx.nd.array(np.float32([60] * 3)))
    out = cvplug.resize(n, (32, 24)).asnumpy()
    assert out.min() < -0.1, "negative values should survive the resize"
    assert abs(out.mean()) < 2.0


def test_crops_and_normalize():
    cv2, cvplug = _cv()
    raw, _ = _jpeg_bytes(cv2)
    src = cvplug.imdecode(raw, 1)
    crop = cvplug.fixed_crop(src, 4, 2, 32, 24)
    assert crop.shape == (24, 32, 3)
    crop2, (x0, y0, w, h) = cvplug.random_crop(src, (20, 16))
    assert crop2.shape == (16, 20, 3)
    crop3, _ = cvplug.random_size_crop(src, (20, 16))
    assert crop3.shape == (16, 20, 3)
    assert cvplug.scale_down((10, 10), (20, 16)) == (10, 8)
    n = cvplug.color_normalize(src, mx.nd.array(np.float32([120, 120, 120])),
                               mx.nd.array(np.float32([60, 60, 60])))
    assert abs(float(n.asnumpy().mean())) < 2.0


def test_image_list_iter(tmp_path):
    cv2, cvplug = _cv()
    names = []
    for i in range(5):
        raw, _ = _jpeg_bytes(cv2, seed=i)
        (tmp_path / ("img%d.jpg" % i)).write_bytes(raw)
        names.append("img%d" % i)
    it = cvplug.ImageListIter(str(tmp_path), names, batch_size=2,
                              size=(32, 24))
    batches = list(it)
    assert len(batches) == 3
    assert batches[0].data[0].shape == (2, 3, 24, 32)
    assert batches[-1].pad == 1
    it.reset()
    assert len(list(it)) == 3


def test_dataframe_iter_columns():
    pd = pytest.importorskip("pandas")
    df = pd.DataFrame({
        "f1": np.arange(10, dtype=np.float32),
        "f2": np.arange(10, dtype=np.float32) * 2,
        "y": (np.arange(10) % 2).astype(np.float32),
    })
    it = DataFrameIter(df, data_field=["f1", "f2"], label_field="y",
                       batch_size=4)
    b = list(it)
    assert len(b) == 3 and b[-1].pad == 2
    assert b[0].data[0].shape == (4, 2)
    np.testing.assert_allclose(b[0].data[0].asnumpy()[:, 1],
                               [0, 2, 4, 6])


def test_dataframe_iter_array_cells_module_fit():
    pd = pytest.importorskip("pandas")
    r = np.random.RandomState(0)
    y = r.randint(0, 2, 64).astype(np.float32)
    x = (y[:, None] * 2 - 1) + 0.3 * r.randn(64, 8).astype(np.float32)
    df = pd.DataFrame({"vec": [row for row in x.astype(np.float32)],
                       "y": y})
    it = DataFrameIter(df, data_field="vec", label_field="y", batch_size=16)
    assert it.provide_data[0].shape == (16, 8)
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=2),
        name="softmax")
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",))
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.5}, num_epoch=4)
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc


def test_dataframe_iter_column_list_with_array_cells():
    """A data_field column list may mix scalar and array-cell columns
    (each stacked per-column, then concatenated along features)."""
    import pandas as pd
    df = pd.DataFrame({
        "vec": [np.array([1.0, 2.0]), np.array([3.0, 4.0]),
                np.array([5.0, 6.0]), np.array([7.0, 8.0])],
        "s": [0.5, 1.5, 2.5, 3.5],
        "y": [0.0, 1.0, 0.0, 1.0],
    })
    it = DataFrameIter(df, data_field=["vec", "s"], label_field="y",
                       batch_size=2)
    batch = next(it)
    assert batch.data[0].shape == (2, 3)
    np.testing.assert_allclose(batch.data[0].asnumpy(),
                               [[1.0, 2.0, 0.5], [3.0, 4.0, 1.5]])


# ---------------------------------------------------------------------------
# caffe runtime bridge (mxtpu/plugin/caffe.py; reference plugin/caffe/
# caffe_op.cc). No pycaffe in this image, so the bridge logic runs against
# a pycaffe API fake — the identical seam a real install plugs into.
# ---------------------------------------------------------------------------

class _FakeBlob:
    def __init__(self, shape):
        self.data = np.zeros(shape, np.float32)
        self.diff = np.zeros(shape, np.float32)


class _FakeTanhNet:
    """pycaffe-API double: single TanH layer, one input/one output."""
    TEST = 1

    def __init__(self, prototxt_path, phase):
        text = open(prototxt_path).read()
        assert "TanH" in text
        import re
        dims = [int(d) for d in re.findall(r"dim: (\d+)", text)]
        self.blobs = {"data0": _FakeBlob(tuple(dims)),
                      "out": _FakeBlob(tuple(dims))}
        self.outputs = ["out"]

    def forward(self):
        self.blobs["out"].data[...] = np.tanh(self.blobs["data0"].data)

    def backward(self):
        y = self.blobs["out"]
        self.blobs["data0"].diff[...] = y.diff * (1 - y.data ** 2)


def test_caffe_bridge_missing_pycaffe_message():
    from mxtpu.plugin import caffe as mxcaffe
    try:
        import caffe  # noqa: F401
        pytest.skip("real pycaffe installed; missing-dep path is N/A")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="pycaffe"):
        mxcaffe._caffe()


def test_caffe_bridge_forward_backward_with_fake(monkeypatch):
    import types
    from mxtpu.plugin import caffe as mxcaffe
    fake = types.SimpleNamespace(Net=_FakeTanhNet, TEST=_FakeTanhNet.TEST)
    monkeypatch.setitem(sys.modules, "caffe", fake)

    import mxtpu.autograd as ag
    x_np = np.array([[0.2, -0.7, 1.3]], np.float32)
    x = mx.nd.array(x_np)
    x.attach_grad()
    with ag.record():
        y = mxcaffe.CaffeOp(
            x, prototxt='layer { name: "t" type: "TanH" '
                        'bottom: "data0" top: "out" }')
    np.testing.assert_allclose(y.asnumpy(), np.tanh(x_np), rtol=1e-6)
    y.backward(mx.nd.ones((1, 3)))
    np.testing.assert_allclose(x.grad.asnumpy(), 1 - np.tanh(x_np) ** 2,
                               rtol=1e-5)


# ---------------------------------------------------------------------------
# TVM bridge (mxtpu/contrib/tvm_bridge.py; reference src/nnvm/
# tvm_bridge.cc MXTVMBridge/WrapAsyncCall). No TVM in this image: logic
# runs against a TVM API fake, the identical seam a real install uses.
# ---------------------------------------------------------------------------

class _FakeTvmNd:
    def __init__(self, arr):
        self._a = arr

    def numpy(self):
        return self._a


class _FakeTvmMod:
    class nd:  # noqa: N801 - mirrors tvm.nd namespace
        @staticmethod
        def from_dlpack(arr):
            raise TypeError("fake has no dlpack")

        @staticmethod
        def array(arr):
            return _FakeTvmNd(np.array(arr))


def test_tvm_bridge_missing_tvm_message():
    from mxtpu.contrib import tvm_bridge
    try:
        import tvm  # noqa: F401
        pytest.skip("real tvm installed; the missing-dep path is N/A")
    except ImportError:
        pass
    with pytest.raises(ImportError, match="tvm"):
        tvm_bridge._tvm()


def test_tvm_bridge_wrap_async_call_with_fake(monkeypatch):
    from mxtpu.contrib import tvm_bridge
    monkeypatch.setitem(sys.modules, "tvm", _FakeTvmMod())

    def packed_add(a, b, out):      # destination-passing convention
        out._a[...] = a.numpy() + b.numpy()

    f = tvm_bridge.wrap_async_call(packed_add, num_inputs=2)
    a = mx.nd.array(np.arange(6, dtype="f").reshape(2, 3))
    b = mx.nd.ones((2, 3))
    c = f(a, b)
    np.testing.assert_allclose(c.asnumpy(),
                               np.arange(6, dtype="f").reshape(2, 3) + 1)
