"""Tests for mxtpu.parallel: mesh construction, sharded data/tensor-parallel
training, ring attention, Ulysses all-to-all.

Strategy mirrors the reference's fake-multi-device tests
(tests/python/unittest/test_multi_device_exec.py — multiple CPU contexts in
one process): conftest.py forces an 8-device virtual CPU platform, so real
jax.sharding Meshes and collectives run without TPU hardware.
"""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import gluon
from mxtpu.gluon import nn
from mxtpu import parallel
from mxtpu.parallel import (MeshContext, ShardingRules, ShardedTrainer,
                            PartitionSpec as P)


def _mlp():
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(32, activation="relu"))
        net.add(nn.Dense(10))
    return net


def test_make_mesh_axes():
    m = parallel.make_mesh(data=4, model=2)
    assert m.axis_names == ("data", "model")
    assert m.devices.shape == (4, 2)
    m2 = parallel.make_mesh(data=-1, model=2)
    assert m2.devices.shape == (4, 2)
    mc = MeshContext(data=8)
    assert mc.num_devices == 8
    assert mc.axis_size("data") == 8
    assert mc.axis_size("model") == 1


def test_sharding_rules():
    mc = MeshContext(data=2, model=4)
    rules = ShardingRules([
        (r".*dense0_weight", P("model", None)),
        (r".*_bias", P()),
    ])
    s = rules.sharding_for(mc, "net0_dense0_weight", (32, 16))
    assert s.spec == P("model", None)
    # non-divisible dim falls back to replication on that dim
    s2 = rules.sharding_for(mc, "net0_dense0_weight", (30, 16))
    assert s2.spec == P(None, None)
    # unmatched -> replicated
    s3 = rules.sharding_for(mc, "other", (8, 8))
    assert s3.spec == P()


def test_data_parallel_matches_single_device():
    """DP over 8 devices must be numerically identical to 1 device:
    the check_consistency discipline of the reference GPU tests."""
    np.random.seed(0)
    x = np.random.randn(16, 8).astype(np.float32)
    y = np.random.randint(0, 10, (16,)).astype(np.float32)

    losses = {}
    for name, mesh in [("single", MeshContext(jax.devices()[:1], data=1)),
                       ("dp8", MeshContext(data=8))]:
        mx.random.seed(7)
        net = _mlp()
        net.initialize(mx.init.Xavier())
        net(mx.nd.array(x))  # shape params
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.5, "momentum": 0.9},
                            mesh=mesh)
        ls = [st.step(x, y) for _ in range(5)]
        st.sync_params()
        losses[name] = ls
    np.testing.assert_allclose(losses["single"], losses["dp8"],
                               rtol=2e-5, atol=2e-5)
    # training actually reduced the loss
    assert losses["dp8"][-1] < losses["dp8"][0]


def test_auto_layout_matches_plain():
    """auto_layout=True compiles the step with XLA-chosen (AUTO)
    layouts for the persistent state and carries them across steps via
    donation — numerics must be bit-identical to the default path (a
    layout is storage order, not math). Conv net so weight layouts are
    non-trivial; DP mesh so the sharded lowering path is the one
    exercised.

    Three configs: the plain baseline, auto_layout with donation, and
    auto_layout WITHOUT donation (outputs never adopt the chosen input
    formats, so every call must relayout). Each run also switches batch
    shape mid-training and back — the second shape compiles a separate
    executable whose chosen layouts may differ, and the state carried
    from the first executable must be relaid out, not rejected."""
    np.random.seed(0)
    x = np.random.uniform(size=(8, 3, 16, 16)).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    x2 = np.concatenate([x, x])          # second shape, still 8-divisible
    y2 = np.concatenate([y, y])

    losses = {}
    for auto, donate in ((False, True), (True, True), (True, False)):
        mx.random.seed(3)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1), nn.Activation("relu"),
                nn.MaxPool2D(2), nn.Flatten(), nn.Dense(10))
        net.initialize(mx.init.Xavier())
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.1, "momentum": 0.9},
                            mesh=MeshContext(data=8), auto_layout=auto,
                            donate=donate)
        ls = [st.step(x, y) for _ in range(4)]
        ls.append(st.step(x2, y2))       # new shape -> new executable
        ls.append(st.step(x, y))         # back: first executable again
        losses[(auto, donate)] = ls
    np.testing.assert_allclose(losses[(False, True)],
                               losses[(True, True)],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(losses[(False, True)],
                               losses[(True, False)],
                               rtol=1e-6, atol=1e-7)
    assert losses[(True, True)][-1] < losses[(True, True)][0]


def test_tensor_parallel_matches_dp():
    """2-way DP x 4-way TP on the dense weights == pure DP numerics."""
    np.random.seed(1)
    x = np.random.randn(8, 16).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)

    results = {}
    for name, mesh, rules in [
        ("dp", MeshContext(data=8), None),
        ("tp", MeshContext(data=2, model=4),
         ShardingRules([(r".*dense\d+_weight", P("model", None))])),
    ]:
        mx.random.seed(3)
        net = _mlp()
        net.initialize(mx.init.Xavier())
        net(mx.nd.array(x))
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "adam", {"learning_rate": 0.01},
                            mesh=mesh, rules=rules)
        ls = [st.step(x, y) for _ in range(4)]
        results[name] = ls
    np.testing.assert_allclose(results["dp"], results["tp"],
                               rtol=2e-5, atol=2e-5)


def test_dynamic_lr_inside_jit():
    """LR schedule must stay live across steps without retracing."""
    np.random.seed(2)
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 2, (8,)).astype(np.float32)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(x))
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.5)
    sched.base_lr = 1.0
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 1.0, "lr_scheduler": sched},
                        mesh=MeshContext(data=8))
    st.step(x, y)
    lr0 = st.learning_rate
    for _ in range(4):
        st.step(x, y)
    assert st.learning_rate < lr0


def test_eval_forward():
    np.random.seed(4)
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(x))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1},
                        mesh=MeshContext(data=8))
    loss, outs = st.forward(x, y)
    assert np.isfinite(loss)
    assert outs[0].shape == (8, 10)


def test_batchnorm_aux_updates_under_dp():
    """BatchNorm running stats must update with GLOBAL batch statistics
    (sync-BN semantics fall out of whole-program jit)."""
    np.random.seed(5)
    x = np.random.randn(16, 6).astype(np.float32) * 3.0 + 1.0
    y = np.random.randint(0, 4, (16,)).astype(np.float32)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8))
        net.add(nn.BatchNorm())
        net.add(nn.Dense(4))
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(x))
    params = net.collect_params()
    rm_name = [k for k in params.keys() if "running_mean" in k][0]
    before = params[rm_name].data().asnumpy().copy()
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1},
                        mesh=MeshContext(data=8))
    for _ in range(3):
        st.step(x, y)
    st.sync_params()
    after = params[rm_name].data().asnumpy()
    assert not np.allclose(before, after)


# ---------------------------------------------------------------------------
# ring attention / sequence parallelism
# ---------------------------------------------------------------------------

def _ref_attention(q, k, v, causal):
    d = q.shape[-1]
    s = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    if causal:
        t = q.shape[2]
        mask = np.tril(np.ones((t, t), bool))
        s = np.where(mask[None, None], s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bhkd->bhqd", p, v)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    np.random.seed(6)
    b, h, t, d = 2, 4, 32, 16
    q = np.random.randn(b, h, t, d).astype(np.float32) * 0.5
    k = np.random.randn(b, h, t, d).astype(np.float32) * 0.5
    v = np.random.randn(b, h, t, d).astype(np.float32)
    mesh = MeshContext(data=2, seq=4)
    out = parallel.ring_attention_sharded(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh, causal=causal)
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


def test_ring_attention_grad():
    """Ring attention is differentiable (trains end to end)."""
    np.random.seed(7)
    b, h, t, d = 1, 2, 16, 8
    q = jnp.asarray(np.random.randn(b, h, t, d).astype(np.float32))
    k = jnp.asarray(np.random.randn(b, h, t, d).astype(np.float32))
    v = jnp.asarray(np.random.randn(b, h, t, d).astype(np.float32))
    mesh = MeshContext(seq=8)

    def f(q, k, v):
        return jnp.sum(parallel.ring_attention_sharded(q, k, v, mesh,
                                                       causal=True) ** 2)

    g = jax.grad(f)(q, k, v)
    assert np.isfinite(np.asarray(g)).all()

    # compare against grad of dense attention
    def f_dense(q, k, v):
        dd = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / jnp.sqrt(dd)
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, jnp.finfo(jnp.float32).min)
        p = jax.nn.softmax(s, -1)
        return jnp.sum(jnp.einsum("bhqk,bhkd->bhqd", p, v) ** 2)

    g_ref = jax.grad(f_dense)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=2e-3, atol=2e-3)


def test_ulysses_attention_matches_full():
    np.random.seed(8)
    b, h, t, d = 2, 8, 32, 4
    q = np.random.randn(b, h, t, d).astype(np.float32) * 0.5
    k = np.random.randn(b, h, t, d).astype(np.float32) * 0.5
    v = np.random.randn(b, h, t, d).astype(np.float32)
    mesh = MeshContext(seq=8)
    from mxtpu.parallel.mesh import shard_map
    from jax.sharding import PartitionSpec as P2
    spec = P2(None, None, "seq", None)
    fn = shard_map(
        lambda a, b_, c: parallel.ulysses_attention(a, b_, c, "seq"),
        mesh=mesh.mesh, in_specs=(spec, spec, spec), out_specs=spec,
        check_vma=False)
    out = fn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    ref = _ref_attention(q, k, v, False)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# pipeline parallelism
# ---------------------------------------------------------------------------

def test_pipeline_matches_sequential():
    """GPipe pipeline over 4 stages == applying the 4 stages in sequence."""
    np.random.seed(10)
    n_stages, d = 4, 8
    ws = np.random.randn(n_stages, d, d).astype(np.float32) * 0.3
    bs = np.random.randn(n_stages, d).astype(np.float32) * 0.1
    x = np.random.randn(16, d).astype(np.float32)

    def stage(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    mesh = MeshContext(pipe=4, data=2)
    out = parallel.pipeline_apply(mesh, stage,
                                  (jnp.asarray(ws), jnp.asarray(bs)),
                                  jnp.asarray(x), n_microbatch=4)
    ref = x
    for i in range(n_stages):
        ref = np.tanh(ref @ ws[i] + bs[i])
    np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)


def test_pipeline_grad():
    """The pipeline schedule is differentiable end to end (backward
    pipelines automatically through the reversed permutes)."""
    np.random.seed(11)
    n_stages, d = 4, 4
    ws = jnp.asarray(np.random.randn(n_stages, d, d).astype(np.float32) * 0.3)
    bs = jnp.asarray(np.zeros((n_stages, d), np.float32))
    x = jnp.asarray(np.random.randn(8, d).astype(np.float32))
    mesh = MeshContext(pipe=4)

    def stage(params, h):
        w, b = params
        return jnp.tanh(h @ w + b)

    def loss(ws, bs, x):
        y = parallel.pipeline_apply(mesh, stage, (ws, bs), x, 4)
        return jnp.mean(y ** 2)

    g = jax.jit(jax.grad(loss, argnums=(0, 1)))(ws, bs, x)

    def loss_ref(ws, bs, x):
        h = x
        for i in range(n_stages):
            h = jnp.tanh(h @ ws[i] + bs[i])
        return jnp.mean(h ** 2)

    g_ref = jax.grad(loss_ref, argnums=(0, 1))(ws, bs, x)
    np.testing.assert_allclose(np.asarray(g[0]), np.asarray(g_ref[0]),
                               rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(np.asarray(g[1]), np.asarray(g_ref[1]),
                               rtol=2e-4, atol=2e-5)


# ---------------------------------------------------------------------------
# expert parallelism (MoE)
# ---------------------------------------------------------------------------

def test_moe_dispatch_capacity():
    logits = jnp.asarray(np.array(
        [[5.0, 0.0], [4.0, 0.0], [3.0, 0.0], [0.0, 2.0]], np.float32))
    dispatch, combine, aux = parallel.moe_dispatch(logits, capacity=2)
    d = np.asarray(dispatch)
    # expert 0 receives tokens 0,1; token 2 overflows capacity
    assert d[0, 0].sum() == 1 and d[1, 0].sum() == 1
    assert d[2].sum() == 0
    assert d[3, 1].sum() == 1
    assert float(aux) > 0


def test_moe_ffn_expert_sharded():
    """MoE layer trains under jit with expert-sharded weights on a
    (data, expert) mesh; grads are finite and dispatch covers tokens."""
    np.random.seed(12)
    t, dmodel, e, hdim = 16, 8, 4, 16
    mesh = MeshContext(data=2, expert=4)
    gate_w = jnp.asarray(np.random.randn(dmodel, e).astype(np.float32) * .1)
    w1 = jnp.asarray(np.random.randn(e, dmodel, hdim).astype(np.float32) * .1)
    b1 = jnp.zeros((e, hdim), jnp.float32)
    w2 = jnp.asarray(np.random.randn(e, hdim, dmodel).astype(np.float32) * .1)
    b2 = jnp.zeros((e, dmodel), jnp.float32)
    x = jnp.asarray(np.random.randn(t, dmodel).astype(np.float32))

    # shard experts over the expert axis
    from jax.sharding import NamedSharding
    ex = NamedSharding(mesh.mesh, P("expert", None, None))
    w1 = jax.device_put(w1, ex)
    w2 = jax.device_put(w2, ex)

    def loss(gw, w1, b1, w2, b2, x):
        y, aux = parallel.moe_ffn(x, gw, w1, b1, w2, b2,
                                  capacity_factor=2.0)
        return jnp.mean(y ** 2) + 0.01 * aux

    val, grads = jax.jit(jax.value_and_grad(loss, argnums=(0, 1, 2, 3, 4)))(
        gate_w, w1, b1, w2, b2, x)
    assert np.isfinite(float(val))
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
    # top-1 routing with cf=2 must place every token
    dispatch, _, _ = parallel.moe_dispatch(x @ gate_w, capacity=8)
    assert float(np.asarray(dispatch).sum()) == t


def test_sharding_rules_from_ctx_groups():
    import mxtpu as mx
    from jax.sharding import PartitionSpec as P
    from mxtpu.parallel import ShardingRules

    with mx.AttrScope(ctx_group="tp"):
        w = mx.sym.var("fc_weight")
    x = mx.sym.var("data")
    out = mx.sym.FullyConnected(x, w, num_hidden=8, no_bias=True,
                                name="fc")
    rules = ShardingRules.from_ctx_groups(out, {"tp": P("model", None)})
    assert tuple(rules.spec_for("fc_weight", (8, 4))) == ("model", None)
    assert tuple(rules.spec_for("data", (2, 4))) == ()
    assert tuple(rules.spec_for("fc_weight_suffix", (8, 4))) == ()


def test_ctx_group_rules_skip_op_nodes():
    import mxtpu as mx
    from jax.sharding import PartitionSpec as P
    from mxtpu.parallel import ShardingRules
    with mx.AttrScope(ctx_group="tp"):
        x = mx.sym.var("data2")
        out = mx.sym.FullyConnected(x, num_hidden=4, name="opnode")
    rules = ShardingRules.from_ctx_groups(out, {"tp": P("model", None)})
    # op node 'opnode' stamped but excluded; its auto-created weight and
    # the variable are included
    assert tuple(rules.spec_for("opnode", (4, 4))) == ()
    assert tuple(rules.spec_for("data2", (2, 4))) == ("model", None)


def test_device_prefetch_stages_and_trains():
    """device_prefetch pre-stages batches with the mesh's batch sharding;
    ShardedTrainer.step_async consumes them without re-transfer, and
    training matches the unprefetched path exactly."""
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import MeshContext, ShardedTrainer, device_prefetch

    def build():
        mx.random.seed(0)
        net = nn.HybridSequential()
        with net.name_scope():
            net.add(nn.Dense(16, activation="relu"))
            net.add(nn.Dense(4))
        net.initialize(mx.init.Xavier(), force_reinit=True)
        return net

    mesh = MeshContext(jax.devices()[:4], data=4)
    r = np.random.RandomState(0)
    batches = [(r.uniform(-1, 1, (8, 6)).astype(np.float32),
                r.randint(0, 4, (8,)).astype(np.float32))
               for _ in range(5)]

    # order + structure + sharding of the staged stream
    staged = list(device_prefetch(iter(batches), mesh=mesh, size=2))
    assert len(staged) == 5
    for (sx, sy), (x, y) in zip(staged, batches):
        assert isinstance(sx, jax.Array)
        assert sx.sharding == mesh.batch_sharding(2)
        np.testing.assert_allclose(np.asarray(sx), x)
        np.testing.assert_allclose(np.asarray(sy), y)

    losses = {}
    for prefetch in (False, True):
        net = build()
        net(mx.nd.array(batches[0][0][:2]))
        st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                            "sgd", {"learning_rate": 0.1}, mesh=mesh)
        if prefetch:
            ls = [float(st.step_async(x, y).asnumpy())
                  for x, y in device_prefetch(iter(batches), mesh=mesh)]
        else:
            ls = [st.step(x, y) for x, y in batches]
        losses[prefetch] = ls
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-6)


def test_device_prefetch_databatch_and_short_iter():
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu.parallel import MeshContext, device_prefetch

    mesh = MeshContext(jax.devices()[:2], data=2)
    it = mx.io.NDArrayIter(np.arange(24).reshape(6, 4).astype(np.float32),
                           np.arange(6).astype(np.float32), batch_size=2)
    out = list(device_prefetch(it, mesh=mesh, size=8))  # size > n batches
    assert len(out) == 3
    b0 = out[0]
    assert b0.data[0].shape == (2, 4)
    np.testing.assert_allclose(b0.data[0].asnumpy(),
                               [[0, 1, 2, 3], [4, 5, 6, 7]])
    # empty iterator
    assert list(device_prefetch(iter([]), mesh=mesh)) == []


def test_device_prefetch_none_label_and_namedtuple():
    import collections
    import numpy as np
    import jax
    import mxtpu as mx
    from mxtpu.io import DataBatch
    from mxtpu.parallel import MeshContext, device_prefetch

    mesh = MeshContext(jax.devices()[:2], data=2)
    # DataBatch with label=None (inference batches)
    b = DataBatch(data=[mx.nd.array(np.zeros((2, 3), np.float32))],
                  label=None)
    out = list(device_prefetch(iter([b]), mesh=mesh))
    assert len(out) == 1 and out[0].label is None
    # namedtuple batches (common collate pattern)
    Batch = collections.namedtuple("Batch", ["data", "label"])
    nb = Batch(np.ones((2, 3), np.float32), np.zeros((2,), np.float32))
    out = list(device_prefetch(iter([nb]), mesh=mesh))
    assert isinstance(out[0], Batch)
    np.testing.assert_allclose(np.asarray(out[0].data), nb.data)


def test_device_prefetch_recycling_iterator_not_aliased():
    """An iterator that reuses ONE DataBatch object across next() calls must
    not have its buffered entries corrupted by later mutations."""
    from mxtpu.parallel import MeshContext, device_prefetch
    from mxtpu.io import DataBatch

    mesh = MeshContext(jax.devices()[:1], data=1)
    shared = DataBatch([mx.nd.zeros((2, 3))], [mx.nd.zeros((2,))])

    def recycling():
        for i in range(4):
            shared.data = [mx.nd.full((2, 3), i)]
            shared.label = [mx.nd.full((2,), i)]
            yield shared

    got = [float(b.data[0].asnumpy()[0, 0])
           for b in device_prefetch(recycling(), mesh=mesh, size=3)]
    assert got == [0.0, 1.0, 2.0, 3.0], got


def test_zero1_state_sharding_matches_plain_dp():
    """ZeRO-1: optimizer state for pure-DP params lives dim-0-sharded
    over the data axis (memory / N per device) and training is
    numerically identical to plain DP — the collectives are inserted by
    the partitioner from sharding constraints, not hand-written."""
    from jax.sharding import PartitionSpec

    def make(zero1):
        mx.random.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(32, activation="relu", in_units=24),
                nn.Dense(8, in_units=32))
        net.initialize(mx.init.Xavier(), force_reinit=True)
        return ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                              "sgd", {"learning_rate": 0.1,
                                      "momentum": 0.9},
                              mesh=MeshContext(data=8), zero1=zero1)

    r = np.random.RandomState(0)
    x = r.rand(16, 24).astype("f")
    y = r.randint(0, 8, (16,)).astype("f")

    plain, z1 = make(False), make(True)
    for _ in range(3):
        l0 = plain.step(x, y)
        l1 = z1.step(x, y)
        assert abs(l0 - l1) < 1e-5, (l0, l1)

    # state placement: dim-0-divisible params got the data shard, and
    # it survives the donated step round-trips
    data_spec = PartitionSpec("data")
    sharded = 0
    for j, z_sh in enumerate(z1._zero1_shardings):
        st = z1._opt_states[j]
        if z_sh is None:
            continue
        sharded += 1
        for leaf in jax.tree_util.tree_leaves(st):
            assert leaf.sharding.spec[0] == data_spec[0], leaf.sharding
            # truly distributed: one device holds 1/8 of the rows
            shard_shape = leaf.addressable_shards[0].data.shape
            assert shard_shape[0] * 8 == leaf.shape[0], (shard_shape,
                                                         leaf.shape)
    assert sharded >= 2, z1._zero1_shardings   # both Dense weights
    # plain DP keeps everything replicated
    for st in plain._opt_states:
        for leaf in jax.tree_util.tree_leaves(st):
            assert leaf.sharding.spec == PartitionSpec(), leaf.sharding
    # end-state weights agree exactly
    for a, b in zip(plain._param_vals, z1._param_vals):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# async gradient-push hook (ISSUE 2: compute overlaps the KVStore push)
# ---------------------------------------------------------------------------

class _FakeFuture:
    def __init__(self):
        self.drained = False

    def result(self):
        self.drained = True


def test_grad_push_hook_backpressure():
    """set_grad_push: the hook sees every step's gradients (one entry
    per trainable param, matching shapes), and the inflight window is
    bounded — by step N+max_inflight the step-N future MUST have been
    drained (backpressure, not unbounded pileup)."""
    np.random.seed(5)
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(x))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1},
                        mesh=MeshContext(data=8))
    seen, futs = [], []

    def hook(grads):
        seen.append(grads)
        futs.append(_FakeFuture())
        return futs[-1]

    st.set_grad_push(hook, max_inflight=1)
    for _ in range(3):
        st.step(x, y)
    assert len(seen) == 3
    want = {p.name: p.shape for p in net._ordered_params()
            if p.grad_req != "null"}
    for grads in seen:
        assert set(grads) == set(want)
        for name, g in grads.items():
            assert g.shape == tuple(want[name])
            assert np.isfinite(g.asnumpy()).all()
    # window=1: by the time push 3 was dispatched, push 1 AND 2 drained
    assert futs[0].drained and futs[1].drained
    assert not futs[2].drained          # still riding
    st.flush_grad_pushes()
    assert futs[2].drained
    # unregister drains and stops calling
    st.set_grad_push(None)
    st.step(x, y)
    assert len(seen) == 3


def test_attach_kvstore_overlapped_push():
    """attach_kvstore: every step's gradients land in a dist_async
    store via push_async (lazy zero-init, per-step clock advance), and
    sync_params waits for the outstanding pushes."""
    np.random.seed(6)
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    net = _mlp()
    net.initialize(mx.init.Xavier())
    net(mx.nd.array(x))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1},
                        mesh=MeshContext(data=8))
    kv = mx.kv.create("dist_async")
    try:
        st.attach_kvstore(kv, max_inflight=2)
        for _ in range(3):
            st.step(x, y)
        st.sync_params()               # implies flush_grad_pushes()
        names = [p.name for p in net._ordered_params()
                 if p.grad_req != "null"]
        srv = kv._own_server
        for name in names:
            # every step's push applied (no lost/dup applies)
            assert srv._clock[name] == 3, (name, srv._clock)
        out = mx.nd.zeros(net._ordered_params()[0].shape)
        kv.pull(names[0], out=out)     # accumulated grads, finite
        assert np.isfinite(out.asnumpy()).all()
    finally:
        kv.close()
