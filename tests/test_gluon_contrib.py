"""gluon.contrib: Concurrent/Identity, IntervalSampler, variational
dropout, LSTMP, ConvRNN/LSTM/GRU cells (reference gluon/contrib)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, gluon
from mxtpu.gluon import nn
from mxtpu.gluon.contrib import nn as cnn
from mxtpu.gluon.contrib import rnn as crnn
from mxtpu.gluon.contrib.data import IntervalSampler


def test_concurrent_and_identity():
    net = cnn.HybridConcurrent(axis=1)
    net.add(nn.Dense(3), cnn.Identity(), nn.Dense(2))
    net.initialize(mx.init.Xavier())
    x = nd.array(np.ones((4, 5), np.float32))
    out = net(x)
    assert out.shape == (4, 3 + 5 + 2)
    np.testing.assert_allclose(out.asnumpy()[:, 3:8], 1.0)

    net2 = cnn.Concurrent(axis=1)
    net2.add(cnn.Identity(), cnn.Identity())
    out2 = net2(x)
    assert out2.shape == (4, 10)


def test_interval_sampler():
    s = IntervalSampler(10, 3)
    idx = list(s)
    assert sorted(idx) == list(range(10))      # rollover covers all
    assert idx[:4] == [0, 3, 6, 9]
    s2 = IntervalSampler(10, 3, rollover=False)
    assert list(s2) == [0, 3, 6, 9]
    assert len(s2) == 4


def test_lstmp_cell():
    mx.random.seed(0)
    cell = crnn.LSTMPCell(hidden_size=8, projection_size=3)
    inputs = [nd.array(np.random.RandomState(i).rand(2, 4)
                       .astype(np.float32)) for i in range(5)]
    cell.initialize(mx.init.Xavier())
    outputs, states = cell.unroll(5, inputs, merge_outputs=False)
    assert outputs[-1].shape == (2, 3)          # projected size
    assert states[0].shape == (2, 3)
    assert states[1].shape == (2, 8)            # cell keeps full width


def test_variational_dropout_rejects_hybridize():
    base = gluon.rnn.RNNCell(4, input_size=4)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    with pytest.raises(NotImplementedError):
        cell.hybridize()


def test_variational_dropout_locked_mask():
    mx.random.seed(0)
    base = gluon.rnn.RNNCell(6, input_size=6)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5)
    cell.initialize(mx.init.Xavier())
    x = nd.array(np.ones((3, 6), np.float32))
    states = cell.begin_state(batch_size=3)
    with mx.autograd.record():
        cell.reset()
        _ = cell(x, states)
        m1 = cell.drop_inputs_mask.asnumpy()
        _ = cell(x, states)
        m2 = cell.drop_inputs_mask.asnumpy()
    np.testing.assert_allclose(m1, m2)          # locked across steps
    assert (m1 == 0).any() or (m1 != 1).any()   # dropout actually applied
    cell.reset()
    assert cell.drop_inputs_mask is None


@pytest.mark.parametrize("Cell,n_states", [
    (crnn.Conv1DRNNCell, 1), (crnn.Conv2DRNNCell, 1),
    (crnn.Conv1DLSTMCell, 2), (crnn.Conv2DLSTMCell, 2),
    (crnn.Conv3DLSTMCell, 2),
    (crnn.Conv2DGRUCell, 1),
])
def test_conv_cells(Cell, n_states):
    mx.random.seed(0)
    nd_dims = {"Conv1D": 1, "Conv2D": 2, "Conv3D": 3}[Cell.__name__[:6]]
    spatial = (8,) * nd_dims
    cell = Cell(input_shape=(2,) + spatial, hidden_channels=4,
                i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize(mx.init.Xavier())
    seq = [nd.array(np.random.RandomState(t).rand(2, 2, *spatial)
                    .astype(np.float32)) for t in range(3)]
    outputs, states = cell.unroll(3, seq, merge_outputs=False)
    assert outputs[-1].shape == (2, 4) + spatial
    assert len(states) == n_states
    for st in states:
        assert st.shape == (2, 4) + spatial


def test_conv_lstm_learns():
    # ConvLSTM can fit "predict the previous frame" on tiny data
    import logging
    logging.disable(logging.INFO)
    mx.random.seed(0)
    cell = crnn.Conv2DLSTMCell(input_shape=(1, 6, 6), hidden_channels=4,
                               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    head = nn.Conv2D(1, 1)
    cell.initialize(mx.init.Xavier())
    head.initialize(mx.init.Xavier())
    all_params = {}
    all_params.update(cell.collect_params())
    all_params.update(head.collect_params())
    trainer = gluon.Trainer(all_params, "adam", {"learning_rate": 1e-2})
    rng = np.random.RandomState(0)
    seq = [nd.array(rng.rand(2, 1, 6, 6).astype(np.float32))
           for _ in range(4)]
    L = gluon.loss.L2Loss()
    first = last = None
    for it in range(30):
        with mx.autograd.record():
            outs, _ = cell.unroll(4, seq, merge_outputs=False)
            pred = head(outs[-2])
            loss = L(pred, seq[-1])
        loss.backward()
        trainer.step(2)
        v = float(loss.mean().asnumpy())
        first = v if first is None else first
        last = v
    assert last < first, (first, last)


def test_modifier_cell_default_unroll():
    # unroll without explicit begin_state must work for ModifierCells
    # (begin_state(batch_size) binds positionally)
    mx.random.seed(0)
    for wrap in (lambda c: crnn.VariationalDropoutCell(c, drop_inputs=0.3),
                 lambda c: gluon.rnn.ZoneoutCell(c, zoneout_states=0.2)):
        base = gluon.rnn.RNNCell(4, input_size=4)
        cell = wrap(base)
        cell.initialize(mx.init.Xavier())
        seq = [nd.array(np.random.RandomState(t).rand(2, 4)
                        .astype(np.float32)) for t in range(3)]
        with mx.autograd.record():
            outputs, _ = cell.unroll(3, seq, merge_outputs=False)
        assert outputs[-1].shape == (2, 4)


def test_wikitext_dataset_local_file(tmp_path):
    """WikiText2 reads a local token file: vocab with <eos>, next-token
    labels, fixed-length samples (reference gluon/contrib/data/text.py)."""
    import numpy as np
    import pytest
    from mxtpu.gluon.contrib.data import WikiText2

    corpus = tmp_path / "wiki.train.tokens"
    corpus.write_text("the cat sat\nthe dog ran\n\nthe cat ran\n")
    ds = WikiText2(root=str(tmp_path), segment="train", seq_len=4)
    assert len(ds) >= 2
    d0, l0 = ds[0]
    assert d0.shape == (4,) and l0.shape == (4,)
    # label is the next-token shift of data
    d1, _ = ds[1]
    np.testing.assert_array_equal(l0.asnumpy()[:3], d0.asnumpy()[1:])
    np.testing.assert_array_equal(l0.asnumpy()[3], d1.asnumpy()[0])
    # every line ends in <eos>; 'the' is the most frequent real token
    vocab = ds.vocabulary
    assert ds.frequencies["the"] == 3
    eos_id = vocab.to_indices(["<eos>"])[0]
    assert eos_id in ds[0][0].asnumpy().tolist() + ds[0][1].asnumpy().tolist()
    # missing file fails with instructions, not a hang/download
    with pytest.raises(IOError):
        WikiText2(root=str(tmp_path / "nope"), segment="train")
