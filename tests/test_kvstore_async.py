"""dist_async parameter service (reference async mode,
src/kvstore/kvstore_dist_server.h:339,462: pushes applied immediately
server-side, no merge barrier — staleness traded for straggler
tolerance). Fast in-process tier; the multi-process straggler
demonstration is tests/nightly/async_worker.py via the local launcher."""
import os
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.kvstore_async import AsyncDistKVStore, ParameterServer


def test_create_returns_async_store():
    kv = mx.kv.create("dist_async")
    try:
        assert isinstance(kv, AsyncDistKVStore)
        assert kv.type == "dist_async"
    finally:
        kv.close()


def test_server_side_optimizer_applies_each_push():
    kv = mx.kv.create("dist_async")
    try:
        kv.init(3, mx.nd.zeros((2, 3)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        kv.push(3, mx.nd.ones((2, 3)))
        kv.push(3, mx.nd.ones((2, 3)))
        out = mx.nd.zeros((2, 3))
        kv.pull(3, out=out)
        # two sequential updates, each applied on arrival: w = 0 - .5 - .5
        np.testing.assert_allclose(out.asnumpy(), -np.ones((2, 3)))
        assert kv.staleness_stats()["pushes"] == 2
    finally:
        kv.close()


def test_push_without_updater_accumulates():
    kv = mx.kv.create("dist_async")
    try:
        kv.init("a", mx.nd.array(np.arange(4, dtype="f")))
        kv.push("a", mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("a", out=out)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.arange(4, dtype="f") + 1)
    finally:
        kv.close()


def test_list_push_merges_locally_before_send():
    kv = mx.kv.create("dist_async")
    try:
        kv.init("k", mx.nd.zeros((3,)))
        kv.push("k", [mx.nd.ones((3,)), mx.nd.ones((3,)) * 2])
        out = mx.nd.zeros((3,))
        kv.pull("k", out=out)
        np.testing.assert_allclose(out.asnumpy(), 3 * np.ones(3))
        # one wire push for the merged device shards
        assert kv.staleness_stats()["clocks"]["k"] == 1
    finally:
        kv.close()


def test_uninitialized_key_errors():
    kv = mx.kv.create("dist_async")
    try:
        with pytest.raises(RuntimeError, match="uninitialized"):
            kv.push("missing", mx.nd.ones((2,)))
        with pytest.raises(RuntimeError, match="uninitialized"):
            kv.pull("missing", out=mx.nd.zeros((2,)))
        with pytest.raises((RuntimeError, KeyError), match="uninitialized"):
            kv.row_sparse_pull("absent", out=mx.nd.zeros((2,)),
                               row_ids=mx.nd.array([0]))
    finally:
        kv.close()


def _worker_env(addr, rank, nproc):
    return {"MXTPU_PS_ADDRS": addr, "MXTPU_PROC_ID": str(rank),
            "MXTPU_NUM_PROCS": str(nproc)}


def _patched_env(env):
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    return saved


def _restore_env(saved):
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_concurrent_workers_interleave_with_staleness():
    """Two 'workers' (threads with their own stores/clocks) against one
    shared server: pushes interleave without any barrier and the server
    observes staleness > 0 — the async property itself."""
    server = ParameterServer().start()
    stores = []
    try:
        saved = _patched_env(_worker_env(server.address, 0, 2))
        try:
            kv0 = mx.kv.create("dist_async")
            stores.append(kv0)
            os.environ["MXTPU_PROC_ID"] = "1"
            kv1 = mx.kv.create("dist_async")
            stores.append(kv1)
        finally:
            _restore_env(saved)
        # manual init: barrier needs both workers, run init concurrently
        t = threading.Thread(
            target=lambda: kv1.init("w", mx.nd.zeros((4,))))
        t.start()
        kv0.init("w", mx.nd.zeros((4,)))
        t.join()

        n_steps = {0: 40, 1: 40}
        def run(kv, rank):
            w = mx.nd.zeros((4,))
            for _ in range(n_steps[rank]):
                kv.pull("w", out=w)
                kv.push("w", mx.nd.ones((4,)) * 0.01)
        th = [threading.Thread(target=run, args=(kv, r))
              for r, kv in enumerate(stores)]
        for x in th:
            x.start()
        for x in th:
            x.join()
        stats = stores[0].staleness_stats()
        assert stats["pushes"] == 80
        assert stats["staleness_max"] > 0, stats
        out = mx.nd.zeros((4,))
        stores[0].pull("w", out=out)
        # no updater: every push accumulated exactly once, stale or not
        np.testing.assert_allclose(out.asnumpy(), 0.01 * 80 * np.ones(4),
                                   rtol=1e-5)
    finally:
        for kv in stores:
            kv.close()
        server.stop()


def test_key_sharding_across_servers():
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    saved = _patched_env(_worker_env(
        s1.address + "," + s2.address, 0, 1))
    try:
        kv = mx.kv.create("dist_async")
        keys = ["k%d" % i for i in range(8)]
        for k in keys:
            kv.init(k, mx.nd.ones((2,)))
            kv.push(k, mx.nd.ones((2,)))
        # every key landed on exactly one server; union covers all keys
        c1 = s1._clock
        c2 = s2._clock
        assert not (set(c1) & set(c2))
        assert set(c1) | set(c2) == set(keys)
        out = mx.nd.zeros((2,))
        for k in keys:
            kv.pull(k, out=out)
            np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(2))
        kv.close()
    finally:
        _restore_env(saved)
        s1.stop()
        s2.stop()


def test_module_fit_through_dist_async():
    """Module.fit with kvstore='dist_async': grads push to the parameter
    service, SGD runs server-side (update_on_kvstore), weights pull back
    — the reference's async training loop shape, single-process."""
    r = np.random.RandomState(5)
    y = (r.rand(192) * 4).astype("f")
    x = r.rand(192, 16).astype("f") * 0.1
    for i in range(192):
        x[i, int(y[i]) * 4:int(y[i]) * 4 + 4] += 1.0
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4),
        name="softmax")
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=4, kvstore="dist_async", optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc
    # the optimizer really ran server-side: pushes were counted there
    kv = mod._kvstore
    stats = kv.staleness_stats()
    assert stats["pushes"] >= 4 * 6 * 2  # epochs * batches * params
    kv.close()


def test_bigarray_parts_roundtrip():
    """Arrays above MXTPU_KVSTORE_BIGARRAY_BOUND split into row parts,
    each an independent subkey (reference BIGARRAY_BOUND key splits) —
    init/push/pull must reassemble exactly."""
    from mxtpu import kvstore_async as ka
    old = ka._BIGARRAY_BOUND
    ka._BIGARRAY_BOUND = 1000
    try:
        kv = mx.kv.create("dist_async")
        r = np.random.RandomState(0)
        w = r.rand(40, 100).astype("f")      # 4000 elems -> 4 parts
        kv.init("big", mx.nd.array(w))
        assert len(kv._parts["big"]) == 4
        out = mx.nd.zeros(w.shape)
        kv.pull("big", out=out)
        np.testing.assert_allclose(out.asnumpy(), w, rtol=1e-6)
        kv.push("big", mx.nd.ones(w.shape))
        kv.pull("big", out=out)
        np.testing.assert_allclose(out.asnumpy(), w + 1, rtol=1e-6)
        kv.close()
    finally:
        ka._BIGARRAY_BOUND = old


def test_row_sparse_pull_async():
    """Only requested rows travel (server-side pull_rows); targets may be
    row_sparse or exactly the gathered shape."""
    from mxtpu import kvstore_async as ka
    from mxtpu.ndarray.sparse import row_sparse_array
    old = ka._BIGARRAY_BOUND
    ka._BIGARRAY_BOUND = 60          # force parts: 20x6=120 elems -> 3+
    try:
        kv = mx.kv.create("dist_async")
        r = np.random.RandomState(1)
        w = r.rand(20, 6).astype("f")
        kv.init("emb", mx.nd.array(w))
        assert len(kv._parts["emb"]) > 1
        ids = np.array([0, 3, 7, 19], "int64")
        dense_tgt = mx.nd.zeros((4, 6))
        kv.row_sparse_pull("emb", out=dense_tgt, row_ids=mx.nd.array(ids))
        np.testing.assert_allclose(dense_tgt.asnumpy(), w[ids], rtol=1e-6)
        rsp = row_sparse_array((np.zeros((1, 6), "f"), [0]), shape=(20, 6))
        kv.row_sparse_pull("emb", out=rsp, row_ids=mx.nd.array(ids))
        np.testing.assert_allclose(rsp.asnumpy()[ids], w[ids], rtol=1e-6)
        # rows outside ids are zero in the pulled row_sparse view
        mask = np.ones(20, bool)
        mask[ids] = False
        assert np.all(rsp.asnumpy()[mask] == 0)
        # dense FULL-shape target (Module.prepare pulls into full
        # executor buffers): ONLY the requested rows refresh — the
        # server slices row-wise, the whole table never rides the wire
        # for a row pull (ISSUE 13 fixed the old whole-table re-fetch)
        sentinel = np.full((20, 6), -7.0, "f")
        full = mx.nd.array(sentinel)
        kv.row_sparse_pull("emb", out=full, row_ids=mx.nd.array(ids))
        got = full.asnumpy()
        np.testing.assert_allclose(got[ids], w[ids], rtol=1e-6)
        np.testing.assert_allclose(got[mask], sentinel[mask])
        # out-of-range ids are refused before any wire traffic
        with pytest.raises(IndexError, match="out of range"):
            kv.row_sparse_pull("emb", out=mx.nd.zeros((1, 6)),
                               row_ids=mx.nd.array([20]))
        with pytest.raises(IndexError, match="out of range"):
            kv.row_sparse_pull("emb", out=mx.nd.zeros((1, 6)),
                               row_ids=mx.nd.array([-1]))
        kv.close()
    finally:
        ka._BIGARRAY_BOUND = old


def test_async_wire_compression():
    """2-bit compression on the push wire: server dequantizes before its
    update; error feedback makes repeated pushes converge to the true
    accumulated gradient."""
    kv = mx.kv.create("dist_async")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    w = np.zeros((4, 8), "f")
    kv.init("w", mx.nd.array(w))
    g = np.full((4, 8), 0.7, "f")
    # no updater: server accumulates pushes. Each push emits exactly one
    # +0.5 code per element (2-bit wire), so 5 pushes of 0.7 land 2.5 on
    # the table with 1.0 carried in the worker-side residual — the
    # reference's error-feedback semantics, not lossless transfer.
    for _ in range(5):
        kv.push("w", mx.nd.array(g))
    out = mx.nd.zeros(w.shape)
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), np.full((4, 8), 2.5),
                               rtol=1e-6)
    res = kv.gradient_compression._residuals["w"]
    np.testing.assert_allclose(np.asarray(res), np.full((4, 8), 1.0),
                               rtol=1e-6)
    kv.close()


def test_ps_token_auth():
    """With MXTPU_PS_TOKEN set, the server reads a raw fixed-length
    preamble and closes unauthenticated sockets WITHOUT unpickling
    anything — the auth check must never feed attacker bytes to pickle."""
    import socket as _socket
    from mxtpu.kvstore_async import (_send_frame, _recv_frame,
                                     _ServerConn, _auth_blob)
    srv = ParameterServer(token="sekrit").start()
    host, _, port = srv.address.partition(":")
    try:
        # no preamble, straight to a (pickle) frame: the server consumes
        # it as a failed raw compare and closes — no reply, no unpickle
        s = _socket.create_connection((host, int(port)), timeout=10)
        _send_frame(s, ("pull", "w"))
        try:
            s.shutdown(_socket.SHUT_WR)  # EOF: the server stops reading
            s.settimeout(10)             # the would-be preamble, closes
            assert s.recv(1) == b""      # orderly close, nothing served
        except OSError:
            # the server may close with our frame's tail unread, which
            # RSTs instead of FINs — equally "closed without serving"
            pass
        s.close()
        # wrong token: closed the same way
        s = _socket.create_connection((host, int(port)), timeout=10)
        s.sendall(_auth_blob("wrong"))
        try:
            s.settimeout(10)
            assert s.recv(1) == b""
        except OSError:
            pass
        s.close()
        # right token: full init/pull roundtrip works
        conn = _ServerConn(srv.address, token="sekrit")
        conn.request("init", "w", np.ones(3, "f"))
        reply = conn.request("pull", "w")
        np.testing.assert_allclose(reply[1], np.ones(3, "f"))
        conn.close()
    finally:
        srv.stop()


def test_scalar_and_edge_row_ids():
    """Rank-0 params round-trip (regression: part slicing must not index
    a 0-d array); out-of-range row_ids raise; empty row_ids are a valid
    no-rows pull."""
    from mxtpu.ndarray.sparse import row_sparse_array
    kv = mx.kv.create("dist_async")
    try:
        kv.init("s", mx.nd.array(3.0))
        kv.push("s", mx.nd.array(1.0))
        out = mx.nd.array(0.0)
        kv.pull("s", out=out)
        assert float(out.asnumpy()) == 4.0
        kv.init("t", mx.nd.array(np.arange(12, dtype="f").reshape(4, 3)))
        with pytest.raises(IndexError, match="out of range"):
            kv.row_sparse_pull("t", out=mx.nd.zeros((1, 3)),
                               row_ids=mx.nd.array([7]))
        rsp = row_sparse_array((np.zeros((1, 3), "f"), [0]), shape=(4, 3))
        kv.row_sparse_pull("t", out=rsp, row_ids=mx.nd.array([], dtype="f"))
        assert np.all(rsp.asnumpy() == 0)
    finally:
        kv.close()


@pytest.mark.slow
def test_realistic_volume_straggler():
    """The async property at real parameter scale (round-4 verdict: the
    service's throughput at ~100 MB/step was unmeasured): one worker
    streams a 33 MB parameter's push/pull rounds flat out while a
    straggler sleeps each step. Big parted pushes must not serialize the
    fleet — the fast worker completes several times more rounds, the
    server observes staleness, and every push still lands exactly once."""
    server = ParameterServer().start()
    stores = []
    try:
        saved = _patched_env(_worker_env(server.address, 0, 2))
        try:
            kv0 = mx.kv.create("dist_async")
            stores.append(kv0)
            os.environ["MXTPU_PROC_ID"] = "1"
            kv1 = mx.kv.create("dist_async")
            stores.append(kv1)
        finally:
            _restore_env(saved)
        shape = (1792, 4608)           # ~33 MB fp32, parts at the 1e6 bound
        t = threading.Thread(
            target=lambda: kv1.init("wbig", mx.nd.zeros(shape)))
        t.start()
        kv0.init("wbig", mx.nd.zeros(shape))
        t.join()
        assert len(kv0._parts["wbig"]) >= 8

        g = mx.nd.ones(shape)
        counts = {}

        # calibrate: one uncontended round, so the straggler's sleep
        # dominates per-round time whatever this host's speed is
        w0 = mx.nd.zeros(shape)
        t0 = time.time()
        kv0.pull("wbig", out=w0)
        kv0.push("wbig", g)
        round_s = time.time() - t0
        sleep_s = max(0.5, 4 * round_s)
        budget = max(6.0, 6 * sleep_s)

        def run(kv, rank, sleep):
            w = mx.nd.zeros(shape)
            n = 0
            deadline = time.time() + budget
            while time.time() < deadline:
                kv.pull("wbig", out=w)
                kv.push("wbig", g)
                n += 1
                if sleep:
                    time.sleep(sleep)
            counts[rank] = n

        th = [threading.Thread(target=run, args=(kv, r, sleep_s * r))
              for r, kv in enumerate(stores)]
        for x in th:
            x.start()
        for x in th:
            x.join()
        assert counts[0] >= 2 * counts[1], counts
        stats = stores[0].staleness_stats()
        assert stats["staleness_max"] > 0, stats
        # accumulate-only server: the table holds exactly
        # (total pushes) * 1.0 in every element — big parted pushes
        # neither dropped nor double-applied
        out = mx.nd.zeros(shape)
        stores[0].pull("wbig", out=out)
        total = counts[0] + counts[1] + 1   # +1: the calibration round
        got = out.asnumpy()
        assert got[0, 0] == total and got[-1, -1] == total, \
            (got[0, 0], got[-1, -1], total)
    finally:
        for kv in stores:
            kv.close()
        server.stop()


def test_push_pull_one_round_trip():
    """kv.push_pull (the fused pushpull wire op, ISSUE 10): apply +
    read-back in one request — accumulate server: the returned value
    is the post-apply table, the clock advances exactly once."""
    kv = mx.kv.create("dist_async")
    try:
        kv.init("w", mx.nd.ones((3,)))
        out = mx.nd.zeros((3,))
        kv.push_pull("w", mx.nd.ones((3,)), out=out)
        np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(3))
        kv.push_pull("w", mx.nd.ones((3,)) * 3, out=out)
        np.testing.assert_allclose(out.asnumpy(), 5 * np.ones(3))
        srv = kv._own_server
        assert srv._clock["w"] == 2
    finally:
        kv.close()


def test_push_pull_big_array_parts():
    """push_pull splits big arrays into the same row parts as
    push/pull and reassembles the returned post-update value exactly."""
    from mxtpu import kvstore_async as ka
    old = ka._BIGARRAY_BOUND
    ka._BIGARRAY_BOUND = 1000
    try:
        kv = mx.kv.create("dist_async")
        r = np.random.RandomState(1)
        w = r.rand(40, 100).astype("f")
        g = r.rand(40, 100).astype("f")
        kv.init("big", mx.nd.array(w))
        assert len(kv._parts["big"]) == 4
        out = mx.nd.zeros(w.shape)
        kv.push_pull("big", mx.nd.array(g), out=out)
        np.testing.assert_allclose(out.asnumpy(), w + g, rtol=1e-6)
        kv.close()
    finally:
        ka._BIGARRAY_BOUND = old


def test_push_pull_server_side_optimizer():
    """With a server-side updater, push_pull returns the POST-UPDATE
    weights (what the fused Module dist step rebinds its parameter
    store with) — matching a separate push-then-pull bit-for-bit."""
    from mxtpu import optimizer as opt
    kv = mx.kv.create("dist_async")
    kv2 = mx.kv.create("dist_async")
    try:
        for k in (kv, kv2):
            k.set_optimizer(opt.SGD(learning_rate=0.5, momentum=0.9,
                                    rescale_grad=1.0))
        w0 = np.arange(6, dtype="f").reshape(2, 3)
        g = np.ones((2, 3), "f")
        kv.init("w", mx.nd.array(w0))
        kv2.init("w", mx.nd.array(w0))
        a, b = mx.nd.zeros((2, 3)), mx.nd.zeros((2, 3))
        for _ in range(3):
            kv.push_pull("w", mx.nd.array(g), out=a)
            kv2.push("w", mx.nd.array(g))
            kv2.pull("w", out=b)
            np.testing.assert_array_equal(a.asnumpy(), b.asnumpy())
    finally:
        kv.close()
        kv2.close()
