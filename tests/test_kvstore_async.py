"""dist_async parameter service (reference async mode,
src/kvstore/kvstore_dist_server.h:339,462: pushes applied immediately
server-side, no merge barrier — staleness traded for straggler
tolerance). Fast in-process tier; the multi-process straggler
demonstration is tests/nightly/async_worker.py via the local launcher."""
import os
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu.kvstore_async import AsyncDistKVStore, ParameterServer


def test_create_returns_async_store():
    kv = mx.kv.create("dist_async")
    try:
        assert isinstance(kv, AsyncDistKVStore)
        assert kv.type == "dist_async"
    finally:
        kv.close()


def test_server_side_optimizer_applies_each_push():
    kv = mx.kv.create("dist_async")
    try:
        kv.init(3, mx.nd.zeros((2, 3)))
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.5))
        kv.push(3, mx.nd.ones((2, 3)))
        kv.push(3, mx.nd.ones((2, 3)))
        out = mx.nd.zeros((2, 3))
        kv.pull(3, out=out)
        # two sequential updates, each applied on arrival: w = 0 - .5 - .5
        np.testing.assert_allclose(out.asnumpy(), -np.ones((2, 3)))
        assert kv.staleness_stats()["pushes"] == 2
    finally:
        kv.close()


def test_push_without_updater_accumulates():
    kv = mx.kv.create("dist_async")
    try:
        kv.init("a", mx.nd.array(np.arange(4, dtype="f")))
        kv.push("a", mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        kv.pull("a", out=out)
        np.testing.assert_allclose(out.asnumpy(),
                                   np.arange(4, dtype="f") + 1)
    finally:
        kv.close()


def test_list_push_merges_locally_before_send():
    kv = mx.kv.create("dist_async")
    try:
        kv.init("k", mx.nd.zeros((3,)))
        kv.push("k", [mx.nd.ones((3,)), mx.nd.ones((3,)) * 2])
        out = mx.nd.zeros((3,))
        kv.pull("k", out=out)
        np.testing.assert_allclose(out.asnumpy(), 3 * np.ones(3))
        # one wire push for the merged device shards
        assert kv.staleness_stats()["clocks"]["k"] == 1
    finally:
        kv.close()


def test_uninitialized_key_errors():
    kv = mx.kv.create("dist_async")
    try:
        with pytest.raises(RuntimeError, match="uninitialized"):
            kv.push("missing", mx.nd.ones((2,)))
        with pytest.raises(RuntimeError, match="uninitialized"):
            kv.pull("missing", out=mx.nd.zeros((2,)))
        with pytest.raises(NotImplementedError):
            kv.row_sparse_pull("missing", out=mx.nd.zeros((2,)),
                               row_ids=mx.nd.array([0]))
    finally:
        kv.close()


def _worker_env(addr, rank, nproc):
    return {"MXTPU_PS_ADDRS": addr, "MXTPU_PROC_ID": str(rank),
            "MXTPU_NUM_PROCS": str(nproc)}


def _patched_env(env):
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    return saved


def _restore_env(saved):
    for k, v in saved.items():
        if v is None:
            os.environ.pop(k, None)
        else:
            os.environ[k] = v


def test_concurrent_workers_interleave_with_staleness():
    """Two 'workers' (threads with their own stores/clocks) against one
    shared server: pushes interleave without any barrier and the server
    observes staleness > 0 — the async property itself."""
    server = ParameterServer().start()
    stores = []
    try:
        saved = _patched_env(_worker_env(server.address, 0, 2))
        try:
            kv0 = mx.kv.create("dist_async")
            stores.append(kv0)
            os.environ["MXTPU_PROC_ID"] = "1"
            kv1 = mx.kv.create("dist_async")
            stores.append(kv1)
        finally:
            _restore_env(saved)
        # manual init: barrier needs both workers, run init concurrently
        t = threading.Thread(
            target=lambda: kv1.init("w", mx.nd.zeros((4,))))
        t.start()
        kv0.init("w", mx.nd.zeros((4,)))
        t.join()

        n_steps = {0: 40, 1: 40}
        def run(kv, rank):
            w = mx.nd.zeros((4,))
            for _ in range(n_steps[rank]):
                kv.pull("w", out=w)
                kv.push("w", mx.nd.ones((4,)) * 0.01)
        th = [threading.Thread(target=run, args=(kv, r))
              for r, kv in enumerate(stores)]
        for x in th:
            x.start()
        for x in th:
            x.join()
        stats = stores[0].staleness_stats()
        assert stats["pushes"] == 80
        assert stats["staleness_max"] > 0, stats
        out = mx.nd.zeros((4,))
        stores[0].pull("w", out=out)
        # no updater: every push accumulated exactly once, stale or not
        np.testing.assert_allclose(out.asnumpy(), 0.01 * 80 * np.ones(4),
                                   rtol=1e-5)
    finally:
        for kv in stores:
            kv.close()
        server.stop()


def test_key_sharding_across_servers():
    s1, s2 = ParameterServer().start(), ParameterServer().start()
    saved = _patched_env(_worker_env(
        s1.address + "," + s2.address, 0, 1))
    try:
        kv = mx.kv.create("dist_async")
        keys = ["k%d" % i for i in range(8)]
        for k in keys:
            kv.init(k, mx.nd.ones((2,)))
            kv.push(k, mx.nd.ones((2,)))
        # every key landed on exactly one server; union covers all keys
        c1 = s1._clock
        c2 = s2._clock
        assert not (set(c1) & set(c2))
        assert set(c1) | set(c2) == set(keys)
        out = mx.nd.zeros((2,))
        for k in keys:
            kv.pull(k, out=out)
            np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(2))
        kv.close()
    finally:
        _restore_env(saved)
        s1.stop()
        s2.stop()


def test_module_fit_through_dist_async():
    """Module.fit with kvstore='dist_async': grads push to the parameter
    service, SGD runs server-side (update_on_kvstore), weights pull back
    — the reference's async training loop shape, single-process."""
    r = np.random.RandomState(5)
    y = (r.rand(192) * 4).astype("f")
    x = r.rand(192, 16).astype("f") * 0.1
    for i in range(192):
        x[i, int(y[i]) * 4:int(y[i]) * 4 + 4] += 1.0
    it = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                           label_name="softmax_label")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4),
        name="softmax")
    mod = mx.mod.Module(sym)
    mod.fit(it, num_epoch=4, kvstore="dist_async", optimizer="sgd",
            optimizer_params={"learning_rate": 0.5})
    acc = dict(mod.score(it, "acc"))["accuracy"]
    assert acc > 0.9, acc
    # the optimizer really ran server-side: pushes were counted there
    kv = mod._kvstore
    stats = kv.staleness_stats()
    assert stats["pushes"] >= 4 * 6 * 2  # epochs * batches * params
    kv.close()
