"""dist_async straggler demonstration (reference async mode,
src/kvstore/kvstore_dist_server.h:339,462: servers apply pushes
immediately, workers never wait for each other).

Launched by tools/launch.py -n 3 -s 2 --launcher local. Every worker runs
independent SGD-through-the-server steps on the same least-squares
problem for a fixed wall-time budget; rank 0 is an injected straggler
(sleeps each step). Asserts the three properties sync mode cannot
produce:

1. progress under the straggler — fast workers complete several times
   more pushes than the straggler in the same wall time;
2. observed gradient staleness > 0 (server-side clocks);
3. the model still converges (stale-gradient SGD on a convex problem).
"""
import json
import os
import sys
import time

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu as mx                                           # noqa: E402

rank = int(os.environ["MXTPU_PROC_ID"])
nproc = int(os.environ["MXTPU_NUM_PROCS"])
out_dir = os.environ["ASYNC_TEST_DIR"]

kv = mx.kv.create("dist_async")
assert kv.type == "dist_async"
assert kv.rank == rank and kv.num_workers == nproc

# init broadcasts rank 0's value and barriers internally (reference
# KVStoreDist::InitImpl); set_optimizer installs the server-side updater
# from rank 0 and barriers before any push can race it
wt = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
kv.init("w", mx.nd.zeros((4,)))
kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))

rng = np.random.RandomState(1234 + rank)    # different data per worker
X = rng.standard_normal((256, 4)).astype(np.float32)
y = X @ wt

w = mx.nd.zeros((4,))
deadline = time.time() + 6.0
pushes = 0
while time.time() < deadline:
    kv.pull("w", out=w)
    wn = w.asnumpy()
    i = np.random.randint(0, 256 - 32)
    Xb, yb = X[i:i + 32], y[i:i + 32]
    g = 2 * Xb.T @ (Xb @ wn - yb) / 32
    kv.push("w", mx.nd.array(g))
    pushes += 1
    if rank == 0:
        time.sleep(0.05)        # the injected straggler

with open(os.path.join(out_dir, "rank%d.json" % rank), "w") as f:
    json.dump({"rank": rank, "pushes": pushes}, f)

# all workers drain before reading global stats / final weights
kv.barrier()

if rank == 0:
    stats = kv.staleness_stats()
    kv.pull("w", out=w)
    final = w.asnumpy()
    counts = {}
    for r in range(nproc):
        with open(os.path.join(out_dir, "rank%d.json" % r)) as f:
            counts[r] = json.load(f)["pushes"]
    fast = min(counts[r] for r in range(1, nproc))
    assert fast >= 3 * counts[0], \
        "straggler blocked the fleet: %r" % (counts,)
    assert stats["staleness_max"] > 0, stats
    err = float(np.abs(final - wt).max())
    assert err < 0.15, (final, wt, err)
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump({"counts": counts, "staleness": stats,
                   "final_err": err}, f)
print("RANK_%d_OK" % rank, flush=True)
