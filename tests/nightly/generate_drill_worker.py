"""Continuous-batching generation drill worker (ISSUE 17 acceptance;
driven by tests/test_dist_launch.py::test_generate_kill_and_swap_drill
through tools/launch.py -n 2 --serve 2 --serve-respawn).

Rank 0 — the PUBLISHER: loads the served LM checkpoint, publishes it
as pinned weight version 1, then keeps publishing deterministically
perturbed versions — live hot-swaps landing under sustained
generation.

Rank 1 — the DRIVER: concurrent client threads stream generate2
sequences at the replica fleet while versions swap underneath and the
harness kill -9s replica 0 mid-stream. Every sequence records its
streamed token frames (idx, tok, version) plus the terminal info; the
driver then verifies the three ISSUE 17 acceptance properties from
the records alone:

  * exactly-once: each sequence's frame indices are 0..n-1, each
    seen once, in order — across the kill, the failover replay and
    any dropped partials;
  * zero torn sequences: every frame of one sequence carries ONE
    weight version, the one the terminal info reports;
  * oracle match: for each (prompt, version) the driver recomputes
    the greedy continuation LOCALLY by full re-prefill from the
    weight-dir snapshot of that exact version — the served tokens
    must match bit-for-bit.

Coordination is file-based in GEN_TEST_DIR (driver_ready,
trainer_done.json); the driver's progress file counts finished
sequences ONCE >= 2 weight versions have answered — the external
kill -9 trigger, so the kill lands with swaps already in flight.
"""
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

OUT_DIR = os.environ["GEN_TEST_DIR"]
PROGRESS = os.environ.get("GEN_PROGRESS_FILE")
ROUNDS = int(os.environ.get("GEN_PUBLISH_ROUNDS", "3"))
MAX_NEW = int(os.environ.get("GEN_DRILL_MAX_NEW", "10"))
# fixed prompt pool: lengths 3..6 so prompt + MAX_NEW - 1 stays inside
# the largest prefill bucket (16) for the oracle's full re-prefill
PROMPTS = [(1, 2, 3), (4, 5, 6, 7), (8, 9, 10, 11, 12),
           (13, 14, 15, 1, 2, 3)]


def _wait_for(path, timeout=180.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return False


def run_publisher():
    import mxtpu as mx
    from mxtpu.serving import WeightPublisher

    prefix = os.environ["MXTPU_SERVE_MODEL"]
    epoch = int(os.environ.get("MXTPU_SERVE_EPOCH", "0"))
    _sym, arg_params, _aux = mx.model.load_checkpoint(prefix, epoch)
    params = {n: v.asnumpy() for n, v in arg_params.items()}

    pub = WeightPublisher(os.environ["MXTPU_SERVE_WEIGHT_DIR"])
    out = pub.publish(params, pin=True, meta={"round": 0})
    print("publisher pinned v%d digest=%s"
          % (out["version"], out["digest"][:12]), flush=True)

    if not _wait_for(os.path.join(OUT_DIR, "driver_ready")):
        print("publisher: driver never became ready", flush=True)
        return 1

    versions = [out["version"]]
    for round_i in range(1, ROUNDS + 1):
        # a deterministic nudge per round: the driver recomputes each
        # version's decode from the SNAPSHOT, so any perturbation works
        # as long as it changes the argmax chain now and then
        rng = np.random.RandomState(1000 + round_i)
        params = {n: a + 0.05 * rng.randn(*a.shape).astype(a.dtype)
                  for n, a in params.items()}
        out = pub.publish(params, meta={"round": round_i})
        if out is None:
            continue
        versions.append(out["version"])
        print("publisher v%d" % out["version"], flush=True)
        time.sleep(float(os.environ.get("GEN_PUBLISH_GAP", "1.5")))

    done = {"final_version": versions[-1], "versions": versions}
    with open(os.path.join(OUT_DIR, "trainer_done.json"), "w") as f:
        json.dump(done, f)
    print("RANK_0_OK", flush=True)
    return 0


def _oracle_tokens(sym, params, prompt, n):
    """The greedy continuation recomputed WITHOUT the serving decode
    path: one full prefill per token on the growing prompt, reading
    the model's next-token pick fresh each time — an independent
    reference the engine's cached single-token decode must match."""
    from mxtpu.serving import InferenceEngine
    eng = InferenceEngine(sym, params, {}, data_shapes={"data": (1,)},
                          buckets=(1,), warm=False)
    pvals, avals, _v = eng._resolve_store(None)
    toks = list(prompt)
    out = []
    for _ in range(n):
        first, _rows = eng.gen_prefill(
            np.asarray(toks, np.int32), pvals, avals)
        nxt = int(np.asarray(first).reshape(-1)[0])
        out.append(nxt)
        toks.append(nxt)
    return out


def run_driver():
    import mxtpu as mx
    from mxtpu.checkpoint import CheckpointManager
    from mxtpu.serving import ServingClient

    addrs = [a for a in os.environ["MXTPU_SERVE_ADDRS"].split(",")
             if a]
    cli = ServingClient(addrs=addrs, budget_ms=30000)
    deadline = time.time() + 180
    while True:
        try:
            cli.hello()
            break
        except ConnectionError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)

    # wait until the fleet swapped to the pinned published version —
    # fresh replicas still answer from ctor version 0
    deadline = time.time() + 180
    while True:
        toks, info = cli.generate2(PROMPTS[0], max_new=2)
        if info["version"] >= 1:
            break
        if time.time() > deadline:
            raise AssertionError(
                "fleet never reached v1 (still %r)" % info)
        time.sleep(0.2)
    with open(os.path.join(OUT_DIR, "driver_ready"), "w") as f:
        f.write("ok")
    print("driver saw v%d, streaming" % info["version"], flush=True)

    lock = threading.Lock()
    state = {"records": [], "errors": [], "versions": set(),
             "client_stats": []}
    stop = threading.Event()

    def pound(seed):
        rng = np.random.RandomState(seed)
        c = ServingClient(addrs=addrs, budget_ms=30000)
        while not stop.is_set():
            prompt = PROMPTS[rng.randint(len(PROMPTS))]
            frames = []
            try:
                toks, inf = c.generate2(
                    prompt, max_new=MAX_NEW,
                    on_token=lambda i, t, v: frames.append((i, t, v)))
                rec = {"prompt": list(prompt), "toks": toks,
                       "version": inf["version"],
                       "reason": inf["reason"], "frames": frames}
                with lock:
                    state["records"].append(rec)
                    state["versions"].add(inf["version"])
                    n, nv = len(state["records"]), \
                        len(state["versions"])
            except Exception as e:       # noqa: BLE001 — recorded
                with lock:
                    state["errors"].append(repr(e))
                    n, nv = len(state["records"]), \
                        len(state["versions"])
            if PROGRESS and nv >= 2:
                # the kill -9 trigger: counts only once hot-swaps are
                # in flight, so the kill lands mid-rollout mid-stream
                try:
                    with open(PROGRESS + ".tmp", "w") as f:
                        f.write(str(n))
                    os.replace(PROGRESS + ".tmp", PROGRESS)
                except OSError:
                    pass
        with lock:
            state["client_stats"].append(c.stats())
        c.close()

    threads = [threading.Thread(target=pound, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()

    # stream until the publisher finished AND the fleet's answers
    # reached the final version (swaps really landed under load)
    done_path = os.path.join(OUT_DIR, "trainer_done.json")
    assert _wait_for(done_path, timeout=300), "publisher never finished"
    with open(done_path) as f:
        done = json.load(f)
    final_v = int(done["final_version"])
    deadline = time.time() + 120
    while time.time() < deadline:
        with lock:
            seen = set(state["versions"])
        if final_v in seen:
            break
        time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=120)

    with lock:
        records = list(state["records"])
        errors = list(state["errors"])
        versions = sorted(state["versions"])

    # -- acceptance property 1+2: exactly-once frames, zero torn -------
    torn = []
    not_exactly_once = []
    for i, rec in enumerate(records):
        idxs = [f[0] for f in rec["frames"]]
        if idxs != list(range(len(rec["toks"]))) \
                or [f[1] for f in rec["frames"]] != rec["toks"]:
            not_exactly_once.append((i, rec))
        vers = {f[2] for f in rec["frames"]}
        if vers - {rec["version"]}:
            torn.append((i, rec))

    # -- acceptance property 3: the oracle recompute -------------------
    # rebuild each answering version's greedy continuation from its
    # weight-dir SNAPSHOT and diff the served tokens bit-for-bit
    prefix = os.environ["MXTPU_SERVE_MODEL"]
    epoch = int(os.environ.get("MXTPU_SERVE_EPOCH", "0"))
    sym, _ap, _aux = mx.model.load_checkpoint(prefix, epoch)
    cm = CheckpointManager(os.environ["MXTPU_SERVE_WEIGHT_DIR"],
                           max_to_keep=0, async_save=False,
                           use_orbax=False)
    expected = {}
    mismatches = []
    for rec in records:
        key = (tuple(rec["prompt"]), rec["version"])
        if key not in expected:
            tree = cm.restore_exact(rec["version"])
            assert tree is not None, \
                "version %d has no snapshot" % rec["version"]
            expected[key] = _oracle_tokens(
                sym, tree["params"], rec["prompt"], MAX_NEW)
        if rec["toks"] != expected[key]:
            mismatches.append({"prompt": rec["prompt"],
                               "version": rec["version"],
                               "served": rec["toks"],
                               "oracle": expected[key]})

    # the kill's client-side story lives in the POUND threads' own
    # clients: sum their counters (the probe client barely routes)
    with lock:
        per_client = list(state["client_stats"])
    agg = {}
    for s in per_client + [cli.stats()]:
        for k, v in s.items():
            if isinstance(v, (int, float)):
                agg[k] = agg.get(k, 0) + v
    cli.close()
    summary = {
        "answered": len(records),
        "errors": errors,
        "versions": versions,
        "final_version": final_v,
        "exactly_once": not not_exactly_once,
        "torn": [i for i, _ in torn],
        "sequences_by_version": {
            str(v): sum(1 for r in records if r["version"] == v)
            for v in versions},
        "oracle": {"checked": len(records),
                   "distinct": len(expected),
                   "mismatches": mismatches},
        "client": agg,
    }
    with open(os.path.join(OUT_DIR, "summary.json"), "w") as f:
        json.dump(summary, f, default=str)
    print("DRIVER_OK answered=%d versions=%s oracle=%d/%d"
          % (len(records), versions, len(records) - len(mismatches),
             len(records)), flush=True)
    print("RANK_1_OK", flush=True)
    return 0


def main():
    rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
    os.makedirs(OUT_DIR, exist_ok=True)
    if rank == 0:
        return run_publisher()
    return run_driver()


if __name__ == "__main__":
    sys.exit(main())
