"""Serving client driver for the kill -9 failover end-to-end test.

Launched by ``tools/launch.py -n 1 --serve 2 ...`` as the worker
command: the two serving replicas it talks to are REAL processes
(``python -m mxtpu.serving``), and the test harness kill -9s one of
them mid-run by parsing the launcher's ``serve replica I pid=P`` line.

The driver fires SERVING_TOTAL_REQUESTS single-row predicts from
SERVING_CLIENT_THREADS concurrent threads through one shared
:class:`mxtpu.serving.ServingClient`. Request i's payload derives from
a fixed seed, every answer is recorded by request index, and a progress
file counts completions so the harness can time its kill. Retriable
sheds back off and retry (bounded), so the only terminal outcomes are
an answer or a hard error.

Because the replicas serve a SINGLE batch bucket, a request's bits do
not depend on which batch composition it coalesced into
(docs/serving.md "Determinism") — so the response table of a killed run
must match an uninterrupted run's BIT FOR BIT, which is exactly what
tests/test_dist_launch.py::test_serving_replica_kill_matches_uninterrupted
asserts, along with the exactly-once delivery accounting and the
failover/batching counters in the summary.

Env: SERVING_TEST_DIR (output), SERVING_PROGRESS_FILE,
SERVING_TOTAL_REQUESTS (default 40), SERVING_CLIENT_THREADS (default
4), SERVING_REQUEST_SLEEP (pacing seconds, default 0.02).
"""
import json
import os
import sys
import threading
import time

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu  # noqa: F401,E402  (package init)
from mxtpu.serving import Overloaded, ServingClient          # noqa: E402

IN_DIM = 6
out_dir = os.environ["SERVING_TEST_DIR"]
progress_path = os.environ.get("SERVING_PROGRESS_FILE")
total = int(os.environ.get("SERVING_TOTAL_REQUESTS", "40"))
n_threads = int(os.environ.get("SERVING_CLIENT_THREADS", "4"))
pacing = float(os.environ.get("SERVING_REQUEST_SLEEP", "0.02"))


def main():
    cli = ServingClient(budget_ms=10000.0)   # MXTPU_SERVE_ADDRS from env
    info = cli.hello()
    answers = {}
    delivered = {}
    errors = {}
    done = [0]
    lock = threading.Lock()

    def one(i):
        x = (np.arange(IN_DIM, dtype="f").reshape(1, IN_DIM)
             * 0.01 + i * 0.1)
        for attempt in range(20):
            try:
                out = cli.predict(x)[0]
            except Overloaded:
                time.sleep(0.05)             # retriable: back off, retry
                continue
            except Exception as e:
                with lock:
                    errors[i] = "%s: %s" % (type(e).__name__, e)
                return
            with lock:
                answers[i] = out
                delivered[i] = delivered.get(i, 0) + 1
                done[0] += 1
                n = done[0]
                if progress_path:
                    # written under the lock: concurrent writers would
                    # race each other's tmp-and-rename
                    tmp = progress_path + ".tmp"
                    with open(tmp, "w") as f:
                        f.write(str(n))
                    os.replace(tmp, progress_path)
            return
        with lock:
            errors[i] = "shed on every retry"

    def runner(tid):
        for i in range(tid, total, n_threads):
            try:
                one(i)
            except BaseException as e:       # a lost request must be
                with lock:                   # visible, never silent
                    errors.setdefault(i, "runner: %s: %s"
                                      % (type(e).__name__, e))
            if pacing:
                time.sleep(pacing)

    threads = [threading.Thread(target=runner, args=(t,))
               for t in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=300)

    # the surviving replica's server-side story (stats() counters)
    server_stats = None
    for addr in cli.stats()["replicas"]:
        try:
            server_stats = cli.server_stats(addr)
            break
        except (ConnectionError, RuntimeError, OSError):
            continue

    np.savez(os.path.join(out_dir, "answers.npz"),
             **{"r%03d" % i: v for i, v in answers.items()})
    summary = {
        "total": total,
        "answered": len(answers),
        "errors": errors,
        "exactly_once": all(n == 1 for n in delivered.values()),
        "client": {k: v for k, v in cli.stats().items()
                   if k not in ("comms",)},
        "replicas_learned": sorted(cli.stats()["replicas"]),
        "hello_model": info.get("model"),
        "server": {
            "counters": server_stats["counters"] if server_stats else None,
            "batcher": server_stats["batcher"] if server_stats else None,
        },
    }
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f)
    cli.close()
    if errors:
        print("CLIENT_ERRORS %r" % errors, flush=True)
        return 1
    print("CLIENT_OK answered=%d" % len(answers), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
