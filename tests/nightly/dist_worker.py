"""Multi-process SPMD worker (reference tests/nightly/dist_sync_kvstore.py,
launched by tools/launch.py --launcher local).

Each process initializes jax.distributed from the launcher's env, builds
a global mesh over all processes' CPU devices, and runs (a) a psum
all-reduce, (b) a tiny data-parallel training step — asserting both are
bitwise identical across processes (the dist_sync property the reference
nightly checks via kvstore push/pull).
"""
import os
import sys

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

nproc = int(os.environ["MXTPU_NUM_PROCS"])
rank = int(os.environ["MXTPU_PROC_ID"])

# the mxtpu import itself joins the process group from the launcher env
# (the reference bootstraps in kv create; see mxtpu/__init__.py) — no
# explicit jax.distributed.initialize here, that's part of the contract
# under test
sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu as mx                                           # noqa: E402

assert jax.process_count() == nproc, jax.process_count()
assert jax.process_index() == rank

import jax.numpy as jnp                                     # noqa: E402
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P  # noqa: E402
from mxtpu.parallel.mesh import shard_map                   # noqa: E402

devs = jax.devices()          # all processes' devices, DCN-addressable
assert len(devs) >= nproc
mesh = Mesh(np.array(devs), ("x",))
sharding = NamedSharding(mesh, P("x"))

# (a) cross-process psum: every process contributes rank+1
n = len(devs)
host = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
x = jax.make_array_from_callback(
    (n, 4), sharding, lambda idx: host[idx])
f = jax.jit(shard_map(lambda v: jax.lax.psum(v, "x"), mesh=mesh,
                      in_specs=P("x"), out_specs=P("x"), check_vma=False))
red = f(x)
expect = host.sum(axis=0)
got = np.asarray(jax.device_get(red.addressable_shards[0].data))[0]
np.testing.assert_allclose(got, expect)

# (b) data-parallel least-squares step: grads psum'd over the mesh
w = jnp.zeros((4,))
rng = np.random.RandomState(0)          # same data everywhere; shards split
X = rng.standard_normal((n * 8, 4)).astype(np.float32)
wt = np.array([1.0, -2.0, 3.0, 0.5], np.float32)
y = X @ wt
Xg = jax.make_array_from_callback((n * 8, 4), sharding,
                                  lambda idx: X[idx])
yg = jax.make_array_from_callback((n * 8,), sharding, lambda idx: y[idx])


@jax.jit
def step(w, Xl, yl):
    def local(w, Xs, ys):
        g = 2 * Xs.T @ (Xs @ w - ys) / (n * 8)
        return jax.lax.psum(g, "x")
    g = shard_map(local, mesh=mesh,
                  in_specs=(P(), P("x"), P("x")), out_specs=P(),
                  check_vma=False)(w, Xl, yl)
    return w - 0.05 * g


for _ in range(200):
    w = step(w, Xg, yg)
w_np = np.asarray(jax.device_get(w))
np.testing.assert_allclose(w_np, wt, atol=2e-2)

# (c) kvstore facade semantics across processes (reference
# tests/nightly/dist_sync_kvstore.py): init broadcasts rank 0's value,
# push SUMS each worker's contribution across all workers before the
# updater applies, pull returns the identical merged state everywhere.
# (dist_async is a real parameter-server mode now — it needs launcher
# -s N server processes and has its own straggler nightly,
# tests/nightly/async_worker.py; only the sync contract is checked here)
kv = mx.kvstore.create("dist_sync")
assert kv.type == "dist_sync"
assert kv.rank == rank and kv.num_workers == nproc, \
    (kv.rank, kv.num_workers)
updates = []
# rank-varying init value: the broadcast must make rank 0's win
kv.init(9, mx.nd.ones((3,)) * (1 + rank * 100))


def updater(key, recv, local, _log=updates):
    _log.append(int(key))
    local[:] = local - 0.1 * recv


kv._set_updater(updater)
kv.push(9, mx.nd.ones((3,)) * (rank + 1))
out = mx.nd.zeros((3,))
kv.pull(9, out=out)
# updater applied exactly once per push (the reference's server-side
# merge-then-apply, kvstore_dist_server.h:279-339)
assert updates == [9], updates
# merged push = sum over workers of (rank+1); init = rank 0's ones
expect_kv = 1.0 - 0.1 * sum(r + 1 for r in range(nproc))
np.testing.assert_allclose(out.asnumpy(),
                           np.full((3,), expect_kv, np.float32),
                           rtol=1e-6)
kv.barrier()

print("RANK_%d_OK nprocs=%d ndevices=%d" % (rank, nproc, n))
