"""Elastic scale-out worker for the E2E drill (ISSUE 7).

Launched by tools/launch.py -n 1 -s 2 with MXTPU_PS_ELASTIC=1 and a
--scale schedule that, mid-run, ADDS a worker (MXTPU_ELASTIC_JOINER=1),
SPLITS server 0's hot keys onto a freshly spawned server, and REMOVES
the added worker again (SIGTERM = clean departure).

The training problem is async_worker.py's least-squares SGD, widened to
six independent keys so the split has a population to halve. The crucial
structural difference from every earlier nightly: NOTHING here slices
data by rank/size. All data flow comes from the server-owned shard
cursor — ``kv.shard_cursor(epoch, NUM_SHARDS)`` — so however many
workers exist at any instant, each (epoch, shard, batch) is processed by
exactly one CLEANLY-finishing worker, and the batch content is a pure
function of (epoch, shard, batch). That makes the fleet-wide work total
exact: every key's server-side clock must end at EPOCHS x SHARDS x
BATCHES regardless of joins, leaves, splits, or map_stale reroutes —
the zero-acknowledged-update-loss + exactly-once invariant in one
integer.

Rank 0 is the anchor: it inits keys, installs the server-side optimizer,
writes the progress file the --scale schedule triggers on, and at the
end asserts the invariants and writes summary.json. A joiner pulls
current params (no init, no static barrier) and simply starts taking
shards. SIGTERM sets a flag checked between shards: the current shard is
finished and acknowledged before the bye, so clean departure never
inflates the work total.
"""
import json
import os
import signal
import sys

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu as mx                                           # noqa: E402

rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
joiner = os.environ.get("MXTPU_ELASTIC_JOINER", "0") == "1"
out_dir = os.environ["ELASTIC_TEST_DIR"]
progress_file = os.environ.get("ELASTIC_PROGRESS_FILE")

EPOCHS = int(os.environ.get("ELASTIC_EPOCHS", "3"))
SHARDS = int(os.environ.get("ELASTIC_SHARDS", "6"))
BATCHES = int(os.environ.get("ELASTIC_BATCHES", "4"))
# per-batch throttle so a --scale drill's wall-clock events land while
# training is still running (0 = flat out; the work TOTAL is identical
# either way, which is the whole point of the cursor)
BATCH_SLEEP = float(os.environ.get("ELASTIC_BATCH_SLEEP", "0"))
KEYS = ["w%d" % i for i in range(6)]      # w0..w3 -> server 0 (split
#                                           source), w4..w5 -> server 1
DIM = 4

# every batch is a pure function of its coordinates: whichever worker
# draws (epoch, shard, batch) computes the identical X
WT = {k: np.random.RandomState(500 + i).uniform(-2, 2, DIM)
         .astype(np.float32) for i, k in enumerate(KEYS)}


def batch_x(epoch, shard, b):
    rs = np.random.RandomState(100000 + epoch * 1009 + shard * 53 + b)
    return rs.standard_normal((32, DIM)).astype(np.float32)


stop = {"flag": False}
signal.signal(signal.SIGTERM,
              lambda *_: stop.__setitem__("flag", True))

kv = mx.kv.create("dist_async")

if not joiner:
    kv.init(KEYS, [mx.nd.zeros((DIM,)) for _ in KEYS])
    kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.05))
else:
    # the join contract: hello already registered us and taught us the
    # shard map; wait for the anchor's init, pull current params, go
    import time
    probe = mx.nd.zeros((DIM,))
    deadline = time.monotonic() + 120
    while True:
        try:
            kv.pull(KEYS[0], out=probe)
            break
        except (RuntimeError, ConnectionError):
            if time.monotonic() > deadline:
                raise
            time.sleep(0.2)
    print("worker %d joined mid-run (params pulled)" % rank, flush=True)

done_batches = 0


def note_progress():
    if progress_file and rank == 0:
        with open(progress_file + ".tmp", "w") as f:
            f.write(str(done_batches))
        os.replace(progress_file + ".tmp", progress_file)


w = {k: mx.nd.zeros((DIM,)) for k in KEYS}
for epoch in range(EPOCHS):
    if stop["flag"]:
        break
    for shard in kv.shard_cursor(epoch, SHARDS):
        for b in range(BATCHES):
            X = batch_x(epoch, shard, b)
            for k in KEYS:
                kv.pull(k, out=w[k])
                wn = w[k].asnumpy()
                g = 2 * X.T @ (X @ wn - X @ WT[k]) / len(X)
                kv.push(k, mx.nd.array(g))
            done_batches += 1
            note_progress()
            if BATCH_SLEEP:
                import time
                time.sleep(BATCH_SLEEP)
        # the shard is acknowledged when the generator resumes; only
        # AFTER that may a clean departure leave
    if stop["flag"]:
        break

if rank == 0:
    # everyone else drains (or has departed): the elastic barrier
    # counts the CURRENT membership, so nobody waits on a ghost
    kv.barrier()
    st = kv.stats()
    clocks = kv.staleness_stats()["clocks"]
    want = EPOCHS * SHARDS * BATCHES
    assert set(clocks) == set(KEYS), clocks
    bad = {k: v for k, v in clocks.items() if v != want}
    assert not bad, "work total broken (want %d everywhere): %r" \
        % (want, bad)
    final_err = 0.0
    for k in KEYS:
        kv.pull(k, out=w[k])
        final_err = max(final_err,
                        float(np.abs(w[k].asnumpy() - WT[k]).max()))
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump({"final_err": final_err,
                   "clocks": {k: int(v) for k, v in clocks.items()},
                   "elastic": st["elastic"],
                   "map_reroutes": st["map_reroutes"],
                   "membership_epochs": st["membership_epochs"],
                   "barrier_recounts": st["barrier_recounts"],
                   "barrier_timeouts": st["barrier_timeouts"]}, f)
elif not stop["flag"]:
    # a worker finishing naturally drains with the fleet; a REMOVED
    # worker skips the barrier — its bye is the departure, and the
    # elastic barrier re-counts the survivors without it
    kv.barrier()

kv.close()
print("RANK_%d_OK" % rank, flush=True)
