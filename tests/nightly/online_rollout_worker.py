"""Online-learning rollout drill worker (ISSUE 11 acceptance; driven by
tests/test_dist_launch.py::test_online_rollout_closes_train_serve_loop
through tools/launch.py -n 2 --serve 2 --serve-respawn).

Rank 0 — the TRAINER: loads the served checkpoint, publishes it as
pinned weight version 1, then actually trains (manual Module
forward/backward/update on a fixed synthetic task) and publishes a
fresh version after every round — the live train→serve stream.

Rank 1 — the DRIVER: concurrent closed-loop clients stream predicts at
the replica fleet while versions swap underneath; every reply records
the answering weight version. The driver probes each newly observed
version with a canonical batch, measures prediction quality
(cross-entropy against the task's true labels — it must IMPROVE
mid-stream), then drives a bit-exact rollback to pinned version 1 via
the rollout admin wire and diffs the probe bits against the ones
recorded at the start.

Coordination is file-based in ROLLOUT_TEST_DIR (driver_ready,
trainer_done.json); the driver's progress file counts answered
requests ONCE swaps are in flight — the external kill -9 trigger.
"""
import json
import os
import sys
import threading
import time

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

IN_DIM, CLASSES, BUCKET = 6, 3, 8
OUT_DIR = os.environ["ROLLOUT_TEST_DIR"]
PROGRESS = os.environ.get("ROLLOUT_PROGRESS_FILE")
ROUNDS = int(os.environ.get("ROLLOUT_TRAIN_ROUNDS", "3"))

# the shared synthetic task: a fixed linear teacher both ranks derive
# from the same seed (the trainer fits it, the driver scores against it)
_W_TRUE = np.random.RandomState(1234).randn(IN_DIM, CLASSES) \
    .astype("f")


def _labels(x):
    return np.argmax(x @ _W_TRUE, axis=1).astype("f")


def _eval_batch():
    x = np.random.RandomState(123).rand(BUCKET, IN_DIM).astype("f")
    return x, _labels(x).astype(int)


def _wait_for(path, timeout=120.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if os.path.exists(path):
            return True
        time.sleep(0.05)
    return False


def run_trainer():
    import mxtpu as mx
    from mxtpu.serving import WeightPublisher

    prefix = os.environ["MXTPU_SERVE_MODEL"]
    epoch = int(os.environ.get("MXTPU_SERVE_EPOCH", "0"))
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix,
                                                           epoch)
    mod = mx.mod.Module(sym, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (16, IN_DIM))],
             label_shapes=[("softmax_label", (16,))])
    mod.init_params(mx.init.Uniform(0.1))
    mod.set_params(arg_params, aux_params)
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5})

    pub = WeightPublisher(os.environ["MXTPU_SERVE_WEIGHT_DIR"])
    ap, _xp = mod.get_params()
    out = pub.publish({n: v.asnumpy() for n, v in ap.items()},
                      pin=True, meta={"round": 0})
    print("trainer published pinned v%d digest=%s"
          % (out["version"], out["digest"][:12]), flush=True)

    # the driver must record version 1's probe bits BEFORE v2 lands
    if not _wait_for(os.path.join(OUT_DIR, "driver_ready")):
        print("trainer: driver never became ready", flush=True)
        return 1

    rng = np.random.RandomState(42)
    x_all = rng.rand(512, IN_DIM).astype("f")
    y_all = _labels(x_all)
    versions = [out["version"]]
    for round_i in range(1, ROUNDS + 1):
        train_iter = mx.io.NDArrayIter(
            x_all, y_all, batch_size=16, shuffle=False,
            label_name="softmax_label")
        for _epoch in range(3):
            train_iter.reset()
            for batch in train_iter:
                mod.forward_backward(batch)
                mod.update()
        ap, _xp = mod.get_params()
        out = pub.publish({n: v.asnumpy() for n, v in ap.items()},
                          meta={"round": round_i})
        if out is None:
            continue
        versions.append(out["version"])
        print("trainer published v%d" % out["version"], flush=True)
        time.sleep(float(os.environ.get("ROLLOUT_PUBLISH_GAP", "1.5")))

    done = {"final_version": versions[-1], "versions": versions,
            "pinned": 1}
    with open(os.path.join(OUT_DIR, "trainer_done.json"), "w") as f:
        json.dump(done, f)
    print("RANK_0_OK", flush=True)
    return 0


def run_driver():
    from mxtpu.serving import RolloutController, ServingClient

    addrs = [a for a in os.environ["MXTPU_SERVE_ADDRS"].split(",")
             if a]
    cli = ServingClient(addrs=addrs, budget_ms=8000)
    deadline = time.time() + 120
    while True:
        try:
            cli.hello()
            break
        except ConnectionError:
            if time.time() > deadline:
                raise
            time.sleep(0.2)

    x_eval, y_eval = _eval_batch()
    lock = threading.Lock()
    state = {"answered": 0, "errors": [], "versions": set(),
             "probe_bits": {}, "loss_by_version": {}}

    def _ce(outs):
        p = np.clip(np.asarray(outs, "f"), 1e-9, 1.0)
        return float(-np.mean(np.log(p[np.arange(BUCKET), y_eval])))

    def _probe():
        """One canonical full-bucket probe; records bits + quality
        under whatever version ANSWERED (coherent by contract)."""
        outs, info = cli.predict2(x_eval)
        v = info["version"]
        with lock:
            state["versions"].add(v)
            state["probe_bits"].setdefault(v, np.asarray(outs[0]))
            state["loss_by_version"].setdefault(v, _ce(outs[0]))
        return v

    # pin down version 1's bits before releasing the trainer (the
    # replicas may still be on ctor version 0 until the publish lands)
    deadline = time.time() + 120
    v = _probe()
    while v < 1 and time.time() < deadline:
        time.sleep(0.2)
        v = _probe()
    assert v == 1, "expected the pinned initial version, got %r" % v
    with open(os.path.join(OUT_DIR, "driver_ready"), "w") as f:
        f.write("ok")
    print("driver recorded v1 probe bits", flush=True)

    stop = threading.Event()

    def pound(seed):
        rng = np.random.RandomState(seed)
        c = ServingClient(addrs=addrs, budget_ms=8000)
        while not stop.is_set():
            try:
                _, info = c.predict2(
                    rng.rand(1, IN_DIM).astype("f"))
                with lock:
                    state["answered"] += 1
                    state["versions"].add(info["version"])
                    n, nv = state["answered"], len(state["versions"])
            except Exception as e:       # noqa: BLE001 — recorded
                with lock:
                    state["errors"].append(repr(e))
                    n, nv = state["answered"], len(state["versions"])
            if PROGRESS and nv >= 2:
                # the kill -9 trigger: only counts once swaps are in
                # flight, so the kill lands mid-rollout-stream
                try:
                    with open(PROGRESS + ".tmp", "w") as f:
                        f.write(str(n))
                    os.replace(PROGRESS + ".tmp", PROGRESS)
                except OSError:
                    pass
        c.close()

    threads = [threading.Thread(target=pound, args=(i,))
               for i in range(4)]
    for t in threads:
        t.start()

    # follow the stream: probe whenever a new version shows up
    done_path = os.path.join(OUT_DIR, "trainer_done.json")
    deadline = time.time() + 240
    while time.time() < deadline:
        _probe()
        if os.path.exists(done_path):
            break
        time.sleep(0.2)
    assert os.path.exists(done_path), "trainer never finished"
    with open(done_path) as f:
        done = json.load(f)
    final_v = int(done["final_version"])
    # drain the stream to the final version
    deadline = time.time() + 60
    while _probe() != final_v and time.time() < deadline:
        time.sleep(0.2)
    stop.set()
    for t in threads:
        t.join(timeout=60)

    with lock:
        answered = state["answered"]
        errors = list(state["errors"])
        versions = sorted(state["versions"])
        losses = dict(state["loss_by_version"])
        v1_bits = state["probe_bits"][1]

    # wait for BOTH replicas (one was kill -9'd and respawned) to
    # settle on the final version before the fleet-wide rollback
    ctl = RolloutController(addrs)
    deadline = time.time() + 120
    settled = False
    while time.time() < deadline and not settled:
        try:
            status = ctl.status()
            settled = all(
                info["weights"]["latest"] >= final_v
                for info in status.values())
        except (ConnectionError, RuntimeError, OSError):
            settled = False
        if not settled:
            time.sleep(0.3)
    assert settled, "fleet never settled on v%d: %s" % (final_v,
                                                        status)

    # bit-exact rollback to the pinned version
    rb = ctl.rollback(1)
    outs, info = cli.predict2(x_eval)
    assert info["version"] == 1, info
    rb_bits = np.asarray(outs[0])
    bit_exact = bool(np.array_equal(rb_bits, v1_bits))

    # zero predict-program recompiles after warmup, on every replica
    compiles = {}
    fleet_stats = ctl.server_stats()
    for addr, s in fleet_stats.items():
        eng = s["engine"]
        compiles[addr] = {"compiles": eng["compiles"],
                          "hits": eng["hits"],
                          "swaps": s["counters"]["swaps"]}
    client_stats = cli.stats()
    ctl.close()
    cli.close()

    summary = {
        "answered": answered,
        "errors": errors,
        "versions": versions,
        "final_version": final_v,
        "loss_by_version": losses,
        "rollback_bit_exact": bit_exact,
        "rollback_info": {a: r.get("weights", {})
                          for a, r in rb.items()},
        "compiles": compiles,
        "client": client_stats,
    }
    with open(os.path.join(OUT_DIR, "summary.json"), "w") as f:
        json.dump(summary, f, default=str)
    np.savez(os.path.join(OUT_DIR, "probe_bits.npz"),
             v1=v1_bits, rollback=rb_bits)
    print("DRIVER_OK answered=%d versions=%s" % (answered, versions),
          flush=True)
    print("RANK_1_OK", flush=True)
    return 0


def main():
    rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
    os.makedirs(OUT_DIR, exist_ok=True)
    if rank == 0:
        return run_trainer()
    return run_driver()


if __name__ == "__main__":
    sys.exit(main())
