"""Observability E2E driver (ISSUE 14): one worker of a launch with
real PS shards and serving replicas, full trace sampling on.

Runs a short fused-dist fit over the REAL wire (its pushpull frames
carry trace ids into the PS process), fires a batch of traced serving
predicts (their frames carry trace ids into the replica process),
paces the traffic so every process's periodic trace autodump lands,
then waits one aggregator interval so fleet.json holds this worker's
exporter row too. The pytest side merges MXTPU_TRACE_DIR and asserts
one timeline covering >= 3 processes stitched by trace id, and runs
tools/mxtop.py --once over the telemetry dir.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.abspath(
    os.path.join(os.path.dirname(__file__), "..", "..")))

import numpy as np  # noqa: E402

import mxtpu as mx  # noqa: E402
from mxtpu import obs  # noqa: E402


def main():
    out_dir = os.environ["OBS_TEST_DIR"]
    mx.random.seed(11)
    np.random.seed(11)

    # -- traced fused-dist training over the real wire ------------------
    r = np.random.RandomState(3)
    x = r.rand(96, 8).astype("f")
    y = (r.rand(96) * 2).astype("f")
    it = mx.io.NDArrayIter(x, y, batch_size=16,
                           label_name="softmax_label")
    sym = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.var("data"), num_hidden=4,
                              name="fc"), name="softmax")
    mod = mx.mod.Module(sym, context=mx.cpu())
    mod.bind(it.provide_data, it.provide_label)
    mod.init_params(mx.init.Uniform(0.1))
    kv = mx.kv.create("dist_async")
    mod.init_optimizer(kvstore=kv, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    assert mod._fused is not None and mod._fused.mode == "dist", \
        "fused dist must engage for the traced-step story"
    # two paced passes ~2.5s apart so the PS's periodic trace autodump
    # (2s tick, fired from its span path) flushes the full history
    for _pass in range(2):
        it.reset()
        for b in it:
            mod.forward_backward(b)
            mod.update()
        mod._fused.flush()
        time.sleep(2.2)

    # -- traced serving predicts ----------------------------------------
    from mxtpu.serving import ServingClient
    cli = ServingClient()
    cli.hello()
    for _pass in range(2):
        for i in range(6):
            outs = cli.predict(np.random.rand(1, 6).astype("f"))
            assert outs[0].shape[0] == 1
        time.sleep(2.2)
    cli.close()

    obs.dump_process_trace()
    snap = obs.REGISTRY.snapshot()
    with open(os.path.join(out_dir, "worker_summary.json"), "w") as f:
        json.dump({
            "steps": snap["metrics"]["module.steps"]["series"].get(
                "", 0),
            "spans": snap["metrics"]["trace.spans"]["series"].get(
                "", 0),
            "views": sorted(k.split("#")[0] for k in snap["views"]),
        }, f)
    kv.close()
    # capture a fleet snapshot WHILE this worker's exporter is alive:
    # the aggregator's final sweeps (after we exit) legitimately show
    # our row as a gap, so the live picture is grabbed mid-run
    exp = obs.ensure_exporter()
    telem_dir = os.environ.get("MXTPU_TELEMETRY_DIR")
    fleet_path = os.path.join(telem_dir, "fleet.json")
    deadline = time.time() + 30
    captured = False
    while time.time() < deadline and not captured:
        try:
            with open(fleet_path) as f:
                doc = json.load(f)
            live = {a for a, s in doc.get("fleet", {}).items()
                    if isinstance(s, dict) and not s.get("gap")}
            if exp is not None and exp.address in live \
                    and len(live) >= 3:
                with open(os.path.join(out_dir, "fleet_live.json"),
                          "w") as f:
                    json.dump(doc, f)
                captured = True
        except (OSError, ValueError):
            pass
        time.sleep(0.2)
    assert captured, "fleet.json never showed all 3 processes live"
    print("OBS_WORKER_OK", flush=True)


if __name__ == "__main__":
    main()
