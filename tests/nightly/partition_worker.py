"""Partition-tolerance E2E worker (ISSUE 19).

Launched by tools/launch.py -n 1 -s 1 --ps-replicas 2 against REAL
parameter-server processes. The worker drives the whole partition
lifecycle from inside its own process — the fault harness cuts the
client->primary link at the wire (``kind=partition,point=worker.send``)
while the server-to-server links stay up, exactly the asymmetric cut a
top-of-rack switch failure produces:

  A. warm-up rounds — the replicated pair converges;
  B. CUT: every client op toward the launch-time primary is severed.
     Pushes buffer under the MXTPU_PS_PARTITION_GRACE window (the
     standby's peer_alive probe confirms the primary is alive, so no
     spurious promotion), pulls degrade to cached values — then the
     grace expires and availability wins: the standby is promoted and
     mints fencing epoch 2. The deposed primary hears the new epoch
     over the UNCUT server-to-server probe link, fences itself (the
     launcher log shows the refusal), rejoins as the new backup and
     catches up — all while the client-side cut still stands;
  C. HEAL: the cut lifts and the worker finishes its rounds against
     the healed, re-redundant pair.

A fixed number of seeded pushes per phase makes the run comparable to
an uninterrupted control: the final server-side table must be
bit-for-bit identical (buffered pushes flush in order with their
original seqs, so not even float addition order may drift). With
MXTPU_HISTORY_DIR set, every invoke/ack/apply is journaled for the
offline consistency checker.
"""
import json
import os
import sys
import time

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu as mx                                           # noqa: E402
from mxtpu import fault                                      # noqa: E402

rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
out_dir = os.environ["PARTITION_TEST_DIR"]
rounds_a = int(os.environ.get("PARTITION_ROUNDS_A", "20"))
rounds_b = int(os.environ.get("PARTITION_ROUNDS_B", "30"))
rounds_c = int(os.environ.get("PARTITION_ROUNDS_C", "20"))
cut_run = os.environ.get("PARTITION_CUT", "0") != "0"

KEYS = ["p%d" % i for i in range(4)]
SHAPE = (8,)
# the whole client command surface toward one address — what a real
# network partition cuts. The server-to-server plane (peer_info,
# join_backup, promote, repl) rides other links, and `stats` stays
# open as the out-of-band observability plane the drill reads through.
CLIENT_OPS = "push|pull|pushpull|spushpull|multi|init|hello|ping" \
             "|barrier|shard_map"

kv = mx.kv.create("dist_async")
kv.init(KEYS, [mx.nd.zeros(SHAPE) for _ in KEYS])


def wait_redundant(timeout=60):
    """Block until the shard pair is redundant: backup attached, caught
    up, forwarding stream drained. Returns the replication rows."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        # health() rows (not stats()): they carry fence_epoch too
        rows = kv.health().get("replication") or []
        if rows and all(
                r["repl"] is not None and not r["repl"]["dead"]
                and (r["repl"]["catchup"] or {}).get("done")
                and r["repl"]["lag"] == 0 for r in rows):
            return rows
        time.sleep(0.2)
    raise AssertionError("replicated pair never became redundant: %r"
                         % (kv.health().get("replication"),))


# same seed both runs: the drill's table must be bit-for-bit equal to
# the control's, so the grad sequence itself must be identical
rng = np.random.RandomState(777 + rank)


def push_round():
    for k in KEYS:
        grad = rng.standard_normal(SHAPE).astype(np.float32)
        kv.push(k, mx.nd.array(grad))


wait_redundant()

# -- phase A: warm-up; prime the pull cache so cut-time pulls have a
# cached value to degrade to -----------------------------------------
for _ in range(rounds_a):
    push_round()
probe = mx.nd.zeros(SHAPE)
for k in KEYS:
    kv.pull(k, out=probe)

inj = None
if cut_run:
    pri_addr = os.environ["MXTPU_PS_ADDRS"].split(",")[0]
    others = os.environ.get("MXTPU_PS_BACKUP_ADDRS", "").split(",")
    # fault rules match addr by substring: the cut must not also
    # swallow the standby's address
    assert not any(pri_addr in b for b in others if b), \
        "primary address is a substring of a backup's: %s vs %r" \
        % (pri_addr, others)
    spec = "kind=partition,point=worker.send,addr=%s,op=%s" \
        % (pri_addr, CLIENT_OPS)
    inj = fault.install(spec)
    print("partition worker: CUT client->%s" % pri_addr, flush=True)

# -- phase B: fixed rounds through the cut (fixed, so the push totals
# match the control run exactly). Early rounds buffer pushes and serve
# degraded pulls inside the grace window; once it expires a pull's
# failover promotes the standby and flushes the buffer in order. -----
for _ in range(rounds_b):
    push_round()
    kv.pull(KEYS[0], out=probe)
    time.sleep(0.05)

if cut_run:
    h = kv.health()
    assert h["fence_epoch"] == 2, \
        "standby never promoted under the cut: %r" % (h,)
    assert h["failovers"] == 1, h
    assert inj.stats()[0][4] >= 1, "the cut never fired"
    print("partition worker: standby promoted, fleet epoch 2",
          flush=True)
    # the deposed primary fences over the uncut server-to-server probe
    # link and rejoins as backup — while the client cut still stands
    rows = wait_redundant()
    assert rows[0]["fence_epoch"] == 2, rows
    fault.uninstall()   # heal
    print("partition worker: HEALED", flush=True)

# -- phase C: the healed pair takes the rest of the workload ----------
for _ in range(rounds_c):
    push_round()

rows = wait_redundant()
h = kv.health()
assert h["pending_pushes"] == 0, h

table = {}
for k in KEYS:
    out = mx.nd.zeros(SHAPE)
    kv.pull(k, out=out)
    table[k] = out.asnumpy()
np.savez(os.path.join(out_dir, "rank%d_table.npz" % rank), **table)

with open(os.path.join(out_dir, "rank%d.json" % rank), "w") as f:
    json.dump({"rank": rank,
               "rounds": rounds_a + rounds_b + rounds_c,
               "failovers": h["failovers"],
               "fence_epoch": h["fence_epoch"],
               "promotions": sum(r.get("promotions", 0) for r in rows),
               "rows": rows}, f)

kv.barrier()
kv.close()
print("PARTITION_RANK_%d_OK" % rank, flush=True)
