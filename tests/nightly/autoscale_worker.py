"""Diurnal autoscaling driver for the E2E acceptance drill (ISSUE 16).

Launched by tools/launch.py -n 1 -s 1 --serve 1 --serve-max 2
--autoscale with MXTPU_PS_ELASTIC=1 and the MXTPU_AUTOSCALE_* bands
tuned so a scripted "day" of load makes every trigger reachable. The
anchor (rank 0) IS the load generator:

* a pump thread pushes the six keys flat out (the single PS shard's
  push rate crosses the split band -> the controller splits it online)
  and bumps ``module.steps`` (the worker fleet's throughput stays
  under the configured target -> the controller adds a worker);
* the main loop streams serving requests (~8/s, above the up_rps
  band -> the controller adds the reserved replica, which PREWARMS
  from the first replica's exported AOT program menu);
* when the executor's verdicts show add_worker + add_replica +
  split_shard all applied, the anchor declares NIGHT: requests stop,
  the request rate decays through the idle band, and the controller
  drains the added replica; pushes continue the whole time.

The launcher's ``--autoscale-fault`` kills the controller -9 on its
FIRST actuation (after the journaled intent, before any verdict); the
respawned controller replays the journal and the executor's dedupe
keeps the replay exactly-once — the pytest side asserts it from the
launcher transcript.

Zero acknowledged loss is asserted HERE: every acked push is counted
as it returns, and at the end every key's server-side clock must equal
its count exactly — across the online split, the reroutes, the
controller kill, and every capacity change. A joiner (rank >= 1,
MXTPU_ELASTIC_JOINER=1) hellos into the membership, idles as a live
fleet row, and leaves cleanly at night.
"""
import json
import os
import signal
import sys
import threading
import time

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu as mx                                           # noqa: E402
from mxtpu import obs                                        # noqa: E402

rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
joiner = os.environ.get("MXTPU_ELASTIC_JOINER", "0") == "1"
out_dir = os.environ["AUTOSCALE_TEST_DIR"]
night_marker = os.path.join(out_dir, "night")

KEYS = ["w%d" % i for i in range(6)]
DIM = 4
STEPS = obs.metrics.counter("module.steps")


def ok_verdicts():
    """{action kind: [action ids]} of every OK verdict the launcher's
    executor has recorded — the driver's view of what the controller
    actually actuated."""
    vdir = os.path.join(os.environ["MXTPU_AUTOSCALE_DIR"], "verdicts")
    out = {}
    try:
        names = os.listdir(vdir)
    except OSError:
        return out
    for fn in names:
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(vdir, fn)) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("verdict") != "ok":
            continue
        aid = fn[:-5]
        kind = aid.split(".", 1)[1] if "." in aid else aid
        out.setdefault(kind, []).append(aid)
    return out


def main_joiner():
    flag = {"stop": False}
    signal.signal(signal.SIGTERM,
                  lambda *_: flag.__setitem__("stop", True))
    kv = mx.kv.create("dist_async")      # hello: a REAL membership join
    print("worker %d joined mid-run" % rank, flush=True)
    deadline = time.time() + 180
    while not flag["stop"] and not os.path.exists(night_marker) \
            and time.time() < deadline:
        STEPS.inc()                      # a live (if unhurried) row
        time.sleep(0.1)
    kv.close()
    print("RANK_%d_OK" % rank, flush=True)
    return 0


def main_anchor():
    from mxtpu.serving import ServingClient
    kv = mx.kv.create("dist_async")
    kv.init(KEYS, [mx.nd.zeros((DIM,)) for _ in KEYS])
    # pin the client to the first (live) replica: the reserved slot's
    # address is advertised but nothing listens there until the
    # controller adds it
    cli = ServingClient(
        addrs=os.environ["MXTPU_SERVE_ADDRS"].split(",")[:1])
    cli.hello()

    counted = {k: 0 for k in KEYS}
    stop = threading.Event()

    def pump():
        # the diurnal base load: hot pushes (split pressure) + a step
        # counter pace that stays under the autoscale target (worker
        # pressure). Counting AFTER each push returns is what "acked"
        # means — the zero-loss ledger.
        while not stop.is_set():
            for k in KEYS:
                kv.push(k, mx.nd.ones((DIM,)))
                counted[k] += 1
            STEPS.inc(3)
            time.sleep(0.02)

    t = threading.Thread(target=pump, daemon=True)
    t.start()

    # -- day: serve traffic until the controller has added a worker,
    # added the reserved replica, and split the hot shard -------------
    want_day = {"add_worker", "add_replica", "split_shard"}
    x = np.random.RandomState(7).rand(1, 6).astype("f")
    deadline = time.time() + 240
    while not want_day <= set(ok_verdicts()):
        if time.time() > deadline:
            stop.set()
            raise AssertionError(
                "day actions never all landed: %r" % ok_verdicts())
        try:
            cli.predict(x)               # ~8 req/s: over the up band
        except Exception:
            pass                         # replica churn is the drill
        time.sleep(0.12)

    # -- night: the request stream stops; the idle band drains the
    # added replica. Pushes keep flowing the whole time. --------------
    tmp = night_marker + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(time.time()))
    os.replace(tmp, night_marker)
    print("autoscale driver: night (day verdicts %r)"
          % sorted(ok_verdicts()), flush=True)
    deadline = time.time() + 150
    while "drain_replica" not in ok_verdicts():
        if time.time() > deadline:
            stop.set()
            raise AssertionError(
                "the idle band never drained a replica: %r"
                % ok_verdicts())
        time.sleep(0.2)

    stop.set()
    t.join(timeout=30)
    assert not t.is_alive(), "the push pump never stopped"

    # -- the ledger: every acked push applied exactly once ------------
    clocks = kv.staleness_stats()["clocks"]
    bad = {k: (clocks.get(k), counted[k]) for k in KEYS
           if clocks.get(k) != counted[k]}
    assert not bad, ("acked updates lost or double-applied across the "
                     "autoscale run: %r" % (bad,))
    summary = {
        "counted": counted,
        "clocks": {k: clocks.get(k) for k in KEYS},
        "clocks_exact": not bad,
        "total_acked": sum(counted.values()),
        "map_reroutes": kv.stats()["map_reroutes"],
        "verdicts": {k: sorted(v) for k, v in ok_verdicts().items()},
    }
    with open(os.path.join(out_dir, "summary.json"), "w") as f:
        json.dump(summary, f, indent=1)
    cli.close()
    kv.close()
    print("RANK_0_OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main_joiner() if joiner or rank != 0 else main_anchor())
