"""Elastic worker for the kill -9 / --worker-respawn end-to-end test.

Launched by tools/launch.py -n 1 -s 1 --worker-respawn. The worker runs
a fixed number of guarded train steps over an NDArrayIter, checkpoints
its FULL state (params, optimizer, step count, RNG keys, LR-scheduler
progress, iterator cursor) every few good steps through TrainGuard, and
pushes every step's gradients to the dist_async parameter server.

With MXTPU_FAULT_SPEC="kind=kill_worker,point=worker.step,nth=K" the
fault harness SIGKILLs the process deterministically at step-attempt K.
The launcher respawns it; the fresh process restores the latest
checkpoint, re-registers with the server (hello + param pull),
fast-forwards its data iterator, and finishes the remaining steps. The
nth=K schedule counts per process, so as long as K exceeds the steps
remaining after a restore the respawned incarnation never re-fires —
the whole scenario is replayable with zero timing dependence.

Because every source of randomness is seeded and the RNG keys ride the
checkpoint, the final parameters must be IDENTICAL to an uninterrupted
run (the parity half of the fault matrix: same script, no fault spec,
fresh state dir).
"""
import json
import os
import sys

import numpy as np

import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))
import mxtpu as mx                                           # noqa: E402
from mxtpu import gluon                                      # noqa: E402
from mxtpu.gluon import nn                                   # noqa: E402
from mxtpu.checkpoint import CheckpointManager               # noqa: E402
from mxtpu.parallel import MeshContext, ShardedTrainer       # noqa: E402
from mxtpu.resilience import TrainGuard                      # noqa: E402

rank = int(os.environ.get("MXTPU_PROC_ID", "0"))
state_dir = os.environ["MXTPU_WORKER_STATE_DIR"]
out_dir = os.environ["RESILIENT_TEST_DIR"]
total_steps = int(os.environ.get("RESILIENT_TOTAL_STEPS", "12"))

# deterministic everything: the respawned incarnation re-derives the
# same init/data, and the checkpoint carries the RNG streams forward
np.random.seed(100 + rank)
mx.random.seed(100 + rank)
import mxtpu.gluon.block as _blk                             # noqa: E402
_blk._NAME_COUNTERS.clear()

net = nn.HybridSequential()
with net.name_scope():
    net.add(nn.Dense(16), nn.Activation("relu"), nn.Dense(10))
net.initialize(mx.init.Xavier())

rng = np.random.RandomState(7 + rank)
X = rng.standard_normal((64, 8)).astype(np.float32)
Y = rng.randint(0, 10, (64,)).astype(np.float32)
net(mx.nd.array(X[:8]))

it = mx.io.NDArrayIter(X, Y, batch_size=8)                   # 8 batches/epoch
sched = mx.lr_scheduler.FactorScheduler(step=4, factor=0.5)
sched.base_lr = 0.1
st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                    {"learning_rate": 0.1, "momentum": 0.9,
                     "lr_scheduler": sched}, mesh=MeshContext())
ckpt = CheckpointManager(state_dir, max_to_keep=3, async_save=False,
                         use_orbax=False)
guard = TrainGuard(st, data_iter=it, ckpt=ckpt, ckpt_every=3, spike_z=0)

kv = None
if os.environ.get("MXTPU_PS_ADDRS"):
    kv = mx.kv.create("dist_async")
    guard.attach_kvstore(kv)

restored = guard.restore()
if restored is not None:
    print("worker %d resumed from checkpoint step %d" % (rank, restored),
          flush=True)
    if kv is not None:
        # re-registration already happened at store creation (hello);
        # pull the server's current view of one key to prove the read
        # path is live again before training resumes
        names = sorted(kv._parts)
        if names:
            probe = mx.nd.zeros(kv._shapes[names[0]])
            kv.pull(names[0], out=probe)
            assert np.isfinite(probe.asnumpy()).all()

if kv is not None and os.environ.get("MXTPU_PS_REPLICAS", "1") != "1":
    # replicated launch: hold training until the shard pair is
    # redundant (backup joined + caught up). The replication guarantee
    # — kill a primary, lose nothing acked — starts once the pair is
    # formed; training into an unformed pair would just be the old
    # single-server story, and the failover E2E must not race it.
    import time
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        rows = kv.stats().get("replication") or []
        if rows and all(
                r["repl"] is not None and not r["repl"]["dead"]
                and (r["repl"]["catchup"] or {}).get("done") for r in rows):
            break
        time.sleep(0.2)
    else:
        raise AssertionError("replicated pair never became redundant")

# step progress on disk: the server-failover E2E test watches this to
# time its external kill -9 of the primary against real training
progress_file = os.environ.get("RESILIENT_PROGRESS_FILE")


def _note_progress():
    if progress_file:
        with open(progress_file + ".tmp", "w") as f:
            f.write(str(int(st._num_update)))
        os.replace(progress_file + ".tmp", progress_file)


loss = float("nan")
while st._num_update < total_steps:
    try:
        batch = it.next()
    except StopIteration:
        it.reset()
        batch = it.next()
    loss = guard.step(batch.data[0], batch.label[0])
    _note_progress()

if not np.isfinite(loss):
    # a restore may land exactly at total_steps (nothing left to run):
    # evaluate once so the finiteness claim still covers the params
    loss, _ = st.forward(X[:8], Y[:8])
assert np.isfinite(loss), "final loss is not finite: %r" % loss
st.sync_params()
params = {p.name: p.data().asnumpy() for p in net._ordered_params()}
np.savez(os.path.join(out_dir, "rank%d_params.npz" % rank), **params)

ps_view = None
if kv is not None and os.environ.get("MXTPU_PS_REPLICAS", "1") != "1":
    # replicated launch: wait for the pair to be redundant again (a
    # respawned ex-primary rejoins as backup and catches up), then
    # record the replication evidence the E2E failover test asserts
    st.flush_grad_pushes()
    import time
    deadline = time.monotonic() + 90
    while time.monotonic() < deadline:
        rows = kv.stats().get("replication") or []
        if rows and all(
                r["repl"] is not None and not r["repl"]["dead"]
                and (r["repl"]["catchup"] or {}).get("done")
                and r["repl"]["lag"] == 0 for r in rows):
            break
        time.sleep(0.5)
    rows = kv.stats().get("replication") or []
    ps_view = {"rows": rows,
               "failovers": kv.health()["failovers"],
               "promotions": sum(r.get("promotions", 0)
                                 for r in rows)}
    # the server-side accumulated gradient table is the parity
    # object: a killed-primary run must match a clean run bit-for-bit
    table = {}
    for name in sorted(kv._parts):
        probe = mx.nd.zeros(kv._shapes[name])
        kv.pull(name, out=probe)
        table[name] = probe.asnumpy()
    np.savez(os.path.join(out_dir, "rank%d_table.npz" % rank), **table)

with open(os.path.join(out_dir, "rank%d.json" % rank), "w") as f:
    json.dump({"rank": rank, "steps": int(st._num_update),
               "loss": loss, "resumed_from": restored,
               "lr": float(st.learning_rate),
               "ps": ps_view,
               "guard": {k: v for k, v in guard.stats().items()
                         if isinstance(v, (int, float))}}, f)
if kv is not None:
    # bounded even if a peer died: the server releases the barrier on
    # its MXTPU_PS_BARRIER_TIMEOUT deadline instead of hanging us
    kv.barrier()
    kv.close()
print("RANK_%d_OK" % rank, flush=True)
