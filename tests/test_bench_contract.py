"""bench.py's output contract: the driver parses exactly one JSON line
with fixed keys, rc 0, under every backend condition. MXTPU_BENCH_TINY
shrinks the model so the contract test stays fast."""
import json
import os
import subprocess
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_cpu_fallback_contract():
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_BENCH_TINY="1",
               PYTHONPATH=_ROOT)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--cpu-fallback"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-500:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    payload = json.loads(lines[-1])
    # relay-down rounds emit the CPU inference scoreboard number (vs the
    # reference's published CPU tables), not a toy training rate
    assert payload["metric"] == "resnet50_infer_cpu_img_per_sec"
    assert payload["unit"] == "images/sec"
    assert payload["tpu_unavailable"] is True
    assert payload.get("tiny") is True
    assert isinstance(payload["value"], (int, float))
    assert "error" not in payload, payload
