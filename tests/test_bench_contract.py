"""bench.py's output contract: the driver parses exactly one JSON line
with fixed keys, rc 0, under every backend condition. MXTPU_BENCH_TINY
shrinks the model so the contract test stays fast."""
import json
import os
import subprocess
import sys

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))


def test_cpu_fallback_contract():
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_BENCH_TINY="1",
               PYTHONPATH=_ROOT)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--cpu-fallback"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-500:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    payload = json.loads(lines[-1])
    # relay-down rounds emit the CPU inference scoreboard number (vs the
    # reference's published CPU tables), not a toy training rate
    assert payload["metric"] == "resnet50_infer_cpu_img_per_sec"
    assert payload["unit"] == "images/sec"
    assert payload["tpu_unavailable"] is True
    assert payload.get("tiny") is True
    assert isinstance(payload["value"], (int, float))
    assert "error" not in payload, payload


def test_attach_best_tpu_measurement(tmp_path, monkeypatch):
    # the fallback JSON line must carry the staged report's best TPU
    # training number so a relay-down round close still ships evidence
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "bench_mod", os.path.join(_ROOT, "bench.py"))
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)

    report = {
        "timestamp": "2026-08-01 12:00:00",
        "bench_batch32": {"value": 500.0, "vs_baseline": 2.75},
        "bench_batch256_nhwc": {"img_per_sec": 900.0},
        "bench_batch128": {"error": "boom"},
    }
    fake_root = tmp_path
    (fake_root / "tpu_checks_report.json").write_text(json.dumps(report))
    real_bench_file = bench.os.path.abspath(bench.__file__)

    monkeypatch.setattr(
        bench.os.path, "dirname",
        lambda p, _real=bench.os.path.dirname, _bf=real_bench_file:
            str(fake_root) if p == _bf else _real(p))
    result = {"tpu_unavailable": True}
    bench._attach_best_tpu_measurement(result)
    best = result["best_tpu_measured"]
    assert best["config"] == "bench_batch256_nhwc"
    assert best["img_per_sec"] == 900.0
    assert best["vs_baseline"] == round(900.0 / bench.BASELINE_IMG_S, 3)
    assert best["measured_at"] == "2026-08-01 12:00:00"

    # no report -> no key, no crash
    result2 = {}
    monkeypatch.setattr(bench.os.path, "dirname",
                        lambda p: str(tmp_path / "nowhere"))
    bench._attach_best_tpu_measurement(result2)
    assert "best_tpu_measured" not in result2


def test_module_bench_contract():
    """tools/bench_module.py: exactly one JSON line, rc 0, with the
    fused-vs-eager fields the perf trajectory (docs/perf_analysis.md
    "Module fast path") is tracked by — tiny models, CPU-only."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_BENCH_TINY="1",
               PYTHONPATH=_ROOT)
    env.pop("MXTPU_MODULE_FUSED", None)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_module.py"),
         "--batches", "3", "--warmup", "2", "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "module_fit"
    assert payload["tiny"] is True
    assert set(payload["models"]) == {"mlp", "lenet"}
    for model, row in payload["models"].items():
        for field in ("fused_img_s", "eager_img_s", "speedup",
                      "batch_size"):
            assert isinstance(row[field], (int, float)), (model, field)
        assert row["fused_img_s"] > 0 and row["eager_img_s"] > 0


def test_module_bench_dist_contract():
    """tools/bench_module.py --dist: exactly one JSON line, rc 0, with
    the eager vs fused-sync vs fused-async loopback-PS fields the
    distributed perf trajectory (docs/perf_analysis.md "Distributed
    Module fast path") is tracked by — tiny model, CPU-only."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_BENCH_TINY="1",
               MXTPU_PS_HEARTBEAT="0", PYTHONPATH=_ROOT)
    for k in ("MXTPU_MODULE_FUSED", "MXTPU_MODULE_FUSED_DIST",
              "MXTPU_MODULE_DIST_MODE"):
        env.pop(k, None)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_module.py"),
         "--dist", "--batches", "3", "--warmup", "2", "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "module_fit_dist"
    assert payload["tiny"] is True
    row = payload["models"]["mlp"]
    for field in ("batch_size", "eager_img_s", "fused_sync_img_s",
                  "fused_async_img_s", "speedup_sync", "speedup_async"):
        assert isinstance(row[field], (int, float)), field
    assert row["eager_img_s"] > 0 and row["fused_sync_img_s"] > 0 \
        and row["fused_async_img_s"] > 0


def test_module_bench_amp_contract():
    """tools/bench_module.py --amp: exactly one JSON line, rc 0, with
    the fp32-vs-bf16 fused fields AND the half-width-wire bytes the
    mixed-precision trajectory (docs/perf_analysis.md "Mixed
    precision") is tracked by — tiny model, CPU-only."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_BENCH_TINY="1",
               MXTPU_PS_HEARTBEAT="0", PYTHONPATH=_ROOT)
    for k in ("MXTPU_AMP", "MXTPU_MODULE_FUSED", "MXTPU_MODULE_FUSED_DIST",
              "MXTPU_MODULE_DIST_MODE"):
        env.pop(k, None)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_module.py"),
         "--amp", "--batches", "3", "--warmup", "2", "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "module_fit_amp"
    assert payload["tiny"] is True
    row = payload["models"]["mlp"]
    for field in ("batch_size", "fp32_img_s", "bf16_img_s", "speedup"):
        assert isinstance(row[field], (int, float)), field
    dist = payload["dist"]
    for field in ("batch_size", "fp32_img_s", "bf16_img_s", "speedup",
                  "fp32_bytes_per_step", "bf16_bytes_per_step",
                  "wire_bytes_ratio"):
        assert isinstance(dist[field], (int, float)), field
    # the half-width wire holds at ANY size (it is structural, not a
    # wall-clock number): bf16 frames carry half the payload bytes
    assert dist["wire_bytes_ratio"] <= 0.55


def test_module_bench_mesh_contract():
    """tools/bench_module.py --mesh: exactly one JSON line, rc 0, with
    the single-vs-sharded train/serve fields the mesh trajectory
    (docs/perf_analysis.md "Sharded Module") is tracked by — tiny
    model, 8 emulated CPU devices."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", MXTPU_BENCH_TINY="1",
               MXTPU_PS_HEARTBEAT="0", PYTHONPATH=_ROOT,
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    for k in ("MXTPU_MODULE_FUSED", "MXTPU_MESH"):
        env.pop(k, None)
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_module.py"),
         "--mesh", "--batches", "3", "--warmup", "2", "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "module_fit_mesh"
    assert payload["tiny"] is True
    assert payload["devices"] == 8
    train = payload["train"]
    for field in ("batch_size", "fused_img_s", "mesh_img_s",
                  "mesh_vs_single", "store_bytes",
                  "store_bytes_worst_device", "store_devices"):
        assert isinstance(train[field], (int, float)), field
    assert train["fused_img_s"] > 0 and train["mesh_img_s"] > 0
    # the structural half of the row holds at ANY size: the donated
    # store (params + opt state) really splits ~1/N across the mesh
    assert train["store_devices"] == 8
    assert train["store_bytes_worst_device"] <= \
        train["store_bytes"] // 8 + 8 * 1024
    serve = payload["serve"]
    for field in ("batch_size", "single_req_s", "mesh_req_s",
                  "mesh_vs_single"):
        assert isinstance(serve[field], (int, float)), field
    assert serve["single_req_s"] > 0 and serve["mesh_req_s"] > 0
    # steady-state sharded serving never recompiles (AOT menu)
    assert serve["recompiles"] == 0


def test_kvstore_bench_contract(tmp_path):
    """tools/bench_kvstore.py: exactly one JSON line, rc 0, with the
    fields the perf trajectory (docs/perf_analysis.md "Comms fast
    path") is tracked by — on a fault-free tiny loopback run."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT,
               MXTPU_PS_HEARTBEAT="0")
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_kvstore.py"),
         "--mb", "2", "--small-keys", "16", "--iters", "2", "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "kvstore_loopback"
    assert payload["transport"] in ("local", "tcp")
    for field in ("payload_mb", "push_mb_s", "pull_mb_s",
                  "small_push_ops_s", "small_pull_ops_s", "n_parts",
                  "window", "iters"):
        assert isinstance(payload[field], (int, float)), field
    for lat in (payload["push"], payload["pull"]):
        assert lat["p50_ms"] > 0 and lat["p99_ms"] >= lat["p50_ms"]
    # both transports always reported: local headline + tcp sub-object
    assert isinstance(payload["tcp"]["push_mb_s"], (int, float))
    # comms counters rode along (the fault-free run retransmits nothing)
    assert payload["wire"]["retransmits"] == 0
    assert payload["wire"]["bytes_sent"] > 0
    assert payload["wire"]["coalesced_subs"] >= 16


def test_serving_bench_contract():
    """tools/bench_serving.py: exactly one JSON line, rc 0, with the
    offered-load sweep fields the perf trajectory (docs/perf_analysis.md
    "Serving") is tracked by — tiny levels, CPU-only loopback."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT,
               MXTPU_BENCH_TINY="1", MXTPU_PS_HEARTBEAT="0")
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_serving.py"),
         "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "serving_loopback"
    assert payload["tiny"] is True
    assert payload["transport"] in ("local", "tcp")
    assert payload["buckets"] and payload["queue_depth"] >= 1
    assert payload["levels"], "offered-load sweep missing"
    for row in payload["levels"]:
        for field in ("clients", "attempts", "answered", "req_s",
                      "shed", "shed_rate", "expired"):
            assert isinstance(row[field], (int, float)), field
        assert row["p50_ms"] > 0 and row["p99_ms"] >= row["p50_ms"]
        # every attempt has exactly one terminal outcome
        assert row["answered"] + row["shed"] + row["expired"] \
            + row["errors"] == row["attempts"]
        # server-side latency histograms (ISSUE 14): per-level bucket
        # deltas of serve.request_ms / serve.batch.flush_ms ride every
        # offered-load point — the same registry numbers mxtop and the
        # telemetry plane read
        for kind in ("request", "batch"):
            h = row["server_lat"][kind]
            assert h["count"] >= row["answered"] or kind == "batch", h
            if h["count"]:
                assert h["p50_ms"] > 0, h
                assert h["p99_ms"] >= h["p50_ms"], h
    # both transports always reported: local headline + tcp sub-object
    assert isinstance(payload["tcp"]["req_s"], (int, float))
    # the dynamic batcher actually batched, and steady state never
    # retraced (the AOT bucket menu absorbed every request)
    assert payload["batches"] <= payload["batched_requests"]
    assert payload["retraces_after_warmup"] == 0
    # continuous deployment (ISSUE 11): swap latency + poll-mode
    # weight-staleness lag ride every bench line, and a weight swap is
    # never a retrace (same shapes -> program-cache hit)
    ro = payload["rollout"]
    assert ro["swaps"] >= 1
    assert ro["swap_ms_p50"] > 0 and ro["swap_ms_max"] >= ro["swap_ms_p50"]
    assert ro["staleness_ms_p50"] > 0
    assert ro["staleness_ms_max"] >= ro["staleness_ms_p50"]
    assert ro["retraces"] == 0
    # continuous-batching generation (ISSUE 17): tokens/s per sweep
    # level with TTFT/per-step percentiles from the serve.gen.*
    # registry histograms, and a retrace-free steady state (the >= 2x
    # batching win at 64-vs-8 is pinned by ci/check_generate_perf.py,
    # not here — tiny levels are too small to assert a ratio)
    gen = payload["generate"]
    assert gen["slots"] >= 1 and gen["max_new"] >= 1
    assert gen["levels"], "generate sweep missing"
    for row in gen["levels"]:
        assert row["errors"] == 0, row
        assert row["tokens"] == row["sequences"] * gen["max_new"], row
        assert row["tok_s"] > 0
        assert row["ttft"]["count"] >= row["sequences"], row
        assert row["ttft"]["p99_ms"] >= row["ttft"]["p50_ms"] > 0
        assert row["step"]["count"] >= 1, row
        assert row["step"]["p99_ms"] >= row["step"]["p50_ms"] > 0
    assert gen["decode_steps"] >= 1
    assert gen["retraces_after_warmup"] == 0


def test_embedding_bench_contract(tmp_path):
    """tools/bench_embedding.py: exactly one JSON line, rc 0, with the
    sparse-wire scaling evidence (docs/perf_analysis.md "Sparse fast
    path"): bytes/step tracking rows touched, never table size."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT,
               MXTPU_PS_HEARTBEAT="0", MXTPU_BENCH_TINY="1")
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools",
                                      "bench_embedding.py"),
         "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "embedding_sparse_wire"
    assert payload["transport"] == "tcp"
    for pt in payload["points"]:
        for kind in ("dense", "sparse"):
            assert pt[kind]["bytes_per_step"] > 0
            assert pt[kind]["steps_per_s"] > 0
        # the contract: sparse bytes track rows touched (within 2x of
        # the touch fraction — headers/ids are the slack), dense don't
        assert pt["bytes_ratio"] <= 2 * pt["touch_fraction"] + 0.01, pt


def test_streaming_bench_contract():
    """tools/bench_streaming.py (ISSUE 18): exactly one JSON line, rc 0,
    with the durable-log + exactly-once loop fields docs/perf_analysis.md
    "Streaming" is tracked by — tiny counts, CPU-only loopback."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT,
               MXTPU_BENCH_TINY="1", MXTPU_PS_HEARTBEAT="0")
    res = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "bench_streaming.py"),
         "--no-write"],
        capture_output=True, text=True, timeout=600, env=env)
    assert res.returncode == 0, res.stderr[-800:]
    lines = [l for l in res.stdout.strip().splitlines() if l.strip()]
    assert len(lines) == 1, "must print exactly ONE JSON line"
    payload = json.loads(lines[0])
    assert payload["bench"] == "streaming_loopback"
    assert payload["tiny"] is True
    assert payload["records"] >= 1 and payload["payload_bytes"] >= 1
    # durable log: append (buffered + fsync-per-record) and sealed tail
    for section in ("append", "append_fsync"):
        assert payload[section]["records_s"] > 0
        assert payload[section]["mb_s"] > 0
    # per-record durability must cost more than seal-time durability
    assert payload["append_fsync"]["records_s"] \
        <= payload["append"]["records_s"]
    assert payload["tail"]["records_s"] > 0
    # exactly-once loop: tail→train steps with the offset commit riding
    # each stream_push frame, plus the respawn-storm dup-refusal rate
    loop = payload["loop"]
    assert loop["steps_s"] > 0 and loop["records_s"] > 0
    assert loop["dup_refused_s"] > 0
