"""Module API tests (reference: tests/python/unittest/test_module.py +
tests/python/train/test_mlp.py — end-to-end convergence asserting final
accuracy, and bind/checkpoint behaviors)."""
import numpy as np
import pytest

import mxtpu as mx


def _toy_problem(n=512, dim=20, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype("float32")
    w = rng.randn(dim, classes).astype("float32")
    y = (x @ w).argmax(axis=1).astype("float32")
    return x, y


def _mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_fit_convergence():
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=32,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(),
            num_epoch=15, eval_metric="acc")
    score = mod.score(val, "acc")
    assert score[0][1] > 0.97, score


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=32,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(), num_epoch=3)
    base = mod.score(val, "acc")
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(val.provide_data, val.provide_label, for_training=False)
    s2 = mod2.score(val, "acc")
    assert abs(s2[0][1] - base[0][1]) < 1e-6

    preds = mod2.predict(val)
    assert preds.shape == (512, 4)


def test_module_forward_backward_shapes():
    x, y = _toy_problem()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    train = mx.io.NDArrayIter(x, y, batch_size=16,
                              label_name="softmax_label")
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params()
    mod.init_optimizer()
    batch = next(train)
    mod.forward(batch)
    outs = mod.get_outputs()
    assert outs[0].shape == (16, 4)
    mod.backward()
    mod.update()


def test_module_input_grads():
    x, y = _toy_problem()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    train = mx.io.NDArrayIter(x, y, batch_size=16,
                              label_name="softmax_label")
    mod.bind(train.provide_data, train.provide_label, for_training=True,
             inputs_need_grad=True)
    mod.init_params()
    batch = next(train)
    mod.forward(batch)
    mod.backward()
    igrads = mod.get_input_grads()
    assert igrads[0].shape == (16, 20)
    assert float(mx.nd.norm(igrads[0]).asscalar()) > 0


def test_module_multi_device():
    """Data-parallel executor group over multiple faked devices
    (reference tests/python/unittest/test_multi_device_exec.py)."""
    x, y = _toy_problem()
    ctxs = [mx.cpu(0), mx.cpu(1)]
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=32,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=ctxs)
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(), num_epoch=10)
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, score


def test_module_reshape():
    x, y = _toy_problem()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind([("data", (32, 20))], [("softmax_label", (32,))])
    mod.init_params()
    mod.reshape([("data", (8, 20))], [("softmax_label", (8,))])
    batch = mx.io.DataBatch([mx.nd.array(x[:8])],
                            [mx.nd.array(y[:8])])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (8, 4)


def test_bucketing_module():
    """Shape-bucketed training (reference test_module.py bucketing)."""
    x, y = _toy_problem()

    def sym_gen(bucket_key):
        data = mx.sym.var("data")
        net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
        net = mx.sym.Activation(net, act_type="relu", name="relu1")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=20,
                                 context=mx.cpu())
    mod.bind([("data", (32, 20))], [("softmax_label", (32,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.create("acc")
    for _ in range(30):
        for i in range(0, 512, 32):
            batch = mx.io.DataBatch(
                [mx.nd.array(x[i:i + 32])], [mx.nd.array(y[i:i + 32])],
                bucket_key=20,
                provide_data=[("data", (32, 20))],
                provide_label=[("softmax_label", (32,))])
            mod.forward(batch)
            mod.backward()
            mod.update()
    metric.reset()
    for i in range(0, 512, 32):
        batch = mx.io.DataBatch(
            [mx.nd.array(x[i:i + 32])], [mx.nd.array(y[i:i + 32])],
            bucket_key=20,
            provide_data=[("data", (32, 20))],
            provide_label=[("softmax_label", (32,))])
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.95


def test_conv_module():
    """Small conv net trains (reference tests/python/train/test_conv.py)."""
    rng = np.random.RandomState(0)
    n = 256
    x = rng.randn(n, 1, 8, 8).astype("float32")
    y = (x.sum(axis=(1, 2, 3)) > 0).astype("float32")
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    net = mx.sym.Activation(net, act_type="relu", name="act1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max",
                         name="pool1")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.02},
            initializer=mx.initializer.Xavier(), num_epoch=20)
    score = mod.score(train, "acc")
    assert score[0][1] > 0.95, score
