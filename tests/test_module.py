"""Module API tests (reference: tests/python/unittest/test_module.py +
tests/python/train/test_mlp.py — end-to-end convergence asserting final
accuracy, and bind/checkpoint behaviors)."""
import numpy as np
import pytest

import mxtpu as mx


def _toy_problem(n=512, dim=20, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype("float32")
    w = rng.randn(dim, classes).astype("float32")
    y = (x @ w).argmax(axis=1).astype("float32")
    return x, y


def _mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=64, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_module_fit_convergence():
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=32,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, eval_data=val, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(),
            num_epoch=15, eval_metric="acc")
    score = mod.score(val, "acc")
    assert score[0][1] > 0.97, score


def test_module_checkpoint_roundtrip(tmp_path):
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=32,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(), num_epoch=3)
    base = mod.score(val, "acc")
    prefix = str(tmp_path / "mlp")
    mod.save_checkpoint(prefix, 3, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 3)
    mod2.bind(val.provide_data, val.provide_label, for_training=False)
    s2 = mod2.score(val, "acc")
    assert abs(s2[0][1] - base[0][1]) < 1e-6

    preds = mod2.predict(val)
    assert preds.shape == (512, 4)


def test_module_forward_backward_shapes():
    x, y = _toy_problem()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    train = mx.io.NDArrayIter(x, y, batch_size=16,
                              label_name="softmax_label")
    mod.bind(train.provide_data, train.provide_label)
    mod.init_params()
    mod.init_optimizer()
    batch = next(train)
    mod.forward(batch)
    outs = mod.get_outputs()
    assert outs[0].shape == (16, 4)
    mod.backward()
    mod.update()


def test_module_input_grads():
    x, y = _toy_problem()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    train = mx.io.NDArrayIter(x, y, batch_size=16,
                              label_name="softmax_label")
    mod.bind(train.provide_data, train.provide_label, for_training=True,
             inputs_need_grad=True)
    mod.init_params()
    batch = next(train)
    mod.forward(batch)
    mod.backward()
    igrads = mod.get_input_grads()
    assert igrads[0].shape == (16, 20)
    assert float(mx.nd.norm(igrads[0]).asscalar()) > 0


def test_module_multi_device():
    """Data-parallel executor group over multiple faked devices
    (reference tests/python/unittest/test_multi_device_exec.py)."""
    x, y = _toy_problem()
    ctxs = [mx.cpu(0), mx.cpu(1)]
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    val = mx.io.NDArrayIter(x, y, batch_size=32,
                            label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=ctxs)
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.01},
            initializer=mx.initializer.Xavier(), num_epoch=10)
    score = mod.score(val, "acc")
    assert score[0][1] > 0.95, score


def test_module_reshape():
    x, y = _toy_problem()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind([("data", (32, 20))], [("softmax_label", (32,))])
    mod.init_params()
    mod.reshape([("data", (8, 20))], [("softmax_label", (8,))])
    batch = mx.io.DataBatch([mx.nd.array(x[:8])],
                            [mx.nd.array(y[:8])])
    mod.forward(batch, is_train=False)
    assert mod.get_outputs()[0].shape == (8, 4)


def test_bucketing_module():
    """Shape-bucketed training (reference test_module.py bucketing)."""
    x, y = _toy_problem()

    def sym_gen(bucket_key):
        data = mx.sym.var("data")
        net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
        net = mx.sym.Activation(net, act_type="relu", name="relu1")
        net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
        net = mx.sym.SoftmaxOutput(net, name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=20,
                                 context=mx.cpu())
    mod.bind([("data", (32, 20))], [("softmax_label", (32,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.01})
    metric = mx.metric.create("acc")
    for _ in range(30):
        for i in range(0, 512, 32):
            batch = mx.io.DataBatch(
                [mx.nd.array(x[i:i + 32])], [mx.nd.array(y[i:i + 32])],
                bucket_key=20,
                provide_data=[("data", (32, 20))],
                provide_label=[("softmax_label", (32,))])
            mod.forward(batch)
            mod.backward()
            mod.update()
    metric.reset()
    for i in range(0, 512, 32):
        batch = mx.io.DataBatch(
            [mx.nd.array(x[i:i + 32])], [mx.nd.array(y[i:i + 32])],
            bucket_key=20,
            provide_data=[("data", (32, 20))],
            provide_label=[("softmax_label", (32,))])
        mod.forward(batch, is_train=False)
        mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.95


def test_conv_module():
    """Small conv net trains (reference tests/python/train/test_conv.py)."""
    np.random.seed(7)   # init draws from the global stream: keep the test
    mx.random.seed(7)   # independent of how many binds ran before it
    rng = np.random.RandomState(0)
    n = 256
    x = rng.randn(n, 1, 8, 8).astype("float32")
    y = (x.sum(axis=(1, 2, 3)) > 0).astype("float32")
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    net = mx.sym.Activation(net, act_type="relu", name="act1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max",
                         name="pool1")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=2, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    train = mx.io.NDArrayIter(x, y, batch_size=32, shuffle=True,
                              label_name="softmax_label")
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.fit(train, optimizer="adam",
            optimizer_params={"learning_rate": 0.02},
            initializer=mx.initializer.Xavier(), num_epoch=20)
    score = mod.score(train, "acc")
    assert score[0][1] > 0.95, score


def test_train_step_runs_one_fused_computation():
    """After the first backward proves this executor is a loss head, each
    forward(is_train=True)+backward() pair must execute exactly one compiled
    computation — the speculative fused fwd+vjp — not a forward followed by
    a second forward-recomputing fwd_bwd (the reference runs forward nodes
    once and reuses activations, graph_executor.cc:81-109)."""
    data = mx.sym.var("data")
    net = mx.sym.Convolution(data, num_filter=4, kernel=(3, 3), name="conv")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    exe = net.simple_bind(mx.cpu(), grad_req="write",
                          data=(2, 3, 8, 8), softmax_label=(2,))
    calls = {"fwd": 0, "fwd_bwd": 0, "fwd_bwd_ones": 0}

    def counted(name, fn):
        def wrapper(*a, **kw):
            calls[name] += 1
            return fn(*a, **kw)
        return wrapper

    exe._fwd = counted("fwd", exe._fwd)
    exe._fwd_bwd = counted("fwd_bwd", exe._fwd_bwd)
    exe._fwd_bwd_ones = counted("fwd_bwd_ones", exe._fwd_bwd_ones)

    exe.arg_dict["data"][:] = np.random.randn(2, 3, 8, 8).astype("float32")
    exe.arg_dict["softmax_label"][:] = np.array([0.0, 2.0])
    # step 1: plain forward, then backward proves the loss-head pattern
    exe.forward(is_train=True)
    exe.backward()
    assert calls == {"fwd": 1, "fwd_bwd": 0, "fwd_bwd_ones": 1}
    # steady state: ONE fused computation per train step, no plain forward
    for _ in range(3):
        exe.forward(is_train=True)
        exe.backward()
    assert calls == {"fwd": 1, "fwd_bwd": 0, "fwd_bwd_ones": 4}
    # inference forward stays on the plain (non-differentiating) path
    exe.forward(is_train=False)
    assert calls["fwd"] == 2 and calls["fwd_bwd_ones"] == 4


def test_speculative_backward_matches_explicit_cotangents():
    """Speculated grads (ones cotangents fused at forward time) must match
    the explicit fwd_bwd path, an executor that receives out_grads must
    fall back and stop speculating, and mutating a bound array between
    forward and backward must invalidate the speculated grads."""
    x = np.random.RandomState(3).randn(4, 5).astype("float32")
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    net = mx.sym.FullyConnected(data, weight=w, num_hidden=3, no_bias=True,
                                name="fc")
    net = mx.sym.sum(mx.sym.square(net))
    exe1 = net.simple_bind(mx.cpu(), grad_req="write", data=(4, 5))
    exe2 = net.simple_bind(mx.cpu(), grad_req="write", data=(4, 5))
    wval = np.random.RandomState(4).randn(3, 5).astype("float32")
    for exe in (exe1, exe2):
        exe.arg_dict["data"][:] = x
        exe.arg_dict["w"][:] = wval
    exe1.forward(is_train=True)
    exe1.backward()           # enables speculation
    exe1.forward(is_train=True)
    assert exe1._cached_grads is not None   # speculation engaged
    exe1.backward()           # speculative cached path
    assert exe1._cached_grads is None       # served grads are released
    exe2._speculate = False
    exe2.forward(is_train=True)
    exe2.backward()           # classic fwd + fused-ones path
    np.testing.assert_allclose(exe1.grad_dict["w"].asnumpy(),
                               exe2.grad_dict["w"].asnumpy(), rtol=1e-6)
    ref_grad = exe2.grad_dict["w"].asnumpy()
    # mutating an input between forward and backward must not serve the
    # speculated (stale) grads: grads reflect the new value, and the
    # executor stops speculating
    exe1.forward(is_train=True)
    exe1.arg_dict["data"][:] = 2.0 * x
    exe1.backward()
    assert exe1._speculate is False
    np.testing.assert_allclose(exe1.grad_dict["w"].asnumpy(),
                               4.0 * ref_grad, rtol=1e-5)
    # explicit out_grads: correct result + speculation stays off
    exe1.arg_dict["data"][:] = x
    og = mx.nd.array(np.full((), 2.0, dtype="float32"))
    exe1.forward(is_train=True)
    exe1.backward(out_grads=[og])
    assert exe1._speculate is False
    np.testing.assert_allclose(exe1.grad_dict["w"].asnumpy(),
                               2.0 * ref_grad, rtol=1e-6)


def test_train_forward_only_integer_output_ok():
    """A for-training executor whose symbol has an integer output must not
    crash at forward (integer outputs take float0 cotangents in the fused
    speculative pass)."""
    data = mx.sym.var("data")
    fc = mx.sym.FullyConnected(data, num_hidden=3, name="fc")
    grp = mx.sym.Group([mx.sym.SoftmaxOutput(fc, name="softmax"),
                        mx.sym.argmax(fc, axis=1)])
    exe = grp.simple_bind(mx.cpu(), grad_req="write",
                          data=(2, 5), softmax_label=(2,))
    exe.arg_dict["data"][:] = np.random.RandomState(0).randn(2, 5).astype("f")
    exe.arg_dict["fc_weight"][:] = \
        np.random.RandomState(1).randn(3, 5).astype("f")
    outs = exe.forward(is_train=True)
    exe.backward()      # loss head proven -> next forward speculates
    outs = exe.forward(is_train=True)
    exe.backward()
    assert outs[0].shape == (2, 3)
    g = exe.grad_dict["fc_weight"].asnumpy()
    assert np.abs(g).sum() > 0


def test_speculation_demoted_when_backward_stops():
    """Training-mode prediction loops (forward(is_train=True) with no
    backward) must not keep paying for speculated backwards: one unserved
    speculation demotes the executor back to plain forwards."""
    data = mx.sym.var("data")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(data, num_hidden=3),
                               name="softmax")
    exe = net.simple_bind(mx.cpu(), grad_req="write",
                          data=(2, 5), softmax_label=(2,))
    calls = {"ones": 0}
    orig = exe._fwd_bwd_ones

    def counting(*a, **kw):
        calls["ones"] += 1
        return orig(*a, **kw)

    exe._fwd_bwd_ones = counting
    exe.forward(is_train=True)
    exe.backward()                    # proves loss head
    exe.forward(is_train=True)        # speculates (1 fused call) ...
    assert calls["ones"] == 2         # (backward fallback + speculation)
    for _ in range(4):                # ... but nobody calls backward
        exe.forward(is_train=True)
    assert exe._speculate is False
    assert calls["ones"] == 2         # exactly one wasted pass, then heals
