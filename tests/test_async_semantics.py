"""Async-dispatch semantics stress tier.

The reference proves its dependency engine with randomized dependency
graphs compared against serial execution
(tests/cpp/engine/threaded_engine_test.cc:124-278 RandSumExpr) and
transports kernel exceptions to the WaitForVar sync point
(docs/architecture/exception_handling.md). mxtpu's equivalents:

* random in-place NDArray mutation/dependency chains executed under the
  default async dispatch must produce bitwise-identical results to the
  same program under NaiveEngine (every op synchronous);
* an error raised inside compiled device code (a host callback in a
  jitted graph, the only runtime-raising path on this backend) must NOT
  fire at dispatch — it must surface at the sync point (`asnumpy` /
  `wait_to_read` / `waitall`) with the op's message intact.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import engine


def _random_program(seed, sync):
    """Run a randomized mutation/dependency chain; return final states.

    Mixes the hazard classes the reference engine test exercises:
    read-after-write (use a freshly assigned array), write-after-read
    (mutate an array another op just consumed), write-after-write
    (reassign the same slot twice), plus views/slices, accumulation
    (+=), cross-array reductions and an executor in the middle.
    """
    engine.set_engine_type("NaiveEngine" if sync
                           else "ThreadedEnginePerDevice")
    try:
        rng = np.random.RandomState(seed)
        n, shape = 6, (4, 4)
        arrs = [mx.nd.array(rng.randn(*shape).astype("f"))
                for _ in range(n)]
        for _ in range(120):
            op = rng.randint(7)
            i, j, k = rng.randint(n, size=3)
            if op == 0:      # WAW + RAW: full reassignment from two reads
                arrs[i][:] = arrs[j] + 0.5 * arrs[k]
            elif op == 1:    # accumulation (kAddTo-style)
                arrs[i] += arrs[j]
            elif op == 2:    # matmul dependency
                arrs[i][:] = mx.nd.dot(arrs[j], arrs[k]) * 0.1
            elif op == 3:    # slice-view write (partial mutation)
                r = rng.randint(shape[0])
                arrs[i][r] = arrs[j][shape[0] - 1 - r]
            elif op == 4:    # reduce -> broadcast back in
                s = mx.nd.sum(arrs[j], axis=0, keepdims=True)
                arrs[i][:] = mx.nd.broadcast_to(s, shape) / shape[0]
            elif op == 5:    # elementwise chain with a copy hazard
                tmp = arrs[j].copy()
                arrs[j][:] = -arrs[j]
                arrs[i][:] = tmp * 2.0 + arrs[k]
            else:            # scalar mutation everyone downstream reads
                arrs[i] *= 0.9
        return [a.asnumpy().copy() for a in arrs]
    finally:
        engine.set_engine_type("ThreadedEnginePerDevice")


@pytest.mark.parametrize("seed", [0, 7, 1234])
def test_random_mutation_chains_async_matches_naive(seed):
    async_out = _random_program(seed, sync=False)
    sync_out = _random_program(seed, sync=True)
    for a, b in zip(async_out, sync_out):
        np.testing.assert_array_equal(a, b)


def _failing_custom_net():
    class FailingOp(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0].asnumpy()
            if np.any(x > 1e5):
                raise ValueError("poisoned activation in failing_op")
            self.assign(out_data[0], req[0], in_data[0])

        def backward(self, req, out_grad, in_grad, out_data, in_data, aux):
            self.assign(in_grad[0], req[0], out_grad[0])

    @mx.operator.register("failing_op_async_test")
    class FailingProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return FailingOp()

    return FailingProp


def test_async_error_surfaces_at_sync_point():
    _failing_custom_net()
    data = mx.sym.var("data")
    net = mx.sym.Custom(data, op_type="failing_op_async_test")
    net = net * 2.0
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(2, 3))

    # healthy input: flows through
    exe.arg_dict["data"][:] = np.ones((2, 3), "f")
    out = exe.forward(is_train=False)[0]
    np.testing.assert_allclose(out.asnumpy(), 2 * np.ones((2, 3)))

    # poisoned input: the raise happens inside the compiled graph's host
    # callback; it must surface at the value sync with the message
    exe.arg_dict["data"][:] = np.full((2, 3), 1e6, "f")
    with pytest.raises(Exception, match="poisoned activation"):
        out = exe.forward(is_train=False)[0]
        out.asnumpy()


def test_async_error_surfaces_at_waitall():
    """Engine::WaitForAll is also a sync point for pending failures."""
    _ = _failing_custom_net  # registered by the test above or here
    try:
        prop = _failing_custom_net()
    except Exception:
        prop = None  # already registered under this op_type
    x = mx.nd.array(np.full((2, 3), 1e6, "f"))
    with pytest.raises(Exception, match="poisoned activation"):
        y = mx.nd.Custom(x, op_type="failing_op_async_test")
        y = y + 1.0
        engine.waitall()
        y.asnumpy()


# ---------------------------------------------------------------------------
# no hidden host syncs in steady-state dispatch paths
# ---------------------------------------------------------------------------

class _iter_trap:
    """Fail the test if anything iterates a concrete jax.Array.

    Array.__iter__ materializes chunks on the host — a silent
    async-queue drain per call. Through a TPU relay with ~ms round
    trips it serializes dispatch entirely; tuple-unpacking
    jax.random.split's result did exactly this in every hybridized
    forward until round 5 (fix: ops.registry.split2). Steady-state hot
    paths must never iterate concrete arrays; this trap pins that."""

    def __enter__(self):
        import jax._src.array as jarray
        self._mod = jarray
        self._orig = jarray.ArrayImpl.__iter__

        def trap(_self):
            raise AssertionError(
                "jax.Array.__iter__ in a steady-state dispatch path "
                "(host-sync hazard; use ops.registry.split2-style "
                "indexing instead of unpacking/iterating)")
        jarray.ArrayImpl.__iter__ = trap
        return self

    def __exit__(self, *a):
        self._mod.ArrayImpl.__iter__ = self._orig


def test_hybrid_forward_iterates_no_concrete_arrays():
    from mxtpu.gluon import nn
    import mxtpu as mx2
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.Flatten(), nn.Dense(8))
    net.initialize(mx2.init.Xavier())
    net.hybridize()
    x = mx2.nd.array(np.random.rand(2, 3, 8, 8).astype("f"))
    net(x)  # compile outside the trap
    with _iter_trap():
        for _ in range(3):
            out = net(x)
    out.wait_to_read()


def test_sharded_trainer_step_iterates_no_concrete_arrays():
    import jax
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import MeshContext, ShardedTrainer
    import mxtpu as mx2
    net = nn.HybridSequential()
    net.add(nn.Dense(16), nn.Activation("relu"), nn.Dense(4))
    net.initialize(mx2.init.Xavier())
    x = np.random.rand(8, 8).astype("f")
    y = np.random.randint(0, 4, (8,)).astype("f")
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(), "sgd",
                        {"learning_rate": 0.1},
                        mesh=MeshContext(jax.devices()[:1], data=1))
    st.step(x, y)  # compile + materialize device step state
    xd = st._shard_batch([x])[0]
    yd = st._shard_batch([y])[0]
    with _iter_trap():
        for _ in range(3):
            loss = st.step_async(xd, yd)
    float(loss.asnumpy())


def test_iter_trap_catches_the_old_pattern():
    import jax
    with _iter_trap():
        with pytest.raises(AssertionError, match="host-sync hazard"):
            _a, _b = jax.random.split(jax.random.PRNGKey(0))


def test_split2_matches_unpack_values():
    """split2 replaced 'a, b = jax.random.split(k)' in eager paths for
    dispatch-async reasons; the VALUES must be identical or every
    seeded model in the zoo quietly reproduces differently."""
    import jax
    from mxtpu.ops.registry import split2
    k = jax.random.PRNGKey(42)
    ks = np.asarray(jax.random.split(k))
    a, b = split2(k)
    np.testing.assert_array_equal(np.asarray(a), ks[0])
    np.testing.assert_array_equal(np.asarray(b), ks[1])
