"""Async-dispatch semantics stress tier.

The reference proves its dependency engine with randomized dependency
graphs compared against serial execution
(tests/cpp/engine/threaded_engine_test.cc:124-278 RandSumExpr) and
transports kernel exceptions to the WaitForVar sync point
(docs/architecture/exception_handling.md). mxtpu's equivalents:

* random in-place NDArray mutation/dependency chains executed under the
  default async dispatch must produce bitwise-identical results to the
  same program under NaiveEngine (every op synchronous);
* an error raised inside compiled device code (a host callback in a
  jitted graph, the only runtime-raising path on this backend) must NOT
  fire at dispatch — it must surface at the sync point (`asnumpy` /
  `wait_to_read` / `waitall`) with the op's message intact.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import engine


def _random_program(seed, sync):
    """Run a randomized mutation/dependency chain; return final states.

    Mixes the hazard classes the reference engine test exercises:
    read-after-write (use a freshly assigned array), write-after-read
    (mutate an array another op just consumed), write-after-write
    (reassign the same slot twice), plus views/slices, accumulation
    (+=), cross-array reductions and an executor in the middle.
    """
    engine.set_engine_type("NaiveEngine" if sync
                           else "ThreadedEnginePerDevice")
    try:
        rng = np.random.RandomState(seed)
        n, shape = 6, (4, 4)
        arrs = [mx.nd.array(rng.randn(*shape).astype("f"))
                for _ in range(n)]
        for _ in range(120):
            op = rng.randint(7)
            i, j, k = rng.randint(n, size=3)
            if op == 0:      # WAW + RAW: full reassignment from two reads
                arrs[i][:] = arrs[j] + 0.5 * arrs[k]
            elif op == 1:    # accumulation (kAddTo-style)
                arrs[i] += arrs[j]
            elif op == 2:    # matmul dependency
                arrs[i][:] = mx.nd.dot(arrs[j], arrs[k]) * 0.1
            elif op == 3:    # slice-view write (partial mutation)
                r = rng.randint(shape[0])
                arrs[i][r] = arrs[j][shape[0] - 1 - r]
            elif op == 4:    # reduce -> broadcast back in
                s = mx.nd.sum(arrs[j], axis=0, keepdims=True)
                arrs[i][:] = mx.nd.broadcast_to(s, shape) / shape[0]
            elif op == 5:    # elementwise chain with a copy hazard
                tmp = arrs[j].copy()
                arrs[j][:] = -arrs[j]
                arrs[i][:] = tmp * 2.0 + arrs[k]
            else:            # scalar mutation everyone downstream reads
                arrs[i] *= 0.9
        return [a.asnumpy().copy() for a in arrs]
    finally:
        engine.set_engine_type("ThreadedEnginePerDevice")


@pytest.mark.parametrize("seed", [0, 7, 1234])
def test_random_mutation_chains_async_matches_naive(seed):
    async_out = _random_program(seed, sync=False)
    sync_out = _random_program(seed, sync=True)
    for a, b in zip(async_out, sync_out):
        np.testing.assert_array_equal(a, b)


def _failing_custom_net():
    class FailingOp(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            x = in_data[0].asnumpy()
            if np.any(x > 1e5):
                raise ValueError("poisoned activation in failing_op")
            self.assign(out_data[0], req[0], in_data[0])

        def backward(self, req, out_grad, in_grad, out_data, in_data, aux):
            self.assign(in_grad[0], req[0], out_grad[0])

    @mx.operator.register("failing_op_async_test")
    class FailingProp(mx.operator.CustomOpProp):
        def list_arguments(self):
            return ["data"]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]], []

        def create_operator(self, ctx, shapes, dtypes):
            return FailingOp()

    return FailingProp


def test_async_error_surfaces_at_sync_point():
    _failing_custom_net()
    data = mx.sym.var("data")
    net = mx.sym.Custom(data, op_type="failing_op_async_test")
    net = net * 2.0
    exe = net.simple_bind(mx.cpu(), grad_req="null", data=(2, 3))

    # healthy input: flows through
    exe.arg_dict["data"][:] = np.ones((2, 3), "f")
    out = exe.forward(is_train=False)[0]
    np.testing.assert_allclose(out.asnumpy(), 2 * np.ones((2, 3)))

    # poisoned input: the raise happens inside the compiled graph's host
    # callback; it must surface at the value sync with the message
    exe.arg_dict["data"][:] = np.full((2, 3), 1e6, "f")
    with pytest.raises(Exception, match="poisoned activation"):
        out = exe.forward(is_train=False)[0]
        out.asnumpy()


def test_async_error_surfaces_at_waitall():
    """Engine::WaitForAll is also a sync point for pending failures."""
    _ = _failing_custom_net  # registered by the test above or here
    try:
        prop = _failing_custom_net()
    except Exception:
        prop = None  # already registered under this op_type
    x = mx.nd.array(np.full((2, 3), 1e6, "f"))
    with pytest.raises(Exception, match="poisoned activation"):
        y = mx.nd.Custom(x, op_type="failing_op_async_test")
        y = y + 1.0
        engine.waitall()
        y.asnumpy()
