"""Sparse NDArray tests, modeled on the reference suites
tests/python/unittest/test_sparse_ndarray.py and test_sparse_operator.py:
construction, cast_storage round trips, retain, sparse dot, stype-aware
arithmetic, lazy optimizer updates, kvstore row_sparse_pull, serialization.
"""
import os
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def _rand_rsp(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(*shape).astype(np.float32)
    mask = rng.rand(shape[0]) < density
    dense[~mask] = 0
    return nd.sparse.row_sparse_array(nd.array(dense)), dense


def _rand_csr(shape, density=0.3, seed=0):
    rng = np.random.RandomState(seed)
    dense = rng.randn(*shape).astype(np.float32)
    dense[rng.rand(*shape) >= density] = 0
    return nd.sparse.csr_matrix(nd.array(dense)), dense


def test_csr_construction():
    data = np.array([1., 2., 3., 4.])
    indices = np.array([0, 2, 1, 3])
    indptr = np.array([0, 2, 3, 4])
    a = nd.sparse.csr_matrix((data, indices, indptr), shape=(3, 4))
    expect = np.array([[1, 0, 2, 0], [0, 3, 0, 0], [0, 0, 0, 4]], np.float32)
    np.testing.assert_array_equal(a.asnumpy(), expect)
    assert a.stype == "csr"
    assert a.nnz == 4
    np.testing.assert_array_equal(a.indices.asnumpy(), indices)
    np.testing.assert_array_equal(a.indptr.asnumpy(), indptr)
    np.testing.assert_array_equal(a.data.asnumpy(), data)


def test_rsp_construction_and_explicit_zero_rows():
    data = np.zeros((2, 3), np.float32)
    data[0] = 1.0
    idx = np.array([1, 4])
    r = nd.sparse.row_sparse_array((data, idx), shape=(6, 3))
    assert r.stype == "row_sparse"
    # explicit zero row stays stored
    np.testing.assert_array_equal(r.indices.asnumpy(), idx)
    assert r.nnz == 2
    dense = r.asnumpy()
    np.testing.assert_array_equal(dense[1], np.ones(3))
    np.testing.assert_array_equal(dense[4], np.zeros(3))


def test_cast_storage_round_trip():
    a = nd.array(np.array([[0, 1.5], [0, 0], [2.5, 0]], np.float32))
    for stype in ("csr", "row_sparse"):
        s = a.tostype(stype)
        assert s.stype == stype
        np.testing.assert_array_equal(s.asnumpy(), a.asnumpy())
        back = s.tostype("default")
        assert back.stype == "default"
        np.testing.assert_array_equal(back.asnumpy(), a.asnumpy())


def test_retain():
    r, dense = _rand_rsp((8, 4), density=0.9, seed=1)
    kept = nd.sparse.retain(r, nd.array(np.array([0, 3, 7])))
    expect = np.zeros_like(dense)
    for i in (0, 3, 7):
        expect[i] = dense[i]
    np.testing.assert_allclose(kept.asnumpy(), expect, rtol=1e-6)
    assert set(kept.indices.asnumpy().tolist()) <= {0, 3, 7}


def test_sparse_dot():
    a, da = _rand_csr((5, 7), seed=2)
    b = np.random.RandomState(3).randn(7, 4).astype(np.float32)
    out = nd.sparse.dot(a, nd.array(b))
    np.testing.assert_allclose(out.asnumpy(), da @ b, rtol=1e-5, atol=1e-5)
    # csr^T . dense -> row_sparse
    c = np.random.RandomState(4).randn(5, 4).astype(np.float32)
    out_t = nd.sparse.dot(a, nd.array(c), transpose_a=True)
    assert out_t.stype == "row_sparse"
    np.testing.assert_allclose(out_t.asnumpy(), da.T @ c, rtol=1e-5, atol=1e-5)


def test_rsp_arithmetic_keeps_stype():
    a, da = _rand_rsp((6, 3), seed=5)
    b, db = _rand_rsp((6, 3), seed=6)
    out = nd.sparse.add(a, b)
    assert out.stype == "row_sparse"
    np.testing.assert_allclose(out.asnumpy(), da + db, rtol=1e-6)
    out2 = nd.sparse.multiply(a, b)
    np.testing.assert_allclose(out2.asnumpy(), da * db, rtol=1e-6)


def test_dense_op_fallback():
    """Any dense op accepts a sparse array (the storage-fallback path)."""
    a, da = _rand_csr((4, 4), seed=7)
    out = nd.relu(a)
    np.testing.assert_allclose(out.asnumpy(), np.maximum(da, 0), rtol=1e-6)


@pytest.mark.parametrize("opt_name,opt_kwargs", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.1}),
    ("adagrad", {"learning_rate": 0.1}),
    ("ftrl", {"learning_rate": 0.1}),
])
def test_lazy_optimizer_update(opt_name, opt_kwargs):
    """Lazy update touches only stored rows; untouched rows keep both
    weight and state unchanged (reference sgd_update FComputeEx on rsp)."""
    from mxtpu import optimizer as opt
    shape = (10, 4)
    rng = np.random.RandomState(8)
    w0 = rng.randn(*shape).astype(np.float32)

    o = opt.create(opt_name, **opt_kwargs)
    w = nd.array(w0.copy())
    state = o.create_state(0, w)

    g_rows = rng.randn(3, 4).astype(np.float32)
    grad = nd.sparse.row_sparse_array((g_rows, np.array([1, 5, 6])),
                                      shape=shape)
    o.update(0, w, grad, state)
    new_w = w.asnumpy()
    touched = [1, 5, 6]
    untouched = [i for i in range(10) if i not in touched]
    np.testing.assert_array_equal(new_w[untouched], w0[untouched])
    assert not np.allclose(new_w[touched], w0[touched])

    # dense reference: same math on a dense grad restricted to those rows
    o2 = opt.create(opt_name, **opt_kwargs)
    w2 = nd.array(w0.copy())
    state2 = o2.create_state(0, w2)
    dense_grad = np.zeros(shape, np.float32)
    dense_grad[touched] = g_rows
    o2.update(0, w2, nd.array(dense_grad), state2)
    np.testing.assert_allclose(new_w[touched], w2.asnumpy()[touched],
                               rtol=1e-5, atol=1e-6)


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("local")
    shape = (8, 3)
    val = np.random.RandomState(9).randn(*shape).astype(np.float32)
    kv.init("w", nd.array(val))
    out = nd.sparse.zeros("row_sparse", shape)
    kv.row_sparse_pull("w", out=out, row_ids=nd.array(np.array([2, 5])))
    assert out.stype == "row_sparse"
    res = out.asnumpy()
    np.testing.assert_allclose(res[2], val[2], rtol=1e-6)
    np.testing.assert_allclose(res[5], val[5], rtol=1e-6)
    np.testing.assert_array_equal(res[0], np.zeros(3))


def test_sparse_save_load(tmp_path):
    a, da = _rand_csr((4, 6), seed=10)
    r, dr = _rand_rsp((5, 2), seed=11)
    d = nd.array(np.ones((2, 2), np.float32))
    fname = str(tmp_path / "arrs.params")
    nd.save(fname, {"a": a, "r": r, "d": d})
    back = nd.load(fname)
    assert back["a"].stype == "csr"
    assert back["r"].stype == "row_sparse"
    assert back["d"].stype == "default"
    np.testing.assert_allclose(back["a"].asnumpy(), da, rtol=1e-6)
    np.testing.assert_allclose(back["r"].asnumpy(), dr, rtol=1e-6)


def test_sparse_zeros():
    z = nd.sparse.zeros("csr", (3, 4))
    assert z.stype == "csr" and z.nnz == 0
    np.testing.assert_array_equal(z.asnumpy(), np.zeros((3, 4)))
    z2 = nd.sparse.zeros("row_sparse", (3, 4))
    assert z2.stype == "row_sparse" and z2.nnz == 0


def test_dense_pull_not_zeroed_by_row_sparse_pull():
    """Pulling into a full-shape dense out must keep all rows (regression:
    Module.prepare pulls into full executor buffers)."""
    kv = mx.kv.create("local")
    val = np.arange(12, dtype=np.float32).reshape(4, 3)
    kv.init("w", nd.array(val))
    dense_out = nd.zeros((4, 3))
    kv.row_sparse_pull("w", out=dense_out, row_ids=nd.array(np.array([1])))
    np.testing.assert_array_equal(dense_out.asnumpy(), val)


def test_push_rsp_list_unions_rows():
    """Multi-device rsp gradient push must union stored rows (regression:
    only device 0's rows were visible to the lazy updater)."""
    from mxtpu import optimizer as opt
    kv = mx.kv.create("local")
    val = np.arange(12, dtype=np.float32).reshape(4, 3)
    kv.init("w", nd.array(val))
    g1 = nd.sparse.row_sparse_array((np.ones((1, 3), np.float32),
                                     np.array([0])), shape=(4, 3))
    g2 = nd.sparse.row_sparse_array((np.ones((1, 3), np.float32),
                                     np.array([2])), shape=(4, 3))
    kv._updater = opt.get_updater(opt.create("sgd", learning_rate=1.0, wd=0.0))
    kv.push("w", [g1, g2])
    got = kv._store["w"].asnumpy()
    assert not np.allclose(got[0], val[0])
    assert not np.allclose(got[2], val[2])
    np.testing.assert_array_equal(got[1], val[1])
    np.testing.assert_array_equal(got[3], val[3])


def test_sparse_astype_preserves_stype():
    c = nd.array(np.eye(3, dtype=np.float32)).tostype("csr").astype("float16")
    assert c.stype == "csr"
    np.testing.assert_array_equal(c.indptr.asnumpy(), [0, 1, 2, 3])
    r = nd.array(np.eye(3, dtype=np.float32)).tostype("row_sparse")
    assert r.astype("float16").stype == "row_sparse"


def test_save_rejects_reserved_keys():
    with pytest.raises(ValueError):
        nd.save("/tmp/reserved.params", {"a::b": nd.zeros((1,))})


def test_sparse_copyto_syncs_metadata():
    a = nd.array(np.eye(4, dtype=np.float32)).tostype("row_sparse")
    b = nd.sparse.zeros("row_sparse", (4, 4))
    a.copyto(b)
    np.testing.assert_array_equal(b.asnumpy(), np.eye(4))
    np.testing.assert_array_equal(b.indices.asnumpy(), [0, 1, 2, 3])
