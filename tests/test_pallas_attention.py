"""Pallas flash-attention kernels vs the pure-XLA reference.

Runs through the Pallas interpreter on the CPU test mesh; on TPU the
same code compiles to Mosaic. Checks forward + backward, causal masks,
sequence-shard offsets, padding (non-block-multiple T), bf16 inputs,
and integration via local_attention / the op registry.
"""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

from mxtpu.ops.pallas_attention import (flash_attention,
                                        flash_attention_reference)


def _rand(shape, dtype=np.float32, seed=0):
    rng = np.random.RandomState(seed)
    return jnp.asarray(rng.standard_normal(shape).astype(dtype))


def _check(q, k, v, causal=False, q_offset=0, k_offset=0, tol=2e-5,
           block_q=64, block_k=64):
    out = flash_attention(q, k, v, causal=causal, q_offset=q_offset,
                          k_offset=k_offset, block_q=block_q,
                          block_k=block_k)
    ref = flash_attention_reference(q, k, v, causal=causal,
                                    q_offset=q_offset, k_offset=k_offset)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=tol, rtol=tol)


def test_forward_matches_reference():
    q, k, v = (_rand((2, 3, 128, 64), seed=i) for i in range(3))
    _check(q, k, v)


def test_forward_causal():
    q, k, v = (_rand((1, 2, 128, 32), seed=i + 7) for i in range(3))
    _check(q, k, v, causal=True)


def test_forward_multi_block():
    q, k, v = (_rand((1, 2, 256, 32), seed=i + 3) for i in range(3))
    _check(q, k, v, causal=True, block_q=64, block_k=64)


def test_forward_unpadded_lengths():
    # T not a multiple of the block size: wrapper pads, kernel masks.
    q = _rand((1, 2, 100, 32), seed=1)
    k = _rand((1, 2, 72, 32), seed=2)
    v = _rand((1, 2, 72, 32), seed=3)
    _check(q, k, v, block_q=64, block_k=64)
    _check(q, k, v, causal=True, block_q=64, block_k=64)


def test_sequence_shard_offsets():
    # Causal mask with sharded sequence: device holding rows [64, 128)
    # attending a K/V block holding rows [0, 64) must be fully visible;
    # the reverse fully masked.
    q, k, v = (_rand((1, 1, 64, 32), seed=i + 11) for i in range(3))
    _check(q, k, v, causal=True, q_offset=64, k_offset=0)
    # fully-masked rows must produce zeros, not NaNs
    out = flash_attention(q, k, v, causal=True, q_offset=0, k_offset=64,
                          block_q=64, block_k=64)
    assert np.isfinite(np.asarray(out)).all()
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_traced_offsets():
    q, k, v = (_rand((1, 1, 64, 32), seed=i + 5) for i in range(3))

    @jax.jit
    def f(qo):
        return flash_attention(q, k, v, causal=True, q_offset=qo,
                               k_offset=0, block_q=64, block_k=64)

    out = f(jnp.int32(64))
    ref = flash_attention_reference(q, k, v, causal=True, q_offset=64)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_reference(causal):
    q, k, v = (_rand((1, 2, 128, 32), seed=i + 21) for i in range(3))

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        return jnp.sum(jnp.sin(o))

    def loss_ref(q, k, v):
        o = flash_attention_reference(q, k, v, causal=causal)
        return jnp.sum(jnp.sin(o))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_flash, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_gradients_unpadded():
    q = _rand((1, 1, 96, 32), seed=31)
    k = _rand((1, 1, 80, 32), seed=32)
    v = _rand((1, 1, 80, 32), seed=33)

    def loss(fn, *args):
        return jnp.sum(fn(*args) ** 2)

    gf = jax.grad(lambda a, b, c: loss(
        lambda *x: flash_attention(*x, causal=True, block_q=64, block_k=64),
        a, b, c), argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda a, b, c: loss(
        lambda *x: flash_attention_reference(*x, causal=True),
        a, b, c), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_bf16_inputs():
    q, k, v = (_rand((1, 2, 128, 64), seed=i).astype(jnp.bfloat16)
               for i in range(3))
    out = flash_attention(q, k, v, block_q=64, block_k=64)
    ref = flash_attention_reference(q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, dtype=np.float32),
                               np.asarray(ref, dtype=np.float32),
                               atol=3e-2, rtol=3e-2)


def test_local_attention_flash_impl():
    from mxtpu.parallel.ring_attention import local_attention
    q, k, v = (_rand((1, 2, 128, 32), seed=i + 41) for i in range(3))
    out = local_attention(q, k, v, causal=True, impl="flash")
    ref = local_attention(q, k, v, causal=True, impl="xla")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_registered_as_op():
    from mxtpu.ops import get_op
    assert get_op("_contrib_flash_attention") is not None
    assert get_op("flash_attention") is not None


def test_nd_namespace():
    import mxtpu as mx
    q, k, v = (_rand((1, 1, 64, 32), seed=i + 51) for i in range(3))
    out = mx.nd.flash_attention(mx.nd.array(np.asarray(q)),
                                mx.nd.array(np.asarray(k)),
                                mx.nd.array(np.asarray(v)))
    ref = flash_attention_reference(q, k, v)
    np.testing.assert_allclose(out.asnumpy(), np.asarray(ref), atol=2e-5)


def test_with_lse_matches_logsumexp():
    from mxtpu.ops.pallas_attention import flash_attention_with_lse
    q, k, v = (_rand((1, 2, 128, 32), seed=i + 61) for i in range(3))
    o, lse = flash_attention_with_lse(q, k, v, block_q=64, block_k=64)
    d = q.shape[-1]
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / d ** 0.5
    ref_lse = jax.scipy.special.logsumexp(s, axis=-1)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(ref_lse),
                               atol=2e-5, rtol=2e-5)


def test_lse_merge_rule():
    # attention over [K1; K2] == lse-merge of attention over K1 and K2
    from mxtpu.ops.pallas_attention import flash_attention_with_lse
    q = _rand((1, 1, 64, 32), seed=71)
    k = _rand((1, 1, 128, 32), seed=72)
    v = _rand((1, 1, 128, 32), seed=73)
    o1, l1 = flash_attention_with_lse(q, k[:, :, :64], v[:, :, :64],
                                      block_q=64, block_k=64)
    o2, l2 = flash_attention_with_lse(q, k[:, :, 64:], v[:, :, 64:],
                                      block_q=64, block_k=64)
    lm = jnp.logaddexp(l1, l2)
    om = o1 * jnp.exp(l1 - lm)[..., None] + o2 * jnp.exp(l2 - lm)[..., None]
    full = flash_attention_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(om), np.asarray(full),
                               atol=2e-5, rtol=2e-5)


def test_with_lse_gradients():
    # d(lse)/d(q,k) path through the custom VJP
    from mxtpu.ops.pallas_attention import flash_attention_with_lse
    q, k, v = (_rand((1, 1, 64, 16), seed=i + 81) for i in range(3))

    def loss_flash(q, k, v):
        o, lse = flash_attention_with_lse(q, k, v, block_q=64, block_k=64)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    def loss_ref(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bhqd,bhkd->bhqk", q, k) / d ** 0.5
        o = jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s, -1), v)
        lse = jax.scipy.special.logsumexp(s, axis=-1)
        return jnp.sum(o ** 2) + jnp.sum(jnp.sin(lse))

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_traced_scale():
    q, k, v = (_rand((1, 1, 64, 32), seed=i + 91) for i in range(3))

    @jax.jit
    def f(s):
        return flash_attention(q, k, v, scale=s, block_q=64, block_k=64)

    out = f(jnp.float32(0.1))
    ref = flash_attention_reference(q, k, v, scale=0.1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_flash_impl(causal):
    from mxtpu.parallel import MeshContext
    from mxtpu.parallel.ring_attention import ring_attention_sharded
    mc = MeshContext(jax.devices(), data=1, seq=8)
    rng = np.random.RandomState(5)
    qq, kk, vv = (jnp.asarray(
        rng.standard_normal((1, 2, 128, 16)).astype(np.float32))
        for _ in range(3))
    out = ring_attention_sharded(qq, kk, vv, mc, causal=causal,
                                 impl="flash")
    ref = flash_attention_reference(qq, kk, vv, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=3e-5, rtol=3e-5)


def test_ring_attention_flash_grad():
    from mxtpu.parallel import MeshContext
    from mxtpu.parallel.ring_attention import ring_attention_sharded
    mc = MeshContext(jax.devices(), data=1, seq=4)
    rng = np.random.RandomState(6)
    qq, kk, vv = (jnp.asarray(
        rng.standard_normal((1, 1, 64, 16)).astype(np.float32))
        for _ in range(3))

    def loss(impl, q, k, v):
        o = ring_attention_sharded(q, k, v, mc, causal=True, impl=impl)
        return jnp.sum(o ** 2)

    gf = jax.grad(lambda *a: loss("flash", *a), argnums=(0, 1, 2))(qq, kk, vv)
    gx = jax.grad(lambda *a: loss("xla", *a), argnums=(0, 1, 2))(qq, kk, vv)
    for a, b in zip(gf, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_traced_scale_gradient():
    # a learnable attention temperature must receive a real gradient
    q, k, v = (_rand((1, 1, 64, 16), seed=i + 101) for i in range(3))

    def loss_flash(s):
        return jnp.sum(flash_attention(q, k, v, scale=s,
                                       block_q=64, block_k=64) ** 2)

    def loss_ref(s):
        return jnp.sum(flash_attention_reference(q, k, v, scale=s) ** 2)

    g = jax.grad(loss_flash)(jnp.float32(0.2))
    gr = jax.grad(loss_ref)(jnp.float32(0.2))
    assert float(jnp.abs(g)) > 0
    np.testing.assert_allclose(float(g), float(gr), rtol=1e-4)
