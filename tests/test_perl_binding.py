"""Perl binding tier: build the AI::MXTpu XS module against libmxtpu_c.so
and run its test suite. Reference counterpart: perl-package/AI-MXNet tests.
Proves the core C ABI is consumable from a non-Python host runtime."""
import os
import shutil
import subprocess

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_PKG = os.path.join(_ROOT, "perl-package", "AI-MXTpu")
_NATIVE = os.path.join(_ROOT, "mxtpu", "_native")


def test_perl_binding(tmp_path):
    if shutil.which("perl") is None:
        pytest.skip("no perl")
    probe = subprocess.run(["perl", "-MExtUtils::MakeMaker", "-e", "1"],
                           capture_output=True)
    if probe.returncode != 0:
        pytest.skip("no ExtUtils::MakeMaker")
    res = subprocess.run(["make", "-C", _NATIVE, "libmxtpu_c.so"],
                         capture_output=True, text=True)
    if res.returncode != 0:
        pytest.skip("libmxtpu_c.so build failed: " + res.stderr[-500:])
    env = dict(os.environ, MXTPU_ROOT=_ROOT, PYTHONPATH=_ROOT,
               JAX_PLATFORMS="cpu")
    subprocess.run(["perl", "Makefile.PL"], cwd=_PKG, env=env, check=True,
                   capture_output=True)
    subprocess.run(["make"], cwd=_PKG, env=env, check=True,
                   capture_output=True)
    res = subprocess.run(["perl", "t/01_basic.t"], cwd=_PKG, env=env,
                         capture_output=True, text=True, timeout=600)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "ok 7" in res.stdout, res.stdout
