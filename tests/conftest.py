"""Test configuration: force a virtual 8-device CPU mesh.

Mirrors the reference's strategy of faking multi-device with multiple CPU
contexts in one process (tests/python/unittest/test_multi_device_exec.py):
here we give XLA 8 host devices so jax.sharding Meshes exercise real
collectives without TPU hardware.

Note: the environment's sitecustomize registers an `axon` TPU backend and
calls jax.config.update("jax_platforms", "axon,cpu") at interpreter start,
which overrides the JAX_PLATFORMS env var — so we must force the config
value back to "cpu" after importing jax, or tests would try to grab the
(single, possibly busy) TPU chip.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


# ---------------------------------------------------------------------------
# fast/slow partition (docs/testing.md): `-m fast` is the pre-merge tier
# (< 2 min); the full suite is the nightly tier. Files listed here spawn
# subprocesses (launchers, native builds, example scripts) or run
# multi-minute sweeps; everything else is fast by default.
# ---------------------------------------------------------------------------
SLOW_FILES = {
    "test_bench_contract.py",     # bench.py child process end to end
    "test_bf16_training.py",      # convergence runs
    "test_c_api.py",              # builds + runs pure-C LeNet training
    "test_c_predict.py",          # native predict builds
    "test_caffe_converter.py",    # converter round trips
    "test_checkpoint.py",         # orbax async + elastic restart
    "test_cpp_package.py",        # compiles + converges C++ LeNet
    "test_dist_launch.py",        # multi-process jax.distributed
    "test_gluon.py",              # model-zoo family forwards
    "test_image_det.py",          # detection aug pipelines
    "test_io.py",                 # record pipelines + process pools
    "test_legacy_params.py",      # model-zoo weight migration subprocess
    "test_module.py",             # fit() convergence runs
    "test_native_cpp.py",         # g++ builds
    "test_onnx_import.py",        # protobuf model imports
    "test_op_sweep.py",           # whole-registry sweep (minutes)
    "test_op_variants.py",        # parameter-grid sweeps
    "test_operator.py",
    "test_parallel.py",           # 8-device mesh shardings
    "test_pallas_attention.py",   # interpreter-mode kernels
    "test_pallas_rnn.py",
    "test_perl_binding.py",       # perl Makefile.PL build
    "test_r_binding.py",          # gcc typecheck
    "test_remat.py",
    "test_rnn.py",
    "test_sparse.py",
    "test_train_scripts.py",      # example/ scripts end to end
    "test_text_image.py",
    "test_nhwc_layout.py",        # resnet-block layout bit-compat (20s)
    "test_vision_ops.py",         # multibox/proposal/nms sweeps
    "test_gluon_contrib.py",      # conv-RNN cell learning runs
    "test_sparse_compact.py",     # 300k-row embedding training
    "test_extra_ops.py",          # deformable/psroi grids
    "test_legacy_api.py",         # FeedForward fit runs
    "test_jvm_binding.py",        # may build the native lib
    "test_aux.py",                # launcher dry-run subprocesses
    "test_gradcomp.py",           # bandwidth tool child interpreter
}


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running / subprocess-spawning test "
                   "(nightly tier; excluded from -m fast)")
    config.addinivalue_line(
        "markers", "fast: pre-merge tier, `pytest -m fast` < 2 min")
    # lock witness (docs/static_analysis.md "Lock witness"): armed
    # BEFORE any mxtpu import, and loaded by FILE PATH — `import
    # mxtpu.devtools.lockwitness` would run mxtpu/__init__ first and
    # every lock created during that import would be born unwrapped,
    # making accesses under those locks look unguarded.
    if os.environ.get("MXTPU_LOCK_WITNESS") == "1":
        import importlib.util
        import pathlib
        lw = pathlib.Path(__file__).resolve().parent.parent / \
            "mxtpu" / "devtools" / "lockwitness.py"
        spec = importlib.util.spec_from_file_location(
            "_mxtpu_lockwitness", str(lw))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.install()


def pytest_collection_modifyitems(config, items):
    import pytest
    for item in items:
        fname = os.path.basename(str(item.fspath))
        if fname in SLOW_FILES or item.get_closest_marker("slow"):
            item.add_marker(pytest.mark.slow)
        else:
            item.add_marker(pytest.mark.fast)
