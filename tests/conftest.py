"""Test configuration: force a virtual 8-device CPU mesh.

Mirrors the reference's strategy of faking multi-device with multiple CPU
contexts in one process (tests/python/unittest/test_multi_device_exec.py):
here we give XLA 8 host devices so jax.sharding Meshes exercise real
collectives without TPU hardware.

Note: the environment's sitecustomize registers an `axon` TPU backend and
calls jax.config.update("jax_platforms", "axon,cpu") at interpreter start,
which overrides the JAX_PLATFORMS env var — so we must force the config
value back to "cpu" after importing jax, or tests would try to grab the
(single, possibly busy) TPU chip.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running end-to-end test")
