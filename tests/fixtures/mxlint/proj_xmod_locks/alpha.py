"""Cross-module AB/BA lock inversion, side A.

``Alpha.step`` takes ``Alpha._a`` and then calls into ``beta`` —
``Beta.poke`` takes ``Beta._b``, so the interprocedural summary adds
the edge ``Alpha._a -> Beta._b``. Side B lives in ``beta.py``, runs on
a ``threading.Thread`` entry point, and adds the reverse edge: a
whole-program-only deadlock (each file on its own is cycle-free)."""
import threading

from beta import Beta


class Alpha:
    def __init__(self):
        self._a = threading.Lock()
        self.partner = Beta(self)

    def step(self):
        with self._a:
            self.partner.poke()   # EXPECT(lock-order)

    def grab_a(self):
        # called from beta's thread while Beta._b is held: the BA arm
        with self._a:
            return True

    def safe_peek(self):
        # negative: consistent order — nothing is held around this
        return self.partner.poke()
