"""Cross-module AB/BA lock inversion, side B: the thread entry point.

``Beta._loop`` runs on a ``threading.Thread(target=...)`` — a
concurrency root — and takes ``Beta._b`` before calling back into
``Alpha.grab_a``, which takes ``Alpha._a``: the reverse edge of the
inversion seeded in ``alpha.py``."""
import threading


class Beta:
    def __init__(self, owner):
        self.owner = owner
        self._b = threading.Lock()
        self._t = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        with self._b:
            self.owner.grab_a()   # EXPECT(lock-order)

    def poke(self):
        with self._b:
            return 1

    def quiet(self):
        # negative: takes _b alone, no call while held
        with self._b:
            x = 2
        return x
