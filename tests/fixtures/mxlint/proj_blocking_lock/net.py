"""blocking-under-lock fixture: waits performed while a lock is held.

Positives: a socket recv under the wire lock (the raw-recv itself is a
deliberately pragma'd blocking-call — the corpus also demonstrates
that the two passes compose), a bounded queue get under the same lock,
a sleep under lock, a wait on ANOTHER object's condition while holding
a lock, and a helper whose every caller holds the lock (the transitive
caller-context).

Negatives: the standard condition idiom (wait on the condition you
hold — wait() releases it), the same bounded get with nothing held,
``dict.get(key)`` (positional arg: never a queue wait), and
``os.path.join`` (join with args is not a thread join).
"""
import os
import queue
import socket
import threading
import time


class Fetcher:
    def __init__(self, addr):
        self._lock = threading.Lock()
        self._cv = threading.Condition()
        self._peer_cv = threading.Condition()
        self._q = queue.Queue()
        self._cache = {}
        self._sock = socket.create_connection(addr, timeout=5.0)

    def fetch(self):
        with self._lock:
            data = self._sock.recv(4096)   # mxlint: allow(blocking-call) — corpus: audited deadline loop  # EXPECT(blocking-under-lock)
            item = self._q.get(timeout=0.5)          # EXPECT(blocking-under-lock)
            time.sleep(0.01)                         # EXPECT(blocking-under-lock)
            with self._peer_cv:
                self._peer_cv.wait(timeout=1.0)      # EXPECT(blocking-under-lock)
            return data, item

    def drain(self):
        with self._lock:
            return self._pop_locked()

    def _pop_locked(self):
        # every caller holds self._lock: the transitive caller context
        # carries it into this helper
        return self._q.get(timeout=0.5)              # EXPECT(blocking-under-lock)

    def wait_ready(self):
        # the condition idiom: wait() RELEASES the held lock — negative
        with self._cv:
            while not self._cache:
                self._cv.wait(timeout=0.5)

    def poll(self):
        # nothing held: bounded get is fine here — negative
        item = self._q.get(timeout=0.5)
        with self._lock:
            hit = self._cache.get("latest")          # dict.get: negative
            path = os.path.join("/tmp", "x")         # path join: negative
        return item, hit, path
