"""mxlint fixture: lock-order pass — a seeded AB/BA deadlock (one arm
direct nesting, the other through a method call), a nested factory
acquisition, and a clean consistently-ordered class."""
import threading


class Inverted:
    def __init__(self):
        self._table_lock = threading.Lock()
        self._stats_lock = threading.Lock()

    def update(self):
        with self._table_lock:
            with self._stats_lock:  # EXPECT(lock-order)
                pass

    def report(self):
        with self._stats_lock:
            self._touch_table()  # EXPECT(lock-order)

    def _touch_table(self):
        with self._table_lock:
            pass


class NestedFactory:
    def __init__(self):
        self._locks = {}
        self._guard = threading.Lock()

    def _lock_for(self, key):
        with self._guard:
            return self._locks.setdefault(key, threading.Lock())

    def transfer(self, src, dst):
        with self._lock_for(src):
            with self._lock_for(dst):  # EXPECT(lock-order)
                pass


class Ordered:
    """Consistent order everywhere: no finding."""

    def __init__(self):
        self._a_lock = threading.Lock()
        self._b_lock = threading.Lock()

    def one(self):
        with self._a_lock:
            with self._b_lock:
                pass

    def two(self):
        with self._a_lock:
            self._under_b()

    def _under_b(self):
        with self._b_lock:
            pass

    def three(self):
        # sequential, not nested: no edge at all
        with self._b_lock:
            pass
        with self._a_lock:
            pass
