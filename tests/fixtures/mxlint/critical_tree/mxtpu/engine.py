"""mxlint fixture: except-swallow pass on a CRITICAL path (this file's
relpath ends in mxtpu/engine.py, so the pass applies fleet-path
scoping): broad typed swallows are findings too."""


def critical(conn):
    try:
        conn.flush()
    except Exception:  # EXPECT(except-swallow)
        pass
    try:
        conn.flush()
    except:  # EXPECT(except-swallow)
        pass
    try:
        conn.flush()
    except (ValueError, Exception):  # EXPECT(except-swallow)
        pass
    try:
        conn.flush()
    except OSError:     # narrow stays allowed even here
        pass
    try:
        conn.flush()
    except Exception:   # mxlint: allow(except-swallow) — fixture: reviewed teardown race
        pass
