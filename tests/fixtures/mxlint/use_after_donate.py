"""mxlint fixture: use-after-donate pass — reads of donated buffers
after the donating call (the PR-5 ``_data``-rebind contract), both for
a local ``jax.jit(..., donate_argnums=...)`` program and for the fused
train step factory. Unmarked reads must stay clean."""
import jax


def step(params, batch, state):
    return params, state


def plain_use_after_donate(params, batch, state):
    f = jax.jit(step, donate_argnums=(0, 2))
    new_params, new_state = f(params, batch, state)
    stale = params.sum()  # EXPECT(use-after-donate)
    also_stale = state  # EXPECT(use-after-donate)
    fine = batch.sum()            # position 1 is not donated
    return stale, also_stale, fine, new_params, new_state


def rebind_is_clean(params, batch, state):
    f = jax.jit(step, donate_argnums=(0, 2))
    # the rebind idiom: the donated NAME is re-bound by the very call,
    # so later reads see the fresh buffer
    params, state = f(params, batch, state)
    return params.sum() + state.sum() + batch.sum()


def spec_via_variable(params, batch, state, donate_on):
    donate = (0, 2) if donate_on else ()
    f = jax.jit(step, donate_argnums=donate)
    out = f(params, batch, state)
    return params  # EXPECT(use-after-donate)


def fused_factory_contract(exec_, fs, tv, st, av, ov, key, t, lr):
    entry = exec_.make_fused_train_step(["w"], fs.optimizer, [0])
    fn, other_names = entry
    res = fn(tv, st, av, ov, key, t, lr, fs.metric_acc)
    stale_params = tv  # EXPECT(use-after-donate)
    stale_acc = fs.metric_acc  # EXPECT(use-after-donate)
    ok_batch = ov                 # position 3 rides non-donated
    ok_lr = lr                    # position 6 is a carried constant
    fs.metric_acc = res[-1]       # the rebind...
    revived = fs.metric_acc       # ...revives the path
    return stale_params, stale_acc, ok_batch, ok_lr, revived
