"""Env-drift fixture, code side.

``MXTPU_FAKE_TIMEOUT`` (direct read, wrapped over two lines) and
``MXTPU_FAKE_DEPTH`` (read through the ``_env_int`` helper in
``envutil.py`` — a cross-module wrapper the whole-program pass must
resolve) are documented: negatives. ``MXTPU_SECRET_KNOB`` is read but
has no definition row: the positive.
"""
import os

from envutil import _env_int


def configure():
    timeout = float(os.environ.get(
        "MXTPU_FAKE_TIMEOUT", "5"))
    depth = _env_int("MXTPU_FAKE_DEPTH", 8)
    secret = _env_int("MXTPU_SECRET_KNOB", 3)   # EXPECT(env-drift)
    return timeout, depth, secret
