"""Env-read helper: its ``name`` parameter flows into
``os.environ.get``, making every resolvable literal call a read site
for the env-drift pass."""
import os


def _env_int(name, default):
    return int(os.environ.get(name, str(default)))
