"""Metrics-drift fixture, code side.

``fake.requests`` (counter) and ``fake.lat_ms`` (histogram) are
documented: negatives. ``fake.view`` is a documented view
registration: negative. ``fake.secret_total`` is registered but has
no definition row in the corpus catalog: the positive. The plain
attribute call with a non-metric-shaped literal (``get``) and the
undotted name must not match at all.
"""


class _Reg:
    def counter(self, name, help="", labels=()):
        return name

    def histogram(self, name, help="", labels=()):
        return name

    def view(self, name, fn):
        return name


REG = _Reg()

_REQS = REG.counter("fake.requests", "documented counter", ("inst",))
_LAT = REG.histogram(
    "fake.lat_ms", "documented histogram wrapped over lines")
_SECRET = REG.counter("fake.secret_total")   # EXPECT(metrics-drift)
_VIEW = REG.view("fake.view", lambda: {})
_NOT_A_METRIC = REG.counter("plainname")     # undotted: out of scope


def poll(d):
    return d.get("fake.requests")            # a read, not a registration
