"""mxlint fixture: trace-purity pass — host syncs and impure writes
inside jitted code, including a root found through ``jax.jit(f)`` and a
helper reached transitively. Unmarked code must stay clean."""
import functools

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def decorated_step(x, carrier):
    y = float(x)  # EXPECT(trace-purity)
    carrier.count = carrier.count + 1  # EXPECT(trace-purity)
    z = x.asnumpy()  # EXPECT(trace-purity)
    w = np.asarray(x)  # EXPECT(trace-purity)
    print("tracing", x.shape)  # EXPECT(trace-purity)
    return y, z, w


@functools.partial(jax.jit, donate_argnums=(0,))
def partial_decorated(x):
    return x.item()  # EXPECT(trace-purity)


def _helper(x):
    # reached transitively from train_step: still traced
    return jnp.asarray(x.tolist())  # EXPECT(trace-purity)


def train_step(params, batch):
    loss = jnp.sum(params * batch)
    return _helper(loss)


jitted = jax.jit(train_step, donate_argnums=(0,))


def host_side(x, metric):
    """NOT traced: the same calls are fine here."""
    v = float(x)
    arr = np.asarray(x)
    metric.count += 1
    print("host", v)
    return arr


@jax.jit
def clean_step(params, grads, lr):
    """Traced and pure: jnp math, local writes only — no findings."""
    new = [p - lr * g for p, g in zip(params, grads)]
    total = jnp.stack([jnp.sum(p) for p in new])
    blessed = float(lr)  # mxlint: allow(trace-purity) — fixture: lr is a trace-time python scalar here
    return new, total, blessed
