"""mxlint fixture: except-swallow pass in a NON-critical module — only
the bare / BaseException swallows are findings here; a broad-but-typed
``except Exception: pass`` is left to the baseline tier."""


def noncritical(path):
    try:
        open(path).close()
    except:  # EXPECT(except-swallow)
        pass
    try:
        open(path).close()
    except BaseException:  # EXPECT(except-swallow)
        pass
    try:
        open(path).close()
    except Exception:       # broad but typed: not flagged off the hot paths
        pass
    try:
        open(path).close()
    except OSError:         # narrow + pass is a normal idiom
        pass
    try:
        open(path).close()
    except Exception as e:  # body does something: never flagged
        print(e)
