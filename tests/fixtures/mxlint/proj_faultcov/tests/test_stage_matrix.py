"""Corpus fault-matrix rows (reference material for the
fault-coverage pass — this file is consulted, never linted)."""

MATRIX = [
    ("drop@alpha", "kind=drop,point=stage.alpha,nth=1"),
]
