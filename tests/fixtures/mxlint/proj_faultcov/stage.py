"""Fault-coverage fixture: two injection points, one fully covered.

``stage.alpha`` is in the corpus fault grammar (docs/env_vars.md) and
has a fault-matrix row (tests/test_stage_matrix.py) — the negative.
``stage.beta`` is in neither: untargetable by operators and untested.
"""
import os

from mxtpu import fault as _fault


def run_stage(batch):
    spec = os.environ.get("MXTPU_FAULT_SPEC", "")
    _fault.fire("stage.alpha", op="run", key=spec)
    out = batch * 2
    _fault.fire("stage.beta", op="drain")   # EXPECT(fault-coverage)
    return out
