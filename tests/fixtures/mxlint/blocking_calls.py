"""mxlint fixture: blocking-call pass — positives marked EXPECT(...),
everything unmarked must NOT be flagged. Never executed, only parsed."""
import socket
import queue
import threading

q = queue.Queue()
ev = threading.Event()
t = threading.Thread(target=print, daemon=True)


def positives(sock, pool_result):
    # wrapped over several lines with NO timeout anywhere — the old
    # 3-line window version of this rule anchored on text, this one on
    # the call node
    c = socket.create_connection(  # EXPECT(blocking-call)
        ("server.example",
         9999),
    )
    # the word timeout in a nearby comment fooled the regex's window;
    # the AST is not fooled (no timeout= in the CALL):
    c2 = socket.create_connection(("h", 1))  # EXPECT(blocking-call)
    # ...the retry layer owns the timeout elsewhere (not here!)
    c.settimeout(None)  # EXPECT(blocking-call)
    data = sock.recv(4096)  # EXPECT(blocking-call)
    n = sock.recv_into(bytearray(16))  # EXPECT(blocking-call)
    ev.wait()  # EXPECT(blocking-call)
    t.join()  # EXPECT(blocking-call)
    item = q.get()  # EXPECT(blocking-call)
    out = pool_result.get()  # EXPECT(blocking-call)
    return c, c2, data, n, item, out


def negatives(sock, d):
    # timeout present even though the call wraps over FOUR lines —
    # beyond the old checker's window, trivial for the AST
    c = socket.create_connection(
        ("server.example",
         9999),
        timeout=5.0,
    )
    c3 = socket.create_connection(("h", 1), 5.0)   # positional timeout
    ev.wait(timeout=1.0)
    ev.wait(2.0)
    t.join(timeout=0.5)
    item = q.get(timeout=1.0)
    value = d.get("key")           # dict-style getter: has an argument
    other = d.get("key", None)
    allowed = q.get()   # mxlint: allow(blocking-call) — fixture: sentinel-terminated daemon queue
    return c, c3, item, value, other, allowed
