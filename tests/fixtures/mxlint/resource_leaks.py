"""resource-leak fixture: abandoned socket/thread/subprocess locals
(positives) against every escape/cleanup shape the pass must respect
(negatives)."""
import socket
import subprocess
import threading


def leaky_probe(host):
    s = socket.create_connection((host, 80), timeout=2.0)   # EXPECT(resource-leak)
    s.sendall(b"ping")
    return True


def closed_probe(host):
    s = socket.create_connection((host, 80), timeout=2.0)
    try:
        s.sendall(b"ping")
    finally:
        s.close()


def context_probe(host):
    s = socket.create_connection((host, 80), timeout=2.0)
    with s:
        s.sendall(b"ping")


def fire_and_forget(fn):
    t = threading.Thread(target=fn)   # EXPECT(resource-leak)
    t.start()


def joined_worker(fn):
    t = threading.Thread(target=fn)
    t.start()
    t.join(timeout=5.0)


def daemon_worker(fn):
    t = threading.Thread(target=fn, daemon=True)
    t.start()


def orphan_child():
    p = subprocess.Popen(["true"])   # EXPECT(resource-leak)
    return None


def reaped_child():
    p = subprocess.Popen(["true"])
    p.wait(timeout=10.0)


def escaping_socket():
    s = socket.socket()
    return s


def registered_socket(registry):
    s = socket.socket()
    registry.append(s)


def stored_socket(obj):
    s = socket.socket()
    obj.sock = s
