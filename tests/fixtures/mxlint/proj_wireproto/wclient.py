"""Wire-protocol drift fixture, client side.

Requests ``ping``/``halt``/``fetch`` (served — negatives) and ``zap``
(no dispatcher serves it: unserved request). Handles ``ok``/``busy``
(emitted — negatives) and ``retired`` (nothing emits it: dead verdict
handler)."""


class WireClient:
    def __init__(self, conn):
        self.conn = conn

    def call(self, key):
        self.conn.request("ping", timeout=1.0)
        reply = self.conn.request("fetch", key, timeout=1.0)
        verdict = reply[0]
        if verdict == "ok":
            return reply[1]
        if verdict == "busy":
            return None
        if verdict == "retired":   # EXPECT(wire-protocol)
            return None
        raise RuntimeError(reply)

    def shutdown(self):
        self.conn.request("halt", timeout=1.0)
        self.conn.request("zap", timeout=1.0)   # EXPECT(wire-protocol)
