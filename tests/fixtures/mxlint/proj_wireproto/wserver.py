"""Wire-protocol drift fixture, server side.

The dispatcher serves ``ping``/``halt``/``legacy_probe``; nothing in
the corpus ever sends ``legacy_probe`` (dead op handler), and the
``backpressure`` verdict it emits is handled by no client (unhandled
verdict). ``ping``/``halt``/``ok``/``busy`` are the negatives: served,
requested, emitted and handled."""


class WireServer:
    def __init__(self, table):
        self.table = table

    def _dispatch(self, msg):
        cmd = msg[0]
        if cmd == "ping":
            return ("ok", {"alive": True})
        if cmd == "halt":
            return ("ok", {"stopping": True})
        if cmd == "fetch":
            if self.table.get(msg[1]) is None:
                return ("busy", {"retry_in": 0.1})
            return ("backpressure",   # EXPECT(wire-protocol)
                    {"depth": len(self.table)})
        if cmd == "legacy_probe":   # EXPECT(wire-protocol)
            return ("ok", "probe")
        return ("err", "unknown command %r" % (cmd,))
