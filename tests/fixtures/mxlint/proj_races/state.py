"""Shared-state fixture: the state object two threaded modules share.

Everything in THIS module is a known-negative for shared-state-race:
init-phase writes, the per-series lock idiom (obs/metrics.py's
``Series``), and registry-bound instruments.
"""
import threading

from obs import counter


class Meter:
    """The obs/metrics per-series idiom: value guarded by its own
    lock, read through a locked getter — fully consistent, no
    finding."""

    def __init__(self):
        self._lock = threading.Lock()
        self._value = 0

    def inc(self):
        with self._lock:
            self._value += 1

    @property
    def value(self):
        with self._lock:
            return self._value


class Shared:
    def __init__(self):
        self.lock_a = threading.Lock()
        self.lock_b = threading.Lock()
        # init-phase writes: the object has not escaped yet
        self.hits = 0
        self.queue_depth = 0
        self.total = 0
        self.acked = 0
        self.dying = False
        self.meter = Meter()
        # registry instrument: per-series locks are the obs plane's
        # guarantee, the race pass must not model its internals
        self.requests = counter("fixture.requests")
