"""The writer side: a producer thread mutating shared state.

Positives here: the cross-thread unlocked counter (mutated from BOTH
the producer thread and the main-thread ``report`` surface with no
lock at all), and the write-under-lock-A half of the split-lock race
(beta's drain thread writes the same field under lock B).
"""
import threading

from state import Shared


class Producer:
    def __init__(self):
        self.state = Shared()
        self.batch = 64            # init-phase: negative
        t = threading.Thread(target=self._loop, daemon=True)
        t.start()

    def _loop(self):
        while not self.state.dying:           # flag read: negative
            self.state.hits += 1              # EXPECT(shared-state-race)
            with self.state.lock_a:
                self.state.queue_depth += 1   # EXPECT(shared-state-race)
                self.state.total += 1
                self.state.acked += 1
            self.state.meter.inc()
            self.state.requests.inc()

    def report(self):
        # the "training thread" half of the unlocked counter race
        self.state.hits += 1                  # EXPECT(shared-state-race)
        return self.state.hits
