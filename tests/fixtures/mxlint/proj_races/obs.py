"""Minimal registry stand-in so the corpus can exercise the
metrics-plane exemption without importing the real mxtpu.obs."""
import threading


class Counter:
    def __init__(self, name):
        self.name = name
        self._lock = threading.Lock()
        self._value = 0

    def inc(self, n=1):
        with self._lock:
            self._value += n


def counter(name):
    return Counter(name)
