"""The reader/consumer side: a drain thread that uses the WRONG lock.

Positives here: the write-under-lock-B half of the split-lock race
(alpha writes ``queue_depth`` under lock A), and the
read-under-lock-B of ``total`` whose writers all hold lock A — the
reader believes it is synchronized and is not.
"""
import threading

from state import Shared


class Consumer:
    def __init__(self, shared=None):
        self.state = shared if shared is not None else Shared()
        self.seen = 0
        t = threading.Thread(target=self._drain, daemon=True)
        t.start()

    def _drain(self):
        while not self.state.dying:           # flag read: negative
            with self.state.lock_b:
                self.state.queue_depth -= 1   # EXPECT(shared-state-race)
                if self.state.total > 0:      # EXPECT(shared-state-race)
                    self.seen += 1
            with self.state.lock_a:
                self.state.acked += 1         # same lock as alpha: negative
            self.state.meter.inc()

    def finish(self):
        # GIL-atomic publication of a plain flag: negative
        self.state.dying = True
