"""Metric tests (reference: tests/python/unittest/test_metric.py)."""
import numpy as np
import pytest

import mxtpu as mx


def test_accuracy():
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m = mx.metric.create("acc")
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6


def test_top_k_accuracy():
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.7, 0.2, 0.1]])
    label = mx.nd.array([1, 1])
    m = mx.metric.create("top_k_accuracy", top_k=2)
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6


def test_f1():
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6], [0.7, 0.3]])
    label = mx.nd.array([1, 0, 1, 0])
    m = mx.metric.create("f1")
    m.update([label], [pred])
    assert m.get()[1] == 1.0


def test_mae_mse_rmse():
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([[2.0], [4.0]])
    mae = mx.metric.create("mae")
    mae.update([label], [pred])
    assert abs(mae.get()[1] - 1.5) < 1e-6
    mse = mx.metric.create("mse")
    mse.update([label], [pred])
    assert abs(mse.get()[1] - 2.5) < 1e-6
    rmse = mx.metric.create("rmse")
    rmse.update([label], [pred])
    assert abs(rmse.get()[1] - np.sqrt(2.5)) < 1e-6


def test_cross_entropy_and_nll():
    pred = mx.nd.array([[0.9, 0.1], [0.2, 0.8]])
    label = mx.nd.array([0, 1])
    ce = mx.metric.create("ce")
    ce.update([label], [pred])
    expect = -(np.log(0.9) + np.log(0.8)) / 2
    assert abs(ce.get()[1] - expect) < 1e-5
    nll = mx.metric.create("nll_loss")
    nll.update([label], [pred])
    assert abs(nll.get()[1] - expect) < 1e-5


def test_perplexity():
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m = mx.metric.create("perplexity", ignore_label=None)
    m.update([label], [pred])
    expect = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(m.get()[1] - expect) < 1e-5


def test_composite_and_custom():
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1]])
    label = mx.nd.array([1, 0])
    comp = mx.metric.create(["acc", "ce"])
    comp.update([label], [pred])
    names, vals = comp.get()
    assert "accuracy" in names and "cross-entropy" in names

    def my_metric(label, pred):
        return float((pred.argmax(axis=1) == label).mean())
    m = mx.metric.np(my_metric)
    m.update([label], [pred])
    assert m.get()[1] == 1.0


def test_pearson():
    pred = mx.nd.array([[1.0], [2.0], [3.0]])
    label = mx.nd.array([[2.0], [4.0], [6.0]])
    m = mx.metric.create("pearsonr")
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6
