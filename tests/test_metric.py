"""Metric tests (reference: tests/python/unittest/test_metric.py)."""
import numpy as np
import pytest

import mxtpu as mx


def test_accuracy():
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6]])
    label = mx.nd.array([1, 0, 0])
    m = mx.metric.create("acc")
    m.update([label], [pred])
    assert abs(m.get()[1] - 2.0 / 3) < 1e-6


def test_top_k_accuracy():
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.7, 0.2, 0.1]])
    label = mx.nd.array([1, 1])
    m = mx.metric.create("top_k_accuracy", top_k=2)
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6


def test_f1():
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1], [0.4, 0.6], [0.7, 0.3]])
    label = mx.nd.array([1, 0, 1, 0])
    m = mx.metric.create("f1")
    m.update([label], [pred])
    assert m.get()[1] == 1.0


def test_mae_mse_rmse():
    pred = mx.nd.array([[1.0], [2.0]])
    label = mx.nd.array([[2.0], [4.0]])
    mae = mx.metric.create("mae")
    mae.update([label], [pred])
    assert abs(mae.get()[1] - 1.5) < 1e-6
    mse = mx.metric.create("mse")
    mse.update([label], [pred])
    assert abs(mse.get()[1] - 2.5) < 1e-6
    rmse = mx.metric.create("rmse")
    rmse.update([label], [pred])
    assert abs(rmse.get()[1] - np.sqrt(2.5)) < 1e-6


def test_cross_entropy_and_nll():
    pred = mx.nd.array([[0.9, 0.1], [0.2, 0.8]])
    label = mx.nd.array([0, 1])
    ce = mx.metric.create("ce")
    ce.update([label], [pred])
    expect = -(np.log(0.9) + np.log(0.8)) / 2
    assert abs(ce.get()[1] - expect) < 1e-5
    nll = mx.metric.create("nll_loss")
    nll.update([label], [pred])
    assert abs(nll.get()[1] - expect) < 1e-5


def test_perplexity():
    pred = mx.nd.array([[0.5, 0.5], [0.9, 0.1]])
    label = mx.nd.array([0, 0])
    m = mx.metric.create("perplexity", ignore_label=None)
    m.update([label], [pred])
    expect = np.exp(-(np.log(0.5) + np.log(0.9)) / 2)
    assert abs(m.get()[1] - expect) < 1e-5


def test_composite_and_custom():
    pred = mx.nd.array([[0.3, 0.7], [0.9, 0.1]])
    label = mx.nd.array([1, 0])
    comp = mx.metric.create(["acc", "ce"])
    comp.update([label], [pred])
    names, vals = comp.get()
    assert "accuracy" in names and "cross-entropy" in names

    def my_metric(label, pred):
        return float((pred.argmax(axis=1) == label).mean())
    m = mx.metric.np(my_metric)
    m.update([label], [pred])
    assert m.get()[1] == 1.0


def test_pearson():
    pred = mx.nd.array([[1.0], [2.0], [3.0]])
    label = mx.nd.array([[2.0], [4.0], [6.0]])
    m = mx.metric.create("pearsonr")
    m.update([label], [pred])
    assert abs(m.get()[1] - 1.0) < 1e-6


# -- device-side accumulation (ISSUE 5: EvalMetric.update_async) ----------

def _device_pair(metric, labels, preds):
    """Run metric.device_batch on jnp arrays, return host (sum, count)."""
    import jax.numpy as jnp
    out = metric.device_batch(tuple(jnp.asarray(l) for l in labels),
                              tuple(jnp.asarray(p) for p in preds))
    assert out is not None
    return float(out[0]), float(out[1])


@pytest.mark.parametrize("name,kwargs", [
    ("acc", {}), ("top_k_accuracy", {"top_k": 2}), ("mae", {}),
    ("mse", {}), ("rmse", {}), ("ce", {}), ("nll_loss", {}),
])
def test_device_batch_matches_host_update(name, kwargs):
    """device_batch (the traced body the fused train step accumulates)
    must agree with the numpy update() path on the same batch."""
    rng = np.random.RandomState(0)
    pred = np.abs(rng.rand(16, 4).astype("float32")) + 1e-3
    pred = pred / pred.sum(axis=1, keepdims=True)
    if name in ("mae", "mse", "rmse"):
        label = rng.rand(16).astype("float32")
        pred_in = rng.rand(16).astype("float32")
    else:
        label = rng.randint(0, 4, 16).astype("float32")
        pred_in = pred
    host = mx.metric.create(name, **kwargs)
    host.update([mx.nd.array(label)], [mx.nd.array(pred_in)])
    dev = mx.metric.create(name, **kwargs)
    assert dev.supports_device_update()
    s, c = _device_pair(dev, [label], [pred_in])
    assert abs(c - host.num_inst) < 1e-6
    assert abs(s - host.sum_metric) < 1e-4 * max(1.0, abs(host.sum_metric))


def test_update_async_drains_lazily_and_resets():
    """update_async routes accumulation through a caller-owned device
    accumulator: get() drains it exactly once per read, reset() discards
    both sides."""
    m = mx.metric.create("acc")
    box = {"sum": 6.0, "count": 10.0, "reads": 0, "resets": 0}

    def reader():
        box["reads"] += 1
        s, c = box["sum"], box["count"]
        box["sum"] = box["count"] = 0.0   # fetch-and-zero contract
        return s, c

    def resetter():
        box["resets"] += 1
        box["sum"] = box["count"] = 0.0

    m.update_async(reader, resetter)
    assert m.get()[1] == 0.6 and box["reads"] == 1
    assert m.get()[1] == 0.6 and box["reads"] == 2  # idempotent re-read
    box["sum"], box["count"] = 4.0, 4.0             # more device batches
    assert abs(m.get()[1] - 10.0 / 14.0) < 1e-9
    m.reset()
    assert box["resets"] == 1
    assert np.isnan(m.get()[1])                     # all state discarded
    m.detach_async()
    m.update([mx.nd.array([1.0])], [mx.nd.array([[0.2, 0.8]])])
    assert m.get()[1] == 1.0                        # host path restored


def test_unsupported_metrics_report_no_device_path():
    comp = mx.metric.create(["acc", "ce"])
    assert not comp.supports_device_update()
    f1 = mx.metric.create("f1")
    assert not f1.supports_device_update()
    named = mx.metric.Accuracy(output_names=["softmax_output"])
    assert not named.supports_device_update()


def test_host_transfer_avoids_copy_when_host_resident():
    """metric._host must not copy a host-resident numpy array when no
    cast is needed (the metric.py:45 hardening)."""
    from mxtpu.metric import _host
    a = np.arange(6, dtype="float32").reshape(2, 3)
    out = _host(a)
    assert out is a                      # asarray view, no copy
    out32 = _host(a, "float32")
    assert out32 is a                    # astype(copy=False) no-op cast
    out64 = _host(a, "float64")
    assert out64.dtype == np.float64 and out64 is not a


def test_metric_accumulates_fp32_under_bf16_step():
    """ISSUE 12 regression: with a bf16 (AMP) step feeding the metric,
    every accumulation must run f32 — a bf16 sum saturates at ~256
    same-magnitude terms (8 mantissa bits), so an epoch of more than
    ~256 batches would silently stop counting."""
    import jax.numpy as jnp
    from mxtpu.metric import _host

    # device path: Loss over a bf16 vector of 4096 ones — a bf16-dtype
    # reduction would answer ~256, f32 answers exactly 4096
    total, count = mx.metric.Loss().device_batch(
        (), (jnp.ones(4096, jnp.bfloat16),))
    assert total.dtype == jnp.float32
    assert float(total) == 4096.0 and count == 4096

    # host path: _host upcasts half floats before numpy reductions
    import ml_dtypes
    host = _host(np.ones(513, ml_dtypes.bfloat16))
    assert host.dtype == np.float32
    assert host.sum() == 513.0           # bf16 pairwise sum gives 512

    # the host Loss.update rides the same upcast
    m = mx.metric.Loss()
    m.update(None, [mx.nd.array(np.ones(600, "f")).astype("bfloat16")])
    assert m.get()[1] == 1.0

    # CE/NLL: the per-row -log picks accumulate f32 on device
    ce = mx.metric.create("ce")
    rows = 512
    scores = jnp.full((rows, 2), 0.5, jnp.bfloat16)
    labels = jnp.zeros(rows, jnp.bfloat16)
    s, c = ce.device_batch((labels,), (scores,))
    assert s.dtype == jnp.float32 and c == rows
    assert abs(float(s) / rows - float(np.log(2))) < 1e-2
