"""C++ unit-test tier (reference tests/cpp/ gtest suites): compile and run
the native recordio test against libmxtpu_io.so."""
import os
import shutil
import subprocess

import pytest

_ROOT = os.path.join(os.path.dirname(__file__), "..")
_NATIVE = os.path.join(_ROOT, "mxtpu", "_native")


def test_recordio_cpp(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    so = os.path.join(_NATIVE, "libmxtpu_io.so")
    if not os.path.exists(so):
        pytest.skip("libmxtpu_io.so not built")
    exe = str(tmp_path / "recordio_test")
    subprocess.run(
        ["g++", "-O1", "-std=c++17",
         os.path.join(_ROOT, "tests", "cpp", "recordio_test.cc"),
         "-L", _NATIVE, "-lmxtpu_io",
         "-Wl,-rpath," + os.path.abspath(_NATIVE), "-o", exe],
        check=True)
    res = subprocess.run([exe, str(tmp_path)], capture_output=True,
                         text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "recordio_test OK" in res.stdout
