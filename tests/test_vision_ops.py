"""Detection / vision / sequence / contrib op tests, modeled on the
reference's per-op checks in tests/python/unittest/test_operator.py
(test_roipooling, test_sequence_*, test_bilinear_sampler,
test_multibox_*, test_correlation, test_quantization ...).
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def test_roi_pooling():
    x = nd.array(np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8))
    rois = nd.array(np.array([[0, 0, 0, 7, 7],
                              [0, 0, 0, 3, 3]], np.float32))
    out = nd.ROIPooling(x, rois, pooled_size=(2, 2), spatial_scale=1.0)
    assert out.shape == (2, 1, 2, 2)
    res = out.asnumpy()
    # full-image roi, 2x2 max-pool of an 8x8 ramp
    np.testing.assert_array_equal(res[0, 0], [[27, 31], [59, 63]])
    np.testing.assert_array_equal(res[1, 0], [[9, 11], [25, 27]])


def test_roi_pooling_grad():
    x = nd.array(np.random.RandomState(0).randn(1, 2, 6, 6)
                 .astype(np.float32))
    rois = nd.array(np.array([[0, 1, 1, 4, 4]], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.ROIPooling(x, rois, pooled_size=(2, 2), spatial_scale=1.0)
        loss = nd.sum(y)
    loss.backward()
    g = x.grad.asnumpy()
    assert np.isfinite(g).all()
    assert g.sum() > 0  # max positions get gradient


def test_multibox_prior():
    x = nd.zeros((1, 3, 4, 4))
    anchors = nd.MultiBoxPrior(x, sizes=(0.5, 0.25), ratios=(1, 2))
    # num_anchors per cell = len(sizes) + len(ratios) - 1 = 3
    assert anchors.shape == (1, 4 * 4 * 3, 4)
    a = anchors.asnumpy()[0]
    # first cell center at (0.125, 0.125), first anchor size .5 ratio 1
    np.testing.assert_allclose(a[0], [0.125 - 0.25, 0.125 - 0.25,
                                      0.125 + 0.25, 0.125 + 0.25],
                               atol=1e-6)


def test_multibox_target_and_detection():
    anchors = nd.MultiBoxPrior(nd.zeros((1, 3, 2, 2)), sizes=(0.4,),
                               ratios=(1,))
    # one gt box matching the top-left anchor region
    label = nd.array(np.array([[[0, 0.05, 0.05, 0.45, 0.45],
                                [-1, 0, 0, 0, 0]]], np.float32))
    cls_pred = nd.zeros((1, 2, anchors.shape[1]))
    bt, bm, ct = nd.MultiBoxTarget(anchors, label, cls_pred)
    assert bt.shape == (1, anchors.shape[1] * 4)
    ct_np = ct.asnumpy()[0]
    assert (ct_np == 1).sum() >= 1          # the matched anchor got class 0+1
    assert (ct_np == 0).sum() >= 1          # background anchors remain

    # detection decode: feed perfect loc targets back -> recovered gt box
    cls_prob = np.zeros((1, 2, anchors.shape[1]), np.float32)
    cls_prob[0, 0, :] = 0.8                 # background
    matched = np.where(ct_np == 1)[0]
    cls_prob[0, 1, matched] = 0.99
    loc = bt.asnumpy().reshape(1, -1)
    det = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc), anchors,
                               nms_threshold=0.5, threshold=0.5)
    d = det.asnumpy()[0]
    kept = d[d[:, 0] >= 0]
    assert len(kept) >= 1
    np.testing.assert_allclose(kept[0, 2:], [0.05, 0.05, 0.45, 0.45],
                               atol=0.02)


def test_nms_suppression():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.4, 0.4],
                                  [0.12, 0.12, 0.42, 0.42],
                                  [0.6, 0.6, 0.9, 0.9]]], np.float32))
    cls_prob = np.zeros((1, 2, 3), np.float32)
    cls_prob[0, 1] = [0.9, 0.8, 0.7]
    loc = np.zeros((1, 12), np.float32)
    det = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc), anchors,
                               nms_threshold=0.5, threshold=0.1)
    d = det.asnumpy()[0]
    kept = d[d[:, 0] >= 0]
    # overlapping anchor 1 suppressed; anchors 0 and 2 survive
    assert len(kept) == 2


def test_proposal():
    rng = np.random.RandomState(1)
    b, a, h, w = 1, 3, 4, 4
    cls_prob = nd.array(rng.rand(b, 2 * a, h, w).astype(np.float32))
    bbox_pred = nd.array((rng.randn(b, 4 * a, h, w) * 0.1).astype(np.float32))
    im_info = nd.array(np.array([[64, 64, 1.0]], np.float32))
    rois = nd.Proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=12,
                       rpn_post_nms_top_n=5, feature_stride=16,
                       scales=(2, 4, 8), ratios=(1,), rpn_min_size=1)
    assert rois.shape == (5, 5)
    r = rois.asnumpy()
    assert (r[:, 0] == 0).all()
    assert (r[:, 1] <= r[:, 3]).all() and (r[:, 2] <= r[:, 4]).all()


def test_bilinear_sampler_identity():
    x = nd.array(np.random.RandomState(2).randn(1, 2, 5, 5)
                 .astype(np.float32))
    ys, xs = np.meshgrid(np.linspace(-1, 1, 5), np.linspace(-1, 1, 5),
                         indexing="ij")
    grid = nd.array(np.stack([xs, ys])[None].astype(np.float32))
    out = nd.BilinearSampler(x, grid)
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_spatial_transformer_identity():
    x = nd.array(np.random.RandomState(3).randn(2, 1, 6, 6)
                 .astype(np.float32))
    theta = nd.array(np.tile(np.array([1, 0, 0, 0, 1, 0], np.float32),
                             (2, 1)))
    out = nd.SpatialTransformer(x, theta, target_shape=(6, 6))
    np.testing.assert_allclose(out.asnumpy(), x.asnumpy(), rtol=1e-5,
                               atol=1e-5)


def test_spatial_transformer_grad():
    x = nd.array(np.random.RandomState(4).randn(1, 1, 4, 4)
                 .astype(np.float32))
    theta = nd.array(np.array([[0.8, 0.1, 0.05, -0.1, 0.9, 0.02]],
                              np.float32))
    theta.attach_grad()
    with mx.autograd.record():
        y = nd.SpatialTransformer(x, theta, target_shape=(4, 4))
        loss = nd.sum(y * y)
    loss.backward()
    assert np.isfinite(theta.grad.asnumpy()).all()
    assert np.abs(theta.grad.asnumpy()).sum() > 0


def test_correlation_self():
    x = nd.array(np.random.RandomState(5).randn(1, 4, 6, 6)
                 .astype(np.float32))
    out = nd.Correlation(x, x, max_displacement=1, pad_size=1)
    assert out.shape == (1, 9, 6, 6)
    # zero displacement channel equals mean of squares
    center = out.asnumpy()[0, 4]
    np.testing.assert_allclose(center, (x.asnumpy()[0] ** 2).mean(0),
                               rtol=1e-5)


def test_sequence_ops():
    t, b, d = 4, 3, 2
    x = np.arange(t * b * d, dtype=np.float32).reshape(t, b, d)
    lens = np.array([2, 4, 1], np.float32)
    last = nd.SequenceLast(nd.array(x), nd.array(lens),
                           use_sequence_length=True)
    np.testing.assert_array_equal(last.asnumpy(),
                                  np.stack([x[1, 0], x[3, 1], x[0, 2]]))
    masked = nd.SequenceMask(nd.array(x), nd.array(lens),
                             use_sequence_length=True, value=-1.0)
    m = masked.asnumpy()
    np.testing.assert_array_equal(m[2, 0], [-1, -1])
    np.testing.assert_array_equal(m[1, 0], x[1, 0])
    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True)
    r = rev.asnumpy()
    np.testing.assert_array_equal(r[0, 0], x[1, 0])
    np.testing.assert_array_equal(r[1, 0], x[0, 0])
    np.testing.assert_array_equal(r[2, 0], x[2, 0])  # beyond len: unchanged
    np.testing.assert_array_equal(r[:, 1], x[::-1, 1])


def test_quantize_dequantize_round_trip():
    x = np.random.RandomState(6).uniform(-3, 3, (4, 5)).astype(np.float32)
    q, lo, hi = nd.quantize(nd.array(x), nd.array([-3.0]), nd.array([3.0]),
                            out_type="uint8")
    assert q.asnumpy().dtype == np.uint8
    back = nd.dequantize(q, lo, hi)
    np.testing.assert_allclose(back.asnumpy(), x, atol=6 / 255 + 1e-6)


def test_fft_ifft():
    x = np.random.RandomState(7).randn(2, 8).astype(np.float32)
    f = nd.fft(nd.array(x))
    assert f.shape == (2, 16)
    ref = np.fft.fft(x, axis=-1)
    np.testing.assert_allclose(f.asnumpy()[:, 0::2], ref.real, atol=1e-4)
    np.testing.assert_allclose(f.asnumpy()[:, 1::2], ref.imag, atol=1e-4)
    back = nd.ifft(f)
    np.testing.assert_allclose(back.asnumpy(), x * 8, atol=1e-4)


def test_count_sketch():
    d_in, d_out = 6, 4
    x = np.random.RandomState(8).randn(2, d_in).astype(np.float32)
    h = np.random.RandomState(9).randint(0, d_out, d_in)
    s = np.random.RandomState(10).choice([-1.0, 1.0], d_in)
    out = nd.count_sketch(nd.array(x), nd.array(h.astype(np.float32)),
                          nd.array(s.astype(np.float32)), out_dim=d_out)
    expect = np.zeros((2, d_out), np.float32)
    for j in range(d_in):
        expect[:, h[j]] += s[j] * x[:, j]
    np.testing.assert_allclose(out.asnumpy(), expect, rtol=1e-5)


def test_psroi_pooling():
    p, dim = 2, 3
    c = dim * p * p
    x = nd.array(np.random.RandomState(11).randn(1, c, 8, 8)
                 .astype(np.float32))
    rois = nd.array(np.array([[0, 0, 0, 7, 7]], np.float32))
    out = nd.psroi_pooling(x, rois, spatial_scale=1.0, output_dim=dim,
                           pooled_size=p)
    assert out.shape == (1, dim, p, p)
    assert np.isfinite(out.asnumpy()).all()


def test_multibox_prior_reference_order_and_aspect():
    """Reference enumeration: sizes at ratios[0] first, then sizes[0] at
    ratios[1:], with in_h/in_w aspect correction on widths."""
    x = nd.zeros((1, 3, 2, 4))  # non-square: aspect = 0.5
    a = nd.contrib.MultiBoxPrior(x, sizes=(0.4, 0.2), ratios=(1, 4)).asnumpy()[0]
    aspect = 2.0 / 4.0
    # anchor 0: size .4 ratio 1 -> w = .4*aspect/2, h = .4/2
    c = [1 / 8, 1 / 4]  # first cell center (x, y)
    np.testing.assert_allclose(
        a[0], [c[0] - 0.4 * aspect / 2, c[1] - 0.2, c[0] + 0.4 * aspect / 2,
               c[1] + 0.2], atol=1e-6)
    # anchor 1: size .2 ratio 1
    np.testing.assert_allclose(
        a[1], [c[0] - 0.2 * aspect / 2, c[1] - 0.1, c[0] + 0.2 * aspect / 2,
               c[1] + 0.1], atol=1e-6)
    # anchor 2: size .4 ratio 4 -> w = .4*aspect*2/2, h = .4/2/2
    np.testing.assert_allclose(
        a[2], [c[0] - 0.4 * aspect, c[1] - 0.1, c[0] + 0.4 * aspect,
               c[1] + 0.1], atol=1e-6)


def test_multibox_target_negative_mining():
    anchors = nd.contrib.MultiBoxPrior(nd.zeros((1, 3, 4, 4)), sizes=(0.3,))
    A = anchors.shape[1]
    label = nd.array(np.array([[[0, 0.3, 0.3, 0.6, 0.6]]], np.float32))
    rng = np.random.RandomState(0)
    cls_pred = nd.array(rng.rand(1, 2, A).astype(np.float32))
    bt, bm, ct = nd.contrib.MultiBoxTarget(
        anchors, label, cls_pred, negative_mining_ratio=3.0,
        negative_mining_thresh=0.5)
    c = ct.asnumpy()[0]
    num_pos = (c > 0).sum()
    num_neg = (c == 0).sum()
    num_ign = (c == -1).sum()
    assert num_pos >= 1
    assert num_neg <= 3 * num_pos
    assert num_ign > 0  # easy negatives ignored


def test_multibox_target_padding_cannot_clobber():
    """A padding gt row must not steal the forced match of a real gt."""
    anchors = nd.array(np.array([[[0.0, 0.0, 0.2, 0.2],
                                  [0.5, 0.5, 0.9, 0.9]]], np.float32))
    label = nd.array(np.array([[[2, 0.02, 0.02, 0.2, 0.2],
                                [-1, 0, 0, 0, 0]]], np.float32))
    bt, bm, ct = nd.contrib.MultiBoxTarget(anchors, label,
                                           nd.zeros((1, 4, 2)))
    c = ct.asnumpy()[0]
    assert c[0] == 3  # class 2 + 1
    assert c[1] == 0


def test_correlation_no_wraparound():
    x = np.zeros((1, 1, 4, 4), np.float32)
    x[0, 0, 0, 0] = 5.0  # mass only at the top-left corner
    out = nd.Correlation(nd.array(x), nd.array(x), max_displacement=1,
                         pad_size=1)
    o = out.asnumpy()[0]
    # displacement (dy=-1): shifted reads above row 0 -> zero, NOT row 3
    # channel order: (dy,dx) in row-major from (-1,-1); (dy=-1,dx=0) is ch 1
    assert o[1, 0, 0] == 0.0
    # zero displacement channel: 25 at the corner
    assert o[4, 0, 0] == 25.0


def test_correlation_kernel_size():
    rng = np.random.RandomState(1)
    x = nd.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    o1 = nd.Correlation(x, x, kernel_size=1, max_displacement=0)
    o3 = nd.Correlation(x, x, kernel_size=3, max_displacement=0, pad_size=1)
    assert o1.shape == o3.shape
    assert not np.allclose(o1.asnumpy(), o3.asnumpy())


def test_proposal_pads_with_top_box():
    """When nearly all boxes fail min-size, padding repeats the top box."""
    rng = np.random.RandomState(2)
    cls_prob = nd.array(rng.rand(1, 2, 2, 2).astype(np.float32))
    bbox_pred = nd.array(np.zeros((1, 4, 2, 2), np.float32))
    im_info = nd.array(np.array([[32, 32, 1.0]], np.float32))
    rois = nd.Proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=4,
                       rpn_post_nms_top_n=4, feature_stride=16,
                       scales=(1,), ratios=(1,), rpn_min_size=14,
                       threshold=0.01)
    r = rois.asnumpy()
    # all rows are valid boxes (w/h >= min size), duplicates allowed
    assert ((r[:, 3] - r[:, 1] + 1) >= 14).all()
    assert ((r[:, 4] - r[:, 2] + 1) >= 14).all()


def test_correlation_shrinks_without_padding():
    """Reference geometry: output = input + 2*pad - 2*(max_disp + k//2)."""
    x = nd.array(np.random.RandomState(12).randn(1, 2, 8, 8)
                 .astype(np.float32))
    out = nd.Correlation(x, x, max_displacement=2, pad_size=0)
    assert out.shape == (1, 25, 4, 4)
    out2 = nd.Correlation(x, x, max_displacement=2, pad_size=2)
    assert out2.shape == (1, 25, 8, 8)


def test_nms_topk_discards_tail():
    anchors = nd.array(np.array([[[0.1, 0.1, 0.3, 0.3],
                                  [0.5, 0.5, 0.7, 0.7],
                                  [0.75, 0.75, 0.95, 0.95]]], np.float32))
    cls_prob = np.zeros((1, 2, 3), np.float32)
    cls_prob[0, 1] = [0.9, 0.8, 0.7]
    loc = np.zeros((1, 12), np.float32)
    det = nd.MultiBoxDetection(nd.array(cls_prob), nd.array(loc), anchors,
                               nms_threshold=0.5, threshold=0.1, nms_topk=2)
    d = det.asnumpy()[0]
    assert (d[:, 0] >= 0).sum() == 2  # third box dropped by topk


def test_sequence_reverse_axis1():
    b, t, d = 2, 4, 3
    x = np.arange(b * t * d, dtype=np.float32).reshape(b, t, d)
    lens = np.array([2, 4], np.float32)
    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True, axis=1)
    r = rev.asnumpy()
    np.testing.assert_array_equal(r[0, 0], x[0, 1])
    np.testing.assert_array_equal(r[0, 2], x[0, 2])
    np.testing.assert_array_equal(r[1], x[1, ::-1])


def test_pipeline_rejects_stage_mismatch():
    import pytest as _pytest
    import jax.numpy as jnp
    from mxtpu.parallel import MeshContext, pipeline_apply
    mesh = MeshContext(pipe=4)
    ws = jnp.zeros((8, 4, 4))  # 8 stages on a 4-wide pipe
    with _pytest.raises(ValueError):
        pipeline_apply(mesh, lambda p, h: h, (ws,), jnp.zeros((4, 4)), 2)
