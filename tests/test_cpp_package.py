"""mxtpu-cpp training package tier: the generated op wrappers stay in sync
with the registry, and the C++ LeNet example compiles and converges.
Reference counterpart: cpp-package/tests + cpp-package/example/lenet.cpp."""
import os
import shutil
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_NATIVE = os.path.join(_ROOT, "mxtpu", "_native")


def test_op_wrappers_up_to_date(tmp_path):
    """Regenerating op.hpp must reproduce the checked-in file, so a newly
    registered op cannot ship without its C++ wrapper."""
    checked_in = os.path.join(_ROOT, "include", "mxtpu-cpp", "op.hpp")
    with open(checked_in) as f:
        before = f.read()
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=_ROOT)
    subprocess.run([sys.executable,
                    os.path.join(_ROOT, "tools", "gen_cpp_op_wrappers.py")],
                   check=True, env=env, capture_output=True)
    with open(checked_in) as f:
        after = f.read()
    assert before == after, ("include/mxtpu-cpp/op.hpp is stale; rerun "
                             "tools/gen_cpp_op_wrappers.py")


def test_cpp_train_lenet(tmp_path):
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    res = subprocess.run(["make", "-C", _NATIVE, "libmxtpu_c.so"],
                         capture_output=True, text=True)
    if res.returncode != 0:
        pytest.skip("libmxtpu_c.so build failed: " + res.stderr[-500:])
    exe = str(tmp_path / "train_lenet_cpp")
    subprocess.run(
        ["g++", "-O1", "-std=c++14",
         os.path.join(_ROOT, "example", "cpp", "train_lenet.cpp"),
         "-I", os.path.join(_ROOT, "include"),
         "-L", _NATIVE, "-lmxtpu_c", "-Wl,-rpath," + _NATIVE,
         "-o", exe],
        check=True)
    env = dict(os.environ, PYTHONPATH=_ROOT, JAX_PLATFORMS="cpu")
    res = subprocess.run([exe], capture_output=True, text=True,
                         timeout=600, env=env)
    assert res.returncode == 0, res.stdout + res.stderr
    assert "train_lenet (mxtpu-cpp) OK" in res.stdout
