"""Elasticity matrix (ISSUE 7): workers join/leave mid-run, hot key
shards split across servers online (mxtpu/kvstore_async.py module
docstring "Elasticity", docs/fault_tolerance.md "Elasticity").

Every row is deterministic: servers are loopback threads in this
process, scale events land on exact request/step schedules (the fault
harness's signal kinds, or direct commands), and the only polls are
bounded condition waits. The matrix:

scenario                          -> invariant proven
---------------------------------------------------------------------
server-owned shard cursor          -> each shard assigned exactly once
                                      per epoch across N workers;
                                      replayed assignment requests are
                                      deduped (same shard back)
worker leaves with work in hand    -> its outstanding shards requeue to
                                      the survivors (at-least-once)
barrier during a join              -> dynamic target grows; the barrier
                                      completes when the NEW fleet
                                      arrives (no timeout)
barrier during a leave             -> released by RE-COUNT against the
                                      shrunk membership, not by the
                                      MXTPU_PS_BARRIER_TIMEOUT deadline
online shard split                 -> value/clock/dedupe-seqs/updater
                                      state move atomically; optimizer
                                      trajectory continues bit-for-bit
push to a moved key                -> map_stale -> reroute -> replay
                                      lands EXACTLY once (dedupe seqs
                                      travelled with the key)
fresh worker after a split         -> learns the map at hello, routes
                                      straight to the new home
split aborted mid-transfer         -> clean prefix moved, rest owned,
                                      re-issued split resumes; nothing
                                      acked lost
src primary killed mid-split       -> promoted backup knows the moved
                                      prefix (map_stale forwards) and
                                      owns the rest; zero acked loss
replicated destination             -> the new shard's backup holds each
                                      key BEFORE the old primary
                                      releases it
"""
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault
from mxtpu import kvstore_async as ka
from mxtpu.kvstore_async import ParameterServer


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    """Same discipline as the fault matrix: tiny retry/backoff windows,
    heartbeat thread off, wire transport pinned on, elastic barriers
    on, clean injector."""
    monkeypatch.setattr(ka, "_RETRIES", 2)
    monkeypatch.setattr(ka, "_BACKOFF", 0.01)
    monkeypatch.setattr(ka, "_BACKOFF_MAX", 0.05)
    monkeypatch.setattr(ka, "_RECONNECT_TIMEOUT", 0.2)
    monkeypatch.setattr(ka, "_DEAD_AFTER", 2)
    monkeypatch.setattr(ka, "_ELASTIC", True)
    monkeypatch.setattr(ka, "_CURSOR_POLL", 0.01)
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    monkeypatch.setattr(ka, "_LOCAL_ON", False)
    fault.uninstall()
    yield
    fault.uninstall()


def _store(monkeypatch, addrs, rank=0, nproc=1):
    monkeypatch.setenv("MXTPU_PS_ADDRS", addrs)
    monkeypatch.setenv("MXTPU_PROC_ID", str(rank))
    monkeypatch.setenv("MXTPU_NUM_PROCS", str(nproc))
    return mx.kv.create("dist_async")


def _wait_for(cond, timeout=10.0, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.01)
    raise AssertionError("timed out waiting for %s" % what)


# ---------------------------------------------------------------------------
# the server-owned data cursor
# ---------------------------------------------------------------------------

def test_cursor_assigns_each_shard_exactly_once(monkeypatch):
    """Two workers drain one epoch concurrently: the union of their
    assignments is every shard, the intersection is empty — dynamic
    work division with no static rank/size slicing anywhere."""
    srv = ParameterServer().start()
    a = _store(monkeypatch, srv.address)
    b = _store(monkeypatch, srv.address)
    try:
        got = {"a": [], "b": []}

        def drain(name, kv):
            for shard in kv.shard_cursor(7, 12):
                got[name].append(shard)

        ta = threading.Thread(target=drain, args=("a", a))
        tb = threading.Thread(target=drain, args=("b", b))
        ta.start(); tb.start()
        ta.join(timeout=10); tb.join(timeout=10)
        assert not ta.is_alive() and not tb.is_alive()
        assert sorted(got["a"] + got["b"]) == list(range(12))
        assert not set(got["a"]) & set(got["b"])
    finally:
        a.close()
        b.close()
        srv.stop()


def test_cursor_replayed_request_gets_same_shard(monkeypatch):
    """The at-most-once story for assignments: a retried cursor_next
    (lost ack) returns the SAME shard, not a second one — the rid is
    the dedupe watermark."""
    srv = ParameterServer().start()
    conn = ka._ServerConn(srv.address)
    try:
        r1 = conn.request("cursor_next", "w1", 0, 4, 1)
        r1b = conn.request("cursor_next", "w1", 0, 4, 1)   # replay
        assert r1[1] == r1b[1] == 0
        r2 = conn.request("cursor_next", "w1", 0, 4, 2)    # fresh rid
        assert r2[1] == 1
    finally:
        conn.close()
        srv.stop()


def test_cursor_requeues_a_leavers_shards(monkeypatch):
    """A worker departs (bye) holding assignments: they go back on the
    queue and a survivor picks them up; the epoch still completes with
    every shard done exactly once by SOMEONE."""
    srv = ParameterServer().start()
    conn = ka._ServerConn(srv.address)
    kv = _store(monkeypatch, srv.address)
    try:
        conn.request("hello", "leaver", 1)
        # the leaver takes shards 0 and 1 and vanishes without done
        assert conn.request("cursor_next", "leaver", 0, 3, 1)[1] == 0
        assert conn.request("cursor_next", "leaver", 0, 3, 2)[1] == 1
        conn.request("bye", "leaver")
        got = list(kv.shard_cursor(0, 3))
        assert sorted(got) == [0, 1, 2]
        _, s = conn.request("stats")
        assert s["cursor_requeues"] == 2
        assert s["leaves"] == 1
    finally:
        conn.close()
        kv.close()
        srv.stop()


# ---------------------------------------------------------------------------
# dynamic barriers: join/leave while waiting
# ---------------------------------------------------------------------------

def test_barrier_completes_when_fleet_grows_mid_wait(monkeypatch):
    """Barrier-during-join: A waits at a dynamic barrier against a
    2-member fleet; worker C JOINS mid-wait (target grows to 3), then
    the other two arrive — the barrier releases only when the grown
    fleet is complete, by arrivals, never by deadline."""
    monkeypatch.setattr(ka, "_BARRIER_TIMEOUT", 30)
    srv = ParameterServer().start()
    a = _store(monkeypatch, srv.address)
    bconn = ka._ServerConn(srv.address)
    bconn.request("hello", "worker-b", 1)      # 2nd member, not arrived
    done = {"a": False}

    def wait_a():
        a.barrier()
        done["a"] = True

    t = threading.Thread(target=wait_a, daemon=True)
    c = None
    try:
        t.start()
        _wait_for(lambda: srv._barrier_arrived == 1,
                  what="A's barrier arrival")
        assert not done["a"]
        c = _store(monkeypatch, srv.address)   # join mid-wait: target 3
        tb = threading.Thread(
            target=lambda: bconn.request("barrier", 0, 30,
                                         timeout=40.0), daemon=True)
        tb.start()
        _wait_for(lambda: srv._barrier_arrived == 2,
                  what="B's barrier arrival")
        assert not done["a"], "released before the joined fleet arrived"
        c.barrier()                            # 3/3: release
        t.join(timeout=5)
        tb.join(timeout=5)
        assert done["a"]
        assert srv._barrier_timeouts == 0
        assert srv._barrier_recounts == 0      # completed by arrivals
    finally:
        t.join(timeout=5)
        bconn.close()
        a.close()
        if c is not None:
            c.close()
        srv.stop()


def test_barrier_recounts_when_member_leaves_mid_wait(monkeypatch):
    """Barrier-during-leave (the ISSUE's re-count requirement): A and B
    are members; A waits; B departs WITHOUT arriving. The barrier
    releases by re-count against the shrunk membership — counted in
    barrier_recounts, NOT in barrier_timeouts, and long before the
    deadline."""
    monkeypatch.setattr(ka, "_BARRIER_TIMEOUT", 60)
    srv = ParameterServer().start()
    a = _store(monkeypatch, srv.address)
    b = _store(monkeypatch, srv.address)
    done = {"a": False}

    def wait_a():
        a.barrier()
        done["a"] = True

    t = threading.Thread(target=wait_a, daemon=True)
    try:
        t.start()
        _wait_for(lambda: srv._barrier_arrived == 1,
                  what="A's barrier arrival")
        assert not done["a"]
        t0 = time.monotonic()
        b.close()          # clean leave: bye drops membership
        t.join(timeout=10)
        assert done["a"], "barrier never released on the leave"
        assert time.monotonic() - t0 < 5, "released by deadline, not " \
                                          "by re-count"
        assert srv._barrier_recounts == 1
        assert srv._barrier_timeouts == 0
        assert a.stats()["barrier_recounts"] == 1
    finally:
        t.join(timeout=5)
        a.close()
        srv.stop()


def test_dead_worker_gc_releases_barrier(monkeypatch):
    """The crash flavor of the leave row: a worker that vanishes
    without a bye is lease-GC'd (MXTPU_PS_WORKER_DEAD_AFTER) and the
    GC itself re-counts the barrier."""
    monkeypatch.setattr(ka, "_BARRIER_TIMEOUT", 60)
    monkeypatch.setattr(ka, "_WORKER_DEAD_AFTER", 0.05)
    srv = ParameterServer().start()
    a = _store(monkeypatch, srv.address)
    conn = ka._ServerConn(srv.address)
    done = {"a": False}

    def wait_a():
        a.barrier()
        done["a"] = True

    t = threading.Thread(target=wait_a, daemon=True)
    try:
        conn.request("hello", "ghost", 1)    # second member, never byes
        t.start()
        _wait_for(lambda: srv._barrier_arrived == 1,
                  what="A's barrier arrival")
        time.sleep(0.08)                     # leases expire (the parked
        #                                      waiter A's too — it is
        #                                      silent while it waits)
        assert srv._gc_workers() >= 1        # the sweep reaps the ghost
        t.join(timeout=10)
        assert done["a"], "GC did not release the barrier"
        assert srv._barrier_recounts == 1
        assert srv._barrier_timeouts == 0
    finally:
        t.join(timeout=5)
        conn.close()
        a.close()
        srv.stop()


# ---------------------------------------------------------------------------
# online shard split
# ---------------------------------------------------------------------------

def _split_world(monkeypatch, n_keys=6, optimizer=False):
    """Two launch-time servers + one fresh (reshard-target) server and
    a store with n_keys initialized and pushed once."""
    s0 = ParameterServer().start()
    s1 = ParameterServer().start()
    dst = ParameterServer().start()
    kv = _store(monkeypatch, "%s,%s" % (s0.address, s1.address))
    keys = ["k%d" % i for i in range(n_keys)]
    kv.init(keys, [mx.nd.zeros((4,)) for _ in keys])
    if optimizer:
        kv.set_optimizer(mx.optimizer.SGD(learning_rate=0.1,
                                          momentum=0.9))
    return s0, s1, dst, kv, keys


def test_split_moves_keys_and_routes_exactly_once(monkeypatch):
    """The core handoff: half of s0's keys move to a fresh server with
    value+clock+dedupe seqs; subsequent pushes hit map_stale, reroute,
    and land exactly once (clock arithmetic is exact across the whole
    fleet)."""
    s0, s1, dst, kv, keys = _split_world(monkeypatch)
    conn = ka._ServerConn(s0.address)
    try:
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        before = dict(s0._clock)
        assert before, "s0 owns no keys — pick different key names"
        reply = conn.request("split", dst.address)
        moved = reply[1]["moved"]
        assert moved and len(moved) == (len(before) + 1) // 2
        for k in moved:
            assert k not in s0._table
            assert s0._moved[k] == dst.address
            assert dst._clock[k] == 1          # clock travelled
        # pushes after the split: moved keys reroute via map_stale
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        out = mx.nd.zeros((4,))
        for k in keys:
            kv.pull(k, out=out)
            np.testing.assert_allclose(out.asnumpy(), 2 * np.ones(4),
                                       err_msg=str(k))
        st = kv.stats()
        assert st["map_reroutes"] >= len(moved)
        assert st["elastic"]["splits"] == 1
        assert st["elastic"]["keys_moved"] == len(moved)
        assert st["elastic"]["keys_adopted"] == len(moved)
        # fleet-wide table integrity: every key applied exactly twice
        clocks = kv.staleness_stats()["clocks"]
        assert set(clocks) == set(keys)
        assert all(v == 2 for v in clocks.values()), clocks
    finally:
        conn.close()
        kv.close()
        s0.stop(); s1.stop(); dst.stop()


def test_split_replays_are_deduped_exactly_once(monkeypatch):
    """The satellite row verbatim: a client pushing to a moved key gets
    map_stale -> refetches the map -> replays — and a RE-replay of the
    same (origin, seq) at the new home is refused as a dup, because the
    dedupe seqs travelled with the key."""
    s0, s1, dst, kv, keys = _split_world(monkeypatch)
    conn = ka._ServerConn(s0.address)
    try:
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        moved = conn.request("split", dst.address)[1]["moved"]
        k = moved[0]
        # a push that still believes in the old map
        seq = next(kv._seq)
        with pytest.raises(RuntimeError, match="map_stale"):
            conn.request("push", k, np.ones(4, "f"), 0,
                         kv._origin, seq)
        # the client-side replay path: reroute + replay
        kv._replay_moved_push(
            (k, np.ones(4, "f"), 0, seq),
            RuntimeError("parameter server: map_stale: key %r moved "
                         "to %s (map_version 1)" % (k, dst.address)))
        assert dst._clock[k] == 2
        # replaying the SAME seq again (retry after a lost ack) is a dup
        dconn = ka._ServerConn(dst.address)
        assert dconn.request("push", k, np.ones(4, "f"), 0,
                             kv._origin, seq)[1] == "dup"
        assert dst._clock[k] == 2
        # and the PRE-split seq dedupe also travelled: replay seq 1
        # (the original pre-split push) at the new home — refused
        old = [s for (o, kk), s in dst._applied.items() if kk == k]
        assert old, "dedupe seqs did not travel with the key"
        dconn.close()
    finally:
        conn.close()
        kv.close()
        s0.stop(); s1.stop(); dst.stop()


def test_fresh_worker_learns_map_at_hello(monkeypatch):
    """A worker joining AFTER a split never sees map_stale at all: the
    hello reply carries the versioned map, so its first push routes
    straight to the key's new home."""
    s0, s1, dst, kv, keys = _split_world(monkeypatch)
    conn = ka._ServerConn(s0.address)
    joiner = None
    try:
        moved = conn.request("split", dst.address)[1]["moved"]
        joiner = _store(monkeypatch, "%s,%s" % (s0.address, s1.address))
        for k in moved:
            assert joiner._key_overrides.get(k) == dst.address
        joiner.push(moved[0], mx.nd.ones((4,)))
        assert joiner.stats()["map_reroutes"] == 0
        assert dst._clock[moved[0]] == 1
    finally:
        conn.close()
        if joiner is not None:
            joiner.close()
        kv.close()
        s0.stop(); s1.stop(); dst.stop()


def test_split_carries_updater_state(monkeypatch):
    """Optimizer continuity: with a server-side momentum SGD, the
    moved key's accumulated updater state travels — the post-split
    trajectory matches an unsplit control server bit-for-bit."""
    s0, s1, dst, kv, keys = _split_world(monkeypatch, optimizer=True)
    # control: an unsplit server seeing the same push stream
    ctrl = ParameterServer().start()
    cconn = ka._ServerConn(ctrl.address)
    conn = ka._ServerConn(s0.address)
    try:
        import pickle
        cconn.request("set_optimizer",
                      pickle.dumps(mx.optimizer.SGD(learning_rate=0.1,
                                                    momentum=0.9)))
        grads = [np.full(4, g, "f") for g in (1.0, 2.0, -1.0, 0.5)]
        # two pushes pre-split, two post-split, same stream to control
        for k in keys:
            cconn.request("init", k, np.zeros(4, "f"))
        for g in grads[:2]:
            for k in keys:
                kv.push(k, mx.nd.array(g))
                cconn.request("push", k, g.copy(), 0)
        moved = conn.request("split", dst.address)[1]["moved"]
        assert moved
        for g in grads[2:]:
            for k in keys:
                kv.push(k, mx.nd.array(g))
                cconn.request("push", k, g.copy(), 0)
        out = mx.nd.zeros((4,))
        for k in keys:
            kv.pull(k, out=out)
            _, want, _ = cconn.request("pull", k)
            np.testing.assert_array_equal(
                out.asnumpy(), np.asarray(want),
                err_msg="momentum state did not travel with %r" % (k,))
    finally:
        conn.close(); cconn.close()
        kv.close()
        ctrl.stop()
        s0.stop(); s1.stop(); dst.stop()


def test_split_aborts_cleanly_and_resumes(monkeypatch):
    """Transfer interrupted mid-way (destination unreachable from the
    second key on): a clean prefix is moved, the rest stays OWNED and
    serving, and a re-issued split finishes the job — nothing acked is
    ever lost."""
    s0, s1, dst, kv, keys = _split_world(monkeypatch)
    conn = ka._ServerConn(s0.address)
    try:
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        n_local = len(s0._table)
        assert n_local >= 2, "need >= 2 keys on s0 for a mid-split abort"
        # move EVERY local key so the abort lands mid-transfer
        local = sorted(s0._table)
        with fault.inject("kind=sever,point=worker.send,"
                          "op=adopt_key,nth=2,count=inf"):
            with pytest.raises(RuntimeError, match="aborted after 1"):
                conn.request("split", dst.address, local, retries=0)
        assert len(s0._moved) == 1                  # the clean prefix
        assert len(s0._table) == n_local - 1        # the rest still ours
        # every key still serves (owned or forwarded), nothing lost
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        clocks = kv.staleness_stats()["clocks"]
        assert all(v == 2 for v in clocks.values()), clocks
        # re-issue: the split resumes over the remaining keys
        reply = conn.request("split", dst.address)
        assert reply[0] == "ok" and reply[1]["moved"]
        assert s0._splits == 1                      # only the COMPLETE one
    finally:
        conn.close()
        kv.close()
        s0.stop(); s1.stop(); dst.stop()


# ---------------------------------------------------------------------------
# split x replication
# ---------------------------------------------------------------------------

def _pair(monkeypatch, **srv_kw):
    """A joined (primary, backup) shard pair plus a replicated store
    pointed at the primary (same helper as the fault matrix)."""
    pri = ParameterServer(role="primary", **srv_kw).start()
    bak = ParameterServer(role="backup", peer_addr=pri.address).start()
    pri._peer_addr = bak.address
    bak.join_cluster(probe_interval=0)
    _wait_for(lambda: bak._catchup_complete, what="initial catch-up")
    monkeypatch.setenv("MXTPU_PS_REPLICAS", "2")
    kv = _store(monkeypatch, pri.address)
    assert isinstance(kv._conns[0], ka._ReplicatedConn)
    return pri, bak, kv


def test_replicated_dst_backs_up_before_release(monkeypatch):
    """'Each new shard gets its backup before the old primary releases
    it': splitting INTO a replicated pair, every adopt is mirrored to
    the destination's backup before src marks the key moved — kill the
    new primary right after the split and nothing is lost."""
    dpri, dbak, kv = _pair(monkeypatch)
    src = ParameterServer().start()
    conn = ka._ServerConn(src.address)
    try:
        sconn = ka._ServerConn(src.address)
        for i in range(4):
            sconn.request("init", "m%d" % i, np.zeros(4, "f"))
            sconn.request("push", "m%d" % i, np.ones(4, "f"), 0,
                          "w", 1)
        sconn.close()
        moved = conn.request("split", dpri.address)[1]["moved"]
        assert moved
        for k in moved:
            # the backup holds the key + clock BEFORE src released it
            assert dbak._clock.get(k) == 1, \
                "dst backup missing %r at release time" % (k,)
            np.testing.assert_allclose(dbak._table[k], np.ones(4))
        # kill the new primary: the promoted backup serves the adopted
        # keys — the split created no unreplicated window
        dpri.kill()
        _wait_for(lambda: not dpri._thread.is_alive(),
                  what="dst primary teardown")
        out = mx.nd.zeros((4,))
        kv._plan(moved[0], (4,))
        kv._key_overrides[moved[0]] = dpri.address
        kv.pull(moved[0], out=out)
        np.testing.assert_allclose(out.asnumpy(), np.ones(4))
        assert dbak._role == "primary"
    finally:
        conn.close()
        kv.close()
        src.stop()
        dpri.stop(); dbak.stop()


def test_src_primary_killed_mid_split_no_acked_loss(monkeypatch):
    """The satellite row: the SOURCE primary dies after a partial
    split. Its sync-replicated backup learned the moved prefix (the
    'moved' records rode the stream before the kill), so after
    promotion it forwards the moved keys with map_stale and serves the
    rest from its mirrored table — zero acknowledged-update loss, and
    re-issuing the split against the promoted primary completes the
    reshard."""
    pri, bak, kv = _pair(monkeypatch)
    dst = ParameterServer().start()
    conn = ka._ServerConn(pri.address)
    try:
        keys = ["m%d" % i for i in range(4)]
        kv.init(keys, [mx.nd.zeros((4,)) for _ in keys])
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        # abort the split after exactly one key moved...
        with fault.inject("kind=sever,point=worker.send,"
                          "op=adopt_key,nth=2,count=inf"):
            with pytest.raises(RuntimeError, match="aborted after 1"):
                conn.request("split", dst.address, retries=0)
        moved_key = list(pri._moved)[0]
        # ...the backup mirrored the release before anything else
        assert bak._moved.get(moved_key) == dst.address
        assert moved_key not in bak._table
        # now the primary dies for real, mid-reshard
        pri.kill()
        _wait_for(lambda: not pri._thread.is_alive(),
                  what="primary teardown")
        # pushes continue: unmoved keys fail over to the promoted
        # backup, the moved key forwards to dst — exactly once each
        for k in keys:
            kv.push(k, mx.nd.ones((4,)))
        assert bak._role == "primary"
        for k in keys:
            want = 2
            have = (dst._clock.get(k) if k == moved_key
                    else bak._clock.get(k))
            assert have == want, (k, have)
        # the reshard resumes against the promoted primary
        bconn = ka._ServerConn(bak.address)
        reply = bconn.request("split", dst.address)
        assert reply[0] == "ok" and reply[1]["moved"]
        bconn.close()
        clocks = {}
        for srv in (bak, dst):
            clocks.update(srv._clock)
        assert set(clocks) == set(keys)
        assert all(v == 2 for v in clocks.values()), clocks
    finally:
        conn.close()
        kv.close()
        pri.stop(); bak.stop(); dst.stop()


def test_moved_map_survives_snapshot_restart(monkeypatch, tmp_path):
    """A respawned source server keeps refusing split-away keys: the
    forwarding table rides the snapshot, so a restart cannot resurrect
    a stale copy of a moved key."""
    src = ParameterServer(snapshot_dir=str(tmp_path),
                          snapshot_every=0).start()
    dst = ParameterServer().start()
    conn = ka._ServerConn(src.address)
    try:
        for i in range(4):
            conn.request("init", "m%d" % i, np.zeros(4, "f"))
            conn.request("push", "m%d" % i, np.ones(4, "f"), 0, "w", 1)
        moved = conn.request("split", dst.address)[1]["moved"]
        src.snapshot()
        conn.close()
        src.stop()
        src2 = ParameterServer(snapshot_dir=str(tmp_path)).start()
        try:
            assert src2._moved == {k: dst.address for k in moved}
            assert src2._map_version >= len(moved)
            c2 = ka._ServerConn(src2.address)
            with pytest.raises(RuntimeError, match="map_stale"):
                c2.request("pull", moved[0])
            c2.close()
        finally:
            src2.stop()
    finally:
        dst.stop()


# ---------------------------------------------------------------------------
# the elastic fault kinds (reproducible drills)
# ---------------------------------------------------------------------------

def test_elastic_fault_kinds_parse_and_signal():
    rules = fault.parse_spec(
        "kind=join_worker,point=worker.step,nth=2;"
        "kind=leave_worker,point=worker.step,nth=4;"
        "kind=split_shard,nth=6")
    assert [r.kind for r in rules] == ["join_worker", "leave_worker",
                                      "split_shard"]
    with pytest.raises(ValueError, match="worker.step"):
        fault.parse_spec("kind=split_shard,point=server.recv")
    inj = fault.FaultInjector(
        "kind=join_worker,point=worker.step,nth=2;"
        "kind=split_shard,point=worker.step,nth=3")
    acts = [inj.fire("worker.step", op="step") for _ in range(4)]
    # a fired rule consumes its event (later rules never see it), so
    # the split rule's 3rd MATCHING event is global event 4
    assert acts == [None, "join_worker", None, "split_shard"]


def test_guard_elastic_callback_fires_on_schedule():
    """TrainGuard delivers the elastic signals to a registered handler
    on exact step counts (and counts them), without disturbing the
    step itself."""
    from mxtpu import gluon
    from mxtpu.gluon import nn
    from mxtpu.parallel import MeshContext, ShardedTrainer
    from mxtpu.resilience import TrainGuard
    import mxtpu.gluon.block as _blk
    _blk._NAME_COUNTERS.clear()
    mx.random.seed(3)
    np.random.seed(3)
    net = nn.HybridSequential()
    with net.name_scope():
        net.add(nn.Dense(8), nn.Dense(10))
    net.initialize(mx.init.Xavier())
    x = np.random.randn(8, 4).astype(np.float32)
    y = np.random.randint(0, 10, (8,)).astype(np.float32)
    net(mx.nd.array(x))
    st = ShardedTrainer(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                        "sgd", {"learning_rate": 0.1},
                        mesh=MeshContext())
    guard = TrainGuard(st, spike_z=0)
    seen = []
    guard.set_elastic_callback(lambda kind: seen.append(
        (kind, guard.stats()["steps"])))
    with fault.inject("kind=join_worker,point=worker.step,nth=2;"
                      "kind=split_shard,point=worker.step,nth=4"):
        for _ in range(5):
            loss = guard.step(mx.nd.array(x), mx.nd.array(y))
            assert np.isfinite(loss)
    # fired BEFORE steps 2 and 5 ran (stats()["steps"] counts completed
    # steps; the join rule consumed step-event 2, so the split rule's
    # 4th matching event is global step 5)
    assert seen == [("join_worker", 1), ("split_shard", 4)]
    assert guard.stats()["elastic_signals"] == 2
    assert guard.stats()["good_steps"] == 5


# ---------------------------------------------------------------------------
# idempotent scale actuation (ISSUE 16 satellite): tools/launch.py routes
# every --scale event (and every autoscale-controller action) through one
# id-keyed ActionExecutor — re-issuing an event after an ambiguous
# timeout returns the recorded verdict instead of double-applying
# ---------------------------------------------------------------------------

def test_reissued_add_worker_event_does_not_double_apply(tmp_path):
    from mxtpu.fleet.actuator import ActionExecutor
    spawned = []
    ex = ActionExecutor(str(tmp_path),
                        {"add_worker": lambda a: spawned.append(a) or
                         {"rank": len(spawned)}}, verbose=False)
    ev = {"action": "add_worker", "after": "1"}
    # the launcher derives the id from the event's position, so the
    # SAME drill event re-issued (ambiguous timeout, operator retry)
    # lands on the same id
    v1 = ex.execute("scale-0-add_worker", dict(ev))
    v2 = ex.execute("scale-0-add_worker", dict(ev))
    assert v1["verdict"] == v2["verdict"] == "ok"
    assert len(spawned) == 1
    # a DIFFERENT event applies normally
    ex.execute("scale-1-add_worker", dict(ev))
    assert len(spawned) == 2


def test_reissued_split_shard_event_does_not_double_split(tmp_path):
    from mxtpu.fleet.actuator import ActionExecutor
    splits = []

    def do_split(action):
        splits.append(action.get("src", "0"))
        return {"src": action.get("src", "0"), "dst": "127.0.0.1:9999"}

    ex = ActionExecutor(str(tmp_path), {"split_shard": do_split},
                        verbose=False)
    ev = {"action": "split_shard", "src": "0", "after": "2"}
    v1 = ex.execute("scale-0-split_shard", dict(ev))
    # retry after an ambiguous timeout: the recorded verdict comes
    # back, the split does NOT run twice (a double split would strand
    # half the keys on a shard nobody routes to)
    v2 = ex.execute("scale-0-split_shard", dict(ev))
    assert splits == ["0"]
    assert v2["detail"]["dst"] == v1["detail"]["dst"]
    # and across a launcher restart the verdict record still holds
    ex2 = ActionExecutor(str(tmp_path), {"split_shard": do_split},
                         verbose=False)
    assert ex2.execute("scale-0-split_shard",
                       dict(ev))["verdict"] == "ok"
    assert splits == ["0"]
