"""Serving fast tier: engine buckets/AOT, dynamic batcher semantics,
admission control, deadlines, drain, and client failover — all loopback
threads in this process (the E2E two-process kill -9 drill lives in
tests/test_dist_launch.py; the four-contract smoke in
ci/check_serving.py).

Determinism notes the rows rely on: a single-bucket menu makes a
request's bits independent of which batch composition it coalesced
into (docs/serving.md "Determinism"), and every fault comes from the
mxtpu.fault schedule harness — no timing-dependent assertions beyond
generous bounds.
"""
import os
import threading

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault
from mxtpu import kvstore_async as ka
from mxtpu.serving import (DeadlineExceeded, InferenceEngine,
                           ModelServer, Overloaded, ServingClient,
                           parse_buckets, parse_shape_spec)

IN_DIM = 6


@pytest.fixture(autouse=True)
def _serving_knobs(monkeypatch):
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    monkeypatch.setattr(ka, "_RETRIES", 1)
    monkeypatch.setattr(ka, "_BACKOFF", 0.01)
    monkeypatch.setattr(ka, "_BACKOFF_MAX", 0.05)
    monkeypatch.setattr(ka, "_RECONNECT_TIMEOUT", 0.2)
    monkeypatch.setattr(ka, "_DEAD_AFTER", 2)
    fault.uninstall()
    yield
    fault.uninstall()


@pytest.fixture(scope="module")
def model():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=8, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, IN_DIM))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.init.Uniform(0.1))
    arg_params, aux_params = mod.get_params()
    return net, arg_params, aux_params


def _engine(model, buckets=(1, 2, 4), warm=True):
    net, arg_params, aux_params = model
    return InferenceEngine(net, arg_params, aux_params,
                           {"data": (IN_DIM,)}, buckets=buckets,
                           warm=warm)


def _server(model, **kw):
    kw.setdefault("batch_deadline_ms_", 10)
    buckets = kw.pop("buckets", (1, 2, 4))
    return ModelServer(_engine(model, buckets=buckets, warm=False),
                       model_name="t", **kw).start()


# ---------------------------------------------------------------------------
# spec parsing + engine
# ---------------------------------------------------------------------------

def test_spec_parsing():
    assert parse_buckets("8,1,4,4") == (1, 4, 8)
    with pytest.raises(ValueError):
        parse_buckets("0,2")
    assert parse_shape_spec("data=3,32,32") == {"data": (3, 32, 32)}
    assert parse_shape_spec("a=4;b=2,2") == {"a": (4,), "b": (2, 2)}
    with pytest.raises(ValueError):
        parse_shape_spec("nodims")


def test_engine_warm_compiles_every_bucket_then_zero_retraces(model):
    eng = _engine(model, buckets=(1, 2, 4), warm=True)
    assert eng.cache.compiles == 3
    x = np.random.RandomState(0).rand(3, IN_DIM).astype("f")
    for _ in range(4):
        out = eng.predict([x])
    assert eng.cache.compiles == 3       # steady state never retraces
    assert out[0].shape == (3, 3)
    # padding accounting: 3 rows ride the 4-bucket
    assert eng.stats()["pad_rows"] == 4 * 1


def test_engine_validates_payloads(model):
    eng = _engine(model, buckets=(1, 2), warm=False)
    with pytest.raises(ValueError):
        eng.check_rows([np.zeros((1, IN_DIM + 1), "f")])  # bad shape
    with pytest.raises(ValueError):
        eng.check_rows([np.zeros((3, IN_DIM), "f")])      # > max bucket
    with pytest.raises(ValueError):
        eng.check_rows([np.zeros((0, IN_DIM), "f")])      # empty
    assert eng.check_rows([np.zeros((2, IN_DIM), "f")]) == 2


def test_engine_from_checkpoint_roundtrip(model, tmp_path):
    net, arg_params, aux_params = model
    mod = mx.mod.Module(net, data_names=("data",),
                        label_names=("softmax_label",))
    mod.bind(data_shapes=[("data", (4, IN_DIM))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params()
    mod.set_params(arg_params, aux_params)
    prefix = str(tmp_path / "m")
    mod.save_checkpoint(prefix, 3)
    eng = InferenceEngine.from_checkpoint(prefix, 3, {"data": (IN_DIM,)},
                                          buckets=(2,), warm=False)
    direct = _engine(model, buckets=(2,), warm=False)
    x = np.random.RandomState(1).rand(2, IN_DIM).astype("f")
    np.testing.assert_array_equal(eng.predict([x])[0],
                                  direct.predict([x])[0])


def test_single_bucket_bits_are_composition_independent(model):
    # the determinism contract the failover drills rest on
    eng = _engine(model, buckets=(4,), warm=True)
    rng = np.random.RandomState(2)
    xs = [rng.rand(1, IN_DIM).astype("f") for _ in range(4)]
    alone = [eng.predict([x])[0] for x in xs]
    packed = eng.predict([np.concatenate(xs)])[0]
    for i in range(4):
        np.testing.assert_array_equal(alone[i][0], packed[i])


# ---------------------------------------------------------------------------
# batching + admission on the server
# ---------------------------------------------------------------------------

def _concurrent(cli, xs, budget_ms=None):
    outs, errs = {}, {}
    lock = threading.Lock()

    def one(i):
        try:
            r = cli.predict(xs[i], budget_ms=budget_ms)[0]
            with lock:
                outs[i] = r
        except Exception as e:
            with lock:
                errs[i] = e

    ts = [threading.Thread(target=one, args=(i,)) for i in range(len(xs))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60)
    return outs, errs


def test_concurrent_requests_coalesce_into_buckets(model):
    srv = _server(model, batch_deadline_ms_=25)
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        xs = [np.full((1, IN_DIM), float(i), "f") for i in range(8)]
        outs, errs = _concurrent(cli, xs)
        assert not errs
        assert len(outs) == 8
        b = srv.stats()["batcher"]
        assert b["batches"] < b["batched_requests"] == 8
        assert b["max_batch_rows"] <= 4          # bucket cap respected
        # responses sliced back per request: row i is softmax of x_i,
        # all rows of a request equal (constant input)
        for i, out in outs.items():
            assert out.shape == (1, 3)
    finally:
        srv.stop()


def test_local_transport_parity(model, monkeypatch):
    # the same admission/batching path serves the in-process shortcut
    monkeypatch.setattr(ka, "_LOCAL_ON", True)
    srv = _server(model)
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        out = cli.predict(np.ones((2, IN_DIM), "f"))[0]
        assert out.shape == (2, 3)
        assert cli.stats()["comms"]["local_reqs"] >= 1
        assert srv.stats()["counters"]["responses"] == 1
    finally:
        srv.stop()


def test_queue_full_sheds_with_retriable_verdict(model):
    srv = _server(model, queue_depth_=0)
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=2000)
        with pytest.raises(Overloaded) as ei:
            cli.predict(np.ones((1, IN_DIM), "f"))
        assert ei.value.retriable
        assert any(v == "overloaded" for _, v, _ in ei.value.verdicts)
        assert srv.stats()["counters"]["shed_overloaded"] == 1
        assert srv.stats()["batcher"]["shed_queue_full"] == 1
    finally:
        srv.stop()


def test_deadline_expiry_drops_before_dispatch(model):
    srv = _server(model, batch_deadline_ms_=50)
    try:
        cli = ServingClient(addrs=[srv.address])
        with pytest.raises(DeadlineExceeded):
            cli.predict(np.ones((1, IN_DIM), "f"), budget_ms=1.0)
        c = srv.stats()["counters"]
        assert c["expired"] == 1
        assert c["responses"] == 0               # zero responses after
        assert srv.stats()["engine"]["predicts"] == 0  # never dispatched
    finally:
        srv.stop()


def test_injected_admission_delay_burns_budget(model):
    # kind=delay at serve.request: deterministic deadline-expiry drill
    srv = _server(model, batch_deadline_ms_=5)
    try:
        cli = ServingClient(addrs=[srv.address])
        with fault.inject("kind=delay,point=serve.request,delay=0.08"):
            with pytest.raises(DeadlineExceeded):
                cli.predict(np.ones((1, IN_DIM), "f"), budget_ms=30.0)
        assert srv.stats()["counters"]["expired"] == 1
    finally:
        srv.stop()


def test_drain_refuses_then_flushes(model):
    srv = _server(model, batch_deadline_ms_=100)
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=5000)
        # park a request in the open batch window, then drain: the
        # parked request must still be answered (flushed, not dropped)
        got = {}
        t = threading.Thread(target=lambda: got.setdefault(
            "out", cli.predict(np.ones((1, IN_DIM), "f"))))
        t.start()
        deadline = 50
        while srv._batcher.pending() == 0 and deadline:
            deadline -= 1
            threading.Event().wait(0.01)
        assert srv.drain(timeout=10.0)
        t.join(timeout=10)
        assert got["out"][0].shape == (1, 3)
        # admissions now refuse with the retriable draining verdict
        with pytest.raises(Overloaded) as ei:
            cli.predict(np.ones((1, IN_DIM), "f"))
        assert any(v == "draining" for _, v, _ in ei.value.verdicts)
        assert srv.stats()["counters"]["shed_draining"] >= 1
    finally:
        srv.stop()


def test_client_drain_is_the_wire_form_of_sigterm(model):
    """``ServingClient.drain()`` drives the ``drain`` wire op — the
    scriptable operator surface (and the reason the op is not a dead
    handler in the wire-protocol contract): the replica acks with
    ``draining: True`` and subsequent admissions shed retriably."""
    srv = _server(model)
    try:
        cli = ServingClient(addrs=[srv.address])
        info = cli.drain(timeout=5.0)
        assert info == {"draining": True}
        deadline = 100
        while not srv._batcher._stopped and deadline:
            deadline -= 1
            threading.Event().wait(0.01)
        with pytest.raises(Overloaded) as ei:
            cli.predict(np.ones((1, IN_DIM), "f"))
        assert any(v == "draining" for _, v, _ in ei.value.verdicts)
    finally:
        srv.stop()


def test_oversized_request_is_an_error_not_a_shed(model):
    srv = _server(model)        # buckets (1,2,4): 5 rows cannot fit
    try:
        cli = ServingClient(addrs=[srv.address])
        with pytest.raises(RuntimeError, match="bad predict payload"):
            cli.predict(np.ones((5, IN_DIM), "f"))
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# replica failover
# ---------------------------------------------------------------------------

def _pair(model, **kw):
    s1 = _server(model, buckets=(4,), **kw)
    s2 = ModelServer(_engine(model, buckets=(4,), warm=False),
                     model_name="t", batch_deadline_ms_=10,
                     replicas=[s1.address], **kw).start()
    s1._replicas.append(s2.address)
    return s1, s2


def test_hello_learns_replica_set(model):
    s1, s2 = _pair(model)
    try:
        cli = ServingClient(addrs=[s1.address])
        info = cli.hello()
        assert sorted(info["replicas"]) == sorted([s1.address,
                                                   s2.address])
        assert cli.signature["data_names"] == ["data"]
        assert sorted(cli.stats()["replicas"]) == \
            sorted([s1.address, s2.address])
    finally:
        s2.stop()
        s1.stop()


def test_killed_replica_fails_over_exactly_once(model):
    s1, s2 = _pair(model)
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        cli.hello()
        rng = np.random.RandomState(3)
        xs = [rng.rand(1, IN_DIM).astype("f") for _ in range(6)]
        oracle = _engine(model, buckets=(4,), warm=True)
        want = [oracle.predict([x])[0] for x in xs]
        with fault.inject("kind=kill,point=serve.batch,nth=1") as inj:
            outs, errs = _concurrent(cli, xs)
        assert inj.stats()[0][4] == 1, "kill never fired"
        assert not errs, errs
        assert len(outs) == 6                   # exactly one answer each
        for i, out in outs.items():
            np.testing.assert_array_equal(out, want[i][:1])
        assert cli.stats()["failovers"] >= 1
        # exactly one replica died; the other answered the replays
        alive = [s for s in (s1, s2) if not s._tcp.dying]
        assert len(alive) == 1
        assert alive[0].stats()["counters"]["responses"] >= 1
    finally:
        s2.stop()
        s1.stop()


def test_draining_replica_steers_clients_to_peer(model):
    s1, s2 = _pair(model)
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=5000)
        cli.hello()
        active = cli.stats()["active"]
        draining = s1 if active == s1.address else s2
        other = s2 if draining is s1 else s1
        draining.drain(timeout=5.0)
        out = cli.predict(np.ones((1, IN_DIM), "f"))[0]
        assert out.shape == (1, 3)
        assert other.stats()["counters"]["responses"] == 1
        assert draining.stats()["counters"]["shed_draining"] == 1
        assert cli.stats()["failovers"] >= 1
    finally:
        s2.stop()
        s1.stop()


def test_dup_request_ids_are_counted(model):
    srv = _server(model)
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=2000)
        x = np.ones((1, IN_DIM), "f")
        cli.predict(x)
        # replay the same rid by hand (what a failover replay does)
        conn = cli._conn_for(srv.address)
        rid = "%s:%d" % (cli._origin, 1)
        reply = conn.request("predict", rid, (x,), 2000.0,
                             timeout=30.0, retries=0)
        assert reply[0] == "ok"
        assert srv.stats()["counters"]["dup_requests"] == 1
    finally:
        srv.stop()


def test_injected_drop_replays_on_peer(model):
    # serve.request drop: the admitted request vanishes without a
    # reply; the client replays the SAME rid on the other replica
    s1, s2 = _pair(model)
    try:
        cli = ServingClient(addrs=[s1.address], budget_ms=2000)
        cli.hello()
        with fault.inject("kind=drop,point=serve.request,nth=1,count=1"):
            out = cli.predict(np.ones((1, IN_DIM), "f"))[0]
        assert out.shape == (1, 3)
        total = (s1.stats()["counters"]["dropped"]
                 + s2.stats()["counters"]["dropped"])
        assert total == 1
        assert cli.stats()["replays"] >= 1
    finally:
        s2.stop()
        s1.stop()


def test_server_stats_surface_the_story(model):
    srv = _server(model)
    try:
        cli = ServingClient(addrs=[srv.address], budget_ms=2000)
        cli.predict(np.ones((2, IN_DIM), "f"))
        s = cli.server_stats()
        assert s["counters"]["responses"] == 1
        assert s["batcher"]["batches"] == 1
        assert s["batcher"]["batched_rows"] == 2
        assert s["engine"]["predicts"] == 1
        assert s["queue_depth"] >= 1 and "batch_deadline_ms" in s
    finally:
        srv.stop()


def test_stale_epoch_probe_cannot_demote_healthy_replica(model):
    # partition anti-flap (ISSUE 19): ping verdicts carry the
    # replica's lifecycle epoch; a delayed pre-resume "draining"
    # verdict that arrives after the client witnessed the resumed
    # epoch is stale evidence and must NOT demote the replica
    s1, s2 = _pair(model)
    try:
        cli = ServingClient(addrs=[s1.address, s2.address],
                            budget_ms=5000)
        assert cli._probe(s1.address) is True
        e0 = cli._addr_epoch[s1.address]
        s1.drain(timeout=5.0)
        # a CURRENT-epoch draining verdict is real demotion evidence
        assert cli._probe(s1.address) is False
        s1.resume()
        assert cli._probe(s1.address) is True
        assert cli._addr_epoch[s1.address] == e0 + 2
        # replay of the drain-era verdict, delivered late: the epoch
        # is below the newest witnessed -> ignored, replica stays
        conn = cli._conn_for(s1.address)
        conn.last_ping = {"draining": True, "epoch": e0 + 1}
        orig_ping = conn.ping
        conn.ping = lambda **kw: True   # deliver the stale dict only
        try:
            assert cli._probe(s1.address) is True
        finally:
            conn.ping = orig_ping
        assert cli.stats()["failovers"] == 0
    finally:
        s2.stop()
        s1.stop()
