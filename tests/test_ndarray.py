"""NDArray tests (modelled on tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxtpu as mx
import mxtpu.ndarray as nd


def test_creation():
    a = nd.array([[1, 2], [3, 4]])
    assert a.shape == (2, 2)
    assert a.dtype == np.float32
    assert a.context.device_type == "cpu"
    b = nd.zeros((3, 4))
    assert (b.asnumpy() == 0).all()
    c = nd.ones((2,), dtype="int32")
    assert c.dtype == np.int32
    d = nd.full((2, 2), 7.0)
    assert (d.asnumpy() == 7).all()
    e = nd.arange(0, 10, 2)
    assert list(e.asnumpy()) == [0, 2, 4, 6, 8]


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    assert np.allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    assert np.allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    assert np.allclose((a * 2).asnumpy(), [[2, 4], [6, 8]])
    assert np.allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    assert np.allclose((1 / a).asnumpy(), 1 / a.asnumpy())
    assert np.allclose((a ** 2).asnumpy(), a.asnumpy() ** 2)
    assert np.allclose((-a).asnumpy(), -a.asnumpy())
    a += b
    assert np.allclose(a.asnumpy(), [[11, 22], [33, 44]])


def test_comparison_returns_numeric():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    eq = (a == b).asnumpy()
    assert eq.dtype == np.float32
    assert list(eq) == [0, 1, 0]


def test_indexing():
    a = nd.array(np.arange(12).reshape(3, 4).astype("f"))
    assert a[1].shape == (4,)
    assert np.allclose(a[1:3].asnumpy(), np.arange(12).reshape(3, 4)[1:3])
    a[0] = 99.0
    assert (a.asnumpy()[0] == 99).all()
    a[1:3] = 0.0
    assert (a.asnumpy()[1:] == 0).all()


def test_reshape_and_methods():
    a = nd.array(np.arange(24).astype("f"))
    b = a.reshape(2, 3, 4)
    assert b.shape == (2, 3, 4)
    assert b.reshape((-1,)).shape == (24,)
    # mxnet special codes
    c = b.reshape(0, -1)
    assert c.shape == (2, 12)
    assert a.sum().asscalar() == pytest.approx(276.0)
    assert b.transpose(axes=(2, 0, 1)).shape == (4, 2, 3)
    assert b.flatten().shape == (2, 12)
    assert b.expand_dims(axis=0).shape == (1, 2, 3, 4)


def test_dot():
    a = nd.array(np.random.randn(3, 4).astype("f"))
    b = nd.array(np.random.randn(4, 5).astype("f"))
    c = nd.dot(a, b)
    assert np.allclose(c.asnumpy(), a.asnumpy() @ b.asnumpy(), atol=1e-5)


def test_copyto_context():
    a = nd.array([1.0, 2.0])
    b = a.as_in_context(mx.cpu(1))
    assert b.context == mx.cpu(1)
    assert np.allclose(a.asnumpy(), b.asnumpy())


def test_save_load(tmp_path):
    f = str(tmp_path / "arrs")
    d = {"w": nd.array([1.0, 2.0]), "b": nd.array([[3.0]])}
    nd.save(f, d)
    back = nd.load(f)
    assert set(back) == {"w", "b"}
    assert np.allclose(back["w"].asnumpy(), [1, 2])


def test_random_seeded():
    mx.random.seed(42)
    a = nd.random_uniform(shape=(5,)).asnumpy()
    mx.random.seed(42)
    b = nd.random_uniform(shape=(5,)).asnumpy()
    assert np.allclose(a, b)
    c = nd.random_normal(loc=1.0, scale=0.0, shape=(3,)).asnumpy()
    assert np.allclose(c, 1.0)


def test_wait_and_scalar():
    a = nd.array([3.5])
    a.wait_to_read()
    assert a.asscalar() == pytest.approx(3.5)
    nd.waitall()


def test_astype_and_T():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    assert a.astype("int32").dtype == np.int32
    assert a.T.shape == (2, 2)
    assert np.allclose(a.T.asnumpy(), a.asnumpy().T)
