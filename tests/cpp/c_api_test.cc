// Unit tests for the core C ABI (include/mxtpu/c_api.h), assert-style like
// recordio_test.cc. Reference counterpart: the reference exercises its
// c_api through every binding's test suite; here we drive it directly.
//
// Covers: NDArray create/copy/shape/reshape/save/load, imperative invoke
// (allocated and in-place out=), autograd record/backward, Symbol
// create/compose/infer-shape/tojson round-trip, Executor bind/fwd/bwd,
// KVStore push/pull with a C updater callback, and the NDArrayIter handle.

#include <cassert>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

#include "../../include/mxtpu/c_api.h"

#define CHECK_OK(expr)                                              \
  do {                                                              \
    if ((expr) != 0) {                                              \
      std::fprintf(stderr, "FAIL %s:%d: %s -> %s\n", __FILE__,      \
                   __LINE__, #expr, MXGetLastError());              \
      return 1;                                                     \
    }                                                               \
  } while (0)

#define CHECK(cond)                                                 \
  do {                                                              \
    if (!(cond)) {                                                  \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,  \
                   #cond);                                          \
      return 1;                                                     \
    }                                                               \
  } while (0)

namespace {

int invoke1(const char *op, std::vector<NDArrayHandle> ins,
            NDArrayHandle *out,
            std::vector<std::pair<std::string, std::string>> params = {}) {
  OpHandle oh;
  if (MXGetOpHandle(op, &oh) != 0) return -1;
  std::vector<const char *> keys, vals;
  for (auto &kv : params) {
    keys.push_back(kv.first.c_str());
    vals.push_back(kv.second.c_str());
  }
  int num_out = 0;
  NDArrayHandle *outs = nullptr;
  if (MXImperativeInvoke(oh, static_cast<int>(ins.size()), ins.data(),
                         &num_out, &outs,
                         static_cast<int>(keys.size()), keys.data(),
                         vals.data()) != 0) {
    return -1;
  }
  if (num_out < 1) return -1;
  *out = outs[0];
  return 0;
}

bool g_updater_called = false;

void sgd_updater(int key, NDArrayHandle recv_grad, NDArrayHandle local,
                 void *handle) {
  (void)key;
  (void)handle;
  g_updater_called = true;
  // local -= 0.5 * recv  via in-place sgd_update(out=local)
  OpHandle oh;
  if (MXGetOpHandle("sgd_update", &oh) != 0) return;
  NDArrayHandle ins[2] = {local, recv_grad};
  NDArrayHandle outs_buf[1] = {local};
  NDArrayHandle *outs = outs_buf;
  int num_out = 1;
  const char *keys[1] = {"lr"};
  const char *vals[1] = {"0.5"};
  MXImperativeInvoke(oh, 2, ins, &num_out, &outs, 1, keys, vals);
}

}  // namespace

int test_ndarray(const char *tmpdir) {
  mx_uint shape[2] = {2, 3};
  NDArrayHandle a;
  CHECK_OK(MXNDArrayCreate(shape, 2, 1, 0, 0, &a));
  float host[6] = {1, 2, 3, 4, 5, 6};
  CHECK_OK(MXNDArraySyncCopyFromCPU(a, host, 6));
  CHECK_OK(MXNDArrayWaitToRead(a));

  mx_uint ndim;
  const mx_uint *dims;
  CHECK_OK(MXNDArrayGetShape(a, &ndim, &dims));
  CHECK(ndim == 2 && dims[0] == 2 && dims[1] == 3);
  int dtype;
  CHECK_OK(MXNDArrayGetDType(a, &dtype));
  CHECK(dtype == 0);
  int dev_type, dev_id;
  CHECK_OK(MXNDArrayGetContext(a, &dev_type, &dev_id));
  CHECK(dev_type >= 1);

  float back[6] = {0};
  CHECK_OK(MXNDArraySyncCopyToCPU(a, back, 6));
  for (int i = 0; i < 6; ++i) CHECK(back[i] == host[i]);

  int new_dims[2] = {3, 2};
  NDArrayHandle b;
  CHECK_OK(MXNDArrayReshape(a, 2, new_dims, &b));
  CHECK_OK(MXNDArrayGetShape(b, &ndim, &dims));
  CHECK(ndim == 2 && dims[0] == 3 && dims[1] == 2);

  NDArrayHandle row;
  CHECK_OK(MXNDArrayAt(a, 1, &row));
  float rowv[3];
  CHECK_OK(MXNDArraySyncCopyToCPU(row, rowv, 3));
  CHECK(rowv[0] == 4 && rowv[2] == 6);

  // save / load round-trip
  std::string fname = std::string(tmpdir) + "/c_api_test.nd";
  const char *keys[1] = {"w"};
  NDArrayHandle save_args[1] = {a};
  CHECK_OK(MXNDArraySave(fname.c_str(), 1, save_args, keys));
  mx_uint n_loaded, n_names;
  NDArrayHandle *loaded;
  const char **names;
  CHECK_OK(MXNDArrayLoad(fname.c_str(), &n_loaded, &loaded, &n_names,
                         &names));
  CHECK(n_loaded == 1 && n_names == 1);
  CHECK(std::strcmp(names[0], "w") == 0);
  float lv[6];
  CHECK_OK(MXNDArraySyncCopyToCPU(loaded[0], lv, 6));
  CHECK(lv[5] == 6);

  CHECK_OK(MXNDArrayFree(row));
  CHECK_OK(MXNDArrayFree(b));
  CHECK_OK(MXNDArrayFree(a));
  std::printf("  ndarray OK\n");
  return 0;
}

int test_imperative_and_autograd() {
  mx_uint shape[1] = {4};
  NDArrayHandle x;
  CHECK_OK(MXNDArrayCreate(shape, 1, 1, 0, 0, &x));
  float hv[4] = {1, 2, 3, 4};
  CHECK_OK(MXNDArraySyncCopyFromCPU(x, hv, 4));

  // allocated-output invoke: y = x * x  (square)
  NDArrayHandle y;
  CHECK_OK(invoke1("square", {x}, &y));
  float yv[4];
  CHECK_OK(MXNDArraySyncCopyToCPU(y, yv, 4));
  CHECK(yv[3] == 16);

  // autograd: grad of sum(x*x) is 2x
  NDArrayHandle grad_buf;
  CHECK_OK(MXNDArrayCreate(shape, 1, 1, 0, 0, &grad_buf));
  NDArrayHandle vars[1] = {x};
  mx_uint reqs[1] = {1};
  NDArrayHandle grads[1] = {grad_buf};
  CHECK_OK(MXAutogradMarkVariables(1, vars, reqs, grads));
  int prev;
  CHECK_OK(MXAutogradSetIsRecording(1, &prev));
  NDArrayHandle sq, total;
  CHECK_OK(invoke1("square", {x}, &sq));
  CHECK_OK(invoke1("sum", {sq}, &total));
  CHECK_OK(MXAutogradSetIsRecording(0, &prev));
  NDArrayHandle heads[1] = {total};
  CHECK_OK(MXAutogradBackward(1, heads, nullptr, 0));
  NDArrayHandle gx;
  CHECK_OK(MXNDArrayGetGrad(x, &gx));
  float gv[4];
  CHECK_OK(MXNDArraySyncCopyToCPU(gx, gv, 4));
  for (int i = 0; i < 4; ++i) CHECK(std::fabs(gv[i] - 2 * hv[i]) < 1e-5);

  CHECK_OK(MXNDArrayFree(gx));
  CHECK_OK(MXNDArrayFree(grad_buf));
  CHECK_OK(MXNDArrayFree(x));
  std::printf("  imperative+autograd OK\n");
  return 0;
}

int test_symbol_and_executor() {
  mx_uint n_ops;
  const char **op_names_arr;
  CHECK_OK(MXListAllOpNames(&n_ops, &op_names_arr));
  CHECK(n_ops > 200);

  // net = FullyConnected(data, weight, bias, num_hidden=2)
  SymbolHandle data, weight, bias;
  CHECK_OK(MXSymbolCreateVariable("data", &data));
  CHECK_OK(MXSymbolCreateVariable("fc_weight", &weight));
  CHECK_OK(MXSymbolCreateVariable("fc_bias", &bias));
  OpHandle fc_op;
  CHECK_OK(MXGetOpHandle("FullyConnected", &fc_op));
  SymbolHandle fc;
  const char *pk[1] = {"num_hidden"};
  const char *pv[1] = {"2"};
  CHECK_OK(MXSymbolCreateAtomicSymbol(fc_op, 1, pk, pv, &fc));
  const char *arg_keys[3] = {"data", "weight", "bias"};
  SymbolHandle args[3] = {data, weight, bias};
  CHECK_OK(MXSymbolCompose(fc, "fc1", 3, arg_keys, args));

  mx_uint n_args;
  const char **arg_names;
  CHECK_OK(MXSymbolListArguments(fc, &n_args, &arg_names));
  CHECK(n_args == 3);
  CHECK(std::strcmp(arg_names[0], "data") == 0);

  // infer shapes from data shape
  const char *in_keys[1] = {"data"};
  mx_uint ind_ptr[2] = {0, 2};
  mx_uint shape_data[2] = {5, 3};
  mx_uint in_size, out_size, aux_size;
  const mx_uint *in_ndim, *out_ndim, *aux_ndim;
  const mx_uint **in_shapes, **out_shapes, **aux_shapes;
  int complete;
  CHECK_OK(MXSymbolInferShape(fc, 1, in_keys, ind_ptr, shape_data, &in_size,
                              &in_ndim, &in_shapes, &out_size, &out_ndim,
                              &out_shapes, &aux_size, &aux_ndim, &aux_shapes,
                              &complete));
  CHECK(complete == 1);
  CHECK(in_size == 3);
  CHECK(in_ndim[1] == 2 && in_shapes[1][0] == 2 && in_shapes[1][1] == 3);
  CHECK(out_size == 1 && out_shapes[0][0] == 5 && out_shapes[0][1] == 2);

  // json round-trip
  const char *json;
  CHECK_OK(MXSymbolSaveToJSON(fc, &json));
  SymbolHandle fc2;
  CHECK_OK(MXSymbolCreateFromJSON(json, &fc2));
  mx_uint n_args2;
  const char **arg_names2;
  CHECK_OK(MXSymbolListArguments(fc2, &n_args2, &arg_names2));
  CHECK(n_args2 == 3);

  // bind + forward + backward
  mx_uint xs[2] = {5, 3}, ws[2] = {2, 3}, bs[1] = {2};
  NDArrayHandle in_args[3], arg_grads[3];
  CHECK_OK(MXNDArrayCreate(xs, 2, 1, 0, 0, &in_args[0]));
  CHECK_OK(MXNDArrayCreate(ws, 2, 1, 0, 0, &in_args[1]));
  CHECK_OK(MXNDArrayCreate(bs, 1, 1, 0, 0, &in_args[2]));
  CHECK_OK(MXNDArrayCreate(xs, 2, 1, 0, 0, &arg_grads[0]));
  CHECK_OK(MXNDArrayCreate(ws, 2, 1, 0, 0, &arg_grads[1]));
  CHECK_OK(MXNDArrayCreate(bs, 1, 1, 0, 0, &arg_grads[2]));
  std::vector<float> xv(15), wv(6, 0.5f), bv(2, 0.1f);
  for (int i = 0; i < 15; ++i) xv[i] = 0.1f * i;
  CHECK_OK(MXNDArraySyncCopyFromCPU(in_args[0], xv.data(), 15));
  CHECK_OK(MXNDArraySyncCopyFromCPU(in_args[1], wv.data(), 6));
  CHECK_OK(MXNDArraySyncCopyFromCPU(in_args[2], bv.data(), 2));
  mx_uint reqs[3] = {1, 1, 1};
  ExecutorHandle ex;
  CHECK_OK(MXExecutorBind(fc, 1, 0, 3, in_args, arg_grads, reqs, 0, nullptr,
                          &ex));
  CHECK_OK(MXExecutorForward(ex, 1));
  mx_uint n_out;
  NDArrayHandle *outs;
  CHECK_OK(MXExecutorOutputs(ex, &n_out, &outs));
  CHECK(n_out == 1);
  float ov[10];
  CHECK_OK(MXNDArraySyncCopyToCPU(outs[0], ov, 10));
  // row 0: x = [0, .1, .2], out = .5*(0+.1+.2) + .1 = .25
  CHECK(std::fabs(ov[0] - 0.25f) < 1e-5);

  NDArrayHandle ograd;
  mx_uint os_[2] = {5, 2};
  CHECK_OK(MXNDArrayCreate(os_, 2, 1, 0, 0, &ograd));
  std::vector<float> ones(10, 1.0f);
  CHECK_OK(MXNDArraySyncCopyFromCPU(ograd, ones.data(), 10));
  NDArrayHandle ogs[1] = {ograd};
  CHECK_OK(MXExecutorBackward(ex, 1, ogs));
  float bgrad[2];
  CHECK_OK(MXNDArraySyncCopyToCPU(arg_grads[2], bgrad, 2));
  CHECK(std::fabs(bgrad[0] - 5.0f) < 1e-5);  // sum over batch of ones

  CHECK_OK(MXExecutorFree(ex));
  for (int i = 0; i < 3; ++i) {
    CHECK_OK(MXNDArrayFree(in_args[i]));
    CHECK_OK(MXNDArrayFree(arg_grads[i]));
  }
  CHECK_OK(MXNDArrayFree(ograd));
  CHECK_OK(MXSymbolFree(fc));
  CHECK_OK(MXSymbolFree(fc2));
  CHECK_OK(MXSymbolFree(data));
  CHECK_OK(MXSymbolFree(weight));
  CHECK_OK(MXSymbolFree(bias));
  std::printf("  symbol+executor OK\n");
  return 0;
}

int test_kvstore() {
  KVStoreHandle kv;
  CHECK_OK(MXKVStoreCreate("local", &kv));
  int rank, size;
  CHECK_OK(MXKVStoreGetRank(kv, &rank));
  CHECK_OK(MXKVStoreGetGroupSize(kv, &size));
  CHECK(rank == 0 && size == 1);

  mx_uint shape[1] = {3};
  NDArrayHandle w, g;
  CHECK_OK(MXNDArrayCreate(shape, 1, 1, 0, 0, &w));
  CHECK_OK(MXNDArrayCreate(shape, 1, 1, 0, 0, &g));
  float wv[3] = {1, 1, 1}, gv[3] = {2, 2, 2};
  CHECK_OK(MXNDArraySyncCopyFromCPU(w, wv, 3));
  CHECK_OK(MXNDArraySyncCopyFromCPU(g, gv, 3));

  int keys[1] = {7};
  NDArrayHandle init_vals[1] = {w};
  CHECK_OK(MXKVStoreInit(kv, 1, keys, init_vals));
  CHECK_OK(MXKVStoreSetUpdater(kv, sgd_updater, nullptr));
  NDArrayHandle push_vals[1] = {g};
  CHECK_OK(MXKVStorePush(kv, 1, keys, push_vals, 0));
  NDArrayHandle out;
  CHECK_OK(MXNDArrayCreate(shape, 1, 1, 0, 0, &out));
  NDArrayHandle pull_vals[1] = {out};
  CHECK_OK(MXKVStorePull(kv, 1, keys, pull_vals, 0));
  float pv[3];
  CHECK_OK(MXNDArraySyncCopyToCPU(out, pv, 3));
  CHECK(g_updater_called);
  // w <- w - 0.5 * g = 1 - 1 = 0
  for (int i = 0; i < 3; ++i) CHECK(std::fabs(pv[i]) < 1e-5);

  CHECK_OK(MXNDArrayFree(w));
  CHECK_OK(MXNDArrayFree(g));
  CHECK_OK(MXNDArrayFree(out));
  CHECK_OK(MXKVStoreFree(kv));
  std::printf("  kvstore OK\n");
  return 0;
}


// ---------------------------------------------------- round-3 ABI breadth

static int g_monitor_calls = 0;
void monitor_cb(const char *name, NDArrayHandle value, void *closure) {
  (void)name; (void)value; (void)closure;
  ++g_monitor_calls;
}

int double_op_dispatch(int phase, int num_arrays, NDArrayHandle *arrays,
                       void *state) {
  (void)state;
  if (phase != 0) return 0;  // identity backward not exercised here
  // forward: arrays = [input, output]; output = 2 * input
  int half = num_arrays / 2;
  for (int k = 0; k < half; ++k) {
    mx_uint ndim = 0;
    const mx_uint *dims = nullptr;
    if (MXNDArrayGetShape(arrays[k], &ndim, &dims) != 0) return -1;
    size_t n = 1;
    for (mx_uint i = 0; i < ndim; ++i) n *= dims[i];
    std::vector<float> buf(n);
    if (MXNDArraySyncCopyToCPU(arrays[k], buf.data(), n) != 0) return -1;
    for (auto &v : buf) v *= 2.0f;
    if (MXNDArraySyncCopyFromCPU(arrays[half + k], buf.data(), n) != 0)
      return -1;
  }
  return 0;
}

int test_round3_breadth(const char *tmpdir) {
  // engine + profiler state surface
  int prev = 0;
  CHECK_OK(MXEngineSetBulkSize(10, &prev));
  CHECK_OK(MXSetNumOMPThreads(2));
  const char *pk[] = {"filename"};
  std::string profile_path = std::string(tmpdir) + "/c_profile.json";
  const char *pv[] = {profile_path.c_str()};
  CHECK_OK(MXSetProfilerConfig(1, pk, pv));
  CHECK_OK(MXSetProfilerState(1));
  ProfileHandle domain = nullptr, task = nullptr, counter = nullptr;
  CHECK_OK(MXProfileCreateDomain("cdomain", &domain));
  CHECK_OK(MXProfileCreateTask(domain, "ctask", &task));
  CHECK_OK(MXProfileDurationStart(task));
  CHECK_OK(MXProfileDurationStop(task));
  CHECK_OK(MXProfileCreateCounter(domain, "ccount", &counter));
  CHECK_OK(MXProfileSetCounter(counter, 41));
  CHECK_OK(MXProfileAdjustCounter(counter, 1));
  CHECK_OK(MXProfileSetMarker(domain, "cmark", "process"));
  CHECK_OK(MXSetProfilerState(0));
  CHECK_OK(MXDumpProfile(1));
  CHECK_OK(MXProfileDestroyHandle(task));
  CHECK_OK(MXProfileDestroyHandle(counter));
  CHECK_OK(MXProfileDestroyHandle(domain));
  std::printf("  profiler OK\n");

  // autograd state queries
  bool rec = true, train = true;
  CHECK_OK(MXAutogradIsRecording(&rec));
  CHECK_OK(MXAutogradIsTraining(&train));
  CHECK(!rec);

  // NDArray breadth: storage type, detach, raw-bytes round trip
  mx_uint shape[2] = {2, 2};
  NDArrayHandle a = nullptr;
  CHECK_OK(MXNDArrayCreate(shape, 2, 1, 0, 0, &a));
  float host[4] = {1, 2, 3, 4};
  CHECK_OK(MXNDArraySyncCopyFromCPU(a, host, 4));
  int stype = -1;
  CHECK_OK(MXNDArrayGetStorageType(a, &stype));
  CHECK(stype == 0);  // kDefaultStorage, reference code
  NDArrayHandle det = nullptr;
  CHECK_OK(MXNDArrayDetach(a, &det));
  CHECK_OK(MXNDArrayWaitToWrite(a));
  size_t nraw = 0;
  const char *raw = nullptr;
  CHECK_OK(MXNDArraySaveRawBytes(a, &nraw, &raw));
  NDArrayHandle reborn = nullptr;
  CHECK_OK(MXNDArrayLoadFromRawBytes(raw, nraw, &reborn));
  float back[4] = {0, 0, 0, 0};
  CHECK_OK(MXNDArraySyncCopyToCPU(reborn, back, 4));
  for (int i = 0; i < 4; ++i) CHECK(back[i] == host[i]);
  NDArrayHandle b = nullptr;
  CHECK_OK(MXNDArrayCreate(shape, 2, 1, 0, 0, &b));
  CHECK_OK(MXNDArraySyncCopyFromNDArray(b, a, -1));
  CHECK_OK(MXNDArraySyncCheckFormat(a, true));
  std::printf("  ndarray breadth OK\n");

  // Symbol breadth: attrs, name, counts, type inference, debug print
  SymbolHandle x = nullptr, fc = nullptr;
  CHECK_OK(MXSymbolCreateVariable("data", &x));
  OpHandle fc_op = nullptr;
  CHECK_OK(MXGetOpHandle("FullyConnected", &fc_op));
  const char *keys[] = {"num_hidden"};
  const char *vals[] = {"4"};
  CHECK_OK(MXSymbolCreateAtomicSymbol(fc_op, 1, keys, vals, &fc));
  SymbolHandle args[] = {x};
  CHECK_OK(MXSymbolCompose(fc, "fc1", 1, nullptr, args));
  const char *nm = nullptr;
  int ok = 0;
  CHECK_OK(MXSymbolGetName(fc, &nm, &ok));
  CHECK(ok == 1 && std::string(nm) == "fc1");
  CHECK_OK(MXSymbolSetAttr(fc, "lr_mult", "2.0"));
  const char *attr = nullptr;
  CHECK_OK(MXSymbolGetAttr(fc, "lr_mult", &attr, &ok));
  CHECK(ok == 1 && std::string(attr) == "2.0");
  mx_uint n_out = 0;
  CHECK_OK(MXSymbolGetNumOutputs(fc, &n_out));
  CHECK(n_out == 1);
  const char *dbg = nullptr;
  CHECK_OK(MXSymbolPrint(fc, &dbg));
  CHECK(dbg && dbg[0] != 0);
  const char *info_name = nullptr, *info_desc = nullptr;
  mx_uint info_nargs = 0;
  const char **an = nullptr, **at = nullptr, **ad = nullptr;
  const char *kv = nullptr;
  CHECK_OK(MXSymbolGetAtomicSymbolInfo(fc_op, &info_name, &info_desc,
                                       &info_nargs, &an, &at, &ad, &kv));
  CHECK(std::string(info_name) == "FullyConnected");

  int tkeys_data[] = {0};
  const char *tkeys[] = {"data"};
  mx_uint in_ts = 0, out_ts = 0, aux_ts = 0;
  const int *in_td = nullptr, *out_td = nullptr, *aux_td = nullptr;
  int complete = 0;
  CHECK_OK(MXSymbolInferType(fc, 1, tkeys, tkeys_data, &in_ts, &in_td,
                             &out_ts, &out_td, &aux_ts, &aux_td,
                             &complete));
  CHECK(complete == 1 && out_ts == 1 && out_td[0] == 0);
  std::printf("  symbol breadth OK\n");

  // SimpleBind + monitor callback + BackwardEx + Print
  const char *sb_shape_names[] = {"data"};
  mx_uint sb_shape_data[] = {3, 5};
  mx_uint sb_shape_idx[] = {0, 2};
  mx_uint n_in = 0, n_aux = 0;
  NDArrayHandle *in_args = nullptr, *arg_grads = nullptr,
                *aux_states = nullptr;
  ExecutorHandle exec = nullptr;
  int shared_len = -1;
  CHECK_OK(MXExecutorSimpleBind(
      fc, 1, 0, 0, nullptr, nullptr, nullptr, 0, nullptr, nullptr, 1,
      sb_shape_names, sb_shape_data, sb_shape_idx, 0, nullptr, nullptr, 0,
      nullptr, nullptr, 0, nullptr, &shared_len, nullptr, nullptr, nullptr,
      nullptr, &n_in, &in_args, &arg_grads, &n_aux, &aux_states, nullptr,
      &exec));
  CHECK(n_in == 3);  // data, weight, bias
  std::vector<float> ones(15, 1.0f);
  CHECK_OK(MXNDArraySyncCopyFromCPU(in_args[0], ones.data(), 15));
  std::vector<float> w(4 * 5, 0.1f);
  CHECK_OK(MXNDArraySyncCopyFromCPU(in_args[1], w.data(), 20));
  CHECK_OK(MXExecutorSetMonitorCallback(exec, monitor_cb, nullptr));
  CHECK_OK(MXExecutorForward(exec, 1));
  mx_uint n_eo = 0;
  NDArrayHandle *eouts = nullptr;
  CHECK_OK(MXExecutorOutputs(exec, &n_eo, &eouts));
  CHECK(n_eo == 1 && g_monitor_calls > 0);
  CHECK_OK(MXExecutorBackwardEx(exec, 0, nullptr, 1));
  const char *exec_dbg = nullptr;
  CHECK_OK(MXExecutorPrint(exec, &exec_dbg));
  CHECK(exec_dbg && exec_dbg[0] != 0);
  CHECK_OK(MXExecutorFree(exec));
  std::printf("  simple_bind OK\n");

  // CachedOp
  CachedOpHandle cop = nullptr;
  CHECK_OK(MXCreateCachedOp(fc, &cop));
  NDArrayHandle cin[3];
  mx_uint dshape[2] = {3, 5};
  CHECK_OK(MXNDArrayCreate(dshape, 2, 1, 0, 0, &cin[0]));
  CHECK_OK(MXNDArraySyncCopyFromCPU(cin[0], ones.data(), 15));
  mx_uint wshape[2] = {4, 5};
  CHECK_OK(MXNDArrayCreate(wshape, 2, 1, 0, 0, &cin[1]));
  CHECK_OK(MXNDArraySyncCopyFromCPU(cin[1], w.data(), 20));
  mx_uint bshape[1] = {4};
  CHECK_OK(MXNDArrayCreate(bshape, 1, 1, 0, 0, &cin[2]));
  int n_co = 0;
  NDArrayHandle *couts = nullptr;
  CHECK_OK(MXInvokeCachedOp(cop, 3, cin, &n_co, &couts));
  CHECK(n_co == 1);
  float cres[12];
  CHECK_OK(MXNDArraySyncCopyToCPU(couts[0], cres, 12));
  CHECK(std::fabs(cres[0] - 0.5f) < 1e-5);  // 5 * 1 * 0.1
  CHECK_OK(MXFreeCachedOp(cop));
  std::printf("  cached op OK\n");

  // KVStore breadth: type, barrier, dead nodes, string keys, compression
  KVStoreHandle kv2 = nullptr;
  CHECK_OK(MXKVStoreCreate("local", &kv2));
  const char *kv_type = nullptr;
  CHECK_OK(MXKVStoreGetType(kv2, &kv_type));
  CHECK(std::string(kv_type) == "local");
  CHECK_OK(MXKVStoreBarrier(kv2));
  int dead = -1;
  CHECK_OK(MXKVStoreGetNumDeadNode(kv2, 0, &dead, 1));
  CHECK(dead == 0);
  int is_worker = 0;
  CHECK_OK(MXKVStoreIsWorkerNode(&is_worker));
  CHECK(is_worker == 1);
  const char *skeys[] = {"weight0"};
  NDArrayHandle kv_val = nullptr;
  mx_uint kshape[1] = {3};
  CHECK_OK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &kv_val));
  float kv_host[3] = {1, 1, 1};
  CHECK_OK(MXNDArraySyncCopyFromCPU(kv_val, kv_host, 3));
  NDArrayHandle kv_vals[] = {kv_val};
  CHECK_OK(MXKVStoreInitEx(kv2, 1, skeys, kv_vals));
  CHECK_OK(MXKVStorePushEx(kv2, 1, skeys, kv_vals, 0));
  NDArrayHandle kv_out = nullptr;
  CHECK_OK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &kv_out));
  NDArrayHandle kv_outs[] = {kv_out};
  CHECK_OK(MXKVStorePullEx(kv2, 1, skeys, kv_outs, 0));
  float kv_res[3];
  CHECK_OK(MXNDArraySyncCopyToCPU(kv_out, kv_res, 3));
  CHECK(std::fabs(kv_res[0] - 1.0f) < 1e-6);
  const char *gck[] = {"type", "threshold"};
  const char *gcv[] = {"2bit", "0.5"};
  CHECK_OK(MXKVStoreSetGradientCompression(kv2, 2, gck, gcv));
  CHECK_OK(MXKVStoreFree(kv2));
  std::printf("  kvstore breadth OK\n");

  // RecordIO round trip
  std::string rec_path = std::string(tmpdir) + "/c_records.rec";
  RecordIOHandle writer = nullptr;
  CHECK_OK(MXRecordIOWriterCreate(rec_path.c_str(), &writer));
  const char payload[] = "hello-from-c";
  CHECK_OK(MXRecordIOWriterWriteRecord(writer, payload, sizeof(payload)));
  CHECK_OK(MXRecordIOWriterFree(writer));
  RecordIOHandle reader = nullptr;
  CHECK_OK(MXRecordIOReaderCreate(rec_path.c_str(), &reader));
  const char *rbuf = nullptr;
  size_t rsize = 0;
  CHECK_OK(MXRecordIOReaderReadRecord(reader, &rbuf, &rsize));
  CHECK(rsize == sizeof(payload) && std::memcmp(rbuf, payload, rsize) == 0);
  CHECK_OK(MXRecordIOReaderReadRecord(reader, &rbuf, &rsize));
  CHECK(rsize == 0);  // end of file
  CHECK_OK(MXRecordIOReaderSeek(reader, 0));  // rewind by byte offset
  CHECK_OK(MXRecordIOReaderReadRecord(reader, &rbuf, &rsize));
  CHECK(rsize == sizeof(payload) && std::memcmp(rbuf, payload, rsize) == 0);
  CHECK_OK(MXRecordIOReaderFree(reader));
  std::printf("  recordio OK\n");

  // custom op registered from C, invoked imperatively
  CHECK_OK(MXCustomOpRegister("c_double", 1, 1, double_op_dispatch,
                              nullptr));
  OpHandle custom_op = nullptr;
  CHECK_OK(MXGetOpHandle("Custom", &custom_op));
  NDArrayHandle cop_in = nullptr;
  CHECK_OK(MXNDArrayCreate(kshape, 1, 1, 0, 0, &cop_in));
  float three[3] = {3, 3, 3};
  CHECK_OK(MXNDArraySyncCopyFromCPU(cop_in, three, 3));
  NDArrayHandle cop_inputs[] = {cop_in};
  int n_cop_out = 0;
  NDArrayHandle *cop_outs = nullptr;
  const char *cop_keys[] = {"op_type"};
  const char *cop_vals[] = {"c_double"};
  CHECK_OK(MXImperativeInvoke(custom_op, 1, cop_inputs, &n_cop_out,
                              &cop_outs, 1, cop_keys, cop_vals));
  CHECK(n_cop_out == 1);
  float doubled[3];
  CHECK_OK(MXNDArraySyncCopyToCPU(cop_outs[0], doubled, 3));
  for (int i = 0; i < 3; ++i) CHECK(std::fabs(doubled[i] - 6.0f) < 1e-5);
  std::printf("  c custom op OK\n");
  return 0;
}

int main(int argc, char **argv) {
  const char *tmpdir = argc > 1 ? argv[1] : "/tmp";
  int version;
  if (MXGetVersion(&version) != 0) {
    std::fprintf(stderr, "MXGetVersion failed: %s\n", MXGetLastError());
    return 1;
  }
  std::printf("mxtpu c_api version %d\n", version);
  if (MXRandomSeed(0) != 0) return 1;
  if (test_ndarray(tmpdir)) return 1;
  if (test_imperative_and_autograd()) return 1;
  if (test_symbol_and_executor()) return 1;
  if (test_kvstore()) return 1;
  if (test_round3_breadth(tmpdir)) return 1;
  if (MXNotifyShutdown() != 0) return 1;
  std::printf("c_api_test OK\n");
  return 0;
}
