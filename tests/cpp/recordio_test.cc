// Native RecordIO C++ unit test (the §4 C++ test tier: reference
// tests/cpp/{engine,storage}_test.cc with gtest; assert-based here to
// avoid a vendored gtest). Compiled and run by tests/test_native_cpp.py.
//
// Covers: write/read roundtrip, reset, random access by offset, prefetcher
// stream equivalence with multiple worker threads, EOF behavior.
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

extern "C" {
void* rio_open_reader(const char* path);
int64_t rio_read_next(void* handle, const char** data);
int64_t rio_read_at(void* handle, uint64_t offset, const char** data);
void rio_reader_reset(void* handle);
void rio_close_reader(void* handle);
void* rio_open_writer(const char* path);
int64_t rio_write(void* handle, const char* data, uint64_t len);
void rio_close_writer(void* handle);
void* pf_create(const char* path, uint64_t capacity);
int64_t pf_next(void* handle, const char** data);
void pf_destroy(void* handle);
}

int main(int argc, char** argv) {
  assert(argc > 1);
  std::string path = std::string(argv[1]) + "/t.rec";

  // write records of varying, non-aligned sizes
  std::vector<std::string> recs;
  for (int i = 0; i < 257; ++i) {
    std::string s;
    for (int j = 0; j < (i * 7) % 61 + 1; ++j)
      s.push_back(static_cast<char>('a' + (i + j) % 26));
    recs.push_back(s);
  }
  void* w = rio_open_writer(path.c_str());
  assert(w != nullptr);
  std::vector<int64_t> offsets;
  for (const auto& s : recs) {
    int64_t off = rio_write(w, s.data(), s.size());
    assert(off >= 0);
    offsets.push_back(off);
  }
  rio_close_writer(w);

  // sequential read + EOF
  void* r = rio_open_reader(path.c_str());
  assert(r != nullptr);
  const char* data = nullptr;
  for (const auto& s : recs) {
    int64_t n = rio_read_next(r, &data);
    assert(n == static_cast<int64_t>(s.size()));
    assert(std::memcmp(data, s.data(), s.size()) == 0);
  }
  assert(rio_read_next(r, &data) == -1);  // EOF

  // reset re-reads from the start
  rio_reader_reset(r);
  assert(rio_read_next(r, &data) == static_cast<int64_t>(recs[0].size()));

  // random access via recorded offsets (the .idx file contract)
  for (int i = 256; i >= 0; i -= 17) {
    int64_t n = rio_read_at(r, static_cast<uint64_t>(offsets[i]), &data);
    assert(n == static_cast<int64_t>(recs[i].size()));
    assert(std::memcmp(data, recs[i].data(), recs[i].size()) == 0);
  }
  rio_close_reader(r);

  // prefetcher yields the same stream (ordering preserved)
  void* p = pf_create(path.c_str(), 8);
  assert(p != nullptr);
  size_t count = 0;
  while (true) {
    int64_t n = pf_next(p, &data);
    if (n < 0) break;
    assert(n == static_cast<int64_t>(recs[count].size()));
    assert(std::memcmp(data, recs[count].data(), recs[count].size()) == 0);
    ++count;
  }
  assert(count == recs.size());
  pf_destroy(p);

  std::printf("recordio_test OK (%zu records)\n", recs.size());
  return 0;
}
