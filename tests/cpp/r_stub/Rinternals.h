/* Minimal R-API stub for the compile-only CI gate of R-package/src.
 *
 * This image has no R toolchain; this header carries just enough of R's
 * C API surface (types + declarations, no behavior) for gcc to fully
 * type-check mxtpu_r.c. A real build still uses `R CMD SHLIB` against
 * the actual headers — the gate catches signature drift against
 * c_api.h, undeclared identifiers, and syntax errors on every CI run.
 */
#ifndef MXTPU_R_STUB_RINTERNALS_H_
#define MXTPU_R_STUB_RINTERNALS_H_

#include <stddef.h>

typedef struct SEXPREC *SEXP;
typedef ptrdiff_t R_xlen_t;

#define NILSXP 0
#define INTSXP 13
#define REALSXP 14
#define STRSXP 16
#define VECSXP 19
#define RAWSXP 24

extern SEXP R_NilValue;

SEXP Rf_allocVector(unsigned int, R_xlen_t);
SEXP Rf_mkChar(const char *);
SEXP Rf_ScalarInteger(int);
SEXP Rf_ScalarReal(double);
int Rf_asInteger(SEXP);
double Rf_asReal(SEXP);
R_xlen_t Rf_xlength(SEXP);
int Rf_length(SEXP);
void Rf_error(const char *, ...);
SEXP Rf_protect(SEXP);
void Rf_unprotect(int);
#define PROTECT(x) Rf_protect(x)
#define UNPROTECT(n) Rf_unprotect(n)

double *REAL(SEXP);
int *INTEGER(SEXP);
unsigned char *RAW(SEXP);
SEXP STRING_ELT(SEXP, R_xlen_t);
void SET_STRING_ELT(SEXP, R_xlen_t, SEXP);
SEXP VECTOR_ELT(SEXP, R_xlen_t);
void SET_VECTOR_ELT(SEXP, R_xlen_t, SEXP);
const char *CHAR(SEXP);

SEXP R_MakeExternalPtr(void *, SEXP, SEXP);
void *R_ExternalPtrAddr(SEXP);
void R_ClearExternalPtr(SEXP);
typedef void (*R_CFinalizer_t)(SEXP);
void R_RegisterCFinalizerEx(SEXP, R_CFinalizer_t, int);
#define TRUE 1
#define FALSE 0
void *R_alloc(size_t, int);

#endif
