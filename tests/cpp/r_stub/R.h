#include "Rinternals.h"
