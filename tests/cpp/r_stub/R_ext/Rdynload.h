#ifndef MXTPU_R_STUB_RDYNLOAD_H_
#define MXTPU_R_STUB_RDYNLOAD_H_
typedef void *(*DL_FUNC)(void);
typedef struct { const char *name; DL_FUNC fun; int numArgs; } \
    R_CallMethodDef;
typedef struct _DllInfo DllInfo;
int R_registerRoutines(DllInfo *, const void *, const R_CallMethodDef *,
                       const void *, const void *);
int R_useDynamicSymbols(DllInfo *, int);
#endif
