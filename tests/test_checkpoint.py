"""Async checkpoint manager (mxtpu/checkpoint.py): orbax backend and the
thread fallback, params + trainer state + metadata, retention, restart."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, gluon
from mxtpu.gluon import nn
from mxtpu.checkpoint import CheckpointManager


def _net_and_trainer(seed=0):
    # fresh process semantics: reset the auto-naming counter so a restart
    # rebuilds the same parameter names the checkpoint was saved under
    import mxtpu.gluon.block as _blk
    _blk._NAME_COUNTERS.clear()
    mx.random.seed(seed)
    net = nn.Dense(3, in_units=4)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = nd.array(np.random.RandomState(seed).rand(2, 4).astype(np.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(2)
    return net, trainer, x


@pytest.mark.parametrize("use_orbax", [True, False])
def test_save_restore_roundtrip(tmp_path, use_orbax):
    if use_orbax:
        pytest.importorskip("orbax.checkpoint")
    net, trainer, x = _net_and_trainer()
    before = net(x).asnumpy()
    ckpt = CheckpointManager(str(tmp_path / ("o" if use_orbax else "f")),
                             use_orbax=use_orbax)
    ckpt.save(7, net.collect_params(), trainer=trainer,
              metadata={"epoch": 7, "note": "hi"})
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 7

    # fresh model restored in place
    net2, trainer2, _ = _net_and_trainer(seed=5)
    tree = ckpt.restore(None, net2.collect_params(), trainer=trainer2)
    np.testing.assert_allclose(net2(x).asnumpy(), before, rtol=1e-6)
    assert tree["metadata"]["epoch"] == 7
    ckpt.close()


@pytest.mark.parametrize("use_orbax", [True, False])
def test_retention_and_latest(tmp_path, use_orbax):
    if use_orbax:
        pytest.importorskip("orbax.checkpoint")
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "r"), max_to_keep=2,
                             async_save=False, use_orbax=use_orbax)
    for step in (1, 2, 3, 4):
        ckpt.save(step, net.collect_params())
    ckpt.wait_until_finished()
    steps = ckpt.all_steps()
    assert steps[-1] == 4 and len(steps) <= 2
    ckpt.close()


def test_restore_empty_returns_none(tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "e"), use_orbax=False)
    assert ckpt.restore() is None
    assert ckpt.latest_step() is None


def test_crash_safe_fallback(tmp_path):
    # a stale .tmp dir from a crashed save must not shadow real steps
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "c"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params())
    import os
    os.makedirs(str(tmp_path / "c" / "step_9.tmp"))
    assert ckpt.all_steps() == [1]
    ckpt.save(2, net.collect_params())   # overwrites cleanly
    assert ckpt.latest_step() == 2


def test_restore_missing_explicit_step(tmp_path):
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "m"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params())
    assert ckpt.restore(3) is None      # reaped/never-written step


def test_async_write_failure_surfaces(tmp_path):
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "good"), use_orbax=False)
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    ckpt.directory = str(blocker)       # writer's makedirs now fails
    ckpt.save(1, net.collect_params())
    with pytest.raises(RuntimeError):
        ckpt.wait_until_finished()
