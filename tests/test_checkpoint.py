"""Async checkpoint manager (mxtpu/checkpoint.py): orbax backend and the
thread fallback, params + trainer state + metadata, retention, restart."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd, gluon
from mxtpu.gluon import nn
from mxtpu.checkpoint import CheckpointManager


def _net_and_trainer(seed=0):
    # fresh process semantics: reset the auto-naming counter so a restart
    # rebuilds the same parameter names the checkpoint was saved under
    import mxtpu.gluon.block as _blk
    _blk._NAME_COUNTERS.clear()
    mx.random.seed(seed)
    net = nn.Dense(3, in_units=4)
    net.initialize(mx.init.Xavier())
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    x = nd.array(np.random.RandomState(seed).rand(2, 4).astype(np.float32))
    with mx.autograd.record():
        loss = (net(x) ** 2).sum()
    loss.backward()
    trainer.step(2)
    return net, trainer, x


@pytest.mark.parametrize("use_orbax", [True, False])
def test_save_restore_roundtrip(tmp_path, use_orbax):
    if use_orbax:
        pytest.importorskip("orbax.checkpoint")
    net, trainer, x = _net_and_trainer()
    before = net(x).asnumpy()
    ckpt = CheckpointManager(str(tmp_path / ("o" if use_orbax else "f")),
                             use_orbax=use_orbax)
    ckpt.save(7, net.collect_params(), trainer=trainer,
              metadata={"epoch": 7, "note": "hi"})
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 7

    # fresh model restored in place
    net2, trainer2, _ = _net_and_trainer(seed=5)
    tree = ckpt.restore(None, net2.collect_params(), trainer=trainer2)
    np.testing.assert_allclose(net2(x).asnumpy(), before, rtol=1e-6)
    assert tree["metadata"]["epoch"] == 7
    ckpt.close()


@pytest.mark.parametrize("use_orbax", [True, False])
def test_retention_and_latest(tmp_path, use_orbax):
    if use_orbax:
        pytest.importorskip("orbax.checkpoint")
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "r"), max_to_keep=2,
                             async_save=False, use_orbax=use_orbax)
    for step in (1, 2, 3, 4):
        ckpt.save(step, net.collect_params())
    ckpt.wait_until_finished()
    steps = ckpt.all_steps()
    assert steps[-1] == 4 and len(steps) <= 2
    ckpt.close()


def test_restore_empty_returns_none(tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "e"), use_orbax=False)
    assert ckpt.restore() is None
    assert ckpt.latest_step() is None


def test_crash_safe_fallback(tmp_path):
    # a stale .tmp dir from a crashed save must not shadow real steps
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "c"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params())
    import os
    os.makedirs(str(tmp_path / "c" / "step_9.tmp"))
    assert ckpt.all_steps() == [1]
    ckpt.save(2, net.collect_params())   # overwrites cleanly
    assert ckpt.latest_step() == 2


def test_restore_missing_explicit_step(tmp_path):
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "m"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params())
    assert ckpt.restore(3) is None      # reaped/never-written step


@pytest.mark.parametrize("crash_point", ["during_write", "before_publish"])
def test_crash_mid_save_never_corrupts_latest(tmp_path, monkeypatch,
                                              crash_point):
    """Kill the fallback writer thread mid-save — either while the
    arrays are being written or at the instant before the atomic
    publish — and prove the 'crash mid-save can never corrupt the
    latest checkpoint' claim: latest_step() still returns the previous
    intact step, restore() loads it bit-exact, and a later save
    recovers cleanly over the leftover .tmp debris."""
    import mxtpu.checkpoint as ckpt_mod
    net, trainer, x = _net_and_trainer()
    before = net(x).asnumpy()
    ckpt = CheckpointManager(str(tmp_path / "k"), use_orbax=False)
    ckpt.save(1, net.collect_params())
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 1

    if crash_point == "during_write":
        real = ckpt_mod._np.savez

        def dying(*a, **kw):
            real(*a, **kw)               # bytes hit the .tmp dir, then
            raise SystemExit("writer thread killed mid-save")

        monkeypatch.setattr(ckpt_mod._np, "savez", dying)
    else:
        def dying_replace(src, dst):
            raise SystemExit("writer thread killed before publish")

        monkeypatch.setattr(ckpt_mod.os, "replace", dying_replace)

    ckpt.save(2, net.collect_params())   # async writer dies mid-flight
    with pytest.raises(RuntimeError, match="latest on-disk step is stale"):
        ckpt.wait_until_finished()
    monkeypatch.undo()

    # the half-written step 2 must be invisible: only its .tmp remains
    assert ckpt.latest_step() == 1
    assert ckpt.all_steps() == [1]
    net2, trainer2, _ = _net_and_trainer(seed=9)
    ckpt.restore(None, net2.collect_params())
    np.testing.assert_allclose(net2(x).asnumpy(), before, rtol=1e-6)

    # and the manager recovers: the next save publishes over the debris
    ckpt.save(2, net.collect_params())
    ckpt.wait_until_finished()
    assert ckpt.latest_step() == 2


def test_publish_fsyncs_blobs_and_dirs_before_rename(tmp_path,
                                                     monkeypatch):
    """The crash-safe-publication contract (ISSUE 4 satellite): every
    array blob AND the manifest are fsynced, then the tmp directory's
    entries, BEFORE the atomic rename — and the parent directory after
    it. An os.replace durable before its contents would let a power
    cut publish a manifest pointing at missing/partial blobs."""
    import mxtpu.checkpoint as ckpt_mod
    events = []
    real_file = CheckpointManager._fsync_file
    real_dir = CheckpointManager._fsync_dir
    real_replace = ckpt_mod.os.replace

    monkeypatch.setattr(
        CheckpointManager, "_fsync_file",
        staticmethod(lambda f: (events.append(("file", f.name)),
                                real_file(f))[1]))
    monkeypatch.setattr(
        CheckpointManager, "_fsync_dir",
        staticmethod(lambda p: (events.append(("dir", p)),
                                real_dir(p))[1]))
    monkeypatch.setattr(
        ckpt_mod.os, "replace",
        lambda src, dst: (events.append(("replace", src)),
                          real_replace(src, dst))[1])

    ckpt = CheckpointManager(str(tmp_path / "f"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, {"w": np.arange(8, dtype="f")},
              metadata={"step": 1},
              extras={"blob": np.ones(3, "f")})
    kinds = [k for k, _ in events]
    assert kinds.index("replace") > kinds.index("dir"), \
        "tmp dir entries must be durable before the publish"
    assert kinds[-1] == "dir", \
        "the publish rename itself must be fsynced (parent dir)"
    file_syncs = {e[1].rsplit("/", 1)[-1] for e in events
                  if e[0] == "file" and kinds.index("replace")
                  > events.index(e)}
    assert {"params.npz", "metadata.npz", "extras.npz",
            "integrity.json"} <= file_syncs, file_syncs
    assert ckpt.all_steps() == [1]


@pytest.mark.parametrize("kill_point", ["between_fsync_and_rename",
                                        "mid_blob_write"])
def test_kill9_in_publish_window_never_corrupts(tmp_path, kill_point):
    """A real SIGKILL — not an exception — lands either between the
    final fsync and the publish rename, or mid-blob-write: the
    published history must never contain a manifest pointing at a
    missing or partial blob. Step 1 stays the intact latest, every
    published step passes its integrity check, and the next save
    recovers over the .tmp debris."""
    import os
    import subprocess
    import sys
    root = os.path.join(os.path.dirname(__file__), "..")
    cdir = str(tmp_path / "k9")
    child = r"""
import os, sys, numpy as np
sys.path.insert(0, %(root)r)
import mxtpu.checkpoint as cm
ckpt = cm.CheckpointManager(%(cdir)r, async_save=False,
                            use_orbax=False)
ckpt.save(1, {"w": np.arange(8, dtype="f")}, metadata={"s": 1})
print("STEP1", flush=True)
import signal
if %(kill_point)r == "between_fsync_and_rename":
    cm.os.replace = lambda s, d: os.kill(os.getpid(), signal.SIGKILL)
else:
    real = cm._np.savez
    def dying(f, **arrs):
        real(f, **arrs)
        os.kill(os.getpid(), signal.SIGKILL)
    cm._np.savez = dying
ckpt.save(2, {"w": np.ones(8, "f") * 2}, metadata={"s": 2})
print("UNREACHABLE", flush=True)
""" % {"root": os.path.abspath(root), "cdir": cdir,
       "kill_point": kill_point}
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, "-c", child], env=env,
                          capture_output=True, text=True, timeout=120)
    assert "STEP1" in proc.stdout, proc.stdout + proc.stderr
    assert "UNREACHABLE" not in proc.stdout
    assert proc.returncode == -9

    ckpt = CheckpointManager(cdir, async_save=False, use_orbax=False)
    # the half-published step is invisible; step 1 is the intact latest
    assert ckpt.all_steps() == [1]
    tree = ckpt.restore(None)
    np.testing.assert_allclose(tree["params"]["w"],
                               np.arange(8, dtype="f"))
    # every PUBLISHED step's manifest references only intact blobs
    for s in ckpt.all_steps():
        ckpt._fallback_restore(s)       # raises CheckpointCorrupt if not
    # and the manager recovers right over the debris
    ckpt.save(2, {"w": np.ones(8, "f") * 2})
    assert ckpt.latest_step() == 2


def test_async_write_failure_surfaces(tmp_path):
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "good"), use_orbax=False)
    blocker = tmp_path / "blocker"
    blocker.write_text("x")
    ckpt.directory = str(blocker)       # writer's makedirs now fails
    ckpt.save(1, net.collect_params())
    with pytest.raises(RuntimeError):
        ckpt.wait_until_finished()


# ---------------------------------------------------------------------------
# checkpoint integrity (ISSUE 3): CRC32 per-array tags + fall back to
# the previous retained step instead of dying on a torn checkpoint
# ---------------------------------------------------------------------------

def test_truncated_newest_falls_back_to_previous(tmp_path):
    """Satellite acceptance: truncate the newest checkpoint on disk
    (the classic kill -9 mid-flush artifact on filesystems without
    atomic rename durability); restore() logs, skips it, and succeeds
    from the prior retained step."""
    import os
    import numpy as np
    net, trainer, x = _net_and_trainer()
    before = net(x).asnumpy()
    ckpt = CheckpointManager(str(tmp_path / "t"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params())
    trainer.step(2)                        # move the weights
    ckpt.save(2, net.collect_params())
    p2 = os.path.join(str(tmp_path / "t"), "step_2", "params.npz")
    with open(p2, "r+b") as f:
        f.truncate(os.path.getsize(p2) // 2)

    net2, trainer2, _ = _net_and_trainer(seed=9)
    tree = ckpt.restore(None, net2.collect_params())
    assert tree is not None                # fell back, did not raise
    np.testing.assert_allclose(net2(x).asnumpy(), before, rtol=1e-6)


def test_crc_mismatch_detected_and_skipped(tmp_path):
    """A checkpoint whose archive still OPENS but whose bytes rotted
    (bit flip, partial overwrite) fails its per-array CRC32 tag and is
    skipped like a truncated one."""
    import json
    import os
    import numpy as np
    from mxtpu.checkpoint import CheckpointCorrupt
    net, trainer, x = _net_and_trainer()
    before = net(x).asnumpy()
    ckpt = CheckpointManager(str(tmp_path / "c"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params())
    trainer.step(2)
    ckpt.save(2, net.collect_params())
    # forge a CRC mismatch on step 2 (same effect as rotten array bytes)
    tag_path = os.path.join(str(tmp_path / "c"), "step_2",
                            "integrity.json")
    with open(tag_path) as f:
        tags = json.load(f)
    name = sorted(tags["params"])[0]
    tags["params"][name] ^= 0xDEAD
    with open(tag_path, "w") as f:
        json.dump(tags, f)

    net2, trainer2, _ = _net_and_trainer(seed=9)
    ckpt.restore(None, net2.collect_params())
    np.testing.assert_allclose(net2(x).asnumpy(), before, rtol=1e-6)

    # when EVERY retained step is corrupt the failure surfaces
    p1 = os.path.join(str(tmp_path / "c"), "step_1", "params.npz")
    with open(p1, "r+b") as f:
        f.truncate(10)
    with pytest.raises(CheckpointCorrupt, match="no intact checkpoint"):
        ckpt.restore(None)


def test_integrity_tags_cover_all_sections(tmp_path):
    """trainer_states/metadata/extras carry CRC tags too — a rotted
    optimizer blob must not restore silently into a training run."""
    import json
    import os
    net, trainer, _ = _net_and_trainer()
    ckpt = CheckpointManager(str(tmp_path / "s"), async_save=False,
                             use_orbax=False)
    ckpt.save(1, net.collect_params(), trainer=trainer,
              metadata={"epoch": 1}, extras={"blob": np.arange(4)})
    tag_path = os.path.join(str(tmp_path / "s"), "step_1",
                            "integrity.json")
    with open(tag_path) as f:
        tags = json.load(f)
    assert set(tags) == {"params", "trainer_states", "metadata",
                         "extras", "digest"}
    # the whole-set identity next to the CRC sections: sha256 of the
    # params, the token rollout verification keys on
    assert tags["digest"] == ckpt.digest(1)
    assert len(tags["digest"]) == 64
    # grandfathering: a pre-tag checkpoint (no integrity.json) loads
    os.unlink(tag_path)
    assert ckpt.restore(1) is not None


# ---------------------------------------------------------------------------
# versioned weight snapshots (ISSUE 11): pins, digests, GC
# ---------------------------------------------------------------------------

def test_pinned_versions_survive_retention(tmp_path):
    """keep-last-K runs over the UNPINNED steps only: a pinned version
    — the serving rollback anchor — is never collected, however many
    newer versions land; unpinning re-exposes it to the next GC."""
    ckpt = CheckpointManager(str(tmp_path / "w"), max_to_keep=2,
                             async_save=False, use_orbax=False)
    params = {"w": np.arange(6, dtype=np.float32)}
    ckpt.save(1, params)
    ckpt.pin(1)
    for step in (2, 3, 4, 5, 6):
        ckpt.save(step, {"w": params["w"] * step})
    # unpinned tail is K=2 deep; step 1 survives by its pin alone
    assert ckpt.all_steps() == [1, 5, 6]
    assert ckpt.pins() == {1}
    # the pinned bits restore exactly (no fallback involved)
    tree = ckpt.restore_exact(1)
    np.testing.assert_array_equal(tree["params"]["w"], params["w"])
    # unpin: the next save's retention pass collects it
    ckpt.unpin(1)
    ckpt.save(7, {"w": params["w"] * 7})
    assert ckpt.all_steps() == [6, 7]


def test_digest_records_and_verifies_identity(tmp_path):
    """The writer records weight_digest(params) in integrity.json;
    digest(step) reads it back, and identical bits give identical
    digests across independent saves (the rollback identity check)."""
    from mxtpu.checkpoint import weight_digest
    ckpt = CheckpointManager(str(tmp_path / "d"), async_save=False,
                             use_orbax=False)
    params = {"a": np.arange(4, dtype=np.float32),
              "b": np.ones((2, 2), np.float32)}
    ckpt.save(1, params)
    d1 = ckpt.digest(1)
    assert d1 == weight_digest(params)
    # same bits, different step -> same digest; different bits differ
    ckpt.save(2, params)
    assert ckpt.digest(2) == d1
    ckpt.save(3, {"a": params["a"] + 1, "b": params["b"]})
    assert ckpt.digest(3) != d1
    assert ckpt.digest(99) is None


def test_corrupt_newest_version_falls_back_to_previous(tmp_path):
    """A subscriber polling the snapshot dir must keep serving from
    the last COMPLETE version when the newest is torn: restore() falls
    back, restore_exact() refuses — and after the corrupt step is
    superseded, the stream resumes normally."""
    import os
    from mxtpu.checkpoint import CheckpointCorrupt
    ckpt = CheckpointManager(str(tmp_path / "c"), max_to_keep=5,
                             async_save=False, use_orbax=False)
    ckpt.save(1, {"w": np.arange(3, dtype=np.float32)})
    ckpt.save(2, {"w": np.arange(3, dtype=np.float32) * 2})
    # tear version 2's params blob (post-publish disk rot)
    blob = os.path.join(str(tmp_path / "c"), "step_2", "params.npz")
    with open(blob, "wb") as f:
        f.write(b"torn")
    with pytest.raises(CheckpointCorrupt):
        ckpt.restore_exact(2)
    tree = ckpt.restore(2)          # falls back to version 1
    np.testing.assert_array_equal(tree["params"]["w"],
                                  np.arange(3, dtype=np.float32))
    # a fresh complete version supersedes the torn one
    ckpt.save(3, {"w": np.arange(3, dtype=np.float32) * 3})
    tree = ckpt.restore_exact(3)
    np.testing.assert_array_equal(tree["params"]["w"],
                                  np.arange(3, dtype=np.float32) * 3)


def test_restore_exact_missing_step_returns_none(tmp_path):
    ckpt = CheckpointManager(str(tmp_path / "m"), async_save=False,
                             use_orbax=False)
    assert ckpt.restore_exact(4) is None
    ckpt.save(4, {"w": np.zeros(2, np.float32)})
    assert ckpt.restore_exact(4) is not None
