"""Pallas fused LSTM time loop vs the lax.scan formulation
(ops/pallas_rnn.py; interpret mode on the CPU test mesh)."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest

import mxtpu as mx
from mxtpu import nd
from mxtpu.ops import rnn as rnn_ops
from mxtpu.ops.pallas_rnn import lstm_scan, _scan_reference


def _inputs(T=6, N=4, H=8, seed=0):
    rng = np.random.RandomState(seed)
    return (jnp.asarray(rng.standard_normal((T, N, 4 * H)).astype(np.float32)),
            jnp.asarray(rng.standard_normal((N, H)).astype(np.float32)),
            jnp.asarray(rng.standard_normal((N, H)).astype(np.float32)),
            jnp.asarray(rng.standard_normal((H, 4 * H)).astype(np.float32)
                        * 0.3))


def test_forward_matches_scan():
    xp, h0, c0, wh = _inputs()
    ys_p, ht_p, ct_p = lstm_scan(xp, h0, c0, wh)
    ys_s, ht_s, ct_s = _scan_reference(xp, h0, c0, wh)
    np.testing.assert_allclose(np.asarray(ys_p), np.asarray(ys_s),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ht_p), np.asarray(ht_s),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ct_p), np.asarray(ct_s),
                               atol=1e-5, rtol=1e-5)


def test_gradients_match_scan():
    xp, h0, c0, wh = _inputs(T=4, N=2, H=4, seed=3)

    def loss(fn, *args):
        ys, ht, ct = fn(*args)
        return jnp.sum(ys ** 2) + jnp.sum(jnp.sin(ht)) + jnp.sum(ct)

    gp = jax.grad(lambda *a: loss(lstm_scan, *a),
                  argnums=(0, 1, 2, 3))(xp, h0, c0, wh)
    gs = jax.grad(lambda *a: loss(_scan_reference, *a),
                  argnums=(0, 1, 2, 3))(xp, h0, c0, wh)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bidirectional", [False, True])
def test_rnn_op_pallas_path(bidirectional):
    """Full fused RNN op: pallas LSTM path == scan path, fwd and grads."""
    T, N, I, H, L = 5, 3, 6, 4, 2
    rng = np.random.RandomState(7)
    x = rng.standard_normal((T, N, I)).astype(np.float32)
    ndir = 2 if bidirectional else 1
    psize = rnn_ops.rnn_param_size("lstm", I, H, L, bidirectional)
    params = (rng.standard_normal(psize) * 0.2).astype(np.float32)
    h0 = np.zeros((L * ndir, N, H), np.float32)
    c0 = np.zeros((L * ndir, N, H), np.float32)

    def run():
        return mx.nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                         nd.array(c0), state_size=H, num_layers=L,
                         mode="lstm", bidirectional=bidirectional,
                         state_outputs=True)

    try:
        rnn_ops.USE_PALLAS_LSTM = False
        ref = [o.asnumpy() for o in run()]
        rnn_ops.USE_PALLAS_LSTM = True
        got = [o.asnumpy() for o in run()]
    finally:
        rnn_ops.USE_PALLAS_LSTM = None
    for a, b in zip(got, ref):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)


def test_gluon_lstm_layer_pallas_path():
    from mxtpu.gluon import rnn as grnn
    T, N, I, H = 4, 2, 5, 3
    rng = np.random.RandomState(1)
    x = nd.array(rng.standard_normal((T, N, I)).astype(np.float32))
    mx.random.seed(0)
    layer = grnn.LSTM(H, num_layers=1)
    layer.initialize(mx.init.Xavier())

    def fwd_and_grad():
        with mx.autograd.record():
            out = layer(x)
            loss = (out * out).sum()
        loss.backward()
        w = next(iter(layer.collect_params().values()))
        return out.asnumpy(), w.grad().asnumpy()

    try:
        rnn_ops.USE_PALLAS_LSTM = False
        out_ref, g_ref = fwd_and_grad()
        rnn_ops.USE_PALLAS_LSTM = True
        out_p, g_p = fwd_and_grad()
    finally:
        rnn_ops.USE_PALLAS_LSTM = None
    np.testing.assert_allclose(out_p, out_ref, atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(g_p, g_ref, atol=1e-5, rtol=1e-5)


def test_bf16_forward_backward_consistent():
    # bf16 inputs: backward recompute must mirror the kernel's f32-carry
    # precision so gradients belong to the same function as the forward
    xp, h0, c0, wh = _inputs(T=5, N=2, H=4, seed=9)
    xp = xp.astype(jnp.bfloat16)
    h0 = h0.astype(jnp.bfloat16)
    c0 = c0.astype(jnp.bfloat16)
    wh = wh.astype(jnp.bfloat16)
    ys_p, ht_p, ct_p = lstm_scan(xp, h0, c0, wh)
    ys_s, ht_s, ct_s = _scan_reference(xp, h0, c0, wh)
    assert ys_p.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(ys_p, np.float32),
                               np.asarray(ys_s, np.float32),
                               atol=2e-2, rtol=2e-2)

    def loss(fn, *a):
        ys, ht, ct = fn(*a)
        return jnp.sum(ys.astype(jnp.float32) ** 2)

    gp = jax.grad(lambda *a: loss(lstm_scan, *a), argnums=(0, 3))(
        xp, h0, c0, wh)
    gs = jax.grad(lambda *a: loss(_scan_reference, *a), argnums=(0, 3))(
        xp, h0, c0, wh)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   atol=5e-2, rtol=5e-2)


def test_gru_forward_and_grads_match_scan():
    from mxtpu.ops.pallas_rnn import gru_scan, _gru_scan_reference
    rng = np.random.RandomState(11)
    T, N, H = 5, 3, 4
    xp = jnp.asarray(rng.standard_normal((T, N, 3 * H)).astype(np.float32))
    h0 = jnp.asarray(rng.standard_normal((N, H)).astype(np.float32))
    whrz = jnp.asarray(rng.standard_normal((H, 2 * H)).astype(np.float32)
                       * 0.3)
    whn = jnp.asarray(rng.standard_normal((H, H)).astype(np.float32) * 0.3)
    bhn = jnp.asarray(rng.standard_normal((H,)).astype(np.float32) * 0.1)
    ys_p, ht_p = gru_scan(xp, h0, whrz, whn, bhn)
    ys_s, ht_s = _gru_scan_reference(xp, h0, whrz, whn, bhn)
    np.testing.assert_allclose(np.asarray(ys_p), np.asarray(ys_s),
                               atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ht_p), np.asarray(ht_s),
                               atol=1e-5, rtol=1e-5)

    def loss(fn, *a):
        ys, ht = fn(*a)
        return jnp.sum(ys ** 2) + jnp.sum(jnp.sin(ht))

    gp = jax.grad(lambda *a: loss(gru_scan, *a),
                  argnums=(0, 1, 2, 3, 4))(xp, h0, whrz, whn, bhn)
    gs = jax.grad(lambda *a: loss(_gru_scan_reference, *a),
                  argnums=(0, 1, 2, 3, 4))(xp, h0, whrz, whn, bhn)
    for a, b in zip(gp, gs):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bidirectional", [False, True])
def test_rnn_op_gru_pallas_path(bidirectional):
    T, N, I, H, L = 5, 3, 6, 4, 2
    rng = np.random.RandomState(7)
    x = rng.standard_normal((T, N, I)).astype(np.float32)
    ndir = 2 if bidirectional else 1
    psize = rnn_ops.rnn_param_size("gru", I, H, L, bidirectional)
    params = (rng.standard_normal(psize) * 0.2).astype(np.float32)
    h0 = np.zeros((L * ndir, N, H), np.float32)

    def run():
        return mx.nd.RNN(nd.array(x), nd.array(params), nd.array(h0),
                         state_size=H, num_layers=L, mode="gru",
                         bidirectional=bidirectional, state_outputs=True)

    try:
        rnn_ops.USE_PALLAS_LSTM = False
        ref = [o.asnumpy() for o in run()]
        rnn_ops.USE_PALLAS_LSTM = True
        got = [o.asnumpy() for o in run()]
    finally:
        rnn_ops.USE_PALLAS_LSTM = None
    for a, b in zip(got, ref):
        np.testing.assert_allclose(a, b, atol=1e-5, rtol=1e-5)
