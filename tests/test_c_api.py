"""Core C ABI tier: build libmxtpu_c.so, compile the C test drivers, run
them. Reference counterpart: the reference's c_api is exercised through
binding test suites; here tests/cpp/c_api_test.cc drives it directly and
example/c_api/train_lenet.c proves end-to-end training through the ABI."""
import os
import shutil
import subprocess
import sys

import pytest

_ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
_NATIVE = os.path.join(_ROOT, "mxtpu", "_native")
_SO = os.path.join(_NATIVE, "libmxtpu_c.so")


def _build_so():
    if shutil.which("g++") is None:
        pytest.skip("no g++")
    res = subprocess.run(["make", "-C", _NATIVE, "libmxtpu_c.so"],
                         capture_output=True, text=True)
    if res.returncode != 0:
        pytest.skip("libmxtpu_c.so build failed: " + res.stderr[-500:])


def _run_c(tmp_path, src, exe_name, cc="g++", extra=(), args=()):
    _build_so()
    exe = str(tmp_path / exe_name)
    subprocess.run(
        [cc, "-O1", src, "-I", _ROOT, "-L", _NATIVE, "-lmxtpu_c",
         "-Wl,-rpath," + _NATIVE, "-o", exe] + list(extra),
        check=True)
    env = dict(os.environ, PYTHONPATH=_ROOT, JAX_PLATFORMS="cpu")
    return subprocess.run([exe] + list(args), capture_output=True,
                          text=True, timeout=600, env=env)


def test_c_api_unit(tmp_path):
    res = _run_c(tmp_path,
                 os.path.join(_ROOT, "tests", "cpp", "c_api_test.cc"),
                 "c_api_test", cc="g++", extra=["-std=c++17"],
                 args=[str(tmp_path)])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "c_api_test OK" in res.stdout


def test_c_api_train_lenet(tmp_path):
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    res = _run_c(tmp_path,
                 os.path.join(_ROOT, "example", "c_api", "train_lenet.c"),
                 "train_lenet", cc="gcc", extra=["-lm"])
    assert res.returncode == 0, res.stdout + res.stderr
    assert "train_lenet (C ABI) OK" in res.stdout
