"""FeedForward legacy front-end, mx.rtc runtime kernels, torch bridge
(reference model.py:419-994, rtc.py, torch.py)."""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu import nd


def _mlp():
    data = mx.sym.var("data")
    h = mx.sym.Activation(mx.sym.FullyConnected(data, num_hidden=16,
                                                name="fc1"),
                          act_type="relu")
    return mx.sym.SoftmaxOutput(mx.sym.FullyConnected(h, num_hidden=2,
                                                      name="fc2"),
                                name="softmax")


def _toy():
    rng = np.random.RandomState(0)
    x = rng.randn(128, 8).astype(np.float32)
    y = (x.sum(1) > 0).astype(np.float32)
    return x, y


def test_feedforward_fit_predict_score():
    import logging
    logging.disable(logging.INFO)
    mx.random.seed(0)
    x, y = _toy()
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=8,
                                 optimizer="sgd", learning_rate=0.1,
                                 initializer=mx.init.Xavier(),
                                 numpy_batch_size=32)
    model.fit(x, y)
    prob = model.predict(x)
    assert prob.shape == (128, 2)
    acc = model.score(mx.io.NDArrayIter(x, y, batch_size=32))
    assert acc > 0.85, acc


def test_feedforward_create_save_load(tmp_path):
    import logging
    logging.disable(logging.INFO)
    mx.random.seed(0)
    x, y = _toy()
    model = mx.model.FeedForward.create(_mlp(), x, y, ctx=mx.cpu(),
                                        num_epoch=3, learning_rate=0.1,
                                        initializer=mx.init.Xavier())
    prefix = str(tmp_path / "ff")
    model.save(prefix, 3)
    loaded = mx.model.FeedForward.load(prefix, 3, ctx=mx.cpu())
    np.testing.assert_allclose(loaded.predict(x), model.predict(x),
                               rtol=1e-5, atol=1e-5)


def test_rtc_axpy():
    src = r'''
def axpy(x_ref, y_ref, out_ref, *, alpha):
    out_ref[...] = alpha * x_ref[...] + y_ref[...]
'''
    mod = mx.rtc.PallasModule(src, exports=["axpy"])
    k = mod.get_kernel("axpy", "const float *x, const float *y, "
                               "float alpha, float *out")
    # note signature order defines arg order at launch
    x = nd.array(np.arange(8, dtype=np.float32).reshape(2, 4))
    y = nd.ones((2, 4))
    out = nd.zeros((2, 4))
    k.launch((x, y, 3.0, out), mx.cpu(0), (1, 1, 1))
    np.testing.assert_allclose(out.asnumpy(),
                               3.0 * x.asnumpy() + 1.0)


def test_rtc_grid_program_id():
    src = r'''
def fill_rows(out_ref):
    i = pl.program_id(0)
    out_ref[i, :] = jnp.full((4,), i, jnp.float32)
'''
    mod = mx.rtc.PallasModule(src, exports=["fill_rows"])
    k = mod.get_kernel("fill_rows", "float *out")
    out = nd.zeros((3, 4))
    k.launch((out,), mx.cpu(0), (3, 1, 1))
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(3, dtype=np.float32)[:, None]
                               * np.ones((1, 4)))


def test_rtc_signature_errors():
    mod = mx.rtc.PallasModule("def f(o_ref):\n    o_ref[...] = 0.0\n",
                              exports=["f"])
    with pytest.raises(ValueError):
        mod.get_kernel("f", "blob *x")
    with pytest.raises(ValueError):
        mod.get_kernel("missing", "float *x")
    k = mod.get_kernel("f", "float *x")
    with pytest.raises(ValueError):
        k.launch((), mx.cpu(0), (1, 1, 1))


def test_torch_bridge_roundtrip():
    torch_mod = pytest.importorskip("torch")
    from mxtpu import torch as bridge
    assert bridge.available()
    a = nd.array(np.array([[3.0, 1.0], [2.0, 4.0]], np.float32))
    t = bridge.to_torch(a)
    assert isinstance(t, torch_mod.Tensor)
    back = bridge.from_torch(t)
    np.testing.assert_allclose(back.asnumpy(), a.asnumpy())


def test_torch_bridge_wrap():
    torch_mod = pytest.importorskip("torch")
    from mxtpu import torch as bridge
    tsort = bridge.wrap(torch_mod.sort)
    values, idx = tsort(nd.array(np.array([3.0, 1.0, 2.0], np.float32)))
    np.testing.assert_allclose(values.asnumpy(), [1.0, 2.0, 3.0])
    np.testing.assert_allclose(idx.asnumpy(), [1, 2, 0])


def test_rtc_output_first_signature():
    # declared order must be honored even when an output precedes inputs
    src = r'''
def dbl(out_ref, x_ref):
    out_ref[...] = x_ref[...] * 2.0
'''
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("dbl", "float *out, const float *x")
    x = nd.array(np.arange(4, dtype=np.float32))
    out = nd.zeros((4,))
    k.launch((out, x), mx.cpu(0), (1, 1, 1))
    np.testing.assert_allclose(out.asnumpy(), 2.0 * x.asnumpy())


def test_rtc_exports_enforced():
    mod = mx.rtc.PallasModule("def f(o_ref):\n    o_ref[...] = 0.0\n")
    with pytest.raises(ValueError):
        mod.get_kernel("jnp", "float *x")   # namespace entry, not a kernel
    with pytest.raises(ValueError):
        mx.rtc.PallasModule("x = 1\n", exports=["g"])


def test_feedforward_predict_return_data():
    import logging
    logging.disable(logging.INFO)
    mx.random.seed(0)
    x, y = _toy()
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                                 learning_rate=0.1, numpy_batch_size=32,
                                 initializer=mx.init.Xavier())
    model.fit(x, y)
    outs, datas, labels = model.predict(
        mx.io.NDArrayIter(x, y, batch_size=50), return_data=True)
    # padding of the last 128/50 batch must be trimmed everywhere
    assert outs.shape == (128, 2)
    np.testing.assert_allclose(datas, x)
    np.testing.assert_allclose(labels, y)


def test_torch_wrap_dict_and_scalars():
    torch_mod = pytest.importorskip("torch")
    from mxtpu import torch as bridge

    def f(t):
        return {"mean": t.mean(), "raw": t, "tag": "ok"}

    out = bridge.wrap(f)(nd.array(np.array([1.0, 3.0], np.float32)))
    assert set(out) == {"mean", "raw", "tag"}
    assert out["tag"] == "ok"
    np.testing.assert_allclose(out["mean"].asnumpy(), 2.0)
    np.testing.assert_allclose(out["raw"].asnumpy(), [1.0, 3.0])


def test_feedforward_fit_after_predict(tmp_path):
    import logging
    logging.disable(logging.INFO)
    mx.random.seed(0)
    x, y = _toy()
    model = mx.model.FeedForward(_mlp(), ctx=mx.cpu(), num_epoch=2,
                                 learning_rate=0.1, numpy_batch_size=32,
                                 initializer=mx.init.Xavier())
    model.fit(x, y)
    model.save(str(tmp_path / "m"), 2)
    # load -> predict binds the fresh module for INFERENCE; the following
    # fit must force a training rebind instead of hitting the backward
    # assert on an inference-bound module
    # begin_epoch resumes at 2, so ask for 2 more epochs
    loaded = mx.model.FeedForward.load(str(tmp_path / "m"), 2, ctx=mx.cpu(),
                                       num_epoch=4, learning_rate=0.1,
                                       numpy_batch_size=32)
    before = loaded.predict(x)
    loaded.fit(x, y)
    after = loaded.predict(x)
    assert not np.allclose(before, after)    # training actually happened


def test_rtc_interior_unit_grid_dim():
    src = r'''
def rows(out_ref):
    j = pl.program_id(1)
    out_ref[0, j] = j * 1.0
'''
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("rows", "float *out")
    out = nd.zeros((1, 3))
    # interior 1 must be kept so program_id(1) addresses the 3-axis
    k.launch((out,), mx.cpu(0), (1, 3, 1))
    np.testing.assert_allclose(out.asnumpy(), [[0.0, 1.0, 2.0]])


def test_rtc_launch_cache():
    src = "def f(x_ref, o_ref):\n    o_ref[...] = x_ref[...] + 1.0\n"
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("f", "const float *x, float *o")
    x, o = nd.ones((4,)), nd.zeros((4,))
    k.launch((x, o), mx.cpu(0))
    assert len(k._cache) == 1
    k.launch((x, o), mx.cpu(0))
    assert len(k._cache) == 1        # same shapes: compiled once
    k.launch((nd.ones((8,)), nd.zeros((8,))), mx.cpu(0))
    assert len(k._cache) == 2


def test_torch_wrap_namedtuple():
    pytest.importorskip("torch")
    import collections
    from mxtpu import torch as bridge
    R = collections.namedtuple("R", "a b")

    def f(t):
        return R(t * 2, t + 1)

    out = bridge.wrap(f)(nd.array(np.array([1.0, 2.0], np.float32)))
    assert type(out).__name__ == "R"
    np.testing.assert_allclose(out.a.asnumpy(), [2.0, 4.0])
    np.testing.assert_allclose(out.b.asnumpy(), [2.0, 3.0])


def test_rtc_scalar_no_recompile():
    src = "def f(x_ref, o_ref, *, alpha):\n    o_ref[...] = x_ref[...] * alpha\n"
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("f", "const float *x, float alpha, float *o")
    x = nd.ones((4,))
    for i, a in enumerate([1.0, 2.0, 3.0]):
        o = nd.zeros((4,))
        k.launch((x, a, o), mx.cpu(0))
        np.testing.assert_allclose(o.asnumpy(), a)
    assert len(k._cache) == 1   # scalar value changes reuse the compile


def test_rtc_int_scalar_static():
    # int scalars are static: usable as Python loop bounds in the body
    src = """
def rep(x_ref, o_ref, *, n):
    acc = x_ref[...]
    for _ in range(n - 1):
        acc = acc + x_ref[...]
    o_ref[...] = acc
"""
    mod = mx.rtc.PallasModule(src)
    k = mod.get_kernel("rep", "const float *x, int n, float *o")
    x = nd.ones((4,))
    o = nd.zeros((4,))
    k.launch((x, 3, o), mx.cpu(0))
    np.testing.assert_allclose(o.asnumpy(), 3.0)
    k.launch((x, 5, o), mx.cpu(0))
    np.testing.assert_allclose(o.asnumpy(), 5.0)
    assert len(k._cache) == 2   # int value IS the specialization key
