"""Fused RNN op torture grid (reference tests/python/unittest/
test_operator.py RNN sections: check_rnn_consistency across modes /
layers / directions, state carry, masking interactions).

The oracle is an independent pure-numpy recurrence implemented here from
the documented cudnn blob layout (ops/rnn.py rnn_blob_blocks) — NOT the
op's own jax code — so layout bugs and cell-math bugs both surface.
"""
import numpy as np
import pytest

import mxtpu as mx
from mxtpu.ops.rnn import rnn_param_size
from mxtpu.test_utils import check_numeric_gradient

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _sig(x):
    return 1.0 / (1.0 + np.exp(-x))


def _np_unpack(params, mode, I, H, L, D):
    """Independent re-read of the cudnn layout: all (wi, wh) blocks
    layer-major / direction-minor, then all (bi, bh) in the same order."""
    G = _GATES[mode]
    mats, off = [], 0
    for layer in range(L):
        isz = I if layer == 0 else H * D
        for _ in range(D):
            wi = params[off:off + G * H * isz].reshape(G * H, isz)
            off += G * H * isz
            wh = params[off:off + G * H * H].reshape(G * H, H)
            off += G * H * H
            mats.append([wi, wh])
    for i in range(L * D):
        mats[i].append(params[off:off + G * H])
        off += G * H
        mats[i].append(params[off:off + G * H])
        off += G * H
    return mats


def _np_direction(xs, h0, c0, wi, wh, bi, bh, mode, reverse):
    T = xs.shape[0]
    H = h0.shape[-1]
    seq = range(T - 1, -1, -1) if reverse else range(T)
    h, c = h0.copy(), c0.copy()
    ys = np.zeros((T, xs.shape[1], H), np.float64)
    for t in seq:
        pre = xs[t] @ wi.T + bi
        if mode in ("rnn_relu", "rnn_tanh"):
            g = pre + h @ wh.T + bh
            h = np.tanh(g) if mode == "rnn_tanh" else np.maximum(g, 0)
        elif mode == "lstm":
            g = pre + h @ wh.T + bh
            i_, f, gg, o = np.split(g, 4, axis=-1)
            c = _sig(f) * c + _sig(i_) * np.tanh(gg)
            h = _sig(o) * np.tanh(c)
        else:   # gru, cuDNN variant: candidate sees r * (h @ Whn + bhn)
            rz = _sig(pre[:, :2 * H] + h @ wh[:2 * H].T + bh[:2 * H])
            r, z = np.split(rz, 2, axis=-1)
            n = np.tanh(pre[:, 2 * H:]
                        + r * (h @ wh[2 * H:].T + bh[2 * H:]))
            h = (1 - z) * n + z * h
        ys[t] = h
    return ys, h, c


def _np_rnn(data, params, state, cell, mode, L, D, H):
    mats = _np_unpack(params, mode, data.shape[2], H, L, D)
    x = data.astype(np.float64)
    hs, cs = [], []
    for layer in range(L):
        outs = []
        for d in range(D):
            idx = layer * D + d
            wi, wh, bi, bh = [m.astype(np.float64) for m in mats[idx]]
            ys, hT, cT = _np_direction(x, state[idx], cell[idx], wi, wh,
                                       bi, bh, mode, reverse=(d == 1))
            outs.append(ys)
            hs.append(hT)
            cs.append(cT)
        x = outs[0] if D == 1 else np.concatenate(outs, axis=-1)
    return x, np.stack(hs), np.stack(cs)


def _mk(mode, L, D, T=4, N=2, I=3, H=4, seed=0):
    r = np.random.RandomState(seed)
    data = r.uniform(-1, 1, (T, N, I)).astype("f")
    psize = rnn_param_size(mode, I, H, L, D == 2)
    params = (r.uniform(-1, 1, psize) / np.sqrt(H)).astype("f")
    state = r.uniform(-1, 1, (L * D, N, H)).astype("f")
    cell = r.uniform(-1, 1, (L * D, N, H)).astype("f")
    return data, params, state, cell


def _run_fused(data, params, state, cell, mode, L, D, H, **kw):
    args = [mx.nd.array(data), mx.nd.array(params), mx.nd.array(state)]
    if mode == "lstm":
        args.append(mx.nd.array(cell))
    return mx.nd.RNN(*args, state_size=H, num_layers=L,
                     bidirectional=(D == 2), mode=mode,
                     state_outputs=True, **kw)


@pytest.mark.parametrize("mode", sorted(_GATES))
@pytest.mark.parametrize("L", [1, 2, 3])
@pytest.mark.parametrize("D", [1, 2])
def test_fused_forward_grid(mode, L, D):
    """Forward + final states vs the numpy oracle across the full
    mode x depth x direction grid (reference check_rnn_consistency)."""
    H = 4
    data, params, state, cell = _mk(mode, L, D, seed=11 * L + D)
    outs = _run_fused(data, params, state, cell, mode, L, D, H)
    ref_y, ref_h, ref_c = _np_rnn(data, params, state,
                                  np.zeros_like(cell) if mode != "lstm"
                                  else cell, mode, L, D, H)
    np.testing.assert_allclose(outs[0].asnumpy(), ref_y, rtol=2e-4,
                               atol=2e-5)
    np.testing.assert_allclose(outs[1].asnumpy(), ref_h, rtol=2e-4,
                               atol=2e-5)
    if mode == "lstm":
        np.testing.assert_allclose(outs[2].asnumpy(), ref_c, rtol=2e-4,
                                   atol=2e-5)


@pytest.mark.parametrize("mode", ["rnn_tanh", "lstm", "gru"])
@pytest.mark.parametrize("D", [1, 2])
def test_fused_grad_grid(mode, D):
    """Numeric gradients through the fused op w.r.t. data, the packed
    parameter blob, AND the initial states, 2 layers deep (reference
    test_operator.py RNN grad sections). Smooth cells only — fp32
    central differences are well-posed for them."""
    L, H, T, N, I = 2, 3, 3, 2, 2
    data, params, state, cell = _mk(mode, L, D, T=T, N=N, I=I, H=H,
                                    seed=5 + D)
    names = ["a0", "a1", "a2"] + (["a3"] if mode == "lstm" else [])
    sym = mx.sym.RNN(*[mx.sym.var(n) for n in names], state_size=H,
                     num_layers=L, bidirectional=(D == 2), mode=mode)
    values = {"a0": data, "a1": params, "a2": state}
    if mode == "lstm":
        values["a3"] = cell
    check_numeric_gradient(sym, values, grad_nodes=names,
                           numeric_eps=1e-3, rtol=0.06, atol=2e-3)


@pytest.mark.parametrize("D", [1, 2])
def test_fused_grad_rnn_relu_vs_oracle(D):
    """rnn_relu gradients: the kink makes fp32 finite differences of the
    op itself ill-posed (a pre-activation within eps of zero anywhere in
    the recurrence corrupts the estimate), so instead compare the op's
    analytic grad against float64 central differences of the NUMPY
    oracle at eps=1e-6 — stable to ~1e-9 away from the kink, and the
    oracle equality with the op is already pinned by the forward grid."""
    mode, L, H = "rnn_relu", 2, 3
    data, params, state, cell = _mk(mode, L, D, T=3, N=2, I=2, H=H,
                                    seed=5 + D)

    names = ["a0", "a1", "a2"]
    sym = mx.sym.RNN(*[mx.sym.var(n) for n in names], state_size=H,
                     num_layers=L, bidirectional=(D == 2), mode=mode)
    shapes = {"a0": data.shape, "a1": params.shape, "a2": state.shape}
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write", **shapes)
    for n, v in (("a0", data), ("a1", params), ("a2", state)):
        ex.arg_dict[n][:] = v
    out = ex.forward(is_train=True)[0]
    ex.backward(mx.nd.ones(out.shape))
    analytic = {n: ex.grad_dict[n].asnumpy() for n in names}

    def oracle_sum(vals):
        y, _, _ = _np_rnn(vals["a0"].reshape(data.shape),
                          vals["a1"].reshape(params.shape),
                          vals["a2"].reshape(state.shape),
                          np.zeros_like(cell), mode, L, D, H)
        return y.sum()

    eps = 1e-6
    flat = {n: v.astype(np.float64).ravel()
            for n, v in (("a0", data), ("a1", params), ("a2", state))}
    for n in names:
        numeric = np.zeros_like(flat[n])
        for i in range(flat[n].size):
            up, dn = dict(flat), dict(flat)
            up[n] = flat[n].copy()
            up[n][i] += eps
            dn[n] = flat[n].copy()
            dn[n][i] -= eps
            numeric[i] = (oracle_sum(up) - oracle_sum(dn)) / (2 * eps)
        np.testing.assert_allclose(
            analytic[n].ravel(), numeric, rtol=5e-3, atol=1e-4,
            err_msg="rnn_relu grad w.r.t. %s" % n)


@pytest.mark.parametrize("mode", ["lstm", "gru"])
def test_state_carry_between_calls(mode):
    """Running T steps in one call == two T/2 calls with the final
    states of the first feeding the second (the stateful-decoding
    pattern; exercises state_outputs round-tripping)."""
    L, D, H = 2, 1, 4
    data, params, state, cell = _mk(mode, L, D, T=6, seed=3)
    full = _run_fused(data, params, state, cell, mode, L, D, H)

    first = _run_fused(data[:3], params, state, cell, mode, L, D, H)
    h_mid = first[1].asnumpy()
    c_mid = first[2].asnumpy() if mode == "lstm" else cell
    second = _run_fused(data[3:], params, h_mid, c_mid, mode, L, D, H)

    joined = np.concatenate([first[0].asnumpy(), second[0].asnumpy()])
    np.testing.assert_allclose(joined, full[0].asnumpy(), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(second[1].asnumpy(), full[1].asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_masking_interaction():
    """Variable-length semantics composed from RNN + sequence ops, the
    reference recipe (sym/rnn use_sequence_length predates 1.1; masking
    is done around the op): a unidirectional RNN over a padded batch
    matches the unpadded run on every valid step, SequenceLast picks the
    true last hidden state, and grads do not flow from masked-out tail
    steps into the valid prefix's loss."""
    mode, L, D, H = "lstm", 1, 1, 4
    data, params, state, cell = _mk(mode, L, D, T=6, seed=9)
    lengths = np.array([4, 6], "f")
    padded = data.copy()
    padded[4:, 0, :] = 7.7    # garbage past sample 0's length

    y_pad = _run_fused(padded, params, state, cell, mode, L, D, H)[0] \
        .asnumpy()
    y_short = _run_fused(data[:4], params, state, cell, mode, L, D, H)[0] \
        .asnumpy()
    # causal op: valid prefix is untouched by the padded tail
    np.testing.assert_allclose(y_pad[:4, 0], y_short[:, 0], rtol=1e-5,
                               atol=1e-6)

    # SequenceLast over the RNN output picks step length-1 per sample
    last = mx.nd.SequenceLast(mx.nd.array(y_pad), mx.nd.array(lengths),
                              use_sequence_length=True).asnumpy()
    np.testing.assert_allclose(last[0], y_pad[3, 0], rtol=1e-6)
    np.testing.assert_allclose(last[1], y_pad[5, 1], rtol=1e-6)

    # masked loss: no gradient reaches the padded tail of the input
    names = ["a0", "a1", "a2", "a3"]
    out = mx.sym.RNN(*[mx.sym.var(n) for n in names], state_size=H,
                     num_layers=L, mode=mode)
    masked = mx.sym.SequenceMask(out, mx.sym.var("len"),
                                 use_sequence_length=True)
    ex = masked.bind(mx.cpu(),
                     {"a0": mx.nd.array(padded),
                      "a1": mx.nd.array(params),
                      "a2": mx.nd.array(state),
                      "a3": mx.nd.array(cell),
                      "len": mx.nd.array(lengths)},
                     args_grad={"a0": mx.nd.zeros(padded.shape)})
    ex.forward(is_train=True)
    ex.backward(mx.nd.ones((6, 2, H)))
    g = ex.grad_dict["a0"].asnumpy()
    assert np.abs(g[4:, 0, :]).max() == 0.0, "masked steps leaked grad"
    assert np.abs(g[:4, 0, :]).max() > 0.0, "valid steps got no grad"


def test_dropout_between_layers():
    """p>0 applies only between layers and only in training mode."""
    mode, L, D, H = "gru", 2, 1, 4
    data, params, state, cell = _mk(mode, L, D, seed=2)
    base = _run_fused(data, params, state, cell, mode, L, D, H)[0] \
        .asnumpy()
    # eval mode: p is inert
    drop_eval = _run_fused(data, params, state, cell, mode, L, D, H,
                           p=0.5)[0].asnumpy()
    np.testing.assert_allclose(drop_eval, base, rtol=1e-6)
    # training mode: stochastic, different from base
    mx.random.seed(0)
    with mx.autograd.record(train_mode=True):
        drop_train = _run_fused(data, params, state, cell, mode, L, D, H,
                                p=0.5)[0].asnumpy()
    assert np.abs(drop_train - base).max() > 1e-3


def test_lstm_state_clip():
    """lstm_state_clip_min/max bound the returned cell state
    (reference RNNParam state clipping)."""
    mode, L, D, H = "lstm", 1, 1, 4
    data, params, state, cell = _mk(mode, L, D, seed=4)
    big_cell = cell * 50.0
    _, _, c_out = _run_fused(data, params, state, big_cell, mode, L, D, H,
                             lstm_state_clip_min=-0.4,
                             lstm_state_clip_max=0.4)
    c = c_out.asnumpy()
    assert c.min() >= -0.4 - 1e-6 and c.max() <= 0.4 + 1e-6
