"""Data iterator tests (reference: tests/python/unittest/test_io.py)."""
import os
import struct

import numpy as np
import pytest

import mxtpu as mx


def test_ndarray_iter_basic():
    data = np.arange(100).reshape(25, 4).astype("float32")
    labels = np.arange(25).astype("float32")
    it = mx.io.NDArrayIter(data, labels, batch_size=5)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (5, 4)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:5])
    np.testing.assert_allclose(batches[0].label[0].asnumpy(), labels[:5])
    # reset and re-iterate
    it.reset()
    assert len(list(it)) == 5


def test_ndarray_iter_pad_discard():
    data = np.arange(23 * 2).reshape(23, 2).astype("float32")
    it = mx.io.NDArrayIter(data, None, batch_size=5, last_batch_handle="pad")
    batches = list(it)
    assert len(batches) == 5
    assert batches[-1].pad == 2
    it = mx.io.NDArrayIter(data, None, batch_size=5,
                           last_batch_handle="discard")
    assert len(list(it)) == 4


def test_ndarray_iter_shuffle():
    data = np.arange(40).reshape(20, 2).astype("float32")
    label = np.arange(20).astype("float32")
    it = mx.io.NDArrayIter(data, label, batch_size=4, shuffle=True)
    seen = []
    for b in it:
        # data/label stay aligned after shuffling
        np.testing.assert_allclose(b.data[0].asnumpy()[:, 0] // 2,
                                   b.label[0].asnumpy())
        seen.extend(b.label[0].asnumpy().tolist())
    assert sorted(seen) == list(range(20))


def test_resize_iter():
    data = np.zeros((16, 2), dtype="float32")
    inner = mx.io.NDArrayIter(data, None, batch_size=4)
    it = mx.io.ResizeIter(inner, 7)
    assert len(list(it)) == 7


def test_prefetching_iter():
    data = np.arange(64).reshape(16, 4).astype("float32")
    label = np.arange(16).astype("float32")
    inner = mx.io.NDArrayIter(data, label, batch_size=4)
    it = mx.io.PrefetchingIter(inner)
    batches = list(it)
    assert len(batches) == 4
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    it.reset()
    assert len(list(it)) == 4


def test_csv_iter(tmp_path):
    data = np.random.RandomState(0).rand(10, 3).astype("float32")
    labels = np.arange(10).astype("float32")
    dpath = str(tmp_path / "data.csv")
    lpath = str(tmp_path / "label.csv")
    np.savetxt(dpath, data, delimiter=",")
    np.savetxt(lpath, labels.reshape(-1, 1), delimiter=",")
    it = mx.io.CSVIter(data_csv=dpath, data_shape=(3,), label_csv=lpath,
                       label_shape=(1,), batch_size=2)
    batches = list(it)
    assert len(batches) == 5
    np.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:2],
                               rtol=1e-5)


def test_mnist_iter(tmp_path):
    """MNISTIter reads idx format (reference src/io/iter_mnist.cc)."""
    rng = np.random.RandomState(0)
    images = (rng.rand(50, 28, 28) * 255).astype(np.uint8)
    labels = rng.randint(0, 10, size=50).astype(np.uint8)
    img_path = str(tmp_path / "train-images-idx3-ubyte")
    lbl_path = str(tmp_path / "train-labels-idx1-ubyte")
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 50, 28, 28))
        f.write(images.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, 50))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=10,
                         shuffle=False)
    batches = list(it)
    assert len(batches) == 5
    assert batches[0].data[0].shape == (10, 1, 28, 28)
    np.testing.assert_allclose(batches[0].data[0].asnumpy(),
                               images[:10].reshape(10, 1, 28, 28) / 255.0,
                               rtol=1e-5)
    it_flat = mx.io.MNISTIter(image=img_path, label=lbl_path, batch_size=10,
                              shuffle=False, flat=True)
    assert next(it_flat).data[0].shape == (10, 784)


def test_libsvm_iter(tmp_path):
    path = str(tmp_path / "data.libsvm")
    with open(path, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:0.5\n")
    it = mx.io.LibSVMIter(data_libsvm=path, data_shape=(4,), batch_size=2)
    b = next(it)
    np.testing.assert_allclose(b.data[0].asnumpy(),
                               [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(b.label[0].asnumpy(), [1, 0])


def test_libsvm_iter_csr(tmp_path):
    import numpy as np
    p = tmp_path / "d.libsvm"
    p.write_text("1 0:1.5 3:2.0\n"
                 "0 1:0.5\n"
                 "1 2:3.0 3:1.0\n")
    import mxtpu as mx
    it = mx.io.LibSVMIter(data_libsvm=str(p), data_shape=(4,), batch_size=2)
    b1 = it.next()
    from mxtpu.ndarray.sparse import CSRNDArray
    assert isinstance(b1.data[0], CSRNDArray)
    np.testing.assert_allclose(
        b1.data[0].asnumpy(),
        [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    np.testing.assert_allclose(b1.label[0].asnumpy(), [1.0, 0.0])
    b2 = it.next()
    assert b2.pad == 1          # round_batch overflow wraps to the start
    np.testing.assert_allclose(b2.data[0].asnumpy()[0],
                               [0, 0, 3.0, 1.0])
    # padded row is dataset row 0 again (reference iter_libsvm.cc wrap)
    np.testing.assert_allclose(b2.data[0].asnumpy()[1],
                               [1.5, 0, 0, 2.0])
    import pytest
    with pytest.raises(StopIteration):
        it.next()
    it.reset()
    assert it.next().pad == 0


def test_libsvm_iter_label_file(tmp_path):
    import numpy as np
    d = tmp_path / "d.libsvm"
    d.write_text("0 0:1.0\n0 1:1.0\n")
    l = tmp_path / "l.libsvm"
    l.write_text("0 0:0.25 2:0.75\n0 1:1.0\n")
    import mxtpu as mx
    it = mx.io.LibSVMIter(data_libsvm=str(d), data_shape=(2,),
                          label_libsvm=str(l), batch_size=2)
    b = it.next()
    np.testing.assert_allclose(b.label[0].asnumpy(),
                               [[0.25, 0, 0.75], [0, 1.0, 0]])


def test_image_record_iter_fast_path(tmp_path):
    """The process-pool ImageRecordIter path (mxtpu/_image_worker.py)
    produces pixel-exact batches for the deterministic config (no resize,
    center crop at native size): decode -> normalize -> NCHW."""
    import numpy as np
    from PIL import Image
    import mxtpu as mx
    from mxtpu import recordio
    from mxtpu.image import _FastRecordIter

    rng = np.random.RandomState(0)
    rec_path = str(tmp_path / "fast.rec")
    idx_path = str(tmp_path / "fast.idx")
    rec = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    raw = {}
    import io as _io
    for i in range(8):
        arr = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")  # lossless
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        rec.write_idx(i, recordio.pack(header, buf.getvalue()))
        raw[i] = arr
    rec.close()

    it = mx.io.ImageRecordIter(path_imgrec=rec_path,
                               data_shape=(3, 32, 32), batch_size=4,
                               preprocess_threads=2, mean_r=10.0,
                               mean_g=20.0, mean_b=30.0)
    assert isinstance(it._prefetch, _FastRecordIter)  # pool path selected
    seen = 0
    labels_seen = []
    for batch in it:
        data = batch.data[0].asnumpy()
        labels = batch.label[0].asnumpy()
        assert data.shape == (4, 3, 32, 32)
        for b in range(4 - (batch.pad or 0)):
            lab = int(labels[b])
            # identify the source image by its label cycle is ambiguous;
            # instead check against the set of normalized sources
            cand = [(raw[i].astype(np.float32) -
                     np.array([10.0, 20.0, 30.0], np.float32))
                    .transpose(2, 0, 1) for i in raw
                    if int(raw_label(i)) == lab]
            assert any(np.allclose(data[b], c) for c in cand)
            labels_seen.append(lab)
        seen += 4 - (batch.pad or 0)
    assert seen == 8
    it.close()


def raw_label(i):
    return i % 3


def test_image_worker_cv2_pil_parity():
    """The cv2 fast decode path and the PIL fallback produce identical
    crop geometry and near-identical pixels (resize interpolation may
    differ by a few intensity levels)."""
    import io as _io
    import numpy as np
    import pytest
    from PIL import Image
    from mxtpu import _image_worker as w

    pytest.importorskip("cv2")
    # smooth content: interpolation backends agree closely on gradients
    # but diverge on per-pixel noise (different sample alignment)
    yy, xx = np.mgrid[0:48, 0:64]
    arr = np.stack([(yy * 4) % 256, (xx * 3) % 256,
                    ((yy + xx) * 2) % 256], axis=-1).astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    raw = buf.getvalue()

    # deterministic config: resize shorter side then center crop
    cfg = {"crop_h": 24, "crop_w": 24, "resize": 32, "rand_crop": False,
           "rand_mirror": False}
    w.init_worker(dict(cfg))
    out_cv, _ = w.decode_augment((7, raw, 0.0))
    w.init_worker(dict(cfg, force_pil=True))
    out_pil, _ = w.decode_augment((7, raw, 0.0))
    assert out_cv.shape == out_pil.shape == (24, 24, 3)
    diff = np.abs(out_cv.astype(np.int32) - out_pil.astype(np.int32))
    assert diff.mean() < 8.0, diff.mean()

    # no-resize path is decode-exact (lossless PNG): bitwise equal
    cfg2 = {"crop_h": 48, "crop_w": 64, "rand_crop": False,
            "rand_mirror": False}
    w.init_worker(dict(cfg2))
    exact_cv, _ = w.decode_augment((3, raw, 0.0))
    w.init_worker(dict(cfg2, force_pil=True))
    exact_pil, _ = w.decode_augment((3, raw, 0.0))
    np.testing.assert_array_equal(exact_cv, exact_pil)
    np.testing.assert_array_equal(exact_cv, arr)


def test_image_worker_gif_falls_back_to_pil():
    """cv2 cannot decode GIF; the worker must fall back per record
    instead of failing the pool (scraped-dataset stragglers)."""
    import io as _io
    import numpy as np
    import pytest
    from PIL import Image
    from mxtpu import _image_worker as w

    pytest.importorskip("cv2")
    arr = (np.arange(32 * 32 * 3).reshape(32, 32, 3) % 256).astype(np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).convert("P").save(buf, format="GIF")
    w.init_worker({"crop_h": 32, "crop_w": 32, "rand_crop": False,
                   "rand_mirror": False})
    out, _ = w.decode_augment((0, buf.getvalue(), 0.0))
    w.init_worker({})
    assert out.shape == (32, 32, 3)


def test_rec2idx_tool(tmp_path):
    """tools/rec2idx.py regenerates a random-access index for a bare .rec
    (reference tools/rec2idx.py)."""
    import subprocess
    import sys
    from mxtpu import recordio

    rec = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(rec, "w")
    payloads = [b"payload-%d" % i for i in range(7)]
    for p in payloads:
        w.write(p)
    w.close()
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "rec2idx.py"), rec],
        capture_output=True, text=True, timeout=120)
    assert out.returncode == 0, out.stderr
    r = recordio.MXIndexedRecordIO(str(tmp_path / "t.idx"), rec, "r")
    assert r.read_idx(0) == payloads[0]
    assert r.read_idx(6) == payloads[6]
    assert sorted(r.keys) == list(range(7))


def test_prefetching_iter_rename_and_multi():
    """PrefetchingIter over two iterators with renamed descriptors: the
    combinator concatenates data/label lists and rewrites DataDesc names
    (reference io.py PrefetchingIter rename_data/rename_label)."""
    x1 = np.arange(24, dtype="f").reshape(12, 2)
    x2 = np.arange(24, 36, dtype="f").reshape(12, 1)
    y = np.arange(12, dtype="f")
    it1 = mx.io.NDArrayIter(x1, y, batch_size=4, data_name="a",
                            label_name="la")
    it2 = mx.io.NDArrayIter(x2, None, batch_size=4, data_name="b")
    pre = mx.io.PrefetchingIter(
        [it1, it2], rename_data=[{"a": "left"}, {"b": "right"}],
        rename_label=[{"la": "y"}, {}])
    names = [d.name for d in pre.provide_data]
    assert names == ["left", "right"], names
    assert [d.name for d in pre.provide_label] == ["y"]
    batches = list(pre)
    assert len(batches) == 3
    assert [a.shape for a in batches[0].data] == [(4, 2), (4, 1)]
    got = np.concatenate([b.data[0].asnumpy() for b in batches])
    np.testing.assert_allclose(got, x1)
    pre.reset()
    assert len(list(pre)) == 3


class _FetchTracker(mx.io.DataIter):
    """Source iterator that flags the moment each batch fetch BEGINS —
    the event-ordering probe for the prefetch-overlap test."""

    def __init__(self, n=4):
        super().__init__(batch_size=2)
        self.n = n
        self.i = 0
        import threading
        self.fetch_started = [threading.Event() for _ in range(n + 1)]

    @property
    def provide_data(self):
        return [mx.io.DataDesc("data", (2, 3))]

    @property
    def provide_label(self):
        return [mx.io.DataDesc("label", (2,))]

    def reset(self):
        self.i = 0

    def next(self):
        idx = self.i
        self.fetch_started[min(idx, self.n)].set()
        if idx >= self.n:
            raise StopIteration
        self.i += 1
        return mx.io.DataBatch(
            data=[mx.nd.ones((2, 3)) * idx],
            label=[mx.nd.ones((2,)) * idx], pad=0)


def test_prefetching_iter_really_overlaps():
    """The ISSUE 2 satellite: prove the worker thread fetches batch N+1
    WHILE the consumer still holds batch N — pure event ordering, no
    timing. The consumer never calls next() between the two asserts, so
    only background prefetch can start fetch N+1."""
    src = _FetchTracker(n=4)
    it = mx.io.PrefetchingIter(src)
    # construction alone must kick off fetch 0 (double buffering primes)
    assert src.fetch_started[0].wait(5), "batch 0 never prefetched"
    assert it.iter_next()                 # consumer takes batch 0...
    held = it.current_batch
    np.testing.assert_allclose(held.data[0].asnumpy(), np.zeros((2, 3)))
    # ...and holds it: batch 1's fetch must begin with NO further call
    assert src.fetch_started[1].wait(5), \
        "no overlap: batch 1 not prefetched while batch 0 is held"
    # the held batch is untouched by the background fetch
    np.testing.assert_allclose(held.data[0].asnumpy(), np.zeros((2, 3)))
    rest = []
    while it.iter_next():
        rest.append(float(it.current_batch.data[0].asnumpy()[0, 0]))
    assert rest == [1.0, 2.0, 3.0]        # in order, none dropped


def test_prefetching_iter_reset_mid_epoch():
    """reset() while the worker holds a prefetched batch must neither
    deadlock nor drop: the next epoch restarts at batch 0 and yields
    the full count again."""
    src = _FetchTracker(n=4)
    it = mx.io.PrefetchingIter(src)
    assert it.iter_next()                 # consume 2 of 4...
    assert it.iter_next()
    it.reset()                            # ...reset with one in flight
    vals = []
    while it.iter_next():
        vals.append(float(it.current_batch.data[0].asnumpy()[0, 0]))
    assert vals == [0.0, 1.0, 2.0, 3.0], \
        "mid-epoch reset dropped or reordered a batch"
    it.reset()                            # reset at epoch END also clean
    assert sum(1 for _ in it) == 4


# ---------------------------------------------------------------------------
# elastic-resume iterator state (ISSUE 3): state_dict/load_state_dict
# round-trips for mid-epoch positions, including restores into FRESH
# process-like objects with prefetch threads restarted cleanly
# ---------------------------------------------------------------------------

def _epoch_data(n=20, width=2):
    X = np.arange(n * width, dtype=np.float32).reshape(n, width)
    Y = np.arange(n, dtype=np.float32)
    return X, Y


def test_ndarray_iter_state_roundtrip_mid_epoch():
    X, Y = _epoch_data()
    it = mx.io.NDArrayIter(X, Y, batch_size=4)
    [it.next() for _ in range(2)]
    state = it.state_dict()
    assert state["cursor"] == 4
    want = [it.next().data[0].asnumpy() for _ in range(3)]
    it2 = mx.io.NDArrayIter(X, Y, batch_size=4)
    it2.load_state_dict(state)
    got = [it2.next().data[0].asnumpy() for _ in range(3)]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    # epoch boundary then next epoch behaves normally
    with pytest.raises(StopIteration):
        it2.next()
    it2.reset()
    assert it2.next().pad == 0


def test_ndarray_iter_state_restores_shuffle_order():
    """The saved run's epoch ORDER must survive a restore into a fresh,
    differently-shuffled iterator — the permutation rides the state, so
    no sample is skipped or double-trained mid-epoch."""
    X, Y = _epoch_data()
    np.random.seed(10)
    it = mx.io.NDArrayIter(X, Y, batch_size=4, shuffle=True)
    [it.next() for _ in range(2)]
    state = it.state_dict()
    want = [it.next().data[0].asnumpy() for _ in range(3)]
    np.random.seed(99)                    # a fresh process shuffles anew
    it2 = mx.io.NDArrayIter(X, Y, batch_size=4, shuffle=True)
    it2.load_state_dict(state)
    got = [it2.next().data[0].asnumpy() for _ in range(3)]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    # data/label rows stay aligned through the re-gather
    it3 = mx.io.NDArrayIter(X, Y, batch_size=4, shuffle=True)
    it3.load_state_dict(state)
    b = it3.next()
    np.testing.assert_array_equal(b.data[0].asnumpy()[:, 0] // 2,
                                  b.label[0].asnumpy())
    # mismatched batch size is refused loudly, not silently misaligned
    it4 = mx.io.NDArrayIter(X, Y, batch_size=5, shuffle=True)
    with pytest.raises(ValueError, match="batch_size"):
        it4.load_state_dict(state)


def test_resize_iter_state_roundtrip_across_wrap():
    """ResizeIter longer than the wrapped epoch: the wrap-around
    position (inner epoch + cursor) must ride the state."""
    X, Y = _epoch_data()                  # 5 inner batches of 4
    it = mx.io.ResizeIter(mx.io.NDArrayIter(X, Y, batch_size=4), 8)
    [it.next() for _ in range(6)]         # 1 past the inner wrap
    state = it.state_dict()
    assert state["cur"] == 6
    want = [it.next().data[0].asnumpy() for _ in range(2)]
    it2 = mx.io.ResizeIter(mx.io.NDArrayIter(X, Y, batch_size=4), 8)
    it2.load_state_dict(state)
    got = [it2.next().data[0].asnumpy() for _ in range(2)]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    with pytest.raises(StopIteration):
        it2.next()                        # resized epoch ends on time


def test_prefetching_iter_state_is_delivered_position():
    """The prefetch thread runs AHEAD of the consumer; state_dict must
    report the position after the last batch the consumer actually saw,
    not the position the worker ran ahead to — otherwise a restore
    skips the prefetched-but-unconsumed batch."""
    X, Y = _epoch_data()
    it = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=4))
    b1 = it.next().data[0].asnumpy()
    state = it.state_dict()
    assert state["delivered"] == 1
    # the inner snapshot rides at the delivered position (batch 1 →
    # cursor 0), even though the worker has already fetched batch 2
    # (cursor 4) — the run-ahead must not leak into the state
    assert state["iters"][0]["cursor"] == 0
    b2 = it.next().data[0].asnumpy()
    assert not np.array_equal(b1, b2)


def test_prefetching_iter_state_restore_into_fresh_object():
    """Restore into a brand-new PrefetchingIter (fresh prefetch threads
    already running, one batch eagerly prefetched from position 0):
    the wrapped iterators rewind to the saved cursor, the stale
    prefetched batch is dropped, and the stream continues exactly
    where the saved run left off — then resets cleanly for the next
    epoch (threads survive the restore)."""
    X, Y = _epoch_data()
    it = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=4))
    [it.next() for _ in range(2)]
    state = it.state_dict()
    want = [it.next().data[0].asnumpy() for _ in range(3)]

    it2 = mx.io.PrefetchingIter(mx.io.NDArrayIter(X, Y, batch_size=4))
    it2.load_state_dict(state)
    got = [it2.next().data[0].asnumpy() for _ in range(3)]
    for a, b in zip(want, got):
        np.testing.assert_array_equal(a, b)
    assert not it2.iter_next()            # epoch ends at the right spot
    it2.reset()                           # threads restart cleanly...
    count = 0
    while it2.iter_next():
        count += 1
    assert count == 5                     # ...and the next epoch is full


def test_prefetching_iter_state_stateless_inner_fast_forwards():
    """A wrapped iterator with no capturable state ({}): restore resets
    it and fast-forwards through the delivered count — slower, but no
    batch is skipped or repeated."""

    class Counting(mx.io.DataIter):       # stateless: base state_dict
        def __init__(self):
            super().__init__(batch_size=2)
            self.provide_data = [("data", (2, 3))]
            self.provide_label = [("label", (2,))]
            self.i = 0

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= 6:
                raise StopIteration
            b = mx.io.DataBatch(
                [mx.nd.array(np.full((2, 3), self.i, "float32"))],
                [mx.nd.array(np.zeros(2, "float32"))], pad=0)
            self.i += 1
            return b

    it = mx.io.PrefetchingIter(Counting())
    [it.next() for _ in range(3)]
    state = it.state_dict()
    assert state["iters"] == [{}]
    it2 = mx.io.PrefetchingIter(Counting())
    it2.load_state_dict(state)
    vals = [float(it2.next().data[0].asnumpy()[0, 0]) for _ in range(3)]
    assert vals == [3.0, 4.0, 5.0]


def test_prefetching_iter_duck_types_state_dict():
    """An iterator outside the DataIter hierarchy (no state_dict at
    all — e.g. image.ImageIter before it grew the contract) still
    prefetches; its snapshot rides as None and restore falls back to
    reset + fast-forward. Regression: the worker thread used to die on
    the missing attribute and strand the consumer in _wait_all."""

    class Bare:                           # deliberately NOT a DataIter
        batch_size = 2
        provide_data = [("data", (2, 3))]
        provide_label = [("label", (2,))]

        def __init__(self):
            self.i = 0

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= 6:
                raise StopIteration
            b = mx.io.DataBatch(
                [mx.nd.array(np.full((2, 3), self.i, "float32"))],
                [mx.nd.array(np.zeros(2, "float32"))], pad=0)
            self.i += 1
            return b

    it = mx.io.PrefetchingIter(Bare())
    [it.next() for _ in range(3)]
    state = it.state_dict()
    assert state["iters"] == [None]
    it2 = mx.io.PrefetchingIter(Bare())
    it2.load_state_dict(state)
    vals = [float(it2.next().data[0].asnumpy()[0, 0]) for _ in range(3)]
    assert vals == [3.0, 4.0, 5.0]


def test_prefetching_iter_propagates_worker_error():
    """A wrapped iterator that raises mid-stream: the error surfaces
    from next() on the consumer thread in bounded time instead of
    hanging the pipeline (the dead-worker hang this guards against is
    exactly what a respawned worker must never inherit)."""

    class Exploding(mx.io.DataIter):
        def __init__(self):
            super().__init__(batch_size=2)
            self.provide_data = [("data", (2, 3))]
            self.provide_label = [("label", (2,))]
            self.i = 0

        def reset(self):
            self.i = 0

        def next(self):
            if self.i >= 2:
                raise RuntimeError("disk on fire")
            b = mx.io.DataBatch(
                [mx.nd.array(np.full((2, 3), self.i, "float32"))],
                [mx.nd.array(np.zeros(2, "float32"))], pad=0)
            self.i += 1
            return b

    it = mx.io.PrefetchingIter(Exploding())
    [it.next() for _ in range(2)]
    with pytest.raises(RuntimeError, match="disk on fire"):
        it.next()


def test_recordio_sigkilled_writer_torn_tail(tmp_path):
    """A writer SIGKILL'd mid-record leaves a torn tail; the reader
    must hand back every complete record and then a clean EOF (None),
    never a partial payload or an exception — the recordio half of the
    streaming durability contract (docs/streaming.md)."""
    import subprocess
    import sys

    rec = str(tmp_path / "torn.rec")
    code = (
        "import os, sys\n"
        "from mxtpu import recordio\n"
        "w = recordio.MXRecordIO(%r, 'w')\n"
        "for i in range(5):\n"
        "    w.write(bytes([i]) * 100)\n"
        "w.handle.flush(); os.fsync(w.handle.fileno())\n"
        "w.write(b'x' * 100000)\n"
        "w.handle.flush()\n"
        "print('ready', flush=True)\n"
        "import time\n"
        "time.sleep(30)\n" % rec)
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE,
                            env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.stdout.readline().split()[0] == b"ready"
    proc.kill()
    proc.wait()
    # truncate mid-frame to model the OS losing the un-synced suffix of
    # the final record (kill alone may leave it whole in the page cache)
    size = os.path.getsize(rec)
    with open(rec, "r+b") as f:
        f.truncate(size - 17)
    r = recordio_mod().MXRecordIO(rec, "r")
    got = []
    while True:
        data = r.read()
        if data is None:
            break
        got.append(data)
    assert [len(d) for d in got][:5] == [100] * 5
    assert got[:5] == [bytes([i]) * 100 for i in range(5)]
    # EOF verdict is stable: re-reads keep reporting "nothing more"
    assert r.read() is None
    r.close()


def recordio_mod():
    from mxtpu import recordio
    return recordio


def test_recordio_close_fsyncs(tmp_path):
    """close() on a writer is a durability point: the OS file must hold
    every record before close() returns (observable proxy: a reopened
    reader sees them all, and the handle was flushed+fsynced)."""
    rec = str(tmp_path / "sync.rec")
    w = recordio_mod().MXRecordIO(rec, "w")
    for i in range(3):
        w.write(b"abc%d" % i)
    w.close()
    r = recordio_mod().MXRecordIO(rec, "r")
    assert [r.read() for i in range(3)] == [b"abc%d" % i for i in range(3)]
    assert r.read() is None
    r.close()
