"""Autograd tests (modelled on tests/python/unittest/test_autograd.py)."""
import numpy as np
import pytest

import mxtpu as mx
import mxtpu.ndarray as nd
import mxtpu.autograd as ag


def test_simple_grad():
    x = nd.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with ag.record():
        y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.asnumpy(), 2 * x.asnumpy())


def test_chain_and_broadcast():
    x = nd.array(np.random.randn(3, 4).astype("f"))
    w = nd.array(np.random.randn(5, 4).astype("f"))
    x.attach_grad()
    w.attach_grad()
    with ag.record():
        y = nd.FullyConnected(data=x, weight=w, num_hidden=5, no_bias=True)
        z = nd.relu(y).sum()
    z.backward()
    mask = (x.asnumpy() @ w.asnumpy().T) > 0
    expected_w = mask.T.astype("f") @ x.asnumpy()
    assert np.allclose(w.grad.asnumpy(), expected_w, atol=1e-5)


def test_grad_req_add():
    x = nd.array([1.0, 2.0])
    x.attach_grad(grad_req="add")
    for _ in range(3):
        with ag.record():
            y = (x * x).sum()
        y.backward()
    assert np.allclose(x.grad.asnumpy(), 3 * 2 * x.asnumpy())


def test_not_recording_outside_scope():
    x = nd.array([1.0])
    x.attach_grad()
    y = x * 2  # not recorded
    with ag.record():
        z = x * 3
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [3.0])


def test_train_mode_dropout():
    x = nd.ones((100, 100))
    with ag.record(train_mode=True):
        y = nd.Dropout(x, p=0.5)
    frac = (y.asnumpy() == 0).mean()
    assert 0.3 < frac < 0.7
    with ag.record(train_mode=False):
        y2 = nd.Dropout(x, p=0.5)
    assert np.allclose(y2.asnumpy(), x.asnumpy())
    assert ag.is_recording() is False


def test_dropout_backward_same_mask():
    x = nd.ones((50, 50))
    x.attach_grad()
    with ag.record():
        y = nd.Dropout(x, p=0.5)
    y.backward()
    # grad is 2.0 where kept, 0 where dropped — matches forward mask
    yv = y.asnumpy()
    g = x.grad.asnumpy()
    assert np.allclose((g > 0), (yv > 0))


def test_detach_blocks_grad():
    x = nd.array([2.0])
    x.attach_grad()
    with ag.record():
        y = x * 3
        z = y.detach() * 2
    z.backward()
    assert np.allclose(x.grad.asnumpy(), [0.0])


def test_autograd_grad_function():
    x = nd.array([1.0, 2.0])
    with ag.record():
        y = (x * x * x).sum()
    g = ag.grad(y, x)
    assert np.allclose(g.asnumpy(), 3 * x.asnumpy() ** 2)


def test_custom_function():
    class Sigmoid(ag.Function):
        def forward(self, x):
            y = nd.sigmoid(x)
            self.save_for_backward(y)
            return y

        def backward(self, dy):
            y, = self.saved_tensors
            return dy * y * (1 - y)

    x = nd.array([0.5, -1.0])
    x.attach_grad()
    f = Sigmoid()
    with ag.record():
        y = f(x)
    y.backward()
    s = 1 / (1 + np.exp(-x.asnumpy()))
    assert np.allclose(x.grad.asnumpy(), s * (1 - s), atol=1e-6)


def test_softmax_output_ce_gradient():
    # SoftmaxOutput backward must be softmax - onehot (ignoring head grad)
    x = nd.array(np.random.randn(4, 3).astype("f"))
    label = nd.array([0.0, 1.0, 2.0, 1.0])
    x.attach_grad()
    with ag.record():
        out = nd.SoftmaxOutput(data=x, label=label)
    out.backward()
    p = np.exp(x.asnumpy()) / np.exp(x.asnumpy()).sum(1, keepdims=True)
    onehot = np.eye(3)[label.asnumpy().astype(int)]
    assert np.allclose(x.grad.asnumpy(), p - onehot, atol=1e-5)


def test_get_symbol_exports_tape():
    """autograd.get_symbol turns the recorded computation into a Symbol
    (reference autograd.py:447 get_symbol / MXAutogradGetSymbol)."""
    import mxtpu as mx
    x = nd.array(np.array([1.0, -2.0, 3.0], np.float32))
    w = nd.array(np.array([0.5, 0.5, 0.5], np.float32))
    with ag.record():
        y = nd.relu(x * w) + 2.0
    s = ag.get_symbol(y)
    args = s.list_arguments()
    assert len(args) == 2
    # evaluating the exported graph reproduces the recorded computation
    ex = s.bind(mx.cpu(), {args[0]: x.copy(), args[1]: w.copy()})
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(
        out, np.maximum(x.asnumpy() * w.asnumpy(), 0) + 2.0)
    # multi-output ops export with the right output picked
    d = nd.array(np.array([[3.0, 1.0, 2.0]], np.float32))
    with ag.record():
        vals = nd.topk(d, k=2, ret_typ="value")
    s2 = ag.get_symbol(vals)
    ex2 = s2.bind(mx.cpu(), {s2.list_arguments()[0]: d.copy()})
    np.testing.assert_allclose(ex2.forward()[0].asnumpy(), [[3.0, 2.0]])
