"""Behavior suite for the crash-safe streaming data plane (ISSUE 18:
mxtpu/streaming/* + the kvstore stream_push/stream_offsets plane).

Deterministic throughout: faults come from the injection harness on
exact schedules, the kvstore servers are loopback threads, and batch
composition is a pure function of log content — which is exactly the
property the exactly-once drills rely on (a respawn's replayed frames
are bit-identical to the dead consumer's, so watermark refusal is
exact)."""
import os
import threading

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault
from mxtpu import kvstore_async as ka
from mxtpu.kvstore_async import ParameterServer, stream_origin
from mxtpu.streaming import (ContinualTrainer, EmitLog, RecordCorrupt,
                             StreamingIter, StreamReader, StreamWriter,
                             decode_record, encode_record)
from mxtpu.streaming import log as slog


@pytest.fixture(autouse=True)
def _fast_failure_knobs(monkeypatch):
    monkeypatch.setattr(ka, "_RETRIES", 2)
    monkeypatch.setattr(ka, "_BACKOFF", 0.01)
    monkeypatch.setattr(ka, "_BACKOFF_MAX", 0.05)
    monkeypatch.setattr(ka, "_RECONNECT_TIMEOUT", 0.2)
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    monkeypatch.setattr(ka, "_LOCAL_ON", False)
    fault.uninstall()
    yield
    fault.uninstall()


def _store(monkeypatch, addrs, rank=0, nproc=1):
    monkeypatch.setenv("MXTPU_PS_ADDRS", addrs)
    monkeypatch.setenv("MXTPU_PROC_ID", str(rank))
    monkeypatch.setenv("MXTPU_NUM_PROCS", str(nproc))
    return mx.kv.create("dist_async")


def _sum_grad_fn(params, records):
    tot = np.zeros((2,), np.float32)
    for _rid, feats, _label in records:
        tot += feats[0]
    return {"acc": tot}


def _write_records(root, n, shard=0, start=0, **kw):
    w = StreamWriter(root, shard=shard, **kw)
    for i in range(start, start + n):
        w.append(encode_record("r%d" % i,
                               (np.full((2,), i, np.float32),),
                               np.float32(i)))
    w.close()


# ---------------------------------------------------------------------------
# the durable log
# ---------------------------------------------------------------------------

def test_log_roundtrip_and_roll(tmp_path):
    """Records roundtrip bit-exact; the writer rolls segments at the
    configured bound and seals each full one (``.open`` -> ``.log``
    rename), so tailers see sealed prefixes plus one growing tail."""
    root = str(tmp_path)
    w = StreamWriter(root, shard=0, segment_bytes_=256)
    payloads = [bytes([i]) * 100 for i in range(7)]
    for p in payloads:
        w.append(p)
    segs = slog.list_segments(root, 0)
    assert len(segs) >= 2 and segs[-1][2] is False    # open tail
    assert all(sealed for _, _, sealed in segs[:-1])
    r = StreamReader(root, 0)
    got = []
    for seq, _path, _sealed in segs:
        records, _end, _ = r.read(seq)
        got.extend(p for p, _ in records)
    assert got == payloads
    w.close()
    assert all(sealed for _, _, sealed in slog.list_segments(root, 0))


def test_log_torn_tail_reads_as_not_yet_written(tmp_path):
    """A half-written record at the tail of the OPEN segment is "not
    yet written": the reader returns every complete record and stops —
    no exception, and a later completed write appends past it."""
    root = str(tmp_path)
    w = StreamWriter(root, shard=0)
    w.append(b"alpha")
    seg, _off = w.append(b"beta")
    # simulate the writer dying mid-append: raw partial frame
    path = os.path.join(root, "shard-0", "seg-%08d.open" % seg)
    frame = slog.frame(b"gamma-that-was-torn")
    with open(path, "ab") as f:
        f.write(frame[:len(frame) - 3])
    records, end, sealed = StreamReader(root, 0).read(seg)
    assert [p for p, _ in records] == [b"alpha", b"beta"]
    assert sealed is False
    # re-read from the committed cursor: same verdict, still no error
    again, _end2, _ = StreamReader(root, 0).read(seg, offset=end)
    assert again == []


def test_log_writer_recovery_truncates_torn_tail(tmp_path):
    """A new writer over a crashed writer's shard truncates the torn
    suffix (counted), seals the complete prefix, and claims the next
    segment — the records before the tear stay durable and readable."""
    root = str(tmp_path)
    w = StreamWriter(root, shard=0)
    seg, _ = w.append(b"kept")
    path = os.path.join(root, "shard-0", "seg-%08d.open" % seg)
    w._fh.close()                     # drop the handle, keep the file
    frame = slog.frame(b"torn")
    with open(path, "ab") as f:
        f.write(frame[:4])
    w2 = StreamWriter(root, shard=0)
    segs = slog.list_segments(root, 0)
    assert segs[0][0] == seg and segs[0][2] is True   # sealed prefix
    records, _end, sealed = StreamReader(root, 0).read(seg)
    assert [p for p, _ in records] == [b"kept"] and sealed
    nseg, _ = w2.append(b"after-recovery")
    assert nseg == seg + 1
    w2.close()


def test_log_sealed_corruption_is_an_error(tmp_path):
    """The torn-tail tolerance is ONLY for the open tail: a CRC failure
    inside a sealed segment is real corruption and must raise."""
    root = str(tmp_path)
    _write_records(root, 3)
    seq, path, sealed = slog.list_segments(root, 0)[0]
    assert sealed
    with open(path, "r+b") as f:
        f.seek(20)
        b = f.read(1)
        f.seek(20)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(RecordCorrupt):
        StreamReader(root, 0).read(seq)


def test_log_drop_mid_append_no_torn_record(tmp_path):
    """Fault row — drop @ stream.append: the record is shed BEFORE any
    byte hits the file (counted), so no torn record is ever visible to
    a tailer."""
    root = str(tmp_path)
    w = StreamWriter(root, shard=0)
    w.append(b"before")
    with fault.inject("kind=drop,point=stream.append,nth=1") as inj:
        assert w.append(b"dropped") is None
        assert inj.stats()[0][4] == 1
    seg, _ = w.append(b"after")
    records, _end, _ = StreamReader(root, 0).read(seg)
    assert [p for p, _ in records] == [b"before", b"after"]
    w.close()


def test_log_truncate_mid_append_then_recovery(tmp_path):
    """Fault row — truncate @ stream.append: the injected mid-frame
    crash leaves a torn tail that tailers skip and the next writer
    truncates away; every acknowledged record survives."""
    root = str(tmp_path)
    w = StreamWriter(root, shard=0)
    seg, _ = w.append(b"acked")
    with fault.inject("kind=truncate,point=stream.append,nth=1"):
        with pytest.raises(ConnectionError):
            w.append(b"torn-by-crash")
    records, _end, _ = StreamReader(root, 0).read(seg)
    assert [p for p, _ in records] == [b"acked"]
    w2 = StreamWriter(root, shard=0)    # recovery seals the prefix
    records, _end, sealed = StreamReader(root, 0).read(seg)
    assert [p for p, _ in records] == [b"acked"] and sealed
    w2.close()


# ---------------------------------------------------------------------------
# the emit codec + bounded queue
# ---------------------------------------------------------------------------

def test_emit_codec_roundtrip():
    rid = "origin-1:42"
    feats = (np.arange(6, dtype=np.float32).reshape(2, 3),
             np.array([7], dtype=np.int64))
    label = np.float32(3.5)
    rid2, feats2, label2 = decode_record(
        encode_record(rid, feats, label))
    assert rid2 == rid
    assert len(feats2) == 2
    np.testing.assert_array_equal(feats2[0], feats[0])
    np.testing.assert_array_equal(feats2[1], feats[1])
    assert feats2[0].dtype == np.float32 and feats2[1].dtype == np.int64
    np.testing.assert_array_equal(label2, label)
    # label-less (outcome still pending) encodes too
    _rid3, _f3, label3 = decode_record(encode_record(rid, feats))
    assert label3 is None


def test_emit_join_and_shed(tmp_path):
    """note+outcome joins into the log; an unjoined outcome is a
    counted orphan; queue overflow sheds with a counter instead of
    blocking; the join table is bounded with eviction."""
    w = StreamWriter(str(tmp_path), shard=0)
    em = EmitLog(w, queue_max=64, join_max_=2)
    x = np.ones((2,), np.float32)
    em.note("a", (x,), ("ok", {}))
    assert em.outcome("a", np.float32(1.0)) is True
    assert em.outcome("never-noted", np.float32(0.0)) is False
    em.note("err", (x,), ("err", "boom"))     # non-ok: not joinable
    assert em.outcome("err", np.float32(0.0)) is False
    # bounded join table: 3 notes into a 2-slot table evicts oldest
    em.note("r1", (x,))
    em.note("r2", (x,))
    em.note("r3", (x,))
    assert em.outcome("r1", np.float32(0.0)) is False   # evicted
    c = em.counters()
    assert c["joined"] == 1 and c["orphans"] == 3
    assert c["join_evicted"] >= 1
    em.close()
    records, _end, sealed = StreamReader(str(tmp_path), 0).read(0)
    assert sealed and len(records) == 1
    rid, feats, label = decode_record(records[0][0])
    assert rid == "a" and float(np.ravel(label)[0]) == 1.0


def test_emit_queue_overflow_sheds_not_blocks(tmp_path):
    """With the drain thread wedged, outcomes beyond the queue bound
    return False immediately (counted shed) — serving never blocks on
    the log."""
    w = StreamWriter(str(tmp_path), shard=0)
    gate = threading.Event()
    real_append = w.append
    w.append = lambda payload: (gate.wait(10), real_append(payload))[1]
    em = EmitLog(w, queue_max=2, join_max_=64)
    x = np.ones((1,), np.float32)
    for i in range(5):
        em.note("r%d" % i, (x,))
    results = [em.outcome("r%d" % i, np.float32(i)) for i in range(5)]
    # 1 in-flight with the drain thread + 2 queued; the rest shed
    assert results.count(False) >= 2
    assert em.counters()["dropped"] >= 2
    gate.set()
    em.close()


# ---------------------------------------------------------------------------
# exactly-once tailing through the kvstore
# ---------------------------------------------------------------------------

def test_streaming_iter_exactly_once_and_replay_refused(monkeypatch,
                                                        tmp_path):
    """The core tentpole drill in-process: consume a sealed stream via
    leases; totals are exact; a FRESH client re-tailing the same group
    consumes nothing (committed-final offsets) and a replayed frame is
    refused wholesale by the (origin, seq) watermark."""
    root = str(tmp_path)
    _write_records(root, 10)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, root, group="g", batch_size=4,
                           idle_timeout=0.3, poll=0.01)
        tr = ContinualTrainer(kv, it,
                              {"acc": np.zeros((2,), np.float32)},
                              _sum_grad_fn)
        assert tr.run() == 3              # 4 + 4 + 2(final)
        np.testing.assert_allclose(tr.params["acc"], 45.0)
        offs = kv.stream_offsets("g")
        assert offs[(0, 0)][1] is True    # committed final

        # respawned consumer: nothing to consume, totals unchanged
        kv2 = _store(monkeypatch, srv.address)
        it2 = StreamingIter(kv2, root, group="g", batch_size=4,
                            idle_timeout=0.3, poll=0.01)
        tr2 = ContinualTrainer(kv2, it2,
                               {"acc": np.zeros((2,), np.float32)},
                               _sum_grad_fn)
        assert tr2.run() == 0
        np.testing.assert_allclose(tr2.params["acc"], 45.0)

        # a manually replayed frame (the respawn's in-flight double)
        # is refused as a whole: grads AND commit
        assert kv.stream_push(
            [("acc", np.full((2,), 99.0, np.float32))],
            ("g", 0, 0, offs[(0, 0)][0], True)) is True
        out = mx.nd.zeros((2,))
        kv.pull("acc", out=out)
        np.testing.assert_allclose(out.asnumpy(), 45.0)
        assert srv._stream_dup >= 1
        kv2.close()
    finally:
        kv.close()
        srv.stop()


def test_stream_offsets_survive_server_snapshot(monkeypatch, tmp_path):
    """The consumption cursor is part of the server's durable state:
    a snapshot-restored server still refuses the respawned consumer's
    replay (exactly-once across BOTH trainer and server crashes)."""
    root = str(tmp_path / "stream")
    snap = str(tmp_path / "snaps")
    _write_records(root, 4)
    srv = ParameterServer(snapshot_dir=snap, snapshot_every=1).start()
    port = int(srv.address.split(":")[1])
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, root, group="g", batch_size=4,
                           idle_timeout=0.3, poll=0.01)
        tr = ContinualTrainer(kv, it,
                              {"acc": np.zeros((2,), np.float32)},
                              _sum_grad_fn)
        assert tr.run() == 1
        srv.snapshot()
        kv.close()
        srv.stop()
        srv2 = ParameterServer(port=port, snapshot_dir=snap).start()
        try:
            kv2 = _store(monkeypatch, srv2.address)
            offs = kv2.stream_offsets("g")
            assert offs[(0, 0)][1] is True
            it2 = StreamingIter(kv2, root, group="g", batch_size=4,
                                idle_timeout=0.3, poll=0.01)
            tr2 = ContinualTrainer(kv2, it2,
                                   {"acc": np.zeros((2,), np.float32)},
                                   _sum_grad_fn)
            assert tr2.run() == 0
            np.testing.assert_allclose(tr2.params["acc"], 6.0)
            kv2.close()
        finally:
            srv2.stop()
    finally:
        srv.stop()


def test_lease_excludes_second_consumer(monkeypatch, tmp_path):
    """Segment leases are exclusive: while one consumer holds a
    segment, a second gets "wait"; after the final commit retires the
    lease the verdict from the offsets is final and the segment is
    never re-consumed."""
    root = str(tmp_path)
    _write_records(root, 2)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    kv2 = _store(monkeypatch, srv.address)
    try:
        lease = stream_origin("g", 0, 0)
        assert kv.stream_lease(lease) == "owned"
        assert kv2.stream_lease(lease) == "wait"
        # holder finishes the segment through the commit plane
        _write = kv.stream_push([], ("g", 0, 0, 9999, True))
        offs = kv2.stream_offsets("g")
        assert offs[(0, 0)] == (9999, True)
        it2 = StreamingIter(kv2, root, group="g", batch_size=4,
                            idle_timeout=0.2, poll=0.01)
        assert it2.iter_next() is False   # final: nothing to lease
    finally:
        kv.close()
        kv2.close()
        srv.stop()


def test_sever_mid_tail_requeues_lease_exactly_once(monkeypatch,
                                                    tmp_path):
    """Fault row — sever @ stream.tail: consumer A dies mid-tail after
    committing one batch; its departure (bye) requeues the lease and
    consumer B resumes AT THE COMMITTED OFFSET — per-record totals
    land exactly once."""
    root = str(tmp_path)
    _write_records(root, 8)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, root, group="g", batch_size=4,
                           idle_timeout=0.3, poll=0.01)
        tr = ContinualTrainer(kv, it,
                              {"acc": np.zeros((2,), np.float32)},
                              _sum_grad_fn)
        # A leases the segment, then its tail read is severed — it
        # dies holding the lease, having committed nothing
        with fault.inject("kind=sever,point=stream.tail,nth=1") as inj:
            with pytest.raises(ConnectionError):
                tr.step()
            assert inj.stats()[0][4] == 1
        kv.close()                        # bye: lease requeues

        kv2 = _store(monkeypatch, srv.address)
        it2 = StreamingIter(kv2, root, group="g", batch_size=4,
                            idle_timeout=0.3, poll=0.01)
        tr2 = ContinualTrainer(kv2, it2,
                               {"acc": np.zeros((2,), np.float32)},
                               _sum_grad_fn)
        assert tr2.run() == 2             # records 0..7, exactly once
        np.testing.assert_allclose(tr2.params["acc"], 28.0)
        assert kv2.stream_offsets("g")[(0, 0)][1] is True
        kv2.close()
    finally:
        srv.stop()


def test_kill_between_push_and_ack_dedupes(monkeypatch, tmp_path):
    """Fault row — trainer killed between the server applying the
    frame and the trainer seeing the ack (sever @ server.send): the
    respawn re-reads from the last committed offset, regenerates the
    bit-identical frame, and the server refuses the double — the
    clock-total is exact, not doubled."""
    root = str(tmp_path)
    _write_records(root, 4)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, root, group="g", batch_size=4,
                           idle_timeout=0.3, poll=0.01)
        tr = ContinualTrainer(kv, it,
                              {"acc": np.zeros((2,), np.float32)},
                              _sum_grad_fn)
        # the ack of the one stream_push frame is severed: the trainer
        # retries the identical frame (deterministic identity) and the
        # server refuses the replayed apply
        with fault.inject(
                "kind=sever,point=server.send,op=stream_push,nth=1") \
                as inj:
            assert tr.step() is True
            assert inj.stats()[0][4] == 1
        np.testing.assert_allclose(tr.params["acc"], 6.0)
        assert srv._clock["acc"] == 1 and srv._stream_dup >= 0
        assert srv._stream_commits == 1
    finally:
        kv.close()
        srv.stop()


def test_gc_only_behind_committed_final_watermark(monkeypatch,
                                                 tmp_path):
    """GC never collects a segment with uncommitted records: sealed
    but unconsumed segments survive; consumed-final ones go."""
    root = str(tmp_path)
    w = StreamWriter(root, shard=0, segment_bytes_=64)
    for i in range(4):
        w.append(encode_record("r%d" % i,
                               (np.full((2,), i, np.float32),),
                               np.float32(i)))
    w.close()
    assert len(slog.list_segments(root, 0)) >= 2
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, root, group="g", batch_size=4,
                           idle_timeout=0.3, poll=0.01)
        assert it.gc() == 0               # nothing committed yet
        assert len(slog.list_segments(root, 0)) >= 2
        tr = ContinualTrainer(kv, it,
                              {"acc": np.zeros((2,), np.float32)},
                              _sum_grad_fn)
        tr.run()
        n_before = len(slog.list_segments(root, 0))
        assert it.gc() == n_before        # all consumed-final: all go
        assert slog.list_segments(root, 0) == []
        np.testing.assert_allclose(tr.params["acc"], 6.0)
    finally:
        kv.close()
        srv.stop()


def test_streaming_iter_is_a_data_iter(monkeypatch, tmp_path):
    """StreamingIter honors the DataIter surface: next() returns a
    DataBatch, state_dict/load_state_dict exist (advisory — resume is
    server-authoritative), and uncommitted batches refuse a second
    next()."""
    root = str(tmp_path)
    _write_records(root, 4)
    srv = ParameterServer().start()
    kv = _store(monkeypatch, srv.address)
    try:
        it = StreamingIter(kv, root, group="g", batch_size=4,
                           idle_timeout=0.3, poll=0.01)
        assert isinstance(it, mx.io.DataIter)
        batch = it.next()
        assert isinstance(batch, mx.io.DataBatch)
        assert len(batch.data) == 4 and batch.pad == 0
        st = it.state_dict()
        assert st["group"] == "g" and st["lease"] == [0, 0]
        it.load_state_dict(st)
        with pytest.raises(RuntimeError, match="not committed"):
            it.next()
        commit = it.pending_commit()
        assert commit[0] == "g" and commit[4] is True
        kv.stream_push([], commit)
        it.commit_done()
        assert it.iter_next() is False
    finally:
        kv.close()
        srv.stop()
