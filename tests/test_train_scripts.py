"""End-to-end tests for the assembled image-classification training path
(reference example/image-classification/train_cifar10.py + common/fit.py:
record-file IO -> augmenters -> fit -> checkpoint -> resume)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import mxtpu as mx

ROOT = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
SCRIPT = os.path.join(ROOT, "example", "image-classification",
                      "train_cifar10.py")


def _run(args, cwd):
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=ROOT)
    return subprocess.run([sys.executable, SCRIPT] + args, cwd=cwd,
                          env=env, capture_output=True, text=True,
                          timeout=560)


@pytest.mark.slow
def test_cifar_script_trains_checkpoints_and_resumes(tmp_path):
    base = ["--synthetic", "48", "--num-layers", "8", "--batch-size", "8",
            "--disp-batches", "4", "--lr", "0.05", "--data-nthreads", "2",
            "--model-prefix", "ckpt/r8"]
    out = _run(base + ["--num-epochs", "1"], str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    assert (tmp_path / "ckpt" / "r8-0001.params").exists()
    assert (tmp_path / "ckpt" / "r8-symbol.json").exists()
    assert "Validation-accuracy" in out.stderr + out.stdout

    # resume from epoch 1 and train one more epoch
    out = _run(base + ["--num-epochs", "2", "--load-epoch", "1"],
               str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    log = out.stderr + out.stdout
    assert "Loaded model" in log
    assert (tmp_path / "ckpt" / "r8-0002.params").exists()
    # the resumed epoch is epoch 1 (0-based), not a restart from 0
    assert "Epoch[1]" in log and "Epoch[0]" not in log


def test_synthetic_recfile_through_record_iter(tmp_path):
    sys.path.insert(0, os.path.join(ROOT, "example",
                                    "image-classification"))
    try:
        from common.data import make_synthetic_recfile
    finally:
        sys.path.pop(0)
    rec = str(tmp_path / "t.rec")
    make_synthetic_recfile(rec, 20, 28, 4)
    it = mx.io.ImageRecordIter(path_imgrec=rec, data_shape=(3, 28, 28),
                               batch_size=5, shuffle=True, rand_crop=True,
                               rand_mirror=True, pad=2,
                               preprocess_threads=2)
    batch = next(it)
    assert batch.data[0].shape == (5, 3, 28, 28)
    assert batch.label[0].shape == (5,)
    labels = batch.label[0].asnumpy()
    assert set(labels.astype(int)).issubset({0, 1, 2, 3})


def test_record_augmentation_surface():
    """The reference record-iter augmentation knobs (affine, pad, hsl)
    produce valid images of unchanged geometry (image_aug_default.cc)."""
    from mxtpu import _image_worker as w
    rng = np.random.RandomState(0)
    img = rng.randint(0, 256, (32, 32, 3)).astype(np.uint8)
    out = w.affine_augment(img, np.random.RandomState(1),
                           max_rotate_angle=10, max_shear_ratio=0.1,
                           min_random_scale=0.8, max_random_scale=1.2,
                           max_aspect_ratio=0.25)
    assert out.shape == img.shape and out.dtype == np.uint8
    padded = w.pad_image(img, 4, fill_value=127)
    assert padded.shape == (40, 40, 3)
    assert (padded[0, 0] == 127).all()
    jit = w.hsl_jitter(img, np.random.RandomState(2), random_h=36,
                       random_s=50, random_l=50)
    assert jit.shape == img.shape and jit.dtype == np.uint8
    # identity config is a no-op passthrough
    assert w.affine_augment(img, rng) is img
    assert w.hsl_jitter(img, rng) is img
    # HLS round-trip is lossless-ish on uint8
    h, l, s = w._rgb_to_hls(img)
    back = w._hls_to_rgb(h, l, s)
    assert np.abs(back.astype(int) - img.astype(int)).max() <= 1
    # hue units are OpenCV's 0-180 scale: a +/-90 jitter bound spans the
    # whole wheel (2 degrees per unit, image_aug_default.cc)
    red = np.zeros((1, 1, 3), np.uint8)
    red[..., 0] = 200

    class FixedRng:
        def uniform(self, lo, hi):
            return hi
    shifted = w.hsl_jitter(red, FixedRng(), random_h=90)
    expect_cyan = np.zeros((1, 1, 3), np.uint8)
    expect_cyan[..., 1] = 200
    expect_cyan[..., 2] = 200
    np.testing.assert_array_equal(shifted, expect_cyan)
