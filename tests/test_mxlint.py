"""mxlint analyzer tests: the fixture corpus (known positives marked
``# EXPECT(pass-id)``, everything unmarked must stay clean), the
``proj_*`` whole-program corpora (cross-module lock inversion,
wire-protocol / fault-coverage / env-drift contract fixtures), pragma
scoping, baseline round-trip, SARIF output, the --diff file filter,
and the live-tree no-new-findings-vs-baseline gate that mirrors
``ci/check_static.py``.
"""
import json
import pathlib
import re
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

from mxlint.core import (Finding, all_passes, build_project,  # noqa: E402
                         diff_against_baseline, load_baseline,
                         run_paths, save_baseline)
from mxlint.cli import changed_files, main as cli_main  # noqa: E402

FIXTURES = ROOT / "tests" / "fixtures" / "mxlint"
_EXPECT = re.compile(r"#\s*EXPECT\((?P<id>[a-z-]+)\)")
# markdown fixtures (a corpus env_vars.md) carry HTML-comment markers
_EXPECT_MD = re.compile(r"<!--\s*EXPECT\((?P<id>[a-z-]+)\)\s*-->")


def _expected(path):
    out = set()
    pat = _EXPECT_MD if path.suffix == ".md" else _EXPECT
    for i, line in enumerate(path.read_text().splitlines(), 1):
        m = pat.search(line)
        if m:
            out.add((i, m.group("id")))
    return out


def _found(path):
    return {(f.line, f.pass_id)
            for f in run_paths([path], root=ROOT)}


# whole-program corpora: each proj_* directory is linted as one closed
# project (its own docs/ and tests/ serve as contract references)
CORPUS_DIRS = sorted(d for d in FIXTURES.iterdir()
                     if d.is_dir() and d.name.startswith("proj_"))


def _corpus_found(corpus):
    return {(f.path, f.line, f.pass_id)
            for f in run_paths([corpus], root=ROOT)}


def _corpus_expected(corpus):
    out = set()
    for f in sorted(corpus.rglob("*.py")) + sorted(corpus.rglob("*.md")):
        rel = str(f.relative_to(ROOT))
        out.update((rel, line, pid) for line, pid in _expected(f))
    return out


FIXTURE_FILES = sorted(f for f in FIXTURES.rglob("*.py")
                       if not any(p.name.startswith("proj_")
                                  for p in f.parents))


def test_fixture_corpus_exists():
    # one fixture per pass at minimum, each with >=1 positive
    ids = set()
    for f in FIXTURE_FILES:
        ids.update(pid for _, pid in _expected(f))
    for d in CORPUS_DIRS:
        ids.update(pid for _, _, pid in _corpus_expected(d))
    assert ids == set(all_passes()), \
        "every pass needs a fixture positive; have %s" % sorted(ids)


@pytest.mark.parametrize("fixture", FIXTURE_FILES,
                         ids=[str(f.relative_to(FIXTURES))
                              for f in FIXTURE_FILES])
def test_fixture(fixture):
    """Exact agreement: every EXPECT line is found by exactly that
    pass, and nothing unmarked is flagged (the known-negatives)."""
    assert _found(fixture) == _expected(fixture)


@pytest.mark.parametrize("corpus", CORPUS_DIRS,
                         ids=[d.name for d in CORPUS_DIRS])
def test_whole_program_corpus(corpus):
    """Exact agreement over a closed multi-module corpus: findings may
    anchor in any module (or the corpus docs), and everything unmarked
    — including the corpus's own docs and tests — stays clean."""
    assert _corpus_found(corpus) == _corpus_expected(corpus)


def test_wrapped_call_beyond_regex_window():
    """The motivating case: a create_connection wrapped over four lines
    with its timeout on the last line was a false positive for the old
    3-line window, and a timeout-free call with the word 'timeout' in a
    nearby comment was a false negative. The AST pass gets both right
    (encoded in blocking_calls.py: the 4-line call is unmarked, the
    comment-fooled call is an EXPECT)."""
    src = (FIXTURES / "blocking_calls.py").read_text()
    assert "timeout=5.0,\n    )" in src            # the wrapped negative
    found = _found(FIXTURES / "blocking_calls.py")
    neg_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                    if "server.example" in ln and "EXPECT" not in
                    src.splitlines()[i - 2])
    assert all(line != neg_line for line, _ in found)


# ---------------------------------------------------------------------------
# seeded-hazard acceptance cases (ISSUE 6): each archetypal bug is
# caught by its pass
# ---------------------------------------------------------------------------

def test_seeded_lock_inversion_is_caught():
    found = _found(FIXTURES / "lock_inversion.py")
    assert sum(1 for _, pid in found if pid == "lock-order") >= 2


def test_seeded_host_sync_in_jit_is_caught():
    found = _found(FIXTURES / "host_sync_in_jit.py")
    assert sum(1 for _, pid in found if pid == "trace-purity") >= 5


def test_seeded_use_after_donate_is_caught():
    found = _found(FIXTURES / "use_after_donate.py")
    assert sum(1 for _, pid in found if pid == "use-after-donate") >= 3


# ---------------------------------------------------------------------------
# whole-program analysis (ISSUE 9): the project symbol table, the
# cross-module lock graph, and the report-vs-analyze split
# ---------------------------------------------------------------------------

def test_cross_module_inversion_needs_whole_program():
    """The seeded AB/BA inversion spans two modules joined by a thread
    entry point: linting either file alone sees no cycle, linting the
    corpus finds one edge site in each module."""
    corpus = FIXTURES / "proj_xmod_locks"
    alone = run_paths([corpus / "alpha.py"], root=ROOT)
    assert all(f.pass_id != "lock-order" for f in alone)
    together = [f for f in run_paths([corpus], root=ROOT)
                if f.pass_id == "lock-order"]
    assert {f.path.rsplit("/", 1)[-1] for f in together} == \
        {"alpha.py", "beta.py"}


def test_thread_entry_points_are_indexed():
    project = build_project([FIXTURES / "proj_xmod_locks"], ROOT)
    targets = {qual for _, qual, _, how in project.entry_points}
    assert "Beta._loop" in targets


def test_project_resolves_attr_typed_cross_module_calls():
    """``self.partner.poke()`` resolves through the ``self.partner =
    Beta(...)`` attribute type into the other module."""
    project = build_project([FIXTURES / "proj_xmod_locks"], ROOT)
    got = project.resolve_callsite(
        "tests/fixtures/mxlint/proj_xmod_locks/alpha.py", "Alpha",
        ("self_attr", "partner", "poke"))
    assert got is not None
    assert got[0].endswith("beta.py") and got[1] == "Beta.poke"


def test_full_tree_request_analyzes_both_roots():
    """Linting only ``mxtpu`` still builds the project over ``tools``
    (a changed file's finding can depend on an unchanged peer), but
    reports only under the requested path."""
    project = build_project([ROOT / "mxtpu"], ROOT)
    assert any(rel.startswith("tools/") for rel in project.modules)
    assert all(rel.startswith("mxtpu") for rel in
               project.report_relpaths)


def test_changed_files_mode_reports_only_changed_files(tmp_path):
    """--diff semantics: the project is whole, the report is the
    changed set — a cross-file contract finding anchored in the
    changed file appears; the peer's own findings do not."""
    (tmp_path / "mxtpu").mkdir()
    (tmp_path / "tools").mkdir()
    client = tmp_path / "mxtpu" / "a_client.py"
    client.write_text(
        "class C:\n"
        "    def __init__(self, conn):\n"
        "        self.conn = conn\n"
        "    def go(self):\n"
        "        self.conn.request('ping', timeout=1.0)\n"
        "        self.conn.request('zap', timeout=1.0)\n")
    server = tmp_path / "tools" / "b_server.py"
    server.write_text(
        "class S:\n"
        "    def _dispatch(self, msg):\n"
        "        cmd = msg[0]\n"
        "        if cmd == 'ping':\n"
        "            return ('ok',)\n"
        "        if cmd == 'legacy':\n"
        "            return ('ok',)\n"
        "        return ('err', 'nope')\n")
    found = run_paths([tmp_path / "mxtpu", tmp_path / "tools"],
                      root=tmp_path, files=[client])
    msgs = [(f.path, f.pass_id, f.message) for f in found]
    assert any("zap" in m for _, pid, m in msgs
               if pid == "wire-protocol"), msgs
    assert all(p.endswith("a_client.py") for p, _, _ in msgs)


def test_open_file_set_skips_project_wide_directions(tmp_path):
    """A loose file list is an *open* project: the dead-handler /
    dead-doc directions stay quiet (they need the whole program to
    mean anything)."""
    f = tmp_path / "srv.py"
    f.write_text(
        "class S:\n"
        "    def _dispatch(self, msg):\n"
        "        cmd = msg[0]\n"
        "        if cmd == 'ping':\n"
        "            return ('ok',)\n"
        "        if cmd == 'legacy':\n"
        "            return ('ok',)\n"
        "        return ('err', 'nope')\n")
    assert run_paths([f], root=tmp_path) == []


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------

def test_pragma_function_scope(tmp_path):
    """A pragma on the def line blesses the whole body; the sibling
    function stays flagged."""
    f = tmp_path / "m.py"
    f.write_text(
        "def blessed(ev):   # mxlint: allow(blocking-call) — whole-fn\n"
        "    ev.wait()\n"
        "    ev.wait()\n"
        "def flagged(ev):\n"
        "    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(5, "blocking-call")]


def test_pragma_comment_only_line_blesses_next_line(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(
        "def g(ev):\n"
        "    # mxlint: allow(blocking-call) — next-line form\n"
        "    ev.wait()\n"
        "    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(4, "blocking-call")]


def test_pragma_in_string_literal_is_not_a_pragma(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(
        's = "# mxlint: allow(blocking-call)"\n'
        "def g(ev):\n"
        "    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(3, "blocking-call")]


# ---------------------------------------------------------------------------
# baseline workflow
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    findings = run_paths([FIXTURES / "blocking_calls.py"], root=ROOT)
    assert findings
    bl = tmp_path / "baseline.json"
    save_baseline(bl, findings)
    again = run_paths([FIXTURES / "blocking_calls.py"], root=ROOT)
    new, old, stale = diff_against_baseline(again, load_baseline(bl))
    assert new == [] and len(old) == len(findings) and stale == []


def test_baseline_is_line_number_free(tmp_path):
    """Moving an offender down a file keeps its grandfathered slot;
    editing its text does not."""
    f = tmp_path / "m.py"
    f.write_text("def g(ev):\n    ev.wait()\n")
    bl = tmp_path / "baseline.json"
    save_baseline(bl, run_paths([f], root=tmp_path))
    # shift the same line down: still grandfathered
    f.write_text("import os\n\n\ndef g(ev):\n    ev.wait()\n")
    new, old, _ = diff_against_baseline(
        run_paths([f], root=tmp_path), load_baseline(bl))
    assert new == [] and len(old) == 1
    # change the offending text: a NEW finding
    f.write_text("def g(ev):\n    ev.wait()  # changed\n")
    new, _, stale = diff_against_baseline(
        run_paths([f], root=tmp_path), load_baseline(bl))
    assert len(new) == 1 and len(stale) == 1


def test_duplicate_offenders_get_distinct_fingerprints(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("def g(ev):\n    ev.wait()\n    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert len(found) == 2
    assert found[0].fingerprint != found[1].fingerprint


# ---------------------------------------------------------------------------
# the live-tree gate (mirrors ci/check_static.py)
# ---------------------------------------------------------------------------

def test_live_tree_no_new_findings_vs_baseline():
    """The whole point: mxtpu/ + tools/ lint clean against the
    committed baseline. A failure here IS a regression (or a new
    deliberate case needing an inline pragma)."""
    findings = run_paths([ROOT / "mxtpu", ROOT / "tools"], root=ROOT)
    baseline = load_baseline(ROOT / "ci" / "mxlint_baseline.json")
    new, _, _ = diff_against_baseline(findings, baseline)
    assert new == [], "new mxlint findings:\n%s" % \
        "\n".join("  %s:%d [%s] %s" % (f.path, f.line, f.pass_id,
                                       f.message) for f in new)


def test_check_static_script_passes():
    proc = subprocess.run(
        [sys.executable, str(ROOT / "ci" / "check_static.py")],
        capture_output=True, text=True, timeout=300, cwd=str(ROOT))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    artifact = ROOT / "mxlint_findings.json"
    assert artifact.exists()
    doc = json.loads(artifact.read_text())
    assert doc["counts"]["new"] == 0
    assert set(doc["passes"]) >= set(all_passes())
    # the SARIF artifact rides along for CI diff annotation
    sarif = json.loads((ROOT / "mxlint_findings.sarif").read_text())
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert run["tool"]["driver"]["name"] == "mxlint"
    assert {r["id"] for r in run["tool"]["driver"]["rules"]} == \
        set(all_passes())
    assert run["results"] == []     # clean tree, empty baseline


def test_sarif_artifact_shape(tmp_path):
    """--sarif renders each finding as one result with rule id,
    location and the line-free partial fingerprint."""
    out = tmp_path / "f.sarif"
    rc = cli_main([str(FIXTURES / "proj_wireproto"), "--sarif",
                   str(out), "--no-baseline", "-q"])
    assert rc == 1
    doc = json.loads(out.read_text())
    results = doc["runs"][0]["results"]
    assert len(results) == 4        # the corpus's four EXPECT rows
    for res in results:
        assert res["ruleId"] == "wire-protocol"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].startswith(
            "tests/fixtures/mxlint/proj_wireproto/")
        assert loc["region"]["startLine"] >= 1
        assert res["partialFingerprints"]["mxlint/v1"]


def test_check_static_runtime_budget_is_pinned():
    """The sanity tier's wall-clock promise is enforced, not hoped
    for: the gate script carries an explicit budget assertion."""
    src = (ROOT / "ci" / "check_static.py").read_text()
    assert "BUDGET_SECONDS" in src and "BUDGET EXCEEDED" in src


# ---------------------------------------------------------------------------
# cli plumbing
# ---------------------------------------------------------------------------

def test_cli_json_artifact(tmp_path, capsys):
    out = tmp_path / "f.json"
    rc = cli_main([str(FIXTURES / "swallow_scoped.py"), "--json",
                   str(out), "--no-baseline", "-q"])
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["counts"]["new"] == 2
    assert all(f["pass"] == "except-swallow" for f in doc["findings"])


def test_cli_pass_subset():
    findings = run_paths([FIXTURES / "host_sync_in_jit.py"], root=ROOT,
                         pass_names=["except-swallow"])
    assert findings == []


def test_diff_mode_file_filter():
    """--diff collects changed python files under the linted roots
    (smoke: must run git and return a list of existing files)."""
    files = changed_files(ROOT, base="HEAD")
    assert isinstance(files, list)
    for f in files:
        assert f.exists() and f.suffix == ".py"
        rel = f.relative_to(ROOT)
        assert rel.parts[0] in ("mxtpu", "tools")


# ---------------------------------------------------------------------------
# mxlint v3 (ISSUE 15): the lockset core, the shared-state-race /
# blocking-under-lock passes, pragma-reason mechanics, and the static
# lock model the runtime witness consumes
# ---------------------------------------------------------------------------

def test_race_caught_from_both_modules():
    """The acceptance case: the split-lock race on ``queue_depth``
    (alpha writes under lock A, beta under lock B) anchors findings in
    BOTH modules of the corpus."""
    corpus = FIXTURES / "proj_races"
    found = [f for f in run_paths([corpus], root=ROOT)
             if f.pass_id == "shared-state-race"
             and "queue_depth" in f.message]
    mods = {f.path.rsplit("/", 1)[-1] for f in found}
    assert mods == {"alpha.py", "beta.py"}


def test_lockset_model_shapes():
    """The model behind both passes: thread + dispatch roots, the
    typed-chain lock tokens, init-phase filtering, and the transitive
    caller context."""
    from mxlint.locksets import lockset_model
    project = build_project([FIXTURES / "proj_races"], ROOT)
    model = lockset_model(project)
    kinds = {k for (k, _) in model.roots.values()}
    assert "thread" in kinds
    # both threaded modules guard through the SAME shared object: the
    # typed-chain token unifies on the declaring class
    races = {(key[0][1], key[1]): (sites, inter)
             for (key, sites, _ctx, inter) in model.shared_attrs()}
    assert ("Shared", "acked") in races
    _sites, inter = races[("Shared", "acked")]
    assert inter and all("Shared.lock_a" in t for t in inter)
    # init-phase writes in Shared.__init__ never appear as live sites
    hit_sites, _ = races[("Shared", "hits")]
    assert all(not s.init_phase for s in hit_sites)
    assert all("state.py" not in s.relpath for s in hit_sites)


def test_transitive_caller_context():
    """public() -> _locked() -> _helper(): the helper inherits the
    lock through ANY depth of the layering idiom, not one level."""
    from mxlint.locksets import lockset_model
    import pathlib
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        f = pathlib.Path(td) / "layered.py"
        f.write_text(
            "import threading\n"
            "class C:\n"
            "    def __init__(self):\n"
            "        self._lock = threading.Lock()\n"
            "    def public(self):\n"
            "        with self._lock:\n"
            "            self._locked()\n"
            "    def _locked(self):\n"
            "        self._helper()\n"
            "    def _helper(self):\n"
            "        pass\n")
        project = build_project([f], ROOT)
        model = lockset_model(project)
        rel = model.funcs and next(
            k for k in model.funcs if k[1] == "C._helper")
        ctx = model.caller_ctx(rel)
        assert any("_lock" in t for t in ctx), ctx


def test_dispatch_handlers_are_concurrency_roots():
    """A structural frame dispatcher is a root even with no Thread
    spawn in sight — the local transport runs it on the requesting
    thread."""
    from mxlint.locksets import lockset_model
    project = build_project([ROOT / "mxtpu"], ROOT)
    model = lockset_model(project)
    dispatch = {key for (kind, key) in model.roots.values()
                if kind == "dispatch"}
    assert any(qual.endswith("ParameterServer._dispatch")
               for (_rel, qual) in dispatch)


def test_reasonless_pragma_is_inert(tmp_path):
    """A bare ``allow(...)`` must not suppress: the finding survives,
    annotated with why; adding a reason suppresses it."""
    f = tmp_path / "m.py"
    f.write_text("def g(ev):\n"
                 "    ev.wait()   # mxlint: allow(blocking-call)\n")
    found = run_paths([f], root=tmp_path)
    assert len(found) == 1
    assert "carries no reason" in found[0].message
    f.write_text("def g(ev):\n"
                 "    ev.wait()   # mxlint: allow(blocking-call) — "
                 "deliberate park\n")
    assert run_paths([f], root=tmp_path) == []


def test_race_pragma_excludes_site_from_model(tmp_path):
    """A reasoned allow(shared-state-race) removes the site from the
    MODEL: blessing the one unlocked writer makes the remaining
    (locked) sites consistent, so no OTHER site is flagged either."""
    src = ("import threading\n"
           "class C:\n"
           "    def __init__(self):\n"
           "        self._lock = threading.Lock()\n"
           "        self.n = 0\n"
           "        t = threading.Thread(target=self._loop,\n"
           "                             daemon=True)\n"
           "        t.start()\n"
           "    def _loop(self):\n"
           "        with self._lock:\n"
           "            self.n += 1\n"
           "    def boot(self):%s\n"
           "        self.n = 0\n")
    (tmp_path / "mod.py").write_text(src % "")
    found = run_paths([tmp_path / "mod.py"], root=tmp_path)
    assert {f.pass_id for f in found} == {"shared-state-race"}
    (tmp_path / "mod.py").write_text(
        src % ("   # mxlint: allow(shared-state-race) — boot phase, "
               "single-threaded"))
    assert run_paths([tmp_path / "mod.py"], root=tmp_path) == []


def test_witness_model_export():
    """The --lock-model contract: guarded shared attributes with
    importable modules and concrete lock declaration sites — what the
    runtime witness watches."""
    from mxlint.locksets import lockset_model
    project = build_project([ROOT / "mxtpu", ROOT / "tools"], ROOT)
    doc = lockset_model(project).witness_model()
    assert doc["version"] == 1
    attrs = {(a["class"], a["attr"]): a for a in doc["attrs"]}
    assert len(attrs) >= 20
    sv = attrs[("Series", "_value")]
    assert sv["module"] == "mxtpu.obs.metrics"
    decls = [tuple(d) for g in sv["guards"] for d in g["decl"]]
    assert all(rel == "mxtpu/obs/metrics.py" for rel, _ in decls)
    for a in attrs.values():
        assert a["module"].startswith("mxtpu")
        assert a["guards"] and all(g["decl"] for g in a["guards"])


def test_cli_lock_model_flag(tmp_path):
    out = tmp_path / "model.json"
    rc = cli_main([str(FIXTURES / "proj_races"), "--lock-model",
                   str(out), "--no-baseline", "-q"])
    assert rc == 1                 # the corpus has findings, model rides along
    doc = json.loads(out.read_text())
    assert doc["version"] == 1     # fixture modules are not mxtpu.*,
    #                                so the export is structurally
    #                                valid but empty
    assert doc["attrs"] == []


def test_blocking_under_lock_condition_idiom_quiet(tmp_path):
    """wait() on the condition you hold releases it — never flagged;
    waiting on a DIFFERENT cv while holding a lock is."""
    f = tmp_path / "m.py"
    f.write_text(
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._cv = threading.Condition()\n"
        "        self._other = threading.Condition()\n"
        "        self._lk = threading.Lock()\n"
        "    def ok(self):\n"
        "        with self._cv:\n"
        "            self._cv.wait(timeout=1.0)\n"
        "    def bad(self):\n"
        "        with self._lk:\n"
        "            with self._other:\n"
        "                self._other.wait(timeout=1.0)\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(13, "blocking-under-lock")]


def test_finding_fingerprint_stability():
    f1 = Finding("a.py", 3, 0, "blocking-call", "msg", text="x.wait()",
                 func="g")
    f2 = Finding("a.py", 9, 4, "blocking-call", "msg", text="x.wait()",
                 func="g")
    from mxlint.core import assign_fingerprints
    assign_fingerprints([f1])
    assign_fingerprints([f2])
    assert f1.fingerprint == f2.fingerprint   # line-independent
