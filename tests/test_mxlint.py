"""mxlint analyzer tests: the fixture corpus (known positives marked
``# EXPECT(pass-id)``, everything unmarked must stay clean), pragma
scoping, baseline round-trip, the --diff file filter, and the live-tree
no-new-findings-vs-baseline gate that mirrors ``ci/check_static.py``.
"""
import json
import pathlib
import re
import subprocess
import sys

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "tools"))

from mxlint.core import (Finding, all_passes, diff_against_baseline,  # noqa: E402
                         load_baseline, run_paths, save_baseline)
from mxlint.cli import changed_files, main as cli_main  # noqa: E402

FIXTURES = ROOT / "tests" / "fixtures" / "mxlint"
_EXPECT = re.compile(r"#\s*EXPECT\((?P<id>[a-z-]+)\)")


def _expected(path):
    out = set()
    for i, line in enumerate(path.read_text().splitlines(), 1):
        m = _EXPECT.search(line)
        if m:
            out.add((i, m.group("id")))
    return out


def _found(path):
    return {(f.line, f.pass_id)
            for f in run_paths([path], root=ROOT)}


FIXTURE_FILES = sorted(FIXTURES.rglob("*.py"))


def test_fixture_corpus_exists():
    # one fixture per pass at minimum, each with >=1 positive
    ids = set()
    for f in FIXTURE_FILES:
        ids.update(pid for _, pid in _expected(f))
    assert ids == set(all_passes()), \
        "every pass needs a fixture positive; have %s" % sorted(ids)


@pytest.mark.parametrize("fixture", FIXTURE_FILES,
                         ids=[str(f.relative_to(FIXTURES))
                              for f in FIXTURE_FILES])
def test_fixture(fixture):
    """Exact agreement: every EXPECT line is found by exactly that
    pass, and nothing unmarked is flagged (the known-negatives)."""
    assert _found(fixture) == _expected(fixture)


def test_wrapped_call_beyond_regex_window():
    """The motivating case: a create_connection wrapped over four lines
    with its timeout on the last line was a false positive for the old
    3-line window, and a timeout-free call with the word 'timeout' in a
    nearby comment was a false negative. The AST pass gets both right
    (encoded in blocking_calls.py: the 4-line call is unmarked, the
    comment-fooled call is an EXPECT)."""
    src = (FIXTURES / "blocking_calls.py").read_text()
    assert "timeout=5.0,\n    )" in src            # the wrapped negative
    found = _found(FIXTURES / "blocking_calls.py")
    neg_line = next(i for i, ln in enumerate(src.splitlines(), 1)
                    if "server.example" in ln and "EXPECT" not in
                    src.splitlines()[i - 2])
    assert all(line != neg_line for line, _ in found)


# ---------------------------------------------------------------------------
# seeded-hazard acceptance cases (ISSUE 6): each archetypal bug is
# caught by its pass
# ---------------------------------------------------------------------------

def test_seeded_lock_inversion_is_caught():
    found = _found(FIXTURES / "lock_inversion.py")
    assert sum(1 for _, pid in found if pid == "lock-order") >= 2


def test_seeded_host_sync_in_jit_is_caught():
    found = _found(FIXTURES / "host_sync_in_jit.py")
    assert sum(1 for _, pid in found if pid == "trace-purity") >= 5


def test_seeded_use_after_donate_is_caught():
    found = _found(FIXTURES / "use_after_donate.py")
    assert sum(1 for _, pid in found if pid == "use-after-donate") >= 3


# ---------------------------------------------------------------------------
# pragmas
# ---------------------------------------------------------------------------

def test_pragma_function_scope(tmp_path):
    """A pragma on the def line blesses the whole body; the sibling
    function stays flagged."""
    f = tmp_path / "m.py"
    f.write_text(
        "def blessed(ev):   # mxlint: allow(blocking-call) — whole-fn\n"
        "    ev.wait()\n"
        "    ev.wait()\n"
        "def flagged(ev):\n"
        "    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(5, "blocking-call")]


def test_pragma_comment_only_line_blesses_next_line(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(
        "def g(ev):\n"
        "    # mxlint: allow(blocking-call) — next-line form\n"
        "    ev.wait()\n"
        "    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(4, "blocking-call")]


def test_pragma_in_string_literal_is_not_a_pragma(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(
        's = "# mxlint: allow(blocking-call)"\n'
        "def g(ev):\n"
        "    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert [(x.line, x.pass_id) for x in found] == \
        [(3, "blocking-call")]


# ---------------------------------------------------------------------------
# baseline workflow
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    findings = run_paths([FIXTURES / "blocking_calls.py"], root=ROOT)
    assert findings
    bl = tmp_path / "baseline.json"
    save_baseline(bl, findings)
    again = run_paths([FIXTURES / "blocking_calls.py"], root=ROOT)
    new, old, stale = diff_against_baseline(again, load_baseline(bl))
    assert new == [] and len(old) == len(findings) and stale == []


def test_baseline_is_line_number_free(tmp_path):
    """Moving an offender down a file keeps its grandfathered slot;
    editing its text does not."""
    f = tmp_path / "m.py"
    f.write_text("def g(ev):\n    ev.wait()\n")
    bl = tmp_path / "baseline.json"
    save_baseline(bl, run_paths([f], root=tmp_path))
    # shift the same line down: still grandfathered
    f.write_text("import os\n\n\ndef g(ev):\n    ev.wait()\n")
    new, old, _ = diff_against_baseline(
        run_paths([f], root=tmp_path), load_baseline(bl))
    assert new == [] and len(old) == 1
    # change the offending text: a NEW finding
    f.write_text("def g(ev):\n    ev.wait()  # changed\n")
    new, _, stale = diff_against_baseline(
        run_paths([f], root=tmp_path), load_baseline(bl))
    assert len(new) == 1 and len(stale) == 1


def test_duplicate_offenders_get_distinct_fingerprints(tmp_path):
    f = tmp_path / "m.py"
    f.write_text("def g(ev):\n    ev.wait()\n    ev.wait()\n")
    found = run_paths([f], root=tmp_path)
    assert len(found) == 2
    assert found[0].fingerprint != found[1].fingerprint


# ---------------------------------------------------------------------------
# the live-tree gate (mirrors ci/check_static.py)
# ---------------------------------------------------------------------------

def test_live_tree_no_new_findings_vs_baseline():
    """The whole point: mxtpu/ + tools/ lint clean against the
    committed baseline. A failure here IS a regression (or a new
    deliberate case needing an inline pragma)."""
    findings = run_paths([ROOT / "mxtpu", ROOT / "tools"], root=ROOT)
    baseline = load_baseline(ROOT / "ci" / "mxlint_baseline.json")
    new, _, _ = diff_against_baseline(findings, baseline)
    assert new == [], "new mxlint findings:\n%s" % \
        "\n".join("  %s:%d [%s] %s" % (f.path, f.line, f.pass_id,
                                       f.message) for f in new)


def test_check_static_script_passes():
    proc = subprocess.run(
        [sys.executable, str(ROOT / "ci" / "check_static.py")],
        capture_output=True, text=True, timeout=300, cwd=str(ROOT))
    assert proc.returncode == 0, proc.stdout + proc.stderr
    artifact = ROOT / "mxlint_findings.json"
    assert artifact.exists()
    doc = json.loads(artifact.read_text())
    assert doc["counts"]["new"] == 0
    assert set(doc["passes"]) >= set(all_passes())


# ---------------------------------------------------------------------------
# cli plumbing
# ---------------------------------------------------------------------------

def test_cli_json_artifact(tmp_path, capsys):
    out = tmp_path / "f.json"
    rc = cli_main([str(FIXTURES / "swallow_scoped.py"), "--json",
                   str(out), "--no-baseline", "-q"])
    assert rc == 1
    doc = json.loads(out.read_text())
    assert doc["counts"]["new"] == 2
    assert all(f["pass"] == "except-swallow" for f in doc["findings"])


def test_cli_pass_subset():
    findings = run_paths([FIXTURES / "host_sync_in_jit.py"], root=ROOT,
                         pass_names=["except-swallow"])
    assert findings == []


def test_diff_mode_file_filter():
    """--diff collects changed python files under the linted roots
    (smoke: must run git and return a list of existing files)."""
    files = changed_files(ROOT, base="HEAD")
    assert isinstance(files, list)
    for f in files:
        assert f.exists() and f.suffix == ".py"
        rel = f.relative_to(ROOT)
        assert rel.parts[0] in ("mxtpu", "tools")


def test_finding_fingerprint_stability():
    f1 = Finding("a.py", 3, 0, "blocking-call", "msg", text="x.wait()",
                 func="g")
    f2 = Finding("a.py", 9, 4, "blocking-call", "msg", text="x.wait()",
                 func="g")
    from mxlint.core import assign_fingerprints
    assign_fingerprints([f1])
    assign_fingerprints([f2])
    assert f1.fingerprint == f2.fingerprint   # line-independent
