"""pjit-sharded fused training + sharded serving (ISSUE 20).

conftest forces 8 emulated CPU devices, so every test here runs real
SPMD programs: ``Module.set_sharding(mesh, rules)`` compiles the fused
train step with the donated param/opt/aux stores sharded by rule,
``MXTPU_MESH`` engages the same path from the environment, and
``InferenceEngine(mesh=, rules=)`` AOT-compiles the serving menu over
the mesh. Pinned here: numerics parity with the single-device
programs, the rules -> NamedSharding mapping, the sharded checkpoint
round-trip, zero steady-state retraces, and the seq-parallel ring
attention route."""
import numpy as np
import pytest

import jax

import mxtpu as mx
from mxtpu.parallel import MeshContext, PartitionSpec as P
from mxtpu.partition import PartitionRules


def _toy_problem(n=128, dim=16, classes=4, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, dim).astype("float32")
    w = rng.randn(dim, classes).astype("float32")
    y = (x @ w).argmax(axis=1).astype("float32")
    return x, y


def _mlp(classes=4):
    data = mx.sym.var("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _fit(monkeypatch, mesh=None, rules=None, optimizer="sgd",
         opt_params=None, epochs=2, env=()):
    monkeypatch.setenv("MXTPU_MODULE_FUSED", "1")
    for k, v in dict(env).items():
        monkeypatch.setenv(k, v)
    np.random.seed(7)
    mx.random.seed(7)
    x, y = _toy_problem()
    train = mx.io.NDArrayIter(x, y, batch_size=32,
                              label_name="softmax_label")
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    if mesh is not None:
        mod.set_sharding(mesh, rules)
    mod.fit(train, optimizer=optimizer,
            optimizer_params=opt_params or {"learning_rate": 0.05,
                                            "momentum": 0.9, "wd": 1e-4},
            initializer=mx.initializer.Xavier(), num_epoch=epochs,
            eval_metric="acc")
    assert mod._fused is not None
    args, _ = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}


def _store_arrays(mod):
    return {n: a._data
            for n, a in mod._fused._group.param_store.items()}


def _spec(sharding):
    """PartitionSpec normalized for comparison: trailing Nones trimmed
    (P('model') and P('model', None) name the same placement)."""
    t = tuple(sharding.spec)
    while t and t[-1] is None:
        t = t[:-1]
    return t


# ---------------------------------------------------------------------------
# sharded fused training
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.05, "momentum": 0.9, "wd": 1e-4}),
    ("adam", {"learning_rate": 0.01}),
])
def test_mesh_vs_single_device_parity(monkeypatch, optimizer, opt_params):
    """Params after K epochs must match between the mesh SPMD program
    and the plain single-device fused step — same math, different
    layout."""
    mesh = MeshContext({"model": 8})
    _, single = _fit(monkeypatch, optimizer=optimizer,
                     opt_params=opt_params)
    mod, sharded = _fit(monkeypatch, mesh=mesh, optimizer=optimizer,
                        opt_params=opt_params)
    assert single.keys() == sharded.keys()
    for k in single:
        np.testing.assert_allclose(sharded[k], single[k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    # the donated store actually lives on the mesh, FSDP dim-0 layout
    store = _store_arrays(mod)
    w = store["fc1_weight"]                      # (32, 16): 32 % 8 == 0
    assert len(w.sharding.device_set) == 8
    assert _spec(w.sharding) == ("model",)
    # per-device bytes ~ 1/N for dividing params
    shard = w.addressable_shards[0].data
    assert shard.size * 8 == w.size


def test_mesh_amp_bf16_parity(monkeypatch):
    """AMP composes with the mesh: MXTPU_AMP=bf16 sharded-vs-single
    stays bit-exact (same bf16 rounding, same reduction order)."""
    mesh = MeshContext({"model": 8})
    _, single = _fit(monkeypatch, env={"MXTPU_AMP": "bf16"})
    _, sharded = _fit(monkeypatch, mesh=mesh, env={"MXTPU_AMP": "bf16"})
    for k in single:
        np.testing.assert_array_equal(sharded[k], single[k], err_msg=k)


def test_mesh_steady_state_no_retrace(monkeypatch):
    """After the first batch compiles the mesh program, further steps
    (and epochs) must be cache hits — zero retraces, zero recompiles."""
    mesh = MeshContext({"model": 8})
    mod, _ = _fit(monkeypatch, mesh=mesh, epochs=1)
    fs = mod._fused._group
    compiles = fs.stats["compiles"]
    x, y = _toy_problem()
    batch = mx.io.DataBatch([mx.nd.array(x[:32])], [mx.nd.array(y[:32])])
    for _ in range(3):
        mod.forward_backward(batch)
        mod.update()
    assert fs.stats["compiles"] == compiles, \
        "steady-state mesh steps must not recompile"


def test_mxtpu_mesh_env_knob(monkeypatch):
    """MXTPU_MESH=model=-1 engages the sharded step with no code
    changes, numerics-parity with the unset default."""
    _, single = _fit(monkeypatch)
    mod, sharded = _fit(monkeypatch, env={"MXTPU_MESH": "model=-1"})
    for k in single:
        np.testing.assert_allclose(sharded[k], single[k],
                                   rtol=1e-5, atol=1e-6, err_msg=k)
    store = _store_arrays(mod)
    assert len(store["fc1_weight"].sharding.device_set) == 8


# ---------------------------------------------------------------------------
# rules -> NamedSharding mapping
# ---------------------------------------------------------------------------

def test_named_shardings_mapping():
    """First match wins; unmatched names replicate; a mesh axis that
    does not divide its dim is dropped for that dim."""
    mesh = MeshContext({"model": 8})
    rules = PartitionRules([
        (r"fc1_.*", P("model")),
        (r"fc1_weight", P(None, "model")),       # shadowed: first wins
        (r"odd_.*", P("model")),
    ])
    sh = rules.named_shardings(mesh, {
        "fc1_weight": (32, 16), "fc1_bias": (32,),
        "odd_weight": (6, 16), "other": (8, 8)})
    assert _spec(sh["fc1_weight"]) == ("model",)
    assert _spec(sh["fc1_bias"]) == ("model",)
    assert _spec(sh["odd_weight"]) == (), \
        "8 does not divide 6: the axis must drop, not crash"
    assert _spec(sh["other"]) == (), "unmatched -> replicated"
    for s in sh.values():
        assert len(s.mesh.devices.ravel()) == 8


def test_opt_state_shardings_inherit():
    """Param-shaped optimizer-state leaves inherit the param sharding;
    scalar leaves replicate."""
    mesh = MeshContext({"model": 8})
    rules = PartitionRules([(r".*", P("model"))])
    shapes = {"w": (32, 4)}
    state = {"w": {"mom": np.zeros((32, 4), np.float32),
                   "step": np.zeros((), np.float32)}}
    sh = rules.opt_state_shardings(mesh, shapes, state)
    assert _spec(sh["w"]["mom"]) == ("model",)
    assert _spec(sh["w"]["step"]) == ()


# ---------------------------------------------------------------------------
# sharded checkpoint round-trip
# ---------------------------------------------------------------------------

def test_sharded_checkpoint_roundtrip(monkeypatch, tmp_path):
    """Params trained on the mesh travel through CheckpointManager
    (grouped by the SAME PartitionRules) and restore bit-exact into a
    fresh sharded serving engine."""
    from mxtpu.checkpoint import CheckpointManager
    from mxtpu.serving import InferenceEngine

    mesh = MeshContext({"model": 8})
    rules = PartitionRules([(r"fc1_.*", P("model")), (r".*", P())])
    mod, trained = _fit(monkeypatch, mesh=mesh, rules=rules, epochs=1)
    ckpt = CheckpointManager(str(tmp_path), async_save=False,
                             use_orbax=False)
    args, _ = mod.get_params()
    ckpt.save(0, args, layout=rules)
    tree = ckpt.restore(0)
    assert set(tree["params"]) == set(trained)
    for k, v in trained.items():
        np.testing.assert_array_equal(tree["params"][k], v, err_msg=k)
    # restored params drive a sharded engine identical to the original
    restored = {k: np.asarray(v) for k, v in tree["params"].items()}
    e0 = InferenceEngine(_mlp(), trained, {}, {"data": (16,)},
                         buckets=(4,), warm=False)
    e1 = InferenceEngine(_mlp(), restored, {}, {"data": (16,)},
                         buckets=(4,), warm=False, mesh=mesh,
                         rules=rules)
    x = np.random.RandomState(1).randn(4, 16).astype(np.float32)
    np.testing.assert_allclose(e1.predict([x])[0], e0.predict([x])[0],
                               rtol=1e-6, atol=1e-6)


# ---------------------------------------------------------------------------
# sharded serving
# ---------------------------------------------------------------------------

def test_sharded_engine_predict_parity_and_swap():
    """The mesh engine's AOT menu matches the single-device engine,
    repeat requests and weight swaps never retrace, and the program
    fingerprint pins the mesh topology (prewarm refuses a mismatch)."""
    from mxtpu.serving import InferenceEngine

    def params(seed):
        rng = np.random.RandomState(seed)
        return {"fc1_weight": rng.randn(32, 16).astype(np.float32) * .1,
                "fc1_bias": np.zeros(32, np.float32),
                "fc2_weight": rng.randn(4, 32).astype(np.float32) * .1,
                "fc2_bias": np.zeros(4, np.float32)}

    mesh = MeshContext({"model": 8})
    e0 = InferenceEngine(_mlp(), params(3), {}, {"data": (16,)},
                         buckets=(4,), warm=True)
    e1 = InferenceEngine(_mlp(), params(3), {}, {"data": (16,)},
                         buckets=(4,), warm=True, mesh=mesh)
    w = dict(zip(e1._param_names, e1._param_vals))["fc1_weight"]
    assert len(w.sharding.device_set) == 8
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    np.testing.assert_allclose(e1.predict([x])[0], e0.predict([x])[0],
                               rtol=1e-6, atol=1e-7)
    compiles = e1.stats()["compiles"]
    e1.predict([x])
    assert e1.stats()["compiles"] == compiles, "repeat request retraced"
    assert e0.swap_weights(params(9)) == e1.swap_weights(params(9)) == 1
    np.testing.assert_allclose(e1.predict([x])[0], e0.predict([x])[0],
                               rtol=1e-6, atol=1e-7)
    assert e1.stats()["compiles"] == compiles, "swap_weights retraced"
    # fingerprints: the mesh engine pins its topology, single stays bare
    fp0, fp1 = e0.program_fingerprint(), e1.program_fingerprint()
    assert "mesh" not in fp0
    assert fp1["mesh"]["shape"] == [["model", 8]]


# ---------------------------------------------------------------------------
# seq-parallel ring attention route
# ---------------------------------------------------------------------------

def test_seq_parallel_ring_route_parity():
    """Under ``seq_parallel(mesh)`` a full-window ``cached_attention``
    routes through the ring (forward AND gradient parity with the
    dense path); decode shapes (T=1) never route."""
    from mxtpu.ops.nn import cached_attention, seq_parallel
    import jax.numpy as jnp

    B, T, D, H = 2, 16, 16, 2
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    k = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    v = jnp.asarray(rng.randn(B, T, D).astype(np.float32))
    kc = jnp.zeros((B, T, D))
    vc = jnp.zeros((B, T, D))
    pos = jnp.zeros((B,), jnp.int32)
    mesh = MeshContext({"seq": 8})

    dense, dk, dv = cached_attention(q, k, v, kc, vc, pos, num_heads=H,
                                     alibi=True)
    with seq_parallel(mesh):
        ring, rk, rv = cached_attention(q, k, v, kc, vc, pos,
                                        num_heads=H, alibi=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               rtol=1e-5, atol=1e-5)
    assert jnp.array_equal(dk, rk) and jnp.array_equal(dv, rv)

    def loss(qq, route):
        def f(o):
            return jnp.sum(o[0] * o[0])
        if route:
            with seq_parallel(mesh):
                return f(cached_attention(qq, k, v, kc, vc, pos,
                                          num_heads=H, alibi=True))
        return f(cached_attention(qq, k, v, kc, vc, pos, num_heads=H,
                                  alibi=True))

    g0 = jax.grad(lambda qq: loss(qq, False))(q)
    g1 = jax.grad(lambda qq: loss(qq, True))(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g0),
                               rtol=1e-4, atol=1e-4)

    # decode step: T=1 != S -> the dense cache path, ring never engages
    with seq_parallel(mesh):
        o1, _, _ = cached_attention(q[:, :1], k[:, :1], v[:, :1],
                                    kc, vc, pos, num_heads=H, alibi=True)
    assert o1.shape == (B, 1, D)
