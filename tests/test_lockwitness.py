"""Lock-witness runtime tests (mxtpu/devtools/lockwitness.py): the
lock wrappers' held-set bookkeeping (incl. the Condition protocol),
the Eraser-style ownership transitions, contradiction/mismatch
recording against a static model, slot-class watching, and the dump
artifact. The witness is installed and UNINSTALLED per test — the
rest of the suite must keep running on the real lock factories."""
import importlib.util
import json
import pathlib
import sys
import threading

import pytest

ROOT = pathlib.Path(__file__).resolve().parent.parent

_spec = importlib.util.spec_from_file_location(
    "_lw_under_test", str(ROOT / "mxtpu" / "devtools" / "lockwitness.py"))
lw = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(lw)


@pytest.fixture
def witness():
    lw.reset()
    lw.caller_filter = False       # tests drive watched attrs directly
    threading.Lock = lw._WLock
    threading.RLock = lw._WRLock
    threading._mxtpu_lock_witness = lw
    try:
        yield lw
    finally:
        lw.uninstall()
        lw.caller_filter = True
        lw.reset()


def _in_thread(fn):
    out = {}

    def run():
        out["r"] = fn()
    t = threading.Thread(target=run)
    t.start()
    t.join(timeout=10.0)
    assert "r" in out or not t.is_alive()
    return out.get("r")


# ---------------------------------------------------------------------------
# lock wrappers
# ---------------------------------------------------------------------------

def test_lock_wrapper_tracks_held(witness):
    lock = threading.Lock()
    assert lw._held() == []
    with lock:
        assert lw._held() == [lock]
        assert lock.locked()
    assert lw._held() == []
    assert not lock.locked()


def test_held_set_is_per_thread(witness):
    lock = threading.Lock()
    with lock:
        assert _in_thread(lambda: list(lw._held())) == []
        assert lw._held() == [lock]


def test_rlock_reentrant(witness):
    rl = threading.RLock()
    with rl:
        with rl:
            assert lw._held() == [rl, rl]
        assert lw._held() == [rl]
    assert lw._held() == []


def test_condition_wait_releases_held(witness):
    """The critical protocol case: Condition.wait() on a witness RLock
    must drop the lock from the held set while parked and restore it
    (at the right multiplicity) on wake."""
    cv = threading.Condition()        # builds on witness RLock
    seen = {}
    started = threading.Event()

    def waiter():
        with cv:
            started.set()
            cv.wait(timeout=5.0)
            seen["after"] = list(lw._held())
    t = threading.Thread(target=waiter)
    t.start()
    assert started.wait(5.0)
    with cv:                          # acquirable => waiter released it
        cv.notify_all()
    t.join(timeout=5.0)
    assert not t.is_alive()
    assert len(seen["after"]) == 1    # reacquired exactly once


# ---------------------------------------------------------------------------
# watched attributes + ownership
# ---------------------------------------------------------------------------

class _Plain:
    def __init__(self):
        self.lock = threading.Lock()
        self.count = 0


class _Slotted:
    __slots__ = ("v",)

    def __init__(self):
        self.v = 0


def test_exclusive_owner_never_contradicts(witness):
    lw.watch(_Plain, "count", {("mxtpu/fake.py", 1)})
    p = _Plain()
    p.count += 1                       # all on the creating thread
    assert p.count == 1
    assert lw.contradictions() == []
    obs = lw.observations()["_Plain.count"]
    assert obs["writes"] >= 1 and obs["shared"] == 0


def test_shared_unguarded_write_is_a_contradiction(witness):
    lw.watch(_Plain, "count", {("mxtpu/fake.py", 1)})
    p = _Plain()
    p.count = 5                        # exclusive: fine

    def bare_write():
        p.count = 6                    # second thread, no lock held
    _in_thread(bare_write)
    cons = lw.contradictions()
    assert len(cons) == 1
    assert cons[0]["class"] == "_Plain" and cons[0]["attr"] == "count"
    assert cons[0]["access"] == "write"


def test_shared_unguarded_read_is_reported_not_fatal(witness):
    """The static model exempts plain snapshot reads — so does the
    witness: recorded in the artifact, never a contradiction."""
    lw.watch(_Plain, "count", {("mxtpu/fake.py", 1)})
    p = _Plain()
    p.count = 5
    _in_thread(lambda: p.count)
    assert lw.contradictions() == []
    reads = lw.unguarded_reads()
    assert len(reads) == 1 and reads[0]["access"] == "read"


def test_shared_guarded_access_matches_model(witness):
    probe = _Plain()                   # learn the lock creation site
    lw.watch(_Plain, "count", {probe.lock.site})
    p = _Plain()                       # __init__ observed on MAIN

    def locked_bump():
        with p.lock:                   # second thread => SHARED
            p.count += 1
    _in_thread(locked_bump)
    assert lw.contradictions() == []
    obs = lw.observations()["_Plain.count"]
    assert obs["guarded"] >= 1 and obs["unguarded"] == 0


def test_wrong_lock_is_a_mismatch_not_a_contradiction(witness):
    lw.watch(_Plain, "count", {("mxtpu/elsewhere.py", 99)})
    p = _Plain()                       # __init__ observed on MAIN
    other = threading.Lock()

    def bump():
        with other:
            p.count += 1
    _in_thread(bump)
    assert lw.contradictions() == []
    assert lw.observations()["_Plain.count"]["mismatch"] >= 1


def test_slot_class_watch_delegates_storage(witness):
    lw.watch(_Slotted, "v", {("mxtpu/fake.py", 1)})
    s = _Slotted()
    s.v = 7
    assert s.v == 7
    obs = lw.observations()["_Slotted.v"]
    assert obs["writes"] >= 1 and obs["reads"] >= 1


def test_test_driven_access_is_filtered_by_default(witness):
    lw.caller_filter = True
    lw.watch(_Plain, "count", {("mxtpu/fake.py", 1)})
    p = _Plain()
    p.count = 1
    _in_thread(lambda: p.count)        # caller is this test file
    assert lw.contradictions() == []   # filtered: not fleet code
    assert lw.observations()["_Plain.count"]["unguarded"] >= 1


# ---------------------------------------------------------------------------
# install / model plumbing / artifact
# ---------------------------------------------------------------------------

def test_install_uninstall_roundtrip(witness):
    lw.uninstall()
    real = threading.Lock
    lw.install(model_path=None)
    assert lw.installed()
    assert threading.Lock is lw._WLock
    assert lw.install(model_path=None) == 0    # idempotent
    lw.uninstall()
    assert threading.Lock is real


def test_install_watches_model_entries(witness, tmp_path, monkeypatch):
    fixture = tmp_path / "lwfixturemod.py"
    fixture.write_text(
        "class Box:\n"
        "    def __init__(self):\n"
        "        self.items = 0\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    model = {"version": 1, "attrs": [
        {"module": "lwfixturemod", "class": "Box", "attr": "items",
         "guards": [{"token": "Box._lock",
                     "decl": [["mxtpu/x.py", 3]]}]},
        {"module": "no.such.module", "class": "X", "attr": "y",
         "guards": []},
    ]}
    mp = tmp_path / "model.json"
    mp.write_text(json.dumps(model))
    lw.uninstall()
    lw.install(model_path=str(mp))
    import lwfixturemod
    assert isinstance(lwfixturemod.Box.__dict__["items"],
                      lw._WatchedAttr)
    b = lwfixturemod.Box()
    b.items += 2
    assert b.items == 2


def test_dump_artifact_shape(witness, tmp_path):
    lw.watch(_Plain, "count", {("mxtpu/fake.py", 1)})
    p = _Plain()
    p.count = 3
    _in_thread(lambda: p.count)
    out = tmp_path / "obs.json"
    doc = lw.dump(str(out))
    loaded = json.loads(out.read_text())
    assert loaded["observations"]["_Plain.count"]["reads"] >= 1
    assert loaded["contradictions"] == doc["contradictions"]
    assert loaded["version"] == 1
