"""Custom op tests, modeled on the reference test_operator.py:test_custom_op:
a user-defined softmax with hand-written backward must match the builtin,
compose with autograd, work symbolically, and survive jit.
"""
import numpy as np

import mxtpu as mx
from mxtpu import nd


class MySoftmax(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        y = np.exp(x - x.max(axis=1, keepdims=True))
        y /= y.sum(axis=1, keepdims=True)
        self.assign(out_data[0], req[0], nd.array(y))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        y = out_data[0].asnumpy()
        g = out_grad[0].asnumpy()
        dx = y * (g - (g * y).sum(axis=1, keepdims=True))
        self.assign(in_grad[0], req[0], nd.array(dx))


@mx.operator.register("mysoftmax")
class MySoftmaxProp(mx.operator.CustomOpProp):
    def __init__(self):
        super().__init__(need_top_grad=True)

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def infer_shape(self, in_shape):
        return [in_shape[0]], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return MySoftmax()


class MyScale2(mx.operator.CustomOp):
    """Two-output op: (x*scale, x+scale)."""

    def __init__(self, scale):
        self.scale = scale

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], nd.array(x * self.scale))
        self.assign(out_data[1], req[1], nd.array(x + self.scale))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        g = out_grad[0].asnumpy() * self.scale + out_grad[1].asnumpy()
        self.assign(in_grad[0], req[0], nd.array(g))


@mx.operator.register("myscale2")
class MyScale2Prop(mx.operator.CustomOpProp):
    def __init__(self, scale="2.0"):
        super().__init__(need_top_grad=True)
        self.scale = float(scale)

    def list_outputs(self):
        return ["scaled", "shifted"]

    def infer_shape(self, in_shape):
        return [in_shape[0]], [in_shape[0], in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return MyScale2(self.scale)


def test_custom_forward_matches_builtin():
    x = np.random.RandomState(0).randn(4, 5).astype(np.float32)
    out = nd.Custom(nd.array(x), op_type="mysoftmax")
    ref = nd.softmax(nd.array(x))
    np.testing.assert_allclose(out.asnumpy(), ref.asnumpy(), rtol=1e-5,
                               atol=1e-6)


def test_custom_backward_through_autograd():
    x = nd.array(np.random.RandomState(1).randn(3, 4).astype(np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(x, op_type="mysoftmax")
        loss = nd.sum(y * y)
    loss.backward()
    g_custom = x.grad.asnumpy().copy()

    x2 = nd.array(x.asnumpy())
    x2.attach_grad()
    with mx.autograd.record():
        y2 = nd.softmax(x2)
        loss2 = nd.sum(y2 * y2)
    loss2.backward()
    np.testing.assert_allclose(g_custom, x2.grad.asnumpy(), rtol=1e-4,
                               atol=1e-5)


def test_custom_multi_output_with_params():
    x = np.random.RandomState(2).randn(2, 3).astype(np.float32)
    a, b = nd.Custom(nd.array(x), op_type="myscale2", scale=3.0)
    np.testing.assert_allclose(a.asnumpy(), x * 3.0, rtol=1e-6)
    np.testing.assert_allclose(b.asnumpy(), x + 3.0, rtol=1e-6)


def test_custom_symbolic_and_jit():
    """Custom op inside a bound symbol graph (pure_callback under jit)."""
    data = mx.sym.var("data")
    out = mx.sym.Custom(data, op_type="mysoftmax", name="cs")
    out = mx.sym.sum(out * out)
    exe = out.simple_bind(mx.cpu(), data=(4, 6))
    x = np.random.RandomState(3).randn(4, 6).astype(np.float32)
    res = exe.forward(data=nd.array(x))
    y = np.exp(x - x.max(1, keepdims=True))
    y /= y.sum(1, keepdims=True)
    np.testing.assert_allclose(float(res[0].asnumpy()), (y * y).sum(),
                               rtol=1e-4)
    exe.backward()
    g = exe.grad_dict["data"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).sum() > 0


class Stateful(mx.operator.CustomOp):
    """Stashes forward state on self for backward (dropout-mask pattern)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.mask = (x > 0).astype(np.float32)
        self.assign(out_data[0], req[0], nd.array(x * self.mask))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        g = out_grad[0].asnumpy()
        self.assign(in_grad[0], req[0], nd.array(g * self.mask))


@mx.operator.register("statefulrelu")
class StatefulProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return [in_shape[0]], [in_shape[0]], []

    def create_operator(self, ctx, shapes, dtypes):
        return Stateful()


def test_custom_op_shares_instance_between_fwd_bwd():
    x = nd.array(np.random.RandomState(4).randn(3, 3).astype(np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = nd.Custom(x, op_type="statefulrelu")
        loss = nd.sum(y)
    loss.backward()
    mask = (x.asnumpy() > 0).astype(np.float32)
    np.testing.assert_allclose(x.grad.asnumpy(), mask, rtol=1e-6)


class IndexOut(mx.operator.CustomOp):
    def forward(self, is_train, req, in_data, out_data, aux):
        x = in_data[0].asnumpy()
        self.assign(out_data[0], req[0], nd.array(
            np.argmax(x, axis=1).astype(np.int32)))

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        self.assign(in_grad[0], req[0],
                    nd.zeros(in_data[0].shape))


@mx.operator.register("myargmax")
class IndexOutProp(mx.operator.CustomOpProp):
    def infer_shape(self, in_shape):
        return [in_shape[0]], [[in_shape[0][0]]], []

    def infer_type(self, in_type):
        return in_type, [np.int32], []

    def create_operator(self, ctx, shapes, dtypes):
        return IndexOut()


def test_custom_op_honors_infer_type():
    x = np.random.RandomState(5).randn(4, 6).astype(np.float32)
    out = nd.Custom(nd.array(x), op_type="myargmax")
    assert out.asnumpy().dtype == np.int32
    np.testing.assert_array_equal(out.asnumpy(), np.argmax(x, axis=1))
