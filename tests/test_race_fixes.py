"""Regression tests for the real shared-state races the mxlint v3
``shared-state-race`` lockset pass surfaced (ISSUE 15) — one test per
fix, each driving the actual concurrent shape that used to corrupt:

* ParameterServer observability counters (``_push_count``/``_stale_*``/
  ``_dup_n``) were ``+=``'d from concurrent per-connection handler
  threads with only per-KEY locks held — cross-key increments lost
  updates. Now under the dedicated ``_ctr_lock``.
* ``ParameterServer.snapshot()`` iterated ``self._applied.items()``
  with a Python-level comprehension while handler threads inserted —
  "dictionary changed size during iteration" mid-snapshot. Now a
  one-shot C-level ``list()`` copy.
* ``_map_version`` bumps under different keys' locks could collide
  and let two different shard maps share a version. Now counted under
  ``_ctr_lock``.
* ``TelemetryAggregator.sweep()`` is public (tests/mxtop --once) AND
  driven by the background loop with no serialization — ring/streak/
  counter interleaving. Now one ``_sweep_lock`` per whole sweep.
* ``WeightSync``'s conn cache and the kvstore client's routing/layout
  caches (``_parts``/``_shapes``/``_key_overrides``) were written
  from the training thread, the async push executor and failover
  replay paths with no lock. Writers now serialize on a leaf lock.
"""
import threading

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import kvstore_async as ka
from mxtpu.kvstore_async import ParameterServer


def _run_threads(n, fn):
    errs = []
    start = threading.Barrier(n)

    def wrap(i):
        try:
            start.wait(timeout=10.0)
            fn(i)
        except BaseException as e:   # noqa: B036 — surface in the test
            errs.append(e)
    ts = [threading.Thread(target=wrap, args=(i,)) for i in range(n)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=60.0)
    assert not any(t.is_alive() for t in ts)
    if errs:
        raise errs[0]
    return errs


def test_push_counters_exact_under_concurrent_handlers():
    """N threads x M pushes to DISTINCT keys (so the per-key locks
    never serialize them): the non-dup push count and the staleness
    sample count must both be exactly N*M — the pre-fix unlocked
    ``+=`` lost increments under this shape."""
    srv = ParameterServer().start()
    nthreads, per = 8, 40
    try:
        base = np.zeros((2,), np.float32)
        for i in range(nthreads):
            srv._dispatch(("init", "k%d" % i, base))
        start_pushes = srv._push_count

        def pusher(i):
            for s in range(1, per + 1):
                reply = srv._dispatch(
                    ("push", "k%d" % i, np.ones((2,), np.float32),
                     0, "origin-%d" % i, s))
                assert reply[0] == "ok"
        _run_threads(nthreads, pusher)
        assert srv._push_count - start_pushes == nthreads * per
        assert srv._stale_n == nthreads * per
        # replays dedupe without disturbing the exact counters
        r = srv._dispatch(("push", "k0", np.ones((2,), np.float32),
                           0, "origin-0", per))
        assert r == ("ok", "dup")
        assert srv._push_count - start_pushes == nthreads * per
        assert srv._dup_n == 1
    finally:
        srv.stop()


def test_snapshot_survives_concurrent_applied_growth(tmp_path):
    """snapshot() must take tear-retrying reference copies of the
    dedupe and forwarding maps (``_racing_copy``): growing
    ``_applied`` from handler threads during a snapshot loop used to
    die with 'dictionary changed size during iteration' — even
    ``list(d.items())`` can observe a concurrent resize."""
    srv = ParameterServer(snapshot_dir=str(tmp_path),
                          snapshot_every=0).start()
    try:
        srv._dispatch(("init", "w", np.zeros((2,), np.float32)))
        errs = []

        def grow():
            # a fresh origin per push: every one grows _applied
            for s in range(1500):
                srv._dispatch(("push", "w", np.ones((2,), np.float32),
                               0, "o-%d-%d" % (threading.get_ident(),
                                               s), 1))
        threads = [threading.Thread(target=grow) for _ in range(4)]
        for t in threads:
            t.start()
        try:
            ok = 0
            while any(t.is_alive() for t in threads):
                if srv.snapshot():
                    ok += 1
        except RuntimeError as e:    # pragma: no cover — the bug
            errs.append(e)
        finally:
            for t in threads:
                t.join(timeout=30.0)
        assert not errs
        assert ok >= 1
        assert len(srv._applied) > 0
    finally:
        srv.stop()


def test_map_version_bumps_are_exact_across_keys():
    """Concurrent moved-record applies for DIFFERENT keys bump
    ``_map_version`` under their own key locks; the counter must still
    advance exactly once per record (a lost bump would let two
    different shard maps share a version)."""
    srv = ParameterServer().start()
    nthreads, per = 8, 25
    try:
        for i in range(nthreads):
            for s in range(per):
                srv._dispatch(("init", "k%d-%d" % (i, s),
                               np.zeros((1,), np.float32)))
        v0 = srv._map_version
        srv._role = "backup"     # moved records are a backup-side op

        # rseq watermark is per-stream serial; give each thread its
        # own stream id so records are not refused as replays
        def mover_streams(i):
            for s in range(per):
                r = srv._dispatch(
                    ("repl", "stream-%d" % i, s + 1,
                     ("moved", "k%d-%d" % (i, s), "addr:1")))
                assert r[0] == "ok", r
        _run_threads(nthreads, mover_streams)
        assert srv._map_version - v0 == nthreads * per
        assert len(srv._moved) == nthreads * per
    finally:
        srv.stop()


def test_aggregator_concurrent_sweeps_are_serialized(tmp_path):
    """TelemetryAggregator.sweep() from many threads (the background
    loop racing a ``mxtop --once`` driver): every sweep counts, the
    history ring stays bounded and internally consistent."""
    from mxtpu.obs.telemetry import TelemetryAggregator
    agg = TelemetryAggregator(targets=[],
                              endpoints_dir=str(tmp_path),
                              history=8)
    n, per = 6, 10
    docs = []
    lock = threading.Lock()

    def sweeper(i):
        for _ in range(per):
            d = agg.sweep()
            with lock:
                docs.append(d)
    _run_threads(n, sweeper)
    assert agg.sweeps == n * per
    assert len(agg._history) <= 8
    # each returned doc was built under the sweep lock: its recorded
    # sweep counter must be unique (no two interleaved sweeps)
    seen = [d["sweeps"] for d in docs]
    assert len(set(seen)) == len(seen)


def test_client_plan_cache_concurrent_writers():
    """_plan() from many threads for overlapping keys: the parts and
    shape caches must end complete and mutually consistent (writers
    serialize on _cache_lock; readers stay lock-free)."""
    kv = ka.AsyncDistKVStore()
    try:
        keys = ["p%d" % i for i in range(32)]

        def planner(i):
            for k in keys:
                plan = kv._plan(k, (4, 3))
                assert plan and kv._shapes[k] == (4, 3)
        _run_threads(8, planner)
        assert set(kv._parts) == set(keys)
        assert set(kv._shapes) == set(keys)
        for k in keys:
            assert kv._plan(k, (4, 3)) == kv._parts[k]
    finally:
        kv.close()


def test_weightsync_conn_cache_stop_race():
    """WeightSync._conn / stop(): concurrent conn-cache population and
    teardown must neither raise nor resurrect connections after
    stop()."""
    from mxtpu.serving.rollout import WeightSync

    class _Engine:
        def version_state(self):
            return {"latest": 0}

    class _Entry:
        engine = _Engine()

    class _Server:
        def _entry_for(self, model):
            return _Entry()

    srv = ParameterServer().start()
    try:
        ws = WeightSync(_Server(), kv_addrs=[srv.address])
        addr = srv.address

        def opener(i):
            for _ in range(5):
                try:
                    ws._conn(addr)
                except (ConnectionError, RuntimeError, OSError):
                    pass
        _run_threads(4, opener)
        ws.stop()
        assert ws._conns == {}
    finally:
        srv.stop()
