"""Configuration-sweep depth for the high-traffic operators.

The registry-wide sweep (tests/test_op_sweep.py) checks every op at ONE
configuration; the reference's test_operator.py additionally walks the
parameter spaces of the hot ops (kernel/stride/pad/dilate/groups for
conv, conventions for pooling, axes for softmax/norm/transpose, transpose
flags for dot, modes for take/clip). This file is that tier: each variant
runs forward vs a numpy reference AND finite-difference gradients through
the symbolic executor (``check_numeric_gradient``), so the Symbol path,
the jitted executor and the vjp are all exercised per configuration.

Reference: tests/python/unittest/test_operator.py (test_convolution_*,
test_pooling_*, test_dot, test_take, test_transpose families).
"""
import importlib.util
import os
import zlib

import numpy as np
import pytest

import mxtpu as mx
import mxtpu.ndarray as nd
from mxtpu.test_utils import check_numeric_gradient, check_symbolic_forward

_spec = importlib.util.spec_from_file_location(
    "op_sweep_helpers",
    os.path.join(os.path.dirname(__file__), "test_op_sweep.py"))
_sweep = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_sweep)
np_conv2d = _sweep.np_conv2d
np_deconv2d = _sweep.np_deconv2d
np_pool2d = _sweep.np_pool2d
np_softmax = _sweep.np_softmax


def _r(seed=0):
    return np.random.RandomState(seed)


def _check(symf, args_np, ref_out, rtol=1e-3, atol=1e-4, grad=True,
           aux=None):
    """Forward vs numpy + FD gradients for a symbol-builder closure."""
    names = ["a%d" % i for i in range(len(args_np))]
    sym = symf(*[mx.sym.var(n) for n in names])
    loc = dict(zip(names, args_np))
    check_symbolic_forward(sym, loc, [ref_out], rtol=rtol, atol=atol,
                           aux_states=aux)
    if grad:
        check_numeric_gradient(sym, loc, aux_states=aux, rtol=5e-2,
                               atol=5e-3)


# ---- Convolution variants -------------------------------------------------

CONV_CASES = [
    # (in_shape, num_filter, kernel, stride, pad, dilate, groups, bias)
    ((1, 1, 7, 7), 2, (1, 1), (1, 1), (0, 0), (1, 1), 1, True),
    ((2, 3, 6, 6), 4, (3, 3), (1, 1), (1, 1), (1, 1), 1, True),
    ((1, 2, 8, 8), 3, (3, 3), (2, 2), (0, 0), (1, 1), 1, False),
    ((1, 2, 9, 9), 2, (3, 3), (1, 1), (2, 2), (2, 2), 1, True),
    ((1, 4, 6, 6), 4, (3, 3), (1, 1), (1, 1), (1, 1), 2, True),
    ((1, 4, 5, 5), 4, (5, 5), (1, 1), (2, 2), (1, 1), 4, False),
    ((2, 2, 7, 5), 3, (3, 1), (2, 1), (1, 0), (1, 1), 1, True),
]


@pytest.mark.parametrize("case", CONV_CASES,
                         ids=lambda c: "c%s_k%s_s%s_p%s_d%s_g%d" % (
                             c[0][1], c[2], c[3], c[4], c[5], c[6]))
def test_convolution_variants(case):
    in_shape, nf, kernel, stride, pad, dilate, groups, bias = case
    r = _r(zlib.crc32(str(case).encode()))
    x = r.uniform(-1, 1, in_shape).astype(np.float32)
    w = r.uniform(-1, 1, (nf, in_shape[1] // groups) + kernel) \
        .astype(np.float32)
    b = r.uniform(-1, 1, (nf,)).astype(np.float32)

    cin_g = in_shape[1] // groups
    parts = []
    for g in range(groups):
        parts.append(np_conv2d(x[:, g * cin_g:(g + 1) * cin_g],
                               w[g * (nf // groups):(g + 1) * (nf // groups)],
                               None, stride=stride, pad=pad, dilate=dilate))
    ref = np.concatenate(parts, axis=1)
    if bias:
        ref = ref + b.reshape(1, -1, 1, 1)

    args = [x, w] + ([b] if bias else [])
    _check(lambda *vs: mx.sym.Convolution(
        *vs, kernel=kernel, num_filter=nf, stride=stride, pad=pad,
        dilate=dilate, num_group=groups, no_bias=not bias),
        args, ref)


def test_convolution_1d_3d():
    r = _r(1)
    # 1-D (NCW)
    x = r.uniform(-1, 1, (2, 3, 8)).astype(np.float32)
    w = r.uniform(-1, 1, (4, 3, 3)).astype(np.float32)
    ref = np_conv2d(x[:, :, None, :], w[:, :, None, :], None,
                    stride=(1, 2), pad=(0, 1))[:, :, 0]
    _check(lambda a, b: mx.sym.Convolution(a, b, kernel=(3,), num_filter=4,
                                           stride=(2,), pad=(1,),
                                           no_bias=True),
           [x, w], ref)
    # 3-D (NCDHW): check against explicit loop on a tiny case
    x3 = r.uniform(-1, 1, (1, 2, 3, 4, 4)).astype(np.float32)
    w3 = r.uniform(-1, 1, (2, 2, 2, 2, 2)).astype(np.float32)
    out = np.zeros((1, 2, 2, 3, 3), np.float64)
    for o in range(2):
        for d in range(2):
            for i in range(3):
                for j in range(3):
                    out[0, o, d, i, j] = (
                        x3[0, :, d:d + 2, i:i + 2, j:j + 2] * w3[o]).sum()
    _check(lambda a, b: mx.sym.Convolution(a, b, kernel=(2, 2, 2),
                                           num_filter=2, no_bias=True),
           [x3, w3], out.astype(np.float32))


DECONV_CASES = [
    ((1, 2, 4, 4), 3, (3, 3), (1, 1), (0, 0)),
    ((1, 3, 4, 4), 2, (3, 3), (2, 2), (1, 1)),
    ((2, 2, 3, 5), 2, (2, 4), (2, 1), (0, 1)),
]


@pytest.mark.parametrize("case", DECONV_CASES,
                         ids=lambda c: "k%s_s%s_p%s" % (c[2], c[3], c[4]))
def test_deconvolution_variants(case):
    in_shape, nf, kernel, stride, pad = case
    r = _r(zlib.crc32(str(case).encode()))
    x = r.uniform(-1, 1, in_shape).astype(np.float32)
    w = r.uniform(-1, 1, (in_shape[1], nf) + kernel).astype(np.float32)
    ref = np_deconv2d(x, w, stride=stride, pad=pad)
    _check(lambda a, b: mx.sym.Deconvolution(
        a, b, kernel=kernel, num_filter=nf, stride=stride, pad=pad,
        no_bias=True), [x, w], ref)


# ---- Pooling variants -----------------------------------------------------

POOL_CASES = [
    ("max", (2, 2), (2, 2), (0, 0), "valid"),
    ("max", (3, 3), (1, 1), (1, 1), "valid"),
    ("avg", (2, 2), (2, 2), (0, 0), "valid"),
    ("avg", (3, 3), (2, 2), (1, 1), "valid"),
    ("max", (2, 2), (2, 2), (0, 0), "full"),
    ("sum", (2, 2), (2, 2), (0, 0), "valid"),
]


@pytest.mark.parametrize("case", POOL_CASES,
                         ids=lambda c: "%s_k%s_s%s_p%s_%s" % c)
def test_pooling_variants(case):
    pool_type, kernel, stride, pad, conv = case
    r = _r(zlib.crc32(str(case).encode()))
    # distinct values so max-pool FD has a unique argmax
    n = 1 * 2 * 7 * 7
    x = (r.permutation(np.arange(n) - n / 2) * 0.07) \
        .reshape(1, 2, 7, 7).astype(np.float32)

    if conv == "full":
        # ceil-mode output; compute via padded-valid equivalence
        H = 7 + 2 * pad[0]
        oh = -(-(H - kernel[0]) // stride[0]) + 1
        need = (oh - 1) * stride[0] + kernel[0] - H
        xp = np.pad(x, ((0, 0), (0, 0),
                        (pad[0], pad[0] + max(need, 0)),
                        (pad[1], pad[1] + max(need, 0))),
                    constant_values=-np.inf if pool_type == "max" else 0)
        ref = np_pool2d(xp, kernel, pool_type, stride, (0, 0))
    elif pool_type == "sum":
        ref = np_pool2d(x, kernel, "avg", stride, pad) * \
            (kernel[0] * kernel[1])
    else:
        ref = np_pool2d(x, kernel, pool_type, stride, pad)

    _check(lambda a: mx.sym.Pooling(
        a, kernel=kernel, pool_type=pool_type, stride=stride, pad=pad,
        pooling_convention=conv), [x], ref)


def test_global_pooling():
    r = _r(3)
    x = r.uniform(-1, 1, (2, 3, 5, 4)).astype(np.float32)
    _check(lambda a: mx.sym.Pooling(a, global_pool=True, pool_type="avg",
                                    kernel=(1, 1)),
           [x], x.mean(axis=(2, 3), keepdims=True))
    _check(lambda a: mx.sym.Pooling(a, global_pool=True, pool_type="max",
                                    kernel=(1, 1)),
           [x], x.max(axis=(2, 3), keepdims=True), grad=False)


# ---- dot / batch_dot transpose flags --------------------------------------

@pytest.mark.parametrize("ta,tb", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_dot_transpose_flags(ta, tb):
    r = _r(4)
    a = r.uniform(-1, 1, (4, 3) if ta else (3, 4)).astype(np.float32)
    b = r.uniform(-1, 1, (5, 4) if tb else (4, 5)).astype(np.float32)
    ref = (a.T if ta else a) @ (b.T if tb else b)
    _check(lambda x, y: mx.sym.dot(x, y, transpose_a=ta, transpose_b=tb),
           [a, b], ref)


@pytest.mark.parametrize("ta,tb", [(False, False), (True, False),
                                   (False, True), (True, True)])
def test_batch_dot_transpose_flags(ta, tb):
    r = _r(5)
    a = r.uniform(-1, 1, (2, 4, 3) if ta else (2, 3, 4)).astype(np.float32)
    b = r.uniform(-1, 1, (2, 5, 4) if tb else (2, 4, 5)).astype(np.float32)
    ref = np.matmul(a.transpose(0, 2, 1) if ta else a,
                    b.transpose(0, 2, 1) if tb else b)
    _check(lambda x, y: mx.sym.batch_dot(x, y, transpose_a=ta,
                                         transpose_b=tb), [a, b], ref)


# ---- softmax / norm axes --------------------------------------------------

@pytest.mark.parametrize("axis", [-1, 0, 1, 2])
def test_softmax_axes(axis):
    r = _r(6)
    x = r.uniform(-2, 2, (3, 4, 5)).astype(np.float32)
    _check(lambda a: mx.sym.softmax(a, axis=axis), [x],
           np_softmax(x, axis=axis))


@pytest.mark.parametrize("axis,keepdims,ord", [(0, False, 2), (1, True, 2),
                                               ((0, 1), False, 2),
                                               (1, False, 1)])
def test_norm_axes(axis, keepdims, ord):
    r = _r(7)
    x = r.uniform(-2, 2, (3, 4)).astype(np.float32) + 0.5
    if ord == 2:
        ref = np.sqrt((x ** 2).sum(axis=axis, keepdims=keepdims))
    else:
        ref = np.abs(x).sum(axis=axis, keepdims=keepdims)
    ref = np.asarray(ref, np.float32)
    _check(lambda a: mx.sym.norm(a, ord=ord, axis=axis, keepdims=keepdims),
           [x], ref, grad=(ord == 2))


# ---- BatchNorm axis + training-mode stats ---------------------------------

@pytest.mark.parametrize("axis", [1, -1])
def test_batchnorm_axis_training_stats(axis):
    r = _r(8)
    x = r.uniform(-1, 1, (4, 3, 5)).astype(np.float32)
    C = x.shape[axis]
    g = r.uniform(0.5, 1.5, (C,)).astype(np.float32)
    b = r.uniform(-0.5, 0.5, (C,)).astype(np.float32)
    mm = np.zeros(C, np.float32)
    mv = np.ones(C, np.float32)
    red = tuple(i for i in range(3) if i != (axis % 3))
    mean = x.mean(axis=red)
    var = x.var(axis=red)
    shape = [1, 1, 1]
    shape[axis % 3] = C
    ref = ((x - mean.reshape(shape)) / np.sqrt(var.reshape(shape) + 1e-3)
           * g.reshape(shape) + b.reshape(shape))

    xs, gs, bs = mx.sym.var("a0"), mx.sym.var("a1"), mx.sym.var("a2")
    mms = mx.sym.var("mm")
    mvs = mx.sym.var("mv")
    sym = mx.sym.BatchNorm(xs, gs, bs, mms, mvs, fix_gamma=False,
                           axis=axis, eps=1e-3)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null",
                         a0=x.shape, a1=g.shape, a2=b.shape)
    ex.arg_dict["a0"][:] = x
    ex.arg_dict["a1"][:] = g
    ex.arg_dict["a2"][:] = b
    ex.aux_dict["mm"][:] = mm
    ex.aux_dict["mv"][:] = mv
    out = ex.forward(is_train=True)[0].asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    # moving stats updated toward batch stats (momentum 0.9)
    np.testing.assert_allclose(ex.aux_dict["mm"].asnumpy(),
                               0.1 * mean, rtol=1e-3, atol=1e-5)
    np.testing.assert_allclose(ex.aux_dict["mv"].asnumpy(),
                               0.9 + 0.1 * var, rtol=1e-3, atol=1e-5)


# ---- take / clip / transpose / reshape ------------------------------------

@pytest.mark.parametrize("axis,mode", [(0, "clip"), (1, "clip"),
                                       (0, "wrap"), (2, "clip")])
def test_take_variants(axis, mode):
    r = _r(9)
    x = r.uniform(-1, 1, (4, 5, 6)).astype(np.float32)
    raw = np.array([[0, 2], [7, -1]], np.int64)  # out-of-range on purpose
    if mode == "clip":
        eff = np.clip(raw, 0, x.shape[axis] - 1)
    else:  # wrap
        eff = raw % x.shape[axis]
    ref = np.take(x, eff, axis=axis)
    out = nd.take(nd.array(x), nd.array(raw.astype(np.float32)),
                  axis=axis, mode=mode).asnumpy()
    np.testing.assert_allclose(out, ref, rtol=1e-6)


def test_transpose_orders():
    r = _r(10)
    x = r.uniform(-1, 1, (2, 3, 4, 5)).astype(np.float32)
    for axes in [(0, 1, 2, 3), (3, 2, 1, 0), (0, 2, 1, 3), (1, 0, 3, 2)]:
        _check(lambda a, axes=axes: mx.sym.transpose(a, axes=axes),
               [x], x.transpose(axes), grad=False)
    _check(lambda a: mx.sym.transpose(a), [x], x.T, grad=True)


def test_reshape_special_codes():
    """MXNet reshape special values (reference matrix_op-inl.h):
    0=copy dim, -1=infer, -2=copy rest, -3=merge two, -4=split."""
    r = _r(11)
    x = r.uniform(-1, 1, (2, 3, 4)).astype(np.float32)
    cases = [
        ((0, -1), (2, 12)),
        ((-1,), (24,)),
        ((0, 0, 0), (2, 3, 4)),
        ((-2,), (2, 3, 4)),
        ((-3, 0), (6, 4)),
        ((0, -3), (2, 12)),
        ((-4, 1, 2, 0, 0), (1, 2, 3, 4)),
        ((6, -1), (6, 4)),
    ]
    for shape_arg, want in cases:
        out = nd.reshape(nd.array(x), shape=shape_arg)
        assert out.shape == want, (shape_arg, out.shape, want)
        np.testing.assert_allclose(out.asnumpy().ravel(), x.ravel())


def test_clip_gradient_at_bounds():
    r = _r(12)
    x = np.array([-2.0, -0.5, 0.3, 0.9, 2.5], np.float32)
    _check(lambda a: mx.sym.clip(a, a_min=-1.0, a_max=1.0), [x],
           np.clip(x, -1, 1), grad=False)
    # gradient: 1 inside, 0 outside
    xn = nd.array(x)
    xn.attach_grad()
    import mxtpu.autograd as ag
    with ag.record():
        y = nd.clip(xn, a_min=-1.0, a_max=1.0)
    y.backward()
    np.testing.assert_allclose(xn.grad.asnumpy(), [0, 1, 1, 1, 0])


# ---- broadcasting edge shapes ---------------------------------------------

@pytest.mark.parametrize("sa,sb", [((1,), (3, 1)), ((2, 1, 4), (1, 3, 1)),
                                   ((3, 1), (3, 4)), ((1, 1), (2, 3))])
def test_broadcast_edge_shapes(sa, sb):
    r = _r(13)
    a = r.uniform(-1, 1, sa).astype(np.float32)
    b = r.uniform(0.5, 1.5, sb).astype(np.float32)
    for opn, npf in [("broadcast_add", np.add), ("broadcast_mul",
                                                 np.multiply),
                     ("broadcast_div", np.divide),
                     ("broadcast_maximum", np.maximum)]:
        _check(lambda x, y, opn=opn: getattr(mx.sym, opn)(x, y),
               [a, b], npf(a, b))


# ---- slice variants -------------------------------------------------------

def test_slice_variants():
    r = _r(14)
    x = r.uniform(-1, 1, (4, 6, 5)).astype(np.float32)
    cases = [
        ({"begin": (1,), "end": (3,)}, x[1:3]),
        ({"begin": (0, 2), "end": (4, 5)}, x[:, 2:5]),
        ({"begin": (1, 0, 1), "end": (3, 6, 4), "step": (1, 2, 1)},
         x[1:3, ::2, 1:4]),
        ({"begin": (None, 4), "end": (None, 1), "step": (None, -1)},
         x[:, 4:1:-1]),
    ]
    for params, ref in cases:
        _check(lambda a, params=params: mx.sym.slice(a, **params), [x],
               ref, grad=False)
    _check(lambda a: mx.sym.slice_axis(a, axis=2, begin=-3, end=-1), [x],
           x[:, :, -3:-1])


# ---- dropout / upsampling / leaky / embedding variants --------------------

import mxtpu.autograd as ag  # noqa: E402


def test_dropout_axes_broadcast_mask():
    """Dropout with axes=(0,): one mask broadcast over the batch axis
    (reference nn/dropout-inl.h axes param)."""
    mx.random.seed(5)
    x = nd.array(np.ones((8, 64), np.float32))
    with ag.train_mode():
        y = nd.Dropout(x, p=0.5, axes=(0,))
    out = y.asnumpy()
    # every row identical (mask shared across axis 0), values 0 or 1/(1-p)
    for row in out[1:]:
        np.testing.assert_array_equal(row, out[0])
    vals = np.unique(out)
    assert set(np.round(vals, 4)).issubset({0.0, 2.0}), vals
    # eval mode: identity
    assert np.array_equal(nd.Dropout(x, p=0.5).asnumpy(), x.asnumpy())


def test_upsampling_nearest_symbolic():
    r = _r(20)
    x = r.uniform(-1, 1, (1, 2, 3, 3)).astype(np.float32)
    _check(lambda a: mx.sym.UpSampling(a, scale=2, sample_type="nearest"),
           [x], np.repeat(np.repeat(x, 2, 2), 2, 3))


def test_upsampling_bilinear_interpolates():
    """Bilinear upsampling of a linear ramp interpolates (monotonic, with
    values strictly between grid points) — a nearest-neighbor regression
    would produce a repeated staircase."""
    ramp = np.arange(4, dtype=np.float32).reshape(1, 1, 1, 4)
    ramp = np.broadcast_to(ramp, (1, 1, 4, 4)).copy()
    w = np.ones((1, 1, 4, 4), np.float32)
    out = nd.UpSampling(nd.array(ramp), nd.array(w), scale=2,
                        sample_type="bilinear", num_filter=1,
                        num_args=2).asnumpy()
    assert out.shape == (1, 1, 8, 8)
    row = out[0, 0, 4]
    assert np.all(np.diff(row) >= -1e-6), row          # monotone ramp
    nearest = np.repeat(ramp[0, 0, 2], 2)
    assert not np.allclose(row, nearest), "staircase = not bilinear"
    interior = row[1:-1]
    assert np.unique(np.round(interior, 4)).size > 4   # true interpolation


@pytest.mark.parametrize("act,reff", [
    ("leaky", lambda x: np.where(x >= 0, x, 0.25 * x)),
    ("elu", lambda x: np.where(x >= 0, x, 0.25 * np.expm1(x))),
    ("selu", lambda x: 1.0507009873554805 *
     np.where(x >= 0, x, 1.6732632423543772 * np.expm1(x))),
])
def test_leaky_relu_family_symbolic(act, reff):
    x = np.array([[-2.0, -0.5, 0.5, 2.0]], np.float32)
    _check(lambda a: mx.sym.LeakyReLU(a, act_type=act), [x], reff(x))


def test_prelu_symbolic():
    x = np.array([-2.0, -0.5, 0.5, 2.0], np.float32)
    x2 = np.broadcast_to(x[:, None], (4, 2)).copy()  # (batch, channel)
    g = np.array([0.2, 0.3], np.float32)
    _check(lambda a, b: mx.sym.LeakyReLU(a, b, act_type="prelu"),
           [x2, g], np.where(x2 >= 0, x2, g * x2))


def test_embedding_grad_rows():
    """Embedding gradient only touches looked-up rows; repeated indices
    accumulate (the sparse-grad contract densely realized) — checked
    through BOTH the tape and the symbolic executor."""
    w_np = np.arange(12, dtype=np.float32).reshape(4, 3)
    idx_np = np.array([1, 1, 3], np.float32)
    expected = np.zeros((4, 3), np.float32)
    expected[1] = 2
    expected[3] = 1

    w = nd.array(w_np)
    w.attach_grad()
    with ag.record():
        out = nd.Embedding(nd.array(idx_np), w, input_dim=4, output_dim=3)
    out.backward(nd.array(np.ones((3, 3), np.float32)))
    np.testing.assert_allclose(w.grad.asnumpy(), expected)

    sym = mx.sym.Embedding(mx.sym.var("idx"), mx.sym.var("w"),
                           input_dim=4, output_dim=3)
    ex = sym.simple_bind(ctx=mx.cpu(),
                         grad_req={"idx": "null", "w": "write"},
                         idx=idx_np.shape, w=w_np.shape)
    ex.arg_dict["idx"][:] = idx_np
    ex.arg_dict["w"][:] = w_np
    ex.forward(is_train=True)
    ex.backward([nd.array(np.ones((3, 3), np.float32))])
    np.testing.assert_allclose(ex.grad_dict["w"].asnumpy(), expected)


# ===========================================================================
# Reference torture grids (tests/python/unittest/test_operator.py:998
# deconvolution, :1133 batchnorm-training, :1219 grouped convolution,
# :1641 dilated convolution): systematic stride x dilate x pad x group
# sweeps on odd shapes, fwd vs numpy + FD grads + bf16 consistency tiers.
# ===========================================================================

from mxtpu.test_utils import check_consistency  # noqa: E402


def _grouped_conv_ref(x, w, b, stride, pad, dilate, groups):
    cin_g = x.shape[1] // groups
    nf = w.shape[0]
    parts = []
    for g in range(groups):
        parts.append(np_conv2d(
            x[:, g * cin_g:(g + 1) * cin_g],
            w[g * (nf // groups):(g + 1) * (nf // groups)],
            None, stride=stride, pad=pad, dilate=dilate))
    ref = np.concatenate(parts, axis=1)
    if b is not None:
        ref = ref + b.reshape(1, -1, 1, 1)
    return ref


# full cartesian grid at odd spatial sizes; forward everywhere, FD
# gradients on the diagonal slice (every config family appears in it)
CONV_GRID = [(s, d, p, g)
             for s in [(1, 1), (2, 2), (2, 1)]
             for d in [(1, 1), (2, 2)]
             for p in [(0, 0), (1, 1), (2, 1)]
             for g in [1, 2]]


@pytest.mark.parametrize("case", CONV_GRID,
                         ids=lambda c: "s%s_d%s_p%s_g%d" % c)
def test_convolution_grid_forward(case):
    stride, dilate, pad, groups = case
    r = _r(zlib.crc32(("grid%s" % (case,)).encode()))
    x = r.uniform(-1, 1, (2, 4, 11, 9)).astype(np.float32)
    w = r.uniform(-1, 1, (4, 4 // groups, 3, 3)).astype(np.float32)
    b = r.uniform(-1, 1, (4,)).astype(np.float32)
    ref = _grouped_conv_ref(x, w, b, stride, pad, dilate, groups)
    _check(lambda a, ww, bb: mx.sym.Convolution(
        a, ww, bb, kernel=(3, 3), num_filter=4, stride=stride, pad=pad,
        dilate=dilate, num_group=groups), [x, w, b], ref, grad=False)


@pytest.mark.parametrize("case", [
    ((2, 2), (1, 1), (0, 0), 1),
    ((1, 1), (2, 2), (1, 1), 2),
    ((2, 1), (1, 1), (2, 1), 2),
    ((2, 2), (2, 2), (2, 2), 1),
], ids=lambda c: "s%s_d%s_p%s_g%d" % c)
def test_convolution_grid_gradients(case):
    stride, dilate, pad, groups = case
    r = _r(zlib.crc32(("gridg%s" % (case,)).encode()))
    x = r.uniform(-1, 1, (1, 2, 9, 7)).astype(np.float32)
    w = r.uniform(-1, 1, (2, 2 // groups, 3, 3)).astype(np.float32)
    b = r.uniform(-1, 1, (2,)).astype(np.float32)
    ref = _grouped_conv_ref(x, w, b, stride, pad, dilate, groups)
    _check(lambda a, ww, bb: mx.sym.Convolution(
        a, ww, bb, kernel=(3, 3), num_filter=2, stride=stride, pad=pad,
        dilate=dilate, num_group=groups), [x, w, b], ref)


@pytest.mark.parametrize("dim", [1, 2, 3])
def test_convolution_grouping_equals_sliced_concat(dim):
    """Grouped conv == concat of per-group convs, fwd AND grads through
    two executors (the reference :1219 property, all spatial dims)."""
    num_filter, num_group = 4, 2
    kernel = (3,) * dim
    shape = (1, 4) + (7,) * dim
    r = _r(100 + dim)

    x = mx.sym.var("x")
    w = mx.sym.var("w")
    b = mx.sym.var("b")
    y1 = mx.sym.Convolution(x, w, b, num_filter=num_filter,
                            num_group=num_group, kernel=kernel)
    xs = mx.sym.SliceChannel(x, num_outputs=num_group, axis=1)
    ws = mx.sym.SliceChannel(w, num_outputs=num_group, axis=0)
    bs = mx.sym.SliceChannel(b, num_outputs=num_group, axis=0)
    y2 = mx.sym.Concat(*[
        mx.sym.Convolution(xs[i], ws[i], bs[i],
                           num_filter=num_filter // num_group,
                           kernel=kernel)
        for i in range(num_group)])

    wshape = (num_filter, shape[1] // num_group) + kernel
    ex1 = y1.simple_bind(mx.cpu(), x=shape, w=wshape, b=(num_filter,))
    ex2 = y2.simple_bind(mx.cpu(), x=shape, w=wshape, b=(num_filter,))
    for name in ("x", "w", "b"):
        v = r.normal(size=ex1.arg_dict[name].shape).astype(np.float32)
        ex1.arg_dict[name][:] = v
        ex2.arg_dict[name][:] = v
    o1 = ex1.forward(is_train=True)[0]
    o2 = ex2.forward(is_train=True)[0]
    np.testing.assert_allclose(o1.asnumpy(), o2.asnumpy(),
                               rtol=1e-4, atol=1e-5)
    ex1.backward([o1])
    ex2.backward([o2])
    for name in ("x", "w", "b"):
        np.testing.assert_allclose(ex1.grad_dict[name].asnumpy(),
                                   ex2.grad_dict[name].asnumpy(),
                                   rtol=1e-3, atol=1e-4)


DEPTHWISE_GRID = [(c, k, s, p, hw)
                  for c in [4, 8]
                  for k in [3, 5]
                  for s in [1, 2]
                  for p in [0, 1]
                  for hw in [7, 12]]


@pytest.mark.parametrize("case", DEPTHWISE_GRID,
                         ids=lambda c: "c%d_k%d_s%d_p%d_hw%d" % c)
def test_depthwise_convolution_grid(case):
    """num_group == channels (the reference :1282 depthwise grid)."""
    c, k, s, p, hw = case
    if hw + 2 * p < k:
        pytest.skip("kernel larger than padded input")
    r = _r(zlib.crc32(("dw%s" % (case,)).encode()))
    x = r.uniform(-1, 1, (2, c, hw, hw)).astype(np.float32)
    w = r.uniform(-1, 1, (c, 1, k, k)).astype(np.float32)
    ref = _grouped_conv_ref(x, w, None, (s, s), (p, p), (1, 1), c)
    _check(lambda a, ww: mx.sym.Convolution(
        a, ww, kernel=(k, k), num_filter=c, num_group=c, stride=(s, s),
        pad=(p, p), no_bias=True), [x, w], ref, grad=False)


def test_convolution_dilated_impulse_response():
    """A unit impulse through a dilated conv places kernel taps exactly
    `dilate` apart (the reference :1641 impulse-response check)."""
    for dil in [1, 2, 3]:
        for ks in [1, 2, 3]:
            n = 18
            x = np.zeros((1, 1, n, n), np.float32)
            x[0, 0, n // 2, n // 2] = 1.0
            w = np.ones((1, 1, ks, ks), np.float32)
            out = nd.Convolution(
                nd.array(x), nd.array(w), kernel=(ks, ks), num_filter=1,
                dilate=(dil, dil), no_bias=True).asnumpy()[0, 0]
            ys, xs = np.nonzero(out)
            assert len(ys) == ks * ks, (dil, ks, len(ys))
            if ks > 1:
                assert np.diff(np.unique(ys)).min() == dil
                assert np.diff(np.unique(xs)).min() == dil


# ---- deconvolution: target_shape / adj / stride grid ----------------------

def test_deconvolution_target_shape_overrides_pad_adj():
    """target_shape wins over (nonsense) pad/adj, 1-D and 2-D
    (reference :998 check_deconvolution_target_shape)."""
    x = mx.sym.var("x")
    d2 = mx.sym.Deconvolution(x, mx.sym.var("w"), kernel=(3, 3),
                              num_filter=5, stride=(2, 2),
                              target_shape=(8, 8), pad=(99, 99),
                              adj=(101, 101), no_bias=True)
    _, outs, _ = d2.infer_shape(x=(2, 3, 4, 4))
    assert outs[0] == (2, 5, 8, 8), outs
    d1 = mx.sym.Deconvolution(x, mx.sym.var("w"), kernel=(3,),
                              num_filter=5, stride=(2,),
                              target_shape=(8,), pad=(99,), adj=(101,),
                              no_bias=True)
    _, outs, _ = d1.infer_shape(x=(2, 3, 4))
    assert outs[0] == (2, 5, 8), outs
    # explicit pad+adj route to the same 8x8 (reference's second case)
    d3 = mx.sym.Deconvolution(x, mx.sym.var("w"), kernel=(3, 3),
                              num_filter=5, stride=(2, 2), pad=(1, 1),
                              adj=(1, 1), no_bias=True)
    _, outs, _ = d3.infer_shape(x=(2, 3, 4, 4))
    assert outs[0] == (2, 5, 8, 8), outs


DECONV_GRID = [
    # (in_shape, kernel, stride, pad, adj)
    ((1, 1, 5, 5), (3, 3), (1, 1), (1, 1), (0, 0)),
    ((4, 3, 14, 14), (3, 3), (1, 1), (1, 1), (0, 0)),
    ((2, 3, 16, 16), (7, 7), (5, 5), (2, 2), (0, 0)),
    ((1, 2, 6, 6), (3, 3), (2, 2), (1, 1), (1, 1)),
    ((1, 1, 5), (3,), (1,), (1,), (0,)),
    ((2, 3, 14), (3,), (1,), (1,), (0,)),
    ((2, 3, 16), (7,), (5,), (2,), (0,)),
]


@pytest.mark.parametrize("case", DECONV_GRID,
                         ids=lambda c: "i%s_k%s_s%s_p%s_a%s" % c)
def test_deconvolution_forward_backward_grid(case):
    """Deconv == adjoint of conv: fwd vs numpy upsample-conv ref, grads
    by FD (reference :998 check_deconvolution_forward_backward grid,
    medium shapes)."""
    in_shape, kernel, stride, pad, adj = case
    nsp = len(kernel)
    r = _r(zlib.crc32(("dc%s" % (case,)).encode()))
    nf = 2
    x = r.uniform(-1, 1, in_shape).astype(np.float32)
    w = r.uniform(-1, 1, (in_shape[1], nf) + kernel).astype(np.float32)
    # adj extends the output at the far edge with COMPUTED positions
    # (not zeros): take the full (pad=0) transposed conv and slice
    # [pad : full - pad + adj] per spatial dim
    if nsp == 1:
        full = np_deconv2d(x[:, :, None, :], w[:, :, None, :],
                           stride=(1,) + stride, pad=(0, 0))
        ref = full[:, :, 0, pad[0]:full.shape[3] - pad[0] + adj[0]]
    else:
        full = np_deconv2d(x, w, stride=stride, pad=(0, 0))
        ref = full[:, :, pad[0]:full.shape[2] - pad[0] + adj[0],
                   pad[1]:full.shape[3] - pad[1] + adj[1]]
    big = int(np.prod(in_shape)) > 400
    _check(lambda a, ww: mx.sym.Deconvolution(
        a, ww, kernel=kernel, num_filter=nf, stride=stride, pad=pad,
        adj=adj, no_bias=True), [x, w], ref, grad=not big)


# ---- BatchNorm: fix_gamma x use_global_stats x axis grid -------------------

BN_GRID = [(shape, fix_gamma, use_global, axis)
           for shape in [(2, 3), (2, 3, 2, 2)]
           for fix_gamma in [True, False]
           for use_global in [True, False]
           for axis in [1, -1, 0]]


@pytest.mark.parametrize("case", BN_GRID,
                         ids=lambda c: "s%dd_fg%d_gs%d_ax%d" % (
                             len(c[0]), c[1], c[2], c[3]))
def test_batchnorm_grid_gradients(case):
    """FD gradients across the BN mode grid (reference :1133
    test_batchnorm_training, incl. varying channel axis)."""
    shape, fix_gamma, use_global, axis = case
    r = _r(zlib.crc32(("bn%s" % (case,)).encode()))
    x = r.normal(-0.1, 1.0, size=shape).astype(np.float32)
    C = shape[axis % len(shape)]
    gamma = np.ones(C, np.float32)
    beta = np.ones(C, np.float32)
    if C > 1:
        gamma[1] = 3
    beta[0] = 3
    mm = r.uniform(0.2, 1.0, C).astype(np.float32)
    mv = r.uniform(0.5, 1.5, C).astype(np.float32)

    sym = mx.sym.BatchNorm(mx.sym.var("a0"), mx.sym.var("a1"),
                           mx.sym.var("a2"), mx.sym.var("mm"),
                           mx.sym.var("mv"), fix_gamma=fix_gamma,
                           use_global_stats=use_global, axis=axis)
    check_numeric_gradient(
        sym, {"a0": x, "a1": gamma, "a2": beta},
        aux_states={"mm": mm, "mv": mv},
        grad_nodes=["a0"] if fix_gamma else ["a0", "a1", "a2"],
        numeric_eps=1e-2, rtol=0.16, atol=1e-2)


def test_batchnorm_output_mean_var():
    r = _r(77)
    x = r.normal(0, 1, (4, 3, 5)).astype(np.float32)
    sym = mx.sym.BatchNorm(mx.sym.var("a0"), mx.sym.var("g"),
                           mx.sym.var("b"), mx.sym.var("mm"),
                           mx.sym.var("mv"), fix_gamma=False,
                           output_mean_var=True)
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="null", a0=x.shape,
                         g=(3,), b=(3,))
    ex.arg_dict["a0"][:] = x
    ex.arg_dict["g"][:] = np.ones(3, np.float32)
    ex.arg_dict["b"][:] = np.zeros(3, np.float32)
    ex.aux_dict["mm"][:] = np.zeros(3, np.float32)
    ex.aux_dict["mv"][:] = np.ones(3, np.float32)
    outs = ex.forward(is_train=True)
    assert len(outs) == 3
    np.testing.assert_allclose(outs[1].asnumpy(), x.mean(axis=(0, 2)),
                               rtol=1e-4, atol=1e-5)
    # third output is the INVERSE std (reference batch_norm.cc saves
    # 1/sqrt(var+eps), not the variance)
    np.testing.assert_allclose(
        outs[2].asnumpy(),
        1.0 / np.sqrt(x.var(axis=(0, 2)) + 1e-3),
        rtol=1e-4, atol=1e-5)


def test_deconvolution_target_shape_validation():
    r = _r(78)
    x = r.normal(0, 1, (1, 2, 5, 5)).astype(np.float32)
    w = r.normal(0, 1, (2, 3, 3, 3)).astype(np.float32)
    # achievable target: solved pad/adj must reproduce the shape exactly
    out = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                              stride=(2, 2), num_filter=3,
                              target_shape=(10, 10))
    assert out.shape == (1, 3, 10, 10)
    # all-zero target_shape means "unset" (reference bCal ignores it)
    out0 = mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                               stride=(2, 2), num_filter=3,
                               target_shape=(0, 0))
    assert out0.shape == (1, 3, 11, 11)
    # unachievable target: reference CHECK_GE "too big target shape"
    with pytest.raises(ValueError, match="too big target shape"):
        mx.nd.Deconvolution(mx.nd.array(x), mx.nd.array(w), kernel=(3, 3),
                            stride=(2, 2), num_filter=3,
                            target_shape=(40, 40))


# ---- bf16 consistency tiers (check_consistency, reference GPU fp16 tier) --

def _bf16_ctx_list(**shapes):
    import jax.numpy as jnp
    fp32 = {"ctx": mx.cpu(),
            "type_dict": {k: np.float32 for k in shapes}}
    bf16 = {"ctx": mx.cpu(),
            "type_dict": {k: jnp.bfloat16 for k in shapes}}
    fp32.update(shapes)
    bf16.update(shapes)
    return [fp32, bf16]


def test_conv_bf16_consistency():
    np.random.seed(11)
    sym = mx.sym.Convolution(mx.sym.var("a0"), mx.sym.var("a1"),
                             kernel=(3, 3), num_filter=4, pad=(1, 1),
                             no_bias=True, name="conv")
    check_consistency(sym, _bf16_ctx_list(a0=(2, 3, 8, 8),
                                          a1=(4, 3, 3, 3)))


def test_fc_bf16_consistency():
    np.random.seed(12)
    sym = mx.sym.FullyConnected(mx.sym.var("a0"), mx.sym.var("a1"),
                                mx.sym.var("a2"), num_hidden=8)
    check_consistency(sym, _bf16_ctx_list(a0=(4, 16), a1=(8, 16),
                                          a2=(8,)))


def test_pool_bf16_consistency():
    np.random.seed(13)
    sym = mx.sym.Pooling(mx.sym.var("a0"), kernel=(2, 2), stride=(2, 2),
                         pool_type="max")
    check_consistency(sym, _bf16_ctx_list(a0=(2, 3, 8, 8)))


def test_bn_bf16_consistency():
    np.random.seed(14)
    sym = mx.sym.BatchNorm(mx.sym.var("a0"), mx.sym.var("a1"),
                           mx.sym.var("a2"), mx.sym.var("mm"),
                           mx.sym.var("mv"), fix_gamma=False)
    check_consistency(sym, _bf16_ctx_list(a0=(4, 3, 6, 6), a1=(3,),
                                          a2=(3,)))


# ---- pooling depth: 1-D/3-D, count_include_pad, stride>kernel -------------

def test_pooling_1d_3d():
    r = _r(31)
    # 1-D max/avg (NCW)
    x1 = r.uniform(-1, 1, (2, 3, 9)).astype(np.float32)
    ref = np_pool2d(x1[:, :, None, :], (1, 3), "max", (1, 2),
                    (0, 0))[:, :, 0]
    _check(lambda a: mx.sym.Pooling(a, kernel=(3,), stride=(2,),
                                    pool_type="max"), [x1], ref,
           grad=False)
    # 3-D avg (NCDHW) vs explicit loop
    x3 = r.uniform(-1, 1, (1, 2, 4, 4, 4)).astype(np.float32)
    out = np.zeros((1, 2, 2, 2, 2), np.float64)
    for d in range(2):
        for i in range(2):
            for j in range(2):
                out[0, :, d, i, j] = x3[0, :, 2*d:2*d+2, 2*i:2*i+2,
                                        2*j:2*j+2].mean(axis=(1, 2, 3))
    _check(lambda a: mx.sym.Pooling(a, kernel=(2, 2, 2),
                                    stride=(2, 2, 2), pool_type="avg"),
           [x3], out.astype(np.float32))


def test_pooling_stride_exceeds_kernel():
    """stride > kernel skips input positions entirely (valid in the
    reference; windows must not overlap or read out of bounds)."""
    r = _r(32)
    x = r.uniform(-1, 1, (1, 1, 8, 8)).astype(np.float32)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(3, 3),
                     pool_type="max").asnumpy()
    assert out.shape == (1, 1, 3, 3)
    want = np.zeros((3, 3), np.float32)
    for i in range(3):
        for j in range(3):
            want[i, j] = x[0, 0, 3*i:3*i+2, 3*j:3*j+2].max()
    np.testing.assert_allclose(out[0, 0], want, rtol=1e-6)


def test_avg_pool_count_include_pad():
    """count_include_pad=False divides by the VALID window size at the
    borders (reference pooling-inl.h GetPadAvg behavior)."""
    x = np.ones((1, 1, 3, 3), np.float32)
    incl = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                      pad=(1, 1), pool_type="avg",
                      count_include_pad=True).asnumpy()
    excl = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                      pad=(1, 1), pool_type="avg",
                      count_include_pad=False).asnumpy()
    # corner window: one valid element of value 1
    np.testing.assert_allclose(excl[0, 0, 0, 0], 1.0, rtol=1e-6)
    np.testing.assert_allclose(incl[0, 0, 0, 0], 0.25, rtol=1e-6)
