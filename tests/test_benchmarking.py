"""The honest-timing helpers every published number flows through
(mxtpu/benchmarking.py): host-fetch sync, zero-valued input chaining,
and the difference-timed loop. On the CPU backend block_until_ready is
trustworthy, so the loop's output can be cross-checked against a naive
wall-clock measurement here; on the TPU relay only the contract tested
below (fetch returns real bytes, chaining preserves values, per-iter
positive and finite) is checkable without hardware."""
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxtpu as mx
from mxtpu.benchmarking import chain_input, hostsync, timed_loop


def test_hostsync_fetches_first_scalar():
    x = jnp.arange(12.0).reshape(3, 4) + 5
    assert float(hostsync(x)) == 5.0
    # pytrees: first leaf wins
    assert float(hostsync({"a": x * 2, "b": x})) == 10.0
    # mxtpu NDArray
    nd = mx.nd.array(np.full((2, 2), 7.0, "f"))
    assert float(hostsync(nd)) == 7.0


def test_hostsync_refuses_unfetchable_state():
    # a step that mutates in place and returns None must be rejected —
    # silently skipping the barrier would revert the loop to measuring
    # dispatch rate (the bug the module exists to fix)
    with pytest.raises(TypeError):
        hostsync(None)
    with pytest.raises(TypeError):
        hostsync([])
    with pytest.raises(TypeError):
        hostsync(jnp.zeros((0,)))


def test_chain_input_preserves_values_jax():
    x = jnp.arange(6.0).reshape(2, 3)
    out = jnp.full((4,), 123.0)
    chained = chain_input(x, out)
    np.testing.assert_array_equal(np.asarray(chained), np.asarray(x))
    assert chained.dtype == x.dtype


def test_chain_input_preserves_values_ndarray():
    x = mx.nd.array(np.arange(6.0, dtype="f").reshape(2, 3))
    out = x * 3 + 1
    chained = chain_input(x, out)
    np.testing.assert_array_equal(chained.asnumpy(), x.asnumpy())
    assert chained.dtype == x.dtype


def test_chain_input_bf16_dtype_stays():
    x = jnp.ones((2, 2), jnp.bfloat16)
    out = jnp.ones((2, 2), jnp.float32)
    assert chain_input(x, out).dtype == jnp.bfloat16


def test_timed_loop_matches_wall_clock_on_cpu():
    # a deliberately slow chained step: per-iter from the difference
    # method must agree with an honest direct measurement on CPU, where
    # block_until_ready really blocks
    n = 256
    b = jax.random.normal(jax.random.PRNGKey(0), (n, n))
    f = jax.jit(lambda x: x @ b / np.sqrt(n))

    def step(s):
        return f(b if s is None else s)

    per, state = timed_loop(step, lo_iters=4, min_work_s=0.02,
                            max_iters=512)
    assert state is not None
    # direct: 50 chained iters, block each... once at the end suffices
    x = b
    t0 = time.perf_counter()
    for _ in range(50):
        x = f(x)
    jax.block_until_ready(x)
    direct = (time.perf_counter() - t0) / 50
    assert per > 0
    assert per < max(direct * 5, 5e-3)
    assert per > direct / 5 or direct < 50e-6


def test_timed_loop_threads_state():
    seen = []

    def step(s):
        s = 0 if s is None else s
        seen.append(s)
        return jnp.float32(s + 1)

    per, final = timed_loop(step, lo_iters=2, min_work_s=-1.0,
                            max_iters=8)
    assert per != 0
    # settle(1) + N + 3N iterations, state carried through all of them
    assert len(seen) == 1 + 2 + 6
    assert int(final) == len(seen)
