"""Continuous-batching generation fast tier (ISSUE 17): the
cached-attention op's prefill/decode bit-compat, the engine's AOT
prefill/adopt/decode programs against a full-recompute oracle, the
continuous scheduler's join/leave semantics, and the wire streaming
protocol under faults — kill -9 mid-generation, dropped token frames,
live hot-swap, and mid-generation expiry (point=serve.step).

The two-process kill -9 drill with a real trainer publishing swaps
lives in tests/test_dist_launch.py; the perf pin (zero retraces, no
host syncs, batching wins) in ci/check_generate_perf.py.
"""
import os
import threading
import time

import numpy as np
import pytest

import mxtpu as mx
from mxtpu import fault
from mxtpu import kvstore_async as ka
from mxtpu.serving import (DeadlineExceeded, InferenceEngine,
                           ModelServer, ServingClient)

V, D, S = 17, 8, 16


@pytest.fixture(autouse=True)
def _serving_knobs(monkeypatch):
    monkeypatch.setenv("MXTPU_PS_HEARTBEAT", "0")
    monkeypatch.setenv("MXTPU_SERVE_GENERATE_SLOTS", "4")
    monkeypatch.setenv("MXTPU_SERVE_GENERATE_PREFILL_BUCKETS", "4,8,16")
    monkeypatch.setattr(ka, "_RETRIES", 1)
    monkeypatch.setattr(ka, "_BACKOFF", 0.01)
    monkeypatch.setattr(ka, "_BACKOFF_MAX", 0.05)
    monkeypatch.setattr(ka, "_RECONNECT_TIMEOUT", 0.2)
    monkeypatch.setattr(ka, "_DEAD_AFTER", 2)
    fault.uninstall()
    yield
    fault.uninstall()


def _lm_symbol(cache_len=S, alibi=False):
    data = mx.sym.Variable("data")
    pos = mx.sym.Variable("pos", shape=(0,), dtype="int32")
    kc = mx.sym.Variable("kc", shape=(0, cache_len, D))
    vc = mx.sym.Variable("vc", shape=(0, cache_len, D))
    emb = mx.sym.Embedding(data=data, input_dim=V, output_dim=D,
                           name="emb")
    q = mx.sym.FullyConnected(data=emb, num_hidden=D, flatten=False,
                              name="q")
    k = mx.sym.FullyConnected(data=emb, num_hidden=D, flatten=False,
                              name="k")
    v = mx.sym.FullyConnected(data=emb, num_hidden=D, flatten=False,
                              name="v")
    att = mx.sym.cached_attention(q, k, v, kc, vc, pos, num_heads=2,
                                  alibi=alibi, name="att")
    out = mx.sym.FullyConnected(data=att[0], num_hidden=V,
                                flatten=False, name="proj")
    return mx.sym.Group([out,
                         mx.sym.identity(att[1], name="kc_next"),
                         mx.sym.identity(att[2], name="vc_next")])


def _lm_params(seed=7):
    rng = np.random.RandomState(seed)
    f = lambda *s: rng.randn(*s).astype(np.float32) * 0.5  # noqa: E731
    return {"emb_weight": f(V, D),
            "q_weight": f(D, D), "q_bias": np.zeros(D, np.float32),
            "k_weight": f(D, D), "k_bias": np.zeros(D, np.float32),
            "v_weight": f(D, D), "v_bias": np.zeros(D, np.float32),
            "proj_weight": f(V, D), "proj_bias": np.zeros(V, np.float32)}


def _engine(seed=7, alibi=False, cache_len=S):
    return InferenceEngine(_lm_symbol(cache_len, alibi=alibi),
                           _lm_params(seed), {},
                           data_shapes={"data": (1,)}, buckets=(1,))


def _oracle(eng, prompt, n):
    """Greedy continuation by FULL RECOMPUTE: re-prefill the growing
    prompt each step — no KV reuse, the independent reference the
    cached decode path must match bit-for-bit."""
    import jax
    store = eng._resolve_store(None)
    cur = list(prompt)
    out = []
    for _ in range(n):
        first, _rows = eng.gen_prefill(np.asarray(cur, np.int32),
                                       store[0], store[1])
        t = int(jax.device_get(first)[0])
        out.append(t)
        cur.append(t)
    return out


# ---------------------------------------------------------------------------
# the op: prefill chunk == token-at-a-time decode chain
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("alibi", [False, True])
def test_cached_attention_prefill_equals_decode_chain(alibi):
    """Attending T tokens in one prefill call is bit-compatible with
    feeding them one at a time through the cache — with and without
    the ALiBi distance bias (absolute cache positions make the bias
    identical across the two schedules)."""
    import jax.numpy as jnp
    from mxtpu.ops.nn import cached_attention
    rng = np.random.RandomState(0)
    B, T, H = 2, 6, 2
    q = rng.randn(B, T, D).astype(np.float32)
    k = rng.randn(B, T, D).astype(np.float32)
    v = rng.randn(B, T, D).astype(np.float32)
    zeros = np.zeros((B, S, D), np.float32)
    full, kn, vn = cached_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(zeros), jnp.asarray(zeros),
        jnp.zeros((B,), jnp.int32), num_heads=H, alibi=alibi)
    full = np.asarray(full)
    assert np.allclose(np.asarray(kn)[:, :T], k, atol=1e-6)
    kc = vc = jnp.asarray(zeros)
    for t in range(T):
        step, kc, vc = cached_attention(
            jnp.asarray(q[:, t:t + 1]), jnp.asarray(k[:, t:t + 1]),
            jnp.asarray(v[:, t:t + 1]), kc, vc,
            jnp.full((B,), t, jnp.int32), num_heads=H, alibi=alibi)
        assert np.allclose(np.asarray(step)[:, 0], full[:, t],
                           atol=1e-5), "diverged at step %d" % t


def test_cached_attention_alibi_changes_the_answer():
    """The bias is actually applied (not silently dropped), and the
    JSON attr round-trip spelling \"True\"/\"False\" is honoured."""
    import jax.numpy as jnp
    from mxtpu.ops.nn import cached_attention
    rng = np.random.RandomState(1)
    q = rng.randn(1, 4, D).astype(np.float32)
    zeros = np.zeros((1, S, D), np.float32)
    run = lambda a: np.asarray(cached_attention(  # noqa: E731
        jnp.asarray(q), jnp.asarray(q), jnp.asarray(q),
        jnp.asarray(zeros), jnp.asarray(zeros),
        jnp.zeros((1,), jnp.int32), num_heads=2, alibi=a)[0])
    assert not np.allclose(run(True), run(False))
    assert np.array_equal(run("True"), run(True))
    assert np.array_equal(run("False"), run(False))


# ---------------------------------------------------------------------------
# the engine: contract detection + decode vs full recompute
# ---------------------------------------------------------------------------

def test_engine_detects_generate_contract():
    eng = _engine()
    assert eng.is_generative
    spec = eng.generate_spec()
    assert spec["token_input"] == "data"
    assert sorted(spec["states"]) == ["kc", "vc"]
    assert spec["cache_len"] == S
    assert spec["prefill_buckets"] == [4, 8, 16]
    plain = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                  num_hidden=3, name="fc")
    eng2 = InferenceEngine(plain, {"fc_weight": np.zeros((3, 4), "f"),
                                   "fc_bias": np.zeros(3, "f")}, {},
                           {"data": (4,)}, buckets=(1,), warm=False)
    assert not eng2.is_generative
    assert eng2.generate_spec() is None


@pytest.mark.parametrize("alibi", [False, True])
def test_decode_matches_full_recompute_zero_retrace(alibi):
    """The served greedy continuation (cached, slot-packed, donated
    decode) equals the full-recompute oracle, and a second sequence
    through the warmed menu compiles NOTHING new."""
    eng = _engine(alibi=alibi)
    ref = _oracle(eng, [3, 1, 4], 10)
    srv = ModelServer(eng, port=0, model_name="lm").start()
    try:
        cli = ServingClient(addrs=[srv.address])
        toks, info = cli.generate2([3, 1, 4], max_new=10, model="lm")
        assert toks == ref, (toks, ref)
        assert info["reason"] == "len" and info["version"] == 0
        before = eng.cache.compiles
        toks2, _ = cli.generate2([3, 1, 4], max_new=10, model="lm")
        assert toks2 == ref
        assert eng.cache.compiles == before, \
            "steady-state decode retraced"
    finally:
        srv.stop()


def test_eos_stops_early():
    eng = _engine()
    ref = _oracle(eng, [3, 1, 4], 10)
    srv = ModelServer(eng, port=0, model_name="lm").start()
    try:
        cli = ServingClient(addrs=[srv.address])
        j = next(i for i in range(1, 10) if ref[i] not in ref[:i])
        toks, info = cli.generate2([3, 1, 4], max_new=10, model="lm",
                                   eos_id=ref[j])
        assert toks == ref[:j + 1], (toks, ref)
        assert info["reason"] == "eos"
    finally:
        srv.stop()


def test_generate_against_oneshot_model_is_an_error():
    plain = mx.sym.FullyConnected(mx.sym.Variable("data"),
                                  num_hidden=3, name="fc")
    eng = InferenceEngine(plain, {"fc_weight": np.zeros((3, 4), "f"),
                                  "fc_bias": np.zeros(3, "f")}, {},
                          {"data": (4,)}, buckets=(1,), warm=False)
    srv = ModelServer(eng, port=0, model_name="t").start()
    try:
        cli = ServingClient(addrs=[srv.address])
        with pytest.raises(RuntimeError, match="not generative"):
            cli.generate2([1, 2], max_new=4, model="t")
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the scheduler: continuous batching — more sequences than slots
# ---------------------------------------------------------------------------

def test_continuous_batching_joins_and_leaves():
    """7 sequences contend for 4 decode slots: every one finishes with
    the SAME tokens it gets solo (composition independence), and the
    queue high-water mark proves some of them actually waited."""
    eng = _engine()
    refs = {j: _oracle(eng, [1 + (j % 5), 2, 3], 6) for j in range(7)}
    srv = ModelServer(eng, port=0, model_name="lm").start()
    try:
        cli = ServingClient(addrs=[srv.address])
        results, errs = {}, []

        def run(j):
            try:
                results[j] = cli.generate2([1 + (j % 5), 2, 3],
                                           max_new=6, model="lm")[0]
            except Exception as e:   # pragma: no cover - surfaced below
                errs.append((j, e))
        ths = [threading.Thread(target=run, args=(j,)) for j in range(7)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert not errs, errs
        assert results == refs
        st = srv.stats()["models"]["lm"]["scheduler"]
        assert st["sequences"] == 7
        assert st["queue_hwm"] >= 1, \
            "7 sequences on 4 slots never queued?"
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the wire: streamed partials, concurrency, plain-request fallback
# ---------------------------------------------------------------------------

def test_wire_streaming_partials_in_order(monkeypatch):
    eng = _engine()
    ref = _oracle(eng, [1, 2, 3], 6)
    srv = ModelServer(eng, port=0, model_name="lm").start()
    try:
        monkeypatch.setattr(ka, "_LOCAL_ON", False)   # real sockets
        cli = ServingClient(addrs=[srv.address])
        seen = []
        toks, info = cli.generate2(
            [1, 2, 3], max_new=6, model="lm",
            on_token=lambda i, t, v: seen.append((i, t)))
        assert toks == ref
        assert seen == list(enumerate(ref)), seen
        results = {}

        def run(j):
            results[j] = cli.generate2([1 + (j % 5), 2, 3], max_new=5,
                                       model="lm")[0]
        ths = [threading.Thread(target=run, args=(j,)) for j in range(8)]
        for t in ths:
            t.start()
        for t in ths:
            t.join(timeout=60)
        assert all(len(v) == 5 for v in results.values()), results
    finally:
        srv.stop()


def test_plain_request_fallback_blocks_for_the_full_answer():
    """A client that cannot stream still gets the terminal reply with
    every token — ``generate`` over plain ``request`` is the
    non-streaming fallback, not an error."""
    eng = _engine()
    srv = ModelServer(eng, port=0, model_name="lm").start()
    conn = None
    try:
        conn = ka._ServerConn(srv.address)
        rep = conn.request("generate", "manual:1",
                           np.asarray([1, 2, 3], np.int32),
                           {"max_new": 4, "model": "lm"})
        assert rep[0] == "ok" and rep[1]["n"] == 4, rep
        assert len(list(rep[1]["tokens"])) == 4
    finally:
        if conn is not None:
            conn.close()
        srv.stop()


def test_hello_advertises_generate_signature():
    eng = _engine()
    srv = ModelServer(eng, port=0, model_name="lm").start()
    try:
        cli = ServingClient(addrs=[srv.address])
        cli.hello()
        sig = cli.models["lm"]["signature"]
        assert "generate" in sig
        assert sig["generate"]["cache_len"] == S
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# faults: the three drill rows (docs/serving.md fault matrix)
# ---------------------------------------------------------------------------

def test_kill_mid_generation_replays_exactly_once(monkeypatch):
    """kill() the active replica after 3 streamed tokens: the client
    replays on the peer with the pinned version and already-delivered
    indices deduped — the user-visible stream is exactly-once, in
    order, never torn across versions."""
    srv0 = ModelServer(_engine(), port=0, model_name="lm").start()
    srv1 = ModelServer(_engine(), port=0, model_name="lm").start()
    try:
        ref, _ = ServingClient(addrs=[srv1.address]).generate2(
            [3, 1, 4], max_new=10, model="lm")
        monkeypatch.setattr(ka, "_LOCAL_ON", False)
        cli = ServingClient(addrs=[srv0.address, srv1.address])
        seen = []

        def on_tok(i, t, v):
            seen.append((i, t, v))
            if i == 2:
                srv0.kill()
        toks, info = cli.generate2([3, 1, 4], max_new=10, model="lm",
                                   on_token=on_tok)
        assert toks == ref, (toks, ref)
        assert [i for i, _, _ in seen] == list(range(10)), seen
        assert [t for _, t, _ in seen] == ref
        assert all(v == info["version"] for _, _, v in seen)
        assert cli.stats()["failovers"] >= 1
    finally:
        srv1.stop()


def test_dropped_token_frame_never_double_emits():
    """Injected drop of one streamed token frame: the client recovers
    the missing token from the terminal reply — no gap, no double
    emit (the idx dedupe is the at-most-once half of exactly-once)."""
    eng = _engine()
    ref = _oracle(eng, [3, 1, 4], 10)
    srv = ModelServer(eng, port=0, model_name="lm").start()
    fault.install("kind=drop,point=server.send,op=generate,nth=3,count=1")
    try:
        cli = ServingClient(addrs=[srv.address])
        seen = []
        toks, _ = cli.generate2([3, 1, 4], max_new=10, model="lm",
                                on_token=lambda i, t, v: seen.append(i))
        assert toks == ref
        assert seen == list(range(10)), seen
    finally:
        fault.uninstall()
        srv.stop()


def test_mid_generation_expiry_returns_expired_verdict():
    """A sequence whose budget runs out MID-generation is evicted at
    the next step boundary with the ``expired`` verdict — it does not
    squat its slot until max_new."""
    eng = _engine()
    srv = ModelServer(eng, port=0, model_name="lm").start()
    fault.install("kind=delay,point=serve.step,delay=0.25,nth=2,count=50")
    try:
        cli = ServingClient(addrs=[srv.address])
        with pytest.raises(DeadlineExceeded, match="expired"):
            cli.generate2([3, 1, 4], max_new=200, budget_ms=400,
                          model="lm")
        st = srv.stats()["models"]["lm"]["scheduler"]
        assert st["expired"] >= 1
    finally:
        fault.uninstall()
        srv.stop()


def test_live_swap_never_tears_an_inflight_sequence():
    """serve.swap lands while a sequence decodes: the sequence keeps
    answering from its admission-time version (every token frame v0,
    final tokens bit-equal to the no-swap run) while the NEXT
    admission answers from v1. Pinned replay of an evicted version is
    refused honestly rather than silently rebound."""
    eng = _engine(seed=7, cache_len=32)
    srv = ModelServer(eng, port=0, model_name="lm").start()
    conn = None
    try:
        cli = ServingClient(addrs=[srv.address])
        ref0, i0 = cli.generate2([3, 1, 4], max_new=12, model="lm")
        assert i0["version"] == 0
        fault.install(
            "kind=delay,point=serve.step,delay=0.05,nth=1,count=1000")
        vers, done = [], []

        def run():
            done.append(cli.generate2(
                [3, 1, 4], max_new=12, model="lm",
                on_token=lambda i, t, v: vers.append(v)))
        th = threading.Thread(target=run)
        th.start()
        time.sleep(0.3)                       # a few tokens in
        srv.swap_weights(_lm_params(8), {}, version=1)
        th.join(timeout=60)
        fault.uninstall()
        toks, info = done[0]
        assert toks == ref0, "in-flight sequence torn by swap"
        assert set(vers) == {0} and info["version"] == 0
        toks1, info1 = cli.generate2([3, 1, 4], max_new=12, model="lm")
        assert info1["version"] == 1
        assert toks1 != ref0
        conn = ka._ServerConn(srv.address)
        with pytest.raises(RuntimeError, match="no longer resident"):
            conn.request("generate", "pin:1",
                         np.asarray([3, 1, 4], np.int32),
                         {"max_new": 4, "model": "lm", "version": 99})
    finally:
        fault.uninstall()
        if conn is not None:
            conn.close()
        srv.stop()


# ---------------------------------------------------------------------------
# the example: train -> checkpoint -> serve generate, end to end
# ---------------------------------------------------------------------------

def test_char_lm_example_smoke(tmp_path):
    """example/char_lm end to end: the trained char transformer's
    served greedy decode reproduces the memorized corpus and the
    decode loop is retrace-free (the example asserts both)."""
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), os.pardir,
                        "example", "char_lm", "char_lm.py")
    spec = importlib.util.spec_from_file_location("char_lm", path)
    char_lm = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(char_lm)
    ppl = char_lm.main(["--model-prefix", str(tmp_path / "char_lm")])
    assert ppl < 1.35
