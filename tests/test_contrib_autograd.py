"""contrib.autograd legacy API + legacy NDArrayOp custom ops
(reference python/mxnet/contrib/autograd.py and operator.py NDArrayOp)."""
import numpy as np

import mxtpu as mx
from mxtpu import nd
from mxtpu.contrib import autograd as cag


def test_train_test_sections():
    assert not mx.autograd.is_recording()
    with cag.train_section():
        assert mx.autograd.is_recording()
        assert mx.autograd.is_training()
        with cag.test_section():
            assert not mx.autograd.is_recording()
    assert not mx.autograd.is_recording()
    prev = cag.set_is_training(True)
    assert mx.autograd.is_training()
    cag.set_is_training(prev)


def test_mark_and_backward():
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    g = nd.zeros((3,))
    cag.mark_variables([x], [g])
    with cag.train_section():
        y = x * x
    cag.backward([y])
    np.testing.assert_allclose(g.asnumpy(), 2 * x.asnumpy())


def test_grad_and_loss():
    f = cag.grad_and_loss(lambda a: nd.sum(a * a * a))
    x = nd.array(np.array([1.0, 2.0], np.float32))
    grads, loss = f(x)
    np.testing.assert_allclose(grads[0].asnumpy(), 3 * x.asnumpy() ** 2)
    np.testing.assert_allclose(loss.asnumpy(), 9.0)
    g_only = cag.grad(lambda a: nd.sum(a * a))(x)
    np.testing.assert_allclose(g_only[0].asnumpy(), 2 * x.asnumpy())


def test_legacy_ndarray_op():
    class Square(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = nd.square(in_data[0])

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = 2 * in_data[0] * out_grad[0]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]]

    data = mx.sym.var("data")
    s = Square().get_symbol(data, name="sq")
    exe = s.simple_bind(mx.cpu(), grad_req="write", data=(3,))
    x = np.array([1.0, 2.0, -3.0], np.float32)
    out = exe.forward(is_train=True, data=x)[0]
    np.testing.assert_allclose(out.asnumpy(), x * x)
    exe.backward(out_grads=nd.ones((3,)))
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), 2 * x)


def test_contrib_io_dataloader_iter():
    from mxtpu import gluon
    from mxtpu.contrib.io import DataLoaderIter
    x = np.arange(40, dtype=np.float32).reshape(10, 4)
    y = (np.arange(10) % 2).astype(np.float32)
    ds = gluon.data.ArrayDataset(x, y)
    loader = gluon.data.DataLoader(ds, batch_size=5)
    it = DataLoaderIter(loader)
    batches = list(it)
    assert len(batches) == 2
    assert batches[0].data[0].shape == (5, 4)
    it.reset()
    assert len(list(it)) == 2
    # Module.fit accepts it
    import logging
    logging.disable(logging.INFO)
    data = mx.sym.var("data")
    net = mx.sym.SoftmaxOutput(mx.sym.FullyConnected(data, num_hidden=2),
                               name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())
    it.reset()
    mod.fit(it, num_epoch=1, initializer=mx.init.Xavier())


def test_contrib_nd_sym_namespaces():
    from mxtpu.contrib import ndarray as cnd
    from mxtpu.contrib import symbol as csym
    out = cnd.quantize(nd.array(np.array([0.0, 0.5, 1.0], np.float32)),
                       nd.array(np.array([0.0], np.float32)),
                       nd.array(np.array([1.0], np.float32)))
    assert len(out) == 3
    assert csym.MultiBoxPrior is not None


def test_contrib_tensorboard_and_onnx_gating():
    import pytest
    from mxtpu.contrib import tensorboard as tb
    import tempfile
    tmpdir = tempfile.mkdtemp()
    try:
        tb._summary_writer(tmpdir)       # gate on what the callback uses
        has_writer = True
    except ImportError:
        has_writer = False
    if has_writer:
        cb = tb.LogMetricsCallback(tmpdir)
        metric = mx.metric.Accuracy()
        metric.update([nd.array(np.array([0.0, 1.0], np.float32))],
                      [nd.array(np.array([[0.9, 0.1], [0.2, 0.8]],
                                         np.float32))])
        from mxtpu.model import BatchEndParam
        cb(BatchEndParam(epoch=0, nbatch=1, eval_metric=metric,
                         locals=None))
    from mxtpu.contrib import onnx as onnx_mod
    # importer is real now (vendored schema — tests/test_onnx_import.py);
    # a missing file surfaces as the usual OSError
    with pytest.raises(OSError):
        onnx_mod.import_model("x.onnx")
