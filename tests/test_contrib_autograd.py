"""contrib.autograd legacy API + legacy NDArrayOp custom ops
(reference python/mxnet/contrib/autograd.py and operator.py NDArrayOp)."""
import numpy as np

import mxtpu as mx
from mxtpu import nd
from mxtpu.contrib import autograd as cag


def test_train_test_sections():
    assert not mx.autograd.is_recording()
    with cag.train_section():
        assert mx.autograd.is_recording()
        assert mx.autograd.is_training()
        with cag.test_section():
            assert not mx.autograd.is_recording()
    assert not mx.autograd.is_recording()
    prev = cag.set_is_training(True)
    assert mx.autograd.is_training()
    cag.set_is_training(prev)


def test_mark_and_backward():
    x = nd.array(np.array([1.0, 2.0, 3.0], np.float32))
    g = nd.zeros((3,))
    cag.mark_variables([x], [g])
    with cag.train_section():
        y = x * x
    cag.backward([y])
    np.testing.assert_allclose(g.asnumpy(), 2 * x.asnumpy())


def test_grad_and_loss():
    f = cag.grad_and_loss(lambda a: nd.sum(a * a * a))
    x = nd.array(np.array([1.0, 2.0], np.float32))
    grads, loss = f(x)
    np.testing.assert_allclose(grads[0].asnumpy(), 3 * x.asnumpy() ** 2)
    np.testing.assert_allclose(loss.asnumpy(), 9.0)
    g_only = cag.grad(lambda a: nd.sum(a * a))(x)
    np.testing.assert_allclose(g_only[0].asnumpy(), 2 * x.asnumpy())


def test_legacy_ndarray_op():
    class Square(mx.operator.NDArrayOp):
        def forward(self, in_data, out_data):
            out_data[0][:] = nd.square(in_data[0])

        def backward(self, out_grad, in_data, out_data, in_grad):
            in_grad[0][:] = 2 * in_data[0] * out_grad[0]

        def infer_shape(self, in_shape):
            return in_shape, [in_shape[0]]

    data = mx.sym.var("data")
    s = Square().get_symbol(data, name="sq")
    exe = s.simple_bind(mx.cpu(), grad_req="write", data=(3,))
    x = np.array([1.0, 2.0, -3.0], np.float32)
    out = exe.forward(is_train=True, data=x)[0]
    np.testing.assert_allclose(out.asnumpy(), x * x)
    exe.backward(out_grads=nd.ones((3,)))
    np.testing.assert_allclose(exe.grad_dict["data"].asnumpy(), 2 * x)
