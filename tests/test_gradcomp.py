"""2-bit gradient compression (reference test_kvstore.py compression
tests + gradient_compression.cc semantics), kvstore server role, and the
bandwidth diagnostic."""
import os
import subprocess
import sys

import numpy as np
import jax.numpy as jnp

import mxtpu as mx
from mxtpu import nd
from mxtpu.gradient_compression import (GradientCompression, quantize_2bit,
                                        dequantize_2bit)


def _ref_2bit(data, residual, threshold):
    """Reference semantics in plain numpy (gradient_compression.cc)."""
    r = residual + data
    out = np.where(r >= threshold, threshold,
                   np.where(r <= -threshold, -threshold, 0.0)).astype(
        np.float32)
    return out, (r - out).astype(np.float32)


def test_quantize_roundtrip_matches_reference():
    rng = np.random.RandomState(0)
    data = rng.standard_normal((7, 33)).astype(np.float32)  # non-multiple of 16
    residual = rng.standard_normal((7, 33)).astype(np.float32) * 0.1
    packed, new_res = quantize_2bit(jnp.asarray(data),
                                    jnp.asarray(residual), 0.5)
    assert packed.dtype == jnp.uint32
    assert packed.size == -(-data.size // 16)     # 16x compression
    out = dequantize_2bit(packed, 0.5, data.shape)
    ref_out, ref_res = _ref_2bit(data, residual, 0.5)
    np.testing.assert_allclose(np.asarray(out), ref_out)
    np.testing.assert_allclose(np.asarray(new_res), ref_res, atol=1e-6)


def test_error_feedback_accumulates():
    gc = GradientCompression(threshold=0.5)
    small = jnp.full((16,), 0.2, jnp.float32)
    # 0.2 < threshold: first two rounds emit zero, residual builds up
    out1 = gc.roundtrip("w", small)
    out2 = gc.roundtrip("w", small)
    out3 = gc.roundtrip("w", small)
    np.testing.assert_allclose(np.asarray(out1), 0.0)
    np.testing.assert_allclose(np.asarray(out2), 0.0)
    # third round: residual 0.6 >= 0.5 fires
    np.testing.assert_allclose(np.asarray(out3), 0.5)
    # nothing is ever lost on average: residual after firing is 0.1
    np.testing.assert_allclose(np.asarray(gc._residuals["w"]), 0.1,
                               atol=1e-6)


def test_invalid_params():
    import pytest
    with pytest.raises(ValueError):
        GradientCompression(type="1bit")
    with pytest.raises(ValueError):
        GradientCompression(threshold=0.0)
    with pytest.raises(ValueError):
        mx.kv.create("local").set_gradient_compression({"threshold": 1})
    with pytest.raises(ValueError):  # typo'd key must not pass silently
        GradientCompression(type="2bit", treshold=2.0)


def test_single_push_not_compressed():
    # reference comm.h Reduce returns a lone src untouched — compression
    # only crosses the wire when >= 2 device shards reduce
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", nd.zeros((4,)))
    g = np.array([0.1, -0.2, 0.7, -0.9], np.float32)
    kv.push("w", nd.array(g))
    out = nd.zeros((4,))
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), g)
    kv.push("w", [nd.array(g)])      # list of one: same rule
    kv.pull("w", out=out)
    np.testing.assert_allclose(out.asnumpy(), g)


def test_kvstore_push_compressed():
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    shape = (4, 8)
    kv.init("w", nd.zeros(shape))
    rng = np.random.RandomState(1)
    grads = [rng.standard_normal(shape).astype(np.float32) for _ in range(3)]

    kv.push("w", [nd.array(g) for g in grads])
    out = nd.zeros(shape)
    kv.pull("w", out=out)

    # simulate: each device slot compresses against its own residual,
    # decompressed shards are summed, store had no updater -> assignment
    expect = np.zeros(shape, np.float32)
    for g in grads:
        q, _ = _ref_2bit(g, np.zeros(shape, np.float32), 0.5)
        expect += q
    np.testing.assert_allclose(out.asnumpy(), expect)

    # second push: per-slot residuals carry over
    kv.push("w", [nd.array(g) for g in grads])
    kv.pull("w", out=out)
    expect2 = np.zeros(shape, np.float32)
    for g in grads:
        _, res = _ref_2bit(g, np.zeros(shape, np.float32), 0.5)
        q2, _ = _ref_2bit(g, res, 0.5)
        expect2 += q2
    np.testing.assert_allclose(out.asnumpy(), expect2)


def test_kvstore_uncompressed_key_unaffected():
    kv = mx.kv.create("local")
    kv.init("a", nd.ones((3,)))
    kv.push("a", nd.array(np.full((3,), 2.0, np.float32)))
    out = nd.zeros((3,))
    kv.pull("a", out=out)
    np.testing.assert_allclose(out.asnumpy(), 2.0)


def test_kvstore_server_worker_role_noop():
    from mxtpu import kvstore_server
    # import as worker (default role) must not exit; server class drives
    # the controller protocol
    kv = mx.kv.create("local")
    srv = kvstore_server.KVStoreServer(kv)
    import pickle
    from mxtpu import optimizer as opt
    srv._controller()(0, pickle.dumps(opt.SGD(learning_rate=0.5)))
    assert kv._updater is not None
    srv.run()


def test_bandwidth_tool_smoke():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    r = subprocess.run(
        [sys.executable, os.path.join(os.path.dirname(__file__), "..",
                                      "tools", "bandwidth.py"),
         "--sizes", "1000", "--iters", "2"],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    assert "psum" in r.stdout and "ppermute" in r.stdout


def test_numpy_mirror_matches_jax_path():
    """The host-side numpy quantizer (the kvstore push fast path) must
    produce bit-identical packed words and residuals to the jax op —
    mixed pushes (device then host) may share one slot's residual."""
    import jax.numpy as jnp
    from mxtpu.gradient_compression import (GradientCompression,
                                            _quantize_2bit_np,
                                            quantize_2bit,
                                            dequantize_2bit)
    rng = np.random.RandomState(3)
    data = rng.randn(5, 33).astype("f")      # odd size: exercises pad
    res = rng.randn(5, 33).astype("f") * 0.1
    p_np, r_np = _quantize_2bit_np(data, res, 0.5)
    p_jx, r_jx = quantize_2bit(jnp.asarray(data), jnp.asarray(res), 0.5)
    np.testing.assert_array_equal(p_np, np.asarray(p_jx))
    np.testing.assert_allclose(r_np, np.asarray(r_jx), rtol=1e-6)
    # a numpy part through GradientCompression round-trips like device
    gc = GradientCompression(type="2bit", threshold=0.5)
    packed = gc.compress("w", data)          # numpy in -> host path
    assert isinstance(packed, np.ndarray) and packed.dtype == np.uint32
    assert isinstance(gc._residuals["w"], np.ndarray)
    out = np.asarray(dequantize_2bit(jnp.asarray(packed), 0.5,
                                     data.shape))
    assert set(np.unique(out)) <= {-0.5, 0.0, 0.5}
    # error feedback carries across rounds identically to the jax path:
    # the first compress left residual data - out (res started at 0)
    np.testing.assert_allclose(gc._residuals["w"], data - out,
                               rtol=1e-5, atol=1e-6)
    p2 = gc.compress("w", data)
    p2_jx, r2_jx = quantize_2bit(jnp.asarray(data),
                                 jnp.asarray(data - out), 0.5)
    np.testing.assert_array_equal(p2, np.asarray(p2_jx))
    np.testing.assert_allclose(gc._residuals["w"], np.asarray(r2_jx),
                               rtol=1e-5, atol=1e-6)
