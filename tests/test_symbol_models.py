"""Symbol-library models (example/image-classification/symbols/): the
Module-path counterparts of the gluon zoo, used by train_imagenet and
the benchmark's symbol-scoring leg."""
import os
import sys

import numpy as np

import mxtpu as mx

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "example", "image-classification"))


def test_inception_bn_small_variant_forward_backward():
    from symbols.inception_bn import get_symbol
    sym = get_symbol(10, "3,28,28")
    ex = sym.simple_bind(ctx=mx.cpu(), grad_req="write",
                         data=(2, 3, 28, 28), softmax_label=(2,))
    r = np.random.RandomState(0)
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            # simple_bind zero-fills args; zero conv weights would zero
            # the whole chain (and its gradients)
            arr[:] = (r.rand(*arr.shape).astype("f") - 0.5) * 0.2
    ex.arg_dict["data"][:] = r.rand(2, 3, 28, 28).astype("f")
    ex.arg_dict["softmax_label"][:] = np.array([1.0, 3.0], "f")
    out = ex.forward(is_train=True)[0]
    assert out.shape == (2, 10)
    p = out.asnumpy()
    np.testing.assert_allclose(p.sum(axis=1), 1.0, rtol=1e-5)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0


def test_inception_bn_imagenet_variant_shapes():
    from symbols.inception_bn import get_symbol
    sym = get_symbol(1000, "3,224,224")
    # channel allocation check at the meeting points (reference plan):
    # final concat before global pool carries 352+320+224+128 = 1024
    arg_shapes, out_shapes, _ = sym.infer_shape(data=(1, 3, 224, 224))
    assert out_shapes[0] == (1, 1000)
    # channel-allocation check: the 5b concat feeds global pool ->
    # flatten -> fc, so fc1_weight's input width is the final plan sum
    # 352 + 320 + 224 + 128 = 1024
    shapes = dict(zip(sym.list_arguments(), arg_shapes))
    assert shapes["fc1_weight"] == (1000, 1024), shapes["fc1_weight"]
    # and the four 5b branches exist with the planned output channels
    assert shapes["5b_b1_0_conv_weight"][0] == 352
    assert shapes["5b_b3_1_conv_weight"][0] == 320
    assert shapes["5b_bd3_2_conv_weight"][0] == 224
    assert shapes["5b_bp_conv_weight"][0] == 128
